"""Control-plane durability + driver failover (PR 18).

Three layers:

- **journal units** — ``ControlPlaneJournal``/``JournalState``: fsync'd
  roundtrip, idempotent replay under duplicated lines, torn tails
  (payload-cut AND mid-UTF-8 byte cut), unknown kinds one-warning,
  requeue alias chains, rollout ``remaining_steps`` arithmetic.
- **adopt units** — ``ReplicaScheduler.adopt`` (fresh rids, requeue
  aliases, dead replicas, restored splits) and ``ModelRegistry``
  journal binding/snapshot/adopt, against the fake-replica world.
- **ride-through units** — ``ServeFrontend`` ``resume`` op +
  ``ServeClient(failover_wait=)``: a mid-stream frontend restart
  resumes at the exact token cursor; a frontend that never returns
  raises typed ``FrontendUnavailable``.

The full driver-kill heal (real 2-replica cluster, oracle-exact
streams, zero loss) is the slow-marked integration test at the bottom
— excluded from tier-1 like the other full-cluster chaos scenarios;
the ``scripts/bench_serving.py --failover`` gate (ci.sh
``--bench-smoke``) keeps it enforced in CI.
"""

import json
import logging
import os
import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu.serving import (FrontendUnavailable,
                                           ModelRegistry, ServeClient,
                                           ServeFrontend)
from tensorflowonspark_tpu.serving.journal import (ControlPlaneJournal,
                                                   JournalState)

from tests.test_serving_cluster import (_FakeWorld, _fake_tokens,
                                        _scheduler)

# --------------------------------------------------------- journal units


def _admit(rid, trace=None, n=4, **kw):
    rec = dict(kind="admit", t=1.0, rid=rid, prompt=[1, 2, 3],
               max_new_tokens=n, temperature=0.0, top_p=1.0, seed=0,
               tenant="default", priority="normal", model=None,
               trace=trace)
    rec.update(kw)
    return rec


def test_journal_roundtrip(tmp_path):
    path = str(tmp_path / "cp.jsonl")
    j = ControlPlaneJournal(path)
    j.record("admit", rid=0, prompt=[1, 2], max_new_tokens=3, trace="t0")
    j.record("route", rid=0, replica=1)
    j.record("commit", rid=0, outcome="done", tokens=3)
    j.record("replica_added", replica=1, role=None, model="m", version="v1")
    j.close()
    st = ControlPlaneJournal.replay(path)
    assert list(st.admitted) == [0] and st.routed[0] == 1
    assert st.committed[0] == {"outcome": "done", "tokens": 3}
    assert not st.unfinished
    assert st.replicas[1]["alive"] and st.replicas[1]["model"] == "m"


def test_journal_record_survives_close_and_bad_payload(tmp_path, caplog):
    """``record`` never raises: closed handle → dropped; a payload json
    can't serialize → dropped with one warning (the serving path must
    not die of its own durability)."""
    j = ControlPlaneJournal(str(tmp_path / "cp.jsonl"))
    with caplog.at_level(logging.WARNING):
        j.record("admit", rid=0, prompt=object())     # not serializable
        j.record("admit", rid=1, prompt=object())
    assert sum("not JSON-serializable" in r.message
               for r in caplog.records) == 1
    j.close()
    j.record("admit", rid=2, prompt=[1])              # no raise after close
    assert ControlPlaneJournal.replay(j.path).admitted == {}


def test_journal_replay_idempotent_under_duplicates():
    recs = [
        _admit(0, trace="a"), _admit(1, trace="b"),
        dict(kind="route", rid=0, replica=2),
        dict(kind="requeue", rid=1, **{"as": 7}),
        dict(kind="commit", rid=7, outcome="done", tokens=4),
        dict(kind="replica_added", replica=2),
        dict(kind="replica_dead", replica=2),
        dict(kind="traffic_split", model="m", split={"v2": 25.0}),
        dict(kind="rollout_started", model="m", version="v2",
             incumbent="v1", steps=[5, 25, 100]),
        dict(kind="rollout_step", model="m", version="v2", percent=5),
        dict(kind="rollout_step_done", model="m", version="v2", percent=5),
    ]
    once = JournalState.from_records(recs)
    twice = JournalState.from_records([r for r in recs for _ in (0, 1)])
    for st in (once, twice):
        assert set(st.unfinished) == {0}
        assert st.committed[1] == {"outcome": "done", "tokens": 4}
        assert st.replicas[2]["alive"] is False
        assert st.rollouts["m"]["done_steps"] == [5]
    assert twice.traffic == once.traffic == {"m": {"v2": 25.0}}


def test_journal_requeue_alias_chain_resolves_to_root():
    """A commit under the SECOND failover's rid still discharges the
    original admission — route/commit resolve through the alias chain."""
    st = JournalState.from_records([
        _admit(0, trace="a"),
        dict(kind="requeue", rid=0, **{"as": 5}),      # failover 1
        dict(kind="requeue", rid=5, **{"as": 9}),      # failover 2
        dict(kind="route", rid=9, replica=3),
        dict(kind="commit", rid=9, outcome="done", tokens=4),
    ])
    assert st._root(9) == 0 and st.routed == {0: 3}
    assert set(st.committed) == {0} and not st.unfinished


def test_journal_torn_tail_payload_cut(tmp_path):
    """A crash mid-``write`` leaves a final line cut inside the JSON
    payload; replay skips exactly that line."""
    path = str(tmp_path / "cp.jsonl")
    j = ControlPlaneJournal(path)
    j.record("admit", rid=0, prompt=[1], max_new_tokens=2)
    j.record("admit", rid=1, prompt=[2], max_new_tokens=2)
    j.close()
    with open(path, "ab") as f:       # torn: payload cut, no newline
        f.write(b'{"t": 3.0, "kind": "commit", "rid": 1, "outc')
    st = ControlPlaneJournal.replay(path)
    assert set(st.admitted) == {0, 1} and not st.committed


def test_journal_torn_tail_mid_utf8(tmp_path, caplog):
    """The torn byte can land INSIDE a multi-byte UTF-8 sequence — the
    decode error must skip the line, not take the replay down."""
    path = str(tmp_path / "cp.jsonl")
    j = ControlPlaneJournal(path)
    j.record("admit", rid=0, prompt=[1], max_new_tokens=2)
    j.close()
    whole = json.dumps({"t": 3.0, "kind": "commit", "rid": 0,
                        "outcome": "café"},
                       ensure_ascii=False).encode("utf-8")
    assert b"\xc3" in whole
    with open(path, "ab") as f:       # cut between the é's two bytes
        f.write(whole[:whole.index(b"\xc3") + 1])
    with caplog.at_level(logging.WARNING):
        st = ControlPlaneJournal.replay(path)
    assert set(st.admitted) == {0} and not st.committed
    assert any("torn/corrupt" in r.message for r in caplog.records)


def test_journal_unknown_kinds_skip_with_one_warning(tmp_path, caplog):
    path = str(tmp_path / "cp.jsonl")
    with open(path, "w") as f:
        for rec in (_admit(0), {"t": 2.0, "kind": "quantum_entangle"},
                    {"t": 2.1, "kind": "quantum_entangle"},
                    dict(kind="commit", t=2.2, rid=0, outcome="done",
                         tokens=1)):
            f.write(json.dumps(rec) + "\n")
    with caplog.at_level(logging.WARNING):
        st = ControlPlaneJournal.replay(path)
    assert st.unknown_kinds == 2
    assert sum("unknown record kind" in r.message
               for r in caplog.records) == 1
    assert set(st.committed) == {0}     # folding continued past them


def test_remaining_steps_resume_arithmetic():
    base = [dict(kind="rollout_started", model="m", version="v2",
                 incumbent="v1", steps=[5, 25, 100]),
            dict(kind="rollout_step", model="m", version="v2", percent=5),
            dict(kind="rollout_step_done", model="m", version="v2",
                 percent=5)]
    # mid-canary: 5 gated; 25 intended but its gate never committed →
    # 25 re-executes (idempotent split), then 100
    st = JournalState.from_records(
        base + [dict(kind="rollout_step", model="m", version="v2",
                     percent=25)])
    assert st.remaining_steps("m") == (25, 100)
    assert st.open_rollouts()["m"]["intended"] == 25
    # every step gated but the finishing promotion never committed →
    # the bare (100,) finisher
    st = JournalState.from_records(
        base + [dict(kind="rollout_step_done", model="m", version="v2",
                     percent=p) for p in (25, 100)])
    assert st.remaining_steps("m") == (100,)
    # terminal rollouts are not open; unknown models owe nothing
    st = JournalState.from_records(
        base + [dict(kind="rollout_done", model="m", version="v2",
                     outcome="promoted")])
    assert st.open_rollouts() == {} and st.remaining_steps("x") == ()


# ----------------------------------------------------------- adopt units


def test_scheduler_adopt_requeues_fresh_rids_and_completes(tmp_path):
    """adopt(): fresh rids past the journal's max, ``requeue`` aliases
    journaled, dead replicas never route, committed-done traces surface
    for the frontend, and the requeued work then actually completes."""
    path = str(tmp_path / "cp.jsonl")
    state = JournalState.from_records([
        _admit(0, trace="tr0", n=3, prompt=[2, 3]),
        _admit(1, trace="tr1", n=3, prompt=[4]),
        _admit(2, trace="tr2", n=3, prompt=[5, 6]),
        dict(kind="route", rid=0, replica=0),
        dict(kind="commit", rid=1, outcome="done", tokens=3),
        dict(kind="replica_added", replica=0),
        dict(kind="replica_added", replica=1),
        dict(kind="replica_dead", replica=1),
    ])
    world = _FakeWorld(2)
    s = _scheduler(world, journal=ControlPlaneJournal(path))
    try:
        adopted = s.adopt(state)
        # committed stream resurfaces by trace; unfinished requeue
        assert adopted["done"] == {"tr1": 3}
        assert set(adopted["requeued"]) == {"tr0", "tr2"}
        # fresh rids: nothing the journal ever named is reused
        assert all(r.rid > 2 for r in adopted["requeued"].values())
        assert s.requeued == 2 and not s.replicas[1].alive
        # the aliases hit the journal, so a SECOND replay folds them
        st2 = ControlPlaneJournal.replay(path)
        assert {st2._root(r.rid) for r in adopted["requeued"].values()} \
            == {0, 2}
        s.start()
        for trace, want_prompt in (("tr0", [2, 3]), ("tr2", [5, 6])):
            req = adopted["requeued"][trace]
            toks = []
            while True:
                ev = req.events.get(timeout=10)
                if ev[0] == "tok":
                    toks.extend(ev[1])
                else:
                    assert ev[0] == "done", ev
                    break
            assert toks == _fake_tokens(want_prompt, 3)
    finally:
        s.stop()


def test_registry_journal_snapshot_and_adopt(tmp_path, caplog):
    """bind_journal snapshots the pre-bind catalog (registrations made
    before the tier booted must replay too); adopt restores eval
    verdicts + states onto re-registered builders and warns-and-skips
    versions nobody re-registered."""
    path = str(tmp_path / "cp.jsonl")
    reg = ModelRegistry()
    reg.register("m", "v1", builder=lambda a: None)
    reg.register("m", "v2", builder=lambda a: None)
    v2 = reg.version("m", "v2")
    v2.eval_passed, v2.eval_metrics = True, {"exact": 1.0}
    v2.state = "canary"
    j = ControlPlaneJournal(path)
    reg.bind_journal(j)               # ← snapshot happens here
    reg.mark("m", "v1", "serving")    # post-bind mutations journal live
    j.close()
    st = ControlPlaneJournal.replay(path)
    assert st.registry[("m", "v2")]["state"] == "canary"
    assert st.registry[("m", "v2")]["eval_passed"] is True
    assert st.registry[("m", "v1")]["state"] == "serving"

    reg2 = ModelRegistry()
    reg2.register("m", "v2", builder=lambda a: None)  # v1 NOT re-registered
    with caplog.at_level(logging.WARNING):
        reg2.adopt(st)
    got = reg2.version("m", "v2")
    assert got.state == "canary" and got.eval_passed is True
    assert got.eval_metrics == {"exact": 1.0}
    assert any("not re-registered" in r.message for r in caplog.records)


# ------------------------------------------------- ride-through units


def test_client_stream_rides_through_frontend_restart():
    """The PR-18 client contract at unit scale: kill the frontend (only)
    mid-stream, stand a new one on the SAME port with the replayed
    request wired into ``resumed``, and the concatenated yield is
    byte-identical — the ``received`` cursor dedups the replay."""
    world = _FakeWorld(1, token_delay=0.1)
    s = _scheduler(world, slots_per_replica=2).start()
    fe1 = ServeFrontend(s, authkey=b"s" * 16)
    addr = fe1.start()
    prompt, n, trace = np.asarray([3, 4], np.int32), 8, "tr-restart"
    got, errs = [], []
    resumed_while_streaming = threading.Event()

    def consume():
        try:
            with ServeClient(addr, b"s" * 16, failover_wait=15.0) as c:
                for delta in c.generate_stream(prompt, n, trace=trace,
                                               timeout=30.0):
                    got.extend(delta)
                    resumed_while_streaming.set()
        except Exception as e:       # surfaces in the main thread
            errs.append(e)

    t = threading.Thread(target=consume)
    t.start()
    while not got and t.is_alive():
        time.sleep(0.01)             # let a first delta land
    fe1.stop()                       # ← "driver dies" (scheduler survives)
    seen_at_kill = len(got)
    # the failover replay: a FRESH request for the same admission (new
    # rid — exactly what scheduler.adopt mints), wired pre-start
    req2 = s.submit(prompt, n, trace=trace)
    fe2 = ServeFrontend(s, authkey=b"s" * 16, port=addr[1])
    fe2.resumed = {trace: req2}
    try:
        assert fe2.start()[1] == addr[1]   # same port, lingering conns ok
        t.join(30)
        assert not t.is_alive() and not errs, errs
        assert got == _fake_tokens([3, 4], n)   # no gap, no repeat
        assert len(got) > seen_at_kill, "stream never resumed"
    finally:
        fe2.stop()
        s.stop()


def test_client_resume_of_committed_stream_returns_done():
    """A stream whose commit landed JUST before the kill: the resume
    finds no live request but ``resumed_done`` knows the trace —
    clients that already hold every token get a clean DONE, clients
    missing tokens get the typed unknown_request error."""
    world = _FakeWorld(1)
    s = _scheduler(world).start()
    fe = ServeFrontend(s, authkey=b"s" * 16)
    fe.resumed_done = {"tr-done": 5}
    addr = fe.start()
    try:
        with ServeClient(addr, b"s" * 16, failover_wait=5.0) as c:
            c.send(c._sock, {"op": "resume", "trace": "tr-done",
                             "received": 5, "stream": True, "timeout": 5})
            assert c.receive(c._sock) == ("DONE", 5)
        with ServeClient(addr, b"s" * 16, failover_wait=5.0) as c:
            c.send(c._sock, {"op": "resume", "trace": "tr-done",
                             "received": 3, "stream": True, "timeout": 5})
            frame = c.receive(c._sock)
            assert frame[0] == "ERR" and frame[1] == "unknown_request"
        with ServeClient(addr, b"s" * 16, failover_wait=5.0) as c:
            c.send(c._sock, {"op": "resume", "trace": "tr-nobody",
                             "received": 0, "stream": True, "timeout": 5})
            frame = c.receive(c._sock)
            assert frame[0] == "ERR" and frame[1] == "unknown_request"
    finally:
        fe.stop()
        s.stop()


def test_client_frontend_unavailable_is_typed():
    """No standby ever rebinds: the ride-through gives up after
    ``failover_wait`` with the typed error, quickly."""
    world = _FakeWorld(1, token_delay=0.1)
    s = _scheduler(world).start()
    fe = ServeFrontend(s, authkey=b"s" * 16)
    addr = fe.start()
    c = ServeClient(addr, b"s" * 16, failover_wait=1.0)
    try:
        stream = c.generate_stream(np.asarray([2], np.int32), 30,
                                   timeout=30.0)
        next(stream)
        fe.stop()                    # nobody comes back
        t0 = time.monotonic()
        with pytest.raises(FrontendUnavailable):
            for _ in stream:
                pass
        assert time.monotonic() - t0 < 10.0
    finally:
        c.close()
        s.stop()


# ------------------------------------------------------ integration


@pytest.mark.slow
@pytest.mark.integration
def test_driver_kill_heals_zero_loss(tmp_path, worker_env):
    """THE tentpole gate at test scale: boot a 2-replica tier, hard-kill
    the driver control plane mid-stream under concurrent clients,
    resume from the journal on the same port, and every accepted
    request completes oracle-exact with zero client errors; the drained
    journal shows no unfinished obligations."""
    from tensorflowonspark_tpu.serving import resume_driver
    from tests.test_serving_cluster import _oracle, _run_serving

    serving = _run_serving(tmp_path, worker_env)
    rng = np.random.default_rng(7)
    reqs = [(rng.integers(0, 83, (int(rng.integers(3, 9)),))
             .astype(np.int32), int(rng.integers(6, 12)))
            for _ in range(4)]
    results, errors = {}, []
    first_token = threading.Event()

    def run_client(i):
        p, n = reqs[i]
        try:
            with serving.client(failover_wait=90.0) as c:
                toks = []
                for delta in c.generate_stream(p, n):
                    toks.extend(delta)
                    first_token.set()
                results[i] = toks
        except Exception as e:
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=run_client, args=(i,))
               for i in range(len(reqs))]
    try:
        for t in threads:
            t.start()
        assert first_token.wait(90), "no stream ever started"
        time.sleep(0.2)              # get streams genuinely mid-flight
        crashed_at = time.time()
        serving.crash()              # ← the driver "dies"
        serving2 = resume_driver(serving.cluster, address=serving.address,
                                 max_batch=2, crashed_at=crashed_at)
        try:
            for t in threads:
                t.join(180)
            assert not errors, errors
            assert len(results) == len(reqs)
            for i, (p, n) in enumerate(reqs):
                assert results[i] == _oracle(p, n), f"request {i} diverged"
            st = ControlPlaneJournal.replay(
                os.path.join(str(tmp_path), "control_plane.jsonl"))
            assert not st.unfinished, st.unfinished
            assert st.resumes == 1
            assert serving2.scheduler.requeued >= 1
        finally:
            serving2.shutdown()
    finally:
        for t in threads:
            t.join(5)
        serving.cluster._abort()
