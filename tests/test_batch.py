"""Batch-inference plane tests (``tensorflowonspark_tpu/batch/``).

Units cover the manifest/ledger/writer invariants the resume proof rests
on; integration tests run real ``LocalProcessBackend`` worker processes
through ``BatchJob`` — including a mid-job SIGKILL with
``run_with_recovery`` restart (committed shards NOT reprocessed, merged
output identical to the uninterrupted oracle) and in-flight dead-worker
reassignment with no restart.  The full-size measured version lives in
``scripts/bench_batch.py`` → ``bench_artifacts/batch.json``.
"""

import os

import numpy as np
import pytest

from tensorflowonspark_tpu.batch import (BatchJob, GridSearch, ProgressLedger,
                                         Shard, ShardManifest, ShardWriter,
                                         expand_param_grid, iter_part,
                                         read_results)
from tensorflowonspark_tpu.batch.ledger import LEDGER_NAME
from tensorflowonspark_tpu.batch.worker import _grouped
from tensorflowonspark_tpu.batch.writer import decode_record
from tests import cluster_funcs as funcs

pytestmark = pytest.mark.integration


def _chunks(n=6, rows=2, cols=2):
    return [np.arange(i * rows * cols, (i + 1) * rows * cols,
                      dtype=np.float64).reshape(rows, cols) for i in range(n)]


def _expected(chunks, scale=2.0, offset=None):
    out = []
    for c in chunks:
        for row in c:
            out.append(((row + offset) if offset is not None
                        else row * scale).tobytes())
    return out


# -- units ------------------------------------------------------------------

def test_manifest_shards_keys_and_trials():
    m = ShardManifest.from_arrays(_chunks(3))
    assert [s.shard_id for s in m] == ["shard-00000", "shard-00001",
                                       "shard-00002"]
    assert m.shards[0].key == "shard-00000"
    mt = m.with_trials(["t0", "t1"])
    assert len(mt) == 6
    assert mt.shards[0].key == "shard-00000@t0"
    assert mt.shards[3].key == "shard-00000@t1"  # trial-major order
    with pytest.raises(ValueError, match="duplicate"):
        ShardManifest([Shard("a", "array", data=[1]),
                       Shard("a", "array", data=[2])])
    with pytest.raises(ValueError, match="unknown shard kind"):
        Shard("x", "parquet", path="p")
    with pytest.raises(ValueError, match="needs a path"):
        Shard("x", "tfrecord")


def test_manifest_tfrecord_glob_save_load(tmp_path):
    from tensorflowonspark_tpu import tfrecord

    for i in range(3):
        tfrecord.write_records(str(tmp_path / f"part-{i:05d}.tfrecord"),
                               [b"r%d" % i])
    m = ShardManifest.from_tfrecords(str(tmp_path / "part-*.tfrecord"))
    assert len(m) == 3 and m.shards[1].path.endswith("part-00001.tfrecord")
    m.save(str(tmp_path))
    m2 = ShardManifest.load(str(tmp_path))
    assert [s.descriptor() for s in m2] == [s.descriptor() for s in m]
    with pytest.raises(FileNotFoundError):
        ShardManifest.from_tfrecords(str(tmp_path / "nope-*.tfrecord"))
    # array manifests persist descriptors but cannot be loaded back
    ma = ShardManifest.from_arrays(_chunks(1))
    ma.save(str(tmp_path / "arr"))
    with pytest.raises(ValueError, match="from_arrays"):
        ShardManifest.load(str(tmp_path / "arr"))


def test_ledger_replay_commit_requeue_and_reprocess(tmp_path):
    d = str(tmp_path)
    with ProgressLedger(d) as led:
        led.attempt(total=3)
        led.assigned("s0", worker=0)
        led.done("s0", worker=0, count=4, path="parts/s0.tfrecord")
        led.assigned("s1", worker=1)
        led.requeued("s1", worker=1)
        led.assigned("s1", worker=0)
        led.attempt(total=3)
        led.done("s1", worker=0, count=4, path="parts/s1.tfrecord")
    r = ProgressLedger.replay(d)
    assert set(r.committed) == {"s0", "s1"}
    assert r.attempts == 2
    assert r.reprocessed_committed == []       # requeue-before-done is fine
    assert r.done_at_attempt(2) == {"s0"}      # what the restart found
    # a committed shard assigned AGAIN is the broken-resume signal
    with ProgressLedger(d) as led:
        led.assigned("s0", worker=1)
    assert ProgressLedger.replay(d).reprocessed_committed == ["s0"]


def test_ledger_replay_skips_corrupt_tail(tmp_path):
    with ProgressLedger(str(tmp_path)) as led:
        led.done("s0", worker=0, count=1, path="p")
    with open(tmp_path / LEDGER_NAME, "a") as f:
        f.write('{"event": "done", "key": "s1"')  # killed mid-append
    r = ProgressLedger.replay(str(tmp_path))
    assert set(r.committed) == {"s0"}


def test_writer_atomic_commit_sweep_and_keys(tmp_path):
    w = ShardWriter(str(tmp_path))
    path, n = w.write("s0", [b"a", b"bb", {"obj": 1}])
    assert n == 3 and os.path.exists(path)
    got = list(iter_part(path))
    assert got[:2] == [b"a", b"bb"] and decode_record(got[2]) == {"obj": 1}
    # overwrite (resume re-score) replaces atomically
    w.write("s0", [b"a", b"bb", {"obj": 1}])
    assert list(iter_part(path))[:2] == [b"a", b"bb"]
    # an in-process predict failure never publishes OR litters
    with pytest.raises(RuntimeError):
        w.write("s1", _raising_iter())
    assert not os.path.exists(w.part_path("s1"))
    assert os.listdir(w.parts_dir) == ["s0.tfrecord"]
    # a SIGKILLed worker (no finally) leaves a temp; the dispatcher sweeps
    orphan = os.path.join(w.parts_dir, ".tmp-part-killed123-s1")
    with open(orphan, "wb") as f:
        f.write(b"half a part")
    assert w.sweep_temps() == 1 and w.sweep_temps() == 0
    assert not os.path.exists(orphan)
    with pytest.raises(ValueError, match="invalid shard key"):
        w.part_path("../escape")


def _raising_iter():
    yield b"one"
    raise RuntimeError("predict blew up mid-shard")


def test_read_results_missing_part_raises(tmp_path):
    m = ShardManifest.from_arrays(_chunks(2))
    ShardWriter(str(tmp_path)).write("shard-00000", [b"x"])
    with pytest.raises(FileNotFoundError, match="shard-00001"):
        read_results(str(tmp_path), m)


def test_expand_param_grid_shapes():
    assert expand_param_grid([{"a": 1}, {"a": 2}]) == {"t0": {"a": 1},
                                                      "t1": {"a": 2}}
    grid = expand_param_grid({"b": [10, 20], "a": ["x"]})
    assert grid == {"t0": {"a": "x", "b": 10}, "t1": {"a": "x", "b": 20}}
    with pytest.raises(ValueError, match="empty"):
        expand_param_grid([])


def test_worker_grouping_shapes():
    assert list(_grouped([1, 2, 3, 4, 5], 2)) == [[1, 2], [3, 4], [5]]
    arr = np.arange(10).reshape(5, 2)
    groups = list(_grouped(arr, 2))
    assert [g.shape[0] for g in groups] == [2, 2, 1]
    assert list(_grouped(iter([b"a", b"b", b"c"]), 2)) == [[b"a", b"b"],
                                                          [b"c"]]


# -- integration (real worker processes) ------------------------------------

def test_batch_job_e2e_array_shards(tmp_path):
    chunks = _chunks(6)
    job = BatchJob(ShardManifest.from_arrays(chunks), str(tmp_path / "out"),
                   funcs.batch_predict_scale, batch_size=1)
    summary = job.run(num_workers=2, max_restarts=0,
                      worker_env={"JAX_PLATFORMS": "cpu"},
                      working_dir=str(tmp_path / "wd"),
                      reservation_timeout=60, shutdown_timeout=60)
    assert summary["scored"] == 6 and summary["requeued"] == 0
    assert job.results() == _expected(chunks)
    replay = ProgressLedger.replay(str(tmp_path / "out"))
    assert len(replay.committed) == 6 and replay.reprocessed_committed == []
    # driver-side telemetry: outcomes counted, nothing left remaining
    from tensorflowonspark_tpu import metrics as tpu_metrics

    snap = tpu_metrics.get_registry().snapshot()
    fam = snap.get("tfos_batch_shards_total", {})
    done = sum(v for labels, v in fam.get("samples", [])
               if labels.get("outcome") == "done")
    assert done >= 6, fam
    rem = snap.get("tfos_batch_shards_remaining_count", {})
    assert rem.get("samples") and rem["samples"][-1][1] == 0, rem


def test_batch_job_rescored_when_committed_part_lost(tmp_path):
    """Trust-but-verify resume: a ledger 'done' whose part file vanished
    (lost rename after an OS crash, manual cleanup) must be demoted and
    re-scored, not skipped into a permanently missing output."""
    chunks = _chunks(4)
    out = str(tmp_path / "out")
    job = BatchJob(ShardManifest.from_arrays(chunks), out,
                   funcs.batch_predict_scale, batch_size=2)
    job.run(num_workers=1, max_restarts=0,
            worker_env={"JAX_PLATFORMS": "cpu"},
            working_dir=str(tmp_path / "wd"), reservation_timeout=60,
            shutdown_timeout=60)
    os.remove(ShardWriter(out).part_path("shard-00002"))
    job2 = BatchJob(ShardManifest.from_arrays(chunks), out,
                    funcs.batch_predict_scale, batch_size=2)
    summary = job2.run(num_workers=1, max_restarts=0,
                       worker_env={"JAX_PLATFORMS": "cpu"},
                       working_dir=str(tmp_path / "wd2"),
                       reservation_timeout=60, shutdown_timeout=60)
    assert summary["scored"] == 1 and summary["skipped_committed"] == 3
    assert job2.results() == _expected(chunks)


def test_batch_job_model_builder_reaches_predict(tmp_path):
    chunks = _chunks(3)
    job = BatchJob(ShardManifest.from_arrays(chunks), str(tmp_path / "out"),
                   funcs.batch_predict_with_model,
                   model_builder=funcs.batch_model_builder_offset,
                   predict_args={"offset": 7.0}, batch_size=2)
    job.run(num_workers=1, max_restarts=0,
            worker_env={"JAX_PLATFORMS": "cpu"},
            working_dir=str(tmp_path / "wd"),
            reservation_timeout=60, shutdown_timeout=60)
    assert job.results() == _expected(chunks, offset=7.0)


def test_batch_job_tfrecord_source(tmp_path):
    from tensorflowonspark_tpu import tfrecord

    for i in range(4):
        tfrecord.write_records(str(tmp_path / f"part-{i:05d}.tfrecord"),
                               [b"x" * (i + 1) for _ in range(3)])
    m = ShardManifest.from_tfrecords(str(tmp_path / "part-*.tfrecord"))
    job = BatchJob(m, str(tmp_path / "out"), funcs.batch_predict_len,
                   batch_size=2)
    job.run(num_workers=2, max_restarts=0,
            worker_env={"JAX_PLATFORMS": "cpu"},
            working_dir=str(tmp_path / "wd"),
            reservation_timeout=60, shutdown_timeout=60)
    want = [(i + 1).to_bytes(4, "little") for i in range(4) for _ in range(3)]
    assert job.results() == want


def test_batch_job_sigkill_restart_resumes_zero_reprocess(tmp_path):
    """The resume contract: SIGKILL the only worker mid-job; the
    run_with_recovery relaunch must replay the ledger, skip every
    committed shard, and produce output identical to an uninterrupted
    run — the tier-1 twin of bench_batch.py's proof."""
    chunks = _chunks(8)
    manifest = ShardManifest.from_arrays(chunks)
    job = BatchJob(manifest, str(tmp_path / "out"),
                   funcs.batch_predict_scale, batch_size=1, prefetch=1)
    summary = job.run(
        num_workers=1, max_restarts=2, reassign_dead=False,
        backoff_base=0.2, working_dir=str(tmp_path / "wd"),
        worker_env={"JAX_PLATFORMS": "cpu",
                    "TFOS_CHAOS": "kill node=0 at_step=5"},
        reservation_timeout=60, shutdown_timeout=60)
    replay = ProgressLedger.replay(str(tmp_path / "out"))
    assert replay.attempts == 2, "the SIGKILL must have forced a restart"
    committed_before = replay.done_at_attempt(2)
    assert len(committed_before) >= 1, "non-vacuous: work committed pre-kill"
    assert replay.reprocessed_committed == []
    assert summary["skipped_committed"] == len(committed_before)
    assert job.results() == _expected(chunks)  # byte-identical to oracle


def test_batch_job_reassigns_dead_worker_without_restart(tmp_path):
    """In-flight healing: with a survivor available, a SIGKILLed worker's
    outstanding shards are requeued (classified by the serving-mode
    monitor or the collector's dead socket) and the job completes in ONE
    attempt; the corpse's exit is tolerated at shutdown."""
    # paced scorer + enough shards that the queue can't drain before
    # node 1 reaches its trigger step: free-running over 8 tiny chunks,
    # a head start for node 0 occasionally finished the whole job
    # before node 1 got anything outstanding to heal
    # (handled_workers == [] flake)
    chunks = _chunks(24)
    job = BatchJob(ShardManifest.from_arrays(chunks), str(tmp_path / "out"),
                   funcs.batch_predict_scale_paced, batch_size=1, prefetch=1)
    summary = job.run(
        num_workers=2, max_restarts=2, reassign_dead=True,
        backoff_base=0.2, working_dir=str(tmp_path / "wd"),
        worker_env={"JAX_PLATFORMS": "cpu",
                    "TFOS_CHAOS": "kill node=1 at_step=3"},
        reservation_timeout=60, shutdown_timeout=60)
    replay = ProgressLedger.replay(str(tmp_path / "out"))
    assert replay.attempts == 1, "no restart: healed in flight"
    assert summary["handled_workers"] == [1]
    assert summary["requeued"] >= 1
    assert replay.reprocessed_committed == []
    assert job.results() == _expected(chunks)


def test_grid_search_multiplexes_trials_one_cluster(tmp_path):
    chunks = _chunks(3)
    gs = GridSearch(ShardManifest.from_arrays(chunks), str(tmp_path / "out"),
                    funcs.batch_predict_scale,
                    param_grid={"scale": [1.0, 3.0]}, batch_size=2)
    summary = gs.run(num_workers=2, max_restarts=0,
                     worker_env={"JAX_PLATFORMS": "cpu"},
                     working_dir=str(tmp_path / "wd"),
                     reservation_timeout=60, shutdown_timeout=60)
    assert summary["scored"] == 6  # 2 trials x 3 shards, one dispatch
    assert summary["trials"] == {"t0": {"scale": 1.0}, "t1": {"scale": 3.0}}
    assert gs.trial_results("t0") == _expected(chunks, scale=1.0)
    assert gs.trial_results("t1") == _expected(chunks, scale=3.0)
    with pytest.raises(KeyError, match="t9"):
        gs.trial_manifest("t9")
