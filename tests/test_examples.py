"""Example-driver smoke tests.

The reference treats ``examples/`` as its de-facto system tests (SURVEY.md
§4: "examples double as the de-facto system tests"); here the fastest three
run in CI as subprocesses with tiny shapes.  The heavier drivers
(resnet_cifar, unet_segmentation, bert_squad, wide_deep_criteo) share the
same harness and are exercised manually / by the driver rounds.
"""

import os
import subprocess
import sys

import pytest

# The slow tail of the suite (each test spawns a fresh interpreter that
# re-imports jax).  Core development loop: ``pytest -m "not example"``;
# CI / driver rounds run the full suite.
pytestmark = pytest.mark.example

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EX = os.path.join(ROOT, "examples")


def _run(script, *argv, timeout=300, cpu_flag=True):
    # Deterministic device count for the example subprocess: the conftest's
    # 8-device XLA_FLAGS would otherwise leak in and break examples whose
    # tiny test batch isn't divisible by dp=8.
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=4"}
    cmd = [sys.executable, os.path.join(EX, script)]
    if cpu_flag:
        cmd.append("--cpu")
    proc = subprocess.run(cmd + list(argv), capture_output=True, text=True,
                          timeout=timeout, env=env, cwd=ROOT)
    assert proc.returncode == 0, f"{script} failed:\n{proc.stdout}\n{proc.stderr}"
    return proc.stdout


@pytest.mark.parametrize("int8", [False, True])
def test_mnist_spark_and_batch_inference(tmp_path, int8):
    export = str(tmp_path / "export")
    extra = ["--int8_export"] if int8 else []
    out = _run("mnist/mnist_spark.py", "--cluster_size", "2", "--steps", "6",
               "--batch_size", "16", "--num_samples", "128",
               "--export_dir", export, *extra)
    assert "mnist_spark: done" in out
    assert os.path.exists(os.path.join(export, "export_meta.json"))

    # the unchanged server consumes fp and int8 exports alike
    out = _run("utils/batch_inference.py", "--export_dir", export,
               "--num_samples", "32", "--batch_size", "16", cpu_flag=False)
    assert "ran 32 samples" in out


def test_mnist_tf_mode():
    out = _run("mnist/mnist_tf.py", "--cluster_size", "2", "--steps", "8",
               "--batch_size", "16", "--num_samples", "128")
    assert "mnist_tf: done" in out


def test_mnist_tf_mode_grain_loader():
    out = _run("mnist/mnist_tf.py", "--cluster_size", "2", "--steps", "8",
               "--batch_size", "16", "--num_samples", "128", "--grain")
    assert "mnist_tf: done" in out


def test_mnist_pipeline(tmp_path):
    out = _run("mnist/mnist_pipeline.py", "--cluster_size", "1",
               "--num_samples", "64", "--batch_size", "16",
               "--export_dir", str(tmp_path / "pipe_export"))
    assert "mnist_pipeline: done" in out
    assert "pred=" in out


# -- the four heavier drivers (VERDICT r1 weak #5: never executed in CI) ----

def test_resnet_cifar(tmp_path):
    out = _run("resnet/resnet_cifar.py", "--cluster_size", "1",
               "--batch_size", "8", "--steps", "4", "--num_samples", "64",
               "--model_dir", str(tmp_path / "ckpt"), "--ckpt_every", "2",
               timeout=600)
    assert "resnet_cifar: done" in out
    assert "eval acc" in out


def test_unet_segmentation():
    out = _run("segmentation/unet_segmentation.py", "--cluster_size", "1",
               "--batch_size", "8", "--steps", "3", "--image_size", "32",
               "--num_samples", "32", timeout=600)
    assert "unet_segmentation: done" in out


def test_wide_deep_criteo_ep_sharding():
    out = _run("wide_deep/wide_deep_criteo.py", "--cluster_size", "1",
               "--num_ps", "2", "--batch_size", "32", "--steps", "10",
               "--vocab_size", "64", "--embed_dim", "8", timeout=600)
    assert "wide_deep_criteo: done" in out
    # the PS-parity claim: embedding tables actually shard over the ep axis
    import re
    m = re.search(r"ep-sharded tables: (\d+)", out)
    assert m, f"no ep-sharding report in output:\n{out}"
    assert int(m.group(1)) > 0, "no table landed on the ep axis"
    assert "'ep': 2" in out, "mesh must have ep=2 (num_ps=2)"


def test_mnist_estimator(tmp_path):
    out = _run("mnist/mnist_estimator.py", "--cluster_size", "2",
               "--max_steps", "8", "--throttle_steps", "4",
               "--batch_size", "16", "--num_samples", "256",
               "--model_dir", str(tmp_path / "est"))
    assert "mnist_estimator: done" in out
    assert "final eval step=8" in out


def test_ring_lm_windowed_ulysses(tmp_path):
    out = _run("long_context/ring_lm.py", "--sp", "2", "--sp_impl", "ulysses",
               "--window", "32", "--seq_len", "64", "--batch_size", "4",
               "--max_steps", "6", "--model_dir", str(tmp_path / "w"),
               timeout=600)
    assert "ring_lm: done" in out


def test_ring_lm_long_context(tmp_path):
    """Both sequence-parallel constructions; the loss trajectories must
    agree (ring and ulysses compute the same attention)."""
    outs = {}
    for impl in ("ring", "ulysses"):
        out = _run("long_context/ring_lm.py", "--sp", "2", "--seq_len", "64",
                   "--max_steps", "10", "--sp_impl", impl,
                   "--model_dir", str(tmp_path / impl), timeout=600)
        assert "ring_lm: done" in out
        import re
        m = re.search(r"loss (\d+\.\d+) -> (\d+\.\d+)", out)
        outs[impl] = (float(m.group(1)), float(m.group(2)))
    assert abs(outs["ring"][1] - outs["ulysses"][1]) < 1e-3, outs


def _check_gpt_tiny(out):
    import re

    assert "gpt_tiny: done" in out
    m = re.search(r"continuation accuracy (\d\.\d+)", out)
    assert m and float(m.group(1)) >= 0.5, out


def test_multislice_train(tmp_path):
    """Hybrid-mesh training: dp crossing 2 simulated slices, ZeRO-3 fsdp
    sharding on ICI (4 devices here -> 2 slices x 2)."""
    out = _run("multislice/multislice_train.py", "--max_steps", "10",
               "--batch_size", "8",
               "--model_dir", str(tmp_path / "ms"), timeout=600)
    assert "multislice: done" in out
    assert "2 slices x 2" in out


def test_gpt_tiny(tmp_path):
    _check_gpt_tiny(_run("gpt/gpt_tiny.py", "--max_steps", "40",
                         "--model_dir", str(tmp_path / "gpt"), timeout=600))


def test_gpt_tiny_llama_arch(tmp_path):
    _check_gpt_tiny(_run("gpt/gpt_tiny.py", "--max_steps", "40",
                         "--arch", "llama", "--chunked_xent",
                         "--model_dir", str(tmp_path / "gpt_l"),
                         timeout=600))


def test_switch_lm_moe(tmp_path):
    out = _run("moe/switch_lm.py", "--ep", "2", "--max_steps", "10",
               "--model_dir", str(tmp_path / "moe"))
    assert "switch_lm: done" in out
    assert "'ep': 2" in out, "mesh must actually have ep=2"


def test_bert_squad(tmp_path):
    out = _run("bert/bert_squad.py", "--cluster_size", "1",
               "--batch_size", "4", "--steps", "3", "--num_samples", "16",
               "--seq_len", "32", "--hidden_size", "32", "--num_layers", "1",
               "--num_heads", "2", "--vocab_size", "128",
               "--export_dir", str(tmp_path / "bert_export"), timeout=600)
    assert "bert_squad: done" in out


def test_inception_imagenet(tmp_path):
    out = _run("imagenet/inception_imagenet.py", "--cluster_size", "1",
               "--batch_size", "4", "--steps", "3", "--image_size", "75",
               "--num_classes", "12", "--num_samples", "16",
               "--model_dir", str(tmp_path / "incep"), timeout=600)
    assert "inception_imagenet: done" in out


def test_streaming_train_driver_side_stop():
    # 4s stream window: at 2s a fully-loaded CI box can fail to move a
    # single batch through the queue inside the window (observed flake
    # with two bench jobs sharing the machine)
    out = _run("streaming/streaming_train.py", "--cluster_size", "2",
               "--stream_seconds", "4", "--batch_size", "8", timeout=300)
    assert "streaming_train: done" in out
    assert "stream ended after" in out


def test_serving_demo():
    out = _run("gpt/serving_demo.py", "--requests", "8", "--slots", "2")
    assert "greedy-exact" in out and "serving_demo: done" in out


def test_serving_demo_block_steps():
    out = _run("gpt/serving_demo.py", "--requests", "6", "--slots", "2",
               "--block-steps", "8")
    assert "greedy-exact" in out and "serving_demo: done" in out
    assert "block-steps k=8" in out and "steps/dispatch" in out


def test_cluster_serving():
    out = _run("gpt/cluster_serving.py", "--requests", "8", "--workers", "2",
               timeout=420)
    assert "greedy-exact across 2 workers" in out
    assert "cluster_serving: done" in out
