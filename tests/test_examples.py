"""Example-driver smoke tests.

The reference treats ``examples/`` as its de-facto system tests (SURVEY.md
§4: "examples double as the de-facto system tests"); here the fastest three
run in CI as subprocesses with tiny shapes.  The heavier drivers
(resnet_cifar, unet_segmentation, bert_squad, wide_deep_criteo) share the
same harness and are exercised manually / by the driver rounds.
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EX = os.path.join(ROOT, "examples")


def _run(script, *argv, timeout=300, cpu_flag=True):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    cmd = [sys.executable, os.path.join(EX, script)]
    if cpu_flag:
        cmd.append("--cpu")
    proc = subprocess.run(cmd + list(argv), capture_output=True, text=True,
                          timeout=timeout, env=env, cwd=ROOT)
    assert proc.returncode == 0, f"{script} failed:\n{proc.stdout}\n{proc.stderr}"
    return proc.stdout


def test_mnist_spark_and_batch_inference(tmp_path):
    export = str(tmp_path / "export")
    out = _run("mnist/mnist_spark.py", "--cluster_size", "2", "--steps", "6",
               "--batch_size", "16", "--num_samples", "128",
               "--export_dir", export)
    assert "mnist_spark: done" in out
    assert os.path.exists(os.path.join(export, "export_meta.json"))

    out = _run("utils/batch_inference.py", "--export_dir", export,
               "--num_samples", "32", "--batch_size", "16", cpu_flag=False)
    assert "ran 32 samples" in out


def test_mnist_tf_mode():
    out = _run("mnist/mnist_tf.py", "--cluster_size", "2", "--steps", "8",
               "--batch_size", "16", "--num_samples", "128")
    assert "mnist_tf: done" in out


def test_mnist_pipeline(tmp_path):
    out = _run("mnist/mnist_pipeline.py", "--cluster_size", "1",
               "--num_samples", "64", "--batch_size", "16",
               "--export_dir", str(tmp_path / "pipe_export"))
    assert "mnist_pipeline: done" in out
    assert "pred=" in out
