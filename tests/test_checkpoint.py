"""Checkpoint/export tests.

Reference analogue: the reference has no checkpoint tests of its own (it
delegates to TF, SURVEY.md §5); these cover the rebuild's model_dir /
export_dir contract used by pipeline and examples.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu import checkpoint as ckpt


@pytest.fixture
def state():
    return {
        "params": {"w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
                   "b": jnp.ones((4,))},
        "step": jnp.asarray(7, jnp.int32),
    }


class TestCheckpointManager:
    def test_save_restore_roundtrip(self, tmp_path, state):
        d = str(tmp_path / "model")
        with ckpt.CheckpointManager(d, async_save=False) as mngr:
            assert mngr.save(0, state)
        restored = ckpt.restore_checkpoint(d)
        np.testing.assert_array_equal(restored["params"]["w"], state["params"]["w"])
        assert int(restored["step"]) == 7

    def test_latest_and_max_to_keep(self, tmp_path, state):
        d = str(tmp_path / "model")
        with ckpt.CheckpointManager(d, max_to_keep=2, async_save=False) as mngr:
            for s in (1, 2, 3):
                mngr.save(s, state, force=True)
            assert mngr.latest_step() == 3
            assert list(mngr.all_steps()) == [2, 3]

    def test_restore_missing_returns_none(self, tmp_path):
        assert ckpt.restore_checkpoint(str(tmp_path / "nope")) is None

    def test_existing_step_skipped_unless_forced(self, tmp_path, state):
        d = str(tmp_path / "model")
        with ckpt.CheckpointManager(d, async_save=False) as mngr:
            assert mngr.save(5, state)
            # same step again: idempotent skip
            assert not mngr.save(5, state)
            # force=True REPLACES the step's contents
            changed = {**state, "step": jnp.asarray(99, jnp.int32)}
            assert mngr.save(5, changed, force=True)
        assert int(ckpt.restore_checkpoint(d)["step"]) == 99

    def test_restore_specific_step(self, tmp_path, state):
        d = str(tmp_path / "model")
        with ckpt.CheckpointManager(d, async_save=False) as mngr:
            mngr.save(1, state, force=True)
            state2 = dict(state, step=jnp.asarray(99, jnp.int32))
            mngr.save(2, state2, force=True)
        assert int(ckpt.restore_checkpoint(d, step=1)["step"]) == 7
        assert int(ckpt.restore_checkpoint(d, step=2)["step"]) == 99


def _linear(params, x):
    return x @ params["w"] + params["b"]


class TestExportedModel:
    def test_export_load_call(self, tmp_path):
        params = {"w": jnp.full((3, 2), 2.0), "b": jnp.ones((2,))}
        x = np.ones((4, 3), np.float32)
        d = ckpt.export_model(str(tmp_path / "export"), _linear, params, [x],
                              input_names=["features"], output_names=["logits"])
        model = ckpt.ExportedModel.load(d)
        out = model.signature()(x)
        np.testing.assert_allclose(out["logits"], np.full((4, 2), 7.0))

    def test_batch_polymorphic(self, tmp_path):
        params = {"w": jnp.ones((3, 2)), "b": jnp.zeros((2,))}
        d = ckpt.export_model(str(tmp_path / "e"), _linear, params,
                              [np.ones((4, 3), np.float32)])
        model = ckpt.ExportedModel.load(d)
        for batch in (1, 4, 17):
            out = model(np.ones((batch, 3), np.float32))
            assert out["output_0"].shape == (batch, 2)

    def test_named_inputs_and_signature_key(self, tmp_path):
        params = {"w": jnp.ones((3, 2)), "b": jnp.zeros((2,))}
        d = ckpt.export_model(
            str(tmp_path / "e"), _linear, params, [np.ones((2, 3), np.float32)],
            input_names=["x"], signature_name="score",
            extra_signatures={"double": (lambda p, x: 2 * _linear(p, x),
                                         [np.ones((2, 3), np.float32)])})
        model = ckpt.ExportedModel.load(d)
        a = model.signature("score")(x=np.ones((2, 3), np.float32))["output_0"]
        b = model.signature("double")(np.ones((2, 3), np.float32))["output_0"]
        np.testing.assert_allclose(b, 2 * a)
        with pytest.raises(KeyError):
            model.signature("missing")

    def test_tag_mismatch_raises(self, tmp_path):
        params = {"w": jnp.ones((3, 2)), "b": jnp.zeros((2,))}
        d = ckpt.export_model(str(tmp_path / "e"), _linear, params,
                              [np.ones((2, 3), np.float32)], tags=("serve",))
        ckpt.ExportedModel.load(d, tag_set="serve")  # ok
        with pytest.raises(ValueError):
            ckpt.ExportedModel.load(d, tag_set="serve,gpu")

    def test_non_chief_skips(self, tmp_path):
        out = ckpt.export_model(str(tmp_path / "e"), _linear, {}, [],
                                is_chief=False)
        assert out is None
        assert not os.path.exists(str(tmp_path / "e"))

    def test_multi_input_polymorphic(self, tmp_path):
        """Two+ inputs must share one symbolic scope for the batch dim."""
        params = {"w": jnp.ones((3, 2))}

        def fn(p, x, mask):
            return (x @ p["w"]) * mask

        d = ckpt.export_model(str(tmp_path / "e"), fn, params,
                              [np.ones((4, 3), np.float32),
                               np.ones((4, 2), np.float32)],
                              input_names=["x", "mask"])
        model = ckpt.ExportedModel.load(d)
        out = model(np.ones((9, 3), np.float32), np.ones((9, 2), np.float32))
        assert out["output_0"].shape == (9, 2)

    def test_extra_signature_different_arity(self, tmp_path):
        """input_names apply to the main signature only; an extra signature
        with different arity keeps correct positional metadata."""
        params = {"w": jnp.ones((3, 2))}
        d = ckpt.export_model(
            str(tmp_path / "e"), lambda p, x: x @ p["w"], params,
            [np.ones((2, 3), np.float32)], input_names=["features"],
            extra_signatures={
                "masked": (lambda p, x, m: (x @ p["w"]) * m,
                           [np.ones((2, 3), np.float32),
                            np.ones((2, 2), np.float32)])})
        model = ckpt.ExportedModel.load(d)
        sig = model.signature("masked")
        assert sig.input_names == ["input_0", "input_1"]
        out = sig(input_0=np.ones((5, 3), np.float32),
                  input_1=np.zeros((5, 2), np.float32))
        np.testing.assert_allclose(out["output_0"], np.zeros((5, 2)))

    def test_scalar_output_shape_meta(self, tmp_path):
        """A 0-d output must be recorded with shape [], not [None]."""
        params = {"w": jnp.ones((3,))}
        d = ckpt.export_model(str(tmp_path / "e"),
                              lambda p, x: jnp.sum(x @ p["w"]), params,
                              [np.ones((2, 3), np.float32)])
        model = ckpt.ExportedModel.load(d)
        spec = model.signature().spec
        assert spec["outputs"][0]["shape"] == []
        out = model(np.ones((4, 3), np.float32))
        assert np.asarray(out["output_0"]).shape == ()

    def test_name_arity_mismatch_raises(self, tmp_path):
        with pytest.raises(ValueError, match="names"):
            ckpt.export_model(str(tmp_path / "e"), _linear,
                              {"w": jnp.ones((3, 2)), "b": jnp.zeros((2,))},
                              [np.ones((2, 3), np.float32)],
                              input_names=["a", "b"])

    def test_loads_without_model_code(self, tmp_path):
        """The export must be runnable from meta + stablehlo + variables
        alone (the SavedModel property) — no reference to _linear."""
        params = {"w": jnp.eye(3), "b": jnp.zeros((3,))}
        d = ckpt.export_model(str(tmp_path / "e"), _linear, params,
                              [np.ones((2, 3), np.float32)])
        model = ckpt.ExportedModel.load(d)
        x = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)
        np.testing.assert_allclose(model(x)["output_0"], x, rtol=1e-6)


def test_walk_containers_defaultdict_falls_back_to_dict():
    """Mapping subclasses whose constructor rejects a mapping (defaultdict
    wants its factory first) must not break the quant walk mid-tree
    (ADVICE r5 item 4): the rebuilt node falls back to a plain dict and
    the quantized leaves still round-trip."""
    from collections import defaultdict

    from tensorflowonspark_tpu.checkpoint import (_plainify_int8,
                                                  _requant_int8)
    from tensorflowonspark_tpu.ops import Int8Array, quantize_int8

    w = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
    params = defaultdict(list)
    params["layer"] = {"kernel": quantize_int8(w), "bias": jnp.zeros((4,))}

    plain, had_any, lshapes = _plainify_int8(params)
    assert had_any and not lshapes
    assert set(plain["layer"]["kernel"].keys()) == {"q", "scale"}

    restored = _requant_int8(plain)
    assert isinstance(restored, dict)  # documented fallback shape
    assert isinstance(restored["layer"]["kernel"], Int8Array)
    np.testing.assert_allclose(
        np.asarray(jnp.asarray(restored["layer"]["kernel"])),
        np.asarray(jnp.asarray(quantize_int8(w))))
