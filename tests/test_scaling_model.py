"""Unit tests for scripts/scaling_model.py's HLO-text core.

The heavy end (compiling workloads on virtual meshes) runs via the script
itself; these cover the pure text-processing and pricing pieces that the
artifact's numbers rest on — cheap enough for the fast tier.
"""

import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.scaling_model import (MODEL_ASSUMPTIONS, axis_bw_GBps,
                                   collective_time_s, extract_collectives)


def _hlo(body: str) -> str:
    return ("ENTRY %main (p0: bf16[128]) -> bf16[128] {\n"
            "  %x = bf16[128]{0} parameter(0)\n" + body + "\n}\n")


def test_allreduce_group_axes_and_dcn_split():
    hlo = _hlo("  ROOT %ar = bf16[128]{0} all-reduce(%x), "
               "replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add")
    # dp=8 plain: spans dp, no dcn tag without extents
    [rec] = extract_collectives(hlo, {"dp": 8}, loop_trip=1)
    assert rec["axes"] == ["dp"] and "dcn" not in rec
    # 2 slices of 4 (dcn-major): same group now crosses DCN
    [rec] = extract_collectives(hlo, {"dp": 8}, loop_trip=1,
                                dcn_extents={"dp": (2, 4)})
    assert rec["dcn"] == {"k_dcn": 2, "k_ici": 4}


def test_permute_classified_from_all_pairs():
    """One cross-slice hop bottlenecks the (parallel) permute — the tag
    must come from ALL source-target pairs, not the first."""
    cross = _hlo("  ROOT %cp = bf16[128]{0} collective-permute(%x), "
                 "source_target_pairs={{0,1},{1,2},{2,3},{3,0}}")
    [rec] = extract_collectives(cross, {"dp": 4}, loop_trip=1,
                                dcn_extents={"dp": (2, 2)})
    assert rec["dcn"] == {"k_dcn": 2, "k_ici": 1}  # {1,2} crosses

    inside = _hlo("  ROOT %cp = bf16[128]{0} collective-permute(%x), "
                  "source_target_pairs={{0,1},{2,3}}")
    [rec] = extract_collectives(inside, {"dp": 4}, loop_trip=1,
                                dcn_extents={"dp": (2, 2)})
    assert "dcn" not in rec


def test_hierarchical_allreduce_price():
    """all-reduce across 2 slices = in-slice ring phases at ICI width
    k_ici + cross-slice phase on the 1/k_ici shard at per-chip DCN."""
    B, ki, kd = 100e6, 4, 2
    bw_i = axis_bw_GBps(ki) * 1e9
    bw_d = MODEL_ASSUMPTIONS["dcn_GBps_per_chip_per_direction"] * 1e9
    want = 2 * B * (ki - 1) / ki / bw_i + 2 * (B / ki) * (kd - 1) / kd / bw_d
    got = collective_time_s("all-reduce", B, ki * kd,
                            dcn={"k_ici": ki, "k_dcn": kd})
    assert math.isclose(got, want, rel_tol=1e-12)
    # and strictly more expensive than the same bytes all-ICI
    assert got > collective_time_s("all-reduce", B, ki * kd)


def test_loop_multiplier_scales_collective_bytes():
    hlo = """
%cond (c: (s32[], bf16[128])) -> pred[] {
  %t = (s32[], bf16[128]) parameter(0)
  %i = s32[] get-tuple-element(%t), index=0
  %n = s32[] constant(7)
  ROOT %lt = pred[] compare(%i, %n), direction=LT
}
%body (b: (s32[], bf16[128])) -> (s32[], bf16[128]) {
  %t2 = (s32[], bf16[128]) parameter(0)
  %v = bf16[128]{0} get-tuple-element(%t2), index=1
  %ar = bf16[128]{0} all-reduce(%v), replica_groups={{0,1}}, to_apply=%add
  ROOT %out = (s32[], bf16[128]) tuple(%t2)
}
ENTRY %main (p0: (s32[], bf16[128])) -> (s32[], bf16[128]) {
  %p = (s32[], bf16[128]) parameter(0)
  ROOT %w = (s32[], bf16[128]) while(%p), condition=%cond, body=%body
}
"""
    [rec] = extract_collectives(hlo, {"dp": 2}, loop_trip=None)
    assert rec["loop_multiplier"] == 7
    assert rec["bytes"] == 7 * 128 * 2  # bf16


def test_reduce_scatter_priced_at_full_input_bytes():
    """The HLO result of reduce-scatter is the 1/k shard; the ring price
    bytes*(k-1)/k expects the full pre-scatter input — the extractor must
    scale the payload back up by k (all-gather needs no correction)."""
    hlo = _hlo("  ROOT %rs = bf16[256]{0} reduce-scatter(%x), "
               "replica_groups={{0,1,2,3}}, dimensions={0}, to_apply=%add")
    [rec] = extract_collectives(hlo, {"dp": 4}, loop_trip=1)
    assert rec["bytes"] == 256 * 2 * 4  # shard elems * bf16 * group size

    ag = _hlo("  ROOT %ag = bf16[1024]{0} all-gather(%x), "
              "replica_groups={{0,1,2,3}}, dimensions={0}")
    [rec] = extract_collectives(ag, {"dp": 4}, loop_trip=1)
    assert rec["bytes"] == 1024 * 2  # already the full gathered size
