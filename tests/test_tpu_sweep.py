"""Smoke tests for the on-chip sweep orchestrator (``scripts/tpu_sweep.py``).

The sweep is the evidence-capture path for every real-TPU number in
``bench_artifacts/``; the axon tunnel is up only in short windows, so a
regression that breaks a stage silently costs a whole window.  These smokes
run the stages in ``SWEEP_SMOKE`` mode (tiny shapes, CPU, ``smoke_``-prefixed
artifacts that can never clobber real-chip data) inside the example tier.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.example

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SWEEP = os.path.join(ROOT, "scripts", "tpu_sweep.py")


def _smoke_env():
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env.update({"SWEEP_SMOKE": "1", "JAX_PLATFORMS": "cpu"})
    return env


def _run_stage(*argv, timeout=420):
    proc = subprocess.run([sys.executable, SWEEP, *argv],
                          capture_output=True, text=True, timeout=timeout,
                          env=_smoke_env(), cwd=ROOT)
    assert proc.returncode == 0, (
        f"tpu_sweep {argv} failed:\n{proc.stdout}\n{proc.stderr}")
    return proc.stdout


def _remove_smoke_artifacts():
    art = os.path.join(ROOT, "bench_artifacts")
    for name in os.listdir(art):
        if name.startswith("smoke_"):
            os.remove(os.path.join(art, name))


@pytest.fixture(autouse=True)
def _clean_smoke_artifacts():
    # before AND after: a killed prior run (teardown never ran) must not
    # leave stale smoke rows for _merge_row to fold into this run's
    _remove_smoke_artifacts()
    yield
    _remove_smoke_artifacts()


def test_resnet_stage_loop_vs_eager():
    """Eager and single-dispatch fori_loop rows both land in the artifact,
    keyed separately."""
    _run_stage("--stage", "resnet", "--batch", "8")
    _run_stage("--stage", "resnet", "--batch", "8", "--loop")
    with open(os.path.join(ROOT, "bench_artifacts",
                           "smoke_resnet_sweep.json")) as f:
        rows = json.load(f)["rows"]
    keys = {(r["batch"], r["remat"], r["stem"], r["bn"], r["loop"])
            for r in rows}
    assert (8, False, "conv7", "f32", False) in keys
    assert (8, False, "conv7", "f32", True) in keys
    assert all(r["images_per_sec"] > 0 for r in rows)


def test_gpt_train_stage():
    _run_stage("--stage", "gpt_train", "--batch", "2")
    with open(os.path.join(ROOT, "bench_artifacts",
                           "smoke_gpt_train_sweep.json")) as f:
        rows = json.load(f)["rows"]
    assert rows and rows[0]["tokens_per_sec"] > 0
    # the analytic count (the MFU numerator) must be populated
    assert rows[0]["flops_analytic"] > 0


def test_only_filter_respects_given_order():
    """--only runs stages in the order GIVEN, not list-definition order —
    so a resume can put diagnosis stages first in a short tunnel window."""
    sys.path.insert(0, os.path.join(ROOT, "scripts"))
    try:
        from tpu_sweep import _select_stages
    finally:
        sys.path.pop(0)
    stages = [("a", ["x"], 1), ("b", ["y"], 2), ("c", ["z"], 3)]
    assert [s[0] for s in _select_stages(stages, "c,a")] == ["c", "a"]
    assert [s[0] for s in _select_stages(stages, "b, c ,b")] == ["b", "c"]
    with pytest.raises(SystemExit):
        _select_stages(stages, "c,nope")


def test_commit_artifacts_is_pathspec_scoped(tmp_path, monkeypatch):
    """--git-commit must never sweep operator-staged files into the
    auto-generated artifact commit, and must skip cleanly when the stage
    wrote nothing."""
    repo = tmp_path
    def git(*a):
        return subprocess.run(["git", *a], cwd=repo, capture_output=True,
                              text=True, check=True)
    git("init", "-q", ".")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    (repo / "bench_artifacts").mkdir()
    (repo / "f.txt").write_text("base")
    git("add", ".")
    git("commit", "-qm", "init")
    # operator stages unrelated work; a stage writes a fresh artifact
    (repo / "f.txt").write_text("wip")
    git("add", "f.txt")
    (repo / "bench_artifacts" / "a.json").write_text("{}")

    sys.path.insert(0, os.path.join(ROOT, "scripts"))
    try:
        import tpu_sweep
    finally:
        sys.path.pop(0)
    monkeypatch.setattr(tpu_sweep, "REPO", str(repo))
    tpu_sweep._commit_artifacts("teststage")

    last = git("show", "--name-only", "--format=%s", "HEAD").stdout
    assert "sweep artifacts" in last and "bench_artifacts/a.json" in last
    assert "f.txt" not in last, "operator-staged file swept into commit"
    staged = git("diff", "--cached", "--name-only").stdout.split()
    assert staged == ["f.txt"], "operator's staged work must survive"
    # idempotent: nothing new -> no commit
    head = git("rev-parse", "HEAD").stdout
    tpu_sweep._commit_artifacts("teststage")
    assert git("rev-parse", "HEAD").stdout == head


def test_only_filter_validates_before_probe():
    """A typo'd stage name fails fast — before the (slow) TPU probe."""
    proc = subprocess.run(
        [sys.executable, SWEEP, "--only", "definitely_not_a_stage"],
        capture_output=True, text=True, timeout=60, env=_smoke_env(),
        cwd=ROOT)
    assert proc.returncode != 0
    assert "not in the stage list" in proc.stderr


def test_serving_stage_dual_regime():
    """The serving stage reports both arrival regimes (steady backlog +
    bursty waves) with occupancy, admission fraction, and batched
    prefill-dispatch counts."""
    _run_stage("--stage", "serving", timeout=560)
    with open(os.path.join(ROOT, "bench_artifacts",
                           "smoke_serving_throughput.json")) as f:
        row = json.load(f)
    for label in ("steady", "bursty"):
        assert row[f"{label}_tps"] > 0
        assert 0 < row[f"{label}_occupancy"] <= 1
        assert 0 <= row[f"{label}_admission_frac"] < 1
        # batched group admission: fewer prefill dispatches than requests
        assert row[f"{label}_prefill_dispatches"] < row["requests"]
    assert row["static_occupancy"] <= 1
    assert row["speedup_bursty"] > 0
    # speculative row: repetitive traffic must actually accept drafts
    assert row["spec_acceptance"] > 0
    assert row["spec_tokens_per_dispatch"] > 1


def test_bert_squad_stage_l5_path():
    """The BERT-SQuAD stage drives the real L5 pipeline (TFEstimator.fit
    -> cluster -> queue feed) and reports a measured row via the result
    file."""
    _run_stage("--stage", "bert_squad", timeout=560)
    with open(os.path.join(ROOT, "bench_artifacts",
                           "smoke_bert_squad.json")) as f:
        row = json.load(f)
    assert row["examples_per_sec"] > 0
    assert row["timed_steps"] >= 5
    assert 0 <= row["feed_wait_frac"] < 1
    assert "TFEstimator" in row["path"]
    import math
    assert math.isfinite(row["loss"])


def test_mfu_attack_join(tmp_path, monkeypatch):
    """mfu_attack joins profile + roofline + flag rows into a ranked
    verdict, and degrades to named pendings when captures are missing."""
    import importlib.util as ilu

    spec = ilu.spec_from_file_location(
        "mfu_attack", os.path.join(ROOT, "scripts", "mfu_attack.py"))
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    art = tmp_path / "bench_artifacts"
    art.mkdir()
    monkeypatch.setattr(mod, "ART", str(art))
    monkeypatch.setattr(mod, "REPO", str(tmp_path))

    (art / "resnet_profile_b256.json").write_text(json.dumps({
        "category_pct": {"convolution fusion": 60.0, "copy": 25.0,
                         "all-reduce": 15.0},
        "top_ops": [{"category": "copy", "op": "copy.1", "self_us": 90.0,
                     "pct": 25.0}]}))
    (art / "resnet_mxu_ceiling.json").write_text(json.dumps({
        "configs": [{"batch": 256, "padding_ceiling_mfu": 0.73,
                     "worst_tile_layers": [{"layer": "s1b1_1x1a",
                                            "tile_efficiency": 0.3}]}]}))
    (art / "resnet_sweep.json").write_text(json.dumps({"rows": [
        {"batch": 256, "remat": False, "stem": "conv7", "bn": "f32",
         "loop": False, "xla": "", "images_per_sec": 2000.0, "mfu": 0.24},
        {"batch": 256, "remat": False, "stem": "conv7", "bn": "f32",
         "loop": False, "xla": "vmem96", "images_per_sec": 2100.0,
         "mfu": 0.252},
        {"batch": 256, "remat": False, "stem": "conv7", "bn": "f32",
         "loop": False, "xla": "nolhs", "images_per_sec": 1900.0,
         "mfu": 0.228}]}))

    import sys as _sys
    monkeypatch.setattr(_sys, "argv", ["mfu_attack.py"])
    mod.main()
    out = json.loads((art / "mfu_attack.json").read_text())
    assert out["pending"] == []
    assert out["non_conv_pct"] == 40.0
    assert out["flag_attack"][0]["xla"] == "vmem96"
    assert out["flag_attack"][0]["speedup_vs_control"] == 1.05
    assert "vmem96" in out["verdict"] and "1.050x" in out["verdict"]
    assert "40.0%" in out["verdict"]


def test_parse_compiler_options_coerces_types():
    """--compiler-options values that look like ints/bools must reach
    compile() typed — PJRT rejects stringly-typed values for typed
    options with an opaque compile-time error (ADVICE r5 item 3)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("tpu_sweep_mod", SWEEP)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    parse = mod._parse_compiler_options

    assert parse("xla_tpu_scoped_vmem_limit_kib=98304") == {
        "xla_tpu_scoped_vmem_limit_kib": 98304}
    assert parse("a=true,b=False,c=text,d=-3,e=0.5") == {
        "a": True, "b": False, "c": "text", "d": -3, "e": 0.5}
    with pytest.raises(ValueError, match="k=v"):
        parse("novalue")
