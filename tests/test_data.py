"""Host-local input pipeline (``data.Dataset``): the InputMode.TENSORFLOW
layer.  Reference semantics being matched: ``tf.data.Dataset`` — shard by
stride, windowed shuffle, structure-aware batching, background prefetch
(SURVEY.md §2b "TFRecord readers on TPU-VM hosts").
"""

import numpy as np
import pytest

from tensorflowonspark_tpu.data import Dataset, device_prefetch
from tensorflowonspark_tpu.example_proto import encode_example
from tensorflowonspark_tpu.tfrecord import write_records


def test_tensor_slices_array_and_tuple_and_dict():
    assert [int(x) for x in Dataset.from_tensor_slices([1, 2, 3])] == [1, 2, 3]

    xs, ys = np.arange(4), np.arange(4) * 10
    pairs = list(Dataset.from_tensor_slices((xs, ys)))
    assert [(int(a), int(b)) for a, b in pairs] == [(0, 0), (1, 10), (2, 20), (3, 30)]

    # a list of lists is a tensor sliced on axis 0, not a structure
    rows = list(Dataset.from_tensor_slices([[1, 2], [3, 4]]))
    assert np.array_equal(rows[0], [1, 2]) and np.array_equal(rows[1], [3, 4])

    d = list(Dataset.from_tensor_slices({"a": xs, "b": ys}))
    assert d[2] == {"a": 2, "b": 20}


def test_shard_exact_partition():
    ds = Dataset.from_tensor_slices(list(range(10)))
    shards = [[int(x) for x in ds.shard(3, i)] for i in range(3)]
    assert shards == [[0, 3, 6, 9], [1, 4, 7], [2, 5, 8]]
    assert sorted(sum(shards, [])) == list(range(10))


def test_map_filter_take_skip_repeat():
    ds = (Dataset.from_tensor_slices(list(range(10)))
          .map(lambda x: int(x) * 2)
          .filter(lambda x: x % 4 == 0))
    assert list(ds) == [0, 4, 8, 12, 16]
    assert list(ds.take(2)) == [0, 4]
    assert list(ds.skip(3)) == [12, 16]
    assert list(ds.take(2).repeat(3)) == [0, 4] * 3
    # re-iteration restarts from the source (tf.data semantics)
    assert list(ds) == [0, 4, 8, 12, 16]


def test_parallel_map_preserves_order():
    ds = Dataset.from_tensor_slices(list(range(64))).map(
        lambda x: int(x) ** 2, num_parallel=8)
    assert list(ds) == [x ** 2 for x in range(64)]


def test_shuffle_is_permutation_and_seeded():
    src = list(range(100))
    ds = Dataset.from_tensor_slices(src)
    a = [int(x) for x in ds.shuffle(16, seed=7)]
    b = [int(x) for x in ds.shuffle(16, seed=7)]
    c = [int(x) for x in ds.shuffle(16, seed=8)]
    assert sorted(a) == src and a == b
    assert a != src  # actually shuffled
    assert a != c


def test_batch_stacks_structures():
    xs = np.arange(10, dtype=np.float32)
    ys = np.arange(10, dtype=np.int32)
    batches = list(Dataset.from_tensor_slices((xs, ys)).batch(4))
    assert [b[0].shape[0] for b in batches] == [4, 4, 2]
    assert batches[0][0].dtype == np.float32
    assert np.array_equal(batches[1][1], [4, 5, 6, 7])
    dropped = list(Dataset.from_tensor_slices((xs, ys)).batch(4, drop_remainder=True))
    assert [b[0].shape[0] for b in dropped] == [4, 4]

    dicts = list(Dataset.from_tensor_slices({"a": xs}).batch(5))
    assert dicts[0]["a"].shape == (5,)


def test_prefetch_matches_and_propagates_errors():
    ds = Dataset.from_tensor_slices(list(range(32))).map(
        lambda x: int(x) + 1).prefetch(4)
    assert list(ds) == list(range(1, 33))

    def boom(x):
        if x == 5:
            raise ValueError("boom at 5")
        return x

    bad = Dataset.from_tensor_slices(list(range(10))).map(boom).prefetch(2)
    with pytest.raises(ValueError, match="boom at 5"):
        list(bad)


def test_tfrecord_file_shard_roundtrip(tmp_path):
    # 4 files x 5 records, then shard 2 ways at file granularity
    for f in range(4):
        write_records(str(tmp_path / f"part-{f:05d}"),
                      [encode_example({"v": f * 5 + r}) for r in range(5)])
    pattern = str(tmp_path / "part-*")

    full = Dataset.from_examples(pattern)
    vals = sorted(int(d["v"]) for d in full)
    assert vals == list(range(20))

    s0 = sorted(int(d["v"]) for d in Dataset.from_examples(pattern, shard=(2, 0)))
    s1 = sorted(int(d["v"]) for d in Dataset.from_examples(pattern, shard=(2, 1)))
    assert sorted(s0 + s1) == list(range(20))
    assert s0 == list(range(0, 5)) + list(range(10, 15))  # files 0 and 2

    # more shards than files -> element-stride fallback, still exact
    parts = [sorted(int(d["v"]) for d in Dataset.from_examples(pattern, shard=(8, i)))
             for i in range(8)]
    assert sorted(sum(parts, [])) == list(range(20))
    assert all(parts)


def test_from_examples_decodes_strings_and_arrays(tmp_path):
    recs = [encode_example({"name": b"abc", "xs": [1.5, 2.5], "n": 7})]
    write_records(str(tmp_path / "one"), recs)
    (d,) = list(Dataset.from_examples(str(tmp_path / "one")))
    assert d["name"] == "abc"
    assert np.allclose(d["xs"], [1.5, 2.5])
    assert int(d["n"]) == 7


def test_device_prefetch_roundtrip():
    import jax

    ds = Dataset.from_tensor_slices(np.arange(12, dtype=np.float32)).batch(4)
    out = list(device_prefetch(iter(ds), depth=2))
    assert len(out) == 3
    assert all(isinstance(b, jax.Array) for b in out)
    assert np.array_equal(np.concatenate(out), np.arange(12))


def test_cache_on_device_replays_device_arrays():
    import jax

    calls = [0]

    def gen():
        calls[0] += 1
        yield from (np.full((2,), i, np.float32) for i in range(3))

    ds = Dataset.from_generator(gen).cache_on_device()
    first = list(ds)
    second = list(ds)
    assert calls[0] == 1, "source must be consumed exactly once"
    assert all(isinstance(b, jax.Array) for b in first)
    # replay yields the SAME device buffers (no re-transfer)
    assert all(a is b for a, b in zip(first, second))
    assert np.array_equal(np.stack(second), [[0, 0], [1, 1], [2, 2]])

    # epochs via .repeat() on top of the cache reuse the device arrays too
    ds2 = Dataset.from_generator(gen).cache_on_device().repeat(2)
    out = list(ds2)
    assert len(out) == 6 and calls[0] == 2


def test_cache_on_device_discards_partial_first_pass():
    ds = Dataset.from_tensor_slices(np.arange(4, dtype=np.float32)) \
        .batch(1).cache_on_device()
    it = iter(ds)
    next(it)  # abandon after one element
    full = list(ds)
    assert len(full) == 4, "partial pass must not be replayed as complete"


def test_cache_on_device_stale_iterator_cannot_corrupt_cache():
    ds = Dataset.from_tensor_slices(np.arange(4, dtype=np.float32)) \
        .batch(1).cache_on_device()
    stale = iter(ds)
    next(stale)                      # first pass, abandoned mid-way
    assert len(list(ds)) == 4       # second pass completes the cache
    list(stale)                     # stale iterator resumes and finishes
    replay = list(ds)               # replay must still be the clean 4
    assert [float(b[0]) for b in replay] == [0.0, 1.0, 2.0, 3.0]


def test_interleave_round_robin():
    ds = Dataset.from_tensor_slices(np.arange(3)).interleave(
        lambda i: [int(i) * 10 + j for j in range(3)], cycle_length=2)
    # sources 0 and 1 open first, round-robin; source 2 replaces whichever
    # exhausts first
    out = list(ds)
    assert sorted(out) == sorted([0, 1, 2, 10, 11, 12, 20, 21, 22])
    assert out[:4] == [0, 10, 1, 11], out  # genuinely interleaved

    # cycle_length=1 degenerates to flat_map ordering
    flat = list(Dataset.from_tensor_slices(np.arange(2)).interleave(
        lambda i: [int(i), int(i)], cycle_length=1))
    assert flat == [0, 0, 1, 1]


def test_interleave_with_sub_datasets_and_files(tmp_path):
    for i in range(2):
        write_records(str(tmp_path / f"part-{i}"),
                      [encode_example({"v": np.asarray([i * 2 + j], np.int64)})
                       for j in range(2)])
    paths = [str(tmp_path / f"part-{i}") for i in range(2)]
    ds = Dataset.from_tensor_slices(np.asarray(paths)) \
        .interleave(lambda p: Dataset.from_examples(str(p)), cycle_length=2)
    # from_examples squeezes single-element features to scalars
    vals = sorted(int(d["v"]) for d in ds)
    assert vals == [0, 1, 2, 3]


def test_host_cache_consumes_source_once():
    calls = [0]

    def gen():
        calls[0] += 1
        yield from range(4)

    ds = Dataset.from_generator(gen).cache()
    assert list(ds) == [0, 1, 2, 3]
    assert list(ds) == [0, 1, 2, 3]
    assert calls[0] == 1

    # partial pass discarded
    it = iter(Dataset.from_generator(gen).cache())
    next(it)
    # calls[0] is now 2; a fresh full pass still works


def test_host_cache_immune_to_consumer_mutation():
    ds = Dataset.from_generator(
        lambda: iter([np.arange(3, dtype=np.float32)])).cache()
    for b in ds:
        b += 100  # in-place mutation by the consumer
    replay = next(iter(ds))
    np.testing.assert_array_equal(replay, [0, 1, 2])
    replay += 7  # mutating a replayed element is private too
    np.testing.assert_array_equal(next(iter(ds)), [0, 1, 2])


def test_padded_batch_promotes_mixed_dtypes():
    ds = Dataset.from_generator(
        lambda: iter([np.array([1], np.int32),
                      np.array([2 ** 40], np.int64)])).padded_batch(2)
    b = next(iter(ds))
    assert b.dtype == np.int64
    np.testing.assert_array_equal(b, [[1], [2 ** 40]])


def test_padded_batch_pads_ragged_sequences():
    seqs = [np.arange(n, dtype=np.int32) + 1 for n in (2, 3, 1, 4)]
    ds = Dataset.from_generator(lambda: iter(seqs)).padded_batch(2)
    batches = list(ds)
    assert batches[0].shape == (2, 3)
    np.testing.assert_array_equal(batches[0], [[1, 2, 0], [1, 2, 3]])
    assert batches[1].shape == (2, 4)
    np.testing.assert_array_equal(batches[1], [[1, 0, 0, 0], [1, 2, 3, 4]])

    # dict elements + custom padding value
    dds = Dataset.from_generator(
        lambda: iter([{"x": np.ones((1,), np.float32)},
                      {"x": np.ones((3,), np.float32)}])) \
        .padded_batch(2, padding_value=-1)
    b = next(iter(dds))
    np.testing.assert_array_equal(b["x"], [[1, -1, -1], [1, 1, 1]])


def test_full_pipeline_end_to_end(tmp_path):
    """The worker-side recipe from the module docstring, minus the mesh."""
    write_records(str(tmp_path / "part-00000"),
                  [encode_example({"x": [float(i), float(i)], "y": i % 3})
                   for i in range(40)])
    ds = (Dataset.from_examples(str(tmp_path / "part-*"))
          .shard(2, 0)
          .map(lambda d: (np.asarray(d["x"], np.float32), np.int32(d["y"])))
          .shuffle(8, seed=0)
          .batch(4, drop_remainder=True)
          .prefetch(2))
    batches = list(ds)
    assert len(batches) == 5  # 20 sharded / 4
    assert batches[0][0].shape == (4, 2)
    assert batches[0][1].dtype == np.int32


class TestCheckpointableIterator:
    def test_resume_continues_exactly(self):
        from tensorflowonspark_tpu.data import Dataset

        ds = Dataset.from_tensor_slices(np.arange(20)).shuffle(
            8, seed=7).batch(2)
        it = ds.checkpointable()
        first = [np.asarray(next(it)) for _ in range(4)]
        state = it.state()
        assert state == {"elements_consumed": 4}

        # restart: a fresh iterator resumed from the saved state yields the
        # same continuation the original would have
        rest_orig = [np.asarray(b) for b in it]
        it2 = ds.checkpointable(state)
        rest_resumed = [np.asarray(b) for b in it2]
        assert len(first) + len(rest_orig) == 10
        np.testing.assert_array_equal(np.stack(rest_orig),
                                      np.stack(rest_resumed))

    def test_state_is_json_safe(self):
        import json

        from tensorflowonspark_tpu.data import Dataset

        it = Dataset.from_tensor_slices(np.arange(6)).checkpointable()
        next(it)
        assert json.loads(json.dumps(it.state())) == it.state()


def test_flat_map_concatenates_in_order():
    from tensorflowonspark_tpu.data import Dataset

    ds = Dataset.from_tensor_slices(np.arange(3)).flat_map(
        lambda x: [int(x) * 10 + i for i in range(2)])
    assert ds.as_numpy() == [0, 1, 10, 11, 20, 21]


class TestGrainIntegration:
    """InputMode.TENSORFLOW via grain (SURVEY §7: per-host sharded loaders
    standing in for tf.data-on-executor)."""

    def test_from_grain_dataloader_composes(self):
        grain = pytest.importorskip("grain.python")

        dl = grain.DataLoader(
            data_source=np.arange(8),
            sampler=grain.IndexSampler(
                8, shard_options=grain.ShardOptions(0, 1),
                shuffle=False, num_epochs=1))
        ds = Dataset.from_grain(dl).map(int).batch(4)
        batches = ds.as_numpy()
        assert [list(b) for b in batches] == [[0, 1, 2, 3], [4, 5, 6, 7]]
        # re-iteration restarts the grain pipeline (cache/repeat contract)
        assert [list(b) for b in ds.as_numpy()] == [[0, 1, 2, 3], [4, 5, 6, 7]]

    def test_from_grain_sharded_partitions_exactly(self):
        grain = pytest.importorskip("grain.python")

        md = grain.MapDataset.source(np.arange(10))
        shards = [Dataset.from_grain_sharded(md, 3, i).map(int).as_numpy()
                  for i in range(3)]
        assert sorted(sum(shards, [])) == list(range(10))
        assert all(len(s) in (3, 4) for s in shards)
        # disjoint
        assert len(set(sum(shards, []))) == 10

    def test_from_grain_sharded_shuffle_consistent_across_hosts(self):
        grain = pytest.importorskip("grain.python")

        md = grain.MapDataset.source(np.arange(12))
        a = [Dataset.from_grain_sharded(md, 2, i, shuffle=True, seed=7)
             .map(int).as_numpy() for i in range(2)]
        b = [Dataset.from_grain_sharded(md, 2, i, shuffle=True, seed=7)
             .map(int).as_numpy() for i in range(2)]
        assert a == b                       # deterministic given the seed
        assert sorted(a[0] + a[1]) == list(range(12))  # still a partition
