"""Host-side KV page pool + prefix index (``models/kv_pages.py``):
allocation, chained-hash prefix matching, refcounts, LRU eviction —
the accounting layer under the paged ``ContinuousBatcher`` (its
device-side exactness is locked by ``tests/test_serving.py``)."""

import numpy as np
import pytest

from tensorflowonspark_tpu.models.kv_pages import KVPagePool


def _p(*toks):
    return np.asarray(toks, np.int32)


def test_validation():
    with pytest.raises(ValueError, match="total_pages"):
        KVPagePool(0, 8)
    with pytest.raises(ValueError, match="power of two"):
        KVPagePool(4, 6)
    pool = KVPagePool(4, 8)
    with pytest.raises(ValueError, match="bad lease"):
        pool.admit(_p(), 4)
    with pytest.raises(ValueError, match="bad lease"):
        pool.admit(_p(1, 2, 3), 2)     # total < prompt


def test_miss_commit_release_then_hit_shares_pages():
    pool = KVPagePool(8, 4)
    prompt = np.arange(10, dtype=np.int32)      # 2 full pages + tail 2
    a = pool.admit(prompt, 14)                  # 4 logical pages
    assert a is not None and a.n_shared == 0 and a.outcome == "miss"
    assert a.tail_start == 0 and len(a.page_ids) == 4
    assert pool.free_pages() == 4
    pool.commit(a)
    assert pool.stats()["miss"] == 1
    b = pool.admit(prompt, 14)
    assert b.outcome == "hit" and b.n_shared == 2 and b.tail_start == 8
    assert b.page_ids[:2] == a.page_ids[:2], "prefix pages not shared"
    assert set(b.page_ids[2:]).isdisjoint(a.page_ids), \
        "tail pages must be private"
    pool.commit(b)
    assert pool.stats()["hit"] == 1
    pool.release(a)
    # b still holds the shared pages: they must not become evictable
    assert pool.cached_pages() == 0
    pool.release(b)
    # all pages back (2 indexed ones parked in the LRU, still cached)
    assert pool.free_pages() == 8 and pool.cached_pages() == 2
    pool.release(b)                             # idempotent
    assert pool.free_pages() == 8


def test_exact_multiple_prompt_never_shares_its_last_page():
    """A prompt of exactly k full pages caps its match at k-1: at least
    one token must be re-run for the first generated token's logits,
    and a shared page is read-only."""
    pool = KVPagePool(8, 4)
    prompt = np.arange(8, dtype=np.int32)       # exactly 2 pages
    a = pool.admit(prompt, 10)
    pool.commit(a)
    b = pool.admit(prompt, 10)
    assert b.n_shared == 1 and b.tail_start == 4
    assert b.outcome == "hit"                   # all SHAREABLE pages hit


def test_mid_page_divergence_is_copy_on_write():
    pool = KVPagePool(12, 4)
    A = np.arange(12, dtype=np.int32)
    B = A.copy()
    B[6] = 99                                   # diverges inside page 2
    a = pool.admit(A, 14)
    pool.commit(a)
    b = pool.admit(B, 14)
    assert b.outcome == "partial" and b.n_shared == 1
    assert b.page_ids[0] == a.page_ids[0]
    assert b.page_ids[1] != a.page_ids[1], "divergent page must be a copy"
    pool.commit(b)
    # the original chain is intact: A still fully hits
    c = pool.admit(A, 14)
    assert c.outcome == "hit" and c.page_ids[:2] == a.page_ids[:2]


def test_chained_hash_blocks_suffix_only_matches():
    """Page 2 of prompt A must not match page 2 of prompt B when their
    page-1 contents differ, even if the page-2 TOKENS are identical —
    the chain key digests the whole prefix."""
    pool = KVPagePool(8, 4)
    tail = [7, 7, 7, 7]
    a = pool.admit(_p(1, 2, 3, 4, *tail, 9), 12)
    pool.commit(a)
    b = pool.admit(_p(5, 6, 7, 8, *tail, 9), 12)
    assert b.outcome == "miss" and b.n_shared == 0


def test_backpressure_and_lru_eviction_order():
    pool = KVPagePool(4, 4)
    a = pool.admit(np.arange(8, dtype=np.int32), 12)     # 3 pages
    pool.commit(a)
    assert pool.admit(np.arange(8, dtype=np.int32) + 50, 12) is None, \
        "pool must refuse when free+evictable cannot cover the tail"
    pool.release(a)                 # 2 pages parked indexed, 3rd freed
    assert pool.free_pages() == 4 and pool.cached_pages() == 2
    # a new 3-page lease: takes the free pages then evicts the OLDEST
    # cached page; the newer cached page survives
    b = pool.admit(np.arange(8, dtype=np.int32) + 50, 12)
    assert b is not None
    assert pool.stats()["evictions"] >= 1
    # A's chain is now broken at its first page: at best a miss
    c = pool.admit(np.arange(8, dtype=np.int32), 12)
    assert c is None or c.outcome == "miss"


def test_matched_pages_are_protected_from_same_lease_eviction():
    """An admission whose tail allocation triggers eviction must not
    evict the very pages its own prefix match selected."""
    pool = KVPagePool(4, 4)
    a = pool.admit(np.arange(9, dtype=np.int32), 9)      # 3 pages, 2 full
    pool.commit(a)
    pool.release(a)                                      # 2 cached, 2 free
    b = pool.admit(np.arange(9, dtype=np.int32), 16)     # 4 logical pages
    assert b is not None and b.n_shared == 2
    assert set(b.page_ids[2:]).isdisjoint(b.page_ids[:2])
    assert pool.stats()["evictions"] == 0                # free pages sufficed


def test_duplicate_commit_keeps_first_copy():
    pool = KVPagePool(8, 4)
    prompt = np.arange(9, dtype=np.int32)
    a = pool.admit(prompt, 9)       # both admitted before either commits
    b = pool.admit(prompt, 9)
    assert b.outcome == "miss", "uncommitted pages must not be matchable"
    pool.commit(a)
    pool.commit(b)                  # loser: duplicate stays private
    c = pool.admit(prompt, 9)
    assert c.page_ids[:2] == a.page_ids[:2]
    pool.release(a)
    pool.release(b)
    pool.release(c)
    assert pool.free_pages() == 8


def test_abandoned_uncommitted_lease_returns_everything():
    pool = KVPagePool(8, 4)
    a = pool.admit(np.arange(9, dtype=np.int32), 12)
    pool.commit(a)
    b = pool.admit(np.arange(9, dtype=np.int32), 12)     # holds 2 shared
    pool.release(b)                 # abandoned before commit
    st = pool.stats()
    assert st["hit"] + st["miss"] + st["partial"] == 1, \
        "an uncommitted lease must not count an outcome"
    pool.release(a)
    assert pool.free_pages() == 8


def test_match_tokens_peek_is_side_effect_free():
    """The chunked-skip decision uses ``match_tokens``: it must report
    the admit-time match WITHOUT touching refcounts, stats, the LRU, or
    the free list (a trial lease could evict cached pages)."""
    pool = KVPagePool(8, 4)
    prompt = np.arange(10, dtype=np.int32)
    assert pool.match_tokens(prompt) == 0
    a = pool.admit(prompt, 14)
    pool.commit(a)
    before = (pool.free_pages(), pool.cached_pages(), pool.stats())
    assert pool.match_tokens(prompt) == 8
    assert (pool.free_pages(), pool.cached_pages(),
            pool.stats()) == before
    # exact-multiple prompts peek with the same shareable cap admit uses
    assert pool.match_tokens(prompt[:8]) == 4
    assert KVPagePool(8, 4, prefix_cache=False).match_tokens(prompt) == 0


def test_prefix_cache_disabled_never_shares():
    pool = KVPagePool(8, 4, prefix_cache=False)
    prompt = np.arange(9, dtype=np.int32)
    a = pool.admit(prompt, 9)
    pool.commit(a)
    b = pool.admit(prompt, 9)
    assert b.outcome == "miss" and b.n_shared == 0
    pool.release(a)
    pool.release(b)
    assert pool.free_pages() == 8 and pool.cached_pages() == 0


def test_adopt_indexes_every_full_prompt_page():
    """Session adoption has no ">= 1 token re-runs" cap: an exact
    k-page prompt shares/indexes ALL k pages (nothing is prefilled; the
    session already carries its first token)."""
    pool = KVPagePool(8, 4)
    prompt = np.arange(8, dtype=np.int32)       # exactly 2 pages
    a = pool.adopt(prompt, 12)                  # 3 logical pages
    assert a is not None and a.n_shared == 0 and a.outcome == "miss"
    assert len(a.page_ids) == 3
    pool.commit(a)
    # both full pages are matchable now (probe with a tail so the
    # admit-side peek's own re-run cap doesn't hide the second page)
    probe = np.concatenate([prompt, np.asarray([99], np.int32)])
    assert pool.match_tokens(probe) == 8
    b = pool.admit(prompt, 12)
    assert b.n_shared == 1, "admit must keep its re-run cap"
    pool.release(b)
    c = pool.adopt(prompt, 12)
    assert c.outcome == "hit" and c.n_shared == 2
    assert c.page_ids[:2] == a.page_ids[:2]
    pool.release(c)
    pool.release(a)


def test_adopt_matches_seeded_prefix_and_imports_only_the_tail():
    """An adopt against a pool already holding the session's system
    prefix shares those pages — the handoff imports only the unmatched
    remainder."""
    pool = KVPagePool(16, 4)
    sysp = np.arange(8, dtype=np.int32)
    seeded = pool.adopt(sysp, 8)
    pool.commit(seeded)
    pool.release(seeded)
    prompt = np.concatenate([sysp, np.asarray([9, 10], np.int32)])
    a = pool.adopt(prompt, 14)
    assert a.outcome == "hit" and a.n_shared == 2
    # pages to import = ceil(10/4) - 2 = 1 (the partial tail page)
    n_pp = -(-prompt.size // 4)
    assert len(a.page_ids[a.n_shared:n_pp]) == 1
    pool.release(a)


def test_adopt_backpressures_when_pool_dry():
    pool = KVPagePool(2, 4)
    a = pool.adopt(np.arange(4, dtype=np.int32), 8)
    assert a is not None
    assert pool.adopt(np.arange(4, dtype=np.int32) + 50, 8) is None
    pool.release(a)
    assert pool.adopt(np.arange(4, dtype=np.int32) + 50, 8) is not None


def test_adopt_cached_imports_in_order_and_respects_capacity():
    """Bare cached-page import (the standby prefix-cache clone): pages
    land in the LRU at refcount 0 — matchable immediately, evictable
    under pressure — and capacity truncation keeps chains reachable."""
    from tensorflowonspark_tpu.models.kv_pages import chain_keys

    donor = KVPagePool(8, 4)
    prompt = np.arange(12, dtype=np.int32)      # 3 full pages
    a = donor.adopt(prompt, 12)
    donor.commit(a)
    donor.release(a)
    keys = [k for k, _ in donor.export_index()]
    assert keys == chain_keys(prompt, 4)

    probe = np.concatenate([prompt, np.asarray([99], np.int32)])
    imp = KVPagePool(8, 4)
    got = imp.adopt_cached(keys)
    assert len(got) == 3 and imp.cached_pages() == 3
    assert imp.free_pages() == 8                # cached pages evictable
    assert imp.match_tokens(probe) == 12
    # re-import is a no-op (keys already indexed)
    assert imp.adopt_cached(keys) == {}

    tiny = KVPagePool(2, 4)
    trunc = tiny.adopt_cached(keys)
    assert len(trunc) == 2, "capacity truncation"
    # the truncated import keeps the chain PREFIX: 2 pages matchable
    assert tiny.match_tokens(probe) == 8


def test_hash_page_data_detects_single_byte_corruption():
    from tensorflowonspark_tpu.models.kv_pages import hash_page_data

    arrays = [np.arange(2 * 4 * 2 * 3, dtype=np.float32)
              .reshape(2, 4, 2, 3)]
    good = hash_page_data(arrays, 2)
    bad = [np.array(arrays[0], copy=True)]
    bad[0][1, 2, 1, 1] += 1e-3
    hashes = hash_page_data(bad, 2)
    assert hashes[0] == good[0] and hashes[1] != good[1]
