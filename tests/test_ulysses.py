"""Ulysses all_to_all sequence parallelism vs the dense oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu import compat
from tensorflowonspark_tpu.parallel import make_mesh
from tensorflowonspark_tpu.parallel.mesh import MeshSpec
from tensorflowonspark_tpu.parallel.ring_attention import reference_attention
from tensorflowonspark_tpu.parallel.ulysses import (ulysses_attention,
                                                    ulysses_self_attention)

B, T, H, D = 2, 16, 4, 8


def _qkv(key):
    ks = jax.random.split(key, 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape) for k in ks)


@pytest.mark.parametrize("sp,dp,causal", [(2, 1, False), (2, 2, True),
                                          (4, 2, False), (4, 1, True)])
def test_ulysses_matches_dense(sp, dp, causal):
    mesh = make_mesh(MeshSpec(sp=sp, dp=dp), devices=jax.devices()[:sp * dp])
    q, k, v = _qkv(jax.random.key(0))
    out = ulysses_self_attention(mesh, q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_padding_mask_and_grads():
    sp, dp = 2, 2
    mesh = make_mesh(MeshSpec(sp=sp, dp=dp), devices=jax.devices()[:sp * dp])
    q, k, v = _qkv(jax.random.key(1))
    mask = jnp.arange(T)[None, :] < 12  # last 4 keys padded out
    mask = jnp.broadcast_to(mask, (B, T))

    out = ulysses_self_attention(mesh, q, k, v, mask=mask)
    ref = reference_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_u(q):
        return jnp.mean(ulysses_self_attention(mesh, q, k, v, mask=mask) ** 2)

    def loss_r(q):
        return jnp.mean(reference_attention(q, k, v, mask=mask) ** 2)

    g_u = jax.jit(jax.grad(loss_u))(q)
    g_r = jax.grad(loss_r)(q)
    np.testing.assert_allclose(np.asarray(g_u), np.asarray(g_r),
                               rtol=2e-4, atol=1e-6)


def test_ulysses_head_divisibility_enforced():
    sp = 8
    mesh = make_mesh(MeshSpec(sp=sp), devices=jax.devices()[:sp])
    q, k, v = _qkv(jax.random.key(2))  # H=4 < sp=8
    with pytest.raises(ValueError, match="must divide"):
        ulysses_self_attention(mesh, q, k, v)


def test_ulysses_single_shard_falls_through():
    q, k, v = _qkv(jax.random.key(3))
    out = ulysses_attention(q, k, v, causal=True)  # outside shard_map
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_ulysses_typoed_axis_fails_loudly_inside_shard_map():
    """A wrong axis_name inside shard_map must raise, not silently compute
    local-only attention."""
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshSpec(sp=2), devices=jax.devices()[:2])
    q, k, v = _qkv(jax.random.key(4))
    spec = P(None, "sp", None, None)
    fn = compat.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis_name="sq_typo"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    with pytest.raises((NameError, Exception), match="sq_typo|unbound"):
        jax.block_until_ready(fn(q, k, v))


def test_ulysses_with_windowed_flash_inner_kernel():
    """The documented sliding-window + SP recipe: ulysses re-shards heads,
    the inner kernel is flash attention with window=W; must match the
    dense band oracle."""
    import functools

    from tensorflowonspark_tpu.ops import flash_attention

    W = 5
    mesh = make_mesh(MeshSpec(sp=2, dp=1), devices=jax.devices()[:2])
    q, k, v = _qkv(jax.random.key(7))
    out = ulysses_self_attention(
        mesh, q, k, v, causal=True,
        attn_fn=functools.partial(flash_attention, window=W,
                                  block_q=8, block_k=8))

    # dense band oracle
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (D ** 0.5)
    pos = jnp.arange(T)
    keep = (pos[:, None] >= pos[None, :]) & (pos[None, :] > pos[:, None] - W)
    s = jnp.where(keep[None, None], s.astype(jnp.float32), -jnp.inf)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1),
                     v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
