"""DataFeed batch semantics tests.

Reference model: ``tests/test_TFNode.py`` — next_batch across EndPartition
markers, should_stop, terminate (SURVEY.md §4).
"""

import numpy as np
import pytest

from tensorflowonspark_tpu.datafeed import DataFeed
from tensorflowonspark_tpu.marker import EndOfFeed, EndPartition
from tensorflowonspark_tpu.queues import QueueServer

AUTH = b"k"


@pytest.fixture()
def mgr():
    s = QueueServer(authkey=AUTH, mode="local", maxsize=64)
    s.start()
    yield s
    s.stop()


def test_next_batch_reslices_chunks(mgr):
    feed = DataFeed(mgr)
    mgr.queue_put("input", [1, 2, 3])
    mgr.queue_put("input", [4, 5, 6, 7])
    mgr.queue_put("input", EndOfFeed())
    assert feed.next_batch(5) == [1, 2, 3, 4, 5]
    assert feed.next_batch(5) == [6, 7]  # buffer drains, then EndOfFeed
    assert feed.should_stop()


def test_next_chunk_blocking_wait(mgr):
    """``next_chunk(timeout=None)`` parks across empty polls instead of
    raising — the batch-plane task-consumer shape — and still returns
    None at EndOfFeed."""
    import threading
    import time

    feed = DataFeed(mgr)
    got = []
    t = threading.Thread(
        target=lambda: got.extend((feed.next_chunk(timeout=None),
                                   feed.next_chunk(timeout=None))),
        daemon=True)
    t.start()
    time.sleep(0.3)            # both gets are parked on an empty queue
    assert t.is_alive() and got == []
    mgr.queue_put("input", {"op": "shard", "key": "s0"})
    mgr.queue_put("input", EndOfFeed())
    t.join(timeout=10)
    assert not t.is_alive()
    assert got == [{"op": "shard", "key": "s0"}, None]
    # finite timeout still raises
    feed2 = DataFeed(mgr)
    with pytest.raises(TimeoutError, match="no data"):
        feed2.next_chunk(timeout=0.2)


def test_partition_alignment(mgr):
    feed = DataFeed(mgr)
    mgr.queue_put("input", [1, 2, 3])
    mgr.queue_put("input", EndPartition())
    mgr.queue_put("input", [4, 5])
    mgr.queue_put("input", EndOfFeed())
    assert feed.next_batch(10) == [1, 2, 3]  # stops at partition edge
    assert feed.next_batch(10) == [4, 5]     # stops at end of feed
    assert feed.should_stop()
    assert feed.next_batch(10) == []


def test_empty_partition_skipped(mgr):
    feed = DataFeed(mgr)
    mgr.queue_put("input", EndPartition())
    mgr.queue_put("input", [1])
    mgr.queue_put("input", EndOfFeed())
    assert feed.next_batch(4) == [1]


def test_input_mapping_selects_columns(mgr):
    feed = DataFeed(mgr, input_mapping={"image": "x", "label": "y"})
    mgr.queue_put("input", [{"image": "img0", "label": 0, "junk": None}])
    mgr.queue_put("input", EndOfFeed())
    assert feed.next_batch(4) == [["img0", 0]]


def test_next_batch_arrays_stacks_columns(mgr):
    feed = DataFeed(mgr)
    mgr.queue_put("input", [(np.ones(3), 1), (np.zeros(3), 0)])
    mgr.queue_put("input", EndOfFeed())
    xs, ys = feed.next_batch_arrays(2)
    assert xs.shape == (2, 3)
    np.testing.assert_array_equal(ys, [1, 0])
    assert feed.next_batch_arrays(2) is None


def test_batch_results_roundtrip(mgr):
    feed = DataFeed(mgr, train_mode=False)
    feed.batch_results(["a", "b"])
    assert mgr.queue_get("output", timeout=5) == ["a", "b"]


def test_terminate_sets_state_and_drains(mgr):
    feed = DataFeed(mgr)
    for i in range(5):
        mgr.queue_put("input", [i])
    feed.terminate(drain_secs=0.5)
    assert mgr.get("state") == "terminating"
    assert feed.should_stop()
    assert mgr.queue_size("input") == 0
