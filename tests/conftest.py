"""Test fixtures.

Mirrors the reference's test backbone (SURVEY.md §4): the reference tests run
against Spark's ``local-cluster[N, cores, mem]`` master — real multi-process
distribution on one machine, fail-fast (``spark.task.maxFailures=1``).  Here
the analogue is (a) an 8-device CPU-simulated mesh inside the test process
(``--xla_force_host_platform_device_count=8``) for sharding tests, and (b)
``LocalProcessBackend`` worker processes for orchestration tests.
"""

import os

# Must happen before any jax import anywhere in the test session.
os.environ["JAX_PLATFORMS"] = "cpu"
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

import pytest  # noqa: E402

# Force the platform at conftest-import time (before any test module touches
# jax): the axon TPU plugin registered by sitecustomize otherwise wins the
# backend race and tests silently run on the real chip.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache: the suite is compile-bound on this class of
# box (~16 min cold for the core loop, mostly >1s jit compiles); cached
# re-runs skip straight to execution.  Keyed by HLO hash, so code changes
# invalidate exactly the programs they touch.
from tensorflowonspark_tpu.util import enable_compilation_cache  # noqa: E402

enable_compilation_cache(os.environ.get("TFOS_TEST_CACHE",
                                        "/tmp/tfos_test_jax_cache"),
                         min_compile_secs=0.2)
# Worker processes spawned by cluster/agent/distributed tests bootstrap
# their own jax; point their cache (node.run sets these env defaults too,
# but inherited env must carry the test dir + the lower threshold — CPU
# compiles of the tiny test models mostly fall in the 0.2-1.0s band the
# 1.0s default would skip) at the same dir so multi-process tests are
# warm on re-runs too.
os.environ.setdefault("TFOS_COMPILATION_CACHE",
                      os.environ.get("TFOS_TEST_CACHE",
                                     "/tmp/tfos_test_jax_cache"))
os.environ.setdefault("TFOS_CACHE_MIN_COMPILE_SECS", "0.2")


@pytest.fixture(scope="session")
def jax_cpu_mesh_devices():
    devices = jax.devices()
    assert len(devices) == 8, f"expected 8 simulated CPU devices, got {len(devices)}"
    return devices


@pytest.fixture()
def worker_env(tmp_path):
    """Env for spawned worker processes: force CPU, keep fail-fast."""
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    }
