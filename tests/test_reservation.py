"""Rendezvous protocol tests.

Reference model: ``tests/test_reservation.py`` — Server/Client
register/await/stop over real localhost sockets, plus timeout behavior
(SURVEY.md §4).
"""

import threading

import pytest

from tensorflowonspark_tpu.reservation import Client, Server


def test_register_and_await():
    server = Server(3)
    addr = server.start()
    infos = [{"executor_id": i, "host": "127.0.0.1", "job_name": "worker",
              "task_index": i, "port": 4000 + i} for i in range(3)]

    def _register(info):
        c = Client(addr)
        c.register(info)
        got = c.await_reservations(timeout=10)
        assert len(got) == 3
        c.close()

    threads = [threading.Thread(target=_register, args=(i,)) for i in infos]
    for t in threads:
        t.start()
    result = server.await_reservations(timeout=10)
    for t in threads:
        t.join(10)
    assert sorted(r["executor_id"] for r in result) == [0, 1, 2]
    server.stop()


def test_partial_reservations_not_done():
    server = Server(2)
    addr = server.start()
    c = Client(addr)
    c.register({"executor_id": 0})
    assert c.get_reservations() is None  # not done yet
    assert server.reservations.remaining() == 1
    c.register({"executor_id": 1})
    assert len(c.await_reservations(timeout=5)) == 2
    c.close()
    server.stop()


def test_await_timeout():
    server = Server(2)
    server.start()
    with pytest.raises(TimeoutError):
        server.await_reservations(timeout=0.5)
    server.stop()


def test_client_request_stop():
    server = Server(1)
    addr = server.start()
    c = Client(addr)
    c.register({"executor_id": 0})
    c.request_stop()
    assert server.done.wait(5)
    c.close()


def test_bootstrap_error_via_status():
    server = Server(2)
    server.start()
    status = {"error": "worker 1 crashed"}
    with pytest.raises(RuntimeError, match="worker 1 crashed"):
        server.await_reservations(timeout=5, status=status)
    server.stop()


def test_frame_version_mismatch_is_diagnosed():
    """A peer speaking a different wire format fails the FIRST frame with
    an explicit magic/version diagnostic, not a silent desync."""
    import socket as _socket
    import struct
    import threading

    from tensorflowonspark_tpu.reservation import MessageSocket

    ms = MessageSocket()
    a, b = _socket.socketpair()
    err = {}

    def recv():
        try:
            ms.receive(b)
        except Exception as e:  # noqa: BLE001 — capturing for assert
            err["e"] = e

    t = threading.Thread(target=recv)
    t.start()
    # old pre-OOB framing: plain 4-byte length prefix, no magic
    a.sendall(struct.pack(">I", 11) + b"x" * 11)
    t.join(10)
    a.close()
    b.close()
    assert isinstance(err.get("e"), EOFError)
    assert "magic/version mismatch" in str(err["e"])
