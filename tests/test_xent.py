"""Chunked LM-head cross-entropy vs the dense oracle (values + grads)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflowonspark_tpu.ops import tied_softmax_xent


def _dense_ref(hidden, table, labels):
    logits = jnp.einsum("...h,vh->...v", hidden.astype(jnp.float32),
                        table.astype(jnp.float32))
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


@pytest.mark.parametrize("chunk", [7, 32, 224])
def test_matches_dense_forward(chunk):
    V, H = 224, 16
    h = jax.random.normal(jax.random.key(0), (3, 5, H))
    t = jax.random.normal(jax.random.key(1), (V, H))
    y = jax.random.randint(jax.random.key(2), (3, 5), 0, V)
    got = tied_softmax_xent(h, t, y, chunk_size=chunk)
    np.testing.assert_allclose(got, _dense_ref(h, t, y), rtol=2e-5, atol=2e-5)


def test_matches_dense_gradients():
    V, H = 96, 8
    h = jax.random.normal(jax.random.key(0), (4, 3, H))
    t = jax.random.normal(jax.random.key(1), (V, H))
    y = jax.random.randint(jax.random.key(2), (4, 3), 0, V)

    def loss_chunked(h, t):
        return tied_softmax_xent(h, t, y, chunk_size=24).mean()

    def loss_dense(h, t):
        return _dense_ref(h, t, y).mean()

    (gh, gt) = jax.grad(loss_chunked, argnums=(0, 1))(h, t)
    (gh_r, gt_r) = jax.grad(loss_dense, argnums=(0, 1))(h, t)
    np.testing.assert_allclose(gh, gh_r, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(gt, gt_r, rtol=2e-5, atol=2e-5)


def test_bf16_hidden_fp32_loss():
    V, H = 64, 8
    h = jax.random.normal(jax.random.key(0), (2, 4, H)).astype(jnp.bfloat16)
    t = jax.random.normal(jax.random.key(1), (V, H)).astype(jnp.bfloat16)
    y = jax.random.randint(jax.random.key(2), (2, 4), 0, V)
    out = tied_softmax_xent(h, t, y, chunk_size=16)
    assert out.dtype == jnp.float32
    ref = _dense_ref(h, t, y)
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)


def test_ragged_vocab_matches_dense():
    # V=50 with chunk 16 -> 4 chunks, last one 14 columns of zero padding
    V, H = 50, 8
    h = jax.random.normal(jax.random.key(0), (3, 4, H))
    t = jax.random.normal(jax.random.key(1), (V, H))
    y = jax.random.randint(jax.random.key(2), (3, 4), 0, V)
    got = tied_softmax_xent(h, t, y, chunk_size=16)
    np.testing.assert_allclose(got, _dense_ref(h, t, y), rtol=2e-5, atol=2e-5)
    # gradients too: padded columns must contribute nothing
    gh, gt = jax.grad(lambda h, t: tied_softmax_xent(
        h, t, y, chunk_size=16).mean(), argnums=(0, 1))(h, t)
    gh_r, gt_r = jax.grad(lambda h, t: _dense_ref(h, t, y).mean(),
                          argnums=(0, 1))(h, t)
    np.testing.assert_allclose(gh, gh_r, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(gt, gt_r, rtol=2e-5, atol=2e-5)


def test_gpt_default_vocab_traces():
    # the GPT family's default vocab (50257, prime) with the default chunk
    h = jax.ShapeDtypeStruct((4, 16), jnp.float32)
    t = jax.ShapeDtypeStruct((50257, 16), jnp.float32)
    y = jax.ShapeDtypeStruct((4,), jnp.int32)
    out = jax.eval_shape(lambda h, t, y: tied_softmax_xent(h, t, y), h, t, y)
    assert out.shape == (4,)


def test_ignore_index_zero_loss_and_grad():
    # HF -100 convention: ignored tokens get loss 0 and NO gradient
    V, H = 64, 8
    h = jax.random.normal(jax.random.key(0), (3, 4, H))
    t = jax.random.normal(jax.random.key(1), (V, H))
    y = jax.random.randint(jax.random.key(2), (3, 4), 0, V)
    y = y.at[0, 1].set(-100).at[2, 3].set(-100)
    got = tied_softmax_xent(h, t, y, chunk_size=16, ignore_index=-100)
    keep = y != -100
    ref = jnp.where(keep, _dense_ref(h, t, jnp.where(keep, y, 0)), 0.0)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)
    assert got[0, 1] == 0.0 and got[2, 3] == 0.0

    def masked_mean(h, t):
        per = tied_softmax_xent(h, t, y, chunk_size=16, ignore_index=-100)
        return per.sum() / keep.sum()

    def dense_masked_mean(h, t):
        per = jnp.where(keep, _dense_ref(h, t, jnp.where(keep, y, 0)), 0.0)
        return per.sum() / keep.sum()

    gh, gt = jax.grad(masked_mean, argnums=(0, 1))(h, t)
    gh_r, gt_r = jax.grad(dense_masked_mean, argnums=(0, 1))(h, t)
    np.testing.assert_allclose(gh, gh_r, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(gt, gt_r, rtol=2e-5, atol=2e-5)
    # ignored tokens' hidden rows get exactly zero gradient
    np.testing.assert_array_equal(gh[0, 1], np.zeros(H))


def test_nonpositive_chunk_raises():
    h = jnp.zeros((2, 8))
    t = jnp.zeros((30, 8))
    y = jnp.zeros((2,), jnp.int32)
    with pytest.raises(ValueError, match="positive"):
        tied_softmax_xent(h, t, y, chunk_size=0)


def test_under_jit_and_sharded_batch(jax_cpu_mesh_devices):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    V, H = 128, 16
    mesh = Mesh(np.array(jax_cpu_mesh_devices).reshape(8), ("dp",))
    h = jax.random.normal(jax.random.key(0), (16, 4, H))
    t = jax.random.normal(jax.random.key(1), (V, H))
    y = jax.random.randint(jax.random.key(2), (16, 4), 0, V)
    hs = jax.device_put(h, NamedSharding(mesh, P("dp")))

    @jax.jit
    def f(h, t):
        return tied_softmax_xent(h, t, y, chunk_size=32).mean()

    np.testing.assert_allclose(float(f(hs, t)),
                               float(_dense_ref(h, t, y).mean()), rtol=1e-5)


def test_gpt_hidden_plus_chunked_xent_matches_logits_loss():
    from tensorflowonspark_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
                    intermediate_size=64, max_position_embeddings=32,
                    dtype=jnp.float32)
    model = GPT(cfg)
    ids = jax.random.randint(jax.random.key(0), (2, 16), 0, cfg.vocab_size)
    params = model.init(jax.random.key(1), ids)["params"]

    def loss_dense(params):
        logits = model.apply({"params": params}, ids)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], ids[:, 1:]).mean()

    def loss_chunked(params):
        h = model.apply({"params": params}, ids, method="hidden")
        table = params["tok_emb"]["embedding"]
        table = getattr(table, "value", table)
        return tied_softmax_xent(h[:, :-1], table, ids[:, 1:],
                                 chunk_size=32).mean()

    np.testing.assert_allclose(float(loss_chunked(params)),
                               float(loss_dense(params)), rtol=1e-5)
    gd = jax.grad(loss_dense)(params)
    gc = jax.grad(loss_chunked)(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, rtol=5e-4, atol=1e-5), gd, gc)


def test_bf16_gradients_track_fp32_reference():
    # many chunks: the fp32 dh carry must keep bf16 grads near the fp32 ones
    V, H = 256, 16
    h32 = jax.random.normal(jax.random.key(0), (4, 8, H))
    t32 = jax.random.normal(jax.random.key(1), (V, H))
    y = jax.random.randint(jax.random.key(2), (4, 8), 0, V)
    gh32 = jax.grad(lambda h: tied_softmax_xent(
        h, t32, y, chunk_size=16).mean())(h32)
    gh16 = jax.grad(lambda h: tied_softmax_xent(
        h, t32.astype(jnp.bfloat16), y, chunk_size=16).mean())(
            h32.astype(jnp.bfloat16))
    # bf16 inputs cost ~1e-2 relative noise; chunk-count must not amplify it
    np.testing.assert_allclose(np.asarray(gh16, np.float32), gh32,
                               rtol=0.1, atol=0.02)
