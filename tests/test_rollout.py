"""Multi-model serving + live rollout (``serving/rollout.py``).

Three layers:

- **registry units** — ``ModelRegistry``/``ModelVersion`` cataloging,
  the adapter-delta math, and the offline-eval promotion gate;
- **scheduler units** — model-labeled routing, deterministic traffic
  splits, the unknown-model typed rejection, the per-model heal grace,
  and the drain-verb hot-swap protocol, all over deterministic
  in-process fake replicas (the ``test_serving_cluster`` idiom);
- **controller units** — a real ``RolloutController`` over the real
  scheduler + fakes: a clean canary promotes, an error-spewing canary
  is caught by the metrics gate and auto-rolled back with the incumbent
  still serving.

Engine-level pieces (``load_params`` shape validation, cross-pool
prefix-page donation) ride at the bottom; the full estimator → eval →
promote → serve parity path lives in ``tests/test_estimator.py``
(isolated, like the rest of that suite).
"""

import queue as _queue
import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu.serving import (ModelRegistry, ReplicaScheduler,
                                           RequestRejected,
                                           RolloutController, RolloutError,
                                           RolloutPolicy, ServingCluster,
                                           apply_adapter)

# --------------------------------------------------------------- fakes


class _FakeBackend:
    def __init__(self, n):
        self.codes = {i: None for i in range(n)}

    def exitcodes(self):
        return dict(self.codes)

    def failed(self):
        return [i for i, c in self.codes.items() if c not in (0, None)]


def _fake_tokens(prompt, n, salt=0):
    """Deterministic 'decode', salted per model version — a pure
    function of (request, version), like the real batcher + params."""
    base = int(np.sum(np.asarray(prompt, np.int64))) + 13 * int(salt)
    return [(base + 7 * k) % 101 for k in range(n)]


class _ModelWorld:
    """N fake replicas speaking the serve queue protocol, each with a
    mutable per-replica behavior (``salt`` = which version's tokens it
    emits, ``fail`` = answer every gen with a typed error — the forced
    canary regression).  Handles the ``op="model"`` hot-swap message:
    applies the payload's ``serve_args`` behavior and acks
    ``model_swapped`` (or ``model_swap_failed`` when the payload says
    so), exactly like a drained real replica."""

    def __init__(self, n, token_delay=0.0):
        self.backend = _FakeBackend(n)
        self.cluster_info = [
            {"executor_id": i, "job_name": "worker",
             "addr": ("127.0.0.1", 0), "authkey": b"x"} for i in range(n)]
        self.cluster_meta = {"queue_shm": False}
        self.working_dir = None
        self.token_delay = token_delay
        self.behavior = {i: {"salt": 0, "fail": False} for i in range(n)}
        self.inq = {i: _queue.Queue() for i in range(n)}
        self.outq = {i: _queue.Queue() for i in range(n)}
        self.control: list = []
        self._dead: set[int] = set()
        self.threads = [threading.Thread(target=self._run, args=(i,),
                                         daemon=True) for i in range(n)]
        for t in self.threads:
            t.start()

    def _run(self, i):
        while i not in self._dead:
            try:
                item = self.inq[i].get(timeout=0.02)
            except _queue.Empty:
                continue
            if not isinstance(item, dict):
                continue
            if item.get("op") == "model" and item.get("event") == "swap":
                sa = item.get("serve_args") or {}
                if sa.get("swap_fail"):
                    self.outq[i].put({"rid": None,
                                      "event": "model_swap_failed",
                                      "error": "injected swap failure",
                                      "swap_token":
                                          item.get("swap_token")})
                    continue
                self.behavior[i] = {"salt": int(sa.get("salt", 0)),
                                    "fail": bool(sa.get("fail"))}
                self.outq[i].put({"rid": None, "event": "model_swapped",
                                  "model": item.get("model"),
                                  "version": item.get("version"),
                                  "swap_token": item.get("swap_token"),
                                  "load": 0})
                continue
            if item.get("op") != "gen":
                continue
            rid, p = item["rid"], item["prompt"]
            beh = dict(self.behavior[i])
            if beh["fail"]:
                self.outq[i].put({"rid": rid, "event": "error",
                                  "error": "injected regression",
                                  "load": 0})
                continue
            toks = _fake_tokens(p, item["max_new_tokens"], beh["salt"])
            for tok in toks:
                if i in self._dead:
                    return
                if self.token_delay:
                    time.sleep(self.token_delay)
                self.outq[i].put({"rid": rid, "event": "tok",
                                  "tokens": [tok], "load": 1})
            self.outq[i].put({"rid": rid, "event": "done", "load": 0})

    def kill(self, i):
        self._dead.add(i)
        self.backend.codes[i] = -9

    def add_replica(self):
        i = len(self.cluster_info)
        info = {"executor_id": i, "job_name": "worker",
                "addr": ("127.0.0.1", 0), "authkey": b"x"}
        self.cluster_info.append(info)
        self.backend.codes[i] = None
        self.behavior[i] = {"salt": 0, "fail": False}
        self.inq[i] = _queue.Queue()
        self.outq[i] = _queue.Queue()
        t = threading.Thread(target=self._run, args=(i,), daemon=True)
        self.threads.append(t)
        t.start()
        return info

    def add_workers(self, n, map_fun=None, tf_args=None, timeout=None):
        # a spawned gang applies the version's serve_args like a real
        # worker's builder would
        infos = [self.add_replica() for _ in range(n)]
        sa = dict(tf_args or {})
        for info in infos:
            self.behavior[int(info["executor_id"])] = {
                "salt": int(sa.get("salt", 0)),
                "fail": bool(sa.get("fail"))}
        return infos

    def retire_worker(self, eid):
        pass

    def _client_for(self, eid):
        world = self

        class _Ctl:
            def put(self, qname, item, timeout=None):
                world.control.append((eid, item))
                world.inq[eid].put(item)

        return _Ctl()

    def client(self, info):
        eid, world = info["executor_id"], self

        class _C:
            def put(self, qname, item, timeout=None):
                if eid in world._dead:
                    raise ConnectionError("replica dead")
                world.inq[eid].put(item)

            def get(self, qname, timeout=0.5):
                if eid in world._dead:
                    raise ConnectionError("replica dead")
                try:
                    return world.outq[eid].get(timeout=timeout)
                except _queue.Empty:
                    raise TimeoutError

            def close(self):
                pass

        return _C()


def _scheduler(world, **kw):
    kw.setdefault("slots_per_replica", 2)
    kw.setdefault("poll_interval", 0.05)
    return ReplicaScheduler(world, client_factory=world.client, **kw)


def _collect(req, timeout=10.0):
    toks, deadline = [], time.monotonic() + timeout
    while True:
        ev = req.events.get(timeout=max(0.01, deadline - time.monotonic()))
        if ev[0] == "tok":
            toks.extend(ev[1])
        elif ev[0] == "done":
            return toks, None
        else:
            return toks, ev


def _builder(args):  # a stand-in "model builder" for registry entries
    return None, {"w": np.zeros((2,), np.float32)}


def _tier(world, scheduler, registry=None):
    """A driver-side ServingCluster over fakes (the _standby_tier
    idiom): no frontend/monitor, real scheduler, real rollout paths.
    Mirrors ``run()``'s founding label so the labeled-tier guards see
    the same state a booted tier would."""
    tier = ServingCluster(world, scheduler, monitor=None, frontend=None,
                          address=("127.0.0.1", 0))
    tier.registry = registry
    if scheduler.default_model is not None:
        for rep in scheduler.replicas.values():
            if rep.model == scheduler.default_model:
                tier._default_model = (rep.model, rep.version)
                break
    return tier


# ------------------------------------------------------- registry units

def test_registry_register_lookup_and_validation():
    reg = ModelRegistry()
    v1 = reg.register("chat", "v1", _builder)
    assert reg.models() == ["chat"] and reg.versions("chat") == ["v1"]
    assert reg.version("chat", "v1") is v1
    assert v1.state == "registered" and not reg.promotable("chat", "v1")
    with pytest.raises(ValueError, match="already registered"):
        reg.register("chat", "v1", _builder)
    with pytest.raises(ValueError, match="exactly one"):
        reg.register("chat", "v2")
    with pytest.raises(ValueError, match="exactly one"):
        reg.register("chat", "v2", _builder, base=_builder)
    with pytest.raises(ValueError, match="adapter= needs base="):
        reg.register("chat", "v2", _builder, adapter={"w": np.ones(2)})
    with pytest.raises(KeyError, match="unknown version"):
        reg.version("chat", "v9")
    # adapter over a registered full base, by key
    v2 = reg.register("chat", "v2", base=("chat", "v1"),
                      adapter={"w": np.ones((2,), np.float32)})
    assert v2.base_builder is _builder
    assert v2.describe()["kind"] == "adapter"
    # adapter-over-adapter is rejected
    with pytest.raises(ValueError, match="adapter-over-adapter"):
        reg.register("chat", "v3", base=("chat", "v2"))
    with pytest.raises(ValueError, match="unknown state"):
        reg.mark("chat", "v1", "bogus")


def test_registry_eval_gate_and_serve_args():
    reg = ModelRegistry()
    reg.register("m", "v2", _builder, serve_args={"seed": 3})
    assert not reg.promotable("m", "v2")
    passed = reg.evaluate("m", "v2",
                          scorer=lambda rs: ({"n": len(rs)}, len(rs) == 2),
                          results=["a", "b"])
    assert passed and reg.promotable("m", "v2")
    entry = reg.version("m", "v2")
    assert entry.state == "evaluated"
    assert entry.eval_metrics == {"n": 2}
    sa = entry.serve_args()
    assert sa["serve_model"] == ("m", "v2") and sa["seed"] == 3
    assert sa["serve_model_builder"] is _builder
    assert entry.swap_payload()["builder"] is _builder
    # a failed eval leaves the version unpromotable
    reg.register("m", "v3", _builder)
    assert not reg.evaluate("m", "v3",
                            scorer=lambda rs: ({}, False), results=[])
    assert not reg.promotable("m", "v3")


def test_apply_adapter_paths_and_errors():
    params = {"a": {"kernel": np.ones((2, 2), np.float32)},
              "b": np.full((3,), 2.0, np.float32)}
    out = apply_adapter(params, {"a/kernel": np.full((2, 2), 0.5),
                                 "b": np.ones((3,))})
    np.testing.assert_allclose(np.asarray(out["a"]["kernel"]), 1.5)
    np.testing.assert_allclose(np.asarray(out["b"]), 3.0)
    # the base is untouched (adapters share it across versions)
    np.testing.assert_allclose(np.asarray(params["a"]["kernel"]), 1.0)
    with pytest.raises(ValueError, match="unknown parameter path"):
        apply_adapter(params, {"a/missing": np.ones((2, 2))})
    with pytest.raises(ValueError, match="shape"):
        apply_adapter(params, {"b": np.ones((4,))})


def test_rollout_policy_validation():
    RolloutPolicy(steps=(25, 100), bake_secs=0.0)
    with pytest.raises(ValueError, match="ending at"):
        RolloutPolicy(steps=(10, 50))
    with pytest.raises(ValueError, match="increasing"):
        RolloutPolicy(steps=(50, 10, 100))
    with pytest.raises(ValueError, match="bake_secs"):
        RolloutPolicy(bake_secs=-1)
    with pytest.raises(ValueError, match="max_e2e_ratio"):
        RolloutPolicy(max_e2e_ratio=0)


# ------------------------------------------------- model routing units

def test_model_routing_isolates_models_and_rejects_unknown():
    """Two hosted models on one scheduler: requests route only to their
    model's replicas (version-salted fake output proves it), stats keep
    per-model series apart, and an unhosted model is rejected typed."""
    world = _ModelWorld(2)
    s = _scheduler(world, model=("a", "v1")).start()
    try:
        # a fresh replica joins as model b (the deploy path's shape);
        # one founding a-gang retires (fake recv threads are per-eid,
        # so reusing a retired eid would race its draining reader)
        s.retire_replica(1)
        info = world.add_replica()
        world.behavior[int(info["executor_id"])] = {"salt": 5,
                                                    "fail": False}
        s.add_replica(info, model=("b", "v1"))
        for k in range(3):
            p = np.asarray([k + 1, 2], np.int32)
            toks, err = _collect(s.submit(p, 4, model="a"))
            assert err is None and toks == _fake_tokens(p, 4, 0)
            toks, err = _collect(s.submit(p, 4, model="b"))
            assert err is None and toks == _fake_tokens(p, 4, 5)
        # unnamed requests resolve to the tier's default model
        p = np.asarray([9], np.int32)
        toks, err = _collect(s.submit(p, 3))
        assert err is None and toks == _fake_tokens(p, 3, 0)
        m = s.metrics()
        assert m["replicas"][0]["model"] == "a"
        assert m["replicas"][2]["model"] == "b"
        assert m["models"]["a"]["v1"]["completed"] == 4
        assert m["models"]["b"]["v1"]["completed"] == 3
        assert s.model_versions("a") == {"v1": [0]}
        assert s.model_versions("b") == {"v1": [2]}
        with pytest.raises(RequestRejected) as ei:
            s.submit(p, 2, model="zebra")
        assert ei.value.reason == "unknown_model"
        # per-model metric series stay apart (the satellite's point)
        from tensorflowonspark_tpu import metrics as tpu_metrics

        snap = tpu_metrics.get_registry().snapshot()
        ttft_models = {lbl["model"] for lbl, _ in
                       snap["tfos_serving_ttft_seconds"]["samples"]}
        assert {"a", "b"} <= ttft_models
    finally:
        s.stop()


def test_traffic_split_is_deterministic_and_clearable():
    """A 50/50 then 10/90 split lands EXACT proportions over the
    dispatch-counter bucket cycle, and clearing the split restores pure
    least-outstanding routing."""
    world = _ModelWorld(2)
    s = _scheduler(world, model=("m", "v1")).start()
    try:
        s.retire_replica(1)
        info = world.add_replica()
        world.behavior[int(info["executor_id"])] = {"salt": 1,
                                                    "fail": False}
        s.add_replica(info, model=("m", "v2"))
        with pytest.raises(ValueError, match="summing to 100"):
            s.set_traffic_split("m", {"v1": 30, "v2": 30})
        s.set_traffic_split("m", {"v2": 50, "v1": 50})
        outs = []
        for k in range(10):
            p = np.asarray([k + 1], np.int32)
            toks, err = _collect(s.submit(p, 3, model="m"))
            assert err is None
            outs.append(toks == _fake_tokens(p, 3, 1))  # served by v2?
        assert sum(outs) == 5, f"50/50 split served {sum(outs)}/10 on v2"
        assert s.metrics()["traffic"] == {"m": {"v2": 50.0, "v1": 50.0}}
        s.set_traffic_split("m", {"v2": 10, "v1": 90})
        outs = []
        for k in range(20):
            p = np.asarray([40 + k], np.int32)
            toks, err = _collect(s.submit(p, 3, model="m"))
            assert err is None
            outs.append(toks == _fake_tokens(p, 3, 1))
        assert sum(outs) == 2, f"10/90 split served {sum(outs)}/20 on v2"
        s.clear_traffic_split("m")
        assert s.metrics()["traffic"] == {}
    finally:
        s.stop()


def test_saturated_model_never_blocks_the_other():
    """Head-of-line isolation: model a's only replica is busy with a
    slow stream and its queue holds a waiting request; model b's
    request must still dispatch immediately."""
    world = _ModelWorld(2, token_delay=0.15)
    s = _scheduler(world, slots_per_replica=1, overcommit=1,
                   model=("a", "v1")).start()
    try:
        s.retire_replica(1)
        info = world.add_replica()
        s.add_replica(info, model=("b", "v1"))
        blocker = s.submit(np.asarray([1], np.int32), 8, model="a")
        waiting = s.submit(np.asarray([2], np.int32), 2, model="a")
        t0 = time.monotonic()
        p = np.asarray([3], np.int32)
        toks, err = _collect(s.submit(p, 2, model="b"))
        fast = time.monotonic() - t0
        assert err is None and toks == _fake_tokens(p, 2, 0)
        assert fast < 1.0, f"model b waited {fast:.2f}s behind model a"
        for req in (blocker, waiting):
            _, err = _collect(req, timeout=15)
            assert err is None
    finally:
        s.stop()


def test_model_heal_grace_holds_then_fresh_replica_serves():
    """The per-model heal window: model b's only replica dies on a tier
    with heal paths — b's queued/new traffic is HELD (not shed) until a
    replacement registers, then completes exactly."""
    world = _ModelWorld(2, token_delay=0.05)
    s = _scheduler(world, model=("a", "v1")).start()
    s.heal_grace = 10.0
    try:
        s.retire_replica(1)
        first_b = world.add_replica()
        b_eid = int(first_b["executor_id"])
        s.add_replica(first_b, model=("b", "v1"))
        world.kill(b_eid)
        deadline = time.monotonic() + 5
        while b_eid not in s.dead_replicas() \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        # model b is dead-but-healing: admission accepts and queues
        p = np.asarray([7, 7], np.int32)
        req = s.submit(p, 3, model="b")
        time.sleep(0.3)
        assert not req.finished, "held request was shed during the heal"
        info = world.add_replica()
        s.add_replica(info, model=("b", "v1"))
        toks, err = _collect(req, timeout=10)
        assert err is None and toks == _fake_tokens(p, 3, 0)
        # model a kept serving throughout
        toks, err = _collect(s.submit(p, 2, model="a"))
        assert err is None
    finally:
        s.stop()


# ------------------------------------------------------ hot-swap units

def _swap_registry():
    reg = ModelRegistry()
    reg.register("m", "v1", _builder, serve_args={"salt": 0})
    reg.register("m", "v2", _builder, serve_args={"salt": 9})
    reg.record_eval("m", "v2", {"ok": 1}, passed=True)
    return reg


def test_hot_swap_drains_swaps_and_resumes():
    """The drain-verb hot swap end-to-end over fakes: routing stops,
    the payload ships, the replica acks, the label flips, routing
    resumes — and post-swap output is the NEW version's."""
    world = _ModelWorld(2)
    reg = _swap_registry()
    s = _scheduler(world, model=("m", "v1")).start()
    tier = _tier(world, s, registry=reg)
    try:
        tier.swap_replica_model(1, "m", "v2")
        m = s.metrics()["replicas"]
        assert m[1]["version"] == "v2" and not m[1]["draining"]
        assert s.model_versions("m") == {"v1": [0], "v2": [1]}
        # the swap message carried the registered payload
        [(eid, msg)] = [(e, i) for e, i in world.control
                        if i.get("op") == "model"]
        assert eid == 1 and msg["version"] == "v2"
        assert msg["serve_args"] == {"salt": 9}
        # v2 traffic lands on the swapped gang with v2 output
        s.set_traffic_split("m", {"v2": 100})
        p = np.asarray([4, 4], np.int32)
        toks, err = _collect(s.submit(p, 4, model="m"))
        assert err is None and toks == _fake_tokens(p, 4, 9)
    finally:
        s.stop()


def test_hot_swap_failure_keeps_old_version_routable():
    world = _ModelWorld(1)
    reg = _swap_registry()
    reg.register("m", "bad", _builder, serve_args={"swap_fail": True})
    reg.record_eval("m", "bad", {}, passed=True)
    s = _scheduler(world, model=("m", "v1")).start()
    tier = _tier(world, s, registry=reg)
    try:
        with pytest.raises(RuntimeError, match="injected swap failure"):
            tier.swap_replica_model(0, "m", "bad")
        rep = s.metrics()["replicas"][0]
        assert rep["version"] == "v1" and not rep["draining"]
        p = np.asarray([2], np.int32)
        toks, err = _collect(s.submit(p, 3, model="m"))
        assert err is None and toks == _fake_tokens(p, 3, 0)
    finally:
        s.stop()


def test_model_less_scale_up_inherits_founding_label():
    """A model-less scale_up on a multi-model tier (the autoscaler's
    call shape) must NOT register an unlabeled replica — unlabeled
    matches every model's routing while serving only the founding
    weights.  The newcomer inherits the founding (model, version)."""
    world = _ModelWorld(1)
    reg = _swap_registry()
    s = _scheduler(world, model=("m", "v1")).start()
    tier = _tier(world, s, registry=reg)
    tier._default_model = ("m", "v1")
    try:
        [eid] = tier.scale_up(1)
        rep = s.metrics()["replicas"][eid]
        assert rep["model"] == "m" and rep["version"] == "v1", rep
        p = np.asarray([2, 2], np.int32)
        toks, err = _collect(s.submit(p, 3, model="m"))
        assert err is None and toks == _fake_tokens(p, 3, 0)
    finally:
        s.stop()


def test_late_swap_ack_relabels_replica():
    """A swap ack arriving after the driver's waiter gave up still
    updates the routing label — the label always tracks the version
    actually served (the timeout path's cancel is best-effort)."""
    world = _ModelWorld(1)
    s = _scheduler(world, model=("m", "v1")).start()
    try:
        s._handle_response(s.replicas[0],
                           {"rid": None, "event": "model_swapped",
                            "model": "m", "version": "v9", "load": 0})
        rep = s.metrics()["replicas"][0]
        assert rep["version"] == "v9" and not rep["draining"]
    finally:
        s.stop()


def test_dead_model_rejects_typed_without_heal():
    """With no heal coming (heal_grace 0), a model whose last gang died
    rejects at ADMISSION (typed unknown_model) instead of accepting
    requests that can only fail no_replica."""
    world = _ModelWorld(2)
    s = _scheduler(world, model=("a", "v1")).start()
    try:
        s.retire_replica(1)
        info = world.add_replica()
        b_eid = int(info["executor_id"])
        s.add_replica(info, model=("b", "v1"))
        world.kill(b_eid)
        deadline = time.monotonic() + 5
        while b_eid not in s.dead_replicas() \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        with pytest.raises(RequestRejected) as ei:
            s.submit(np.asarray([1], np.int32), 2, model="b")
        assert ei.value.reason == "unknown_model"
        assert "no longer" in str(ei.value)
        # model a is untouched
        _, err = _collect(s.submit(np.asarray([1], np.int32), 2,
                                   model="a"))
        assert err is None
    finally:
        s.stop()


# ----------------------------------------------------- controller units

def test_rollout_refuses_unevaluated_version():
    world = _ModelWorld(1)
    reg = ModelRegistry()
    reg.register("m", "v1", _builder)
    reg.register("m", "v2", _builder)
    s = _scheduler(world, model=("m", "v1")).start()
    tier = _tier(world, s, registry=reg)
    try:
        with pytest.raises(RolloutError, match="offline eval"):
            tier.rollout("m", "v2")
    finally:
        s.stop()


def test_rollout_promotes_clean_canary():
    """A healthy canary walks every traffic step and promotes: both
    gangs end on v2, the split is cleared, the registry records
    serving/retired."""
    world = _ModelWorld(2)
    reg = _swap_registry()
    s = _scheduler(world, model=("m", "v1")).start()
    tier = _tier(world, s, registry=reg)
    try:
        # background load keeps the gate fed with canary samples
        stop = threading.Event()

        def load():
            k = 0
            while not stop.is_set():
                k += 1
                try:
                    _collect(s.submit(np.asarray([k % 11 + 1], np.int32),
                                      3, model="m"), timeout=5)
                except Exception:
                    return
                time.sleep(0.01)

        t = threading.Thread(target=load, daemon=True)
        t.start()
        ctl = tier.rollout("m", "v2", policy=RolloutPolicy(
            steps=(50, 100), bake_secs=0.4, min_samples=3,
            max_e2e_ratio=None))
        stop.set()
        t.join(5)
        assert ctl.state == "promoted", ctl.detail
        assert s.model_versions("m") == {"v2": [0, 1]}
        assert s.metrics()["traffic"] == {}
        assert reg.version("m", "v2").state == "serving"
        assert reg.version("m", "v1").state == "retired"
        # the fleet serves v2 output now
        p = np.asarray([6], np.int32)
        toks, err = _collect(s.submit(p, 3, model="m"))
        assert err is None and toks == _fake_tokens(p, 3, 9)
    finally:
        s.stop()


def test_rollout_rolls_back_on_canary_error_rate():
    """Acceptance: an injected canary regression (every request errors)
    trips the metrics gate — traffic snaps back to v1, the canary gang
    swaps back, v2 is marked rolled_back, and the incumbent never
    stopped serving."""
    world = _ModelWorld(2)
    reg = _swap_registry()
    reg.register("m", "v3", _builder, serve_args={"salt": 0, "fail": True})
    reg.record_eval("m", "v3", {"offline": "cannot see latency"},
                    passed=True)
    s = _scheduler(world, model=("m", "v1")).start()
    tier = _tier(world, s, registry=reg)
    try:
        stop = threading.Event()
        outcomes = {"ok": 0, "err": 0}

        def load():
            k = 0
            while not stop.is_set():
                k += 1
                try:
                    _, err = _collect(
                        s.submit(np.asarray([k % 11 + 1], np.int32), 3,
                                 model="m"), timeout=5)
                    outcomes["err" if err else "ok"] += 1
                except Exception:
                    return
                time.sleep(0.01)

        t = threading.Thread(target=load, daemon=True)
        t.start()
        ctl = tier.rollout("m", "v3", policy=RolloutPolicy(
            steps=(50, 100), bake_secs=0.5, min_samples=1,
            max_error_rate=0.2, max_e2e_ratio=None))
        stop.set()
        t.join(5)
        assert ctl.state == "rolled_back", ctl.detail
        assert "error rate" in ctl.detail["reason"]
        assert reg.version("m", "v3").state == "rolled_back"
        # the canary gang swapped BACK to v1; the whole fleet serves v1
        assert s.model_versions("m") == {"v1": [0, 1]}
        assert s.metrics()["traffic"] == {}
        p = np.asarray([8, 1], np.int32)
        toks, err = _collect(s.submit(p, 4, model="m"))
        assert err is None and toks == _fake_tokens(p, 4, 0)
        assert outcomes["ok"] > 0, "the incumbent stopped serving"
    finally:
        s.stop()


def test_deploy_model_requires_labeled_tier():
    """Hosting a second model beside an UNLABELED founding fleet would
    let the founding weights serve the new model's traffic (unlabeled
    replicas match every model) — deploy_model refuses up front."""
    world = _ModelWorld(1)
    reg = _swap_registry()
    s = _scheduler(world).start()            # no model label
    tier = _tier(world, s, registry=reg)
    try:
        with pytest.raises(RuntimeError, match="model-labeled tier"):
            tier.deploy_model("m", "v2")
    finally:
        s.stop()


def test_promote_phase_swap_failure_clears_split():
    """A finishing-swap failure after the steps baked clean must not
    strand the {new: 100} split: routing falls back to capacity across
    the mixed fleet and the rollout reports failed."""
    world = _ModelWorld(2)
    reg = _swap_registry()
    s = _scheduler(world, model=("m", "v1")).start()
    tier = _tier(world, s, registry=reg)
    try:
        calls = []
        real = tier.swap_replica_model

        def flaky(eid, mid, ver, timeout=None):
            calls.append(eid)
            if len(calls) >= 2:              # the finishing swap
                raise RuntimeError("injected finishing-swap failure")
            return real(eid, mid, ver, timeout=timeout)

        tier.swap_replica_model = flaky
        # min_samples=0: the promotion-evidence gate must not trip —
        # this test targets the FINISHING loop's failure cleanup
        ctl = RolloutController(tier, "m", "v2", policy=RolloutPolicy(
            steps=(100,), bake_secs=0.05, min_samples=0))
        with pytest.raises(RuntimeError, match="injected"):
            ctl.run()
        assert ctl.state == "failed"
        assert s.metrics()["traffic"] == {}, \
            "the failed promote leaked a live traffic split"
        # the mixed fleet still serves (each gang its own version)
        p = np.asarray([5], np.int32)
        toks, err = _collect(s.submit(p, 3, model="m"))
        assert err is None and toks in (_fake_tokens(p, 3, 0),
                                        _fake_tokens(p, 3, 9))
    finally:
        s.stop()


def test_rollout_needs_single_incumbent():
    world = _ModelWorld(2)
    reg = _swap_registry()
    s = _scheduler(world, model=("m", "v1")).start()
    tier = _tier(world, s, registry=reg)
    try:
        tier.swap_replica_model(1, "m", "v2")
        reg.register("m", "v4", _builder)
        reg.record_eval("m", "v4", {}, passed=True)
        with pytest.raises(RolloutError, match="exactly one incumbent"):
            tier.rollout("m", "v4")
    finally:
        s.stop()


# ------------------------------------------------- engine-level pieces

def _tiny_paged_batcher(prefill_only=False, seed=0):
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import GPT, GPTConfig
    from tensorflowonspark_tpu.models.serving import ContinuousBatcher

    cfg = GPTConfig(vocab_size=31, hidden_size=16, num_layers=1,
                    num_heads=2, intermediate_size=32,
                    max_position_embeddings=32, dtype=jnp.float32,
                    pos_encoding="rope")
    params = GPT(cfg).init(jax.random.key(seed),
                           jnp.ones((1, 4), jnp.int32))["params"]
    return ContinuousBatcher(cfg, params, max_batch=2,
                             kv_page_tokens=4, prefill_only=prefill_only)


def test_load_params_validates_tree_shapes():
    """A hot-swapped/cloned tree must match the compiled structure —
    shape or structure drift raises instead of poisoning a dispatch."""
    import jax

    b = _tiny_paged_batcher()
    good = jax.tree.map(lambda x: np.asarray(x), b.params)
    b.unload_params()
    bad = {k: v for k, v in good.items()}
    bad["extra"] = np.zeros((1,), np.float32)
    with pytest.raises(ValueError, match="structure differs"):
        b.load_params(bad)
    wrong = jax.tree.map(
        lambda x: np.zeros(tuple(np.shape(x)) + (1,), np.float32), good)
    with pytest.raises(ValueError, match="shape/dtype"):
        b.load_params(wrong)
    b.load_params(good)          # the faithful tree re-arms it
    rid = b.submit(np.asarray([1, 2, 3], np.int32), 2)
    while b.result(rid) is None:
        b.step()


def test_prefix_donation_prewarms_decode_pool():
    """Cross-pool prefix-page donation (ROADMAP item-2 leftover): a
    prefill pool's exported prefix index, imported by a decode batcher,
    turns the decode side's session adopt into a prefix HIT — the
    donated pages are matched instead of importing the session's page
    data."""
    prefill = _tiny_paged_batcher(prefill_only=True)
    decode = _tiny_paged_batcher()
    prompt = np.asarray([3, 1, 4, 1, 5, 9, 2, 6], np.int32)  # 2 pages
    # the prefill pool computes the prompt once; its index holds the
    # full prompt pages after release
    rid = prefill.submit(prompt, 4)
    prefill.step()
    sessions = prefill.take_sessions()
    assert len(sessions) == 1
    export = prefill.export_prefix_cache()
    assert export is not None and export["pages"] >= 2
    assert decode.import_prefix_cache(export) >= 2
    # a second prefill of the same prompt hands off again; the decode
    # side adopts it with its donated pages matching
    rid2 = prefill.submit(prompt, 4)
    prefill.step()
    [(_, session)] = prefill.take_sessions()
    before = decode._pages.stats()["hit"]
    brid = decode.adopt_session(session)
    decode.step()                 # seats the adoption
    assert decode._pages.stats()["hit"] == before + 1, \
        "the donated pages did not match the adopted session's prefix"
    # and the adopted stream completes
    while decode.result(brid) is None:
        decode.step()
    assert decode.sessions_adopted == 1
