"""Pipeline parallelism: schedule correctness and strategy integration.

Oracle: running the stacked stages sequentially (a plain Python loop) on one
device.  The pipelined version over a real multi-device ``pp`` mesh must
match its forward values AND its gradients — grads flow backwards through
``ppermute``, which is the part a schedule bug would silently corrupt.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflowonspark_tpu.parallel import (PipelineStrategy, make_mesh,
                                            pipeline_apply, stack_stage_params)
from tensorflowonspark_tpu.parallel.mesh import MeshSpec

HID = 16


def _stage_fn(params, x):
    """One homogeneous stage: 2-layer MLP block with residual."""
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return x + h @ params["w2"]


def _make_stage_params(key, n_stages):
    keys = jax.random.split(key, n_stages)
    return stack_stage_params([
        {"w1": jax.random.normal(k, (HID, HID)) * 0.1,
         "b1": jnp.zeros((HID,)),
         "w2": jax.random.normal(k, (HID, HID)) * 0.1}
        for k in keys])


def _sequential(stacked, x):
    n = jax.tree.leaves(stacked)[0].shape[0]
    for i in range(n):
        params_i = jax.tree.map(lambda p: p[i], stacked)
        x = _stage_fn(params_i, x)
    return x


@pytest.mark.parametrize("pp,dp,num_mb", [(4, 1, 8), (2, 2, 4), (4, 2, 5)])
def test_pipeline_matches_sequential_forward_and_grad(pp, dp, num_mb):
    mesh = make_mesh(MeshSpec(pp=pp, dp=dp),
                     devices=jax.devices()[:pp * dp])
    stacked = _make_stage_params(jax.random.key(0), pp)
    x = jax.random.normal(jax.random.key(1), (2 * num_mb * dp, HID))

    y_ref = _sequential(stacked, x)
    y_pipe = pipeline_apply(mesh, _stage_fn, stacked, x, num_microbatches=num_mb)
    np.testing.assert_allclose(np.asarray(y_pipe), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)

    def loss_pipe(p):
        return jnp.mean(pipeline_apply(mesh, _stage_fn, p, x,
                                       num_microbatches=num_mb) ** 2)

    def loss_ref(p):
        return jnp.mean(_sequential(p, x) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_ref = jax.grad(loss_ref)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g_pipe, g_ref)


def test_pipeline_rejects_bad_microbatching():
    mesh = make_mesh(MeshSpec(pp=2, dp=1), devices=jax.devices()[:2])
    stacked = _make_stage_params(jax.random.key(0), 2)
    x = jnp.zeros((6, HID))
    with pytest.raises(ValueError, match="must divide"):
        pipeline_apply(mesh, _stage_fn, stacked, x, num_microbatches=4)

    # divisible globally but not per data shard: caught up front too
    mesh2 = make_mesh(MeshSpec(pp=2, dp=2), devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="must divide"):
        pipeline_apply(mesh2, _stage_fn, stacked, jnp.zeros((4, HID)),
                       num_microbatches=4)


def test_pipeline_strategy_trains_and_matches_single_device():
    """Full train step through PipelineStrategy == unpipelined oracle step."""
    pp, dp, num_mb = 2, 2, 4
    strat = PipelineStrategy(_stage_fn, num_stages=pp, num_microbatches=num_mb,
                             dp=dp, devices=jax.devices()[:pp * dp])
    assert 0.0 < strat.bubble_fraction < 1.0
    tx = optax.sgd(0.1)

    head = jax.random.normal(jax.random.key(2), (HID, 4)) * 0.1
    x = jax.random.normal(jax.random.key(3), (8, HID))
    y = jax.random.randint(jax.random.key(4), (8,), 0, 4)

    def init_fn():
        return {"stages": _make_stage_params(jax.random.key(0), pp),
                "head": head}

    state = strat.init_state(init_fn, tx)
    # stage params born sharded over pp; head replicated
    stages_sharding = jax.tree.leaves(state.params["stages"])[0].sharding
    assert "pp" in (stages_sharding.spec[0] or ())

    def loss_fn(params, batch):
        h = strat.apply(params["stages"], batch["x"])
        logits = h @ params["head"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    step = strat.build_train_step(loss_fn)
    batch = {"x": jax.device_put(x, strat.batch_sharding()),
             "y": jax.device_put(y, strat.batch_sharding())}
    state2, metrics = step(state, batch)
    loss_pipe = float(metrics["loss"])

    # oracle: same init, sequential trunk, single device
    params0 = init_fn()

    def oracle_loss(params):
        h = _sequential(params["stages"], x)
        logits = h @ params["head"]
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    loss_ref, g_ref = jax.value_and_grad(oracle_loss)(params0)
    np.testing.assert_allclose(loss_pipe, float(loss_ref), rtol=1e-5)

    updates, _ = tx.update(g_ref, tx.init(params0), params0)
    params_ref = optax.apply_updates(params0, updates)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        jax.device_get(state2.params), params_ref)
    assert int(state2.step) == 1
