"""Pipeline parallelism: schedule correctness and strategy integration.

Oracle: running the stacked stages sequentially (a plain Python loop) on one
device.  The pipelined version over a real multi-device ``pp`` mesh must
match its forward values AND its gradients — grads flow backwards through
``ppermute``, which is the part a schedule bug would silently corrupt.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflowonspark_tpu import compat
from tensorflowonspark_tpu.parallel import (PipelineStrategy, make_mesh,
                                            pipeline_apply, stack_stage_params)
from tensorflowonspark_tpu.parallel.mesh import MeshSpec

HID = 16


def _stage_fn(params, x):
    """One homogeneous stage: 2-layer MLP block with residual."""
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return x + h @ params["w2"]


def _make_stage_params(key, n_stages):
    keys = jax.random.split(key, n_stages)
    return stack_stage_params([
        {"w1": jax.random.normal(k, (HID, HID)) * 0.1,
         "b1": jnp.zeros((HID,)),
         "w2": jax.random.normal(k, (HID, HID)) * 0.1}
        for k in keys])


def _sequential(stacked, x):
    n = jax.tree.leaves(stacked)[0].shape[0]
    for i in range(n):
        params_i = jax.tree.map(lambda p: p[i], stacked)
        x = _stage_fn(params_i, x)
    return x


@pytest.mark.parametrize("pp,dp,num_mb", [(4, 1, 8), (2, 2, 4), (4, 2, 5)])
def test_pipeline_matches_sequential_forward_and_grad(pp, dp, num_mb):
    mesh = make_mesh(MeshSpec(pp=pp, dp=dp),
                     devices=jax.devices()[:pp * dp])
    stacked = _make_stage_params(jax.random.key(0), pp)
    x = jax.random.normal(jax.random.key(1), (2 * num_mb * dp, HID))

    y_ref = _sequential(stacked, x)
    y_pipe = pipeline_apply(mesh, _stage_fn, stacked, x, num_microbatches=num_mb)
    np.testing.assert_allclose(np.asarray(y_pipe), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)

    def loss_pipe(p):
        return jnp.mean(pipeline_apply(mesh, _stage_fn, p, x,
                                       num_microbatches=num_mb) ** 2)

    def loss_ref(p):
        return jnp.mean(_sequential(p, x) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_ref = jax.grad(loss_ref)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g_pipe, g_ref)


def test_pipeline_rejects_bad_microbatching():
    mesh = make_mesh(MeshSpec(pp=2, dp=1), devices=jax.devices()[:2])
    stacked = _make_stage_params(jax.random.key(0), 2)
    x = jnp.zeros((6, HID))
    with pytest.raises(ValueError, match="must divide"):
        pipeline_apply(mesh, _stage_fn, stacked, x, num_microbatches=4)

    # divisible globally but not per data shard: caught up front too
    mesh2 = make_mesh(MeshSpec(pp=2, dp=2), devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="must divide"):
        pipeline_apply(mesh2, _stage_fn, stacked, jnp.zeros((4, HID)),
                       num_microbatches=4)


def test_pipeline_strategy_trains_and_matches_single_device():
    """Full train step through PipelineStrategy == unpipelined oracle step."""
    pp, dp, num_mb = 2, 2, 4
    strat = PipelineStrategy(_stage_fn, num_stages=pp, num_microbatches=num_mb,
                             dp=dp, devices=jax.devices()[:pp * dp])
    assert 0.0 < strat.bubble_fraction < 1.0
    tx = optax.sgd(0.1)

    head = jax.random.normal(jax.random.key(2), (HID, 4)) * 0.1
    x = jax.random.normal(jax.random.key(3), (8, HID))
    y = jax.random.randint(jax.random.key(4), (8,), 0, 4)

    def init_fn():
        return {"stages": _make_stage_params(jax.random.key(0), pp),
                "head": head}

    state = strat.init_state(init_fn, tx)
    # stage params born sharded over pp; head replicated
    stages_sharding = jax.tree.leaves(state.params["stages"])[0].sharding
    assert "pp" in (stages_sharding.spec[0] or ())

    def loss_fn(params, batch):
        h = strat.apply(params["stages"], batch["x"])
        logits = h @ params["head"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    step = strat.build_train_step(loss_fn)
    batch = {"x": jax.device_put(x, strat.batch_sharding()),
             "y": jax.device_put(y, strat.batch_sharding())}
    state2, metrics = step(state, batch)
    loss_pipe = float(metrics["loss"])

    # oracle: same init, sequential trunk, single device
    params0 = init_fn()

    def oracle_loss(params):
        h = _sequential(params["stages"], x)
        logits = h @ params["head"]
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    loss_ref, g_ref = jax.value_and_grad(oracle_loss)(params0)
    np.testing.assert_allclose(loss_pipe, float(loss_ref), rtol=1e-5)

    updates, _ = tx.update(g_ref, tx.init(params0), params0)
    params_ref = optax.apply_updates(params0, updates)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        jax.device_get(state2.params), params_ref)
    assert int(state2.step) == 1


def _head_fn(hp, y, tgt):
    """Per-microbatch loss head: linear projection + mse."""
    return jnp.mean((y @ hp["wo"] - tgt) ** 2)


def _oracle_value_and_grad(stacked, hp, x, tgt):
    """Serial single-device oracle for loss + every gradient."""
    def loss_fn(stacked, hp, x):
        return _head_fn(hp, _sequential(stacked, x), tgt)
    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
        stacked, hp, x)
    return loss, *grads


@pytest.mark.parametrize("pp,dp,num_mb", [(4, 1, 8), (2, 2, 4), (4, 2, 5),
                                          (2, 1, 1)])
def test_1f1b_matches_serial_value_and_grad(pp, dp, num_mb):
    """The interleaved (1F1B-style) schedule must reproduce the serial
    oracle's loss, stage grads, head grads, and input grad — the whole
    train pass, not just the forward."""
    from tensorflowonspark_tpu.parallel import pipeline_value_and_grad

    mesh = make_mesh(MeshSpec(pp=pp, dp=dp), devices=jax.devices()[:pp * dp])
    stacked = _make_stage_params(jax.random.key(0), pp)
    hp = {"wo": jax.random.normal(jax.random.key(2), (HID, HID)) * 0.2}
    B = 2 * num_mb * dp
    x = jax.random.normal(jax.random.key(1), (B, HID))
    tgt = jax.random.normal(jax.random.key(3), (B, HID))

    # NOTE the oracle loss is the mean over microbatches of per-mb means,
    # which equals the full-batch mean here because microbatches are
    # equal-sized
    loss, dstages, dhp, dx = jax.jit(
        lambda s, h, x, t: pipeline_value_and_grad(
            mesh, _stage_fn, _head_fn, s, h, x, t,
            num_microbatches=num_mb))(stacked, hp, x, tgt)
    want_loss, want_ds, want_dh, want_dx = _oracle_value_and_grad(
        stacked, hp, x, tgt)

    np.testing.assert_allclose(float(loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
        dstages, want_ds)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5), dhp, want_dh)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(want_dx),
                               rtol=2e-4, atol=2e-5)


def _schedule_scan_carry_elems(pp, M, mb):
    """Total element count of the 1F1B schedule scan's carry, found by
    walking the jaxpr for the LARGEST scan (the ring legs add small
    ones)."""
    from tensorflowonspark_tpu.parallel import pipeline_value_and_grad

    mesh = make_mesh(MeshSpec(pp=pp, dp=1), devices=jax.devices()[:pp])
    stacked = _make_stage_params(jax.random.key(0), pp)
    hp = {"wo": jnp.eye(HID)}
    B = M * mb
    x = jnp.ones((B, HID))
    tgt = jnp.zeros((B, HID))
    jaxpr = jax.make_jaxpr(
        lambda s, h, x, t: pipeline_value_and_grad(
            mesh, _stage_fn, _head_fn, s, h, x, t, num_microbatches=M))(
        stacked, hp, x, tgt)

    best = 0

    def walk(jx):
        nonlocal best
        for eqn in jx.eqns:
            if eqn.primitive.name == "scan":
                nc = eqn.params["num_carry"]
                consts = eqn.params["num_consts"]
                carry = eqn.invars[consts:consts + nc]
                best = max(best, sum(int(np.prod(v.aval.shape))
                                     for v in carry))
            for p in eqn.params.values():
                for q in (p if isinstance(p, (list, tuple)) else (p,)):
                    if hasattr(q, "eqns"):          # raw Jaxpr
                        walk(q)
                    elif hasattr(q, "jaxpr"):       # ClosedJaxpr
                        walk(q.jaxpr)
        return best

    walk(jaxpr.jaxpr)
    assert best > 0, "schedule did not lower to a scan"
    return best


def test_1f1b_residual_buffer_is_stage_bound_not_microbatch_bound():
    """The schedule's in-flight residual state is 2S-1 slots regardless
    of the microbatch count — the memory contract that lets M grow to
    shrink the bubble.  Asserted from the scan carry itself: growing M
    4x (8 -> 32) at fixed microbatch size grows the carry by EXACTLY the
    dx/x collector delta (the one legitimately M-sized carry entry), so
    no hidden O(M) residual exists."""
    pp, mb = 2, 2
    c8 = _schedule_scan_carry_elems(pp, 8, mb)
    c32 = _schedule_scan_carry_elems(pp, 32, mb)
    assert c32 - c8 == (32 - 8) * mb * HID, (c8, c32)


def test_1f1b_composes_with_tensor_parallel_stage():
    """The interleaved schedule with a Megatron-tp transformer stage
    (collectives INSIDE stage_fn) on a pp2·tp2 mesh matches the serial
    single-device oracle for loss and stage grads."""
    from tensorflowonspark_tpu.parallel import (make_transformer_stage,
                                                pipeline_value_and_grad)

    pp, tp, num_mb = 2, 2, 4
    hidden, heads, ffn = 16, 2, 32
    mesh = make_mesh(MeshSpec(pp=pp, tp=tp), devices=jax.devices()[:pp * tp])
    stage_fn, init_fn, param_specs = make_transformer_stage(
        hidden, heads, ffn, tp=tp, causal=True)
    keys = jax.random.split(jax.random.key(0), pp)
    stacked = stack_stage_params([init_fn(k) for k in keys])
    hp = {"wo": jax.random.normal(jax.random.key(2), (hidden, hidden)) * 0.2}
    B, T = 2 * num_mb, 8
    x = jax.random.normal(jax.random.key(1), (B, T, hidden))
    tgt = jax.random.normal(jax.random.key(3), (B, T, hidden))

    def head(hp, y, t):
        return jnp.mean((y @ hp["wo"] - t) ** 2)

    loss, ds, dh, dx = jax.jit(
        lambda s, h, x, t: pipeline_value_and_grad(
            mesh, stage_fn, head, s, h, x, t, num_microbatches=num_mb,
            param_specs=param_specs))(stacked, hp, x, tgt)

    # serial oracle: single-device mesh of the same tp width is not
    # available inside one test process; instead run the stages serially
    # UNDER the same mesh (tp collectives active, pp folded away)
    def serial_loss(stacked, hp, x):
        n = jax.tree.leaves(stacked)[0].shape[0]
        y = x
        for i in range(n):
            pi = jax.tree.map(lambda p: p[i], stacked)
            y = _tp_serial_stage(mesh, stage_fn, pi, y, param_specs)
        return head(hp, y, tgt)

    want_loss, (want_ds, want_dh, want_dx) = jax.value_and_grad(
        serial_loss, argnums=(0, 1, 2))(stacked, hp, x)
    np.testing.assert_allclose(float(loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5), ds, want_ds)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5), dh, want_dh)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(want_dx),
                               rtol=5e-4, atol=5e-5)


def _tp_serial_stage(mesh, stage_fn, params_i, x, param_specs):
    """Run ONE stage under shard_map over tp only (pp replicated).

    The ring-attention leg's internal scan needs sp-varying inputs to
    type-check even at sp=1; the size-1 pcast/psum pair is the identity.
    """
    from jax.sharding import PartitionSpec as P

    def wrapped(p, x):
        x = compat.pcast(x, ("sp",), to="varying")
        return jax.lax.psum(stage_fn(p, x), ("sp",))

    return compat.shard_map(
        wrapped, mesh=mesh,
        in_specs=(param_specs, P()), out_specs=P())(params_i, x)


def test_1f1b_sequence_sharded_dx_matches_serial():
    """With activations/targets sequence-sharded over sp, the returned
    input gradient must carry the full global-mean divisor (dp AND sp
    shards) — exact against the serial oracle."""
    from jax.sharding import PartitionSpec as P

    from tensorflowonspark_tpu.parallel import pipeline_value_and_grad

    pp, sp, num_mb = 2, 2, 4
    mesh = make_mesh(MeshSpec(pp=pp, sp=sp), devices=jax.devices()[:pp * sp])
    stacked = _make_stage_params(jax.random.key(0), pp)
    hp = {"wo": jax.random.normal(jax.random.key(2), (HID, HID)) * 0.2}
    B, T = 2 * num_mb, 4
    x = jax.random.normal(jax.random.key(1), (B, T, HID))
    tgt = jax.random.normal(jax.random.key(3), (B, T, HID))

    def head(hp, y, t):
        return jnp.mean((y @ hp["wo"] - t) ** 2)

    loss, ds, dh, dx = jax.jit(
        lambda s, h, x, t: pipeline_value_and_grad(
            mesh, _stage_fn, head, s, h, x, t, num_microbatches=num_mb,
            data_spec=P(("dp", "fsdp"), "sp", None),
            target_spec=P(("dp", "fsdp"), "sp", None)))(stacked, hp, x, tgt)

    def serial_loss(stacked, hp, x):
        return head(hp, _sequential(stacked, x), tgt)

    want_loss, (want_ds, want_dh, want_dx) = jax.value_and_grad(
        serial_loss, argnums=(0, 1, 2))(stacked, hp, x)
    np.testing.assert_allclose(float(loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5), ds, want_ds)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5), dh, want_dh)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(want_dx),
                               rtol=2e-4, atol=2e-5)


def test_pipeline_strategy_1f1b_step_matches_oracle():
    """The strategy-level 1F1B train step (state in, state out, optax
    update applied) matches the single-device oracle update exactly."""
    pp, dp, num_mb = 2, 2, 4
    strat = PipelineStrategy(_stage_fn, num_stages=pp,
                             num_microbatches=num_mb, dp=dp,
                             devices=jax.devices()[:pp * dp])
    tx = optax.sgd(0.1)
    B = 2 * num_mb * dp
    x = jax.random.normal(jax.random.key(3), (B, HID))
    tgt = jax.random.normal(jax.random.key(4), (B, HID))

    def head(hp, y, t):
        return jnp.mean((y @ hp["wo"] - t) ** 2)

    def init_fn():
        return {"stages": _make_stage_params(jax.random.key(0), pp),
                "wo": jax.random.normal(jax.random.key(2), (HID, HID)) * 0.2}

    state = strat.init_state(init_fn, tx)
    step = strat.build_train_step_1f1b(head)
    batch = (jax.device_put(x, strat.batch_sharding()),
             jax.device_put(tgt, strat.batch_sharding()))
    state2, metrics = step(state, batch)

    params0 = init_fn()

    def oracle_loss(params):
        y = _sequential(params["stages"], x)
        return head({"wo": params["wo"]}, y, tgt)

    loss_ref, g_ref = jax.value_and_grad(oracle_loss)(params0)
    np.testing.assert_allclose(float(metrics["loss"]), float(loss_ref),
                               rtol=1e-5)
    updates, _ = tx.update(g_ref, tx.init(params0), params0)
    params_ref = optax.apply_updates(params0, updates)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
        jax.device_get(state2.params), params_ref)
    assert int(state2.step) == 1


def test_pipeline_strategy_1f1b_guards_within_stage_axes():
    """A tp>1 mesh without param_specs must fail LOUDLY: stage
    collectives on replicated params would silently overcount."""
    strat = PipelineStrategy(_stage_fn, num_stages=2, num_microbatches=4,
                             tp=2, dp=1, devices=jax.devices()[:4])
    strat.init_state(
        lambda: {"stages": _make_stage_params(jax.random.key(0), 2),
                 "wo": jnp.eye(HID)}, optax.sgd(0.1))
    with pytest.raises(ValueError, match="within-stage axes"):
        strat.build_train_step_1f1b(lambda hp, y, t: jnp.mean(y))


@pytest.mark.parametrize("seed", [31, 32, 33])
def test_1f1b_fuzz_random_configs_match_serial(seed):
    """Random (pp, dp, microbatches, batch multiple) configurations all
    reproduce the serial oracle's loss and gradients — the schedule's
    index arithmetic must hold off the hand-picked test points too."""
    from tensorflowonspark_tpu.parallel import pipeline_value_and_grad

    rng = np.random.default_rng(seed)
    pp = int(rng.choice([2, 4]))
    dp = int(rng.choice([1, 2]))
    if pp * dp > len(jax.devices()):
        pp, dp = 2, 1
    num_mb = int(rng.integers(1, 9))
    per = int(rng.integers(1, 4))
    B = per * num_mb * dp
    mesh = make_mesh(MeshSpec(pp=pp, dp=dp), devices=jax.devices()[:pp * dp])
    stacked = _make_stage_params(jax.random.key(seed), pp)
    hp = {"wo": jax.random.normal(jax.random.key(seed + 1),
                                  (HID, HID)) * 0.2}
    x = jax.random.normal(jax.random.key(seed + 2), (B, HID))
    tgt = jax.random.normal(jax.random.key(seed + 3), (B, HID))

    loss, ds, dh, dx = jax.jit(
        lambda s, h, x, t: pipeline_value_and_grad(
            mesh, _stage_fn, _head_fn, s, h, x, t,
            num_microbatches=num_mb))(stacked, hp, x, tgt)
    want_loss, want_ds, want_dh, want_dx = _oracle_value_and_grad(
        stacked, hp, x, tgt)
    msg = f"seed={seed} pp={pp} dp={dp} mb={num_mb} B={B}"
    np.testing.assert_allclose(float(loss), float(want_loss),
                               rtol=1e-5, atol=1e-6, err_msg=msg)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
        err_msg=msg), (ds, dh), (want_ds, want_dh))
    np.testing.assert_allclose(np.asarray(dx), np.asarray(want_dx),
                               rtol=2e-4, atol=2e-5, err_msg=msg)
