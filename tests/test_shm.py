"""Zero-copy shared-memory data plane tests (``shm.py`` + its
``queues.py`` negotiation).  All fast-tier: CPU only, loopback + /dev/shm.

Leak assertions track the EXACT segment names a test created (via the
channel's ring) rather than global /dev/shm state, so pre-existing
segments from other tenants never flake these tests.
"""

import gc
import os
import threading

import numpy as np
import pytest

from tensorflowonspark_tpu import shm as shm_mod
from tensorflowonspark_tpu.queues import QueueClient, QueueServer

AUTH = b"k" * 16

# payload comfortably above MessageSocket.OOB_MIN_BYTES so it takes the
# out-of-band (and, when negotiated, the shm) path
BIG_SHAPE = (512, 128)  # f32 = 256 KB


def _big(seed=0):
    return (np.arange(np.prod(BIG_SHAPE), dtype=np.float32) + seed).reshape(
        BIG_SHAPE)


def _segments_alive(names):
    return [n for n in names if os.path.exists(os.path.join("/dev/shm", n))]


@pytest.fixture()
def server():
    s = QueueServer(authkey=AUTH, mode="local", maxsize=8)
    s.start()
    yield s
    s.stop()


def test_negotiation_and_roundtrip_integrity(server):
    c = QueueClient(server.addr, AUTH)
    assert c.shm_active, "same-process client must negotiate shm"
    big, small = _big(), np.arange(16, dtype=np.int32)
    chunk = [big, small, {"label": 3, "x": big + 1}]
    c.put("input", chunk)
    got = server.queue_get("input", timeout=5)
    np.testing.assert_array_equal(got[0], big)
    np.testing.assert_array_equal(got[1], small)
    assert got[2]["label"] == 3
    np.testing.assert_array_equal(got[2]["x"], big + 1)
    got[0][0, 0] = -1.0  # zero-copy views must stay writable
    assert c._chan.stats["shm_msgs"] == 1
    assert c._chan.stats["fallbacks"] == 0
    c.close()


def test_received_views_are_physically_shared(server):
    """The receive side must get views of the producer's segment, not a
    copy: a write through the received array is visible through a fresh
    attach of the ring segment."""
    from multiprocessing import shared_memory

    c = QueueClient(server.addr, AUTH)
    c.put("input", _big())
    item = server.queue_get("input", timeout=5)
    item[0, 0] = 1234.5
    [name] = c._chan.ring_segment_names()
    seg = shared_memory.SharedMemory(name=name, create=False)
    try:
        assert np.frombuffer(seg.buf, np.float32, count=1)[0] == 1234.5
    finally:
        del item
        seg.close()
    c.close()


def test_slot_release_recycles_ring(server, monkeypatch):
    """Dropping the consumer's views releases the slot back to the
    producer (piggybacked on the next response): a 2-slot ring sustains
    many more than 2 messages with zero fallbacks."""
    monkeypatch.setenv(shm_mod.SLOTS_ENV, "2")
    monkeypatch.setenv(shm_mod.SLOT_MB_ENV, "1")
    c = QueueClient(server.addr, AUTH)
    for i in range(10):
        c.put("input", _big(i))
        got = server.queue_get("input", timeout=5)
        assert got[0, 0] == float(i)
        del got
        gc.collect()  # drop the lease promptly
    assert c._chan.stats["shm_msgs"] == 10
    assert c._chan.stats["fallbacks"] == 0
    c.close()


def test_pool_exhaustion_falls_back_then_recovers(server, monkeypatch):
    """Ring exhausted (consumer still holds every lease) → the message
    takes the socket path, correctly; once leases drop, shm resumes."""
    monkeypatch.setenv(shm_mod.SLOTS_ENV, "1")
    monkeypatch.setenv(shm_mod.SLOT_MB_ENV, "1")
    c = QueueClient(server.addr, AUTH)
    c.put("input", _big(1))
    held = server.queue_get("input", timeout=5)  # lease the only slot
    c.put("input", _big(2))                      # must fall back, not fail
    got2 = server.queue_get("input", timeout=5)
    assert got2[0, 0] == 2.0
    assert c._chan.stats == {"shm_msgs": 1, "fallbacks": 1, "free_slots": 0}
    del held, got2
    gc.collect()
    c.kv_get("state")  # any exchange carries the pending release back
    assert c._chan.stats["free_slots"] == 1
    c.put("input", _big(3))                      # shm path again
    got3 = server.queue_get("input", timeout=5)
    assert got3[0, 0] == 3.0
    assert c._chan.stats["shm_msgs"] == 2
    c.close()


def test_oversized_payload_falls_back(server, monkeypatch):
    monkeypatch.setenv(shm_mod.SLOT_MB_ENV, "1")
    c = QueueClient(server.addr, AUTH)
    big = np.random.rand(1 << 19).astype(np.float32)  # 2 MB > 1 MB slot
    c.put("input", big)
    np.testing.assert_array_equal(server.queue_get("input", timeout=5), big)
    assert c._chan.stats["fallbacks"] == 1
    c.close()


def test_env_kill_switch_pins_socket_path(monkeypatch):
    monkeypatch.setenv(shm_mod.DISABLE_ENV, "1")
    s = QueueServer(authkey=AUTH, mode="local")
    s.start()
    try:
        c = QueueClient(s.addr, AUTH)
        assert not c.shm_active
        c.put("input", _big())
        np.testing.assert_array_equal(s.queue_get("input", timeout=5),
                                      _big())
        c.close()
    finally:
        s.stop()


def test_server_param_disable_downgrades_client(server):
    s = QueueServer(authkey=AUTH, mode="local", shm=False)
    s.start()
    try:
        c = QueueClient(s.addr, AUTH)  # client offers, server refuses
        assert not c.shm_active
        c.put("input", _big())
        np.testing.assert_array_equal(s.queue_get("input", timeout=5),
                                      _big())
        c.close()
    finally:
        s.stop()


def test_client_param_disable(server):
    c = QueueClient(server.addr, AUTH, shm=False)
    assert not c.shm_active
    c.put("input", [1, 2])
    assert server.queue_get("input", timeout=5) == [1, 2]
    c.close()


def test_cross_host_probe_failure_downgrades(server, monkeypatch):
    """A peer that cannot actually read the probe segment (the cross-host
    case) must land on the socket protocol, transparently."""
    monkeypatch.setattr(shm_mod, "verify_probe", lambda name, tok: False)
    c = QueueClient(server.addr, AUTH)
    assert not c.shm_active
    c.put("input", _big())
    np.testing.assert_array_equal(server.queue_get("input", timeout=5),
                                  _big())
    c.close()


def test_no_leaked_segments_after_normal_shutdown():
    s = QueueServer(authkey=AUTH, mode="local")
    s.start()
    c = QueueClient(s.addr, AUTH)
    c.put("input", _big())
    item = s.queue_get("input", timeout=5)
    names = c._chan.ring_segment_names()
    assert names, "expected a ring segment in flight"
    del item  # consumer done
    gc.collect()
    c.close()
    s.stop()
    assert _segments_alive(names) == []


def test_no_leaked_segments_with_leases_still_held():
    """Closing while a consumer STILL holds views must unlink the names
    (memory itself lives until the views die — that's the mmap contract)."""
    s = QueueServer(authkey=AUTH, mode="local")
    s.start()
    c = QueueClient(s.addr, AUTH)
    c.put("input", _big())
    item = s.queue_get("input", timeout=5)
    names = c._chan.ring_segment_names()
    c.close()  # lease never released — close anyway
    s.stop()
    assert _segments_alive(names) == []
    assert item[0, 0] == 0.0  # view stays valid until dropped
    del item


def test_consumer_crash_leaves_no_segments():
    """Worker process dies mid-lease (hard os._exit, no cleanup): the
    producer's close still unlinks every ring segment."""
    import multiprocessing as mp

    from tests.cluster_funcs import shm_crash_server

    ctx = mp.get_context("spawn")
    parent, child = ctx.Pipe()
    p = ctx.Process(target=shm_crash_server, args=(child,), daemon=True)
    p.start()
    try:
        addr = parent.recv()
        c = QueueClient(tuple(addr), AUTH)
        assert c.shm_active, "cross-process same-host must negotiate shm"
        c.put("input", _big(7))
        assert parent.recv() == 7  # payload crossed the process boundary
        names = c._chan.ring_segment_names()
        assert names
        parent.send("die")
        p.join(10)
        assert p.exitcode == 1
        c.close()
        assert _segments_alive(names) == []
    finally:
        if p.is_alive():  # pragma: no cover - only on assertion failure
            p.terminate()


def test_datafeed_next_chunk_over_shm(server):
    from tensorflowonspark_tpu.datafeed import DataFeed
    from tensorflowonspark_tpu.marker import EndOfFeed, EndPartition

    c = QueueClient(server.addr, AUTH)
    c.put("input", _big(1))
    c.put("input", EndPartition())
    c.put("input", _big(2))
    c.put("input", EndOfFeed())
    feed = DataFeed(server)
    assert feed.next_chunk(timeout=5)[0, 0] == 1.0
    assert feed.next_chunk(timeout=5)[0, 0] == 2.0  # marker skipped
    assert feed.next_chunk(timeout=5) is None
    assert feed.should_stop()
    c.close()


def test_concurrent_feeders_over_shm(server):
    """Two shm connections (two rings) interleaving on one queue."""
    def _feed(tag):
        c = QueueClient(server.addr, AUTH)
        for i in range(6):
            c.put("input", [_big(i), tag], timeout=10)
        c.close()

    threads = [threading.Thread(target=_feed, args=(t,)) for t in (0, 1)]
    for t in threads:
        t.start()
    seen = []
    for _ in range(12):
        arr, tag = server.queue_get("input", timeout=10)
        seen.append((int(arr[0, 0]), tag))
    for t in threads:
        t.join(5)
    assert sorted(seen) == sorted([(i, t) for t in (0, 1) for i in range(6)])


def test_probe_rejects_foreign_names_and_malformed_tokens():
    assert not shm_mod.verify_probe("not-ours", b"x" * 16)
    assert not shm_mod.verify_probe(None, b"x" * 16)
    assert not shm_mod.verify_probe(shm_mod.SEG_PREFIX + "nonexistent",
                                    b"x" * 16)
    # malformed hello fields must downgrade, never raise (the server's
    # connection thread calls this on peer-controlled input)
    probe = shm_mod.Probe()
    try:
        assert not shm_mod.verify_probe(probe.name, None)
        assert not shm_mod.verify_probe(probe.name, b"")
        assert not shm_mod.verify_probe(probe.name, "not-bytes")
        assert shm_mod.verify_probe(probe.name, probe.token)
    finally:
        probe.close()
