"""HF → GPT conversion verified at the logit level.

Randomly initialised ``transformers`` models (no network needed) and the
converted JAX model must produce the same logits — this pins the GPT
config down to operation-for-operation agreement with the GPT-2 and
Llama-class architectures.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from tensorflowonspark_tpu.models import GPT  # noqa: E402
from tensorflowonspark_tpu.models.convert import (  # noqa: E402
    gpt2_config_from_hf, gpt2_params_from_hf, llama_config_from_hf,
    llama_params_from_hf)


def test_gpt2_conversion_matches_hf_logits():
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(vocab_size=97, n_positions=32, n_embd=32,
                        n_layer=2, n_head=4,
                        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(hf_cfg).eval()

    cfg = gpt2_config_from_hf(hf_cfg)
    params = gpt2_params_from_hf(hf.state_dict(), cfg)

    ids = np.random.default_rng(0).integers(0, 97, (2, 12))
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = GPT(cfg).apply({"params": params}, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_llama_conversion_matches_hf_logits():
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(vocab_size=101, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=4,
                         num_key_value_heads=2, intermediate_size=48,
                         max_position_embeddings=32, rms_norm_eps=1e-5,
                         tie_word_embeddings=True,
                         attention_dropout=0.0)
    torch.manual_seed(1)
    hf = LlamaForCausalLM(hf_cfg).eval()

    cfg = llama_config_from_hf(hf_cfg)
    assert cfg.pos_encoding == "rope" and cfg.norm == "rmsnorm" \
        and cfg.mlp == "swiglu" and cfg.num_kv_heads == 2
    params = llama_params_from_hf(hf.state_dict(), cfg)

    ids = np.random.default_rng(1).integers(0, 101, (2, 10))
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = GPT(cfg).apply({"params": params}, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), want, rtol=3e-4, atol=3e-4)


def test_llama_untied_head_rejected():
    from transformers import LlamaConfig

    hf_cfg = LlamaConfig(tie_word_embeddings=False)
    with pytest.raises(ValueError, match="tie"):
        llama_config_from_hf(hf_cfg)


def test_mistral_conversion_with_active_sliding_window():
    """Mistral-class: GQA + rope + rmsnorm + swiglu + sliding window.
    Sequence longer than the window, so the band actually engages."""
    from transformers import MistralConfig, MistralForCausalLM

    hf_cfg = MistralConfig(vocab_size=89, hidden_size=32,
                           num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=2, intermediate_size=48,
                           max_position_embeddings=64, rms_norm_eps=1e-5,
                           sliding_window=6, tie_word_embeddings=True,
                           attention_dropout=0.0)
    torch.manual_seed(2)
    hf = MistralForCausalLM(hf_cfg).eval()

    cfg = llama_config_from_hf(hf_cfg)
    assert cfg.sliding_window == 6
    params = llama_params_from_hf(hf.state_dict(), cfg)

    ids = np.random.default_rng(2).integers(0, 89, (2, 16))  # 16 > window
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = GPT(cfg).apply({"params": params}, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), want, rtol=3e-4, atol=3e-4)


def test_unsupported_variants_rejected():
    from transformers import GPT2Config, LlamaConfig

    with pytest.raises(ValueError, match="activation_function"):
        gpt2_config_from_hf(GPT2Config(activation_function="gelu"))
    with pytest.raises(ValueError, match="rope_scaling"):
        llama_config_from_hf(LlamaConfig(
            tie_word_embeddings=True,
            rope_scaling={"rope_type": "linear", "factor": 2.0}))


def test_qwen2_window_layer_semantics():
    from transformers import Qwen2Config

    base = dict(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=2,
                intermediate_size=48, tie_word_embeddings=True,
                use_sliding_window=True, sliding_window=6)
    # mwl >= num_layers: HF windows NO layer -> converted window dropped
    cfg = llama_config_from_hf(Qwen2Config(max_window_layers=2, **base))
    assert cfg.sliding_window is None
    # mwl == 0: every layer windowed -> global window carries over
    cfg = llama_config_from_hf(Qwen2Config(max_window_layers=0, **base))
    assert cfg.sliding_window == 6
    # mixed: no global equivalent
    with pytest.raises(ValueError, match="max_window_layers"):
        llama_config_from_hf(Qwen2Config(max_window_layers=1, **base))


def test_bert_conversion_matches_hf_hidden_states():
    """Random HF BertModel and the converted Bert agree on the encoder's
    last hidden state (incl. padding-mask semantics and token types)."""
    from tensorflowonspark_tpu.models import Bert
    from tensorflowonspark_tpu.models.convert import (bert_config_from_hf,
                                                      bert_params_from_hf)

    hf_cfg = transformers.BertConfig(
        vocab_size=97, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=48, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    torch.manual_seed(0)
    hf = transformers.BertModel(hf_cfg).eval()

    B, T = 2, 16
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 97, (B, T))
    types = rng.integers(0, 2, (B, T))
    mask = np.ones((B, T), np.int64)
    mask[0, 12:] = 0  # padded tail on row 0

    with torch.no_grad():
        want = hf(input_ids=torch.tensor(ids),
                  attention_mask=torch.tensor(mask),
                  token_type_ids=torch.tensor(types)
                  ).last_hidden_state.numpy()

    cfg = bert_config_from_hf(hf_cfg)
    assert cfg.gelu_exact and cfg.norm_eps == hf_cfg.layer_norm_eps
    params = bert_params_from_hf(hf.state_dict(), cfg)
    got = Bert(cfg).apply({"params": params}, jnp.asarray(ids),
                          attention_mask=jnp.asarray(mask, bool),
                          token_type_ids=jnp.asarray(types))
    # compare non-padded positions (padded-query rows are attention
    # implementation detail on both sides)
    keep = mask.astype(bool)
    np.testing.assert_allclose(np.asarray(got)[keep], want[keep],
                               rtol=2e-4, atol=2e-5)
