"""Expert-parallel MoE: the all_to_all dispatch path vs a local oracle.

Routing and capacity are decided per token-shard from local information
only, so the exact oracle for an ``ep``-sharded run is ``moe_fn`` itself
built with ``ep=1`` (all experts local, no collectives) applied to each
shard's tokens on one device.  The distributed path — one-hot dispatch,
two ``all_to_all`` hops, per-owner expert compute — must reproduce it
bit-for-bit in values AND parameter gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu.parallel import make_mesh
from tensorflowonspark_tpu.parallel.mesh import MeshSpec
from tensorflowonspark_tpu.parallel.moe import make_moe_layer, moe_apply

HID, FFN, EXPERTS = 8, 16, 4


@pytest.mark.parametrize("ep,dp,top_k", [(2, 1, 1), (2, 2, 2), (4, 1, 2)])
def test_moe_matches_local_oracle(ep, dp, top_k):
    mesh = make_mesh(MeshSpec(ep=ep, dp=dp),
                     devices=jax.devices()[:ep * dp])
    moe_fn, init_fn, param_specs = make_moe_layer(
        HID, FFN, EXPERTS, top_k=top_k, ep=ep)
    oracle_fn, _, _ = make_moe_layer(HID, FFN, EXPERTS, top_k=top_k, ep=1)
    params = init_fn(jax.random.key(0))

    shards = ep * dp
    t_local = 6
    x = jax.random.normal(jax.random.key(1), (shards * t_local, HID))

    y, aux = moe_apply(mesh, moe_fn, params, x, param_specs=param_specs)

    # oracle: each token shard routed independently with all experts local.
    # token order on the mesh axis (dp, ep): dp is the outer axis.
    y_parts, aux_parts = [], []
    for s in range(shards):
        xs = x[s * t_local:(s + 1) * t_local]
        ys, auxs = oracle_fn(params, xs)
        y_parts.append(ys)
        aux_parts.append(auxs)
    y_ref = jnp.concatenate(y_parts)
    aux_ref = jnp.mean(jnp.stack(aux_parts))

    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)

    # ---- gradients ----
    def loss_dist(p):
        y, aux = moe_apply(mesh, moe_fn, p, x, param_specs=param_specs)
        return jnp.mean(y ** 2) + 0.01 * aux

    def loss_ref(p):
        parts = [oracle_fn(p, x[s * t_local:(s + 1) * t_local])
                 for s in range(shards)]
        y = jnp.concatenate([p_[0] for p_ in parts])
        aux = jnp.mean(jnp.stack([p_[1] for p_ in parts]))
        return jnp.mean(y ** 2) + 0.01 * aux

    g_dist = jax.jit(jax.grad(loss_dist))(params)
    g_ref = jax.grad(loss_ref)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-6),
        jax.device_get(g_dist), jax.device_get(g_ref))


def test_moe_capacity_drops_tokens():
    """With a tiny capacity factor, overflow tokens are dropped (zero
    output), never mis-routed."""
    moe_fn, init_fn, _ = make_moe_layer(
        HID, FFN, EXPERTS, top_k=1, capacity_factor=0.25, ep=1)
    params = init_fn(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (16, HID))
    y, _ = moe_fn(params, x)
    # capacity = 0.25*16*1/4 = 1 slot per expert -> at most 4 nonzero rows
    nonzero = np.count_nonzero(np.abs(np.asarray(y)).sum(-1) > 1e-7)
    assert nonzero <= EXPERTS


def test_moe_rejects_bad_expert_count():
    with pytest.raises(ValueError, match="must divide"):
        make_moe_layer(HID, FFN, 6, ep=4)
