"""Data-plane queue server/client tests (TFManager equivalent)."""

import threading

import numpy as np
import pytest

from tensorflowonspark_tpu.queues import QueueClient, QueueServer

AUTH = b"secret"


@pytest.fixture()
def server():
    s = QueueServer(authkey=AUTH, mode="local", maxsize=4)
    s.start()
    yield s
    s.stop()


def test_put_get_roundtrip(server):
    c = QueueClient(server.addr, AUTH)
    chunk = [(np.arange(4), 1), (np.arange(4) + 1, 0)]
    c.put("input", chunk)
    got = server.get_queue("input").get(timeout=5)
    np.testing.assert_array_equal(got[0][0], np.arange(4))
    c.close()


def test_bad_authkey_rejected(server):
    with pytest.raises(ConnectionError):
        QueueClient(server.addr, b"wrong")


def test_kv_state(server):
    c = QueueClient(server.addr, AUTH)
    assert c.kv_get("state") == "running"
    c.kv_set("state", "terminating")
    assert server.get("state") == "terminating"
    c.close()


def test_backpressure_full_queue(server):
    c = QueueClient(server.addr, AUTH)
    for i in range(4):
        c.put("input", [i], timeout=1)
    with pytest.raises(TimeoutError):  # maxsize=4 → fifth put times out
        c.put("input", [4], timeout=0.3)
    c.close()


def test_output_queue_from_training_side(server):
    # training side pushes in-process, feeder reads over TCP
    server.queue_put("output", ["pred1", "pred2"])
    c = QueueClient(server.addr, AUTH)
    assert c.queue_get("output", timeout=5) == ["pred1", "pred2"]
    c.close()


def test_unknown_queue_name_errors_cleanly(server):
    c = QueueClient(server.addr, AUTH)
    with pytest.raises(ValueError, match="unknown queue"):
        c.put("nonexistent", [1])
    c.put("input", ["still works"])  # connection survives the error
    assert server.get_queue("input").get(timeout=5) == ["still works"]
    c.close()


def test_concurrent_feeders(server):
    def _feed(tag):
        c = QueueClient(server.addr, AUTH)
        for i in range(8):
            c.put("input", [f"{tag}-{i}"], timeout=10)
        c.close()

    threads = [threading.Thread(target=_feed, args=(t,)) for t in "ab"]
    for t in threads:
        t.start()
    got = []
    for _ in range(16):
        got.extend(server.get_queue("input").get(timeout=10))
    for t in threads:
        t.join(5)
    assert sorted(got) == sorted([f"{t}-{i}" for t in "ab" for i in range(8)])


def test_out_of_band_framing_roundtrip():
    """MessageSocket's pickle-5 frame: large contiguous arrays travel
    out-of-band (nbuf > 0), small/non-contiguous payloads stay in-band,
    and every shape reconstructs equal and WRITABLE on the far side."""
    import socket as _socket
    import struct

    import numpy as np

    from tensorflowonspark_tpu.reservation import MessageSocket

    ms = MessageSocket()

    class FakeSock:
        def __init__(self):
            self.data = bytearray()

        def sendall(self, b):
            self.data += bytes(b)

    def nbuf_of(msg):
        fs = FakeSock()
        ms.send(fs, msg)
        magic, ver, _, nbuf = struct.unpack(">BBII", fs.data[:10])
        assert (magic, ver) == (ms.FRAME_MAGIC, ms.FRAME_VERSION)
        return nbuf

    def roundtrip(msg):
        a, b = _socket.socketpair()
        out = {}
        try:
            t = threading.Thread(
                target=lambda: out.setdefault("v", ms.receive(b)))
            t.start()
            ms.send(a, msg)
            t.join(10)
            assert not t.is_alive(), "receive hung"
            return out["v"]
        finally:
            a.close()
            b.close()

    big = np.arange(64 * 1024, dtype=np.float32)          # 256 KB -> OOB
    small = np.arange(16, dtype=np.int32)                 # in-band
    noncontig = np.ones((256, 512), np.float32)[:, ::2]   # in-band
    msg = {"big": big, "small": small, "nc": noncontig, "s": "x"}
    assert nbuf_of(msg) == 1, "exactly the big contiguous array goes OOB"
    assert nbuf_of({"only_small": small, "n": 3}) == 0

    out = roundtrip(msg)
    np.testing.assert_array_equal(out["big"], big)
    np.testing.assert_array_equal(out["small"], small)
    np.testing.assert_array_equal(out["nc"], noncontig)
    out["big"][0] = -1.0  # reconstructed-from-bytearray must stay mutable
