"""Telemetry plane: metrics registry, Prometheus exposition, aggregation,
HTTP endpoints, and request tracing (docs/observability.md).

The exposition tests parse the rendered text with a minimal
text-format-0.0.4 parser written here — escaping and histogram
cumulativity are pinned against what a real scraper would read, not
against our own renderer's internals.
"""

import json
import re
import threading
import urllib.request

import pytest

from tensorflowonspark_tpu import metrics, tracing
from tensorflowonspark_tpu.observability import EventLog

# ------------------------------------------------- minimal text parser

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")


def _parse_labels(body: str) -> dict:
    """Parse `k="v",k2="v2"` honoring \\\\, \\" and \\n escapes."""
    labels: dict[str, str] = {}
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        key = body[i:eq]
        assert body[eq + 1] == '"', body
        j = eq + 2
        val: list[str] = []
        while body[j] != '"':
            if body[j] == "\\":
                nxt = body[j + 1]
                val.append({"n": "\n", "\\": "\\", '"': '"'}[nxt])
                j += 2
            else:
                val.append(body[j])
                j += 1
        labels[key] = "".join(val)
        i = j + 1
        if i < len(body):
            assert body[i] == ","
            i += 1
    return labels


def parse_prometheus(text: str) -> dict:
    """name -> {"type", "help", "samples": [(sample_name, labels, value)]}."""
    out: dict[str, dict] = {}

    def family(name: str) -> dict:
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        key = base if base in out else name
        return out.setdefault(key, {"samples": []})

    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, name, help_text = line.split(" ", 3)
            out.setdefault(name, {"samples": []})["help"] = help_text
        elif line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            out.setdefault(name, {"samples": []})["type"] = kind
        elif line.startswith("#"):
            continue
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"unparseable sample line: {line!r}"
            name, labels, value = m.group(1), m.group(2), m.group(3)
            family(name)["samples"].append(
                (name, _parse_labels(labels) if labels else {},
                 float(value)))
    return out


# ------------------------------------------------------- registry units

def test_counter_gauge_histogram_basics():
    reg = metrics.MetricsRegistry()
    c = reg.counter("tfos_t_requests_total", "reqs", labelnames=("outcome",))
    c.inc(outcome="ok")
    c.inc(2, outcome="shed")
    c.labels(outcome="ok").inc(3)
    assert c.value(outcome="ok") == 4 and c.value(outcome="shed") == 2
    assert c.value(outcome="never") == 0

    g = reg.gauge("tfos_t_depth_count", "depth")
    g.set(7)
    assert g.value() == 7
    g.set(3)
    assert g.value() == 3

    h = reg.histogram("tfos_t_wait_seconds", "wait", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.record(v)
    snap = reg.snapshot()
    ((labels, series),) = snap["tfos_t_wait_seconds"]["samples"]
    assert labels == {}
    assert series["counts"] == [1, 2, 1]      # per-bucket, overflow last
    assert series["count"] == 4
    assert series["sum"] == pytest.approx(6.05)


def test_registry_get_or_create_and_conflicts():
    reg = metrics.MetricsRegistry()
    a = reg.counter("tfos_t_shared_total", "x")
    b = reg.counter("tfos_t_shared_total", "x")
    assert a is b
    with pytest.raises(ValueError):            # kind conflict
        reg.gauge("tfos_t_shared_total")
    with pytest.raises(ValueError):            # label-schema conflict
        reg.counter("tfos_t_shared_total", labelnames=("x",))
    h = reg.histogram("tfos_t_shared_seconds")
    assert reg.histogram("tfos_t_shared_seconds") is h
    with pytest.raises(ValueError):            # bucket-layout conflict
        reg.histogram("tfos_t_shared_seconds", buckets=(60.0, 300.0))


def test_metric_naming_enforced_at_registration():
    reg = metrics.MetricsRegistry()
    with pytest.raises(ValueError):            # no tfos_ prefix
        reg.counter("serving_requests_total")
    with pytest.raises(ValueError):            # counter needs _total
        reg.counter("tfos_steps_count")
    with pytest.raises(ValueError):            # gauge needs a unit suffix
        reg.gauge("tfos_queue_depth")
    with pytest.raises(ValueError):            # not snake case
        reg.histogram("tfos_TTFT_seconds")
    with pytest.raises(ValueError):            # wrong label set at use
        reg.counter("tfos_t_lbl_total", labelnames=("a",)).inc(b="x")


def test_disabled_registry_is_noop():
    reg = metrics.MetricsRegistry(enabled=False)
    c = reg.counter("anything goes — never registered", "x")
    c.inc()
    c.labels(outcome="x").inc()
    reg.histogram("also unchecked").record(1.0)
    assert reg.snapshot() == {}


def test_collect_hook_sets_gauges_at_snapshot_time():
    reg = metrics.MetricsRegistry()
    g = reg.gauge("tfos_t_live_count", "live")
    state = {"n": 3}
    reg.add_collect_hook(lambda: g.set(state["n"]))
    assert reg.snapshot()["tfos_t_live_count"]["samples"] == [[{}, 3.0]]
    state["n"] = 9
    assert reg.snapshot()["tfos_t_live_count"]["samples"] == [[{}, 9.0]]
    # a raising hook must not break the snapshot
    reg.add_collect_hook(lambda: 1 / 0)
    assert "tfos_t_live_count" in reg.snapshot()


def test_histogram_record_is_thread_safe_lock_free():
    reg = metrics.MetricsRegistry()
    h = reg.histogram("tfos_t_conc_seconds", buckets=(0.5,))
    child = h.labels()

    def worker():
        for _ in range(500):
            child.record(0.1)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ((_, series),) = reg.snapshot()["tfos_t_conc_seconds"]["samples"]
    assert series["count"] == 8 * 500 and series["counts"][0] == 8 * 500


# --------------------------------------------------------- exposition

def test_exposition_parses_with_types_helps_and_escaping():
    reg = metrics.MetricsRegistry()
    c = reg.counter("tfos_t_esc_total", 'weird "help"\nwith newline',
                    labelnames=("path",))
    c.inc(path='with"quote')
    c.inc(path="with\\backslash")
    c.inc(path="with\nnewline")
    parsed = parse_prometheus(reg.render())
    fam = parsed["tfos_t_esc_total"]
    assert fam["type"] == "counter"
    assert "newline" in fam["help"]
    values = {s[1]["path"]: s[2] for s in fam["samples"]}
    # the escape round-trip: parser recovers the original label values
    assert values == {'with"quote': 1.0, "with\\backslash": 1.0,
                      "with\nnewline": 1.0}


def test_exposition_histogram_buckets_are_cumulative():
    reg = metrics.MetricsRegistry()
    h = reg.histogram("tfos_t_cum_seconds", "h", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 2.0, 100.0):
        h.record(v)
    parsed = parse_prometheus(reg.render())
    fam = parsed["tfos_t_cum_seconds"]
    assert fam["type"] == "histogram"
    buckets = [(s[1]["le"], s[2]) for s in fam["samples"]
               if s[0].endswith("_bucket")]
    les = [b[0] for b in buckets]
    counts = [b[1] for b in buckets]
    assert les == ["0.1", "1", "10", "+Inf"]
    assert counts == [1.0, 3.0, 4.0, 5.0]          # cumulative
    assert counts == sorted(counts)                # non-decreasing
    total = [s[2] for s in fam["samples"] if s[0].endswith("_count")]
    assert total == [5.0] and counts[-1] == total[0]
    (sum_v,) = [s[2] for s in fam["samples"] if s[0].endswith("_sum")]
    assert sum_v == pytest.approx(103.05)


def test_merge_snapshots_stamps_node_label():
    reg_a, reg_b = metrics.MetricsRegistry(), metrics.MetricsRegistry()
    reg_a.counter("tfos_t_m_total").inc(2)
    reg_b.counter("tfos_t_m_total").inc(5)
    merged = metrics.merge_snapshots({"0": reg_a.snapshot(),
                                      "driver": reg_b.snapshot()})
    parsed = parse_prometheus(metrics.render_prometheus(merged))
    values = {s[1]["node"]: s[2]
              for s in parsed["tfos_t_m_total"]["samples"]}
    assert values == {"0": 2.0, "driver": 5.0}


def test_http_endpoint_serves_metrics_and_statusz():
    reg = metrics.MetricsRegistry()
    reg.counter("tfos_t_http_total").inc()
    srv = metrics.MetricsHTTPServer(reg.render,
                                    statusz=lambda: {"state": "ok"})
    host, port = srv.start()
    try:
        body = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=5)
        assert body.status == 200
        assert "version=0.0.4" in body.headers["Content-Type"]
        text = body.read().decode()
        assert parse_prometheus(text)["tfos_t_http_total"]["samples"]
        sz = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/statusz", timeout=5).read())
        assert sz == {"state": "ok"}
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://{host}:{port}/nope", timeout=5)
    finally:
        srv.stop()


# ------------------------------------------------------------ tracing

def test_tracer_emits_and_stitch_reconstructs_timeline(tmp_path):
    wd = str(tmp_path)
    tracer = tracing.Tracer(str(tmp_path / tracing.TRACE_FILENAME))
    trace = tracing.new_trace_id()
    other = tracing.new_trace_id()
    sched_log = EventLog(str(tmp_path / "serving_events.jsonl"))
    sched_log.emit("request_admitted", rid=0, trace=trace, depth=0)
    sched_log.emit("request_routed", rid=0, trace=trace, replica=1,
                   attempt=1)
    tracer.event("replica_intake", trace, rid=0, replica=1)
    sched_log.emit("request_admitted", rid=1, trace=other, depth=1)
    tracer.event("replica_first_token", trace, rid=0, replica=1)
    sched_log.emit("request_done", rid=0, trace=trace, tokens=8,
                   e2e_secs=0.5)
    sched_log.close()
    tracer.close()

    timeline = tracing.stitch_trace(wd, trace)
    kinds = [r["kind"] for r in timeline]
    assert kinds == ["request_admitted", "request_routed", "replica_intake",
                     "replica_first_token", "request_done"]
    assert all(r["trace"] == trace for r in timeline)
    assert [r["t"] for r in timeline] == sorted(r["t"] for r in timeline)

    text = tracing.format_timeline(timeline)
    assert "request_admitted" in text and "replica=1" in text

    traces = tracing.list_traces(wd)
    assert set(traces) == {trace, other}
    assert traces[trace]["spans"] == 5


def test_stitch_folds_in_untraced_failures_as_context(tmp_path):
    trace = tracing.new_trace_id()
    sched_log = EventLog(str(tmp_path / "serving_events.jsonl"))
    sched_log.emit("request_admitted", rid=0, trace=trace, depth=0)
    sched_log.emit("replica_dead", replica=1, reason="kill")   # no trace
    sched_log.emit("request_requeued", rid=0, trace=trace, from_replica=1,
                   delivered=3)
    sched_log.emit("request_done", rid=0, trace=trace, tokens=8)
    sched_log.close()
    health_log = EventLog(str(tmp_path / "health_events.jsonl"))
    health_log.emit("crash", workers=[1])                      # no trace
    health_log.close()

    timeline = tracing.stitch_trace(str(tmp_path), trace)
    kinds = [(r["kind"], bool(r.get("_context"))) for r in timeline]
    assert ("replica_dead", True) in kinds
    assert ("crash", True) in kinds
    assert ("request_requeued", False) in kinds
    assert "[context]" in tracing.format_timeline(timeline)


def test_stitch_unknown_trace_returns_empty(tmp_path):
    assert tracing.stitch_trace(str(tmp_path), "deadbeef") == []


def test_tfos_trace_cli(tmp_path, capsys):
    import importlib.util
    import os

    trace = tracing.new_trace_id()
    log = EventLog(str(tmp_path / "serving_events.jsonl"))
    log.emit("request_admitted", rid=0, trace=trace, depth=0)
    log.emit("request_done", rid=0, trace=trace, tokens=4)
    log.close()

    spec = importlib.util.spec_from_file_location(
        "tfos_trace", os.path.join(os.path.dirname(__file__), "..",
                                   "scripts", "tfos_trace.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    assert mod.main(["--dir", str(tmp_path), "--list"]) == 0
    assert trace in capsys.readouterr().out
    assert mod.main(["--dir", str(tmp_path), trace]) == 0
    out = capsys.readouterr().out
    assert "request_admitted" in out and "request_done" in out
    assert mod.main(["--dir", str(tmp_path), "not-a-trace"]) == 1
