"""TFRecord codec + Example proto + dfutil tests.

Reference model: ``tests/test_dfutil.py`` upstream (DataFrame → TFRecords →
DataFrame round trip with schema inference, needing the tensorflow-hadoop
JAR).  Here the codec is the package's own (native C++ + Python fallback);
byte-compatibility is cross-checked against TensorFlow where available
(test-only dependency — the package itself never imports TF).
"""

import numpy as np
import pytest

from tensorflowonspark_tpu import dfutil, example_proto, tfrecord
from tensorflowonspark_tpu.dataframe import DataFrame, Row


# -- CRC32C -----------------------------------------------------------------

def test_crc32c_known_vectors():
    # RFC 3720 (iSCSI) test vectors for Castagnoli CRC
    assert tfrecord.crc32c(b"") == 0
    assert tfrecord.crc32c(b"123456789") == 0xE3069283
    assert tfrecord.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert tfrecord.crc32c(b"\xff" * 32) == 0x62A8AB43


def test_native_and_python_crc_agree():
    data = bytes(range(256)) * 7 + b"tail"
    native = tfrecord._native()
    if native is None:
        pytest.skip("native codec unavailable (no g++)")
    assert native.tfr_crc32c(data, len(data)) == _py_crc(data)
    assert native.tfr_masked_crc(data, len(data)) == _py_masked(data)


def _py_crc(data):
    table = tfrecord._py_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ table[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _py_masked(data):
    crc = _py_crc(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- framing ----------------------------------------------------------------

def test_frame_and_iter_roundtrip():
    records = [b"", b"x", b"hello world" * 100, bytes(range(256))]
    buf = b"".join(tfrecord.frame_record(r) for r in records)
    assert list(tfrecord.iter_records(buf)) == records


def test_corruption_detected():
    buf = bytearray(tfrecord.frame_record(b"payload-bytes"))
    buf[14] ^= 0xFF  # flip a data byte
    with pytest.raises(tfrecord.TFRecordCorruptError, match="data"):
        list(tfrecord.iter_records(bytes(buf)))
    with pytest.raises(tfrecord.TFRecordCorruptError, match="truncated"):
        list(tfrecord.iter_records(tfrecord.frame_record(b"abc")[:-2]))
    # verify=False skips crc checks but still frames correctly
    buf2 = bytearray(tfrecord.frame_record(b"abcd"))
    buf2[9] ^= 0xFF  # corrupt length crc
    assert list(tfrecord.iter_records(bytes(buf2), verify=False)) == [b"abcd"]


def test_file_write_read(tmp_path):
    path = str(tmp_path / "data.tfrecord")
    n = tfrecord.write_records(path, [f"rec{i}".encode() for i in range(50)])
    assert n == 50
    assert list(tfrecord.read_records(path)) == [f"rec{i}".encode() for i in range(50)]


def test_truncated_tail_error_names_path_and_offset(tmp_path):
    """A part file cut mid-record (half-copied shard, killed writer) must
    raise the typed error carrying the source path and the byte offset of
    the broken record — not a bare struct/Value error (satellite)."""
    path = str(tmp_path / "trunc.tfrecord")
    good = [b"alpha", b"beta-record"]
    tfrecord.write_records(path, good + [b"tail-record-that-gets-cut"])
    whole = open(path, "rb").read()
    good_len = sum(16 + len(r) for r in good)

    # cut inside the tail record's PAYLOAD (header intact)
    with open(path, "wb") as f:
        f.write(whole[:good_len + 12 + 5])
    with pytest.raises(tfrecord.TFRecordCorruptError) as ei:
        list(tfrecord.read_records(path))
    assert path in str(ei.value) and str(good_len) in str(ei.value)
    assert ei.value.path == path and ei.value.offset == good_len
    # the intact prefix still streams before the error
    seen = []
    with pytest.raises(tfrecord.TFRecordCorruptError):
        for r in tfrecord.read_records(path):
            seen.append(r)
    assert seen == good

    # cut inside the tail record's HEADER
    with open(path, "wb") as f:
        f.write(whole[:good_len + 7])
    with pytest.raises(tfrecord.TFRecordCorruptError) as ei:
        list(tfrecord.read_records(path))
    assert ei.value.offset == good_len and ei.value.path == path

    # in-memory iter_records carries the offset too (path optional)
    with pytest.raises(tfrecord.TFRecordCorruptError) as ei:
        list(tfrecord.iter_records(whole[:good_len + 3], path="<buf>"))
    assert ei.value.offset == good_len and "<buf>" in str(ei.value)


def test_tf_reads_our_files(tmp_path):
    tf = pytest.importorskip("tensorflow")
    path = str(tmp_path / "ours.tfrecord")
    tfrecord.write_records(path, [b"alpha", b"beta" * 1000])
    got = [r.numpy() for r in tf.data.TFRecordDataset(path)]
    assert got == [b"alpha", b"beta" * 1000]


def test_we_read_tf_files(tmp_path):
    tf = pytest.importorskip("tensorflow")
    path = str(tmp_path / "theirs.tfrecord")
    with tf.io.TFRecordWriter(path) as w:
        w.write(b"one")
        w.write(b"two" * 500)
    assert list(tfrecord.read_records(path)) == [b"one", b"two" * 500]


# -- Example proto ----------------------------------------------------------

def test_example_roundtrip_all_kinds():
    feats = {
        "label": 7,
        "weights": [0.5, 1.5, -2.0],
        "name": "sample-1",
        "blob": b"\x00\x01\xff",
        "ids": [-1, 0, 1 << 40],
    }
    decoded = example_proto.decode_example(example_proto.encode_example(feats))
    assert decoded["label"] == ("int64", [7])
    assert decoded["ids"] == ("int64", [-1, 0, 1 << 40])
    kind, vals = decoded["weights"]
    assert kind == "float"
    np.testing.assert_allclose(vals, [0.5, 1.5, -2.0])
    assert decoded["name"] == ("bytes", [b"sample-1"])
    assert decoded["blob"] == ("bytes", [b"\x00\x01\xff"])


def test_example_bytes_match_tensorflow():
    tf = pytest.importorskip("tensorflow")
    ours = example_proto.encode_example(
        {"a": [1, 2], "b": [0.25], "c": "hi"})
    theirs = tf.train.Example.FromString(ours)   # must parse cleanly
    assert list(theirs.features.feature["a"].int64_list.value) == [1, 2]
    assert list(theirs.features.feature["b"].float_list.value) == [0.25]
    assert theirs.features.feature["c"].bytes_list.value[0] == b"hi"

    # and we parse TF's serialization of the same features
    ex = tf.train.Example(features=tf.train.Features(feature={
        "a": tf.train.Feature(int64_list=tf.train.Int64List(value=[1, 2])),
        "b": tf.train.Feature(float_list=tf.train.FloatList(value=[0.25])),
        "c": tf.train.Feature(bytes_list=tf.train.BytesList(value=[b"hi"])),
    }))
    decoded = example_proto.decode_example(ex.SerializeToString())
    assert decoded["a"] == ("int64", [1, 2])
    assert decoded["b"] == ("float", [0.25])
    assert decoded["c"] == ("bytes", [b"hi"])


def test_numpy_inputs():
    decoded = example_proto.decode_example(example_proto.encode_example({
        "arr": np.array([1, 2, 3], np.int64),
        "f32": np.float32(1.5),
    }))
    assert decoded["arr"] == ("int64", [1, 2, 3])
    assert decoded["f32"] == ("float", [1.5])


# -- dfutil -----------------------------------------------------------------

def _sample_df():
    rows = [Row(idx=i, pixels=[float(i), float(i) + 0.5], tag=f"t{i}",
                raw=bytes([i]))
            for i in range(10)]
    return DataFrame(rows, num_partitions=3)


def test_dfutil_roundtrip(tmp_path):
    df = _sample_df()
    out = str(tmp_path / "records")
    n = dfutil.saveAsTFRecords(df, out)
    assert n == 10
    import os
    assert sorted(os.listdir(out)) == ["_SUCCESS", "part-r-00000",
                                       "part-r-00001", "part-r-00002"]
    back = dfutil.loadTFRecords(out, binary_features=["raw"])
    assert back.num_partitions == 3
    assert back.columns == ["idx", "pixels", "raw", "tag"]  # sorted on decode
    for orig, got in zip(df.collect(), back.collect()):
        assert got.idx == orig.idx
        np.testing.assert_allclose(got.pixels, orig.pixels)
        assert got.tag == orig.tag          # utf-8 decoded
        assert got.raw == orig.raw          # kept binary


def test_dfutil_schema_inference():
    row = Row(idx=3, pixels=[1.0, 2.0], tag="x", raw=b"\x01")
    schema = dfutil.infer_schema(row, binary_features=["raw"])
    assert schema == {"idx": "int64", "pixels": "float[]",
                      "raw": "bytes", "tag": "string"}


def test_corrupt_length_field_does_not_wrap(tmp_path):
    # regression: a corrupted 8-byte length near UINT64_MAX must raise, not
    # wrap the bounds check and loop forever (even with verify=False)
    buf = bytearray(tfrecord.frame_record(b"abcdef"))
    buf[0:8] = (0xFFFFFFFFFFFFFFF0).to_bytes(8, "little")
    with pytest.raises(tfrecord.TFRecordCorruptError):
        list(tfrecord.iter_records(bytes(buf), verify=False))


def test_bytearray_and_memoryview_inputs():
    data = b"payload"
    assert tfrecord.crc32c(bytearray(data)) == tfrecord.crc32c(data)
    framed = tfrecord.frame_record(memoryview(data))
    assert list(tfrecord.iter_records(bytearray(framed))) == [data]


def test_streaming_read_does_not_slurp(tmp_path):
    # read_records must yield before consuming the whole file: write two
    # records, truncate the second mid-payload — the first must still arrive
    path = str(tmp_path / "t.tfrecord")
    good = tfrecord.frame_record(b"first-record")
    bad = tfrecord.frame_record(b"second-record")[:-6]
    with open(path, "wb") as f:
        f.write(good + bad)
    it = tfrecord.read_records(path)
    assert next(it) == b"first-record"
    with pytest.raises(tfrecord.TFRecordCorruptError):
        next(it)


def test_dfutil_ragged_list_columns(tmp_path):
    # regression: a list column with a length-1 value in some row must come
    # back as a list everywhere, not collapse to a scalar in that row
    df = DataFrame([Row(v=[1.0, 2.0]), Row(v=[3.0])])
    out = str(tmp_path / "ragged")
    dfutil.saveAsTFRecords(df, out)
    back = dfutil.loadTFRecords(out)
    vals = [r.v for r in back.collect()]
    assert vals[0] == [1.0, 2.0]
    assert vals[1] == [3.0]          # still a list


def test_dfutil_empty_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        dfutil.loadTFRecords(str(tmp_path))


def test_dfutil_tf_interop(tmp_path):
    tf = pytest.importorskip("tensorflow")
    out = str(tmp_path / "records")
    dfutil.saveAsTFRecords(_sample_df(), out)
    import glob
    ds = tf.data.TFRecordDataset(sorted(glob.glob(out + "/part-*")))
    parsed = [tf.io.parse_single_example(r, {
        "idx": tf.io.FixedLenFeature([], tf.int64),
        "tag": tf.io.FixedLenFeature([], tf.string),
    }) for r in ds]
    assert [int(p["idx"]) for p in parsed] == list(range(10))
    assert parsed[4]["tag"].numpy() == b"t4"


def test_empty_feature_roundtrip(tmp_path):
    """A record with an empty-list cell must not crash the load path
    (regression: IndexError in fromTFExample on len-0 features)."""
    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.dataframe import DataFrame, Row

    df = DataFrame([Row(v=[1.0, 2.0]), Row(v=[]), Row(v=[3.0])])
    out = str(tmp_path / "tfr")
    dfutil.saveAsTFRecords(df, out)
    back = dfutil.loadTFRecords(out)
    vals = sorted((r.v for r in back.collect()), key=len)
    assert vals == [[], [1.0, 2.0], [3.0]] or vals == [[], [3.0], [1.0, 2.0]]


def test_empty_feature_scalar_schema_yields_null(tmp_path):
    """All-len-1 plus one empty feature: the empty cell must come back as a
    list cell (empty features force list typing), never crash."""
    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.dataframe import DataFrame, Row

    df = DataFrame([Row(x=[7.0]), Row(x=[])])
    out = str(tmp_path / "tfr2")
    dfutil.saveAsTFRecords(df, out)
    back = dfutil.loadTFRecords(out)
    assert sorted(r.x for r in back.collect()) == [[], [7.0]]


# -- remote-filesystem IO (VERDICT r1 missing #2) ---------------------------

def test_roundtrip_over_memory_scheme():
    """Write/read TFRecords through a non-local fsspec filesystem — the
    gs:// production path, exercised via fsspec's memory:// backend."""
    from tensorflowonspark_tpu.data import Dataset
    from tensorflowonspark_tpu.tfrecord import read_records, write_records

    base = "memory://tfos-test/records"
    recs = [b"alpha", b"beta", b"gamma" * 100]
    write_records(f"{base}/part-r-00000", recs[:2])
    write_records(f"{base}/part-r-00001", recs[2:])

    got = list(read_records(f"{base}/part-r-00000"))
    assert got == recs[:2]

    ds = Dataset.from_tfrecords(f"{base}/part-*")
    assert list(ds) == recs

    # file-granularity sharding across schemes
    ds0 = Dataset.from_tfrecords(f"{base}/part-*", shard=(2, 1))
    assert list(ds0) == recs[2:]


def test_dfutil_roundtrip_over_memory_scheme():
    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu import filesystem as fsutil
    from tensorflowonspark_tpu.dataframe import DataFrame, Row

    df = DataFrame.from_partitions([
        [Row(x=1.5, label="a"), Row(x=2.5, label="b")],
        [Row(x=3.5, label="c")],
    ])
    out = "memory://tfos-test/df"
    n = dfutil.saveAsTFRecords(df, out)
    assert n == 3
    assert fsutil.exists(f"{out}/_SUCCESS")

    back = dfutil.loadTFRecords(out)
    rows = sorted(back.collect(), key=lambda r: r.x)
    assert [r.label for r in rows] == ["a", "b", "c"]
    assert [r.x for r in rows] == [1.5, 2.5, 3.5]


def test_file_scheme_paths(tmp_path):
    """file:// URIs resolve through fsspec to the local filesystem."""
    from tensorflowonspark_tpu.tfrecord import read_records, write_records

    path = f"file://{tmp_path}/x.tfrecord"
    write_records(path, [b"one", b"two"])
    assert list(read_records(path)) == [b"one", b"two"]
    # and the plain-path view sees the same bytes
    assert list(read_records(str(tmp_path / "x.tfrecord"))) == [b"one", b"two"]


def test_filesystem_join_and_scheme_detection():
    from tensorflowonspark_tpu import filesystem as fsutil

    assert fsutil.has_scheme("gs://bucket/x")
    assert fsutil.has_scheme("memory://a")
    assert not fsutil.has_scheme("/abs/path")
    assert not fsutil.has_scheme("rel/path")
    assert fsutil.join("gs://b/dir", "part-0") == "gs://b/dir/part-0"
    assert fsutil.join("gs://b/dir/", "sub", "f") == "gs://b/dir/sub/f"
    assert fsutil.join("/local/dir", "f").endswith("/local/dir/f")


def test_native_example_decoder_matches_python_oracle():
    """decode_example (native path when built) must be byte-identical to
    decode_example_py across feature shapes, including packed/unpacked
    lists, negatives, empties, and unicode names."""
    import numpy as np

    from tensorflowonspark_tpu.example_proto import (decode_example,
                                                     decode_example_py,
                                                     encode_example)

    rng = np.random.default_rng(0)
    for trial in range(20):
        feats = {}
        for j in range(rng.integers(0, 6)):
            kind = rng.integers(0, 3)
            name = f"f{trial}_{j}_é"
            if kind == 0:
                feats[name] = [bytes(rng.integers(0, 255, rng.integers(0, 9),
                                                  ).astype(np.uint8))
                               for _ in range(rng.integers(0, 4))]
            elif kind == 1:
                feats[name] = rng.normal(size=rng.integers(0, 50)) \
                    .astype(np.float32)
            else:
                feats[name] = (rng.integers(-2**40, 2**40,
                                            rng.integers(0, 50))
                               .astype(np.int64))
        ex = encode_example(feats)
        assert decode_example(ex) == decode_example_py(ex)

    # malformed input raises on both paths
    import pytest

    with pytest.raises(ValueError):
        decode_example_py(b"\x0a\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff")
    with pytest.raises(ValueError):
        decode_example(b"\x0a\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff")


def test_native_decoder_hostile_inputs_never_crash():
    """Adversarial wire bytes: huge length varints (would wrap a signed
    bound check into out-of-bounds reads), truncation, junk — every case
    must raise or return, never segfault, and agree with the oracle."""
    from tensorflowonspark_tpu.example_proto import (decode_example,
                                                     decode_example_py)

    hostile = [
        b"\x0a" + b"\x80" * 9 + b"\x01",          # flen = 2^63 (INT64_MIN)
        b"\x0a" + b"\xff" * 9 + b"\x01",          # flen near UINT64_MAX
        b"\x0a\x05\x0a\xff\xff\xff\x7f",          # inner len >> remaining
        b"\x0a\x03\x0a\x01",                      # truncated entry
        bytes(range(256)) * 3,                    # junk
        b"",
    ]
    for buf in hostile:
        try:
            a = decode_example(buf)
            ok_native = True
        except ValueError:
            ok_native = False
        try:
            b = decode_example_py(buf)
            ok_py = True
        except ValueError:
            ok_py = False
        if ok_native and ok_py:
            assert a == b, buf


def test_native_decoder_accepts_bytearray_and_last_value_wins():
    from tensorflowonspark_tpu.example_proto import (_write_len_field,
                                                     decode_example,
                                                     decode_example_py,
                                                     encode_example,
                                                     encode_float_list,
                                                     encode_int64_list)

    ba = bytearray(encode_example({"a": [1, 2]}))
    assert decode_example(ba) == decode_example_py(bytes(ba))

    # two Feature values in one map entry: proto says LAST wins
    entry = bytearray()
    _write_len_field(entry, 1, b"k")
    _write_len_field(entry, 2, encode_int64_list([1]))
    _write_len_field(entry, 2, encode_float_list([2.0]))
    fmap = bytearray()
    _write_len_field(fmap, 1, bytes(entry))
    ex = bytearray()
    _write_len_field(ex, 1, bytes(fmap))
    assert decode_example(bytes(ex)) == decode_example_py(bytes(ex)) \
        == {"k": ("float", [2.0])}
