"""tfos-check: engine mechanics, the five rules, the repo-wide tier-1 gate,
and the submit-time preflight (docs/analysis.md).

The gate test is the ratchet: it analyzes the WHOLE package against the
committed ``analysis_baseline.json`` and fails on any finding not
grandfathered there — new code must come in clean (or explicitly
``# tfos: ignore[rule-id]``'d with a reason, or deliberately baselined).
"""

import json
import logging
import os
import subprocess
import sys
import threading

import pytest

import tensorflowonspark_tpu
from tensorflowonspark_tpu.analysis import (ALL_RULES, RULE_IDS, Finding,
                                            analyze_paths, analyze_source,
                                            load_baseline, new_findings,
                                            write_baseline)
from tensorflowonspark_tpu.analysis.__main__ import main as cli_main
from tensorflowonspark_tpu.analysis.engine import parse_suppressions
from tensorflowonspark_tpu.analysis.exports import (check_exports,
                                                    documented_names,
                                                    public_exports)
from tensorflowonspark_tpu.analysis.preflight import (PreflightError,
                                                      check_payload)

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.abspath(tensorflowonspark_tpu.__file__)))
PKG_DIR = os.path.join(REPO_ROOT, "tensorflowonspark_tpu")
BASELINE = os.path.join(REPO_ROOT, "analysis_baseline.json")
FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


def fixture_findings(name: str) -> list:
    return analyze_paths([os.path.join(FIXTURES, name)], root=FIXTURES)


def rules_of(findings) -> set:
    return {f.rule for f in findings}


# ------------------------------------------------------------------ gate

class TestRepoGate:
    def test_package_clean_against_baseline(self):
        """THE tier-1 gate: repo-wide analyzer pass vs the committed
        baseline.  A failure here names the exact new finding — fix it,
        suppress it with a reasoned `# tfos: ignore[rule]`, or (last
        resort) re-baseline via
        `python -m tensorflowonspark_tpu.analysis --exports
        --write-baseline --baseline analysis_baseline.json`."""
        findings = analyze_paths([PKG_DIR], root=REPO_ROOT)
        findings += check_exports(REPO_ROOT)
        assert os.path.exists(BASELINE), "analysis_baseline.json missing"
        new = new_findings(findings, load_baseline(BASELINE))
        assert not new, "NEW analyzer findings:\n" + "\n".join(
            f.format() for f in new)

    def test_every_rule_registered(self):
        assert set(RULE_IDS) == {"closure-capture", "jit-purity",
                                 "lock-discipline", "resource-lifecycle",
                                 "broad-except", "metric-naming",
                                 "wire-protocol", "journal-kinds",
                                 "blocking-under-lock", "compat-discipline",
                                 "doc-drift"}


# ------------------------------------------------------------- rule units

class TestRuleFixtures:
    """Each rule: at least one positive (flagging) and one negative
    (clean) fixture."""

    @pytest.mark.parametrize("rule_id,stem", [
        ("closure-capture", "closure_capture"),
        ("jit-purity", "jit_purity"),
        ("lock-discipline", "lock_discipline"),
        ("resource-lifecycle", "resource_lifecycle"),
        ("broad-except", "broad_except"),
        ("metric-naming", "metric_naming"),
        ("wire-protocol", "wire_protocol"),
        ("journal-kinds", "journal_kinds"),
        ("blocking-under-lock", "blocking_under_lock"),
        ("compat-discipline", "compat_discipline"),
    ])
    def test_positive_and_negative(self, rule_id, stem):
        bad = fixture_findings(f"{stem}_bad.py")
        assert rule_id in rules_of(bad), \
            f"{stem}_bad.py produced no {rule_id} finding"
        good = fixture_findings(f"{stem}_good.py")
        assert rule_id not in rules_of(good), \
            f"{stem}_good.py false positives: " + "\n".join(
                f.format() for f in good if f.rule == rule_id)

    def test_closure_capture_names_the_variable(self):
        msgs = [f.message for f in fixture_findings("closure_capture_bad.py")
                if f.rule == "closure-capture"]
        assert any("'lock'" in m for m in msgs)
        assert any("'sock'" in m for m in msgs)
        assert any("'client'" in m for m in msgs)

    def test_jit_purity_catalog(self):
        msgs = " | ".join(
            f.message for f in fixture_findings("jit_purity_bad.py"))
        for marker in ("time.*", "np.random", "print()", "branches on "
                       "traced value", "float()", ".item()"):
            assert marker in msgs, f"jit-purity missed {marker}"

    def test_lock_discipline_reports_cycle(self):
        msgs = [f.message for f in fixture_findings("lock_discipline_bad.py")]
        assert any("cycle" in m and "_alock" in m and "_block" in m
                   for m in msgs)

    def test_lock_discipline_lock_held_convention(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.n = 0\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run).start()\n"
            "    def _run(self):\n"
            "        with self._lock:\n"
            "            self._bump()\n"
            "    def _bump(self):\n"
            '        """Bump (lock held by caller)."""\n'
            "        self.n += 1\n"
            "    def reset(self):\n"
            "        with self._lock:\n"
            "            self.n = 0\n")
        assert analyze_source(src, "conv.py") == []

    def test_lock_discipline_substringy_names_are_not_locks(self):
        """'poll_seconds' (contains 'cond') and 'clock' (contains 'lock')
        are ordinary shared state — a substring heuristic used to exempt
        them from the mutation check entirely."""
        src_tmpl = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.{attr} = 0\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run).start()\n"
            "    def _run(self):\n"
            "        self.{attr} += 1\n"
            "    def tune(self):\n"
            "        self.{attr} = 5\n")
        for attr in ("poll_seconds", "clock", "blocked_count"):
            found = analyze_source(src_tmpl.format(attr=attr), "sub.py")
            assert any(f.rule == "lock-discipline" and attr in f.message
                       for f in found), f"{attr} exempted as a lock"

    def test_resource_lifecycle_counts_all_four_kinds(self):
        kinds = {f.message.split(" '")[0]
                 for f in fixture_findings("resource_lifecycle_bad.py")}
        assert kinds == {"socket", "shared-memory segment", "thread",
                         "file handle"}


# -------------------------------------------------- cross-file rules (v2)

class TestCrossFileRules:
    """wire-protocol / journal-kinds / doc-drift finalize() verdicts, the
    blocking-under-lock catalog, and the gating that keeps partial runs
    quiet."""

    def test_wire_protocol_all_directions(self):
        msgs = [f.message for f in fixture_findings("wire_protocol_bad.py")
                if f.rule == "wire-protocol"]
        joined = " | ".join(msgs)
        assert "op 'orbit' is produced here but no analyzed consumer" \
            in joined
        assert "dispatches on op 'land' that no analyzed producer" in joined
        assert "op 'dock' event 'soft' is produced here but no handler" \
            in joined
        assert "reads msg['fuel_kg'] but no producer of that op" in joined
        assert "event 'telemetry' is produced here but no analyzed consumer" \
            in joined
        assert "event 'splashdown' that no analyzed producer" in joined

    def test_wire_protocol_findings_name_file_and_line(self):
        orbit = [f for f in fixture_findings("wire_protocol_bad.py")
                 if "orbit" in f.message]
        assert orbit and orbit[0].path == "wire_protocol_bad.py"
        assert orbit[0].line > 0

    def test_wire_protocol_one_sided_run_is_quiet(self):
        """A producer-only file (no consumer anywhere in the analyzed set)
        must report nothing — the directions are gated on having seen
        both sides, so partial-path runs can't cry wolf."""
        src = 'def send(conn, post):\n    post(conn, {"op": "launch"})\n'
        assert [f for f in analyze_source(src, "p.py")
                if f.rule == "wire-protocol"] == []

    def test_journal_kinds_all_directions(self):
        msgs = [f.message for f in fixture_findings("journal_kinds_bad.py")
                if f.rule == "journal-kinds"]
        joined = " | ".join(msgs)
        assert "'not_allowlisted' is recorded here but missing from " \
            "KNOWN_KINDS" in joined
        assert "'finish' is in KNOWN_KINDS but the replay _fold never" \
            in joined
        assert "'ghost_kind' is in KNOWN_KINDS but no analyzed producer" \
            in joined
        assert "context kind 'comet_strike' in CONTEXT_KINDS is never " \
            "emitted" in joined

    def test_journal_kinds_recorder_only_file_is_quiet(self):
        """record("k") calls with no KNOWN_KINDS in the analyzed set must
        not report — the allowlist side wasn't seen."""
        src = ("class P:\n"
               "    def admit(self, rid):\n"
               "        self.journal.record('anything', rid=rid)\n")
        assert [f for f in analyze_source(src, "p.py")
                if f.rule == "journal-kinds"] == []

    def test_blocking_under_lock_catalog(self):
        msgs = [f.message for f in
                fixture_findings("blocking_under_lock_bad.py")
                if f.rule == "blocking-under-lock"]
        joined = " | ".join(msgs)
        for marker in ("sleep()", ".recv()", ".get() with no timeout",
                       ".join() with no timeout", "os.fsync()",
                       "subprocess.run()"):
            assert marker in joined, f"blocking-under-lock missed {marker}"
        assert "while holding self._lock" in joined
        # the lock-held-by-caller docstring convention seeds a held lock
        assert any("_drain" in m and "caller's lock" in m for m in msgs)

    def test_doc_drift_positive_and_negative(self):
        bad_root = os.path.join(FIXTURES, "doc_drift_bad")
        bad = analyze_paths([os.path.join(bad_root, "mod.py")],
                            root=bad_root)
        joined = " | ".join(f.message for f in bad
                            if f.rule == "doc-drift")
        assert "'tfos_undocumented_total' is registered here but missing" \
            in joined
        assert "'tfos_ghost_total'" in joined and "stale row" in joined
        assert "chaos verb 'flap' is in VERBS but missing" in joined
        assert "verb 'term' that chaos.VERBS does not define" in joined
        assert "'tfos_documented_total'" not in joined
        good_root = os.path.join(FIXTURES, "doc_drift_good")
        good = analyze_paths([os.path.join(good_root, "mod.py")],
                             root=good_root)
        assert [f for f in good if f.rule == "doc-drift"] == []

    def test_doc_drift_unanchored_run_is_quiet(self):
        """Registering a metric without the telemetry plane (validate_name)
        in the analyzed set must not consult any docs."""
        src = ("from tensorflowonspark_tpu.metrics import get_registry\n"
               "reg = get_registry()\n"
               "c = reg.counter('tfos_orphan_total', 'x')\n")
        assert [f for f in analyze_source(src, "p.py")
                if f.rule == "doc-drift"] == []


# ------------------------------------------------------- mutation seeding

class TestMutationRegressions:
    """The acceptance bar for the cross-file rules: seed a realistic
    regression into a copy of the REAL repo sources and assert the rule
    names the file, the symbol, and the missing counterpart."""

    def _mutate(self, tmp_path, relpath, old, new):
        src = open(os.path.join(PKG_DIR, relpath), encoding="utf-8").read()
        assert old in src, f"mutation anchor {old!r} gone from {relpath}"
        out = tmp_path / os.path.basename(relpath)
        out.write_text(src.replace(old, new))
        return str(out)

    def test_wire_protocol_catches_renamed_op(self, tmp_path):
        """Rename the client's 'generate' op: the frontend's dispatch goes
        dead and BOTH ends are named."""
        from tensorflowonspark_tpu.analysis import WireProtocolRule

        mutated = self._mutate(tmp_path, os.path.join("serving", "client.py"),
                               '"op": "generate"', '"op": "generate_v2"')
        findings = analyze_paths(
            [mutated, os.path.join(PKG_DIR, "serving", "frontend.py")],
            rules=[WireProtocolRule()], root=str(tmp_path))
        msgs = [f.format() for f in findings]
        assert any("generate_v2" in m and "no analyzed consumer" in m
                   and "client.py" in m for m in msgs), msgs
        assert any("op 'generate'" in m and "no analyzed producer" in m
                   and "frontend.py" in m for m in msgs), msgs

    def test_wire_protocol_intact_package_is_clean(self):
        """The unmutated protocol surface — every op/event/field pair in
        the real serving, batch, and queue planes — reconciles."""
        from tensorflowonspark_tpu.analysis import WireProtocolRule

        findings = analyze_paths([PKG_DIR], rules=[WireProtocolRule()],
                                 root=REPO_ROOT)
        assert [f for f in findings if f.rule == "wire-protocol"] == []

    def test_journal_kinds_catches_dropped_kind(self, tmp_path):
        """Drop 'admit' from KNOWN_KINDS: the scheduler's admit record is
        journaled but no longer durable, and the finding says so."""
        from tensorflowonspark_tpu.analysis import JournalKindsRule

        mutated = self._mutate(tmp_path, os.path.join("serving",
                                                      "journal.py"),
                               '"admit",', '')
        findings = analyze_paths(
            [mutated, os.path.join(PKG_DIR, "serving", "scheduler.py")],
            rules=[JournalKindsRule()], root=str(tmp_path))
        msgs = [f.format() for f in findings]
        assert any("journal kind 'admit' is recorded here but missing "
                   "from KNOWN_KINDS" in m and "scheduler.py" in m
                   for m in msgs), msgs

    def test_compat_discipline_catches_raw_shard_map(self, tmp_path):
        """Reintroduce a raw jax.shard_map call into a copy of a real
        module: flagged with the compat counterpart named."""
        from tensorflowonspark_tpu.analysis import CompatDisciplineRule

        src = open(os.path.join(PKG_DIR, "serving", "sharded.py"),
                   encoding="utf-8").read()
        out = tmp_path / "sharded.py"
        out.write_text(src + "\n\ndef _raw(f, mesh):\n"
                             "    import jax\n"
                             "    return jax.shard_map(f, mesh=mesh)\n")
        findings = analyze_paths([str(out)],
                                 rules=[CompatDisciplineRule()],
                                 root=str(tmp_path))
        msgs = [f.format() for f in findings]
        assert any("raw 'jax.shard_map'" in m and "compat.shard_map" in m
                   for m in msgs), msgs

    def test_compat_discipline_repo_is_clean(self):
        from tensorflowonspark_tpu.analysis import CompatDisciplineRule

        findings = analyze_paths([PKG_DIR], rules=[CompatDisciplineRule()],
                                 root=REPO_ROOT)
        assert findings == []


# -------------------------------------------------------------- parallel

class TestParallelJobs:
    def test_jobs_matches_serial_on_fixtures(self):
        """--jobs must be invisible in the results: per-file findings AND
        cross-file finalize verdicts identical to the serial run."""
        serial = analyze_paths([FIXTURES], root=FIXTURES)
        parallel = analyze_paths([FIXTURES], root=FIXTURES, jobs=4)
        assert [f.to_dict() for f in parallel] == \
            [f.to_dict() for f in serial]

    def test_jobs_matches_serial_on_package(self):
        serial = analyze_paths([PKG_DIR], root=REPO_ROOT)
        parallel = analyze_paths([PKG_DIR], root=REPO_ROOT, jobs=3)
        assert [f.to_dict() for f in parallel] == \
            [f.to_dict() for f in serial]

    def test_stats_collects_every_rule(self):
        stats = {}
        analyze_paths([os.path.join(FIXTURES, "broad_except_bad.py")],
                      root=FIXTURES, stats=stats)
        assert set(RULE_IDS) <= set(stats)
        assert all(v >= 0 for v in stats.values())


# ---------------------------------------------------- suppressions/baseline

class TestEngineMechanics:
    SRC_BAD = "try:\n    pass\nexcept Exception:\n    pass\n"

    def test_finding_without_suppression(self):
        assert rules_of(analyze_source(self.SRC_BAD, "x.py")) == \
            {"broad-except"}

    def test_same_line_suppression(self):
        src = ("try:\n    pass\n"
               "except Exception:  # tfos: ignore[broad-except]\n"
               "    pass\n")
        assert analyze_source(src, "x.py") == []

    def test_comment_line_above_suppression(self):
        src = ("try:\n    pass\n"
               "# tfos: ignore[broad-except] — reason goes here\n"
               "except Exception:\n    pass\n")
        assert analyze_source(src, "x.py") == []

    def test_suppression_is_rule_scoped(self):
        src = ("try:\n    pass\n"
               "except Exception:  # tfos: ignore[jit-purity]\n"
               "    pass\n")
        assert rules_of(analyze_source(src, "x.py")) == {"broad-except"}

    def test_parse_suppressions_multi_rule(self):
        supp = parse_suppressions(
            "x = 1  # tfos: ignore[rule-a, rule-b]\n")
        assert supp == {1: {"rule-a", "rule-b"}}

    def test_parse_suppressions_pending_consumed_by_inline_line(self):
        """An above-line suppression lands on the next code line even when
        that line carries its own inline suppression — and must NOT leak
        onto the statement after it."""
        supp = parse_suppressions(
            "# tfos: ignore[broad-except]\n"
            "x = foo()  # tfos: ignore[jit-purity]\n"
            "y = bar()\n")
        assert supp == {2: {"broad-except", "jit-purity"}}

    def test_overlapping_paths_analyze_each_file_once(self):
        """`pkg pkg/file.py` on the CLI must not double-count findings —
        with the count-aware ratchet a duplicate pass would report fully
        baselined findings as new."""
        explicit = os.path.join(FIXTURES, "broad_except_bad.py")
        once = analyze_paths([explicit], root=FIXTURES)
        twice = analyze_paths([FIXTURES, explicit], root=FIXTURES)
        bad_rel = "broad_except_bad.py"
        assert [f for f in twice if f.path == bad_rel] == \
            [f for f in once if f.path == bad_rel]

    def test_baseline_ratchet(self, tmp_path):
        old = Finding("broad-except", "m.py", 3,
                      "'except Exception' swallows the error silently — "
                      "narrow the type, log with context, or re-raise")
        path = str(tmp_path / "base.json")
        write_baseline([old], path)
        baseline = load_baseline(path)
        # same finding at a DIFFERENT line is still grandfathered
        moved = Finding(old.rule, old.path, 17, old.message)
        assert new_findings([moved], baseline) == []
        # a second occurrence beyond the baselined count is new
        assert new_findings([moved, moved], baseline) == [moved]
        # a different file is new
        other = Finding(old.rule, "other.py", 3, old.message)
        assert new_findings([other], baseline) == [other]

    def test_syntax_error_is_a_finding(self):
        assert rules_of(analyze_source("def broken(:\n", "x.py")) == \
            {"syntax-error"}

    def test_nonexistent_path_is_a_finding_not_a_vacuous_pass(self, tmp_path):
        findings = analyze_paths([str(tmp_path / "typo_dir")],
                                 root=str(tmp_path))
        assert rules_of(findings) == {"read-error"}

    def test_nonexistent_py_file_is_exactly_one_finding(self, tmp_path):
        findings = analyze_paths([str(tmp_path / "missing.py")],
                                 root=str(tmp_path))
        assert len(findings) == 1 and findings[0].rule == "read-error"

    def test_closure_capture_message_is_line_stable(self):
        """The message is the baseline key — it must not embed line
        numbers, or grandfathered findings churn on unrelated edits."""
        msgs = [f.message for f in fixture_findings("closure_capture_bad.py")
                if f.rule == "closure-capture"]
        assert msgs and all("line" not in m for m in msgs)

    def test_resource_lifecycle_scopes_are_separate(self):
        """A nested def's `return` must not mask the enclosing function's
        leak, and a nested leak is reported exactly once."""
        src = ("import socket\n"
               "def outer():\n"
               "    sock = socket.socket()\n"      # leaked: flagged
               "    def make():\n"
               "        sock = socket.socket()\n"  # own scope: returned
               "        return sock\n"
               "    return make\n")
        findings = [f for f in analyze_source(src, "x.py")
                    if f.rule == "resource-lifecycle"]
        assert [f.line for f in findings] == [3]

    def test_resource_lifecycle_closure_capture_is_escape(self):
        src = ("import socket\n"
               "def outer(register):\n"
               "    sock = socket.socket()\n"
               "    def cleanup():\n"
               "        sock.close()\n"
               "    register(cleanup)\n")
        assert analyze_source(src, "x.py") == []

    def test_closure_capture_tfcluster_facade_skips_spark_context(self):
        """The reference-compat facade is ``TFCluster.run(sc, map_fun,
        ...)`` — the payload is the SECOND positional arg, and a Lock
        capture in it must still be flagged (not the SparkContext)."""
        src = ("import threading\n"
               "def main(sc):\n"
               "    lock = threading.Lock()\n"
               "    def map_fun(args, ctx):\n"
               "        with lock:\n"
               "            pass\n"
               "    TFCluster.run(sc, map_fun, None, 4)\n")
        findings = [f for f in analyze_source(src, "x.py")
                    if f.rule == "closure-capture"]
        assert findings and "'lock'" in findings[0].message

    def test_lock_discipline_acquire_release_bracketing_counts_as_held(self):
        """Explicit acquire()/release() (the try/finally idiom) must count
        as holding the lock, same as `with self._lock:`."""
        src = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self.n = 0\n"
               "    def start(self):\n"
               "        threading.Thread(target=self._run).start()\n"
               "    def _run(self):\n"
               "        self._lock.acquire()\n"
               "        try:\n"
               "            self.n += 1\n"
               "        finally:\n"
               "            self._lock.release()\n"
               "    def bump(self):\n"
               "        with self._lock:\n"
               "            self.n += 1\n")
        assert analyze_source(src, "x.py") == []

    def test_reused_rule_instances_do_not_leak_finalize_state(self):
        """A rule instance reused across runs must not re-report the
        previous run's cross-file findings."""
        rules = [cls() for cls in ALL_RULES]
        cycle_src = ("import threading\n"
                     "class C:\n"
                     "    def __init__(self):\n"
                     "        self._alock = threading.Lock()\n"
                     "        self._block = threading.Lock()\n"
                     "    def ab(self):\n"
                     "        with self._alock:\n"
                     "            with self._block:\n"
                     "                pass\n"
                     "    def ba(self):\n"
                     "        with self._block:\n"
                     "            with self._alock:\n"
                     "                pass\n")
        first = analyze_source(cycle_src, "a.py", rules=rules)
        assert any("cycle" in f.message for f in first)
        assert analyze_source("x = 1\n", "b.py", rules=rules) == []

    def test_lock_order_multi_item_with_is_sequential(self):
        """`with self._b, self._a:` acquires b THEN a — paired with a
        nested `with self._a: with self._b:` elsewhere it is the classic
        AB-BA deadlock and must produce a cycle finding."""
        src = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._alock = threading.Lock()\n"
               "        self._block = threading.Lock()\n"
               "    def ab(self):\n"
               "        with self._alock:\n"
               "            with self._block:\n"
               "                pass\n"
               "    def ba(self):\n"
               "        with self._block, self._alock:\n"
               "            pass\n")
        findings = analyze_source(src, "x.py")
        assert any("cycle" in f.message for f in findings)

    def test_closure_capture_keyword_payload_also_checked(self):
        """`TPUCluster.run(map_fun=train, ...)` must be inspected like the
        positional form."""
        src = ("import threading\n"
               "def main():\n"
               "    lock = threading.Lock()\n"
               "    def train(args, ctx):\n"
               "        with lock:\n"
               "            pass\n"
               "    TPUCluster.run(map_fun=train, tf_args=None,\n"
               "                   num_workers=2)\n")
        findings = [f for f in analyze_source(src, "x.py")
                    if f.rule == "closure-capture"]
        assert findings and "'lock'" in findings[0].message

    def test_lock_order_same_class_name_across_files_not_merged(self, tmp_path):
        """Two unrelated classes that happen to share a name (and lock
        names) in different files must not have their acquisition edges
        merged into a phantom AB-BA cycle."""
        (tmp_path / "a.py").write_text(
            "import threading\n"
            "class Server:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._cond_lock = threading.Lock()\n"
            "    def step(self):\n"
            "        with self._lock:\n"
            "            with self._cond_lock:\n"
            "                pass\n")
        (tmp_path / "b.py").write_text(
            "import threading\n"
            "class Server:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._cond_lock = threading.Lock()\n"
            "    def step(self):\n"
            "        with self._cond_lock:\n"
            "            with self._lock:\n"
            "                pass\n")
        findings = analyze_paths([str(tmp_path)], root=str(tmp_path))
        assert not any("cycle" in f.message for f in findings)

    def test_jit_purity_static_argnums_branch_not_flagged(self):
        """Branching on a static_argnums/static_argnames-declared argument
        is valid JAX (jit re-traces per value) and must stay clean."""
        src = ("import jax\n"
               "from functools import partial\n"
               "@partial(jax.jit, static_argnums=(2,))\n"
               "def step(x, y, training):\n"
               "    if training:\n"
               "        return x + y\n"
               "    return x\n"
               "@partial(jax.jit, static_argnames=('mode',))\n"
               "def run(x, mode):\n"
               "    if mode:\n"
               "        return x * 2\n"
               "    return x\n")
        assert analyze_source(src, "x.py") == []

    def test_jit_purity_non_static_branch_still_flagged(self):
        src = ("import jax\n"
               "from functools import partial\n"
               "@partial(jax.jit, static_argnums=(2,))\n"
               "def step(x, y, training):\n"
               "    if y:\n"
               "        return x\n"
               "    return x + 1\n")
        assert rules_of(analyze_source(src, "x.py")) == {"jit-purity"}

    def test_jit_purity_static_shape_int_not_flagged(self):
        src = ("import jax\n"
               "@jax.jit\n"
               "def f(batch):\n"
               "    n = int(batch.shape[0])\n"
               "    return batch * n\n")
        assert analyze_source(src, "x.py") == []

    def test_finally_non_cleanup_method_is_not_cleanup(self):
        """A finally that merely TOUCHES the resource (no close/join/
        unlink, no del/None) must not silence the leak finding."""
        src = ("import socket\n"
               "def probe(log):\n"
               "    s = socket.socket()\n"
               "    try:\n"
               "        s.connect(('h', 1))\n"
               "    finally:\n"
               "        s.setblocking(True)\n")
        assert rules_of(analyze_source(src, "x.py")) == {"resource-lifecycle"}

    def test_finally_del_or_none_is_cleanup(self):
        src = ("import socket\n"
               "def probe():\n"
               "    s = socket.socket()\n"
               "    try:\n"
               "        s.connect(('h', 1))\n"
               "    finally:\n"
               "        del s\n")
        assert analyze_source(src, "x.py") == []


# ------------------------------------------------------------------- CLI

class TestCLI:
    def test_clean_path_exits_zero(self, capsys):
        rc = cli_main([os.path.join(FIXTURES, "broad_except_good.py")])
        assert rc == 0
        assert "0 new finding(s)" in capsys.readouterr().out

    def test_findings_exit_one_and_json(self, capsys):
        rc = cli_main(["--json",
                       os.path.join(FIXTURES, "broad_except_bad.py")])
        assert rc == 1
        data = json.loads(capsys.readouterr().out)
        assert len(data) == 3
        assert {d["rule"] for d in data} == {"broad-except"}

    def test_rule_filter(self, capsys):
        rc = cli_main(["--rules", "jit-purity",
                       os.path.join(FIXTURES, "broad_except_bad.py")])
        assert rc == 0
        capsys.readouterr()

    def test_unknown_rule_id_rejected(self, capsys):
        with pytest.raises(SystemExit):
            cli_main(["--rules", "no-such-rule", FIXTURES])
        capsys.readouterr()

    def test_missing_baseline_is_usage_error(self, tmp_path, capsys):
        rc = cli_main(["--baseline", str(tmp_path / "nope.json"), FIXTURES])
        assert rc == 2
        capsys.readouterr()

    def test_default_paths_use_checkout_root_from_any_cwd(self, tmp_path,
                                                          monkeypatch,
                                                          capsys):
        """The docs-advertised gate invocation must produce baseline-
        matching keys regardless of cwd (root defaults to the checkout
        root when paths are defaulted)."""
        monkeypatch.chdir(tmp_path)
        rc = cli_main(["--exports", "--baseline", BASELINE])
        assert rc == 0
        capsys.readouterr()

    def test_write_then_gate_roundtrip(self, tmp_path, capsys):
        bad = os.path.join(FIXTURES, "broad_except_bad.py")
        base = str(tmp_path / "b.json")
        assert cli_main(["--write-baseline", "--baseline", base, bad]) == 0
        # same findings now grandfathered
        assert cli_main(["--baseline", base, bad]) == 0
        capsys.readouterr()

    def test_jobs_and_stats_flags(self, capsys):
        rc = cli_main(["--jobs", "2", "--stats",
                       os.path.join(FIXTURES, "broad_except_good.py")])
        captured = capsys.readouterr()
        assert rc == 0
        assert "0 new finding(s)" in captured.out
        assert "stats:" in captured.err and "TOTAL" in captured.err

    def test_bad_jobs_is_usage_error(self, capsys):
        with pytest.raises(SystemExit):
            cli_main(["--jobs", "0", FIXTURES])
        capsys.readouterr()

    def test_scripts_shim(self):
        """`python scripts/tfos_check.py` works from a fresh checkout."""
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                          "tfos_check.py"),
             os.path.join(FIXTURES, "broad_except_bad.py")],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1, proc.stderr
        assert "broad-except" in proc.stdout


# ---------------------------------------------------------- exports drift

class TestExportsDrift:
    def test_current_repo_is_reconciled(self):
        assert check_exports(REPO_ROOT) == []

    def test_detects_both_directions(self, tmp_path):
        pkg = tmp_path / "tensorflowonspark_tpu"
        pkg.mkdir()
        (pkg / "__init__.py").write_text(
            "from tensorflowonspark_tpu.cluster import TPUCluster\n"
            "from tensorflowonspark_tpu.node import NodeContext\n")
        docs = tmp_path / "docs"
        docs.mkdir()
        (docs / "api.md").write_text(
            "## `tensorflowonspark_tpu` (package root)\n\n"
            "`TPUCluster`, `Ghost`.\n\n## `cluster`\n")
        msgs = [f.message for f in check_exports(str(tmp_path))]
        assert any("'NodeContext' is missing from docs" in m for m in msgs)
        assert any("'Ghost'" in m and "does not export" in m for m in msgs)

    def test_missing_inputs_fail_loudly_not_vacuously(self, tmp_path):
        """A missing __init__.py or docs/api.md must produce read-error
        findings, not a silent pass of the exports gate."""
        findings = check_exports(str(tmp_path))
        assert findings and all(f.rule == "read-error" for f in findings)
        assert {f.path for f in findings} == \
            {"tensorflowonspark_tpu/__init__.py", "docs/api.md"}

    def test_export_parsers(self):
        exported = public_exports(os.path.join(PKG_DIR, "__init__.py"))
        documented, _ = documented_names(
            os.path.join(REPO_ROOT, "docs", "api.md"))
        for name in ("TPUCluster", "run_with_recovery", "serving",
                     "PreemptionGuard"):
            assert name in exported
            assert name in documented


# -------------------------------------------------------------- preflight

def _gen_fn():
    yield 1


def _module_map_fun(args, ctx):
    return 0


_module_lock = threading.Lock()


def _fn_with_lock_default(args, ctx, guard=_module_lock):
    return 0


# preflight test doubles live at module level: instances of function-local
# classes are themselves (correctly) rejected as unpicklable-by-reference,
# which would mask the specific behavior each test exercises

class _GetstateHolder:
    def __init__(self):
        self._lock = threading.Lock()
        self.data = [1, 2, 3]

    def __getstate__(self):
        return {"data": self.data}


class _ItemHolder:
    def __init__(self, item):
        self.item = item

    def __getstate__(self):
        return {"item": self.item}


class _LockAttrHolder:
    def __init__(self):
        self.lock = threading.Lock()


class _SlotHolder:
    __slots__ = ("lock",)

    def __init__(self):
        self.lock = threading.Lock()


class _GetstateLiar:
    def __init__(self):
        self.data = 1

    def __getstate__(self):
        return {"oops": threading.Lock()}


class _CallablePayload:
    def __init__(self):
        self.guard = threading.RLock()

    def __call__(self, args, ctx):
        pass

class TestPreflight:
    def test_getstate_dropping_the_lock_passes(self):
        """An object that excludes its Lock via __getstate__ pickles fine
        and must pass preflight — walk what pickle ships, not raw
        __dict__."""
        check_payload({"args": _GetstateHolder()}, name="tf_args")

    def test_getstate_sibling_id_reuse_does_not_mask_offender(self):
        """Temporary __getstate__() dicts must be kept alive during the
        walk: a freed dict's address can be reused by a sibling's state,
        which would make the offender look already-seen and skip it."""
        with pytest.raises(PreflightError) as ei:
            check_payload([_ItemHolder("clean"),
                           _ItemHolder(threading.Lock())], name="tf_args")
        assert "lock" in str(ei.value)

    def test_deep_first_visit_does_not_mask_shallow_revisit(self):
        """An object first reached AT the depth cutoff is pruned; a later
        shallower path must re-walk it, not trust the pruned visit."""
        h = _LockAttrHolder()
        deep = {"a": {"b": {"c": {"d": h}}}, "h": h}
        with pytest.raises(PreflightError):
            check_payload(deep, name="tf_args")

    def test_slots_instance_state_is_walked(self):
        with pytest.raises(PreflightError) as ei:
            check_payload({"h": _SlotHolder()}, name="tf_args")
        assert ".lock" in str(ei.value)

    def test_function_local_class_instance_rejected(self):
        """Instances of a function-local class are the class-level twin of
        the nested-function case: pickle must re-import the class by
        reference and cannot."""
        class Cfg:
            pass

        with pytest.raises(PreflightError) as ei:
            check_payload({"cfg": Cfg()}, name="tf_args")
        assert "module level" in str(ei.value)

    def test_dict_keys_are_walked(self):
        import socket

        s = socket.socket()
        try:
            with pytest.raises(PreflightError) as ei:
                check_payload({s: "peer"}, name="tf_args")
            assert "socket" in str(ei.value)
        finally:
            s.close()

    def test_getstate_returning_a_lock_still_fails(self):
        with pytest.raises(PreflightError) as ei:
            check_payload(_GetstateLiar(), name="tf_args")
        assert "__getstate__" in str(ei.value)

    def test_lock_in_closure_named(self):
        lock = threading.Lock()

        def map_fun(args, ctx):
            with lock:
                pass

        with pytest.raises(PreflightError) as ei:
            check_payload(map_fun)
        assert "'lock'" in str(ei.value)
        assert "unpicklable" in str(ei.value)

    def test_clean_payloads_pass(self):
        check_payload(_module_map_fun)
        check_payload({"lr": 0.1, "layers": [1, 2, 3]}, name="tf_args")

    def test_jax_array_is_advisory_not_fatal(self, caplog):
        """Modern jax arrays pickle (the child rebuilds a host copy) —
        rejecting them would fail previously-working submissions.  The
        preflight warns instead."""
        jnp = pytest.importorskip("jax.numpy")
        with caplog.at_level(logging.WARNING,
                             logger="tensorflowonspark_tpu.analysis"
                                    ".preflight"):
            check_payload({"w": jnp.ones(3)}, name="tf_args")  # no raise
        assert any("jax array" in r.getMessage() for r in caplog.records)

    def test_depth_cutoff_is_logged_not_silent(self, caplog):
        """An offender below _MAX_DEPTH slips through (deliberate cost
        bound) — but the pruned branch must leave a debug trace, not
        silently imply the payload was fully vetted."""
        deep = {"a": {"b": {"c": {"d": {"e": threading.Lock()}}}}}
        with caplog.at_level(logging.DEBUG,
                             logger="tensorflowonspark_tpu.analysis"
                                    ".preflight"):
            check_payload(deep, name="tf_args")
        assert any("depth cutoff" in r.getMessage() for r in caplog.records)

    def test_nested_function_and_lambda_rejected_even_when_clean(self):
        """Functions pickle by reference: a <locals> function or lambda
        cannot be imported by the spawned worker no matter how clean its
        captures are — the most common spawn-pickle failure."""
        def nested(args, ctx):
            return 0

        with pytest.raises(PreflightError) as ei:
            check_payload(nested)
        assert "module level" in str(ei.value)
        with pytest.raises(PreflightError):
            check_payload(lambda a, c: None)

    def test_socket_in_args_container(self):
        import socket

        s = socket.socket()
        try:
            with pytest.raises(PreflightError) as ei:
                check_payload({"cfg": {"conn": s}}, name="tf_args")
            assert "tf_args['cfg']['conn']" in str(ei.value)
        finally:
            s.close()

    def test_open_file_default_arg(self):
        f = open(os.devnull)
        try:
            def map_fun(args, ctx, sink=f):
                pass

            with pytest.raises(PreflightError):
                check_payload(map_fun)
        finally:
            f.close()

    def test_callable_object_state_walked(self):
        with pytest.raises(PreflightError) as ei:
            check_payload(_CallablePayload())
        assert ".guard" in str(ei.value)

    def test_partial_pieces_walked(self):
        import functools

        ev = threading.Event()

        def fn(event, args, ctx):
            pass

        with pytest.raises(PreflightError) as ei:
            check_payload(functools.partial(fn, ev))
        assert "args[0]" in str(ei.value)

    def test_numpy_and_plain_data_not_flagged(self):
        import numpy as np

        check_payload({"weights": np.ones((8, 8)), "name": "ok"},
                      name="tf_args")

    def test_in_memory_buffers_pass(self):
        """io.BytesIO/StringIO pickle fine; only fd-backed files are
        rejected."""
        import io
        import pickle

        payload = {"blob": io.BytesIO(b"weights"), "txt": io.StringIO("x")}
        pickle.dumps(payload)  # the ground truth the preflight must match
        check_payload(payload, name="tf_args")

    def test_module_level_generator_function_passes(self):
        check_payload({"make_data": _gen_fn}, name="tf_args")

    def test_shared_offender_reported_under_both_payload_paths(self):
        """check_payloads must name an offender reachable from BOTH
        map_fun and tf_args under both paths — one resubmit fixes all."""
        from tensorflowonspark_tpu.analysis.preflight import check_payloads

        lock = threading.Lock()
        with pytest.raises(PreflightError) as ei:
            check_payloads(({"l": lock}, "map_fun"), ([lock], "tf_args"))
        msg = str(ei.value)
        assert "map_fun['l']" in msg and "tf_args[0]" in msg

    def test_module_level_function_defaults_never_ship(self):
        """A module-level function pickles by reference — the worker
        re-imports it, so an unpicklable DEFAULT is irrelevant and must
        not be rejected."""
        import pickle

        pickle.dumps(_fn_with_lock_default)  # ground truth
        check_payload(_fn_with_lock_default)
        check_payload({"fn": _fn_with_lock_default}, name="tf_args")

    def test_live_generator_rejected(self):
        with pytest.raises(PreflightError) as ei:
            check_payload({"data": _gen_fn()}, name="tf_args")
        assert "generator" in str(ei.value)


class _RecordingBackend:
    """Backend double: booting it at all is the failure condition."""

    def __init__(self):
        self.start_calls = 0

    def start(self, *a, **kw):
        self.start_calls += 1
        raise AssertionError("backend.start reached — preflight must "
                             "reject the payload before any spawn")

    def alive(self):
        return []

    def failed(self):
        return []

    def join(self, timeout=None):
        return True

    def terminate(self):
        pass


class TestRunPreflightIntegration:
    def test_run_rejects_lock_closure_before_spawn(self, tmp_path):
        """Acceptance: TPUCluster.run fails a Lock-capturing map_fun at
        submit time, naming the variable, with zero workers spawned."""
        from tensorflowonspark_tpu import TPUCluster

        progress_lock = threading.Lock()

        def map_fun(args, ctx):
            with progress_lock:
                pass

        backend = _RecordingBackend()
        with pytest.raises(PreflightError) as ei:
            TPUCluster.run(map_fun, {"steps": 1}, 1, backend=backend,
                           working_dir=str(tmp_path))
        assert "'progress_lock'" in str(ei.value)
        assert backend.start_calls == 0

    def test_run_checks_tf_args_too(self, tmp_path):
        from tensorflowonspark_tpu import TPUCluster

        def map_fun(args, ctx):
            pass

        backend = _RecordingBackend()
        with pytest.raises(PreflightError) as ei:
            TPUCluster.run(map_fun, {"bad": threading.Lock()}, 1,
                           backend=backend, working_dir=str(tmp_path))
        assert "tf_args['bad']" in str(ei.value)
        assert backend.start_calls == 0

    def test_escape_hatch_backend_flag(self, tmp_path):
        """A backend that never pickles can opt out per-submission with
        ``pickles_payload = False`` — no process-global env var needed."""
        from tensorflowonspark_tpu import TPUCluster

        lock = threading.Lock()

        def map_fun(args, ctx):
            with lock:
                pass

        backend = _RecordingBackend()
        backend.pickles_payload = False
        with pytest.raises(AssertionError, match="backend.start reached"):
            TPUCluster.run(map_fun, {}, 1, backend=backend,
                           working_dir=str(tmp_path))
        assert backend.start_calls == 1

    def test_escape_hatch_env(self, tmp_path, monkeypatch):
        from tensorflowonspark_tpu import TPUCluster

        monkeypatch.setenv("TFOS_NO_PREFLIGHT", "1")
        lock = threading.Lock()

        def map_fun(args, ctx):
            with lock:
                pass

        backend = _RecordingBackend()
        # preflight skipped: the run proceeds all the way to backend.start
        with pytest.raises(AssertionError, match="backend.start reached"):
            TPUCluster.run(map_fun, {}, 1, backend=backend,
                           working_dir=str(tmp_path))
        assert backend.start_calls == 1
