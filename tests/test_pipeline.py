"""Pipeline-layer tests.

Reference model: ``tests/test_pipeline.py`` upstream — TFEstimator.fit →
TFModel.transform end-to-end on a small model, input/output mapping,
signature selection (SURVEY.md §4) — plus unit coverage of the Param
machinery the reference inherits from pyspark.ml.
"""

import numpy as np
import pytest

from tensorflowonspark_tpu.dataframe import DataFrame, Row
from tensorflowonspark_tpu import pipeline as pl
from tests import cluster_funcs as funcs


# -- Param machinery --------------------------------------------------------

def test_mixin_accessors_and_defaults():
    est = pl.TFEstimator(lambda a, c: None, pl.Namespace())
    assert est.getBatchSize() == 100          # default
    est.setBatchSize(32).setClusterSize(4).setEpochs(2)
    assert est.getBatchSize() == 32
    assert est.getClusterSize() == 4
    assert est.getOrDefault("num_ps") == 0    # every mixin default registered
    assert est.getTagSet() == "serve"
    assert est.getSignatureDefKey() == "serving_default"
    assert "batch_size" in est.explainParams()


def test_setparams_rejects_unknown():
    est = pl.TFEstimator(lambda a, c: None)
    with pytest.raises(ValueError, match="no param"):
        est.setParams(nonexistent=1)


def test_tfparams_merge_args_params():
    args = pl.Namespace(lr=0.5, batch_size=7)
    est = pl.TFEstimator(lambda a, c: None, args)
    est.setBatchSize(64)
    merged = est.merge_args_params()
    assert merged.lr == 0.5
    assert merged.batch_size == 64            # set param wins over tf_args
    assert merged.epochs == 1                 # defaults flow in too


def test_params_copy_is_isolated():
    est = pl.TFEstimator(lambda a, c: None)
    est.setBatchSize(8)
    clone = est.copy({"batch_size": 16})
    assert est.getBatchSize() == 8
    assert clone.getBatchSize() == 16
    clone2 = est.copy({est.getParam("epochs"): 5})
    assert clone2.getEpochs() == 5


def test_param_grid_builder():
    est = pl.TFEstimator(lambda a, c: None)
    grid = (pl.ParamGridBuilder()
            .addGrid(est.getParam("batch_size"), [8, 16])
            .addGrid(est.getParam("epochs"), [1, 2, 3])
            .build())
    assert len(grid) == 6
    assert {frozenset((p.name, v) for p, v in g.items()) for g in grid} == {
        frozenset({("batch_size", b), ("epochs", e)})
        for b in (8, 16) for e in (1, 2, 3)}


# -- Pipeline / grid search over a dummy estimator --------------------------

_HasShift = pl._mixin("shift", "test shift", 0.0)


class _MeanEstimator(pl.Estimator, _HasShift):
    """Predict mean(y) + shift — tiny estimator for grid-search tests."""

    def _fit(self, df):
        mean = float(np.mean([r.y for r in df.collect()]))
        model = _MeanModel()
        model._mean = mean + self.getShift()
        return model


class _MeanModel(pl.Transformer):
    def _transform(self, df):
        return DataFrame([Row(y=r.y, pred=self._mean) for r in df.collect()],
                         num_partitions=df.num_partitions)


def test_pipeline_chains_stages():
    df = DataFrame([Row(y=float(i)) for i in range(8)])
    model = pl.Pipeline([_MeanEstimator()]).fit(df)
    assert isinstance(model, pl.PipelineModel)
    out = model.transform(df)
    assert out.columns == ["y", "pred"]
    assert out.collect()[0].pred == pytest.approx(3.5)


def test_train_validation_split_picks_best():
    df = DataFrame([Row(y=1.0) for _ in range(20)])
    est = _MeanEstimator()
    grid = pl.ParamGridBuilder().addGrid(est.getParam("shift"), [-1.0, 0.0, 2.0]).build()

    def evaluator(out):  # higher is better
        return -float(np.mean([(r.pred - r.y) ** 2 for r in out.collect()]))

    tvs = pl.TrainValidationSplit(est, evaluator, grid, trainRatio=0.5)
    best = tvs.fit(df)
    assert np.argmax(best.validationMetrics) == 1     # shift=0 wins
    assert best.transform(df).collect()[0].pred == pytest.approx(1.0)


# -- TFModel.transform against a real export --------------------------------

@pytest.fixture()
def linear_export(tmp_path):
    """Export y = 3x - 1 as a serving signature (in-process, CPU)."""
    from tensorflowonspark_tpu.checkpoint import export_model

    def serve(p, x):
        return p["w"] * x + p["b"]

    export_dir = str(tmp_path / "export")
    export_model(export_dir, serve, {"w": np.float32(3.0), "b": np.float32(-1.0)},
                 [np.zeros((2,), np.float32)],
                 input_names=["x"], output_names=["y"], is_chief=True)
    return export_dir


def test_tfmodel_transform_with_mappings(linear_export):
    df = DataFrame([Row(feature=np.float32(i), other="junk") for i in range(10)],
                   num_partitions=3)
    model = pl.TFModel()
    model.setExportDir(linear_export).setBatchSize(4)
    model.setInputMapping({"feature": "x"}).setOutputMapping({"y": "prediction"})
    out = model.transform(df)
    assert out.columns == ["prediction"]
    preds = [float(r.prediction) for r in out.collect()]
    assert preds == pytest.approx([3.0 * i - 1.0 for i in range(10)])
    assert out.num_partitions == 3


def test_tfmodel_bad_signature_and_missing_export(linear_export):
    model = pl.TFModel()
    model.setExportDir(linear_export).setInputMapping({"x": "x"})
    model.setSignatureDefKey("nope")
    with pytest.raises(KeyError, match="nope"):
        model.transform(DataFrame([Row(x=np.float32(0))]))
    with pytest.raises(ValueError, match="export_dir"):
        pl.TFModel().transform(DataFrame([Row(x=np.float32(0))]))


def test_model_cache_is_singleton(linear_export):
    a = pl._load_model_cached(linear_export, "serve")
    b = pl._load_model_cached(linear_export, "serve")
    assert a is b


def test_model_cache_invalidated_on_reexport(linear_export):
    # regression: grid search re-exports every point to the same dir — the
    # cache must serve the new weights, not the first fit's
    import os
    import time

    from tensorflowonspark_tpu.checkpoint import export_model

    model = pl.TFModel()
    model.setExportDir(linear_export).setInputMapping({"x": "x"})
    df = DataFrame([Row(x=np.float32(1.0))])
    assert float(model.transform(df).collect()[0].y) == pytest.approx(2.0)  # 3x-1

    time.sleep(0.01)
    export_model(linear_export, lambda p, x: p["w"] * x + p["b"],
                 {"w": np.float32(10.0), "b": np.float32(0.0)},
                 [np.zeros((2,), np.float32)],
                 input_names=["x"], output_names=["y"], is_chief=True)
    os.utime(os.path.join(linear_export, "export_meta.json"))
    assert float(model.transform(df).collect()[0].y) == pytest.approx(10.0)


def test_train_validation_split_empty_grid_raises():
    tvs = pl.TrainValidationSplit(_MeanEstimator(), lambda d: 0.0, [])
    with pytest.raises(ValueError, match="empty"):
        tvs.fit(DataFrame([Row(y=1.0)]))


def test_train_validation_split_shuffles_sorted_input():
    # rows sorted by y: a prefix cut would train only on low values
    df = DataFrame([Row(y=float(i)) for i in range(100)])
    est = _MeanEstimator()
    grid = pl.ParamGridBuilder().addGrid(est.getParam("shift"), [0.0]).build()

    def evaluator(out):
        return -float(np.mean([(r.pred - r.y) ** 2 for r in out.collect()]))

    best = tvs_fit = pl.TrainValidationSplit(est, evaluator, grid,
                                             trainRatio=0.5).fit(df)
    # with a random split, train mean ≈ global mean (49.5), not prefix mean (24.5)
    pred = best.transform(df).collect()[0].pred
    assert abs(pred - 49.5) < 8.0


# -- end-to-end: fit on a real cluster, transform the export -----------------

@pytest.mark.integration
def test_estimator_fit_then_transform(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.uniform(-1, 1, 256).astype(np.float32)
    y = (2.0 * x).astype(np.float32)
    df = DataFrame([Row(x=float(a), y=float(b)) for a, b in zip(x, y)],
                   num_partitions=4)

    export_dir = str(tmp_path / "export")
    args = pl.Namespace(lr=0.5, export_dir=export_dir)
    est = pl.TFEstimator(funcs.fn_train_linear_export, args)
    (est.setClusterSize(1).setEpochs(4).setBatchSize(32)
        .setInputMapping({"x": "x"}).setOutputMapping({"y": "pred"}))

    model = est.fit(df)
    assert isinstance(model, pl.TFModel)
    out = model.transform(df.select("x"))
    preds = np.array([float(r.pred) for r in out.collect()])
    np.testing.assert_allclose(preds, 2.0 * x, atol=0.15)


def test_transform_runs_partitions_concurrently(monkeypatch):
    """VERDICT r1 weak #6: partitions must be processed in parallel, like
    the reference's mapPartitions on all executors."""
    import threading
    import time

    active = [0]
    peak = [0]
    lock = threading.Lock()

    class _Sig:
        output_names = ["y"]

        def __call__(self, **feed):
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            time.sleep(0.15)
            with lock:
                active[0] -= 1
            return {"y": np.asarray(feed["x"]) * 2.0}

    class _Model:
        def signature(self, key):
            return _Sig()

    monkeypatch.setattr(pl, "_load_model_cached", lambda d, t: _Model())

    df = DataFrame.from_partitions(
        [[Row(x=float(i + 10 * p)) for i in range(3)] for p in range(4)])
    model = pl.TFModel()
    model.setExportDir("/nonexistent-fake")
    model.setBatchSize(8)
    out = model.transform(df)

    got = sorted(r.y for r in out.collect())
    want = sorted(float(i + 10 * p) * 2.0 for p in range(4) for i in range(3))
    assert got == want
    assert peak[0] >= 2, f"partitions ran serially (peak concurrency {peak[0]})"


def test_driver_ps_nodes_rejected():
    from tensorflowonspark_tpu.cluster import TPUCluster

    with pytest.raises(ValueError, match="driver_ps_nodes"):
        TPUCluster.run(funcs.fn_noop, {}, num_workers=2, num_ps=1,
                       driver_ps_nodes=True)


def test_cross_validator_kfold_picks_best_and_refits_on_full_data():
    df = DataFrame([Row(y=1.0) for _ in range(21)])
    est = _MeanEstimator()
    grid = pl.ParamGridBuilder().addGrid(
        est.getParam("shift"), [-1.0, 0.0, 2.0]).build()

    def evaluator(out):  # higher is better
        return -float(np.mean([(r.pred - r.y) ** 2 for r in out.collect()]))

    cv = pl.CrossValidator(est, evaluator, grid, numFolds=3)
    best = cv.fit(df)
    assert len(best.avgMetrics) == 3
    assert int(np.argmax(best.avgMetrics)) == 1       # shift=0 wins
    # winner refit on the FULL frame (pyspark contract)
    assert best.transform(df).collect()[0].pred == pytest.approx(1.0)


def test_cross_validator_validates_inputs():
    est = _MeanEstimator()
    with pytest.raises(ValueError, match="numFolds"):
        pl.CrossValidator(est, lambda d: 0.0, [{}], numFolds=1)
    cv = pl.CrossValidator(est, lambda d: 0.0, [], numFolds=2)
    with pytest.raises(ValueError, match="empty"):
        cv.fit(DataFrame([Row(y=1.0) for _ in range(4)]))
    grid = pl.ParamGridBuilder().addGrid(
        est.getParam("shift"), [0.0]).build()
    cv = pl.CrossValidator(est, lambda d: 0.0, grid, numFolds=4)
    with pytest.raises(ValueError, match="folds"):
        cv.fit(DataFrame([Row(y=1.0) for _ in range(3)]))


def test_cross_validator_accepts_string_keyed_param_maps(caplog):
    import logging

    df = DataFrame([Row(y=1.0) for _ in range(9)])
    est = _MeanEstimator()

    def evaluator(out):
        return -float(np.mean([(r.pred - r.y) ** 2 for r in out.collect()]))

    with caplog.at_level(logging.INFO):
        best = pl.CrossValidator(est, evaluator,
                                 [{"shift": 0.0}, {"shift": 2.0}],
                                 numFolds=3).fit(df)
    assert int(np.argmax(best.avgMetrics)) == 0
