"""Preemption: SIGTERM mid-training checkpoints and stops gracefully."""

import os
import signal

import numpy as np
import optax
import pytest

from tensorflowonspark_tpu import preemption
from tensorflowonspark_tpu.estimator import (Estimator, EvalSpec, TrainSpec,
                                             train_and_evaluate)
from tensorflowonspark_tpu.preemption import PreemptionGuard


@pytest.fixture(autouse=True)
def _clear_latch():
    preemption.reset()
    preemption._CALLBACKS.clear()
    yield
    preemption.reset()
    preemption._CALLBACKS.clear()


def test_guard_latches_sigterm_and_restores_handler():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as guard:
        assert not guard.preempted
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.wait(5)
        assert guard.preempted and preemption.is_preempted()
    assert signal.getsignal(signal.SIGTERM) is prev
    # the process-wide latch survives the guard's exit
    assert preemption.is_preempted()


def test_guard_off_main_thread_degrades_inert():
    """Constructed off the main thread (e.g. inside a feeder thread) the
    guard must degrade to an inert flag: no handler swap, no raise, and no
    handler restoration on exit that could clobber the main thread's."""
    import threading

    prev = signal.getsignal(signal.SIGTERM)
    result = {}

    def run():
        with PreemptionGuard() as guard:
            result["guard"] = guard
            result["handler_inside"] = signal.getsignal(signal.SIGTERM)
        result["handler_after"] = signal.getsignal(signal.SIGTERM)

    t = threading.Thread(target=run)
    t.start()
    t.join(10)
    assert not t.is_alive()
    assert result["handler_inside"] is prev, "no handler must be installed"
    assert result["handler_after"] is prev
    assert not result["guard"].preempted  # inert flag, never set
    # ...but the inert guard still SEES a latch set elsewhere in-process
    preemption._PREEMPTED.set()
    assert result["guard"].preempted
    assert signal.getsignal(signal.SIGTERM) is prev


def test_on_preempted_callbacks_fire_once_and_late_registration():
    calls = []
    preemption.on_preempted(lambda: calls.append("early"))
    with PreemptionGuard() as guard:
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.wait(5)
        os.kill(os.getpid(), signal.SIGTERM)  # second signal: no re-notify
        assert guard.wait(5)
    assert calls == ["early"]
    # registering after the latch fires immediately (node.run may attach
    # the heartbeat reporter after a very early SIGTERM)
    preemption.on_preempted(lambda: calls.append("late"))
    assert calls == ["early", "late"]


def _make_estimator(model_dir):
    import jax.numpy as jnp

    def init_fn():
        return {"w": jnp.zeros((4, 1))}

    def loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)

    return Estimator(init_fn, loss_fn, optax.sgd(0.1), str(model_dir),
                     save_every_steps=1000)


def test_sigterm_mid_training_saves_and_stops(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = rng.normal(size=(16, 1)).astype(np.float32)

    def input_fn():
        # fire the "preemption" after the third batch of the stream
        for i in range(1000):
            if i == 3:
                os.kill(os.getpid(), signal.SIGTERM)
            yield {"x": x, "y": y}

    with _make_estimator(tmp_path / "m") as est:
        final = est.train(input_fn, max_steps=1000)
    # stopped early (well before the 1000-step budget), without dying; the
    # prefetch lookahead means the signal (fired while producing batch 3)
    # lands a step or two before the consumer reaches it
    assert 1 <= final < 1000

    # the checkpoint at the stop step exists and a relaunch resumes there
    preemption.reset()
    with _make_estimator(tmp_path / "m") as est2:
        assert est2.global_step == final


def test_train_and_evaluate_stops_after_preemption(tmp_path):
    x = np.ones((8, 4), np.float32)
    y = np.ones((8, 1), np.float32)

    def input_fn():
        while True:
            yield {"x": x, "y": y}

    calls = [0]

    def eval_input_fn():
        calls[0] += 1
        yield {"x": x, "y": y}

    with _make_estimator(tmp_path / "m") as est:
        # set the process-wide latch directly: no guard is installed yet, so
        # a real SIGTERM here would kill pytest; the semantics under test
        # are the loop's reaction, and signal delivery is covered above
        preemption._PREEMPTED.set()
        train_and_evaluate(
            est,
            TrainSpec(input_fn=input_fn, max_steps=50),
            EvalSpec(input_fn=eval_input_fn, steps=1, throttle_steps=10))
    assert est.global_step < 50
    assert calls[0] == 0, "no eval round after preemption"
