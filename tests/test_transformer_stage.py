"""Composed 4D parallelism: pp (pipeline) x tp (Megatron) x sp (ring) x dp.

Oracle: the same math on one device — dense attention, sequential stages,
full (unsharded) weights.  The manual-SPMD stage must match forward values
and gradients across mesh layouts that exercise every axis combination an
8-device CPU mesh allows.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu.parallel import make_mesh, pipeline_apply, stack_stage_params
from tensorflowonspark_tpu.parallel.mesh import MeshSpec
from tensorflowonspark_tpu.parallel.ring_attention import reference_attention
from tensorflowonspark_tpu.parallel.transformer import (_layer_norm,
                                                        make_transformer_stage)
from jax.sharding import PartitionSpec as P

HID, HEADS, FFN, SEQ = 16, 4, 32, 8


def _oracle_stage(p, x, causal):
    h = _layer_norm(x, **p["ln1"])
    qkv = jnp.einsum("bth,hkjd->btkjd", h, p["wqkv"])
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    o = reference_attention(q, k, v, causal=causal)
    x = x + jnp.einsum("btjd,jdm->btm", o, p["wo"])
    h = _layer_norm(x, **p["ln2"])
    return x + jax.nn.gelu(h @ p["wup"]) @ p["wdown"]


def _oracle(stacked, x, causal):
    for i in range(jax.tree.leaves(stacked)[0].shape[0]):
        x = _oracle_stage(jax.tree.map(lambda p: p[i], stacked), x, causal)
    return x


@pytest.mark.parametrize("pp,dp,tp,sp,causal,sp_impl", [
    (2, 2, 2, 1, False, "ring"),
    (2, 1, 2, 2, True, "ring"),
    (2, 2, 1, 2, False, "ring"),
    (4, 1, 2, 1, True, "ring"),
    (2, 1, 2, 2, True, "ulysses"),
    (2, 2, 1, 2, False, "ulysses"),
])
def test_pipelined_tp_sp_transformer_matches_oracle(pp, dp, tp, sp, causal,
                                                    sp_impl):
    mesh = make_mesh(MeshSpec(pp=pp, dp=dp, tp=tp, sp=sp),
                     devices=jax.devices()[:pp * dp * tp * sp])
    stage_fn, init_fn, param_specs = make_transformer_stage(
        HID, HEADS, FFN, tp=tp, causal=causal, sp_impl=sp_impl)
    stacked = stack_stage_params(
        [init_fn(k) for k in jax.random.split(jax.random.key(0), pp)])
    num_mb = 2
    batch = 2 * num_mb * dp
    x = jax.random.normal(jax.random.key(1), (batch, SEQ, HID))
    data_spec = P(("dp", "fsdp"), "sp", None)

    y_ref = _oracle(stacked, x, causal)
    y_pipe = pipeline_apply(mesh, stage_fn, stacked, x,
                            num_microbatches=num_mb,
                            param_specs=param_specs, data_spec=data_spec)
    np.testing.assert_allclose(np.asarray(y_pipe), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-4)

    def loss_pipe(p):
        return jnp.mean(pipeline_apply(mesh, stage_fn, p, x,
                                       num_microbatches=num_mb,
                                       param_specs=param_specs,
                                       data_spec=data_spec) ** 2)

    def loss_ref(p):
        return jnp.mean(_oracle(p, x, causal) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_ref = jax.grad(loss_ref)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4),
        jax.device_get(g_pipe), g_ref)


def test_stage_param_sharding_is_applied():
    """Params placed via param_specs actually shard the head/ffn axes."""
    pp, tp = 2, 2
    mesh = make_mesh(MeshSpec(pp=pp, dp=2, tp=tp),
                     devices=jax.devices()[:8])
    stage_fn, init_fn, param_specs = make_transformer_stage(
        HID, HEADS, FFN, tp=tp)
    stacked = stack_stage_params(
        [init_fn(k) for k in jax.random.split(jax.random.key(0), pp)])
    from jax.sharding import NamedSharding
    placed = jax.device_put(
        stacked,
        jax.tree.map(lambda s: NamedSharding(mesh, P("pp", *s)), param_specs,
                     is_leaf=lambda s: isinstance(s, P)))
    shard = placed["wqkv"].addressable_shards[0]
    # [pp, hidden, 3, heads, head_dim] -> pp and heads axes sharded
    assert shard.data.shape[0] == 1
    assert shard.data.shape[3] == HEADS // tp
