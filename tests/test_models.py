"""Model zoo tests: forward shapes + one optimization step each, at toy sizes."""

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflowonspark_tpu.models import (Bert, BertConfig,
                                          BertForQuestionAnswering,
                                          BertForSequenceClassification,
                                          CifarResNet, MNISTNet, ResNet50,
                                          UNet, WideDeep)

TINY_BERT = BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                       num_heads=4, intermediate_size=64,
                       max_position_embeddings=64, dtype=jnp.float32)

# The big-model smoke tests jit init/apply instead of running eagerly:
# eager dispatch of a deep conv net is the slow path on a 1-core box
# (inception eager ≈ 50 s), and only jitted programs land in the
# persistent compile cache conftest enables — cached re-runs of these
# tests are seconds, not minutes.


def test_mnist_forward_and_step():
    model = MNISTNet()
    x = jnp.zeros((4, 28, 28, 1))
    params = jax.jit(model.init)(jax.random.key(0), x)
    logits = jax.jit(model.apply)(params, x)
    assert logits.shape == (4, 10)

    def loss_fn(p):
        out = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            out, jnp.zeros(4, jnp.int32)).mean()

    g = jax.jit(jax.grad(loss_fn))(params)
    assert jnp.isfinite(jax.tree.reduce(lambda a, b: a + b.sum(), g, 0.0))


def test_cifar_resnet_forward_train_mode():
    model = CifarResNet(dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = jax.jit(partial(model.init, train=True))(
        jax.random.key(0), x)
    assert "batch_stats" in variables
    logits, updates = jax.jit(
        partial(model.apply, train=True, mutable=["batch_stats"]))(
            variables, x)
    assert logits.shape == (2, 10)
    assert "batch_stats" in updates


def test_resnet50_forward_shape():
    model = ResNet50(num_classes=1000, dtype=jnp.float32)
    x = jnp.zeros((1, 64, 64, 3))  # small spatial for test speed
    variables = jax.jit(model.init)(jax.random.key(0), x)
    logits = jax.jit(model.apply)(variables, x)
    assert logits.shape == (1, 1000)


def test_s2d_stem_exactly_matches_conv7_stem():
    """The space-to-depth stem is the 7×7/s2 stem under an exact weight
    transform (MLPerf ResNet trick) — same params everywhere else, full
    forward outputs must agree to float32 tolerance."""
    from tensorflowonspark_tpu.models.resnet import (ResNet, BasicBlock,
                                                     conv7_stem_to_s2d_kernel)

    k = dict(stage_sizes=(1, 1), block=BasicBlock, num_classes=7,
             dtype=jnp.float32)
    m7 = ResNet(**k)
    ms = ResNet(**k, stem="s2d")
    x = jax.random.normal(jax.random.key(0), (2, 64, 64, 3), jnp.float32)
    v7 = m7.init(jax.random.key(1), x)
    k7 = v7["params"]["Conv_0"]["kernel"]
    assert k7.shape == (7, 7, 3, 64)
    vs = {**v7, "params": {**v7["params"],
                           "Conv_0": {"kernel": conv7_stem_to_s2d_kernel(k7)}}}
    out7 = m7.apply(v7, x)
    outs = ms.apply(vs, x)
    np.testing.assert_allclose(np.asarray(outs), np.asarray(out7),
                               rtol=1e-5, atol=1e-5)


def test_s2d_stem_trains_from_scratch():
    from tensorflowonspark_tpu.models.resnet import ResNet, BasicBlock

    model = ResNet(stage_sizes=(1, 1), block=BasicBlock, num_classes=5,
                   stem="s2d", dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=True)
    assert variables["params"]["Conv_0"]["kernel"].shape == (4, 4, 12, 64)
    logits, _ = model.apply(variables, x, train=True,
                            mutable=["batch_stats"])
    assert logits.shape == (2, 5)


def test_unet_preserves_spatial_dims():
    model = UNet(num_classes=3, features=(8, 16, 32), dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 1))
    variables = jax.jit(model.init)(jax.random.key(0), x)
    out = jax.jit(model.apply)(variables, x)
    assert out.shape == (2, 32, 32, 3)


def test_bert_trunk_and_heads():
    ids = jnp.ones((2, 16), jnp.int32)
    mask = jnp.ones((2, 16), bool)
    trunk = Bert(TINY_BERT)
    params = trunk.init(jax.random.key(0), ids, mask)
    hidden = trunk.apply(params, ids, mask)
    assert hidden.shape == (2, 16, 32)

    qa = BertForQuestionAnswering(TINY_BERT)
    qp = qa.init(jax.random.key(1), ids, mask)
    start, end = qa.apply(qp, ids, mask)
    assert start.shape == end.shape == (2, 16)

    cls = BertForSequenceClassification(TINY_BERT, num_classes=3)
    cp = cls.init(jax.random.key(2), ids, mask)
    assert cls.apply(cp, ids, mask).shape == (2, 3)


def test_bert_scan_layers_stacked_params_and_grads():
    """scan_layers+remat: one stacked block, masked attention still works,
    gradients reach every leaf."""
    import dataclasses

    cfg = dataclasses.replace(TINY_BERT, scan_layers=True, remat=True)
    ids = jnp.ones((2, 8), jnp.int32)
    mask = jnp.array([[1] * 6 + [0] * 2] * 2, bool)
    trunk = Bert(cfg)
    params = trunk.init(jax.random.key(0), ids, mask)
    assert "layers" in params["params"] and "layer_0" not in params["params"]
    stacked = jax.tree.leaves(params["params"]["layers"])[0]
    assert stacked.shape[0] == cfg.num_layers

    out = trunk.apply(params, ids, mask)
    assert out.shape == (2, 8, cfg.hidden_size)
    g = jax.grad(lambda p: jnp.mean(trunk.apply(p, ids, mask) ** 2))(params)
    assert all(float(jnp.abs(x).sum()) > 0 for x in jax.tree.leaves(g))

    # mask participates on the scan path too
    full = trunk.apply(params, ids, jnp.ones((2, 8), bool))
    assert not np.allclose(np.asarray(full[:, :6]), np.asarray(out[:, :6]))


@pytest.mark.parametrize("train", [False, True])
def test_bert_loop_remat_gradients(train):
    """Regression (same class as the GPT r5 fix): the loop branch's
    ``nn.remat(EncoderLayer)`` must mark ``train`` static — a traced
    kwarg breaks ``deterministic=not train`` with
    ``TracerBoolConversionError`` under jit."""
    import dataclasses

    cfg = dataclasses.replace(TINY_BERT, scan_layers=False, remat=True)
    ids = jnp.ones((2, 8), jnp.int32)
    mask = jnp.array([[1] * 6 + [0] * 2] * 2, bool)
    trunk = Bert(cfg)
    params = trunk.init(jax.random.key(0), ids, mask)

    def loss(p):
        out = trunk.apply(
            p, ids, mask, train=train,
            rngs={"dropout": jax.random.key(3)} if train else None)
        return jnp.mean(out.astype(jnp.float32) ** 2)

    g = jax.jit(jax.grad(loss))(params)
    assert all(float(jnp.abs(x).sum()) > 0 for x in jax.tree.leaves(g))


def test_bert_attention_mask_blocks_padding():
    ids = jnp.ones((1, 8), jnp.int32)
    trunk = Bert(TINY_BERT)
    params = trunk.init(jax.random.key(0), ids)
    full = trunk.apply(params, ids, jnp.ones((1, 8), bool))
    # padding tokens masked out: outputs at unmasked positions must differ
    # from the all-visible case if mask actually participates
    half = trunk.apply(params, ids, jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]], bool))
    assert not np.allclose(np.asarray(full[:, :4]), np.asarray(half[:, :4]))


def test_bert_with_ring_attention(jax_cpu_mesh_devices):
    from functools import partial

    from tensorflowonspark_tpu.parallel import make_mesh, ring_self_attention

    mesh = make_mesh(sp=4)
    cfg_ring = BertConfig(vocab_size=128, hidden_size=32, num_layers=1,
                          num_heads=4, intermediate_size=64,
                          max_position_embeddings=64, dtype=jnp.float32,
                          dropout_rate=0.0,
                          attention_fn=partial(ring_self_attention, mesh))
    cfg_dense = dataclasses.replace(cfg_ring, attention_fn=None)
    ids = jnp.ones((2, 32), jnp.int32)
    model_ring = Bert(cfg_ring)
    model_dense = Bert(cfg_dense)
    params = model_dense.init(jax.random.key(0), ids)
    out_dense = model_dense.apply(params, ids)
    out_ring = model_ring.apply(params, ids)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense),
                               rtol=2e-4, atol=2e-5)


def test_bert_ring_attention_respects_mask(jax_cpu_mesh_devices):
    """Regression: the custom attention_fn path must consume the padding
    mask (it was silently dropped before)."""
    from functools import partial

    from tensorflowonspark_tpu.parallel import make_mesh, ring_self_attention

    mesh = make_mesh(sp=4)
    cfg_ring = BertConfig(vocab_size=128, hidden_size=32, num_layers=1,
                          num_heads=4, intermediate_size=64,
                          max_position_embeddings=64, dtype=jnp.float32,
                          dropout_rate=0.0,
                          attention_fn=partial(ring_self_attention, mesh))
    cfg_dense = dataclasses.replace(cfg_ring, attention_fn=None)
    ids = jnp.ones((2, 32), jnp.int32)
    mask = jnp.arange(32)[None, :] < 20
    mask = jnp.broadcast_to(mask, (2, 32))
    params = Bert(cfg_dense).init(jax.random.key(0), ids)
    out_dense = Bert(cfg_dense).apply(params, ids, mask)
    out_ring = Bert(cfg_ring).apply(params, ids, mask)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense),
                               rtol=2e-4, atol=2e-5)
    # and the mask must actually change the result
    out_nomask = Bert(cfg_ring).apply(params, ids)
    assert not np.allclose(np.asarray(out_ring), np.asarray(out_nomask))


def test_wide_deep_forward_and_grad():
    model = WideDeep(vocab_sizes=(50, 30, 20), embed_dim=4, mlp_dims=(16, 8),
                     num_dense=5)
    dense = jnp.ones((4, 5))
    cat = jnp.array([[0, 1, 2]] * 4, jnp.int32)
    params = model.init(jax.random.key(0), dense, cat)
    logit = model.apply(params, dense, cat)
    assert logit.shape == (4,)

    def loss_fn(p):
        out = model.apply(p, dense, cat)
        return optax.sigmoid_binary_cross_entropy(out, jnp.ones(4)).mean()

    g = jax.grad(loss_fn)(params)
    leaves = jax.tree.leaves(g)
    assert leaves and all(jnp.isfinite(l).all() for l in leaves)


def test_inception_v3_forward_shape():
    from tensorflowonspark_tpu.models import InceptionV3

    model = InceptionV3(num_classes=11, dtype=jnp.float32)
    x = jnp.zeros((1, 75, 75, 3))  # smallest supported spatial extent
    variables = jax.jit(lambda x: model.init(
        {"params": jax.random.key(0), "dropout": jax.random.key(1)},
        x, train=True))(x)
    assert "batch_stats" in variables
    logits, updates = jax.jit(lambda v, x: model.apply(
        v, x, train=True, mutable=["batch_stats"],
        rngs={"dropout": jax.random.key(1)}))(variables, x)
    assert logits.shape == (1, 11)
    assert "batch_stats" in updates
    # inference path: no dropout rng needed
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 11)


def test_inception_v3_aux_head_canonical_size():
    from tensorflowonspark_tpu.models import InceptionV3

    model = InceptionV3(num_classes=7, aux_logits=True, dtype=jnp.float32)
    x = jax.ShapeDtypeStruct((2, 299, 299, 3), jnp.float32)

    def init(x):
        return model.init({"params": jax.random.key(0),
                           "dropout": jax.random.key(1)}, x, train=True)

    variables = jax.eval_shape(init, x)

    def fwd(v, x):
        return model.apply(v, x, train=True, mutable=["batch_stats"],
                           rngs={"dropout": jax.random.key(1)})

    (out, _updates) = jax.eval_shape(fwd, variables, x)
    logits, aux = out
    assert logits.shape == (2, 7)
    assert aux.shape == (2, 7)
