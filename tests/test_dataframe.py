"""DataFrame/Row stand-in tests (the pyspark.sql subset pipeline relies on)."""

import numpy as np
import pytest

from tensorflowonspark_tpu.dataframe import DataFrame, Row


def test_row_access_patterns():
    r = Row(image=[1, 2], label=3)
    assert r.image == [1, 2]
    assert r["label"] == 3
    assert r[0] == [1, 2]
    assert "label" in r and "nope" not in r
    assert list(r) == [[1, 2], 3]
    assert r.asDict() == {"image": [1, 2], "label": 3}
    with pytest.raises(AttributeError):
        _ = r.missing


def test_row_equality_with_arrays():
    a = Row(x=np.arange(3), y=1)
    b = Row(x=np.arange(3), y=1)
    c = Row(x=np.arange(4), y=1)
    assert a == b
    assert a != c


def test_dataframe_partitioning_and_collect():
    rows = [Row(a=i, b=i * 2) for i in range(10)]
    df = DataFrame(rows, num_partitions=3)
    assert df.columns == ["a", "b"]
    assert df.count() == 10
    assert df.num_partitions == 3
    assert [r.a for r in df.collect()] == list(range(10))


def test_dataframe_from_columns_and_to_columns():
    df = DataFrame.from_columns({"x": np.arange(6), "y": np.arange(6) * 10},
                                num_partitions=2)
    cols = df.to_columns()
    np.testing.assert_array_equal(cols["x"], np.arange(6))
    np.testing.assert_array_equal(cols["y"], np.arange(6) * 10)


def test_dataframe_select_and_map_partitions():
    df = DataFrame([Row(a=i, b=-i, c=0) for i in range(4)], num_partitions=2)
    sel = df.select("b", "a")
    assert sel.columns == ["b", "a"]
    assert list(sel.collect()[1]) == [-1, 1]
    sums = df.map_partitions(lambda p: [sum(r.a for r in p)])
    assert sums == [0 + 1, 2 + 3]


def test_dataframe_to_lists_matches_rdd_map_list():
    df = DataFrame([Row(img=[i], lbl=i) for i in range(4)], num_partitions=2)
    assert df.to_lists() == [[[[0], 0], [[1], 1]], [[[2], 2], [[3], 3]]]


def test_dataframe_schema_mismatch_rejected():
    with pytest.raises(ValueError):
        DataFrame([Row(a=1), Row(b=2)])


def test_dataframe_rows_from_dicts_and_lists():
    df = DataFrame([{"a": 1, "b": 2}, {"a": 3, "b": 4}])
    assert df.columns == ["a", "b"]
    df2 = DataFrame([[1, 2], [3, 4]], columns=["a", "b"])
    assert df2.collect()[1].b == 4
    df3 = df.repartition(2)
    assert df3.num_partitions == 2
