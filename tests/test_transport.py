"""Cross-host bulk transport tests (``transport.py`` + the ``queues.py``
three-tier hello).  All fast-tier: CPU only, loopback sockets.

The negotiation-downgrade tests mirror ``tests/test_shm.py`` shape for
shape: every path out of the bulk tier (handshake failure, env kill
switch, refusing endpoint, oversized payload, shm winning on a shared
host) must land on a working per-message pickle connection — degraded
throughput, never correctness.

The two counter-pinned tests at the bottom are the acceptance proof that
the standby weight clone and the disagg KV-session handoff actually RIDE
the bulk tier when shm is unavailable (the cross-host case, simulated by
pinning shm off), and that a corrupted page payload is rejected by
``adopt_session``'s content hashes without poisoning the engine.
"""

import gc
import socket
import struct
import threading

import numpy as np
import pytest

from tensorflowonspark_tpu import shm as shm_mod
from tensorflowonspark_tpu import transport as tp
from tensorflowonspark_tpu.queues import QueueClient, QueueServer
from tensorflowonspark_tpu.reservation import MessageSocket

AUTH = b"k" * 16

#: sample-sized buffers — above transport.BULK_OOB_MIN (4 KB) but below
#: MessageSocket.OOB_MIN_BYTES (64 KB), so the per-message tier carries
#: them in-band: exactly the shape the bulk tier exists to fix
SAMPLE = 2048  # f64 = 16 KB


def _chunk(n=48, seed=0):
    return [np.arange(SAMPLE, dtype=np.float64) + seed + i
            for i in range(n)]


def _assert_chunk_equal(got, n=48, seed=0):
    assert len(got) == n
    for i, a in enumerate(got):
        np.testing.assert_array_equal(
            a, np.arange(SAMPLE, dtype=np.float64) + seed + i)


@pytest.fixture()
def server():
    s = QueueServer(authkey=AUTH, mode="local", maxsize=8, shm=False)
    s.start()
    yield s
    s.stop()


# ------------------------------------------------- negotiation + roundtrip

def test_negotiation_and_roundtrip_integrity(server):
    c = QueueClient(server.addr, AUTH, shm=False)
    assert c.bulk_active, "shm-less client must negotiate the bulk tier"
    assert not c.shm_active
    c.put("input", _chunk())
    _assert_chunk_equal(server.queue_get("input", timeout=5))
    assert server.bulk_conns == 1
    assert c._chan.stats["bulk_msgs"] >= 1
    assert c._chan.stats["fallbacks"] == 0
    # nested containers and mixed dtypes survive the scatter/gather path
    big = np.arange(SAMPLE * 4, dtype=np.float32).reshape(64, -1)
    c.put("input", {"x": big, "meta": {"label": 7},
                    "small": np.arange(16, dtype=np.int32)})
    got = server.queue_get("input", timeout=5)
    np.testing.assert_array_equal(got["x"], big)
    assert got["meta"]["label"] == 7
    np.testing.assert_array_equal(got["small"],
                                  np.arange(16, dtype=np.int32))
    got["x"][0, 0] = -1.0  # received views must stay writable
    c.close()


def test_shm_preferred_over_bulk_on_same_host():
    """Tier one outranks tier two: a client that CAN prove shared memory
    must negotiate shm even when both endpoints would accept bulk."""
    s = QueueServer(authkey=AUTH, mode="local")
    s.start()
    try:
        c = QueueClient(s.addr, AUTH)
        assert c.shm_active and not c.bulk_active
        assert s.shm_conns == 1 and s.bulk_conns == 0
        c.put("input", _chunk(4))
        _assert_chunk_equal(s.queue_get("input", timeout=5), 4)
        c.close()
    finally:
        s.stop()


def test_env_kill_switch_pins_pickle_path(server, monkeypatch):
    monkeypatch.setenv(tp.DISABLE_ENV, "1")
    c = QueueClient(server.addr, AUTH, shm=False)
    assert not c.bulk_active and not c.shm_active
    c.put("input", _chunk(4))
    _assert_chunk_equal(server.queue_get("input", timeout=5), 4)
    assert server.bulk_conns == 0
    c.close()


def test_server_param_disable_downgrades_client():
    s = QueueServer(authkey=AUTH, mode="local", shm=False, bulk=False)
    s.start()
    try:
        c = QueueClient(s.addr, AUTH, shm=False)  # offers, server refuses
        assert not c.bulk_active
        c.put("input", _chunk(4))
        _assert_chunk_equal(s.queue_get("input", timeout=5), 4)
        c.close()
    finally:
        s.stop()


def test_client_param_disable(server):
    c = QueueClient(server.addr, AUTH, shm=False, bulk=False)
    assert not c.bulk_active
    c.put("input", [1, 2])
    assert server.queue_get("input", timeout=5) == [1, 2]
    c.close()


def test_handshake_failure_downgrades_old_peer(server, monkeypatch):
    """An old server that doesn't speak ``bulk_hello`` replies ERR for
    the unknown op — the client must silently land on the pickle path."""
    monkeypatch.setattr(
        tp, "hello_payload",
        lambda: {"op": "bulk_hello_vNEXT", "ver": 99})
    c = QueueClient(server.addr, AUTH, shm=False)
    assert not c.bulk_active
    c.put("input", _chunk(4))
    _assert_chunk_equal(server.queue_get("input", timeout=5), 4)
    c.close()


def test_handshake_version_mismatch_downgrades(server, monkeypatch):
    """A frame-version the server doesn't recognize is a refusal
    (``BULK False``), not an error."""
    good = tp.hello_payload()
    monkeypatch.setattr(tp, "hello_payload",
                        lambda: dict(good, ver=99))
    c = QueueClient(server.addr, AUTH, shm=False)
    assert not c.bulk_active
    assert server.bulk_conns == 0
    c.put("input", _chunk(4))
    _assert_chunk_equal(server.queue_get("input", timeout=5), 4)
    c.close()


def test_oversized_payload_falls_back(server, monkeypatch):
    """A payload larger than the peer's advertised slab travels inline
    (pickle-5 OOB socket framing) on the SAME connection; the next
    fitting payload rides bulk again."""
    monkeypatch.setenv(tp.SLAB_MB_ENV, "1")
    c = QueueClient(server.addr, AUTH, shm=False)
    assert c.bulk_active
    big = np.random.rand(1 << 18)              # 2 MB > the 1 MB slab
    c.put("input", big)
    np.testing.assert_array_equal(server.queue_get("input", timeout=5), big)
    assert c._chan.stats["fallbacks"] == 1
    assert c._chan.stats["bulk_msgs"] == 0
    c.put("input", _chunk(32))                 # 512 KB: fits again
    _assert_chunk_equal(server.queue_get("input", timeout=5), 32)
    assert c._chan.stats["bulk_msgs"] == 1
    c.close()


def test_small_control_messages_stay_inline(server):
    """Sub-threshold payloads (every control message) skip bulk framing
    without counting as fallbacks — small is the design, not a failure."""
    c = QueueClient(server.addr, AUTH, shm=False)
    assert c.bulk_active
    c.put("input", {"op": "marker", "tiny": np.arange(8)})
    got = server.queue_get("input", timeout=5)
    assert got["op"] == "marker"
    assert c._chan.stats["bulk_msgs"] == 0
    assert c._chan.stats["inline_msgs"] >= 1
    assert c._chan.stats["fallbacks"] == 0
    c.close()


def test_datafeed_next_chunk_over_bulk(server):
    from tensorflowonspark_tpu.datafeed import DataFeed
    from tensorflowonspark_tpu.marker import EndOfFeed, EndPartition

    c = QueueClient(server.addr, AUTH, shm=False)
    assert c.bulk_active
    c.put("input", _chunk(32, seed=1))
    c.put("input", EndPartition())
    c.put("input", _chunk(32, seed=2))
    c.put("input", EndOfFeed())
    feed = DataFeed(server)
    assert feed.next_chunk(timeout=5)[0][0] == 1.0
    assert feed.next_chunk(timeout=5)[0][0] == 2.0  # marker skipped
    assert feed.next_chunk(timeout=5) is None
    assert feed.should_stop()
    assert c._chan.stats["bulk_msgs"] == 2
    c.close()


# ------------------------------------------------------- slab pool units

def test_slab_pool_exhaustion_one_shot_then_recycles():
    pool = tp.SlabPool(slabs=1, slab_bytes=1 << 16)
    a = pool.acquire(1 << 12)
    assert pool.free_slabs == 0
    b = pool.acquire(1 << 12)          # exhausted: one-shot slab
    assert pool.pool_misses == 1
    views_a = a.views([0], [64])
    b.discard()
    assert pool.free_slabs == 0        # one-shot slab never pools
    del views_a
    gc.collect()                        # the lease's last view died
    assert pool.free_slabs == 1
    c = pool.acquire(1 << 12)
    assert pool.pool_misses == 1        # recycled, no new miss
    c.discard()
    pool.close()


def test_slab_views_anchor_until_last_derived_array_dies():
    """numpy base collapse: an array DERIVED from a received view keeps
    the slab leased after the view itself is gone (the shm-ring lease
    design, applied to pooled process memory)."""
    pool = tp.SlabPool(slabs=1, slab_bytes=1 << 16)
    lease = pool.acquire(1 << 12)
    [v] = lease.views([0], [1024])
    arr = np.frombuffer(v, np.uint8)[10:20]
    del v
    gc.collect()
    assert pool.free_slabs == 0, "derived array must keep the lease"
    del arr
    gc.collect()
    assert pool.free_slabs == 1
    pool.close()


def test_full_pool_of_small_slabs_upgrades_for_bigger_streams():
    """When the pool filled with small demand-sized slabs and the stream
    size then grows, the pool evicts a small free slab and allocates a
    bigger one in its place — it must not fall into the one-shot path
    forever."""
    pool = tp.SlabPool(slabs=2, slab_bytes=8 << 20)
    small = [pool.acquire(100), pool.acquire(100)]   # two MIN_SLAB slabs
    for lease in small:
        lease.discard()                               # both free again
    big = pool.acquire(4 << 20)                       # bigger than both
    assert pool.pool_misses == 0, "free small slab should be replaced"
    [v] = big.views([0], [4 << 20])
    assert len(v) == 4 << 20
    del v
    gc.collect()
    # the upgraded slab pools and is reused for the next big stream
    again = pool.acquire(4 << 20)
    assert pool.pool_misses == 0
    again.discard()
    pool.close()


def test_oversized_acquire_is_a_one_shot_slab():
    pool = tp.SlabPool(slabs=2, slab_bytes=1 << 12)
    lease = pool.acquire(1 << 14)      # larger than any pooled slab
    assert pool.pool_misses == 1
    [v] = lease.views([0], [1 << 14])
    assert len(v) == 1 << 14
    pool.close()


def test_aligned_layout_lens_matches_sender_layout():
    rng = np.random.default_rng(0)
    bufs = [memoryview(bytes(int(n)))
            for n in rng.integers(1, 5000, size=32)]
    send_offs, send_total = shm_mod.aligned_layout(list(bufs))
    recv_offs, recv_total = tp.aligned_layout_lens(
        [len(b) for b in bufs])
    assert send_offs == recv_offs and send_total == recv_total
    assert all(o % 64 == 0 for o in recv_offs)


# ------------------------------------------------- hello payload policy

def test_accept_payload_validation():
    good = tp.hello_payload()
    acc = tp.accept_payload(good)
    assert acc is not None and acc["chunk"] == good["chunk"]
    assert tp.accept_payload(dict(good, ver=2)) is None
    assert tp.accept_payload(dict(good, chunk=1024)) is None  # < 4 KB floor
    assert tp.accept_payload(dict(good, chunk="nope")) is None
    assert tp.accept_payload({}) is None
    # chunk negotiation: the smaller proposal wins
    small = tp.accept_payload(dict(good, chunk=8192))
    assert small["chunk"] == 8192


def test_resolve_crc_env_wins_and_typos_stay_safe(monkeypatch):
    monkeypatch.delenv(tp.CRC_ENV, raising=False)
    assert tp.resolve_crc() == "fast"
    assert tp.resolve_crc("full") == "full"
    monkeypatch.setenv(tp.CRC_ENV, "off")
    assert tp.resolve_crc("full") == "off"       # env outranks the peer
    monkeypatch.setenv(tp.CRC_ENV, "fulll")      # typo: stay verified
    assert tp.resolve_crc() == "fast"


def test_bulk_resolve_tristate(monkeypatch):
    monkeypatch.delenv(tp.DISABLE_ENV, raising=False)
    assert tp.bulk_resolve(None) and tp.bulk_resolve(True)
    assert not tp.bulk_resolve(False)
    monkeypatch.setenv(tp.DISABLE_ENV, "1")
    assert not tp.bulk_resolve(None) and not tp.bulk_resolve(True)


# ------------------------------------------- frame integrity (wire level)

class _CaptureSock:
    """Sender-side fake: records the exact wire byte stream."""

    def __init__(self):
        self.buf = bytearray()

    def sendmsg(self, iov):
        n = 0
        for v in iov:
            self.buf += bytes(v)
            n += len(v)
        return n


class _FeedSock:
    """Receiver-side fake: serves a byte stream to ``recv_into``; EOF
    (socket closed) once drained."""

    def __init__(self, data):
        self.data = memoryview(bytes(data))
        self.pos = 0

    def recv_into(self, view):
        n = min(len(view), len(self.data) - self.pos)
        view[:n] = self.data[self.pos:self.pos + n]
        self.pos += n
        return n


def _captured_stream(msg, crc_mode="full"):
    """The full wire image of one bulk message + the offset of the first
    chunk frame (right after the MessageSocket envelope frame)."""
    ms = MessageSocket()
    cap = _CaptureSock()
    tx = tp.BulkChannel(ms, cap, crc_mode=crc_mode, pipeline=False)
    tx.min_payload = 1024
    tx.send(msg)
    assert tx.bulk_msgs == 1, "test payload must take the bulk path"
    # envelope frame: [1B magic][1B ver][4B plen][4B nbuf] + pickle (the
    # bulk descriptor never carries MessageSocket-level OOB buffers)
    magic, ver, plen, nbuf = struct.unpack(">BBII", cap.buf[:10])
    assert nbuf == 0
    return cap.buf, 10 + plen


def _receive_stream(buf, crc_mode="full"):
    ms = MessageSocket()
    rx = tp.BulkChannel(ms, _FeedSock(buf), crc_mode=crc_mode,
                        pipeline=False)
    try:
        return rx.receive()
    finally:
        rx.close()


def _payload():
    return {"arrs": [np.arange(SAMPLE, dtype=np.float64) + i
                     for i in range(12)]}


def test_wire_roundtrip_through_fake_sockets():
    buf, _ = _captured_stream(_payload())
    got = _receive_stream(buf)
    _assert_chunk_equal(got["arrs"], 12)


def test_corrupt_payload_byte_rejected_full_crc():
    buf, chunk0 = _captured_stream(_payload(), crc_mode="full")
    bad = bytearray(buf)
    bad[-50] ^= 0xFF                     # payload byte of the last chunk
    with pytest.raises(tp.BulkIntegrityError, match="CRC mismatch"):
        _receive_stream(bad, crc_mode="full")


def test_corrupt_prefix_byte_rejected_fast_crc():
    """``fast`` mode checksums each chunk's first 4 KB — a flip there
    (desync, mis-offset scatter, stale slab) must still be caught."""
    buf, chunk0 = _captured_stream(_payload(), crc_mode="fast")
    bad = bytearray(buf)
    bad[chunk0 + tp._HDR.size + 100] ^= 0xFF
    with pytest.raises(tp.BulkIntegrityError, match="CRC mismatch"):
        _receive_stream(bad, crc_mode="fast")


def test_corrupt_header_magic_rejected():
    buf, chunk0 = _captured_stream(_payload())
    bad = bytearray(buf)
    bad[chunk0] ^= 0xFF                  # chunk frame magic byte
    with pytest.raises(tp.BulkIntegrityError, match="magic"):
        _receive_stream(bad)


def test_sequence_gap_rejected():
    buf, chunk0 = _captured_stream(_payload())
    bad = bytearray(buf)
    # _HDR = [1B magic][1B ver][2B flags][4B sid][4B seq]... -> seq @ +8
    struct.pack_into(">I", bad, chunk0 + 8, 7)
    with pytest.raises(tp.BulkIntegrityError, match="sequence gap"):
        _receive_stream(bad)


def test_digest_mismatch_rejected():
    buf, _ = _captured_stream(_payload())
    bad = bytearray(buf)
    bad[-1] ^= 0xFF                      # digest frame's crc field
    with pytest.raises(tp.BulkIntegrityError, match="digest"):
        _receive_stream(bad)


def test_truncated_stream_is_connection_death():
    buf, _ = _captured_stream(_payload())
    with pytest.raises(EOFError):
        _receive_stream(buf[:-30])


def test_crc_off_skips_payload_verification():
    """``off`` disables payload CRCs by contract (headers still checked):
    a mid-chunk flip is NOT a transport error — end-to-end content
    checks (the KV handoff's page hashes) own that layer."""
    buf, chunk0 = _captured_stream(_payload(), crc_mode="off")
    bad = bytearray(buf)
    bad[-50] ^= 0xFF
    got = _receive_stream(bad, crc_mode="off")
    assert len(got["arrs"]) == 12        # delivered, corrupted
    flat = np.concatenate(got["arrs"])
    ref = np.concatenate(_payload()["arrs"])
    assert not np.array_equal(flat, ref)


def test_failed_stream_discards_lease_and_pool_recovers():
    """An integrity failure mid-stream returns the slab to the pool —
    a few poisoned messages must not leak the receive buffers."""
    ms = MessageSocket()
    buf, chunk0 = _captured_stream(_payload())
    bad = bytearray(buf)
    bad[chunk0] ^= 0xFF
    rx = tp.BulkChannel(ms, _FeedSock(bad), pipeline=False, slabs=1)
    with pytest.raises(tp.BulkIntegrityError):
        rx.receive()
    assert rx._pool.free_slabs == 1 and rx._pool.pool_misses == 0
    rx.close()


# ---------------------- acceptance: clone + handoff ride the bulk tier

def _tiny_model():
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=61, hidden_size=32, num_layers=2,
                    num_heads=4, intermediate_size=64,
                    max_position_embeddings=48, dtype=jnp.float32,
                    pos_encoding="rope")
    params = GPT(cfg).init(jax.random.key(0),
                           jnp.ones((1, 4), jnp.int32))["params"]
    return cfg, params


def _bulk_rx_bytes():
    from tensorflowonspark_tpu import metrics as _metrics

    return _metrics.get_registry().counter(
        "tfos_transport_bytes_total",
        "Bulk-transport payload bytes by tier and direction.",
        labelnames=("tier", "dir")).value(tier="bulk", dir="rx")


def test_kv_session_handoff_rides_bulk_and_rejects_corruption(
        server, monkeypatch):
    """Satellite: the disagg KV-page handoff on a simulated cross-host
    hop (shm unavailable -> bulk negotiated, pinned via the transport
    counters), with ``adopt_session``'s content hashes still rejecting a
    corrupted page WITHOUT poisoning the adopting engine."""
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import (ContinuousBatcher,
                                              greedy_generate)

    monkeypatch.setenv(tp.MIN_KB_ENV, "1")   # tiny-model sessions qualify
    cfg, params = _tiny_model()
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (36,)).astype(np.int32)
    budget = 6

    pre = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                            prefill_only=True)
    pre.submit(prompt, budget)
    sessions = []
    for _ in range(20):
        pre.step()
        sessions.extend(pre.take_sessions())
        if not pre.load()["total"]:
            break
    [(_, sess)] = sessions

    # the cross-host hop: the session crosses a shm-less queue connection
    before = _bulk_rx_bytes()
    c = QueueClient(server.addr, AUTH, shm=False)
    assert c.bulk_active and not c.shm_active
    c.put("input", ("handoff", 0, sess))
    _, _, sess_rx = server.queue_get("input", timeout=10)
    c.put("input", ("handoff", 1, sess))
    _, _, sess_corrupt = server.queue_get("input", timeout=10)
    assert c._chan.stats["bulk_msgs"] == 2, \
        "the KV-page handoff must ride the bulk tier when shm is off"
    assert _bulk_rx_bytes() - before >= 2 * sum(
        np.asarray(a).nbytes for a in sess["kv"])
    c.close()

    # a page corrupted past the transport layer (CRC-sampled regions
    # clean) is the adopting engine's to reject, by content hash
    kv0 = np.asarray(sess_corrupt["kv"][0])
    kv0[tuple(0 for _ in kv0.shape)] += 1.0
    dec = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    with pytest.raises(ValueError, match="content hash mismatch"):
        dec.adopt_session(sess_corrupt)
    # the engine is NOT poisoned: the intact received session adopts and
    # decodes oracle-exact, zero re-prefill
    drid = dec.adopt_session(sess_rx)
    results = dec.run()
    assert dec.prefill_dispatches == 0
    oracle = np.asarray(greedy_generate(
        cfg, params, jnp.asarray(prompt)[None, :], budget))[0, len(prompt):]
    np.testing.assert_array_equal(results[drid], oracle)


def test_weight_clone_rides_bulk_when_shm_unavailable(server, monkeypatch):
    """Acceptance: ``serve_clone_request``'s params transfer negotiates
    the bulk tier when shm is pinned off (the cross-host standby heal),
    pinned via the transport counters, tree-exact on arrival."""
    import jax

    from tensorflowonspark_tpu.models import ContinuousBatcher
    from tensorflowonspark_tpu.serving.replica import serve_clone_request

    monkeypatch.setenv(shm_mod.DISABLE_ENV, "1")   # shm unavailable
    monkeypatch.setenv(tp.MIN_KB_ENV, "8")         # tiny params qualify
    cfg, params = _tiny_model()
    batcher = ContinuousBatcher(cfg, params, max_batch=2)

    class _Ctx:
        executor_id = 0

    before = _bulk_rx_bytes()
    conns_before = server.bulk_conns
    serve_clone_request(
        batcher, {"reply_addr": server.addr, "reply_authkey": AUTH},
        _Ctx(), export_pages=False)
    msg = server.queue_get("input", timeout=30)
    assert msg["op"] == "standby" and msg["event"] == "params"
    assert server.bulk_conns == conns_before + 1, \
        "the weight clone must negotiate the bulk tier when shm is off"
    flat_sent = jax.tree.leaves(jax.tree.map(np.asarray, params))
    flat_got = jax.tree.leaves(msg["params"])
    assert len(flat_sent) == len(flat_got)
    for a, b in zip(flat_sent, flat_got):
        np.testing.assert_array_equal(a, b)
    big = sum(a.nbytes for a in flat_sent if a.nbytes >= tp.BULK_OOB_MIN)
    assert _bulk_rx_bytes() - before >= big
