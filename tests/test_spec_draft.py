"""Draft-model speculative decoding (``models/serving.py`` DraftModel)
and the AOT executable cache (``serving/aot.py``).

Correctness never depends on the draft: every accepted token passed the
fused target verify, so outputs must equal the solo ``greedy_generate``
oracle whether the draft agrees (same weights), diverges (different
weights), or is absent.  The AOT cache's contract is load-or-compile:
a second process over the same directory loads every site, a corrupt
entry degrades to a recompile, never a wrong executable.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu.models import (GPT, GPTConfig, ContinuousBatcher,
                                          DraftModel, greedy_generate)
from tensorflowonspark_tpu.serving.aot import AOTExecutableCache


def _make(seed=0, **kw):
    base = dict(vocab_size=61, hidden_size=32, num_layers=2, num_heads=4,
                intermediate_size=64, max_position_embeddings=48,
                dtype=jnp.float32, pos_encoding="rope")
    cfg = GPTConfig(**{**base, **kw})
    params = GPT(cfg).init(jax.random.key(seed),
                           jnp.ones((1, 4), jnp.int32))["params"]
    return cfg, params


def _oracle(cfg, params, prompt, n):
    out = greedy_generate(cfg, params, jnp.asarray(prompt)[None, :], n)
    return np.asarray(out)[0, len(prompt):]


def test_draft_greedy_exact_and_accepts():
    """A same-weights draft must agree with the target, so acceptance is
    total, outputs stay oracle-exact, and the decode loop commits more
    than one token per dispatch."""
    cfg, params = _make()
    rng = np.random.default_rng(30)
    reqs = [(rng.integers(0, cfg.vocab_size, (t,)).astype(np.int32), n)
            for t, n in ((5, 12), (3, 10))]
    b = ContinuousBatcher(cfg, params, max_batch=2, speculative_k=4)
    b.set_draft(DraftModel(cfg, params, window=16))
    rids = [b.submit(p, n) for p, n in reqs]
    results = b.run()
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(results[rid], _oracle(cfg, params, p, n))
    assert b.draft_dispatches > 0
    assert b.spec_proposed > 0 and b.spec_accepted == b.spec_proposed
    # 22 tokens one-per-dispatch would cost >= 12 batched decode steps
    assert b.decode_dispatches < 12


def test_divergent_draft_stays_oracle_exact():
    """A draft with DIFFERENT weights mispredicts; the verify rejects
    and falls back to the target's own token — outputs identical to the
    no-draft run, token for token."""
    cfg, params = _make(seed=0)
    _, wrong = _make(seed=2)       # empirically disagrees with seed 0
    rng = np.random.default_rng(31)
    p = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
    b = ContinuousBatcher(cfg, params, max_batch=1, speculative_k=4)
    b.set_draft(DraftModel(cfg, wrong, window=16))
    rid = b.submit(p, 14)
    results = b.run()
    np.testing.assert_array_equal(results[rid], _oracle(cfg, params, p, 14))
    assert b.spec_proposed > 0          # it did speculate...
    assert b.spec_accepted < b.spec_proposed   # ...and got corrected


def test_sampled_rows_keep_draft0_fallback():
    """Sampled slots are ineligible for draft speculation: with a draft
    armed they produce exactly the plain batcher's tokens (pure function
    of the request's sampling parameters)."""
    cfg, params = _make()
    rng = np.random.default_rng(32)
    rep = np.tile(np.asarray([5, 9], np.int32), 6)
    nov = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)

    def run(draft):
        b = ContinuousBatcher(cfg, params, max_batch=2,
                              speculative_k=4 if draft else None)
        if draft:
            b.set_draft(DraftModel(cfg, params, window=16))
        r_greedy = b.submit(rep, 10)
        r_samp = b.submit(nov, 8, temperature=0.9, top_p=0.8, seed=42)
        res = b.run()
        return res[r_greedy], res[r_samp]

    g_draft, s_draft = run(True)
    g_plain, s_plain = run(False)
    np.testing.assert_array_equal(g_draft, g_plain)
    np.testing.assert_array_equal(s_draft, s_plain)


def test_accept_len_histogram_drain():
    """Per-dispatch accepted lengths accumulate for the replica metrics
    loop and drain destructively (the histogram publisher's contract)."""
    cfg, params = _make()
    b = ContinuousBatcher(cfg, params, max_batch=1, speculative_k=4)
    b.set_draft(DraftModel(cfg, params, window=16))
    rid = b.submit(np.asarray([3, 1, 4, 1, 5], np.int32), 10)
    b.run()
    assert rid is not None
    lens = b.take_spec_accept_lens()
    assert lens and all(isinstance(n, int) and 0 <= n <= 4 for n in lens)
    assert b.take_spec_accept_lens() == []      # drained


def test_set_draft_validation():
    cfg, params = _make()
    draft = DraftModel(cfg, params, window=16)

    plain = ContinuousBatcher(cfg, params, max_batch=1)
    with pytest.raises(ValueError, match="speculative_k"):
        plain.set_draft(draft)                  # draft needs spec_k

    b = ContinuousBatcher(cfg, params, max_batch=1, speculative_k=4)
    with pytest.raises(TypeError):
        b.set_draft(object())
    cfg2, params2 = _make(vocab_size=37)
    with pytest.raises(ValueError, match="vocab"):
        b.set_draft(DraftModel(cfg2, params2, window=16))
    with pytest.raises(ValueError, match="window"):
        # window + k overruns the draft's positions: 46 + 4 > 48
        b.set_draft(DraftModel(cfg, params, window=46))
    with pytest.raises(ValueError):
        DraftModel(cfg, params, window=0)

    b.set_draft(draft)
    assert b._draft_model is draft
    b.set_draft(None)                           # clears cleanly
    assert b._draft_model is None

    pf = ContinuousBatcher(cfg, params, max_batch=1, kv_page_tokens=8)
    pf.set_role("prefill")
    with pytest.raises(ValueError, match="prefill"):
        pf.set_draft(draft)


def test_aot_cache_hit_miss_corrupt(tmp_path):
    """The load-or-compile contract on a trivial site: first handle
    compiles and serializes, a second handle over the same directory
    loads (0 compiles), a corrupt entry counts an error and degrades to
    a recompile that overwrites it — never a crash."""
    x = jnp.arange(8, dtype=jnp.float32)

    def use(expect):
        c = AOTExecutableCache(str(tmp_path))
        f = c.wrap(("site", "v0"), lambda a: a * 2 + 1)
        np.testing.assert_allclose(np.asarray(f(x)), np.arange(8) * 2 + 1)
        assert (c.loads, c.compiles) == expect
        return c

    use((0, 1))                                 # miss -> compile + store
    use((1, 0))                                 # hit -> pure load
    [entry] = [p for p in os.listdir(tmp_path) if p.endswith(".aotx")]
    with open(tmp_path / entry, "wb") as f:
        f.write(b"garbage")
    c = use((0, 1))                             # corrupt -> recompile
    assert c.errors == 1
    use((1, 0))                                 # ...which re-stored it


def test_batcher_aot_identical_workload_compiles_zero(tmp_path):
    """A second batcher process-equivalent (fresh handles, same cache
    dir) over the identical workload resolves every serve-step site from
    disk — including verify and the draft's propose."""
    cfg, params = _make()
    p = np.asarray([2, 7, 1, 8], np.int32)

    def serve():
        cache = AOTExecutableCache(str(tmp_path))
        b = ContinuousBatcher(cfg, params, max_batch=2, speculative_k=4,
                              aot_cache=cache)
        b.set_draft(DraftModel(cfg, params, window=16))
        rid = b.submit(p, 9)
        out = b.run()[rid]
        np.testing.assert_array_equal(out, _oracle(cfg, params, p, 9))
        return cache.stats()

    first = serve()
    assert first["compiles"] > 0 and first["errors"] == 0
    second = serve()
    assert second["compiles"] == 0 and second["loads"] > 0


@pytest.mark.slow
def test_draft_composes_with_paged_prefix_cache():
    """Draft speculation over the paged-KV pool with the prefix cache:
    a shared system prompt hits the cache, the draft proposes, outputs
    stay oracle-exact."""
    cfg, params = _make()
    rng = np.random.default_rng(33)
    sys_p = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    reqs = [np.concatenate([sys_p, rng.integers(
        0, cfg.vocab_size, (3,)).astype(np.int32)]) for _ in range(3)]
    b = ContinuousBatcher(cfg, params, max_batch=2, speculative_k=4,
                          kv_page_tokens=8, prefix_cache=True)
    b.set_draft(DraftModel(cfg, params, window=16))
    rids = [b.submit(p, 8) for p in reqs]
    results = b.run()
    for rid, p in zip(rids, reqs):
        np.testing.assert_array_equal(results[rid], _oracle(cfg, params, p, 8))
    assert b.spec_accepted > 0


@pytest.mark.slow
def test_draft_with_tp_sharded_params_under_mesh():
    """Draft propose + fused verify over Megatron-tp-sharded params on a
    2-device mesh: acceptance fires, outputs equal the sharded solo run
    (the gang-leader posture of ``serving/sharded.py``)."""
    from tensorflowonspark_tpu.parallel import MeshSpec, make_mesh
    from tensorflowonspark_tpu.parallel.sharding import flax_shardings

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                    intermediate_size=64, max_position_embeddings=64,
                    dtype=jnp.float32, pos_encoding="rope")
    params = GPT(cfg).init(jax.random.key(0),
                           jnp.ones((1, 4), jnp.int32))["params"]
    mesh = make_mesh(MeshSpec(tp=2, dp=1), devices=jax.devices()[:2])
    abstract = jax.eval_shape(
        lambda: GPT(cfg).init(jax.random.key(0),
                              jnp.ones((1, 4), jnp.int32)))
    sharded = jax.device_put(params, flax_shardings(mesh, abstract)["params"])

    rep = np.tile(np.asarray([3, 8, 13], np.int32), 4)
    with mesh:
        b = ContinuousBatcher(cfg, sharded, max_batch=2, speculative_k=4)
        b.set_draft(DraftModel(cfg, sharded, window=16))
        rid = b.submit(rep, 12)
        results = b.run()
        want = np.asarray(greedy_generate(
            cfg, sharded, jnp.asarray(rep)[None, :], 12))[0, len(rep):]
    np.testing.assert_array_equal(results[rid], want)
    assert b.spec_accepted > 0


@pytest.mark.slow
def test_standby_posture_from_prebaked_cache(tmp_path):
    """The promotion path end-to-end in-process: one engine pre-bakes
    the cache via the standby warm-up sweep; a fresh engine then warms
    with 0 compiles, survives the unload/reload weights posture, and
    serves oracle-exact with the draft re-armed."""
    from tensorflowonspark_tpu.serving.standby import _warm_batcher

    cfg, params = _make()

    def build():
        cache = AOTExecutableCache(str(tmp_path))
        b = ContinuousBatcher(cfg, params, max_batch=2, speculative_k=4,
                              aot_cache=cache)
        b.set_draft(DraftModel(cfg, params, window=16))
        return b, cache

    b1, c1 = build()
    _warm_batcher(b1)
    assert c1.compiles > 0

    b2, c2 = build()
    _warm_batcher(b2)
    assert c2.compiles == 0 and c2.loads > 0    # pure cache read

    host = jax.tree_util.tree_map(np.asarray, params)
    b2.unload_params()
    b2.load_params(host)
    b2.set_draft(DraftModel(cfg, params, window=16))   # promotion re-arm
    p = np.asarray([4, 2, 9, 7], np.int32)
    rid = b2.submit(p, 10)
    out = b2.run()[rid]
    np.testing.assert_array_equal(out, _oracle(cfg, params, p, 10))
    assert b2.spec_accepted > 0
