"""Reference-facade parity: TFCluster/TFNode/TFManager/gpu_info/compat.

A reference user's imports and call shapes must work verbatim (SURVEY.md §2a
symbol names); these tests exercise each façade module end to end.
"""

import numpy as np

from tests import cluster_funcs as funcs


def test_tfcluster_run_reference_signature(tmp_path):
    from tensorflowonspark_tpu import TFCluster

    cluster = TFCluster.run(
        None, funcs.fn_sum_feed, {"batch_size": 8}, 2, 0, False,
        TFCluster.InputMode.SPARK, reservation_timeout=60,
        worker_env={"JAX_PLATFORMS": "cpu"}, working_dir=str(tmp_path))
    cluster.train(list(range(40)), num_epochs=1)
    cluster.shutdown(timeout=120)
    total = 0
    for f in tmp_path.glob("sum.*"):
        s, n = f.read_text().split(":")
        total += int(s)
    assert total == sum(range(40))


def test_tfnode_surface():
    from tensorflowonspark_tpu import TFNode

    assert TFNode.DataFeed is not None
    assert callable(TFNode.hdfs_path)
    assert callable(TFNode.start_cluster_server)
    assert callable(TFNode.export_saved_model)


def test_tfmanager_start_connect():
    import secrets

    from tensorflowonspark_tpu import TFManager

    key = secrets.token_bytes(8)
    mgr = TFManager.start(key, ["input", "output", "error"], mode="remote")
    try:
        addr = mgr.addr
        client = TFManager.connect(addr, key)
        client.put("input", [1, 2, 3])
        assert mgr.queue_get("input", timeout=5) == [1, 2, 3]
        client.close()
    finally:
        mgr.stop()


def test_gpu_info_shim():
    from tensorflowonspark_tpu import gpu_info

    csv = gpu_info.get_gpus(1)
    assert isinstance(csv, str)
    assert gpu_info.MAX_RETRIES >= 1


def test_compat_shims(tmp_path):
    import jax.numpy as jnp

    from tensorflowonspark_tpu import compat
    from tensorflowonspark_tpu.checkpoint import ExportedModel

    compat.disable_auto_shard(object())  # no-op, must not raise
    assert isinstance(compat.is_gpu_available(), bool)

    def fn(params, x):
        return params["w"] * x

    out = compat.export_saved_model(
        (fn, {"w": jnp.asarray(2.0)}, [np.zeros((3,), np.float32)]),
        str(tmp_path / "exp"), is_chief=True)
    assert out is not None
    model = ExportedModel.load(str(tmp_path / "exp"))
    got = model(np.asarray([1.0, 2.0, 3.0], np.float32))
    np.testing.assert_allclose(list(got.values())[0], [2.0, 4.0, 6.0])


def test_tfsparknode_aliases():
    from tensorflowonspark_tpu import TFSparkNode
    from tensorflowonspark_tpu.node import NodeContext

    assert TFSparkNode.TFNodeContext is NodeContext
    assert callable(TFSparkNode.run)


def test_tfcluster_run_rejects_scless_signature():
    import pytest

    from tensorflowonspark_tpu import TFCluster

    with pytest.raises(TypeError, match="SparkContext"):
        TFCluster.run(funcs.fn_noop, {}, 2, 0)


def test_host_fetch_drain():
    """Benchmark drain helper: fetches through arrays, numbers, pytrees
    (the block_until_ready-is-unreliable-on-axon workaround; every timing
    harness in bench.py / scripts/ ends its loops with this)."""
    import jax.numpy as jnp

    from tensorflowonspark_tpu.util import host_fetch_drain

    assert host_fetch_drain(jnp.ones((3, 3))) == 9.0
    assert host_fetch_drain(2.5) == 2.5
    assert host_fetch_drain(
        {"a": jnp.ones(4), "b": 1.0, "c": jnp.array(True)}) == 6.0
