"""Parallel-layer tests on the 8-device CPU-simulated mesh (SURVEY.md §4's
local-cluster analogue for sharding)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu.parallel import (DataParallelStrategy, FSDPStrategy,
                                            MeshSpec, PartitionRules,
                                            ShardedEmbedding, make_mesh,
                                            mesh_from_num_ps, ring_self_attention,
                                            shard_batch)
from tensorflowonspark_tpu.parallel.embedding import apply_sharded_lookup
from tensorflowonspark_tpu.parallel.ring_attention import reference_attention


@pytest.fixture(autouse=True)
def _mesh_devices(jax_cpu_mesh_devices):
    return jax_cpu_mesh_devices


# -- mesh ------------------------------------------------------------------

def test_make_mesh_infers_free_axis():
    mesh = make_mesh(tp=4)
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4
    assert set(mesh.axis_names) == {"pp", "dp", "fsdp", "ep", "sp", "tp"}


def test_make_mesh_rejects_bad_sizes():
    with pytest.raises(ValueError):
        make_mesh(MeshSpec(dp=3, tp=3))


def test_mesh_resolve_errors_name_axis_and_device_count():
    """Bad axis sizes must fail with a single-line error naming the axis
    and the device count — not an opaque reshape/modulo error."""
    # non-dividing fixed axis while inferring another
    with pytest.raises(ValueError, match=r"'dp'.*'tp': 3.*8 devices"):
        MeshSpec(tp=3).resolve(8)
    # fixed product mismatch, no free axis
    with pytest.raises(ValueError, match=r"'tp': 3.*require 3 devices.*8"):
        MeshSpec(dp=1, tp=3).resolve(8)
    # zero/negative sizes name the offending axis (historically a
    # ZeroDivisionError out of the modulo)
    with pytest.raises(ValueError, match=r"axis 'tp' has invalid size 0"):
        MeshSpec(tp=0).resolve(8)
    with pytest.raises(ValueError, match=r"axis 'sp' has invalid size -2"):
        MeshSpec(sp=-2).resolve(8)
    # two inferred axes are ambiguous, named
    with pytest.raises(ValueError, match=r"'dp'.*'tp'"):
        MeshSpec(dp=-1, tp=-1).resolve(8)
    # unknown axis kwargs name the valid set
    with pytest.raises(ValueError, match=r"unknown mesh axes \['xp'\]"):
        make_mesh(xp=2)


def test_mesh_from_num_ps_maps_to_ep():
    mesh = mesh_from_num_ps(4)
    assert mesh.shape["ep"] == 4 and mesh.shape["dp"] == 2


def test_hybrid_mesh_dcn_axis_crosses_slices():
    """dp over DCN, tp*sp inside each slice: every tp/sp neighbour pair
    stays in one slice, the dp hop crosses slices (2 fake slices of 4)."""
    from tensorflowonspark_tpu.parallel import make_hybrid_mesh

    mesh = make_hybrid_mesh(ici=dict(tp=2, sp=2), dcn=dict(dp=2),
                            slice_key=lambda d: d.id // 4)
    assert mesh.shape == {"pp": 1, "dp": 2, "fsdp": 1, "ep": 1,
                          "sp": 2, "tp": 2}
    grid = mesh.devices  # [pp, dp, fsdp, ep, sp, tp]
    slice_of = lambda d: d.id // 4  # noqa: E731
    for dp in range(2):
        block = grid[0, dp, 0, 0]  # [sp, tp] — one slice's worth
        assert {slice_of(d) for d in block.flat} == {dp}


def test_hybrid_mesh_axis_interleaves_dcn_major():
    """A single axis sized across both link classes: dcn-major, so
    consecutive entries along the axis stay in-slice until the slice's
    ici extent is exhausted."""
    from tensorflowonspark_tpu.parallel import make_hybrid_mesh

    mesh = make_hybrid_mesh(ici=dict(dp=4), dcn=dict(dp=2),
                            slice_key=lambda d: d.id // 4)
    assert mesh.shape["dp"] == 8
    dp_slices = [d.id // 4 for d in mesh.devices[0, :, 0, 0, 0, 0]]
    assert dp_slices == [0, 0, 0, 0, 1, 1, 1, 1]


def test_hybrid_mesh_single_slice_equals_make_mesh():
    from tensorflowonspark_tpu.parallel import make_hybrid_mesh

    # all 8 virtual devices are one process -> one slice; no dcn axes
    hybrid = make_hybrid_mesh(ici=dict(dp=2, tp=4))
    plain = make_mesh(dp=2, tp=4)
    assert [d.id for d in hybrid.devices.flat] == \
        [d.id for d in plain.devices.flat]


def test_hybrid_mesh_validation_errors():
    from tensorflowonspark_tpu.parallel import make_hybrid_mesh

    with pytest.raises(ValueError, match="unknown dcn axes"):
        make_hybrid_mesh(dcn=dict(bogus=2))
    with pytest.raises(ValueError, match="slice count"):
        make_hybrid_mesh(dcn=dict(dp=4), slice_key=lambda d: d.id // 4)
    with pytest.raises(ValueError, match="uneven slices"):
        make_hybrid_mesh(dcn=dict(dp=2),
                         slice_key=lambda d: 0 if d.id < 3 else 1)


def test_mesh_strategy_composes_with_hybrid_mesh():
    """The main training API accepts a multislice mesh: MeshStrategy over
    make_hybrid_mesh (dp across 2 fake slices, tp inside) trains to the
    same loss as plain single-device gradient descent."""
    import optax

    from tensorflowonspark_tpu.parallel import MeshStrategy, make_hybrid_mesh

    mesh = make_hybrid_mesh(ici=dict(dp=2, tp=2), dcn=dict(dp=2),
                            slice_key=lambda d: d.id // 4)
    strategy = MeshStrategy(mesh=mesh)
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((16,)), jnp.float32)
    tx = optax.sgd(0.1)

    def init_fn():
        return {"w": jnp.zeros((4,), jnp.float32)}

    def loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)

    state = strategy.init_state(init_fn, tx)
    step = strategy.build_train_step(loss_fn)
    batch = strategy.shard_batch({"x": X, "y": y})
    for _ in range(3):
        state, metrics = step(state, batch)

    # plain single-device oracle: same trajectory, weights AND last loss
    w = jnp.zeros((4,))
    losses = []
    for _ in range(3):
        loss, g = jax.value_and_grad(
            lambda w: jnp.mean((X @ w - y) ** 2))(w)
        losses.append(float(loss))
        w = w - 0.1 * g
    got_w = np.asarray(jax.device_get(state.params["w"]))
    np.testing.assert_allclose(got_w, np.asarray(w), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(metrics["loss"]), losses[-1],
                               rtol=1e-5)


def test_hybrid_mesh_dp_step_matches_single_device():
    """A data-parallel mean-loss grad step over the hybrid mesh (dp
    crossing the fake DCN boundary) equals the single-device value."""
    from tensorflowonspark_tpu.parallel import make_hybrid_mesh
    from jax.sharding import NamedSharding

    mesh = make_hybrid_mesh(ici=dict(dp=2, tp=2), dcn=dict(dp=2),
                            slice_key=lambda d: d.id // 4)
    w = jnp.asarray(np.random.default_rng(0).standard_normal((8, 8)),
                    jnp.float32)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((16, 8)),
                    jnp.float32)

    def loss(w, x):
        return jnp.mean(jnp.tanh(x @ w) ** 2)

    want = jax.grad(loss)(w, x)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dp",))))
    ws = jax.device_put(w, NamedSharding(mesh, P()))
    got = jax.jit(jax.grad(loss))(ws, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


# -- sharding --------------------------------------------------------------

def test_shard_batch_partitions_dim0():
    mesh = make_mesh(dp=8)
    batch = {"x": np.arange(32, dtype=np.float32).reshape(16, 2)}
    sharded = shard_batch(mesh, batch)
    assert sharded["x"].sharding.spec == P(("dp", "fsdp"))
    np.testing.assert_array_equal(np.asarray(sharded["x"]), batch["x"])


def test_partition_rules_path_matching():
    params = {"dense": {"kernel": jnp.ones((8, 16)), "bias": jnp.ones((16,))},
              "emb": {"embedding": jnp.ones((32, 8))}}
    rules = PartitionRules([
        (r".*emb.*", P("tp", None)),
        (r".*kernel", P(None, "tp")),
        (r".*", P()),
    ])
    specs = rules.tree_specs(params)
    assert specs["emb"]["embedding"] == P("tp", None)
    assert specs["dense"]["kernel"] == P(None, "tp")
    assert specs["dense"]["bias"] == P()


# -- strategies ------------------------------------------------------------

def _loss(params, batch):
    pred = batch["x"] @ params["w"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _init(key):
    return {"w": jax.random.normal(key, (4, 1)) * 0.1, "b": jnp.zeros((1,))}


def test_data_parallel_training_converges():
    strat = DataParallelStrategy()
    tx = optax.sgd(0.1)
    state = strat.init_state(_init, tx, jax.random.key(0))
    step = strat.build_train_step(_loss)
    true_w = np.array([[1.0], [-2.0], [0.5], [3.0]])
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(60):
        x = rng.normal(size=(64, 4)).astype(np.float32)
        batch = strat.shard_batch({"x": x, "y": x @ true_w})
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < 0.01 * losses[0]
    assert strat.num_replicas_in_sync == 8


def test_fsdp_shards_large_params():
    strat = FSDPStrategy(min_shard_size=16)
    tx = optax.adam(1e-3)

    def init(key):
        return {"big": jax.random.normal(key, (64, 8)),
                "tiny": jnp.zeros((3,))}

    state = strat.init_state(init, tx, jax.random.key(0))
    # big param sharded over fsdp on dim 0; tiny replicated
    assert state.params["big"].sharding.spec == P("fsdp", None)
    big_shard = state.params["big"].addressable_shards[0]
    assert big_shard.data.shape == (8, 8)
    assert state.params["tiny"].sharding.spec in (P(), P(None))


def test_fsdp_train_step_matches_single_device():
    strat = FSDPStrategy(min_shard_size=1)
    tx = optax.sgd(0.05)
    state = strat.init_state(_init, tx, jax.random.key(1))
    step = strat.build_train_step(_loss)
    x = np.ones((8, 4), np.float32)
    batch = strat.shard_batch({"x": x, "y": np.full((8, 1), 2.0, np.float32)})
    state1, m1 = step(state, batch)

    # oracle: same math, no sharding
    params = _init(jax.random.key(1))
    g = jax.grad(_loss)(params, {"x": jnp.asarray(x), "y": jnp.full((8, 1), 2.0)})
    expect_w = params["w"] - 0.05 * g["w"]
    np.testing.assert_allclose(np.asarray(state1.params["w"]), np.asarray(expect_w),
                               rtol=1e-5)


def test_anchor_activations_batch_sharding():
    """anchor_activations pins (pytrees of) activations to the data axes
    — the FSDP propagation anchor (scaling_model measured 47 GB -> 1.1 GB
    per step on BERT-base fsdp=8 from one anchor at the loss head)."""
    strat = FSDPStrategy(min_shard_size=1)
    x = jnp.ones((8, 4, 6))
    out = strat.anchor_activations({"h": x, "pooled": jnp.ones((8, 6)),
                                    "loss": jnp.float32(0.5)})
    assert out["h"].sharding.spec == P(("dp", "fsdp"), None, None)
    assert out["pooled"].sharding.spec == P(("dp", "fsdp"), None)
    assert float(out["loss"]) == 0.5  # scalars pass through untouched
    # numerics untouched, and usable under jit (the real usage site)
    np.testing.assert_array_equal(np.asarray(out["h"]), np.asarray(x))
    y = jax.jit(lambda a: strat.anchor_activations(a) * 2)(x)
    np.testing.assert_array_equal(np.asarray(y), 2 * np.asarray(x))


# -- sharded embedding (num_ps replacement) --------------------------------

def test_sharded_embedding_module_matches_dense():
    mesh = make_mesh(ep=4, dp=2)
    emb = ShardedEmbedding(num_embeddings=32, features=8, axis="ep")
    ids = jnp.array([[0, 5, 31], [7, 2, 16]])
    with mesh:
        params = emb.init(jax.random.key(0), ids)
        out = emb.apply(params, ids)
    table = params["params"]["embedding"]
    table = getattr(table, "value", table)  # unwrap nn.Partitioned
    np.testing.assert_allclose(np.asarray(out), np.asarray(jnp.take(table, ids, axis=0)),
                               rtol=1e-6)


def test_explicit_sharded_lookup_matches_take():
    mesh = make_mesh(ep=8)
    table = jax.random.normal(jax.random.key(2), (40, 16))
    ids = jnp.array([0, 4, 39, 12, 5])
    out = apply_sharded_lookup(mesh, table, ids, axis_name="ep")
    np.testing.assert_allclose(np.asarray(out), np.asarray(jnp.take(table, ids, axis=0)),
                               rtol=1e-5, atol=1e-6)


# -- ring attention --------------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    mesh = make_mesh(dp=2, sp=4)
    key = jax.random.key(3)
    kq, kk, kv = jax.random.split(key, 3)
    B, T, H, D = 4, 32, 2, 8
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, T, H, D))
    v = jax.random.normal(kv, (B, T, H, D))
    out = ring_self_attention(mesh, q, k, v, causal=causal)
    expect = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_jit_under_mesh():
    mesh = make_mesh(sp=8)
    B, T, H, D = 2, 64, 4, 16
    qkv = [jax.random.normal(jax.random.key(i), (B, T, H, D)) for i in range(3)]

    fn = jax.jit(lambda q, k, v: ring_self_attention(mesh, q, k, v, causal=True))
    out = fn(*qkv)
    expect = reference_attention(*qkv, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_build_train_step_extras_routing():
    """Three-arg loss_fn named 'extras' gets state.extras; a defaulted third
    arg must NOT (regression: arg-count-only inference misrouted extras)."""
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.parallel.strategy import DataParallelStrategy

    strategy = DataParallelStrategy()
    tx = optax.sgd(0.1)
    state = strategy.init_state(lambda: {"w": jnp.zeros(())}, tx)
    state.extras["scale"] = jnp.asarray(3.0)

    def loss_extras(params, batch, extras):
        return ((params["w"] * extras["scale"] - batch) ** 2).mean(), \
            {"extras": {"scale": extras["scale"] + 1}}
    loss_extras.has_aux = True

    step = strategy.build_train_step(loss_extras)
    state, _ = step(state, jnp.ones((8,)))
    assert float(state.extras["scale"]) == 4.0

    def loss_default(params, batch, rng=None):
        # extras must not land here; rng DOES (the per-step key plumbing)
        assert rng is not None
        return ((params["w"] - batch) ** 2).mean()

    state2 = strategy.init_state(lambda: {"w": jnp.zeros(())}, tx)
    step2 = strategy.build_train_step(loss_default)
    step2(state2, jnp.ones((8,)))

    def loss_kwargs(params, batch, **kw):
        assert not kw
        return ((params["w"] - batch) ** 2).mean()

    state3 = strategy.init_state(lambda: {"w": jnp.zeros(())}, tx)
    step3 = strategy.build_train_step(loss_kwargs)
    step3(state3, jnp.ones((8,)))


def test_build_train_step_rng_plumbing():
    """A loss_fn with an `rng` parameter receives a per-step key that is
    deterministic in (seed, step): different across steps, identical
    across runs, and resume-safe (derived from state.step)."""
    import jax
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.parallel.strategy import DataParallelStrategy

    def make(seed=0):
        s = DataParallelStrategy()
        s._base_rng = jax.random.key(seed)
        tx = optax.sgd(0.0)  # lr 0: params never change, isolate the rng
        state = s.init_state(lambda: {"w": jnp.zeros(())}, tx)
        return s, state

    seen = []

    def loss_fn(params, batch, rng=None):
        noise = jax.random.normal(rng, ())
        return params["w"] ** 2 + 0.0 * batch.sum(), {"noise": noise}
    loss_fn.has_aux = True

    strategy, state = make()
    step = strategy.build_train_step(loss_fn)
    batch = jnp.ones((8,))
    for _ in range(3):
        state, metrics = step(state, batch)
        seen.append(float(metrics["noise"]))
    assert len(set(seen)) == 3, f"per-step keys must differ: {seen}"

    # a fresh run reproduces the stream; resuming at step 1 reproduces
    # the step-1 noise (keys derive from state.step, not call count)
    strategy2, state2 = make()
    step2 = strategy2.build_train_step(loss_fn)
    state2, m0 = step2(state2, batch)
    assert float(m0["noise"]) == seen[0]
    state2, m1 = step2(state2, batch)
    assert float(m1["noise"]) == seen[1]


def test_gradient_accumulation_matches_big_batch():
    """accum_steps=4 over a 32-batch == one step on the full 32 batch
    (mean-reduced loss -> identical SGD update), and extras thread through
    the microbatch scan."""
    import jax
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.parallel.strategy import DataParallelStrategy

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = rng.normal(size=(32, 1)).astype(np.float32)
    tx = optax.sgd(0.1)

    def init():
        return {"w": jnp.zeros((4, 1))}

    def loss_fn(params, batch, extras):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2), \
            {"extras": {"count": extras["count"] + 1}}
    loss_fn.has_aux = True

    def run(accum):
        s = DataParallelStrategy()
        state = s.init_state(init, tx)
        state.extras["count"] = jnp.asarray(0)
        step = s.build_train_step(loss_fn, accum_steps=accum)
        batch = s.shard_batch({"x": x, "y": y})
        state, metrics = step(state, batch)
        return state, metrics

    s1, m1 = run(1)
    s4, m4 = run(4)
    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(s1.params["w"]),
                               np.asarray(s4.params["w"]), rtol=1e-5, atol=1e-7)
    assert int(s4.extras["count"]) == 4, "extras must thread per microbatch"
    assert int(s1.extras["count"]) == 1

    # per-microbatch rng: the i-th microbatch's key must be
    # fold_in(fold_in(base, step), i) — not the bare step key
    def loss_rng(params, batch, rng=None):
        return params["w"].sum() * 0.0 + jnp.mean(batch["x"]) * 0.0 \
            + jax.random.normal(rng, ()), {"noise": jax.random.normal(rng, ())}
    loss_rng.has_aux = True

    s = DataParallelStrategy()
    state = s.init_state(init, tx)
    step = s.build_train_step(loss_rng, accum_steps=2)
    # metrics carry the LAST microbatch's aux
    state, ma = step(state, s.shard_batch({"x": x, "y": y}))
    step_key = jax.random.fold_in(s._base_rng, 0)
    want = float(jax.random.normal(jax.random.fold_in(step_key, 1), ()))
    buggy = float(jax.random.normal(step_key, ()))
    assert float(ma["noise"]) == want, "microbatch key must fold in its index"
    assert float(ma["noise"]) != buggy

    with pytest.raises(ValueError, match="accum_steps"):
        s.build_train_step(loss_rng, accum_steps=0)

    # indivisible batch fails with a CLEAR error at trace time
    step3 = s.build_train_step(loss_rng, accum_steps=3)
    with pytest.raises(ValueError, match="not divisible"):
        step3(s.init_state(init, tx), s.shard_batch({"x": x, "y": y}))


def test_auto_fsdp_overlay_prefers_dim0_extension():
    """The ZeRO-3 overlay (``__graft_entry__.auto_fsdp_overlay``) must put
    fsdp on the FIRST divisible dim, extending an already-sharded dim 0
    (embedding vocab rows ``("tp",) -> ("tp", "fsdp")``) rather than
    falling through to a later dim: fsdp on a gather operand's feature
    dim makes GSPMD pay an involuntary-full-rematerialization reshard
    (round-3 verdict item 4)."""
    import __graft_entry__ as ge

    mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    overlay = ge.auto_fsdp_overlay(mesh)

    def apply(shape, spec):
        from jax.sharding import NamedSharding
        leaf = jax.ShapeDtypeStruct(shape, jnp.float32)
        return overlay(NamedSharding(mesh, P(*spec)), leaf).spec

    # embedding-table pattern: vocab already tp-sharded -> extend dim 0
    assert apply((128, 64), ("tp", None)) == P(("tp", "fsdp"), None)
    # unsharded dim 0 takes fsdp alone
    assert apply((64, 128), (None, "tp")) == P("fsdp", "tp")
    # dim 0 not divisible by tp*fsdp -> falls through to dim 1
    assert apply((126, 64), ("tp", None)) == P("tp", "fsdp")
    # small leaves and already-fsdp leaves stay untouched
    from jax.sharding import NamedSharding
    small = jax.ShapeDtypeStruct((8,), jnp.float32)
    sh = NamedSharding(mesh, P(None))
    assert overlay(sh, small) is sh
    done = NamedSharding(mesh, P("fsdp", None))
    big = jax.ShapeDtypeStruct((64, 64), jnp.float32)
    assert overlay(done, big) is done


def test_sparse_embedding_sgd_matches_dense_oracle():
    """Sparse SGD (touch only the batch's rows) must equal the dense-path
    oracle exactly: scatter-added duplicate gradients == the dense table
    gradient, and untouched rows must be bit-identical."""
    from tensorflowonspark_tpu.parallel import build_sparse_embedding_train_step

    mesh = make_mesh(ep=4)
    V, F, lr = 32, 8, 0.1
    table0 = jax.random.normal(jax.random.key(0), (V, F))
    ids = jnp.array([3, 17, 3, 31, 0, 3])     # duplicates on purpose
    tgt = jax.random.normal(jax.random.key(1), (ids.size, F))

    def loss_fn(emb, tgt):
        return jnp.mean((emb - tgt) ** 2)

    step = build_sparse_embedding_train_step(mesh, loss_fn, lr=lr,
                                             optimizer="sgd")
    table, _, loss = step(table0, table0, ids, tgt)

    # dense oracle: gradient through the gather, plain SGD
    def dense_loss(t):
        return loss_fn(jnp.take(t, ids, axis=0), tgt)
    g = jax.grad(dense_loss)(table0)
    want = table0 - lr * g
    np.testing.assert_allclose(np.asarray(table), np.asarray(want),
                               rtol=1e-6, atol=1e-7)
    untouched = [i for i in range(V) if i not in set(np.asarray(ids))]
    np.testing.assert_array_equal(np.asarray(table)[untouched],
                                  np.asarray(table0)[untouched])
    assert np.isfinite(float(loss))


def test_sparse_embedding_adagrad_semantics():
    """Adagrad sparse semantics: acc += sum of squared per-occurrence row
    gradients; update = -lr * summed gradient / sqrt(acc_new); rows the
    batch never touches keep zero accumulator and original values
    (TF SparseApplyAdagrad semantics, made deterministic for dups)."""
    from tensorflowonspark_tpu.parallel import build_sparse_embedding_train_step

    mesh = make_mesh(ep=4)
    V, F, lr, eps = 16, 4, 0.5, 1e-8
    table0 = jax.random.normal(jax.random.key(2), (V, F))
    acc0 = jnp.zeros((V, F))
    ids = jnp.array([1, 9, 1, 14])
    tgt = jax.random.normal(jax.random.key(3), (ids.size, F))

    def loss_fn(emb, tgt):
        return jnp.sum((emb - tgt) ** 2)

    step = build_sparse_embedding_train_step(mesh, loss_fn, lr=lr,
                                             optimizer="adagrad")
    table, acc, _ = step(table0, acc0, ids, tgt)

    # numpy oracle with the documented semantics
    t0 = np.asarray(table0)
    emb = t0[np.asarray(ids)]
    g_rows = 2.0 * (emb - np.asarray(tgt))      # d/demb of sum((e-t)^2)
    want_t, want_a = t0.copy(), np.zeros((V, F))
    for r in set(np.asarray(ids).tolist()):
        occ = [j for j, i in enumerate(np.asarray(ids)) if i == r]
        want_a[r] += sum(g_rows[j] ** 2 for j in occ)
        want_t[r] -= lr * sum(g_rows[j] for j in occ) \
            / np.sqrt(want_a[r] + eps)
    np.testing.assert_allclose(np.asarray(table), want_t,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(acc), want_a,
                               rtol=1e-5, atol=1e-6)
