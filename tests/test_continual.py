"""Continual-learning loop (``continual/`` — docs/continual.md).

Four layers:

- **publisher units** — flatten/diff/digest/replace math, the chief-only
  delta-only emit (payload bytes ≈ delta, never the base), the
  ``CheckpointManager`` save-listener hook, and the collector's
  digest/dedupe/config-error handling;
- **wire acceptance** — a multi-MB publication round-trips the real
  queue plane pinned to the BULK tier via the ``tfos_transport_*``
  counters, and a SIGKILL-mid-publish trainer under ``run_with_recovery``
  never surfaces a partial candidate (crash-atomicity);
- **retention units** — ``ModelRegistry(keep_versions=)`` eviction:
  payloads dropped, lineage kept, evicted versions unservable and
  unpromotable, journal replay/adopt honoring evictions;
- **pipeline units** — ``ContinualPipeline`` over the fake-replica
  world: promote / reject-offline / roll-back outcomes with their
  journal records, the payload store round-trip, and ``resume`` —
  a concluded rollout finalizes without re-shifting traffic (no double
  promotion), stored candidates re-hydrate, lost ones are skipped.

The full train→publish→gate→canary scenario (real clusters, chaos
driver kill) is ``scripts/bench_continual.py``'s job, wired into
``ci.sh --bench-smoke``.
"""

import json
import pickle
import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu import metrics as _metrics
from tensorflowonspark_tpu.continual import (CheckpointPublisher,
                                             Publication,
                                             PublicationCollector,
                                             ContinualPipeline, OfflineEval,
                                             build_published_full,
                                             diff_params, flatten_params,
                                             payload_digest, payload_nbytes,
                                             replace_leaves)
from tensorflowonspark_tpu.serving import (ModelRegistry, RolloutError,
                                           RolloutPolicy)
from tensorflowonspark_tpu.serving.journal import (ControlPlaneJournal,
                                                   JournalState)

from tests.test_rollout import (_ModelWorld, _builder, _collect,
                                _fake_tokens, _scheduler, _tier)

AUTH = b"k" * 16


class _RecMgr:
    """In-process stand-in for the worker's queue server: records puts."""

    def __init__(self):
        self.sent = []

    def queue_put(self, qname, item, timeout=None):
        self.sent.append((qname, item))


class _Ctx:
    def __init__(self, chief=True, mgr=None):
        self.executor_id = 0
        self.is_chief = chief
        self.mgr = mgr if mgr is not None else _RecMgr()


def _pubs_count(outcome):
    return _metrics.get_registry().counter(
        "tfos_continual_publications_total",
        "Checkpoint publications by ingest outcome.",
        labelnames=("outcome",)).value(outcome=outcome)


def _versions_count(outcome):
    return _metrics.get_registry().counter(
        "tfos_continual_versions_total",
        "Continual-loop candidates by terminal outcome.",
        labelnames=("outcome",)).value(outcome=outcome)


# ------------------------------------------------------ publisher units


def test_flatten_diff_digest_replace_roundtrip():
    base = {"a": {"kernel": np.ones((2, 3), np.float32)},
            "b": np.zeros((4,), np.float64)}
    flat = flatten_params(base)
    assert set(flat) == {"a/kernel", "b"}
    assert payload_nbytes(flat) == 2 * 3 * 4 + 4 * 8

    params = {"a": {"kernel": base["a"]["kernel"] + 0.5},
              "b": base["b"]}
    delta = diff_params(base, params)
    assert set(delta) == {"a/kernel"}          # unchanged leaves excluded
    np.testing.assert_allclose(delta["a/kernel"], 0.5)
    assert diff_params(base, params, atol=1.0) == {}   # below atol: noise

    with pytest.raises(ValueError, match="disagree on paths"):
        diff_params(base, {"a": {"kernel": np.ones((2, 3))}})
    with pytest.raises(ValueError, match="shape mismatch"):
        diff_params(base, {"a": {"kernel": np.ones((3, 2))},
                           "b": base["b"]})

    # the digest covers dtype AND shape — a reshape never collides
    d1 = payload_digest({"w": np.arange(6, dtype=np.float32)})
    d2 = payload_digest({"w": np.arange(6, dtype=np.float32).reshape(2, 3)})
    d3 = payload_digest({"w": np.arange(6, dtype=np.float64)})
    assert len({d1, d2, d3}) == 3

    # replace_leaves: full-publication application over the structure
    rebuilt = replace_leaves(base, flatten_params(params))
    np.testing.assert_allclose(rebuilt["a"]["kernel"], 1.5)
    assert rebuilt["a"]["kernel"].dtype == np.float32
    with pytest.raises(ValueError, match="misses leaf"):
        replace_leaves(base, {"b": np.zeros((4,))})
    with pytest.raises(ValueError, match="shape"):
        replace_leaves(base, {"a/kernel": np.ones((9,)), "b": flat["b"]})


def test_build_published_full_replaces_every_leaf():
    def base_builder(args):
        return {"cfg": True}, {"w": np.zeros((3,), np.float32)}

    cfg, params = build_published_full(
        {"serve_base_builder": base_builder,
         "serve_published_params": {"w": np.full((3,), 7.0)}})
    assert cfg == {"cfg": True}
    np.testing.assert_allclose(params["w"], 7.0)
    assert params["w"].dtype == np.float32     # cast to the base's dtype


def test_publisher_is_chief_only_and_ships_delta_not_base():
    """Satellite: an adapter-flavored publication's payload is the DELTA
    — a small fraction of the base's bytes — and only the chief emits."""
    base = {"w": np.zeros((1 << 16,), np.float64),   # 512 KB
            "b": np.zeros((32,), np.float32)}
    params = {"w": base["w"], "b": base["b"] + 1.0}
    ctx = _Ctx()
    pub = CheckpointPublisher(ctx, "m", base=base,
                              serve_args={"salt": 9})
    assert pub.publish(5, params) == "step-5"
    [(qname, msg)] = ctx.mgr.sent
    assert qname == "publish" and msg["op"] == "publish"
    assert msg["flavor"] == "adapter" and msg["version"] == "step-5"
    assert set(msg["payload"]) == {"b"}
    np.testing.assert_allclose(msg["payload"]["b"], 1.0)
    assert msg["digest"] == payload_digest(msg["payload"])
    base_bytes = payload_nbytes(flatten_params(base))
    assert msg["nbytes"] * 100 < base_bytes, \
        f"delta payload {msg['nbytes']}B is not ≪ base {base_bytes}B"
    # a non-chief worker publishes nothing (orbax saves everywhere, one
    # candidate per step must emerge)
    ctx2 = _Ctx(chief=False)
    assert CheckpointPublisher(ctx2, "m").publish(1, params) is None
    assert ctx2.mgr.sent == []
    # and a queue-less context (non-SPARK boot) is a typed config error
    class _NoQueues:
        executor_id = 0
        is_chief = True
        mgr = None

    with pytest.raises(RuntimeError, match="queue server"):
        CheckpointPublisher(_NoQueues(), "m")


def test_checkpoint_save_listener_fires_and_swallows_errors(tmp_path):
    """The emit hook (``CheckpointManager.add_save_listener``) fires on
    successful saves with (step, state); a raising listener is logged
    and swallowed; bare numpy scalars in the state are normalized for
    orbax (the pre-existing StandardSave failure)."""
    from tensorflowonspark_tpu.checkpoint import CheckpointManager

    events, boom = [], []
    with CheckpointManager(str(tmp_path / "ckpt")) as ckpt:
        ckpt.add_save_listener(lambda step, state: events.append(step))

        def bad(step, state):
            boom.append(step)
            raise RuntimeError("listener boom")

        ckpt.add_save_listener(bad)
        assert ckpt.save(1, {"step": np.int64(1), "w": np.float32(3.0)},
                         force=True)
        ckpt.wait()
        assert events == [1] and boom == [1]   # both ran; boom swallowed
        assert not ckpt.save(1, {"step": np.int64(1), "w": np.float32(3.0)})
        assert events == [1], "a skipped save must not publish"
        state = ckpt.restore()
        assert int(state["step"]) == 1 and float(state["w"]) == 3.0


def test_publisher_attach_publishes_each_durable_save(tmp_path):
    from tensorflowonspark_tpu.checkpoint import CheckpointManager

    ctx = _Ctx()
    pub = CheckpointPublisher(ctx, "m", metadata={"run": "r1"})
    with CheckpointManager(str(tmp_path / "ckpt")) as ckpt:
        pub.attach(ckpt, transform=lambda s: s["params"])
        ckpt.save(3, {"params": {"w": np.ones((4,), np.float32)},
                      "step": np.int64(3)}, force=True)
    [(qname, msg)] = ctx.mgr.sent
    assert msg["version"] == "step-3" and msg["flavor"] == "full"
    assert msg["metadata"] == {"run": "r1"} and msg["step"] == 3
    np.testing.assert_array_equal(msg["payload"]["w"],
                                  np.ones((4,), np.float32))


def test_collector_rejects_corrupt_dedupes_and_flags_config(tmp_path):
    """Collector hygiene over a real queue server: a digest-mismatched
    (partial/corrupt) message is dropped and counted, a duplicate
    ``(model, version)`` is dropped, a non-publication message is
    ignored, and a missing ``publish`` queue is a TYPED config error
    pointing at ``queues=CONTINUAL_QUEUES``."""
    from tensorflowonspark_tpu.queues import QueueServer

    server = QueueServer(authkey=AUTH, qnames=("publish",), mode="local",
                         shm=False)
    server.start()

    class _Cluster:
        cluster_info = [{"executor_id": 0, "addr": server.addr,
                         "authkey": AUTH}]
        cluster_meta = {"queue_shm": False, "queue_bulk": None}

    try:
        col = PublicationCollector(_Cluster())
        payload = {"w": np.arange(8, dtype=np.float32)}
        good = {"op": "publish", "model": "m", "version": "v1",
                "flavor": "full", "step": 1, "seq": 0, "src": 0,
                "serve_args": {}, "metadata": {}, "payload": payload,
                "digest": payload_digest(payload), "nbytes": 32}
        corrupt_before = _pubs_count("corrupt")
        dup_before = _pubs_count("duplicate")
        server.queue_put("publish", dict(good, digest="0" * 64))
        server.queue_put("publish", {"op": "gen", "rid": 1})
        server.queue_put("publish", good)
        server.queue_put("publish", dict(good))        # duplicate version
        pubs = col.poll()
        assert [p.version for p in pubs] == ["v1"]
        np.testing.assert_array_equal(pubs[0].payload["w"], payload["w"])
        assert _pubs_count("corrupt") == corrupt_before + 1
        assert _pubs_count("duplicate") == dup_before + 1
        # mark_seen pre-seeds the dedupe (the resume path)
        col.mark_seen("m", "v2")
        server.queue_put("publish", dict(good, version="v2",
                                         digest=good["digest"]))
        assert col.poll() == []
        col.close()
    finally:
        server.stop()

    # a server WITHOUT the publish queue: config error, not a dead worker
    plain = QueueServer(authkey=AUTH, qnames=("input",), mode="local",
                        shm=False)
    plain.start()

    class _Cluster2:
        cluster_info = [{"executor_id": 0, "addr": plain.addr,
                         "authkey": AUTH}]
        cluster_meta = {"queue_shm": False, "queue_bulk": None}

    try:
        col2 = PublicationCollector(_Cluster2())
        plain.queue_put("input", "x")      # make qsize server-side valid
        with pytest.raises(RuntimeError, match="CONTINUAL_QUEUES"):
            col2.poll()
        col2.close()
    finally:
        plain.stop()


# ------------------------------------------- wire acceptance (satellite)


def _bulk_rx_bytes():
    return _metrics.get_registry().counter(
        "tfos_transport_bytes_total",
        "Bulk-transport payload bytes by tier and direction.",
        labelnames=("tier", "dir")).value(tier="bulk", dir="rx")


def test_weight_stream_rides_bulk_tier_and_delta_stays_small(monkeypatch):
    """Acceptance: a multi-MB FULL publication crosses the collector's
    queue client on the BULK tier (pinned via ``tfos_transport_*``
    counters, digest-exact on arrival); the follow-up ADAPTER
    publication moves ≈ the delta's bytes — a small fraction of the
    base — over the same wire."""
    from tensorflowonspark_tpu import transport as tp
    from tensorflowonspark_tpu.queues import QueueServer

    monkeypatch.setenv(tp.MIN_KB_ENV, "1")
    server = QueueServer(authkey=AUTH, qnames=("publish",), mode="local",
                         shm=False)
    server.start()

    class _Cluster:
        cluster_info = [{"executor_id": 0, "addr": server.addr,
                         "authkey": AUTH}]
        cluster_meta = {"queue_shm": False, "queue_bulk": None}

    base = {"w": np.zeros((1 << 19,), np.float64),     # 4 MB
            "b": np.zeros((1 << 12,), np.float32)}     # 16 KB
    full = {"w": np.arange(1 << 19, dtype=np.float64),
            "b": np.full((1 << 12,), 2.0, np.float32)}
    base_bytes = payload_nbytes(flatten_params(base))

    ctx = _Ctx(mgr=server)
    try:
        col = PublicationCollector(_Cluster())
        # full flavor: every leaf crosses the wire
        CheckpointPublisher(ctx, "m").publish(1, full)
        before = _bulk_rx_bytes()
        [pub_full] = col.poll()
        rx_full = _bulk_rx_bytes() - before
        assert col._clients[0].bulk_active and not col._clients[0].shm_active
        assert rx_full >= base_bytes, \
            f"full payload must ride bulk: rx {rx_full} < {base_bytes}"
        assert pub_full.flavor == "full"
        assert payload_digest(pub_full.payload) == pub_full.digest
        np.testing.assert_array_equal(pub_full.payload["w"], full["w"])

        # adapter flavor: only the delta's bytes move
        delta_params = {"w": base["w"], "b": base["b"] + 1.0}
        CheckpointPublisher(ctx, "m", base=base).publish(2, delta_params)
        before = _bulk_rx_bytes()
        [pub_delta] = col.poll()
        rx_delta = _bulk_rx_bytes() - before
        assert set(pub_delta.payload) == {"b"}
        assert rx_delta >= pub_delta.nbytes
        assert rx_delta < base_bytes // 4, \
            f"adapter swap moved {rx_delta}B — base-sized, not delta-sized"
        np.testing.assert_allclose(pub_delta.payload["b"], 1.0)
        col.close()
    finally:
        server.stop()


def test_publish_crash_atomicity_no_partial_candidate(tmp_path):
    """Acceptance (crash-atomicity): attempt 1 publishes a multi-MB
    candidate and SIGKILLs itself while the driver's collector races the
    stream — whatever the collector surfaces must be WHOLE (digest-clean,
    value-exact), never partial; the relaunched attempt's clean publish
    arrives normally."""
    from tensorflowonspark_tpu.cluster import run_with_recovery
    from tensorflowonspark_tpu.continual import CONTINUAL_QUEUES
    from tests import cluster_funcs

    collected: dict = {}
    corrupt_before = _pubs_count("corrupt")

    def drive(cluster):
        col = PublicationCollector(cluster)
        for ver in collected:
            col.mark_seen("atom", ver)
        try:
            while True:
                for pub in col.poll():
                    collected[pub.version] = pub
                codes = cluster.backend.exitcodes()
                if codes and all(c is not None for c in codes.values()):
                    for pub in col.poll():
                        collected[pub.version] = pub
                    break
                time.sleep(0.05)
        finally:
            col.close()
        return set()

    run_with_recovery(cluster_funcs.fn_publish_crash_once,
                      {"model": "atom", "big_elems": 1 << 20}, 1,
                      max_restarts=2, queues=CONTINUAL_QUEUES,
                      driver_fn=drive)
    # attempt 2's clean candidate arrived intact
    assert "step-2" in collected, sorted(collected)
    np.testing.assert_array_equal(collected["step-2"].payload["w"],
                                  np.full((8,), 2.0, np.float64))
    # whatever else surfaced is whole-or-nothing — the kill raced the
    # driver's get, so step-1 may be absent entirely, but never partial
    for ver, pub in collected.items():
        assert payload_digest(pub.payload) == pub.digest
    if "step-1" in collected:
        np.testing.assert_array_equal(
            collected["step-1"].payload["w"],
            np.full((1 << 20,), 1.0, np.float64))
    assert _pubs_count("corrupt") == corrupt_before, \
        "a torn stream must surface as a dead connection, not corruption"


# ------------------------------------------------------ retention units


def test_registry_retention_evicts_payload_keeps_lineage():
    reg = ModelRegistry(keep_versions=1)
    with pytest.raises(ValueError, match="keep_versions"):
        ModelRegistry(keep_versions=-1)
    reg.register("m", "v1", _builder, serve_args={"salt": 0})
    reg.register("m", "v2", base=_builder,
                 adapter={"w": np.ones((2,), np.float32)})
    reg.register("m", "v3", _builder)
    for v in ("v1", "v2", "v3"):
        reg.record_eval("m", v, {"ok": 1}, passed=True)
    reg.mark("m", "v1", "retired")
    assert not reg.version("m", "v1").evicted      # 1 dead ≤ keep_versions
    reg.mark("m", "v2", "rolled_back")
    e1, e2 = reg.version("m", "v1"), reg.version("m", "v2")
    assert e1.evicted and not e2.evicted, "oldest dead version evicts"
    # payloads dropped, lineage kept
    assert e1.builder is None and e1.state == "retired"
    d = e1.describe()
    assert d["evicted"] and d["state"] == "retired" and d["kind"] == "full"
    assert reg.version("m", "v2").describe()["kind"] == "adapter"
    # an evicted version can never serve or promote again
    assert not reg.promotable("m", "v1")
    with pytest.raises(RolloutError, match="keep_versions"):
        e1.serve_args()
    with pytest.raises(RolloutError, match="keep_versions"):
        e1.swap_payload()
    # live versions untouched
    assert reg.promotable("m", "v3")
    assert reg.version("m", "v3").swap_payload()["builder"] is _builder


def test_retention_journal_replay_and_adopt(tmp_path):
    """Evictions journal (``registry_evict``) and survive both replay
    paths: a live-bound registry's records and the bind-time snapshot of
    a pre-bind eviction; ``adopt`` re-evicts on the resumed driver."""
    path = str(tmp_path / "cp.jsonl")
    j = ControlPlaneJournal(path)
    reg = ModelRegistry(keep_versions=0)
    reg.bind_journal(j)
    reg.register("m", "v1", _builder)
    reg.register("m", "v2", _builder)
    for v in ("v1", "v2"):
        reg.record_eval("m", v, {}, passed=True)
    reg.mark("m", "v1", "retired")                # keep 0 → evict now
    assert reg.version("m", "v1").evicted
    j.close()
    st = ControlPlaneJournal.replay(path)
    assert st.registry[("m", "v1")]["evicted"]
    assert not st.registry[("m", "v2")]["evicted"]

    # the resumed driver re-registers builders then adopts: the evicted
    # version must come back evicted (its payload is gone for good)
    reg2 = ModelRegistry()
    reg2.register("m", "v1", _builder)
    reg2.register("m", "v2", _builder)
    reg2.adopt(st)
    assert reg2.version("m", "v1").evicted
    with pytest.raises(RolloutError, match="evicted"):
        reg2.version("m", "v1").serve_args()
    assert reg2.promotable("m", "v2")

    # bind-time snapshot: an eviction that happened BEFORE the journal
    # existed is written into the snapshot
    path2 = str(tmp_path / "cp2.jsonl")
    reg3 = ModelRegistry(keep_versions=0)
    reg3.register("m", "v1", _builder)
    reg3.record_eval("m", "v1", {}, passed=True)
    reg3.mark("m", "v1", "retired")
    j2 = ControlPlaneJournal(path2)
    reg3.bind_journal(j2)
    j2.close()
    assert ControlPlaneJournal.replay(path2).registry[("m", "v1")]["evicted"]


# ------------------------------------------------- delta-only swap units


def test_adapter_swap_ships_delta_only_without_peer_clone():
    """Satellite: the hot-swap control message for an ADAPTER version
    carries the delta and NO peer hint — even when a peer already serves
    the version — so the worker re-applies the delta over its cached
    pristine base instead of cloning full params; a full version with a
    serving peer still gets the peer clone."""
    world = _ModelWorld(3)
    reg = ModelRegistry()
    reg.register("m", "v1", _builder, serve_args={"salt": 0})
    reg.register("m", "v2", base=_builder,
                 adapter={"w": np.ones((2,), np.float32)},
                 serve_args={"salt": 9})
    reg.register("m", "v3", _builder, serve_args={"salt": 5})
    for v in ("v2", "v3"):
        reg.record_eval("m", v, {}, passed=True)
    s = _scheduler(world, model=("m", "v1")).start()
    tier = _tier(world, s, registry=reg)
    try:
        tier.swap_replica_model(1, "m", "v2")
        tier.swap_replica_model(2, "m", "v2")  # peer 1 serves v2 already
        msgs = [i for _, i in world.control if i.get("op") == "model"]
        assert len(msgs) == 2
        for msg in msgs:
            assert msg["peer"] is None, \
                "adapter swaps must not clone full params from a peer"
            assert set(msg["adapter"]) == {"w"}
            assert msg.get("builder") is None
            # the wire payload is delta-sized, not base-sized
            assert len(pickle.dumps(msg["adapter"])) < 1024
        # contrast: a FULL version with a serving peer names the peer
        world.control.clear()
        tier.swap_replica_model(1, "m", "v3")
        tier.swap_replica_model(2, "m", "v3")
        full_msgs = [i for _, i in world.control if i.get("op") == "model"]
        assert full_msgs[0]["peer"] is None          # nobody serves v3 yet
        assert full_msgs[1]["peer"] is not None, \
            "full swaps should keep the peer-clone fast path"
        # the swapped gangs actually serve the new versions' outputs
        s.set_traffic_split("m", {"v3": 100})
        p = np.asarray([3, 4], np.int32)
        toks, err = _collect(s.submit(p, 3, model="m"))
        assert err is None and toks == _fake_tokens(p, 3, 5)
    finally:
        s.stop()


def test_resolve_version_params_reuses_cached_pristine_base():
    """Worker side of delta-only: two different deltas over one base
    build the base ONCE and each apply over the PRISTINE tree (delta2's
    params show no trace of delta1); a builder-visible serve_args knob
    invalidates the cache, serve_-prefixed knobs don't."""
    from tensorflowonspark_tpu.serving.replica import resolve_version_params

    calls = {"n": 0}

    def counting_base(args):
        calls["n"] += 1
        return None, {"w": np.zeros((4,), np.float32)}

    cache: dict = {}
    args = {"batch_size": 1}
    p1, _ = resolve_version_params(
        args, {"base_builder": counting_base,
               "adapter": {"w": np.full((4,), 1.0, np.float32)}},
        base_cache=cache)
    assert calls["n"] == 1
    np.testing.assert_allclose(p1["w"], 1.0)
    p2, _ = resolve_version_params(
        args, {"base_builder": counting_base,
               "adapter": {"w": np.full((4,), 5.0, np.float32)}},
        base_cache=cache)
    assert calls["n"] == 1, "second delta must reuse the cached base"
    np.testing.assert_allclose(p2["w"], 5.0)   # delta2 over PRISTINE base
    np.testing.assert_allclose(p1["w"], 1.0)   # earlier result untouched
    # serve_-prefixed overlay keys keep the cache valid
    p3, _ = resolve_version_params(
        args, {"base_builder": counting_base, "adapter": {},
               "serve_args": {"serve_step_delay": 0.0}},
        base_cache=cache)
    assert calls["n"] == 1
    np.testing.assert_allclose(p3["w"], 0.0)
    # a builder-visible knob (e.g. seed) rebuilds the base
    resolve_version_params(
        args, {"base_builder": counting_base, "adapter": {},
               "serve_args": {"seed": 3}}, base_cache=cache)
    assert calls["n"] == 2


# -------------------------------------------------------- pipeline units


class _FakeGridSearch:
    """Offline-gate stand-in: the real GridSearch boots a batch cluster;
    these units pin the pipeline's WIRING (trial params carry the
    candidate, the verdict lands in ``record_eval``) over canned
    results keyed off the candidate's ``quality`` serve arg.  The real
    batch-plane path is bench_continual's job."""

    instances: list = []

    def __init__(self, manifest, output_dir, predict_fn, param_grid, **kw):
        self.manifest = manifest
        self.output_dir = output_dir
        self.param_grid = param_grid
        self.ran = None
        _FakeGridSearch.instances.append(self)

    def run(self, num_workers):
        self.ran = num_workers
        return self

    def trial_results(self, trial_id, decode=False):
        assert trial_id == "t0"
        cand = self.param_grid[0]["continual_candidate"]
        return [float(cand["serve_args"].get("quality", 1.0))] * 4


def _eval_spec(tmp_path):
    return OfflineEval(
        manifest="unused-manifest", output_dir=str(tmp_path / "eval"),
        predict_fn=lambda model, records, tp: records,
        scorer=lambda rs: ({"quality": float(np.mean(rs)), "n": len(rs)},
                           float(np.mean(rs)) >= 0.5),
        num_workers=1)


def _adapter_pub(version, step, *, quality=1.0, salt=9, model="m"):
    payload = {"w": np.full((2,), 0.25 * step, np.float32)}
    return Publication(
        model=model, version=version, flavor="adapter", step=step,
        payload=payload, serve_args={"salt": salt, "quality": quality},
        metadata={"run": "r1"}, digest=payload_digest(payload), src=0,
        seq=step)


def _pipeline_world(tmp_path, monkeypatch, keep_versions=None):
    monkeypatch.setattr("tensorflowonspark_tpu.batch.gridsearch.GridSearch",
                        _FakeGridSearch)
    _FakeGridSearch.instances = []
    world = _ModelWorld(2)
    journal = ControlPlaneJournal(str(tmp_path / "cp.jsonl"))
    reg = ModelRegistry(keep_versions=keep_versions)
    reg.bind_journal(journal)
    reg.register("m", "v1", _builder, serve_args={"salt": 0})
    reg.record_eval("m", "v1", {}, passed=True)
    s = _scheduler(world, model=("m", "v1"), journal=journal).start()
    tier = _tier(world, s, registry=reg)
    return world, reg, s, tier


def _bg_load(s, stop):
    def load():
        k = 0
        while not stop.is_set():
            k += 1
            try:
                _collect(s.submit(np.asarray([k % 11 + 1], np.int32), 3,
                                  model="m"), timeout=5)
            except Exception:
                return
            time.sleep(0.01)

    t = threading.Thread(target=load, daemon=True)
    t.start()
    return t


def _journal_kinds(tmp_path):
    with open(str(tmp_path / "cp.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


_POLICY = RolloutPolicy(steps=(50, 100), bake_secs=0.4, min_samples=3,
                        max_e2e_ratio=None)


def test_pipeline_promotes_healthy_candidate_and_journals(
        tmp_path, monkeypatch):
    world, reg, s, tier = _pipeline_world(tmp_path, monkeypatch)
    pipe = ContinualPipeline(tier, "m", base_builder=_builder,
                             eval_spec=_eval_spec(tmp_path),
                             policy=_POLICY)
    promoted_before = _versions_count("promoted")
    stop = threading.Event()
    t = _bg_load(s, stop)
    try:
        pub = _adapter_pub("step-2", 2)
        assert pipe.process(pub) == "promoted"
    finally:
        stop.set()
        t.join(5)
        s.stop()
    assert reg.version("m", "step-2").state == "serving"
    assert reg.version("m", "v1").state == "retired"
    assert s.model_versions("m") == {"step-2": [0, 1]}
    assert _versions_count("promoted") == promoted_before + 1
    # the offline gate ran the candidate's trial over the eval manifest
    [gs] = _FakeGridSearch.instances
    assert gs.manifest == "unused-manifest" and gs.ran == 1
    cand = gs.param_grid[0]["continual_candidate"]
    assert cand["version"] == "step-2" and cand["flavor"] == "adapter"
    assert reg.version("m", "step-2").eval_metrics["quality"] == 1.0
    # durable lifecycle: candidate → offline_eval → rollout → done
    recs = _journal_kinds(tmp_path)
    cand_recs = [r for r in recs if r["kind"] == "continual_candidate"]
    assert [r["version"] for r in cand_recs] == ["step-2"]
    assert cand_recs[0]["digest"] == pub.digest
    stages = [r["stage"] for r in recs if r["kind"] == "continual_stage"]
    assert stages == ["offline_eval", "rollout"]
    [done] = [r for r in recs if r["kind"] == "continual_done"]
    assert done["outcome"] == "promoted" and done["version"] == "step-2"
    # the payload store round-trips digest-exact
    back = pipe.load_publication("step-2")
    assert back is not None and back.digest == pub.digest
    np.testing.assert_array_equal(back.payload["w"], pub.payload["w"])
    assert back.serve_args == pub.serve_args
    # duplicates and foreign models are dropped, not re-run
    assert pipe.process(_adapter_pub("step-2", 2)) is None
    assert pipe.process(_adapter_pub("x", 9, model="other")) is None


def test_pipeline_rejects_bad_candidate_offline_never_canaries(
        tmp_path, monkeypatch):
    """Acceptance: a data-quality regression is caught at the OFFLINE
    gate — zero canary traffic, zero swap messages, incumbent untouched."""
    world, reg, s, tier = _pipeline_world(tmp_path, monkeypatch)
    pipe = ContinualPipeline(tier, "m", base_builder=_builder,
                             eval_spec=_eval_spec(tmp_path),
                             policy=_POLICY)
    rejected_before = _versions_count("rejected_offline")
    try:
        out = pipe.process(_adapter_pub("step-3", 3, quality=0.0))
        assert out == "rejected_offline"
    finally:
        s.stop()
    assert _versions_count("rejected_offline") == rejected_before + 1
    entry = reg.version("m", "step-3")
    assert entry.eval_passed is False and not reg.promotable("m", "step-3")
    assert [i for _, i in world.control if i.get("op") == "model"] == [], \
        "a rejected candidate must never touch the serving fleet"
    assert s.model_versions("m") == {"v1": [0, 1]}
    [done] = [r for r in _journal_kinds(tmp_path)
              if r["kind"] == "continual_done"]
    assert done["outcome"] == "rejected_offline"
    # without an eval harness, an unscored candidate is rejected too —
    # never silently promoted
    pipe2 = ContinualPipeline(tier, "m", base_builder=_builder,
                              eval_spec=None, policy=_POLICY)
    assert pipe2.process(_adapter_pub("step-4", 4)) == "rejected_offline"


def test_pipeline_rolls_back_runtime_regression(tmp_path, monkeypatch):
    """Acceptance: a candidate that passes offline (the gate can't see
    runtime behavior) but errors live is auto-rolled back by the canary
    gate; the incumbent keeps serving."""
    world, reg, s, tier = _pipeline_world(tmp_path, monkeypatch)
    pipe = ContinualPipeline(
        tier, "m", base_builder=_builder, eval_spec=_eval_spec(tmp_path),
        policy=RolloutPolicy(steps=(50, 100), bake_secs=0.5, min_samples=1,
                             max_error_rate=0.2, max_e2e_ratio=None))
    rolled_before = _versions_count("rolled_back")
    stop = threading.Event()
    t = _bg_load(s, stop)
    try:
        pub = _adapter_pub("step-5", 5)
        pub.serve_args["fail"] = True        # live-only regression
        pub.digest = payload_digest(pub.payload)
        assert pipe.process(pub) == "rolled_back"
    finally:
        stop.set()
        t.join(5)
        s.stop()
    assert _versions_count("rolled_back") == rolled_before + 1
    assert reg.version("m", "step-5").state == "rolled_back"
    assert s.model_versions("m") == {"v1": [0, 1]}
    p = np.asarray([8], np.int32)
    [done] = [r for r in _journal_kinds(tmp_path)
              if r["kind"] == "continual_done"]
    assert done["outcome"] == "rolled_back"


def test_resume_finalizes_concluded_rollout_without_retraffic(
        tmp_path, monkeypatch):
    """No-double-promotion: the driver died AFTER the rollout concluded
    but BEFORE ``continual_done`` hit the journal — resume just
    finalizes the outcome; zero new swap/traffic actions."""
    world, reg, s, tier = _pipeline_world(tmp_path, monkeypatch)
    # the pre-kill world: step-2 already promoted (serving), v1 retired
    reg.register("m", "step-2", base=_builder,
                 adapter={"w": np.ones((2,), np.float32)},
                 serve_args={"salt": 9})
    reg.record_eval("m", "step-2", {"quality": 1.0}, passed=True)
    reg.mark("m", "step-2", "serving")
    reg.mark("m", "v1", "retired")
    state = JournalState.from_records([
        dict(kind="continual_candidate", model="m", version="step-2",
             flavor="adapter", step=2, digest="d", src=0),
        dict(kind="continual_stage", model="m", version="step-2",
             stage="rollout"),
        dict(kind="rollout_started", model="m", version="step-2",
             incumbent="v1", steps=[50, 100]),
        dict(kind="rollout_step", model="m", version="step-2", percent=50),
        dict(kind="rollout_step_done", model="m", version="step-2",
             percent=50),
        dict(kind="rollout_done", model="m", version="step-2",
             outcome="promoted"),
    ])
    assert ("m", "step-2") in state.open_candidates()
    pipe = ContinualPipeline(tier, "m", base_builder=_builder,
                             policy=_POLICY)
    promoted_before = _versions_count("promoted")
    try:
        assert pipe.resume(state) == {("m", "step-2"): "promoted"}
    finally:
        s.stop()
    assert _versions_count("promoted") == promoted_before + 1
    assert [i for _, i in world.control if i.get("op") == "model"] == [], \
        "finalizing a concluded rollout must not re-shift traffic"
    assert reg.version("m", "step-2").state == "serving"
    [done] = [r for r in _journal_kinds(tmp_path)
              if r["kind"] == "continual_done"]
    assert done["outcome"] == "promoted"
    # a second resume finds nothing open (continual_done closed it):
    # replaying the REAL journal now folds the done record in
    recs = [dict(kind="continual_candidate", model="m", version="step-2",
                 flavor="adapter", step=2, digest="d", src=0),
            dict(kind="continual_done", model="m", version="step-2",
                 outcome="promoted")]
    assert JournalState.from_records(recs).open_candidates() == {}


def test_resume_rehydrates_stored_candidate_and_skips_lost(
        tmp_path, monkeypatch):
    """A candidate journaled before the kill but absent from the rebuilt
    registry re-registers from the payload store and finishes its loop;
    one whose store never made it is skipped (awaiting re-publication),
    not promoted blind."""
    world, reg, s, tier = _pipeline_world(tmp_path, monkeypatch)
    pipe = ContinualPipeline(tier, "m", base_builder=_builder,
                             eval_spec=_eval_spec(tmp_path),
                             policy=_POLICY)
    pub = _adapter_pub("step-7", 7)
    pipe._store(pub)                       # the pre-kill driver stored it
    state = JournalState.from_records([
        dict(kind="continual_candidate", model="m", version="step-7",
             flavor="adapter", step=7, digest=pub.digest, src=0),
        dict(kind="continual_stage", model="m", version="step-7",
             stage="offline_eval"),
        dict(kind="continual_candidate", model="m", version="step-8",
             flavor="adapter", step=8, digest="lost", src=0),
    ])
    stop = threading.Event()
    t = _bg_load(s, stop)
    try:
        results = pipe.resume(state)
    finally:
        stop.set()
        t.join(5)
        s.stop()
    assert results == {("m", "step-7"): "promoted"}
    assert reg.version("m", "step-7").state == "serving"
    assert "step-8" not in reg.versions("m"), \
        "a payload-less candidate must wait for re-publication"


def test_resume_restores_journaled_eval_verdict(tmp_path, monkeypatch):
    """A candidate killed mid-ROLLOUT re-hydrates from the store with its
    journaled offline verdict restored: the rebuilt registry's adopt()
    ran before the re-registration and had to skip the eval record, so
    the pipeline must re-apply it — otherwise the rollout gate
    (require_eval) refuses its own already-vetted candidate."""
    world, reg, s, tier = _pipeline_world(tmp_path, monkeypatch)
    pipe = ContinualPipeline(tier, "m", base_builder=_builder,
                             eval_spec=_eval_spec(tmp_path),
                             policy=_POLICY)
    pub = _adapter_pub("step-9", 9)
    pipe._store(pub)
    state = JournalState.from_records([
        dict(kind="continual_candidate", model="m", version="step-9",
             flavor="adapter", step=9, digest=pub.digest, src=0),
        dict(kind="registry_eval", model="m", version="step-9",
             passed=True, metrics={"quality": 1.0}),
        dict(kind="continual_stage", model="m", version="step-9",
             stage="rollout"),
    ])
    stop = threading.Event()
    t = _bg_load(s, stop)
    try:
        results = pipe.resume(state)
    finally:
        stop.set()
        t.join(5)
        s.stop()
    assert results == {("m", "step-9"): "promoted"}
    entry = reg.version("m", "step-9")
    assert entry.eval_passed is True
    assert entry.eval_metrics == {"quality": 1.0}
