"""SummaryWriter: hand-encoded TB event files must parse with TF's reader."""

import glob
import os

import pytest

from tensorflowonspark_tpu.observability import SummaryWriter


def test_scalars_roundtrip_through_tf_event_parser(tmp_path):
    """The oracle is TensorFlow's own Event proto parser: if TF decodes our
    records, TensorBoard renders them."""
    event_pb2 = pytest.importorskip("tensorflow.core.util.event_pb2")

    logdir = str(tmp_path / "tb")
    with SummaryWriter(logdir) as w:
        w.scalar("train/loss", 0.5, step=1)
        w.scalars({"train/loss": 0.25, "train/acc": 0.9}, step=2)

    files = glob.glob(os.path.join(logdir, "events.out.tfevents.*"))
    assert len(files) == 1

    from tensorflowonspark_tpu.tfrecord import read_records

    events = []
    for rec in read_records(files[0], verify=True):
        ev = event_pb2.Event()
        ev.ParseFromString(rec)
        events.append(ev)

    assert events[0].file_version == "brain.Event:2"
    assert events[0].wall_time > 0

    scalars = {}
    for ev in events[1:]:
        for val in ev.summary.value:
            scalars[(ev.step, val.tag)] = val.simple_value
    assert scalars[(1, "train/loss")] == 0.5
    assert scalars[(2, "train/loss")] == 0.25
    assert abs(scalars[(2, "train/acc")] - 0.9) < 1e-6


def test_estimator_writes_training_curves(tmp_path):
    """Estimator emits train/ and eval/ scalars under model_dir/tensorboard."""
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tensorflowonspark_tpu.estimator import Estimator

    def init_fn():
        return {"w": jnp.zeros((4, 1))}

    def loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)

    x = np.ones((8, 4), np.float32)
    y = np.ones((8, 1), np.float32)

    def input_fn():
        for _ in range(6):
            yield {"x": x, "y": y}

    model_dir = str(tmp_path / "m")
    with Estimator(init_fn, loss_fn, optax.sgd(0.1), model_dir,
                   log_every_steps=2) as est:
        est.train(input_fn, max_steps=6)
        est.evaluate(input_fn, steps=2)

    files = glob.glob(os.path.join(model_dir, "tensorboard",
                                   "events.out.tfevents.*"))
    assert len(files) == 1

    event_pb2 = pytest.importorskip("tensorflow.core.util.event_pb2")
    from tensorflowonspark_tpu.tfrecord import read_records

    tags = set()
    for rec in read_records(files[0]):
        ev = event_pb2.Event()
        ev.ParseFromString(rec)
        for val in ev.summary.value:
            tags.add(val.tag)
    assert "train/loss" in tags
    assert "eval/loss" in tags


def test_scalars_without_tf_installed_write_and_reread(tmp_path):
    """Self-contained round trip (no TF): records frame and re-read."""
    logdir = str(tmp_path / "tb")
    with SummaryWriter(logdir, filename_suffix=".v2") as w:
        for s in range(5):
            w.scalar("loss", 1.0 / (s + 1), step=s)
        w.flush()
    files = glob.glob(os.path.join(logdir, "events.out.tfevents.*.v2"))
    assert len(files) == 1

    from tensorflowonspark_tpu.tfrecord import read_records

    recs = list(read_records(files[0], verify=True))
    assert len(recs) == 6  # file_version + 5 scalar events
