"""Distributed online serving tier (``tensorflowonspark_tpu/serving``).

Two layers, mirroring the health tests' split:

- **unit** — ``ReplicaScheduler`` + ``ServeFrontend``/``ServeClient``
  against deterministic in-process fake replicas, so every policy branch
  (shed, deadline, least-outstanding routing, requeue-once failover,
  typed errors, stream dedup across failover) is exercised fast.
- **integration** — real 2-replica clusters (``LocalProcessBackend``,
  spawned worker processes hosting ``ContinuousBatcher``), locked
  greedy-exact against solo ``greedy_generate`` oracles, including a
  chaos SIGKILL of a replica mid-stream (fast variant tier-1; the soak
  is ``-m slow``).
"""

import queue as _queue
import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu.serving import (DeadlineExceeded, ReplicaFailed,
                                           ReplicaScheduler, RequestRejected,
                                           ServeClient, ServeFrontend)

# --------------------------------------------------------------- fakes


class _FakeBackend:
    def __init__(self, n):
        self.codes = {i: None for i in range(n)}

    def exitcodes(self):
        return dict(self.codes)

    def failed(self):
        return [i for i, c in self.codes.items() if c not in (0, None)]


def _fake_tokens(prompt, n):
    """The fake replica's deterministic 'decode': a pure function of the
    request, like the real batcher's contract — so a failover replay
    regenerates the identical sequence."""
    base = int(np.sum(np.asarray(prompt, np.int64)))
    return [(base + 7 * k) % 101 for k in range(n)]


class _FakeWorld:
    """N serial fake replicas speaking the serve queue protocol over
    in-process queues; ``kill(i)`` emulates a SIGKILL (exit code -9,
    connections start raising)."""

    def __init__(self, n, token_delay=0.0):
        self.backend = _FakeBackend(n)
        self.cluster_info = [
            {"executor_id": i, "job_name": "worker",
             "addr": ("127.0.0.1", 0), "authkey": b"x"} for i in range(n)]
        self.cluster_meta = {"queue_shm": False}
        self.working_dir = None
        self.token_delay = token_delay
        self.inq = {i: _queue.Queue() for i in range(n)}
        self.outq = {i: _queue.Queue() for i in range(n)}
        self._dead: set[int] = set()
        self.threads = [threading.Thread(target=self._run, args=(i,),
                                         daemon=True) for i in range(n)]
        for t in self.threads:
            t.start()

    def _run(self, i):
        while i not in self._dead:
            try:
                item = self.inq[i].get(timeout=0.02)
            except _queue.Empty:
                continue
            rid, p = item["rid"], item["prompt"]
            for k, tok in enumerate(_fake_tokens(p, item["max_new_tokens"])):
                if i in self._dead:
                    return               # died mid-stream
                if self.token_delay:
                    time.sleep(self.token_delay)
                self.outq[i].put({"rid": rid, "event": "tok",
                                  "tokens": [tok], "load": 1})
            self.outq[i].put({"rid": rid, "event": "done", "load": 0})

    def kill(self, i):
        self._dead.add(i)
        self.backend.codes[i] = -9

    def client(self, info):
        eid, world = info["executor_id"], self

        class _C:
            def put(self, qname, item, timeout=None):
                if eid in world._dead:
                    raise ConnectionError("replica dead")
                world.inq[eid].put(item)

            def get(self, qname, timeout=0.5):
                if eid in world._dead:
                    raise ConnectionError("replica dead")
                try:
                    return world.outq[eid].get(timeout=timeout)
                except _queue.Empty:
                    raise TimeoutError

            def close(self):
                pass

        return _C()


def _scheduler(world, **kw):
    kw.setdefault("slots_per_replica", 2)
    kw.setdefault("poll_interval", 0.05)
    return ReplicaScheduler(world, client_factory=world.client, **kw)


def _collect(req, timeout=10.0):
    """Drain one request's event stream; returns (tokens, error_or_None)."""
    toks, deadline = [], time.monotonic() + timeout
    while True:
        ev = req.events.get(timeout=max(0.01, deadline - time.monotonic()))
        if ev[0] == "tok":
            toks.extend(ev[1])
        elif ev[0] == "done":
            return toks, None
        else:
            return toks, ev


# ------------------------------------------------------- scheduler units

def test_scheduler_routes_and_completes():
    world = _FakeWorld(2)
    s = _scheduler(world).start()
    try:
        prompts = [np.arange(1, 4 + i, dtype=np.int32) for i in range(6)]
        reqs = [s.submit(p, 5) for p in prompts]
        for req, p in zip(reqs, prompts):
            toks, err = _collect(req)
            assert err is None and toks == _fake_tokens(p, 5)
        m = s.metrics()
        assert m["accepted"] == m["completed"] == 6
        assert m["shed"] == m["failed"] == m["requeued"] == 0
        assert m["ttft"]["count"] == 6 and m["e2e"]["p99_secs"] is not None
        # least-outstanding routing spread work over both replicas
        assert all(r["served"] > 0 for r in m["replicas"].values())
    finally:
        s.stop()


def test_scheduler_sheds_at_bounded_depth():
    world = _FakeWorld(1, token_delay=0.2)   # slow: backlog builds
    s = _scheduler(world, slots_per_replica=1, overcommit=1,
                   max_queue_depth=2).start()
    try:
        a = s.submit(np.asarray([1], np.int32), 3)
        b = s.submit(np.asarray([2], np.int32), 3)
        with pytest.raises(RequestRejected) as ei:
            s.submit(np.asarray([3], np.int32), 3)
        assert ei.value.reason == "queue_full"
        assert s.metrics()["shed"] == 1
        for req in (a, b):                   # accepted work still completes
            _, err = _collect(req)
            assert err is None
    finally:
        s.stop()


def test_scheduler_expires_queued_request_past_deadline():
    world = _FakeWorld(1, token_delay=0.2)
    s = _scheduler(world, slots_per_replica=1, overcommit=1).start()
    try:
        blocker = s.submit(np.asarray([1], np.int32), 4)  # owns the slot
        late = s.submit(np.asarray([2], np.int32), 4, timeout=0.05)
        toks, err = _collect(late)
        assert err is not None and err[1] == "deadline" and toks == []
        assert s.metrics()["expired"] == 1
        _, err = _collect(blocker)
        assert err is None
    finally:
        s.stop()


def test_replica_death_requeues_once_with_exact_stream():
    """Kill the replica serving a request mid-stream: the request replays
    on the survivor and the client-visible stream is the exact oracle
    sequence with no duplicates or gaps (skip-dedup across failover)."""
    world = _FakeWorld(2, token_delay=0.05)
    s = _scheduler(world, slots_per_replica=1, overcommit=1).start()
    try:
        p = np.asarray([3, 5], np.int32)
        req = s.submit(p, 8)
        # wait until some tokens flowed, then kill the serving replica
        while not req.tokens:
            time.sleep(0.01)
        victim = req.replica
        world.kill(victim)
        toks, err = _collect(req, timeout=15)
        assert err is None
        assert toks == _fake_tokens(p, 8), "failover stream not exact"
        m = s.metrics()
        assert m["requeued"] == 1 and m["completed"] == 1
        assert not m["replicas"][victim]["alive"]
        assert s.dead_replicas() == {victim}
    finally:
        s.stop()


def test_trace_id_survives_requeue_failover(tmp_path):
    """End-to-end tracing across the failover path: the trace id stamped
    at admission survives the requeue-once hop to the surviving replica,
    and ``tracing.stitch_trace`` reconstructs the full
    admission → route → first-token → requeue → re-route → done timeline
    (with the untraced ``replica_dead`` folded in as context)."""
    from tensorflowonspark_tpu import tracing
    from tensorflowonspark_tpu.observability import EventLog

    world = _FakeWorld(2, token_delay=0.05)
    log = EventLog(str(tmp_path / "serving_events.jsonl"))
    s = _scheduler(world, slots_per_replica=1, overcommit=1,
                   event_log=log).start()
    try:
        p = np.asarray([3, 5], np.int32)
        trace = tracing.new_trace_id()
        req = s.submit(p, 8, trace=trace)
        assert req.trace == trace
        assert req.message()["trace"] == trace   # rides the wire message
        while not req.tokens:
            time.sleep(0.01)
        victim = req.replica
        world.kill(victim)
        toks, err = _collect(req, timeout=15)
        assert err is None and toks == _fake_tokens(p, 8)
    finally:
        s.stop()
        log.close()

    timeline = tracing.stitch_trace(str(tmp_path), trace)
    kinds = [r["kind"] for r in timeline if not r.get("_context")]
    assert kinds[0] == "request_admitted" and kinds[-1] == "request_done"
    routed = [r for r in timeline if r["kind"] == "request_routed"]
    assert len(routed) == 2, "expected a route before and after failover"
    assert routed[0]["replica"] == victim != routed[1]["replica"]
    assert [r["attempt"] for r in routed] == [1, 2]
    (requeued,) = [r for r in timeline if r["kind"] == "request_requeued"]
    assert requeued["from_replica"] == victim and requeued["trace"] == trace
    assert all(r["trace"] == trace for r in timeline
               if not r.get("_context"))
    # the replica kill that explains the hop appears as a context row
    assert any(r["kind"] == "replica_dead" and r.get("_context")
               for r in timeline)
    # and the CLI-facing formatter renders it
    text = tracing.format_timeline(timeline)
    assert "request_requeued" in text and "[context]" in text


def test_scheduler_registry_series_update(tmp_path):
    """The scheduler's registry instruments: outcome counters tick and
    the collect hook mirrors queue depth / per-replica gauges into a
    snapshot."""
    from tensorflowonspark_tpu import metrics as tpu_metrics

    world = _FakeWorld(2)
    s = _scheduler(world).start()
    reg = tpu_metrics.get_registry()
    c = reg.counter("tfos_serving_requests_total", labelnames=("outcome",))
    accepted0 = c.value(outcome="accepted")
    completed0 = c.value(outcome="completed")
    try:
        req = s.submit(np.asarray([1, 2], np.int32), 4)
        _, err = _collect(req)
        assert err is None
        assert c.value(outcome="accepted") == accepted0 + 1
        assert c.value(outcome="completed") == completed0 + 1
        snap = reg.snapshot()    # runs the collect hook
        outst = {tuple(sorted(lbl.items())): v for lbl, v in
                 snap["tfos_serving_replica_outstanding_count"]["samples"]}
        assert (("replica", "0"),) in outst and (("replica", "1"),) in outst
        assert snap["tfos_serving_replicas_alive_count"]["samples"] \
            == [[{}, 2.0]]
        ((_, ttft),) = snap["tfos_serving_ttft_seconds"]["samples"]
        assert ttft["count"] >= 1
        # a dead replica's series are removed, not frozen at last value
        world.kill(1)
        deadline = time.monotonic() + 5
        while 1 not in s.dead_replicas() and time.monotonic() < deadline:
            time.sleep(0.02)
        snap = reg.snapshot()
        labels = [lbl for lbl, _ in
                  snap["tfos_serving_replica_outstanding_count"]["samples"]]
        assert {"replica": "0"} in labels and {"replica": "1"} not in labels
        assert snap["tfos_serving_replicas_alive_count"]["samples"] \
            == [[{}, 1.0]]
    finally:
        s.stop()


def test_replica_death_beyond_requeue_limit_fails_typed():
    world = _FakeWorld(2, token_delay=0.05)
    s = _scheduler(world, slots_per_replica=1, overcommit=1,
                   requeue_limit=0).start()
    try:
        req = s.submit(np.asarray([4], np.int32), 8)
        while not req.tokens:
            time.sleep(0.01)
        world.kill(req.replica)
        _, err = _collect(req, timeout=15)
        assert err is not None and err[1] == "replica_failed"
        assert s.metrics()["failed"] == 1
    finally:
        s.stop()


def test_last_replica_death_fails_no_replica_and_rejects_submits():
    world = _FakeWorld(1, token_delay=0.05)
    s = _scheduler(world, slots_per_replica=1, overcommit=1).start()
    try:
        req = s.submit(np.asarray([5], np.int32), 8)
        while not req.tokens:
            time.sleep(0.01)
        world.kill(0)
        _, err = _collect(req, timeout=15)
        assert err is not None and err[1] == "no_replica"
        with pytest.raises(RequestRejected) as ei:
            s.submit(np.asarray([6], np.int32), 2)
        assert ei.value.reason == "no_replica"
    finally:
        s.stop()


def test_monitor_failure_subscription_marks_dead():
    """on_cluster_failure (the ClusterMonitor hook) retires the implicated
    replica even when its process looks alive (the hang shape)."""
    from tensorflowonspark_tpu.health import HANG, ClusterFailure

    world = _FakeWorld(2)
    s = _scheduler(world).start()
    try:
        s.on_cluster_failure(ClusterFailure(HANG, "wedged", (1,)))
        assert s.dead_replicas() == {1}
        # traffic keeps flowing on the survivor
        req = s.submit(np.asarray([9], np.int32), 3)
        toks, err = _collect(req)
        assert err is None and toks == _fake_tokens([9], 3)
    finally:
        s.stop()


def test_scheduler_stop_rejects_and_errors_leftovers():
    world = _FakeWorld(1, token_delay=0.3)
    s = _scheduler(world).start()
    req = s.submit(np.asarray([1, 2], np.int32), 5)
    s.stop()
    _, err = _collect(req)
    assert err is not None and err[1] == "shutdown"
    with pytest.raises(RequestRejected) as ei:
        s.submit(np.asarray([1], np.int32), 1)
    assert ei.value.reason == "shutdown"


# ------------------------------------------------- frontend/client units

def test_frontend_client_roundtrip_and_typed_shed():
    """The TCP edge over fake replicas: generate, generate_stream (delta
    concat == generate), stats, and a typed queue_full rejection."""
    world = _FakeWorld(2)
    s = _scheduler(world, max_queue_depth=64).start()
    fe = ServeFrontend(s, authkey=b"s" * 16)
    addr = fe.start()
    try:
        with ServeClient(addr, b"s" * 16) as c:
            assert c.ping()
            p = np.asarray([2, 3, 4], np.int32)
            got = c.generate(p, 6)
            assert got.tolist() == _fake_tokens(p, 6)
            deltas = list(c.generate_stream(p, 6))
            assert [t for d in deltas for t in d] == _fake_tokens(p, 6)
            stats = c.stats()
            assert stats["completed"] == 2
            assert stats["ttft"]["count"] == 2
        with pytest.raises(ConnectionError):
            ServeClient(addr, b"wrong-key-------")
        # shed: shrink the bound under the scheduler lock-free counters
        s.max_queue_depth = 0
        with ServeClient(addr, b"s" * 16) as c, \
                pytest.raises(RequestRejected) as ei:
            c.generate(p, 2)
        assert ei.value.reason == "queue_full"
    finally:
        fe.stop()
        s.stop()


def test_frontend_deadline_mid_request_is_typed():
    world = _FakeWorld(1, token_delay=0.15)
    s = _scheduler(world, slots_per_replica=1, overcommit=1).start()
    fe = ServeFrontend(s, authkey=b"s" * 16)
    addr = fe.start()
    try:
        with ServeClient(addr, b"s" * 16) as c, \
                pytest.raises(DeadlineExceeded):
            c.generate(np.asarray([1], np.int32), 50, timeout=0.3)
    finally:
        fe.stop()
        s.stop()


# ------------------------------------------------------ integration

def _oracle(prompt, n, seed=0):
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import greedy_generate
    from tests.cluster_funcs import serving_tiny_gpt_builder

    cfg, params = serving_tiny_gpt_builder({"seed": seed})
    out = greedy_generate(cfg, params,
                          jnp.asarray(prompt, jnp.int32)[None, :], n)
    return np.asarray(out)[0, len(prompt):].tolist()


def _requests(rng, n, vocab=83, tmin=3, tmax=9, bmin=4, bmax=12):
    return [(rng.integers(0, vocab, (int(rng.integers(tmin, tmax)),))
             .astype(np.int32), int(rng.integers(bmin, bmax)))
            for _ in range(n)]


def _run_serving(tmp_path, worker_env, num_replicas=2, **kw):
    from tests.cluster_funcs import serving_tiny_gpt_builder

    from tensorflowonspark_tpu.serving import ServingCluster

    kw.setdefault("max_batch", 2)
    kw.setdefault("reservation_timeout", 120)
    return ServingCluster.run(
        serving_tiny_gpt_builder, num_replicas,
        worker_env=worker_env, working_dir=str(tmp_path), **kw)


@pytest.mark.integration
def test_serving_cluster_end_to_end(tmp_path, worker_env):
    """Acceptance: N concurrent clients against 2 replicas under
    staggered admission — every request greedy-exact vs its solo oracle,
    both replicas served traffic, streaming deltas concat exactly."""
    serving = _run_serving(tmp_path, worker_env)
    try:
        rng = np.random.default_rng(0)
        reqs = _requests(rng, 12)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 4):   # 4-way stagger
                        p, n = reqs[i]
                        results[i] = c.generate(p, n).tolist()
                        time.sleep(0.01 * cid)
            except Exception as e:                        # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors, errors
        assert len(results) == len(reqs)
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"

        # streaming: delta concat equals the oracle too
        with serving.client() as c:
            p, n = reqs[0]
            deltas = list(c.generate_stream(p, n))
            assert [t for d in deltas for t in d] == _oracle(p, n)
            assert len(deltas) > 1, "no incremental streaming happened"
            stats = c.stats()
        assert stats["completed"] == len(reqs) + 1
        assert stats["shed"] == stats["failed"] == 0
        assert all(r["served"] > 0 for r in stats["replicas"].values()), \
            f"routing starved a replica: {stats['replicas']}"
        assert stats["e2e"]["p99_secs"] is not None
    finally:
        serving.shutdown(timeout=120)


@pytest.mark.integration
def test_serving_replica_kill_requeues_and_stays_exact(tmp_path, worker_env):
    """Chaos: SIGKILL replica 1 mid-decode (TFOS_CHAOS at_step trigger on
    the serving loop's report_step).  Every accepted request must still
    complete with oracle-exact tokens — in-flight work on the dead
    replica is re-queued to the survivor — and the death must be
    recorded (requeued>0 or the dead replica visible in metrics) with
    zero failed requests."""
    env = dict(worker_env, TFOS_CHAOS="kill node=1 at_step=4")
    serving = _run_serving(tmp_path, env)
    try:
        rng = np.random.default_rng(1)
        reqs = _requests(rng, 8, bmin=10, bmax=16)   # long enough to span
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 2):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=120).tolist()
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"
        m = serving.metrics()
        assert m["completed"] == len(reqs) and m["failed"] == 0, m
        assert serving.scheduler.dead_replicas() == {1}, \
            "chaos kill was not detected"
    finally:
        serving.shutdown(timeout=120)


@pytest.mark.integration
@pytest.mark.slow
def test_serving_kill_soak_under_sustained_load(tmp_path, worker_env):
    """Soak: sustained staggered traffic while a replica dies mid-run;
    every accepted request completes exactly, none lost."""
    env = dict(worker_env, TFOS_CHAOS="kill node=0 at_step=12")
    serving = _run_serving(tmp_path, env, max_batch=2)
    try:
        rng = np.random.default_rng(2)
        reqs = _requests(rng, 24, bmin=6, bmax=14)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 3):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=180).tolist()
                        time.sleep(0.05)
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"
        m = serving.metrics()
        assert m["completed"] == len(reqs) and m["failed"] == 0, m
        assert serving.scheduler.dead_replicas() == {0}
        events = [e["kind"] for e in _serving_events(tmp_path)]
        assert "replica_dead" in events
    finally:
        serving.shutdown(timeout=180)


def _serving_events(tmp_path):
    import os

    from tensorflowonspark_tpu.observability import EventLog

    path = os.path.join(str(tmp_path), "serving_events.jsonl")
    return EventLog.read(path) if os.path.exists(path) else []
