"""Distributed online serving tier (``tensorflowonspark_tpu/serving``).

Two layers, mirroring the health tests' split:

- **unit** — ``ReplicaScheduler`` + ``ServeFrontend``/``ServeClient``
  against deterministic in-process fake replicas, so every policy branch
  (shed, deadline, least-outstanding routing, requeue-once failover,
  typed errors, stream dedup across failover) is exercised fast.
- **integration** — real 2-replica clusters (``LocalProcessBackend``,
  spawned worker processes hosting ``ContinuousBatcher``), locked
  greedy-exact against solo ``greedy_generate`` oracles, including a
  chaos SIGKILL of a replica mid-stream (fast variant tier-1; the soak
  is ``-m slow``).
"""

import queue as _queue
import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu.serving import (DeadlineExceeded, ReplicaFailed,
                                           ReplicaScheduler, RequestRejected,
                                           ServeClient, ServeFrontend)

# --------------------------------------------------------------- fakes


class _FakeBackend:
    def __init__(self, n):
        self.codes = {i: None for i in range(n)}

    def exitcodes(self):
        return dict(self.codes)

    def failed(self):
        return [i for i, c in self.codes.items() if c not in (0, None)]


def _fake_tokens(prompt, n):
    """The fake replica's deterministic 'decode': a pure function of the
    request, like the real batcher's contract — so a failover replay
    regenerates the identical sequence."""
    base = int(np.sum(np.asarray(prompt, np.int64)))
    return [(base + 7 * k) % 101 for k in range(n)]


class _FakeWorld:
    """N serial fake replicas speaking the serve queue protocol over
    in-process queues; ``kill(i)`` emulates a SIGKILL (exit code -9,
    connections start raising)."""

    def __init__(self, n, token_delay=0.0):
        self.backend = _FakeBackend(n)
        self.cluster_info = [
            {"executor_id": i, "job_name": "worker",
             "addr": ("127.0.0.1", 0), "authkey": b"x"} for i in range(n)]
        self.cluster_meta = {"queue_shm": False}
        self.working_dir = None
        self.token_delay = token_delay
        self.inq = {i: _queue.Queue() for i in range(n)}
        self.outq = {i: _queue.Queue() for i in range(n)}
        self._dead: set[int] = set()
        self.threads = [threading.Thread(target=self._run, args=(i,),
                                         daemon=True) for i in range(n)]
        for t in self.threads:
            t.start()

    def _run(self, i):
        while i not in self._dead:
            try:
                item = self.inq[i].get(timeout=0.02)
            except _queue.Empty:
                continue
            rid, p = item["rid"], item["prompt"]
            for k, tok in enumerate(_fake_tokens(p, item["max_new_tokens"])):
                if i in self._dead:
                    return               # died mid-stream
                if self.token_delay:
                    time.sleep(self.token_delay)
                self.outq[i].put({"rid": rid, "event": "tok",
                                  "tokens": [tok], "load": 1})
            self.outq[i].put({"rid": rid, "event": "done", "load": 0})

    def kill(self, i):
        self._dead.add(i)
        self.backend.codes[i] = -9

    def add_replica(self):
        """Bring up one more fake replica (live scale-up); returns its
        info dict, shaped like a reservation."""
        i = len(self.cluster_info)
        info = {"executor_id": i, "job_name": "worker",
                "addr": ("127.0.0.1", 0), "authkey": b"x"}
        self.cluster_info.append(info)
        self.backend.codes[i] = None
        self.inq[i] = _queue.Queue()
        self.outq[i] = _queue.Queue()
        t = threading.Thread(target=self._run, args=(i,), daemon=True)
        self.threads.append(t)
        t.start()
        return info

    def exit_clean(self, i):
        """Emulate a clean worker exit (drained retire / preemption)."""
        self._dead.add(i)
        self.backend.codes[i] = 0

    def client(self, info):
        eid, world = info["executor_id"], self

        class _C:
            def put(self, qname, item, timeout=None):
                if eid in world._dead:
                    raise ConnectionError("replica dead")
                world.inq[eid].put(item)

            def get(self, qname, timeout=0.5):
                if eid in world._dead:
                    raise ConnectionError("replica dead")
                try:
                    return world.outq[eid].get(timeout=timeout)
                except _queue.Empty:
                    raise TimeoutError

            def close(self):
                pass

        return _C()


def _scheduler(world, **kw):
    kw.setdefault("slots_per_replica", 2)
    kw.setdefault("poll_interval", 0.05)
    return ReplicaScheduler(world, client_factory=world.client, **kw)


def _collect(req, timeout=10.0):
    """Drain one request's event stream; returns (tokens, error_or_None)."""
    toks, deadline = [], time.monotonic() + timeout
    while True:
        ev = req.events.get(timeout=max(0.01, deadline - time.monotonic()))
        if ev[0] == "tok":
            toks.extend(ev[1])
        elif ev[0] == "done":
            return toks, None
        else:
            return toks, ev


# ------------------------------------------------------- scheduler units

def test_scheduler_routes_and_completes():
    world = _FakeWorld(2)
    s = _scheduler(world).start()
    try:
        prompts = [np.arange(1, 4 + i, dtype=np.int32) for i in range(6)]
        reqs = [s.submit(p, 5) for p in prompts]
        for req, p in zip(reqs, prompts):
            toks, err = _collect(req)
            assert err is None and toks == _fake_tokens(p, 5)
        m = s.metrics()
        assert m["accepted"] == m["completed"] == 6
        assert m["shed"] == m["failed"] == m["requeued"] == 0
        assert m["ttft"]["count"] == 6 and m["e2e"]["p99_secs"] is not None
        # least-outstanding routing spread work over both replicas
        assert all(r["served"] > 0 for r in m["replicas"].values())
    finally:
        s.stop()


def test_routing_tie_breaks_on_kv_page_pressure():
    """Equal outstanding + equal reported load: the replica reporting
    MORE free KV pages wins the route (memory pressure tie-break); both
    primary keys still outrank it."""
    from types import SimpleNamespace

    world = _FakeWorld(2)
    s = _scheduler(world)            # policy unit: never started
    try:
        a, b = s.replicas[0], s.replicas[1]
        # replicas report page capacity on the response wire
        s._handle_response(a, {"rid": None, "event": "",
                               "load": 0, "free_pages": 2})
        s._handle_response(b, {"rid": None, "event": "",
                               "load": 0, "free_pages": 9})
        assert s.metrics()["replicas"][1]["free_pages"] == 9
        with s._lock:
            assert s._pick_replica() is b
        # fewer outstanding outranks page pressure...
        b.outstanding[99] = SimpleNamespace(finished=True)
        with s._lock:
            assert s._pick_replica() is a
        b.outstanding.clear()
        # ...and so does lower self-reported load
        a.reported_load, b.reported_load = 0, 3
        with s._lock:
            assert s._pick_replica() is a
    finally:
        s.stop()


def test_scheduler_sheds_at_bounded_depth():
    world = _FakeWorld(1, token_delay=0.2)   # slow: backlog builds
    s = _scheduler(world, slots_per_replica=1, overcommit=1,
                   max_queue_depth=2).start()
    try:
        a = s.submit(np.asarray([1], np.int32), 3)
        b = s.submit(np.asarray([2], np.int32), 3)
        with pytest.raises(RequestRejected) as ei:
            s.submit(np.asarray([3], np.int32), 3)
        assert ei.value.reason == "queue_full"
        assert s.metrics()["shed"] == 1
        for req in (a, b):                   # accepted work still completes
            _, err = _collect(req)
            assert err is None
    finally:
        s.stop()


def test_scheduler_expires_queued_request_past_deadline():
    world = _FakeWorld(1, token_delay=0.2)
    s = _scheduler(world, slots_per_replica=1, overcommit=1).start()
    try:
        blocker = s.submit(np.asarray([1], np.int32), 4)  # owns the slot
        late = s.submit(np.asarray([2], np.int32), 4, timeout=0.05)
        toks, err = _collect(late)
        assert err is not None and err[1] == "deadline" and toks == []
        assert s.metrics()["expired"] == 1
        _, err = _collect(blocker)
        assert err is None
    finally:
        s.stop()


def test_replica_death_requeues_once_with_exact_stream():
    """Kill the replica serving a request mid-stream: the request replays
    on the survivor and the client-visible stream is the exact oracle
    sequence with no duplicates or gaps (skip-dedup across failover)."""
    world = _FakeWorld(2, token_delay=0.05)
    s = _scheduler(world, slots_per_replica=1, overcommit=1).start()
    try:
        p = np.asarray([3, 5], np.int32)
        req = s.submit(p, 8)
        # wait until some tokens flowed, then kill the serving replica
        while not req.tokens:
            time.sleep(0.01)
        victim = req.replica
        world.kill(victim)
        toks, err = _collect(req, timeout=15)
        assert err is None
        assert toks == _fake_tokens(p, 8), "failover stream not exact"
        m = s.metrics()
        assert m["requeued"] == 1 and m["completed"] == 1
        assert not m["replicas"][victim]["alive"]
        assert s.dead_replicas() == {victim}
    finally:
        s.stop()


def test_trace_id_survives_requeue_failover(tmp_path):
    """End-to-end tracing across the failover path: the trace id stamped
    at admission survives the requeue-once hop to the surviving replica,
    and ``tracing.stitch_trace`` reconstructs the full
    admission → route → first-token → requeue → re-route → done timeline
    (with the untraced ``replica_dead`` folded in as context)."""
    from tensorflowonspark_tpu import tracing
    from tensorflowonspark_tpu.observability import EventLog

    world = _FakeWorld(2, token_delay=0.05)
    log = EventLog(str(tmp_path / "serving_events.jsonl"))
    s = _scheduler(world, slots_per_replica=1, overcommit=1,
                   event_log=log).start()
    try:
        p = np.asarray([3, 5], np.int32)
        trace = tracing.new_trace_id()
        req = s.submit(p, 8, trace=trace)
        assert req.trace == trace
        assert req.message()["trace"] == trace   # rides the wire message
        while not req.tokens:
            time.sleep(0.01)
        victim = req.replica
        world.kill(victim)
        toks, err = _collect(req, timeout=15)
        assert err is None and toks == _fake_tokens(p, 8)
    finally:
        s.stop()
        log.close()

    timeline = tracing.stitch_trace(str(tmp_path), trace)
    kinds = [r["kind"] for r in timeline if not r.get("_context")]
    assert kinds[0] == "request_admitted" and kinds[-1] == "request_done"
    routed = [r for r in timeline if r["kind"] == "request_routed"]
    assert len(routed) == 2, "expected a route before and after failover"
    assert routed[0]["replica"] == victim != routed[1]["replica"]
    assert [r["attempt"] for r in routed] == [1, 2]
    (requeued,) = [r for r in timeline if r["kind"] == "request_requeued"]
    assert requeued["from_replica"] == victim and requeued["trace"] == trace
    assert all(r["trace"] == trace for r in timeline
               if not r.get("_context"))
    # the replica kill that explains the hop appears as a context row
    assert any(r["kind"] == "replica_dead" and r.get("_context")
               for r in timeline)
    # and the CLI-facing formatter renders it
    text = tracing.format_timeline(timeline)
    assert "request_requeued" in text and "[context]" in text


def test_scheduler_registry_series_update(tmp_path):
    """The scheduler's registry instruments: outcome counters tick and
    the collect hook mirrors queue depth / per-replica gauges into a
    snapshot."""
    from tensorflowonspark_tpu import metrics as tpu_metrics

    world = _FakeWorld(2)
    s = _scheduler(world).start()
    reg = tpu_metrics.get_registry()
    c = reg.counter("tfos_serving_requests_total",
                    labelnames=("outcome", "model"))
    accepted0 = c.value(outcome="accepted", model="default")
    completed0 = c.value(outcome="completed", model="default")
    try:
        req = s.submit(np.asarray([1, 2], np.int32), 4)
        _, err = _collect(req)
        assert err is None
        # single-model tiers collapse to the model="default" series
        assert c.value(outcome="accepted", model="default") == accepted0 + 1
        assert c.value(outcome="completed",
                       model="default") == completed0 + 1
        snap = reg.snapshot()    # runs the collect hook
        outst = {tuple(sorted(lbl.items())): v for lbl, v in
                 snap["tfos_serving_replica_outstanding_count"]["samples"]}
        assert (("replica", "0"),) in outst and (("replica", "1"),) in outst
        assert snap["tfos_serving_replicas_alive_count"]["samples"] \
            == [[{}, 2.0]]
        ((_, ttft),) = snap["tfos_serving_ttft_seconds"]["samples"]
        assert ttft["count"] >= 1
        # a dead replica's series are removed, not frozen at last value
        world.kill(1)
        deadline = time.monotonic() + 5
        while 1 not in s.dead_replicas() and time.monotonic() < deadline:
            time.sleep(0.02)
        snap = reg.snapshot()
        labels = [lbl for lbl, _ in
                  snap["tfos_serving_replica_outstanding_count"]["samples"]]
        assert {"replica": "0"} in labels and {"replica": "1"} not in labels
        assert snap["tfos_serving_replicas_alive_count"]["samples"] \
            == [[{}, 1.0]]
    finally:
        s.stop()


def test_replica_death_beyond_requeue_limit_fails_typed():
    world = _FakeWorld(2, token_delay=0.05)
    s = _scheduler(world, slots_per_replica=1, overcommit=1,
                   requeue_limit=0).start()
    try:
        req = s.submit(np.asarray([4], np.int32), 8)
        while not req.tokens:
            time.sleep(0.01)
        world.kill(req.replica)
        _, err = _collect(req, timeout=15)
        assert err is not None and err[1] == "replica_failed"
        assert s.metrics()["failed"] == 1
    finally:
        s.stop()


def test_last_replica_death_fails_no_replica_and_rejects_submits():
    world = _FakeWorld(1, token_delay=0.05)
    s = _scheduler(world, slots_per_replica=1, overcommit=1).start()
    try:
        req = s.submit(np.asarray([5], np.int32), 8)
        while not req.tokens:
            time.sleep(0.01)
        world.kill(0)
        _, err = _collect(req, timeout=15)
        assert err is not None and err[1] == "no_replica"
        with pytest.raises(RequestRejected) as ei:
            s.submit(np.asarray([6], np.int32), 2)
        assert ei.value.reason == "no_replica"
    finally:
        s.stop()


def test_monitor_failure_subscription_marks_dead():
    """on_cluster_failure (the ClusterMonitor hook) retires the implicated
    replica even when its process looks alive (the hang shape)."""
    from tensorflowonspark_tpu.health import HANG, ClusterFailure

    world = _FakeWorld(2)
    s = _scheduler(world).start()
    try:
        s.on_cluster_failure(ClusterFailure(HANG, "wedged", (1,)))
        assert s.dead_replicas() == {1}
        # traffic keeps flowing on the survivor
        req = s.submit(np.asarray([9], np.int32), 3)
        toks, err = _collect(req)
        assert err is None and toks == _fake_tokens([9], 3)
    finally:
        s.stop()


def test_scheduler_stop_rejects_and_errors_leftovers():
    world = _FakeWorld(1, token_delay=0.3)
    s = _scheduler(world).start()
    req = s.submit(np.asarray([1, 2], np.int32), 5)
    s.stop()
    _, err = _collect(req)
    assert err is not None and err[1] == "shutdown"
    with pytest.raises(RequestRejected) as ei:
        s.submit(np.asarray([1], np.int32), 1)
    assert ei.value.reason == "shutdown"


# --------------------------------------------- tenant admission units

def test_token_bucket_rate_and_burst():
    from tensorflowonspark_tpu.serving import TokenBucket

    b = TokenBucket(rate=2.0, burst=3)
    t = 100.0
    assert [b.try_take(t) for _ in range(4)] == [True, True, True, False]
    assert b.try_take(t + 0.5)            # 0.5s x 2/s = 1 token back
    assert not b.try_take(t + 0.5)
    # refill caps at burst, no matter how long idle
    assert [b.try_take(t + 100.0) for _ in range(4)] \
        == [True, True, True, False]


def test_tenant_throttle_sheds_only_the_noisy_tenant():
    """Acceptance: per-tenant shed hits ONLY the over-budget tenant —
    the noisy tenant's burst exhausts its bucket and gets typed
    ``tenant_throttled`` rejections while the quiet tenant's requests,
    submitted between the noisy ones, all sail through."""
    world = _FakeWorld(2)
    s = _scheduler(world, max_queue_depth=256,
                   tenants={"noisy": {"rate": 0.001, "burst": 3},
                            "quiet": {"rate": None}}).start()
    try:
        accepted, shed = [], []
        for k in range(8):
            try:
                accepted.append(
                    s.submit(np.asarray([k + 1], np.int32), 2,
                             tenant="noisy"))
            except RequestRejected as e:
                assert e.reason == "tenant_throttled"
                assert "noisy" in str(e)
                shed.append(k)
            # interleaved quiet traffic is never shed
            accepted.append(s.submit(np.asarray([50 + k], np.int32), 2,
                                     tenant="quiet"))
        assert len(shed) == 5            # burst of 3 admitted, rest shed
        for req in accepted:
            _, err = _collect(req)
            assert err is None
        m = s.metrics()
        assert m["tenants"]["noisy"]["shed"] == 5
        assert m["tenants"]["noisy"]["accepted"] == 3
        assert m["tenants"]["quiet"]["shed"] == 0
        assert m["tenants"]["quiet"]["accepted"] == 8
        assert m["shed"] == 5 and m["failed"] == 0
    finally:
        s.stop()


def test_priority_classes_order_the_pending_queue():
    """With one busy slot, later-admitted high-priority work dispatches
    ahead of earlier low-priority work (FIFO within a class)."""
    world = _FakeWorld(1, token_delay=0.1)
    s = _scheduler(world, slots_per_replica=1, overcommit=1,
                   max_queue_depth=16,
                   tenants={"batch": {"priority": "low"},
                            "inter": {"priority": "high"}}).start()
    try:
        blocker = s.submit(np.asarray([1], np.int32), 3)   # owns the slot
        low = [s.submit(np.asarray([10 + k], np.int32), 2, tenant="batch")
               for k in range(2)]
        high = s.submit(np.asarray([30], np.int32), 2, tenant="inter")
        for req in (blocker, high, *low):
            _, err = _collect(req)
            assert err is None
        assert high.priority == "high" and low[0].priority == "low"
        # the replica is strictly serial, so first-token times reflect
        # dispatch order: high (admitted LAST) ran before both lows
        assert high.first_token_at < low[0].first_token_at \
            < low[1].first_token_at
        assert s.metrics()["completed"] == 4
    finally:
        s.stop()


def test_priority_override_can_only_demote():
    world = _FakeWorld(1)
    s = _scheduler(world, tenants={"t": {"priority": "normal"}}).start()
    try:
        up = s.submit(np.asarray([1], np.int32), 1, tenant="t",
                      priority="high")
        down = s.submit(np.asarray([2], np.int32), 1, tenant="t",
                        priority="low")
        assert up.priority == "normal"      # promotion denied
        assert down.priority == "low"       # demotion honored
        with pytest.raises(ValueError):
            s.submit(np.asarray([3], np.int32), 1, priority="urgent")
        for req in (up, down):
            _, err = _collect(req)
            assert err is None
    finally:
        s.stop()


# --------------------------------------------- elastic membership units

def test_live_add_replica_takes_traffic():
    world = _FakeWorld(1)
    s = _scheduler(world).start()
    try:
        _, err = _collect(s.submit(np.asarray([1], np.int32), 3))
        assert err is None
        s.add_replica(world.add_replica())
        assert s.alive_replicas() == {0, 1}
        # saturate: enough parallel work that least-outstanding routing
        # must spill onto the newcomer
        reqs = [s.submit(np.asarray([k + 2], np.int32), 3)
                for k in range(8)]
        for req in reqs:
            _, err = _collect(req)
            assert err is None
        m = s.metrics()
        assert m["replicas"][1]["served"] > 0, "newcomer got no traffic"
        with pytest.raises(ValueError):
            s.add_replica(world.cluster_info[1])   # double registration
    finally:
        s.stop()


def test_drain_based_retire_is_clean_and_loses_nothing():
    """Mark-drain → drain → retire mid-stream: the in-flight request
    finishes on the draining replica (exact), no new work routes to it,
    and the departure never counts as a death."""
    world = _FakeWorld(2, token_delay=0.05)
    s = _scheduler(world, slots_per_replica=1, overcommit=1).start()
    try:
        p = np.asarray([3, 5], np.int32)
        req = s.submit(p, 6)
        while not req.tokens:
            time.sleep(0.01)
        victim = req.replica
        assert s.mark_draining(victim)
        assert not s.mark_draining(victim)     # idempotent
        assert s.draining_replicas() == {victim}
        # new work only lands on the survivor
        other = [s.submit(np.asarray([9 + k], np.int32), 2)
                 for k in range(3)]
        toks, err = _collect(req)
        assert err is None and toks == _fake_tokens(p, 6)
        assert s.drain_replica(victim, timeout=10)
        s.retire_replica(victim)
        world.exit_clean(victim)
        for r in other:
            assert r.replica != victim
            _, err = _collect(r)
            assert err is None
        m = s.metrics()
        assert s.dead_replicas() == set()       # retired, NOT dead
        assert m["replicas"][victim]["retired"]
        assert m["requeued"] == 0 and m["failed"] == 0
        # traffic continues on the survivor
        _, err = _collect(s.submit(np.asarray([40], np.int32), 2))
        assert err is None
    finally:
        s.stop()


def test_forced_retire_requeues_in_flight_exactly():
    """Retiring WITHOUT waiting for the drain re-queues the in-flight
    request to the survivor — stream stays exact and the planned move
    does not burn the request's failover attempt."""
    world = _FakeWorld(2, token_delay=0.05)
    s = _scheduler(world, slots_per_replica=1, overcommit=1).start()
    try:
        p = np.asarray([4, 7], np.int32)
        req = s.submit(p, 8)
        while not req.tokens:
            time.sleep(0.01)
        victim = req.replica
        s.retire_replica(victim, reason="forced")   # no drain first
        world.exit_clean(victim)
        toks, err = _collect(req, timeout=15)
        assert err is None and toks == _fake_tokens(p, 8)
        m = s.metrics()
        assert m["requeued"] == 1 and m["completed"] == 1
        assert s.dead_replicas() == set()
        # the replay kept its one real-failure requeue budget: retire the
        # serving replica mid-flight AGAIN (replacement registered
        # first) and the request must still complete via a second
        # planned re-queue — only real deaths charge the failover limit
        s.add_replica(world.add_replica())
        req2 = s.submit(p, 8)
        while not req2.tokens:
            time.sleep(0.01)
        s.retire_replica(req2.replica, reason="forced")
        toks, err = _collect(req2, timeout=15)
        assert err is None and toks == _fake_tokens(p, 8)
    finally:
        s.stop()


# ------------------------------------------------- sharded gang units

def test_gang_registration_and_capacity_accounting():
    """gang_size=2 over 4 workers registers TWO routable endpoints
    (leaders 0 and 2, members 1 and 3) with device-weighted capacity —
    a tp gang is one endpoint with a weight, not N replicas."""
    world = _FakeWorld(4)
    s = _scheduler(world, gang_size=2, capacity_weight=2).start()
    try:
        assert set(s.replicas) == {0, 2}
        assert s.gang_members(0) == (0, 1) and s.gang_members(2) == (2, 3)
        assert s.resolve_gang(1) == 0 and s.resolve_gang(3) == 2
        assert s.resolve_gang(2) == 2        # leaders resolve to selves
        m = s.metrics()
        assert m["gang_size"] == 2 and m["capacity_devices"] == 4
        assert m["replicas"][0]["weight"] == 2
        assert m["replicas"][0]["members"] == [1]
        # traffic routes over LEADERS only
        reqs = [s.submit(np.arange(1, 3 + k, dtype=np.int32), 4)
                for k in range(6)]
        for req in reqs:
            _, err = _collect(req)
            assert err is None
        m = s.metrics()
        assert all(m["replicas"][eid]["served"] > 0 for eid in (0, 2))
        # live gang add registers leader + member as one endpoint
        info4 = world.add_replica()
        world.add_replica()                  # member slot (eid 5)
        s.add_replica(info4, members=(5,))
        assert s.alive_replicas() == {0, 2, 4}
        assert s.resolve_gang(5) == 4
        assert s.metrics()["capacity_devices"] == 6
        # a gang endpoint needs exactly gang_size-1 members
        with pytest.raises(ValueError, match="gang"):
            s.add_replica({"executor_id": 6, "addr": ("x", 0),
                           "authkey": b"x"}, members=())
    finally:
        s.stop()


def test_gang_misaligned_blocks_rejected():
    world = _FakeWorld(3)
    with pytest.raises(ValueError, match="not a multiple of gang_size"):
        _scheduler(world, gang_size=2)


def test_gang_member_death_fails_whole_gang_over_once():
    """SIGKILL one NON-LEADER shard mid-stream: the whole gang
    classifies dead, its in-flight request re-queues ONCE to the
    surviving gang, and the client stream is the exact oracle sequence
    (skip-dedup across the gang failover)."""
    world = _FakeWorld(4, token_delay=0.05)
    s = _scheduler(world, gang_size=2, capacity_weight=2,
                   slots_per_replica=1, overcommit=1).start()
    try:
        p = np.asarray([3, 5], np.int32)
        req = s.submit(p, 8)
        while not req.tokens:
            time.sleep(0.01)
        victim_leader = req.replica
        member = victim_leader + 1
        world.kill(member)                  # the member, NOT the leader
        from tensorflowonspark_tpu.health import ClusterFailure

        s.on_cluster_failure(ClusterFailure(
            "crash", f"crash: worker {member} exit=-9",
            failed_workers=(member,)))
        toks, err = _collect(req, timeout=15)
        assert err is None
        assert toks == _fake_tokens(p, 8), "gang failover stream not exact"
        m = s.metrics()
        assert m["requeued"] == 1 and m["completed"] == 1
        assert not m["replicas"][victim_leader]["alive"]
        # dead set covers the WHOLE gang (shutdown tolerance needs every
        # corpse), and capacity dropped by the gang's weight
        assert s.dead_replicas() == {victim_leader, member}
        assert m["capacity_devices"] == 2
    finally:
        s.stop()


def test_gang_member_exit_detected_by_supervisor():
    """The backend-exitcode supervision path alone (no monitor event)
    must also resolve a member's death to the whole gang."""
    world = _FakeWorld(4)
    s = _scheduler(world, gang_size=2, poll_interval=0.05).start()
    try:
        world.kill(3)                       # member of gang 2
        deadline = time.monotonic() + 5
        while s.alive_replicas() != {0} and time.monotonic() < deadline:
            time.sleep(0.02)
        assert s.alive_replicas() == {0}
        assert s.dead_replicas() == {2, 3}
    finally:
        s.stop()


def test_autoscaler_weights_capacity_by_gang_devices():
    """A tp=4 gang counts 4 capacity units in the up-pressure signal:
    the same queue depth that would scale a 4-replica tier up must NOT
    scale a single 4-device gang tier up at 4x the per-unit threshold,
    and vice versa must once the weighted threshold is crossed."""
    from tensorflowonspark_tpu.serving import Autoscaler

    fake = _FakeServing(replicas=1)
    # graft gang weight onto the fake's metrics
    base_metrics = fake.scheduler.metrics

    def metrics():
        m = base_metrics()
        for r in m["replicas"].values():
            r["weight"] = 4
        return m

    fake.scheduler.metrics = metrics
    a = Autoscaler(fake, min_replicas=1, max_replicas=3,
                   up_queue_per_replica=4.0, up_consecutive=1,
                   up_cooldown=0.0)
    fake.queued = 9        # 9 > 4*1 endpoint, but NOT > 4*4 devices
    s = a.sample()
    assert s["capacity"] == 4
    assert a.decide(s, now=1.0)[0] == "hold"
    fake.queued = 17       # 17 > 4 units x 4/unit: genuine overload
    d, reason = a.decide(a.sample(), now=2.0)
    assert d == "up" and "capacity" in reason


# ------------------------------------------------------ autoscaler units

class _FakeServing:
    """Scheduler-facade the Autoscaler drives in units: canned metrics,
    recorded actions."""

    def __init__(self, replicas=1):
        self.n = replicas
        self.queued = 0
        self.outstanding = 0
        self.added = 0
        self.retired = []
        self.events = []
        fake = self

        class _Sched:
            def metrics(self):
                return {
                    "queued": fake.queued,
                    "ttft": {"p95_secs": None},
                    "replicas": {
                        i: {"alive": True, "draining": False,
                            "outstanding": fake.outstanding // max(1, fake.n)}
                        for i in range(fake.n)},
                }

            def emit_event(self, kind, **fields):
                fake.events.append((kind, fields))

        self.scheduler = _Sched()

    def add_replicas(self, n):
        self.n += n
        self.added += n
        return list(range(self.n - n, self.n))

    def retire_replica(self, eid, drain_timeout=None):
        self.n -= 1
        self.retired.append(eid)
        return True


def test_autoscaler_decisions_hysteresis_and_cooldown():
    from tensorflowonspark_tpu.serving import Autoscaler

    fake = _FakeServing(replicas=1)
    a = Autoscaler(fake, min_replicas=1, max_replicas=3,
                   up_queue_per_replica=4.0, up_consecutive=2,
                   up_cooldown=10.0, down_consecutive=2,
                   down_cooldown=30.0,
                   down_outstanding_per_replica=1.0)
    t = 1000.0
    fake.queued = 9                       # 9 > 4*1: overload
    assert a.decide(a.sample(), now=t)[0] == "hold"      # 1 sample: wait
    d, reason = a.decide(a.sample(), now=t + 1)
    assert d == "up" and "queued 9" in reason            # hysteresis met
    a.acted("up", now=t + 1)
    fake.n = 2
    # still overloaded but inside the up-cooldown: hold
    assert a.decide(a.sample(), now=t + 2)[0] == "hold"
    assert a.decide(a.sample(), now=t + 3)[0] == "hold"
    # past the cooldown (and streak rebuilt): up again, capped at max
    d, _ = a.decide(a.sample(), now=t + 12)
    assert d == "up"
    a.acted("up", now=t + 12)
    fake.n = 3
    fake.queued = 20
    # at max_replicas: no more ups no matter the load
    for k in range(5):
        assert a.decide(a.sample(), now=t + 30 + k)[0] == "hold"
    # load vanishes: scale down only after ITS hysteresis + cooldown
    fake.queued = 0
    fake.outstanding = 0
    assert a.decide(a.sample(), now=t + 40)[0] == "hold"
    d, reason = a.decide(a.sample(), now=t + 41)
    assert d == "down" and "idle" in reason
    a.acted("down", now=t + 41)
    fake.n = 2
    # down-cooldown holds the next shrink
    assert a.decide(a.sample(), now=t + 42)[0] == "hold"
    assert a.decide(a.sample(), now=t + 43)[0] == "hold"
    d, _ = a.decide(a.sample(), now=t + 72)
    assert d == "down"


def test_autoscaler_ttft_signal_and_min_bound():
    from tensorflowonspark_tpu.serving import Autoscaler

    fake = _FakeServing(replicas=2)
    a = Autoscaler(fake, min_replicas=2, max_replicas=3,
                   up_ttft_p95=0.5, up_consecutive=1, up_cooldown=0.0,
                   down_consecutive=1, down_cooldown=0.0)
    s = a.sample()
    s["ttft_p95"] = 0.8                   # latency breach, queue empty
    d, reason = a.decide(s, now=1.0)
    assert d == "up" and "ttft" in reason
    a.acted("up", now=1.0)
    # idle at min_replicas: never below the floor
    fake.queued = 0
    fake.outstanding = 0
    assert a.decide(a.sample(), now=100.0)[0] == "hold"


def test_autoscaler_loop_acts_and_emits_events():
    """The threaded loop end-to-end over the facade: overload → add;
    idle → drain-based retire; both actions land in the event stream."""
    from tensorflowonspark_tpu.serving import Autoscaler

    fake = _FakeServing(replicas=1)
    fake.queued = 50
    a = Autoscaler(fake, min_replicas=1, max_replicas=2, interval=0.05,
                   up_queue_per_replica=4.0, up_consecutive=2,
                   up_cooldown=0.0, down_consecutive=2, down_cooldown=0.0)
    a.start()
    try:
        deadline = time.monotonic() + 5
        while fake.added == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert fake.added >= 1, "no scale-up happened"
        fake.queued = 0
        fake.outstanding = 0
        deadline = time.monotonic() + 5
        while not fake.retired and time.monotonic() < deadline:
            time.sleep(0.02)
        assert fake.retired, "no scale-down happened"
    finally:
        a.stop()
    kinds = [k for k, _ in fake.events]
    assert "scale_up" in kinds and "scale_down" in kinds
    up = dict(fake.events)[("scale_up")]
    assert "reason" in up and "queued" in up


# ---------------------------------------------- warm-standby pool units

class _PoolWorld(_FakeWorld):
    """_FakeWorld + the cluster surface StandbyPool/ServingCluster need:
    ``add_workers`` spawns fake replicas in gang-sized blocks,
    ``_client_for`` swallows driver control messages (promote etc.)."""

    def add_workers(self, n, map_fun=None, tf_args=None, timeout=None):
        return [self.add_replica() for _ in range(n)]

    def _client_for(self, eid):
        class _Null:
            def put(self, qname, item, timeout=None):
                pass
        return _Null()

    def retire_worker(self, eid):
        pass


def _standby_tier(world, scheduler, pool_size):
    """A driver-side ServingCluster over fakes (no frontend/monitor),
    with a filled warm-standby pool — the unit harness for promotion
    race-safety."""
    from tensorflowonspark_tpu.serving import ServingCluster, StandbyPool

    tier = ServingCluster(world, scheduler, monitor=None, frontend=None,
                          address=("127.0.0.1", 0))
    scheduler.on_replica_ready = tier._on_standby_ready
    tier.standbys = StandbyPool(tier, pool_size)
    tier.standbys.fill()
    return tier


def test_standby_promotion_race_promotes_two_different_standbys():
    """Acceptance (race-safety): a concurrent replica failure and an
    autoscaler scale-up each acquire a standby — with two pooled, they
    promote two DIFFERENT ones (acquire pops atomically; a double
    promotion would blow up scheduler.add_replica's double-registration
    guard)."""
    world = _PoolWorld(2)
    s = _scheduler(world).start()
    tier = _standby_tier(world, s, pool_size=2)
    try:
        assert tier.standbys.stats()["standbys"] == 2    # eids 2 and 3
        got = []
        threads = [threading.Thread(
            target=lambda src=src: got.append(tier.promote_standby(src)))
            for src in ("failure", "scale_up")]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert sorted(got) == [2, 3], got
        assert {2, 3} <= s.alive_replicas()
        # both promoted gangs serve traffic
        for k in range(4):
            _, err = _collect(s.submit(np.asarray([k + 1], np.int32), 2))
            assert err is None
        # the standby_ready acks close the heal measurements AND release
        # the deferred backfills (restock waits for restored capacity)
        for eid in got:
            s._handle_response(s.replicas[eid],
                               {"rid": None, "event": "standby_ready"})
        deadline = time.monotonic() + 5
        while tier.standbys.stats()["standbys"] < 2 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert tier.standbys.stats()["standbys"] == 2
        m = tier.metrics()
        assert m["standby"]["promotions"] == {"failure": 1, "scale_up": 1}
        assert m["standby"]["heal"]["count"] == 2
    finally:
        tier.standbys.stop()
        s.stop()


def test_standby_promotion_race_with_one_standby_falls_back_cold():
    """With ONE pooled standby, a concurrent failure-heal + scale-up
    yield one promotion + one COLD spawn — never the same standby twice,
    and the tier still grows by two distinct replicas."""
    world = _PoolWorld(2)
    s = _scheduler(world).start()
    tier = _standby_tier(world, s, pool_size=1)
    try:
        standby_eid = tier.standbys.stats()["ready"][0]
        world.kill(1)
        s.on_cluster_failure(__import__(
            "tensorflowonspark_tpu.health", fromlist=["ClusterFailure"]
        ).ClusterFailure("crash", "crash: worker 1", (1,)))
        threads = [
            threading.Thread(target=tier._spawn_replacement,
                             kwargs=dict(eid=1, source="failure",
                                         promote_source="failure")),
            threading.Thread(target=lambda: tier.scale_up(1)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        deadline = time.monotonic() + 10
        while len(s.alive_replicas()) < 3 and time.monotonic() < deadline:
            time.sleep(0.05)
        alive = s.alive_replicas()
        assert standby_eid in alive, "the standby was never promoted"
        assert len(alive) == 3, alive    # 0 + promoted + one cold spawn
        for k in range(4):
            _, err = _collect(s.submit(np.asarray([k + 1], np.int32), 2))
            assert err is None
    finally:
        tier.standbys.stop()
        s.stop()


def test_standby_death_shrinks_pool_backfills_never_registers():
    """Acceptance (standby churn): a DEAD standby leaves the pool and is
    backfilled by a fresh one — and at no point does an unpromoted
    standby register with the scheduler."""
    from tensorflowonspark_tpu.health import ClusterFailure

    world = _PoolWorld(1)
    s = _scheduler(world).start()
    tier = _standby_tier(world, s, pool_size=1)
    try:
        standby_eid = tier.standbys.stats()["ready"][0]
        assert standby_eid == 1 and s.alive_replicas() == {0}
        world.kill(standby_eid)
        tier._on_cluster_failure(ClusterFailure(
            "crash", f"crash: worker {standby_eid}",
            (standby_eid,)))
        assert tier.standbys.leader_of(standby_eid) is None
        assert standby_eid in tier.standbys.dead
        deadline = time.monotonic() + 5
        while tier.standbys.stats()["standbys"] < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        fresh = tier.standbys.stats()["ready"]
        assert fresh and fresh[0] != standby_eid, fresh
        # the scheduler never saw either standby: no registration, no
        # death, no capacity change
        assert s.alive_replicas() == {0}
        assert s.dead_replicas() == set()
        assert standby_eid not in s.replicas
        _, err = _collect(s.submit(np.asarray([5], np.int32), 3))
        assert err is None
    finally:
        tier.standbys.stop()
        s.stop()


# ------------------------------------------------- frontend/client units

def test_frontend_client_roundtrip_and_typed_shed():
    """The TCP edge over fake replicas: generate, generate_stream (delta
    concat == generate), stats, and a typed queue_full rejection."""
    world = _FakeWorld(2)
    s = _scheduler(world, max_queue_depth=64).start()
    fe = ServeFrontend(s, authkey=b"s" * 16)
    addr = fe.start()
    try:
        with ServeClient(addr, b"s" * 16) as c:
            assert c.ping()
            p = np.asarray([2, 3, 4], np.int32)
            got = c.generate(p, 6)
            assert got.tolist() == _fake_tokens(p, 6)
            deltas = list(c.generate_stream(p, 6))
            assert [t for d in deltas for t in d] == _fake_tokens(p, 6)
            stats = c.stats()
            assert stats["completed"] == 2
            assert stats["ttft"]["count"] == 2
        with pytest.raises(ConnectionError):
            ServeClient(addr, b"wrong-key-------")
        # shed: shrink the bound under the scheduler lock-free counters
        s.max_queue_depth = 0
        with ServeClient(addr, b"s" * 16) as c, \
                pytest.raises(RequestRejected) as ei:
            c.generate(p, 2)
        assert ei.value.reason == "queue_full"
    finally:
        fe.stop()
        s.stop()


def test_frontend_deadline_mid_request_is_typed():
    world = _FakeWorld(1, token_delay=0.15)
    s = _scheduler(world, slots_per_replica=1, overcommit=1).start()
    fe = ServeFrontend(s, authkey=b"s" * 16)
    addr = fe.start()
    try:
        with ServeClient(addr, b"s" * 16) as c, \
                pytest.raises(DeadlineExceeded):
            c.generate(np.asarray([1], np.int32), 50, timeout=0.3)
    finally:
        fe.stop()
        s.stop()


def test_frontend_carries_tenant_and_priority():
    """Tenant/priority ride the wire: a client bound to the noisy tenant
    sees typed tenant_throttled shed; the quiet client's traffic (and
    the default tenant) sails through."""
    world = _FakeWorld(1)
    s = _scheduler(world, max_queue_depth=64,
                   tenants={"noisy": {"rate": 0.001, "burst": 1},
                            "quiet": {"rate": None,
                                      "priority": "high"}}).start()
    fe = ServeFrontend(s, authkey=b"s" * 16)
    addr = fe.start()
    try:
        p = np.asarray([5], np.int32)
        with ServeClient(addr, b"s" * 16, tenant="noisy") as c:
            c.generate(p, 2)                       # burst of 1
            with pytest.raises(RequestRejected) as ei:
                c.generate(p, 2)
            assert ei.value.reason == "tenant_throttled"
            # per-call override outruns the client default
            c.generate(p, 2, tenant="quiet")
        with ServeClient(addr, b"s" * 16) as c:    # default tenant
            c.generate(p, 2)
            stats = c.stats()
        assert stats["tenants"]["noisy"]["shed"] == 1
        assert stats["tenants"]["noisy"]["accepted"] == 1
        assert stats["tenants"]["quiet"]["accepted"] == 1
        assert stats["tenants"]["default"]["accepted"] == 1
    finally:
        fe.stop()
        s.stop()


def test_client_reconnects_once_on_idle_socket_error():
    """Satellite: a transient socket failure on an IDLE connection (the
    frontend closed the keep-alive between requests) is healed by one
    reconnect-and-retry; a genuinely dead frontend still raises after
    the single retry — typed, not swallowed."""
    world = _FakeWorld(1)
    s = _scheduler(world).start()
    fe = ServeFrontend(s, authkey=b"s" * 16)
    addr = fe.start()
    c = ServeClient(addr, b"s" * 16, timeout=5.0)
    try:
        p = np.asarray([2, 3], np.int32)
        got = c.generate(p, 3)
        # sever the established connection out from under the client —
        # the next send/receive fails like a reset idle keep-alive
        c._sock.shutdown(__import__("socket").SHUT_RDWR)
        c._sock.close()
        assert c.ping(), "reconnect-and-retry did not heal the connection"
        assert c.generate(p, 3).tolist() == got.tolist()
        # stream path heals the same way
        c._sock.close()
        deltas = list(c.generate_stream(p, 3))
        assert [t for d in deltas for t in d] == got.tolist()
    finally:
        c.close()
        fe.stop()
    # frontend really gone: the single retry must fail loudly
    c2_error = None
    try:
        c2 = ServeClient(addr, b"s" * 16, timeout=1.0)
    except (ConnectionError, OSError):
        c2 = None      # listener already down: constructor refuses
    if c2 is not None:
        try:
            c2.ping()
        except (ConnectionError, OSError, EOFError) as e:
            c2_error = e
        finally:
            c2.close()
        assert c2_error is not None, "dead frontend went unnoticed"
    s.stop()


# ------------------------------------------------------ integration

def _oracle(prompt, n, seed=0):
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import greedy_generate
    from tests.cluster_funcs import serving_tiny_gpt_builder

    cfg, params = serving_tiny_gpt_builder({"seed": seed})
    out = greedy_generate(cfg, params,
                          jnp.asarray(prompt, jnp.int32)[None, :], n)
    return np.asarray(out)[0, len(prompt):].tolist()


def _requests(rng, n, vocab=83, tmin=3, tmax=9, bmin=4, bmax=12):
    return [(rng.integers(0, vocab, (int(rng.integers(tmin, tmax)),))
             .astype(np.int32), int(rng.integers(bmin, bmax)))
            for _ in range(n)]


def _run_serving(tmp_path, worker_env, num_replicas=2, **kw):
    from tests.cluster_funcs import serving_tiny_gpt_builder

    from tensorflowonspark_tpu.serving import ServingCluster

    kw.setdefault("max_batch", 2)
    kw.setdefault("reservation_timeout", 120)
    return ServingCluster.run(
        serving_tiny_gpt_builder, num_replicas,
        worker_env=worker_env, working_dir=str(tmp_path), **kw)


@pytest.mark.integration
def test_serving_cluster_end_to_end(tmp_path, worker_env):
    """Acceptance: N concurrent clients against 2 replicas under
    staggered admission — every request greedy-exact vs its solo oracle,
    both replicas served traffic, streaming deltas concat exactly."""
    serving = _run_serving(tmp_path, worker_env)
    try:
        rng = np.random.default_rng(0)
        reqs = _requests(rng, 12)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 4):   # 4-way stagger
                        p, n = reqs[i]
                        results[i] = c.generate(p, n).tolist()
                        time.sleep(0.01 * cid)
            except Exception as e:                        # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors, errors
        assert len(results) == len(reqs)
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"

        # streaming: delta concat equals the oracle too
        with serving.client() as c:
            p, n = reqs[0]
            deltas = list(c.generate_stream(p, n))
            assert [t for d in deltas for t in d] == _oracle(p, n)
            assert len(deltas) > 1, "no incremental streaming happened"
            stats = c.stats()
        assert stats["completed"] == len(reqs) + 1
        assert stats["shed"] == stats["failed"] == 0
        assert all(r["served"] > 0 for r in stats["replicas"].values()), \
            f"routing starved a replica: {stats['replicas']}"
        assert stats["e2e"]["p99_secs"] is not None
    finally:
        serving.shutdown(timeout=120)


@pytest.mark.integration
def test_serving_replica_kill_requeues_and_stays_exact(tmp_path, worker_env):
    """Chaos: SIGKILL replica 1 mid-decode (TFOS_CHAOS at_step trigger on
    the serving loop's report_step).  Every accepted request must still
    complete with oracle-exact tokens — in-flight work on the dead
    replica is re-queued to the survivor — and the death must be
    recorded (requeued>0 or the dead replica visible in metrics) with
    zero failed requests."""
    env = dict(worker_env, TFOS_CHAOS="kill node=1 at_step=4")
    serving = _run_serving(tmp_path, env)
    try:
        rng = np.random.default_rng(1)
        reqs = _requests(rng, 8, bmin=10, bmax=16)   # long enough to span
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 2):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=120).tolist()
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"
        m = serving.metrics()
        assert m["completed"] == len(reqs) and m["failed"] == 0, m
        assert serving.scheduler.dead_replicas() == {1}, \
            "chaos kill was not detected"
    finally:
        serving.shutdown(timeout=120)


@pytest.mark.integration
@pytest.mark.slow
def test_serving_kill_soak_under_sustained_load(tmp_path, worker_env):
    """Soak: sustained staggered traffic while a replica dies mid-run;
    every accepted request completes exactly, none lost."""
    env = dict(worker_env, TFOS_CHAOS="kill node=0 at_step=12")
    serving = _run_serving(tmp_path, env, max_batch=2)
    try:
        rng = np.random.default_rng(2)
        reqs = _requests(rng, 24, bmin=6, bmax=14)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 3):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=180).tolist()
                        time.sleep(0.05)
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"
        m = serving.metrics()
        assert m["completed"] == len(reqs) and m["failed"] == 0, m
        assert serving.scheduler.dead_replicas() == {0}
        events = [e["kind"] for e in _serving_events(tmp_path)]
        assert "replica_dead" in events
    finally:
        serving.shutdown(timeout=180)


@pytest.mark.integration
def test_live_add_and_drain_retire_replica(tmp_path, worker_env):
    """Elastic membership end-to-end on real worker processes: grow a
    1-replica tier to 2 (reservation path re-opens, newcomer serves
    oracle-exact traffic), then drain-retire the founding replica — the
    departure is clean (no dead replicas, no worker error) and the tier
    keeps serving on the survivor through shutdown."""
    serving = _run_serving(tmp_path, worker_env, num_replicas=1)
    try:
        rng = np.random.default_rng(3)
        reqs = _requests(rng, 10, bmin=5, bmax=9)
        with serving.client() as c:
            p, n = reqs[0]
            assert c.generate(p, n).tolist() == _oracle(p, n)
        added = serving.add_replicas(1)
        assert added == [1]
        assert serving.scheduler.alive_replicas() == {0, 1}
        # concurrent traffic so least-outstanding routing uses both
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 3):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=120).tolist()
            except Exception as e:       # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"
        m = serving.metrics()
        assert m["replicas"][1]["served"] > 0, "newcomer got no traffic"
        # drain-based scale-down of the founder
        assert serving.retire_replica(0, drain_timeout=60)
        assert serving.scheduler.dead_replicas() == set()
        assert serving.scheduler.alive_replicas() == {1}
        with serving.client() as c:
            p, n = reqs[1]
            assert c.generate(p, n, timeout=120).tolist() == _oracle(p, n)
        m = serving.metrics()
        assert m["failed"] == 0
        kinds = [e["kind"] for e in _serving_events(tmp_path)]
        for kind in ("replica_added", "replica_draining", "replica_retired"):
            assert kind in kinds, (kind, kinds)
    finally:
        serving.shutdown(timeout=120)   # must not raise over the retiree


@pytest.mark.integration
def test_preempted_replica_drains_and_is_replaced(tmp_path, worker_env):
    """Acceptance: chaos ``replace node=1`` SIGTERMs replica 1 mid-
    decode.  Its PreemptionGuard latches, the tier sees the grace-window
    phase flip, drains it, and spawns a replacement — zero accepted
    requests lost, every stream oracle-exact, and shutdown classifies
    NO failure (the reclaim was membership flex, not a crash)."""
    env = dict(worker_env, TFOS_CHAOS="replace node=1 at_step=4")
    serving = _run_serving(tmp_path, env)
    try:
        rng = np.random.default_rng(4)
        reqs = _requests(rng, 8, bmin=8, bmax=14)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 2):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=180).tolist()
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(240)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"
        # the replacement replica registers live (executor id 2)
        deadline = time.monotonic() + 90
        while 2 not in serving.scheduler.alive_replicas() \
                and time.monotonic() < deadline:
            time.sleep(0.25)
        assert 2 in serving.scheduler.alive_replicas(), \
            "preempted replica was not replaced"
        m = serving.metrics()
        assert m["failed"] == 0 and m["completed"] == m["accepted"], m
        assert m["replicas"][1]["alive"] is False
        kinds = [e["kind"] for e in _serving_events(tmp_path)]
        assert "replica_added" in kinds
        assert "replica_draining" in kinds or "replica_dead" in kinds
        # the replacement serves traffic
        with serving.client() as c:
            p, n = reqs[0]
            assert c.generate(p, n, timeout=120).tolist() == _oracle(p, n)
    finally:
        serving.shutdown(timeout=180)   # a reclaim must not fail shutdown


@pytest.mark.integration
def test_warm_standby_promotes_on_replica_kill(tmp_path, worker_env):
    """Acceptance (the heal window, closed): a tier with a warm standby
    loses replica 1 to a chaos SIGKILL mid-decode.  The heal PROMOTES
    the standby — control message + peer weight clone from replica 0 —
    instead of cold-spawning: zero accepted requests lost, every stream
    oracle-exact across the failover, the promoted standby serves, the
    pool backfills, and the event log tells the warm story
    (heal_started → standby_promoted → standby_ready with heal_secs)."""
    env = dict(worker_env, TFOS_CHAOS="kill node=1 at_step=4")
    serving = _run_serving(tmp_path, env, num_replicas=2, warm_standbys=1)
    try:
        assert serving.wait_standbys(timeout=120), "standby never warmed"
        assert serving.standbys.stats() == {"standbys": 1, "ready": [2]}
        rng = np.random.default_rng(6)
        reqs = _requests(rng, 8, bmin=10, bmax=16)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 2):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=180).tolist()
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(240)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"
        # the standby (executor 2) was promoted into the scheduler
        deadline = time.monotonic() + 90
        while 2 not in serving.scheduler.alive_replicas() \
                and time.monotonic() < deadline:
            time.sleep(0.25)
        assert 2 in serving.scheduler.alive_replicas(), \
            "standby was never promoted"
        assert serving.scheduler.dead_replicas() == {1}
        m = serving.metrics()
        assert m["failed"] == 0 and m["completed"] == m["accepted"], m
        assert m["standby"]["promotions"] == {"failure": 1}
        # the promoted replica serves traffic (probe until routed there)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if serving.metrics()["replicas"][2]["served"] > 0:
                break
            ts = [threading.Thread(target=lambda: _probe(serving, reqs[0]))
                  for _ in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
        assert serving.metrics()["replicas"][2]["served"] > 0, \
            "promoted standby never served"
        # the pool backfilled a fresh standby (executor 3)
        deadline = time.monotonic() + 90
        while serving.standbys.stats()["standbys"] < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.25)
        assert serving.standbys.stats()["ready"] == [3]
        kinds = [e["kind"] for e in _serving_events(tmp_path)]
        for kind in ("heal_started", "standby_promoted", "standby_ready",
                     "standby_booted", "replica_replaced"):
            assert kind in kinds, (kind, kinds)
        ready = [e for e in _serving_events(tmp_path)
                 if e["kind"] == "standby_ready"]
        assert ready and ready[0]["heal_secs"] > 0
        assert m["standby"]["heal"]["count"] >= 1
    finally:
        serving.shutdown(timeout=180)


def _probe(serving, req):
    with serving.client() as c:
        p, n = req
        assert c.generate(p, n, timeout=60).tolist() == _oracle(p, n)


@pytest.mark.integration
def test_standby_death_backfills_and_never_registers_live(tmp_path,
                                                          worker_env):
    """Chaos kills the STANDBY itself (node 1, time-triggered — a
    standby reports no steps): the pool shrinks, backfills a fresh
    standby, the scheduler never registered either, and the tier keeps
    serving oracle-exact through shutdown (the corpse is tolerated)."""
    env = dict(worker_env, TFOS_CHAOS="kill node=1 after_secs=2")
    serving = _run_serving(tmp_path, env, num_replicas=1, warm_standbys=1)
    try:
        # wait for the kill to land and the backfill to replace it
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            stats = serving.standbys.stats()
            if stats["ready"] and stats["ready"][0] != 1:
                break
            time.sleep(0.25)
        assert serving.standbys.stats()["ready"] == [2], \
            serving.standbys.stats()
        assert 1 in serving.standbys.dead
        assert serving.scheduler.alive_replicas() == {0}
        assert serving.scheduler.dead_replicas() == set()
        assert 1 not in serving.scheduler.replicas
        rng = np.random.default_rng(7)
        p, n = _requests(rng, 1)[0]
        with serving.client() as c:
            assert c.generate(p, n, timeout=120).tolist() == _oracle(p, n)
        kinds = [e["kind"] for e in _serving_events(tmp_path)]
        assert "standby_dead" in kinds and kinds.count("standby_booted") >= 2
    finally:
        serving.shutdown(timeout=120)   # must tolerate the standby corpse


@pytest.mark.integration
@pytest.mark.slow
def test_autoscaler_ramp_soak_with_replace_chaos(tmp_path, worker_env):
    """Soak (the satellite's ramp scenario as a test): a 1-replica tier
    under a burst 16-deep queue scales itself up; chaos ``replace``
    reclaims the scaled-up replica mid-run (drain + replacement); after
    the burst the autoscaler drains back down.  Zero accepted requests
    lost, every stream oracle-exact."""
    env = dict(worker_env, TFOS_CHAOS="replace node=1 at_step=6")
    serving = _run_serving(
        tmp_path, env, num_replicas=1, max_queue_depth=64,
        autoscale=dict(min_replicas=1, max_replicas=3, interval=0.5,
                       up_queue_per_replica=2.0, up_consecutive=2,
                       up_cooldown=4.0, down_outstanding_per_replica=1.0,
                       down_consecutive=6, down_cooldown=6.0))
    try:
        rng = np.random.default_rng(5)
        reqs = _requests(rng, 16, bmin=6, bmax=12)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(i):
            try:
                with serving.client() as c:
                    p, n = reqs[i]
                    results[i] = c.generate(p, n, timeout=300).tolist()
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(i,))
                   for i in range(len(reqs))]
        for t in threads:     # burst: the queue piles onto one replica
            t.start()
        for t in threads:
            t.join(360)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"
        # idle tail: let the autoscaler shrink back toward min_replicas
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (serving.autoscaler.scale_downs >= 1
                    and serving.autoscaler.scale_ups >= 1):
                break
            time.sleep(0.5)
        m = serving.metrics()
        assert m["failed"] == 0 and m["completed"] == m["accepted"], m
        assert serving.autoscaler.scale_ups >= 1, "no scale-up under burst"
        assert serving.autoscaler.scale_downs >= 1, "no drain scale-down"
        kinds = [e["kind"] for e in _serving_events(tmp_path)]
        assert "scale_up" in kinds and "scale_down" in kinds
        assert "replica_retired" in kinds
    finally:
        serving.shutdown(timeout=300)


# --------------------------------------------- sharded gang integration

def _sharded_oracle(prompt, n, seed=0):
    import jax.numpy as jnp

    from tests.cluster_funcs import serving_sharded_gpt_builder

    from tensorflowonspark_tpu.models import greedy_generate

    cfg, params = serving_sharded_gpt_builder({"seed": seed})
    out = greedy_generate(cfg, params,
                          jnp.asarray(prompt, jnp.int32)[None, :], n)
    return np.asarray(out)[0, len(prompt):].tolist()


def _run_sharded_serving(tmp_path, num_replicas=1, chaos=None, **kw):
    from tests.cluster_funcs import serving_sharded_gpt_builder

    from tensorflowonspark_tpu.serving import ServingCluster

    env = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
    if chaos:
        env["TFOS_CHAOS"] = chaos
    kw.setdefault("max_batch", 2)
    kw.setdefault("reservation_timeout", 120)
    return ServingCluster.run(
        serving_sharded_gpt_builder, num_replicas, mesh={"tp": 2},
        worker_env=env, working_dir=str(tmp_path), **kw)


@pytest.mark.integration
def test_sharded_gang_serves_oracle_exact(tmp_path):
    """Acceptance: one tp=2 gang (leader + barrier member over real
    worker processes) serves concurrent streams greedy-exact vs the solo
    oracle, registers as ONE weighted endpoint, and shuts down clean."""
    serving = _run_sharded_serving(tmp_path)
    try:
        m = serving.scheduler.metrics()
        assert m["gang_size"] == 2 and m["capacity_devices"] == 2
        assert m["replicas"][0]["members"] == [1]
        rng = np.random.default_rng(3)
        reqs = _requests(rng, 6, vocab=64)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 2):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=180).tolist()
            except Exception as e:                      # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(240)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _sharded_oracle(p, n), \
                f"request {i} diverged from the solo oracle"
        m = serving.metrics()
        assert m["failed"] == 0 and m["completed"] == len(reqs)
        assert serving.scheduler.dead_replicas() == set()
    finally:
        serving.shutdown(timeout=180)


@pytest.mark.integration
def test_sharded_gang_member_kill_fails_over_exact(tmp_path):
    """Chaos: SIGKILL the NON-LEADER shard of gang 0 mid-stream (member
    executor 1, at_step on ITS barrier-mirrored step counter).  The
    whole gang must classify dead, its in-flight requests re-queue ONCE
    to the surviving gang, every accepted request completes oracle-exact
    (single-requeue skip-dedup), and shutdown tolerates the corpses."""
    serving = _run_sharded_serving(tmp_path, num_replicas=2,
                                   chaos="kill node=1 at_step=4")
    try:
        rng = np.random.default_rng(5)
        reqs = _requests(rng, 8, vocab=64, bmin=10, bmax=16)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 2):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=240).tolist()
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _sharded_oracle(p, n), \
                f"request {i} diverged across the gang failover"
        m = serving.metrics()
        assert m["failed"] == 0 and m["completed"] == len(reqs), m
        assert m["requeued"] >= 1, "the chaos kill landed nowhere"
        # ONE shard died; the WHOLE gang is the failure domain
        assert serving.scheduler.dead_replicas() == {0, 1}, \
            serving.scheduler.dead_replicas()
        assert m["replicas"][2]["alive"]
        events = _serving_events(tmp_path)
        dead = [e for e in events if e["kind"] == "replica_dead"]
        assert len(dead) == 1 and sorted(dead[0]["shards"]) == [0, 1], \
            "gang death must be reported exactly once, naming its shards"
    finally:
        serving.shutdown(timeout=180)


def _serving_events(tmp_path):
    import os

    from tensorflowonspark_tpu.observability import EventLog

    path = os.path.join(str(tmp_path), "serving_events.jsonl")
    return EventLog.read(path) if os.path.exists(path) else []


# --------------------------------------- disaggregated prefill/decode

class _DisaggWorld(_FakeWorld):
    """Fake specialized pools speaking the handoff protocol: prefill
    fakes answer a gen with the FIRST token + a ``handoff`` session;
    decode fakes answer an ``adopt`` by streaming the remainder.  The
    deterministic ``_fake_tokens`` stream spans the boundary, so replay
    exactness is assertable exactly like the unified fakes."""

    def __init__(self, n_prefill, n_decode, token_delay=0.0,
                 prefill_delay=0.0):
        self.roles = {i: ("prefill" if i < n_prefill else "decode")
                      for i in range(n_prefill + n_decode)}
        self.prefill_delay = prefill_delay
        super().__init__(n_prefill + n_decode, token_delay=token_delay)

    def _run(self, i):
        role = self.roles.get(i, "decode")   # late adds join decode
        while i not in self._dead:
            try:
                item = self.inq[i].get(timeout=0.02)
            except _queue.Empty:
                continue
            rid = item["rid"]
            if role == "prefill":
                p, n = item["prompt"], item["max_new_tokens"]
                toks = _fake_tokens(p, n)
                if self.prefill_delay:
                    time.sleep(self.prefill_delay)
                if i in self._dead:
                    return                   # died mid-prefill
                self.outq[i].put({"rid": rid, "event": "tok",
                                  "tokens": [toks[0]], "load": 0,
                                  "role": "prefill"})
                if n == 1:
                    self.outq[i].put({"rid": rid, "event": "done",
                                      "load": 0, "role": "prefill"})
                    continue
                self.outq[i].put(
                    {"rid": rid, "event": "handoff", "role": "prefill",
                     "load": 0, "free_pages": 7,
                     "session": {"prompt": p, "tokens": [toks[0]],
                                 "remaining": n - 1, "pages": 2,
                                 "kv": []}})
            else:
                sess = item["session"]
                p, g = sess["prompt"], len(sess["tokens"])
                toks = _fake_tokens(p, g + sess["remaining"])[g:]
                for tok in toks:
                    if i in self._dead:
                        return               # died post-handoff
                    if self.token_delay:
                        time.sleep(self.token_delay)
                    self.outq[i].put({"rid": rid, "event": "tok",
                                      "tokens": [tok], "load": 1,
                                      "role": "decode"})
                self.outq[i].put({"rid": rid, "event": "done", "load": 0,
                                  "role": "decode"})


def _disagg_scheduler(world, **kw):
    kw.setdefault("roles", dict(world.roles))
    return _scheduler(world, **kw)


def test_disagg_routes_prompt_to_prefill_then_session_to_decode():
    world = _DisaggWorld(1, 1)
    s = _disagg_scheduler(world).start()
    try:
        prompts = [np.arange(1, 4 + i, dtype=np.int32) for i in range(5)]
        reqs = [s.submit(p, 6) for p in prompts]
        for req, p in zip(reqs, prompts):
            toks, err = _collect(req)
            assert err is None and toks == _fake_tokens(p, 6)
        m = s.metrics()
        assert m["handoffs"] == 5 and m["completed"] == 5
        assert m["queued_handoffs"] == 0
        assert m["replicas"][0]["role"] == "prefill"
        assert m["replicas"][1]["role"] == "decode"
        # every DONE came from the decode gang; the prefill gang only
        # ever prefilled (its served count tracks done events)
        assert m["replicas"][1]["served"] == 5
        assert m["replicas"][0]["served"] == 0
        # the handoff message's free_pages piggyback reached the router
        assert m["replicas"][0]["free_pages"] == 7
    finally:
        s.stop()


def test_submit_rejects_bare_prompt_on_decode_only_tier():
    """The routing safety fix: a tier whose prefill pool is gone (or was
    never configured) rejects prompts TYPED at admission instead of
    queueing them on a decode-only gang forever."""
    world = _DisaggWorld(1, 1)
    s = _disagg_scheduler(world, roles={0: "decode", 1: "decode"}).start()
    try:
        with pytest.raises(RequestRejected) as ei:
            s.submit(np.asarray([1, 2], np.int32), 4)
        assert ei.value.reason == "role_mismatch"
        assert "refusing to queue a bare prompt on a decode-only gang" \
            in str(ei.value)
    finally:
        s.stop()
    # the same rejection when the prefill pool DIES out from under a
    # live tier
    world = _DisaggWorld(1, 1, prefill_delay=0.05)
    s = _disagg_scheduler(world).start()
    try:
        world.kill(0)
        deadline = time.monotonic() + 5
        while 0 not in s.dead_replicas() and time.monotonic() < deadline:
            time.sleep(0.02)
        with pytest.raises(RequestRejected) as ei:
            s.submit(np.asarray([1], np.int32), 3)
        assert ei.value.reason == "role_mismatch"
    finally:
        s.stop()


def test_disagg_prefill_death_mid_prefill_requeues_once_exact():
    world = _DisaggWorld(2, 1, prefill_delay=0.4)
    s = _disagg_scheduler(world, slots_per_replica=1, overcommit=1).start()
    try:
        p = np.asarray([2, 7], np.int32)
        req = s.submit(p, 6)
        deadline = time.monotonic() + 5
        while req.replica is None and time.monotonic() < deadline:
            time.sleep(0.005)
        victim = req.replica
        assert victim in (0, 1), "prompt routed off the prefill pool"
        world.kill(victim)
        toks, err = _collect(req, timeout=15)
        assert err is None and toks == _fake_tokens(p, 6)
        m = s.metrics()
        assert m["requeued"] == 1 and m["completed"] == 1
        assert s.dead_replicas() == {victim}
    finally:
        s.stop()


def test_disagg_decode_death_post_handoff_replays_full_pipeline():
    """A decode gang dying POST-handoff replays the request through the
    whole prefill→handoff→adopt pipeline once: the client stream stays
    exact (skip-dedup spans the boundary) and the request hands off
    TWICE."""
    world = _DisaggWorld(1, 2, token_delay=0.05)
    s = _disagg_scheduler(world, slots_per_replica=1, overcommit=1).start()
    try:
        p = np.asarray([3, 5, 8], np.int32)
        req = s.submit(p, 10)
        # wait until the DECODE side is streaming (>= 2 tokens: first
        # came from prefill, the rest from the adopted session)
        deadline = time.monotonic() + 10
        while len(req.tokens) < 3 and time.monotonic() < deadline:
            time.sleep(0.005)
        victim = req.replica
        assert world.roles[victim] == "decode", "request not in decode"
        world.kill(victim)
        toks, err = _collect(req, timeout=15)
        assert err is None and toks == _fake_tokens(p, 10), \
            "post-handoff failover stream not exact"
        m = s.metrics()
        assert m["requeued"] == 1 and m["completed"] == 1
        assert m["handoffs"] == 2, "the replay must re-handoff"
    finally:
        s.stop()


def test_disagg_requeue_once_budget_spans_the_boundary():
    """One failover attempt TOTAL across the pipeline: the adopt hop
    never charges the budget (a normal request = 1 attempt), and the
    second decode-side death fails typed."""
    world = _DisaggWorld(1, 2, token_delay=0.08)
    s = _disagg_scheduler(world, slots_per_replica=1, overcommit=1).start()
    try:
        p = np.asarray([9, 1], np.int32)
        req = s.submit(p, 12)
        deadline = time.monotonic() + 10
        while len(req.tokens) < 3 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert req.attempts == 1, \
            "the adopt dispatch must not charge the failover budget"
        world.kill(req.replica)          # first decode death: replays
        deadline = time.monotonic() + 10
        while s.metrics()["requeued"] < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        # wait for the replay to reach the surviving decode gang
        deadline = time.monotonic() + 10
        while (req.replica is None
               or world.roles.get(req.replica) != "decode"
               or req.replica in world._dead) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        world.kill(req.replica)          # second death: budget exhausted
        toks, err = _collect(req, timeout=15)
        assert err is not None and err[1] == "replica_failed"
        assert s.metrics()["failed"] == 1
    finally:
        s.stop()


def test_trace_id_survives_handoff_and_post_handoff_requeue(tmp_path):
    """Satellite: the stitched timeline gains the handoff span — one
    trace id covers admission → prefill route → handoff (pages/bytes) →
    adopt route → requeue → re-prefill → re-handoff → done."""
    from tensorflowonspark_tpu import tracing
    from tensorflowonspark_tpu.observability import EventLog

    world = _DisaggWorld(1, 2, token_delay=0.05)
    log = EventLog(str(tmp_path / "serving_events.jsonl"))
    s = _disagg_scheduler(world, slots_per_replica=1, overcommit=1,
                          event_log=log).start()
    try:
        p = np.asarray([4, 4], np.int32)
        trace = tracing.new_trace_id()
        req = s.submit(p, 10, trace=trace)
        deadline = time.monotonic() + 10
        while len(req.tokens) < 3 and time.monotonic() < deadline:
            time.sleep(0.005)
        world.kill(req.replica)          # decode side, post-handoff
        toks, err = _collect(req, timeout=15)
        assert err is None and toks == _fake_tokens(p, 10)
    finally:
        s.stop()
        log.close()

    timeline = tracing.stitch_trace(str(tmp_path), trace)
    kinds = [r["kind"] for r in timeline if not r.get("_context")]
    assert kinds[0] == "request_admitted" and kinds[-1] == "request_done"
    handoffs = [r for r in timeline if r["kind"] == "request_handoff"]
    assert len(handoffs) == 2, "replay must re-handoff under ONE trace"
    assert all(h["trace"] == trace for h in handoffs)
    assert handoffs[0]["from_replica"] == 0
    assert handoffs[0]["pages"] == 2 and "bytes" in handoffs[0]
    adopt_routes = [r for r in timeline
                    if r["kind"] == "request_handoff_routed"]
    assert len(adopt_routes) == 2
    assert all(world.roles[r["replica"]] == "decode"
               for r in adopt_routes)
    (requeued,) = [r for r in timeline if r["kind"] == "request_requeued"]
    assert requeued["trace"] == trace
    assert all(r["trace"] == trace for r in timeline
               if not r.get("_context"))
    # the CLI-facing formatter renders the handoff span
    assert "request_handoff" in tracing.format_timeline(timeline)


class _DisaggPoolWorld(_DisaggWorld):
    """``_DisaggWorld`` + the cluster surface StandbyPool/ServingCluster
    need, with driver control messages RECORDED — the promote message
    must carry the target pool's role."""

    def __init__(self, n_prefill, n_decode, **kw):
        super().__init__(n_prefill, n_decode, **kw)
        self.control: list = []

    def add_workers(self, n, map_fun=None, tf_args=None, timeout=None):
        return [self.add_replica() for _ in range(n)]

    def _client_for(self, eid):
        world = self

        class _Ctl:
            def put(self, qname, item, timeout=None):
                world.control.append((eid, item))

        return _Ctl()

    def retire_worker(self, eid):
        pass


def _disagg_standby_tier(world, scheduler, pool_size, disagg):
    from tensorflowonspark_tpu.serving import ServingCluster, StandbyPool

    tier = ServingCluster(world, scheduler, monitor=None, frontend=None,
                          address=("127.0.0.1", 0))
    tier.disagg = dict(disagg)
    scheduler.on_replica_ready = tier._on_standby_ready
    tier.standbys = StandbyPool(tier, pool_size)
    tier.standbys.fill()
    return tier


def test_promote_with_role_joins_decode_pool_and_serves():
    """Satellite (ROADMAP item 2 leftover): a role-less warm standby is
    promoted INTO a killed decode gang's pool — the promote control
    message carries ``role="decode"``, the scheduler registers the
    newcomer into the decode pool, per-role accounting records it, and
    the healed pipeline serves prefill→handoff→adopt exact."""
    from tensorflowonspark_tpu.health import ClusterFailure

    world = _DisaggPoolWorld(1, 1)
    s = _disagg_scheduler(world).start()
    tier = _disagg_standby_tier(world, s, pool_size=1,
                                disagg={"prefill": 1, "decode": 1})
    try:
        assert tier.standbys.stats() == {"standbys": 1, "ready": [2]}
        world.kill(1)                                  # the decode gang
        s.on_cluster_failure(ClusterFailure("crash", "crash: worker 1",
                                            (1,)))
        tier._spawn_replacement(1, source="failure",
                                promote_source="failure")
        deadline = time.monotonic() + 10
        while (2 not in s.alive_replicas() or not world.control) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 2 in s.alive_replicas(), "standby was never promoted"
        assert world.control, "promote control message never sent"
        assert s.replica_role(2) == "decode", \
            "the newcomer must join the DEAD gang's pool"
        [(ctl_eid, promote)] = [(e, m) for e, m in world.control
                                if m.get("op") == "standby"]
        assert ctl_eid == 2
        assert promote["op"] == "standby" and promote["event"] == "promote"
        assert promote["role"] == "decode", \
            "the promote message must carry the target pool's role"
        # a decode-pool promotion also triggers a prefix-page donation
        # request to a prefill gang (background thread — wait for it)
        deadline = time.monotonic() + 5
        while not any(m.get("op") == "prefix" for _, m in world.control) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        donations = [(e, m) for e, m in world.control
                     if m.get("op") == "prefix"]
        assert donations and donations[0][0] == 0, \
            "the donation export must go to the prefill gang"
        assert donations[0][1]["event"] == "export"
        # the healed pipeline spans the boundary: prompt -> prefill 0 ->
        # handoff -> adopted by the promoted decode gang 2
        for k in range(3):
            p = np.asarray([5 + k, 2], np.int32)
            toks, err = _collect(s.submit(p, 6))
            assert err is None and toks == _fake_tokens(p, 6)
        m = s.metrics()
        assert m["handoffs"] >= 3
        assert m["replicas"][2]["role"] == "decode"
        # per-role pool accounting
        assert tier.metrics()["standby"]["promotions"] == {
            "failure": 1, "role:decode": 1}
    finally:
        tier.standbys.stop()
        s.stop()


def test_expectation_holds_handoff_queue_through_the_heal_window():
    """When the dead decode gang was its pool's LAST, the requeued
    handoffs must WAIT for the in-flight replacement (expect_replica)
    instead of shedding as no_replica — and still fail typed once the
    heal gives up (expect_done with no replacement registered)."""
    world = _DisaggWorld(1, 1, token_delay=0.05)
    s = _disagg_scheduler(world).start()
    try:
        p = np.asarray([3, 1], np.int32)
        req = s.submit(p, 8)
        deadline = time.monotonic() + 10
        while len(req.tokens) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        s.expect_replica("decode")       # the heal announces itself
        world.kill(1)                    # ...then the only decode dies
        deadline = time.monotonic() + 10
        while s.metrics()["requeued"] < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.3)                  # dispatch must NOT shed it
        assert not req.finished, \
            "a held pool's work was shed during the heal window"
        info = world.add_replica()       # the replacement lands
        s.add_replica(info, role="decode")
        s.expect_done("decode")
        toks, err = _collect(req, timeout=15)
        assert err is None and toks == _fake_tokens(p, 8)
        # a SECOND death with no expectation restores the typed shed
        req2 = s.submit(p, 6)
        deadline = time.monotonic() + 10
        while req2.replica is None and time.monotonic() < deadline:
            time.sleep(0.005)
        world.kill(2)
        toks, err = _collect(req2, timeout=15)
        assert err is not None and err[1] == "no_replica", err
    finally:
        s.stop()


def test_promote_role_mismatch_skips_warm_pool_never_crashes():
    """A mismatched promote call (role on a unified tier, no role on a
    disagg tier) SKIPS the warm pool — returning None so the heal thread
    falls back to the cold path's explicit error — and never consumes a
    standby."""
    world = _PoolWorld(1)
    s = _scheduler(world).start()
    tier = _standby_tier(world, s, pool_size=1)
    try:
        assert tier.promote_standby("failure", role="decode") is None
        assert tier.standbys.stats()["standbys"] == 1, \
            "a skipped promotion must not consume the standby"
    finally:
        tier.standbys.stop()
        s.stop()
    world2 = _DisaggPoolWorld(1, 1)
    s2 = _disagg_scheduler(world2).start()
    tier2 = _disagg_standby_tier(world2, s2, pool_size=1,
                                 disagg={"prefill": 1, "decode": 1})
    try:
        assert tier2.promote_standby("scale_up") is None
        assert tier2.standbys.stats()["standbys"] == 1
    finally:
        tier2.standbys.stop()
        s2.stop()


class _FakeDisaggServing(_FakeServing):
    """Two-pool facade: per-role replica sets + both backlog queues, so
    the per-pool autoscalers can be driven deterministically."""

    def __init__(self, n_prefill=1, n_decode=1):
        super().__init__(replicas=n_prefill + n_decode)
        fake = self
        self.by_role = {"prefill": n_prefill, "decode": n_decode}
        self.queued_handoffs = 0
        self.outstanding_by_role = {"prefill": 0, "decode": 0}
        self.added_roles = []

        class _Sched:
            def metrics(self):
                reps = {}
                eid = 0
                for role in ("prefill", "decode"):
                    for _ in range(fake.by_role[role]):
                        reps[eid] = {
                            "alive": True, "draining": False,
                            "role": role,
                            "outstanding":
                                fake.outstanding_by_role[role]
                                // max(1, fake.by_role[role])}
                        eid += 1
                return {"queued": fake.queued,
                        "queued_handoffs": fake.queued_handoffs,
                        "ttft": {"p95_secs": None},
                        "replicas": reps}

            def emit_event(self, kind, **fields):
                fake.events.append((kind, fields))

        self.scheduler = _Sched()

    def scale_up(self, n, role=None):
        self.by_role[role] += n
        self.added_roles.extend([role] * n)
        return list(range(n))


def test_autoscaler_per_pool_signals_and_independence():
    """Per-pool controllers read DIFFERENT backlogs: prompt-queue
    pressure moves only the prefill pool, handoff-queue pressure only
    the decode pool — each within its own bounds."""
    from tensorflowonspark_tpu.serving import Autoscaler, AutoscalerConfig

    fake = _FakeDisaggServing(n_prefill=1, n_decode=1)
    pre = Autoscaler(fake, AutoscalerConfig(
        role="prefill", min_replicas=1, max_replicas=3,
        up_queue_per_replica=2.0, up_consecutive=1, up_cooldown=0.0))
    dec = Autoscaler(fake, AutoscalerConfig(
        role="decode", min_replicas=1, max_replicas=3,
        up_queue_per_replica=2.0, up_consecutive=1, up_cooldown=0.0))

    # prompt backlog only: prefill scales, decode holds
    fake.queued, fake.queued_handoffs = 9, 0
    sp, sd = pre.sample(), dec.sample()
    assert sp["alive"] == 1 and sd["alive"] == 1, "role filter leaked"
    assert sp["queued"] == 9 and sd["queued"] == 0
    assert pre.decide(sp, now=1.0)[0] == "up"
    assert dec.decide(sd, now=1.0)[0] == "hold"

    # handoff backlog only: decode scales, prefill holds
    fake.queued, fake.queued_handoffs = 0, 9
    fake.outstanding_by_role = {"prefill": 5, "decode": 5}  # not idle
    sp, sd = pre.sample(), dec.sample()
    assert sp["queued"] == 0 and sd["queued"] == 9
    assert pre.decide(sp, now=2.0)[0] == "hold"
    assert dec.decide(sd, now=2.0)[0] == "up"
    dec._scale_up(sd, "test")
    assert fake.added_roles == ["decode"], \
        "the decode controller must grow the decode pool"
    ups = [f for k, f in fake.events if k == "scale_up"]
    assert ups and ups[-1]["role"] == "decode"

    # per-pool victim selection: the decode controller's scale-down
    # victim must be a decode gang even when a prefill gang is idler
    fake.queued = fake.queued_handoffs = 0
    fake.outstanding_by_role = {"prefill": 0, "decode": 4}
    m = fake.scheduler.metrics()
    victim = dec._victim(m)
    assert victim is not None \
        and m["replicas"][victim[0]]["role"] == "decode"


@pytest.mark.integration
def test_disagg_cluster_end_to_end(tmp_path, worker_env):
    """Acceptance: a real 1-prefill + 1-decode tier serves concurrent
    clients oracle-exact, every request moves as a KV-page handoff, and
    the specialization holds — zero prefill dispatches on the decode
    gang, zero decode dispatches on the prefill gang."""
    serving = _run_serving(
        tmp_path, worker_env, num_replicas=2,
        disagg={"prefill": 1, "decode": 1},
        batcher_kwargs={"kv_page_tokens": 8})
    try:
        rng = np.random.default_rng(2)
        reqs = _requests(rng, 8)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 2):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=120).tolist()
            except Exception as e:                    # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"
        m = serving.metrics()
        assert m["handoffs"] >= len(reqs) and m["failed"] == 0
        assert m["replicas"][0]["role"] == "prefill"
        assert m["replicas"][1]["role"] == "decode"
        # heartbeat-carried engine counters prove the specialization
        time.sleep(2.5)
        nodes = serving.metrics()["nodes"]

        def _counter(eid, name):
            fam = (nodes.get(eid, {}).get("metrics") or {}).get(name)
            return sum(v for _, v in (fam or {}).get("samples", ()))

        assert _counter(1, "tfos_replica_prefill_dispatches_total") == 0, \
            "the decode gang ran a prefill"
        assert _counter(0, "tfos_replica_decode_dispatches_total") == 0, \
            "the prefill gang ran a decode step"
        assert _counter(0, "tfos_replica_sessions_total") >= len(reqs)
    finally:
        serving.shutdown(timeout=120)


@pytest.mark.integration
def test_disagg_prefill_gang_kill_mid_prefill_stays_exact(tmp_path,
                                                          worker_env):
    """Chaos, prefill side: SIGKILL prefill gang 0 mid-run; its
    in-flight prompts requeue ONCE to the surviving prefill gang and
    every accepted request completes oracle-exact."""
    env = dict(worker_env, TFOS_CHAOS="kill node=0 at_step=1")
    serving = _run_serving(
        tmp_path, env, num_replicas=3,
        disagg={"prefill": 2, "decode": 1},
        batcher_kwargs={"kv_page_tokens": 8})
    try:
        rng = np.random.default_rng(3)
        reqs = _requests(rng, 8, tmin=6, tmax=12, bmin=8, bmax=14)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 2):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=120).tolist()
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"
        m = serving.metrics()
        assert m["failed"] == 0 and m["requeued"] >= 1, m
        assert serving.scheduler.dead_replicas() == {0}
    finally:
        serving.shutdown(timeout=120)


@pytest.mark.integration
def test_disagg_decode_gang_kill_post_handoff_stays_exact(tmp_path,
                                                          worker_env):
    """Chaos, decode side: SIGKILL decode gang 1 while it streams
    adopted sessions; the stranded requests replay through the FULL
    prefill→handoff→adopt pipeline onto the surviving decode gang,
    skip-dedup keeping every client stream exact."""
    env = dict(worker_env, TFOS_CHAOS="kill node=1 at_step=3")
    serving = _run_serving(
        tmp_path, env, num_replicas=3,
        disagg={"prefill": 1, "decode": 2},
        batcher_kwargs={"kv_page_tokens": 8})
    try:
        rng = np.random.default_rng(4)
        reqs = _requests(rng, 8, bmin=10, bmax=16)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 2):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=120).tolist()
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"
        m = serving.metrics()
        assert m["failed"] == 0 and m["requeued"] >= 1, m
        assert serving.scheduler.dead_replicas() == {1}
        # the replays re-handed-off: more handoffs than completions
        assert m["handoffs"] > m["completed"] - m["requeued"]
    finally:
        serving.shutdown(timeout=120)


@pytest.mark.integration
def test_disagg_standby_promotes_into_killed_decode_gang(tmp_path,
                                                         worker_env):
    """Satellite acceptance (disagg x warm_standbys): chaos SIGKILLs the
    only decode gang while it streams adopted sessions; the heal
    PROMOTES the role-less warm standby INTO the decode pool
    (promote-with-role: control message carries role="decode", the
    engine specializes via set_role, the scheduler registers it into the
    pool) — every accepted request completes oracle-exact across the
    heal and the per-role accounting tells the story."""
    env = dict(worker_env, TFOS_CHAOS="kill node=1 at_step=3")
    serving = _run_serving(
        tmp_path, env, num_replicas=2,
        disagg={"prefill": 1, "decode": 1},
        batcher_kwargs={"kv_page_tokens": 8},
        warm_standbys=1)
    try:
        assert serving.wait_standbys(timeout=180), "standby never warmed"
        assert serving.standbys.stats() == {"standbys": 1, "ready": [2]}
        rng = np.random.default_rng(8)
        reqs = _requests(rng, 8, bmin=10, bmax=16)
        results: dict[int, list] = {}
        errors: list = []

        def run_client(cid):
            try:
                with serving.client() as c:
                    for i in range(cid, len(reqs), 2):
                        p, n = reqs[i]
                        results[i] = c.generate(p, n, timeout=240).tolist()
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=run_client, args=(cid,))
                   for cid in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        assert not errors, errors
        for i, (p, n) in enumerate(reqs):
            assert results[i] == _oracle(p, n), f"request {i} diverged"
        # the standby joined the DEAD gang's pool
        deadline = time.monotonic() + 90
        while 2 not in serving.scheduler.alive_replicas() \
                and time.monotonic() < deadline:
            time.sleep(0.25)
        assert 2 in serving.scheduler.alive_replicas(), \
            "standby was never promoted"
        assert serving.scheduler.replica_role(2) == "decode"
        assert serving.scheduler.dead_replicas() == {1}
        m = serving.metrics()
        assert m["failed"] == 0 and m["completed"] == m["accepted"], m
        assert m["requeued"] >= 1, "the killed decode work must replay"
        assert m["standby"]["promotions"] == {"failure": 1,
                                              "role:decode": 1}
        assert m["replicas"][2]["role"] == "decode"
        promoted = [e for e in _serving_events(tmp_path)
                    if e["kind"] == "standby_promoted"]
        assert promoted and promoted[0]["role"] == "decode"
        replaced = [e for e in _serving_events(tmp_path)
                    if e["kind"] == "replica_replaced"]
        assert replaced and replaced[0]["mode"] == "warm" \
            and replaced[0]["role"] == "decode"
    finally:
        serving.shutdown(timeout=180)
