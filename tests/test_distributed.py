"""Multi-process ``jax.distributed`` integration tests.

The round-1 gap (VERDICT "What's missing" #3): ``NodeContext.
initialize_distributed`` was never exercised with ``num_processes > 1``.
These tests run the COMPOSED path — worker backends + reservation rendezvous
+ coordination service + cross-process collectives — on loopback with the
CPU backend (gloo), mirroring the reference's ``local-cluster[2,...]``
pattern (SURVEY.md §4).
"""

import numpy as np
import pytest

from tensorflowonspark_tpu.agent import AgentBackend, HostAgent
from tensorflowonspark_tpu.cluster import TPUCluster
from tests import cluster_funcs

# one CPU device per process → a 2-device global mesh over 2 processes
DIST_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


def _expected_loss_and_w(steps: int = 3, lr: float = 0.1):
    """The single-process value the 2-process run must reproduce."""
    rng = np.random.default_rng(0)
    X = rng.standard_normal((8, 4)).astype(np.float32)
    y = (X @ np.arange(1.0, 5.0, dtype=np.float32)).astype(np.float32)
    w = np.zeros(4, np.float32)
    for _ in range(steps):
        r = X @ w - y
        loss = float(np.mean(r**2))
        w = w - lr * (2.0 / len(y)) * (X.T @ r)
    return loss, w


def _read_results(working_dir, num_workers):
    out = []
    for i in range(num_workers):
        with open(f"{working_dir}/dist.{i}") as f:
            nproc, ndev, loss, w = f.read().split(":")
        out.append((int(nproc), int(ndev), float(loss),
                    np.array([float(v) for v in w.split(",")])))
    return out


def test_two_process_pjit_matches_single_process(tmp_path):
    cluster = TPUCluster.run(
        cluster_funcs.fn_distributed_pjit_train, {"steps": 3},
        num_workers=2, working_dir=str(tmp_path), worker_env=DIST_ENV,
        reservation_timeout=120)
    cluster.shutdown(timeout=240)

    want_loss, want_w = _expected_loss_and_w(steps=3)
    results = _read_results(tmp_path, 2)
    for nproc, ndev, loss, w in results:
        assert nproc == 2, "jax.distributed must span both worker processes"
        assert ndev == 2, "global mesh must see both processes' devices"
        np.testing.assert_allclose(loss, want_loss, rtol=1e-5)
        np.testing.assert_allclose(w, want_w, rtol=1e-5, atol=1e-6)


def test_two_process_pipeline_parallel_matches_oracle(tmp_path):
    """pp=2 across two processes: the GPipe ppermute rides a real process
    boundary; losses must match the single-process sequential oracle."""
    cluster = TPUCluster.run(
        cluster_funcs.fn_distributed_pipeline_train, {"steps": 2},
        num_workers=2, working_dir=str(tmp_path), worker_env=DIST_ENV,
        reservation_timeout=120)
    cluster.shutdown(timeout=240)

    # oracle: same math, sequential stages, one process
    import jax
    import jax.numpy as jnp
    import optax

    rng = np.random.default_rng(0)
    w0 = (rng.standard_normal((2, 8, 8)) * 0.1).astype(np.float32)
    x = jnp.asarray(rng.standard_normal((4, 8)).astype(np.float32))
    tx = optax.sgd(0.1)
    params = {"w": jnp.asarray(w0)}
    opt = tx.init(params)

    def loss_fn(p):
        h = x
        for i in range(2):
            h = h + jnp.tanh(h @ p["w"][i])
        return jnp.mean(h ** 2)

    want = []
    for _ in range(2):
        loss, g = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(g, opt, params)
        params = optax.apply_updates(params, upd)
        want.append(float(loss))

    for i in range(2):
        with open(f"{tmp_path}/pipe.{i}") as f:
            got = [float(v) for v in f.read().split(":")]
        np.testing.assert_allclose(got, want, rtol=1e-5)


def test_two_process_pjit_via_host_agent(tmp_path):
    """Same SPMD map_fun, but launched through a real HostAgent daemon
    (LAUNCH/STATUS protocol) instead of LocalProcessBackend."""
    key = b"\x01" * 16
    agent = HostAgent(port=0, authkey=key)
    addr = agent.start()
    try:
        backend = AgentBackend([addr], authkey=key, worker_env=DIST_ENV)
        cluster = TPUCluster.run(
            cluster_funcs.fn_distributed_pjit_train, {"steps": 3},
            num_workers=2, working_dir=str(tmp_path), backend=backend,
            reservation_timeout=120)
        cluster.shutdown(timeout=240)
        backend.close()
    finally:
        agent.stop()

    want_loss, want_w = _expected_loss_and_w(steps=3)
    for nproc, ndev, loss, w in _read_results(tmp_path, 2):
        assert (nproc, ndev) == (2, 2)
        np.testing.assert_allclose(loss, want_loss, rtol=1e-5)
        np.testing.assert_allclose(w, want_w, rtol=1e-5, atol=1e-6)
