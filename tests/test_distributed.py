"""Multi-process ``jax.distributed`` integration tests.

The round-1 gap (VERDICT "What's missing" #3): ``NodeContext.
initialize_distributed`` was never exercised with ``num_processes > 1``.
These tests run the COMPOSED path — worker backends + reservation rendezvous
+ coordination service + cross-process collectives — on loopback with the
CPU backend (gloo), mirroring the reference's ``local-cluster[2,...]``
pattern (SURVEY.md §4).
"""

import numpy as np
import pytest

from tensorflowonspark_tpu.agent import AgentBackend, HostAgent
from tensorflowonspark_tpu.cluster import TPUCluster
from tests import cluster_funcs

pytestmark = pytest.mark.integration  # spawns worker processes + jax.distributed

# one CPU device per process → a 2-device global mesh over 2 processes
DIST_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}

# four CPU devices per process → an 8-device global mesh over 2 processes:
# the pod regime (multi-process AND multi-device, axes inside and across
# the process boundary) — VERDICT r2 missing #3
MULTIDEV_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
}


def _expected_loss_and_w(steps: int = 3, lr: float = 0.1):
    """The single-process value the 2-process run must reproduce."""
    rng = np.random.default_rng(0)
    X = rng.standard_normal((8, 4)).astype(np.float32)
    y = (X @ np.arange(1.0, 5.0, dtype=np.float32)).astype(np.float32)
    w = np.zeros(4, np.float32)
    for _ in range(steps):
        r = X @ w - y
        loss = float(np.mean(r**2))
        w = w - lr * (2.0 / len(y)) * (X.T @ r)
    return loss, w


def _read_results(working_dir, num_workers):
    out = []
    for i in range(num_workers):
        with open(f"{working_dir}/dist.{i}") as f:
            nproc, ndev, loss, w = f.read().split(":")
        out.append((int(nproc), int(ndev), float(loss),
                    np.array([float(v) for v in w.split(",")])))
    return out


def test_two_process_pjit_matches_single_process(tmp_path):
    cluster = TPUCluster.run(
        cluster_funcs.fn_distributed_pjit_train, {"steps": 3},
        num_workers=2, working_dir=str(tmp_path), worker_env=DIST_ENV,
        reservation_timeout=120)
    cluster.shutdown(timeout=240)

    want_loss, want_w = _expected_loss_and_w(steps=3)
    results = _read_results(tmp_path, 2)
    for nproc, ndev, loss, w in results:
        assert nproc == 2, "jax.distributed must span both worker processes"
        assert ndev == 2, "global mesh must see both processes' devices"
        np.testing.assert_allclose(loss, want_loss, rtol=1e-5)
        np.testing.assert_allclose(w, want_w, rtol=1e-5, atol=1e-6)


def test_two_process_pipeline_parallel_matches_oracle(tmp_path):
    """pp=2 across two processes: the GPipe ppermute rides a real process
    boundary; losses must match the single-process sequential oracle."""
    cluster = TPUCluster.run(
        cluster_funcs.fn_distributed_pipeline_train, {"steps": 2},
        num_workers=2, working_dir=str(tmp_path), worker_env=DIST_ENV,
        reservation_timeout=120)
    cluster.shutdown(timeout=240)

    # oracle: same math, sequential stages, one process
    import jax
    import jax.numpy as jnp
    import optax

    rng = np.random.default_rng(0)
    w0 = (rng.standard_normal((2, 8, 8)) * 0.1).astype(np.float32)
    x = jnp.asarray(rng.standard_normal((4, 8)).astype(np.float32))
    tx = optax.sgd(0.1)
    params = {"w": jnp.asarray(w0)}
    opt = tx.init(params)

    def loss_fn(p):
        h = x
        for i in range(2):
            h = h + jnp.tanh(h @ p["w"][i])
        return jnp.mean(h ** 2)

    want = []
    for _ in range(2):
        loss, g = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(g, opt, params)
        params = optax.apply_updates(params, upd)
        want.append(float(loss))

    for i in range(2):
        with open(f"{tmp_path}/pipe.{i}") as f:
            got = [float(v) for v in f.read().split(":")]
        np.testing.assert_allclose(got, want, rtol=1e-5)


def _mlp_oracle(steps: int = 3, lr: float = 0.1):
    """Single-process float32 oracle for ``fn_distributed_multidev_train``."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    X = rng.standard_normal((8, 4)).astype(np.float32)
    y = rng.standard_normal((8,)).astype(np.float32)
    W1 = (rng.standard_normal((4, 8)) * 0.5).astype(np.float32)
    W2 = (rng.standard_normal((8,)) * 0.5).astype(np.float32)

    @jax.jit
    def train_step(W1, W2):
        def loss_fn(W1, W2):
            h = jnp.tanh(X @ W1)
            return jnp.mean((h @ W2 - y) ** 2)

        loss, (g1, g2) = jax.value_and_grad(loss_fn, argnums=(0, 1))(W1, W2)
        return W1 - lr * g1, W2 - lr * g2, loss

    losses = []
    for _ in range(steps):
        W1, W2, loss = train_step(W1, W2)
        losses.append(float(loss))
    fp = float(jnp.sum(W1 ** 2) + jnp.sum(W2 ** 2))
    return losses, fp


@pytest.mark.parametrize("span", [False, True],
                         ids=["axes_inside_process", "tp_spans_processes"])
def test_two_process_four_device_gspmd(tmp_path, span):
    """2 processes × 4 devices: dp across processes with fsdp·tp inside,
    and the transposed layout where every tp pair SPANS the process
    boundary.  Parity against the single-process oracle either way."""
    cluster = TPUCluster.run(
        cluster_funcs.fn_distributed_multidev_train,
        {"steps": 3, "span_process_boundary": span},
        num_workers=2, working_dir=str(tmp_path), worker_env=MULTIDEV_ENV,
        reservation_timeout=120)
    cluster.shutdown(timeout=240)

    want_losses, want_fp = _mlp_oracle(steps=3)
    for i in range(2):
        with open(f"{tmp_path}/mdev.{i}") as f:
            nproc, ndev, losses, fp = f.read().split(":")
        assert (int(nproc), int(ndev)) == (2, 8)
        got = [float(v) for v in losses.split(",")]
        np.testing.assert_allclose(got, want_losses, rtol=1e-5)
        np.testing.assert_allclose(float(fp), want_fp, rtol=1e-5)


def test_two_process_hybrid_mesh(tmp_path):
    """make_hybrid_mesh's process_index slice fallback across a REAL
    process boundary: 2 procs × 4 devices, dp across the processes,
    fsdp·tp inside; parity with the single-process oracle."""
    cluster = TPUCluster.run(
        cluster_funcs.fn_distributed_hybrid_mesh_train, {"steps": 3},
        num_workers=2, working_dir=str(tmp_path), worker_env=MULTIDEV_ENV,
        reservation_timeout=120)
    cluster.shutdown(timeout=240)

    want_losses, want_fp = _mlp_oracle(steps=3)
    for i in range(2):
        with open(f"{tmp_path}/hybrid.{i}") as f:
            nproc, ndev, losses, fp = f.read().split(":")
        assert (int(nproc), int(ndev)) == (2, 8)
        got = [float(v) for v in losses.split(",")]
        np.testing.assert_allclose(got, want_losses, rtol=1e-5)
        np.testing.assert_allclose(float(fp), want_fp, rtol=1e-5)


def _pipeline_multidev_oracle(steps: int = 2):
    """Sequential single-device replay of ``fn_distributed_pipeline_
    multidev``'s math: the SAME ``make_transformer_stage`` stages (tp=1,
    every axis size 1 — psum/ring reduce to identity) applied one after
    the other, same adamw schedule."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from tensorflowonspark_tpu import compat
    from tensorflowonspark_tpu.parallel import (make_mesh,
                                                make_transformer_stage,
                                                stack_stage_params)
    from tensorflowonspark_tpu.parallel.mesh import MeshSpec

    hid, heads, ffn, seq, vocab = 32, 4, 64, 8, 64
    batch = 8
    mesh1 = make_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
    stage_fn, init_fn, _ = make_transformer_stage(hid, heads, ffn, tp=1,
                                                  causal=True)
    tx = optax.adamw(1e-3)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, vocab, (batch, seq)).astype(np.int32))

    def init_params():
        keys = jax.random.split(jax.random.key(0), 2)
        return {
            "emb": jax.random.normal(jax.random.key(1), (vocab, hid)) * 0.02,
            "stages": stack_stage_params([init_fn(k) for k in keys]),
        }

    params = jax.jit(init_params)()
    opt = tx.init(params)
    # check_vma=False: ring_attention's carry init mixes axis-varying and
    # invariant leaves when every axis is size 1 (pipeline_apply disables
    # the check for the same reason)
    run = compat.shard_map(
        lambda p0, p1, x: stage_fn(p1, stage_fn(p0, x)),
        mesh=mesh1, in_specs=(P(), P(), P()), out_specs=P(),
        check_vma=False)

    def loss_fn(p):
        x = p["emb"][ids]
        p0 = jax.tree.map(lambda a: a[0], p["stages"])
        p1 = jax.tree.map(lambda a: a[1], p["stages"])
        y = run(p0, p1, x)
        logits = jnp.einsum("bsh,vh->bsv", y, p["emb"])
        labels = jnp.roll(ids, -1, axis=1)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    want = []
    for _ in range(steps):
        loss, g = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(g, opt, params)
        params = optax.apply_updates(params, upd)
        want.append(float(loss))
    return want


def test_two_process_four_device_pipeline(tmp_path):
    """GPipe pp=2 across processes with Megatron-tp·dp-sharded stages
    (4 devices per stage) — stage-hop ppermute crosses the boundary, tp
    psums stay inside; parity with the sequential oracle."""
    cluster = TPUCluster.run(
        cluster_funcs.fn_distributed_pipeline_multidev, {"steps": 2},
        num_workers=2, working_dir=str(tmp_path), worker_env=MULTIDEV_ENV,
        reservation_timeout=120)
    cluster.shutdown(timeout=240)

    want = _pipeline_multidev_oracle(steps=2)
    for i in range(2):
        with open(f"{tmp_path}/mpipe.{i}") as f:
            got = [float(v) for v in f.read().split(":")]
        np.testing.assert_allclose(got, want, rtol=5e-4)


def test_two_process_pjit_via_host_agent(tmp_path):
    """Same SPMD map_fun, but launched through a real HostAgent daemon
    (LAUNCH/STATUS protocol) instead of LocalProcessBackend."""
    key = b"\x01" * 16
    agent = HostAgent(port=0, authkey=key)
    addr = agent.start()
    try:
        backend = AgentBackend([addr], authkey=key, worker_env=DIST_ENV)
        cluster = TPUCluster.run(
            cluster_funcs.fn_distributed_pjit_train, {"steps": 3},
            num_workers=2, working_dir=str(tmp_path), backend=backend,
            reservation_timeout=120)
        cluster.shutdown(timeout=240)
        backend.close()
    finally:
        agent.stop()

    want_loss, want_w = _expected_loss_and_w(steps=3)
    for nproc, ndev, loss, w in _read_results(tmp_path, 2):
        assert (nproc, ndev) == (2, 2)
        np.testing.assert_allclose(loss, want_loss, rtol=1e-5)
        np.testing.assert_allclose(w, want_w, rtol=1e-5, atol=1e-6)
