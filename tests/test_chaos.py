"""Chaos fault injection: plan grammar units + real kill/restore scenarios.

The integration tests here are the VERDICT-demanded demonstration that the
recovery story is a verified subsystem, not a claim: real worker processes
(LocalProcessBackend) self-apply ``TFOS_CHAOS`` faults mid-training and the
driver's ClusterMonitor must detect, classify, and abort — by process
observation and heartbeat staleness, not feed-socket luck (the map_funs
never touch the data feed: InputMode.TENSORFLOW).

The fast kill-detect / hang-watchdog / preemption tests stay in tier-1;
the full ``run_with_recovery`` kill-then-resume scenario (multiple cluster
boots + orbax round trips) carries ``-m slow``.
"""

import time

import pytest

from tensorflowonspark_tpu import chaos
from tensorflowonspark_tpu.chaos import ChaosPlanError, parse_plan
from tensorflowonspark_tpu.cluster import InputMode, TPUCluster
from tensorflowonspark_tpu.health import ClusterFailure
from tests import cluster_funcs as funcs


# ---------------------------------------------------------- plan grammar

def test_parse_plan_full_grammar():
    plan = parse_plan(
        "kill node=1 at_step=3; term node=2,at_step=4,grace=1.5;"
        "stall node=0 at_step=2 secs=9.5 ; drop node=3 after_secs=0.25;"
        "replace node=4 at_step=8 grace=30")
    assert [a.verb for a in plan] == ["kill", "term", "stall", "drop",
                                      "replace"]
    assert plan[0].node == 1 and plan[0].at_step == 3
    assert plan[1].grace == 1.5
    assert plan[2].secs == 9.5
    assert plan[3].after_secs == 0.25
    assert plan[4].node == 4 and plan[4].grace == 30
    assert plan[4].describe() == "replace node=4 at_step=8"
    assert [a.index for a in plan] == [0, 1, 2, 3, 4]


@pytest.mark.parametrize("bad", [
    "explode node=0 at_step=1",        # unknown verb
    "kill node=0",                     # no trigger
    "kill at_step=3",                  # no node
    "kill node=zero at_step=3",        # bad int
    "kill node=0 at_step=3 volume=11", # unknown key
    "kill node=0 at_step",             # not key=value
])
def test_parse_plan_rejects_malformed(bad):
    with pytest.raises(ChaosPlanError):
        parse_plan(bad)


@pytest.mark.parametrize("bad,tokens", [
    # bad action verb: the verb and the accepted set are both named
    ("explode node=0 at_step=1", ["'explode'", "kill"]),
    # missing node=: the whole offending action is quoted
    ("kill at_step=3", ["'kill at_step=3'", "node=<int>"]),
    # non-numeric at_step: key and offending value are both named
    ("kill node=0 at_step=soon", ["'at_step'", "'soon'"]),
    # non-numeric node
    ("kill node=zero at_step=3", ["'node'", "'zero'"]),
    # unknown key: key is named, known keys listed
    ("kill node=0 at_step=3 volume=11", ["'volume'", "at_step"]),
    # bare token with no '=': the token is quoted
    ("kill node=0 at_step", ["'at_step'", "key=value"]),
    # missing trigger: both trigger spellings offered
    ("kill node=0", ["at_step=", "after_secs="]),
])
def test_parse_plan_errors_are_single_line_and_name_the_token(bad, tokens):
    """A typo'd $TFOS_CHAOS plan must fail with a single-line error that
    names the offending token — it surfaces through a worker crash file,
    where a multi-line or vague message costs a round of debugging."""
    with pytest.raises(ChaosPlanError) as ei:
        parse_plan(bad)
    msg = str(ei.value)
    assert "\n" not in msg, f"multi-line chaos error: {msg!r}"
    for token in tokens:
        assert token in msg, f"error {msg!r} does not name {token!r}"


def test_parse_plan_error_names_offending_action_in_multiaction_plan():
    """Only the bad action is quoted, not the whole plan."""
    with pytest.raises(ChaosPlanError) as ei:
        parse_plan("kill node=0 at_step=1; stall node=1 at_step=nope")
    msg = str(ei.value)
    assert "\n" not in msg
    assert "'nope'" in msg
    assert "stall" in msg and "kill node=0" not in msg


def test_parse_plan_flap_grammar():
    plan = parse_plan("flap node=2 every=1.5 count=3")
    (a,) = plan
    assert a.verb == "flap" and a.node == 2
    assert a.every == 1.5 and a.count == 3
    assert a.describe() == "flap node=2 every=1.5 count=3"
    # count defaults to 1; every alone is the trigger
    (b,) = parse_plan("flap node=0 every=2")
    assert b.count is None and b.describe().endswith("count=1")


@pytest.mark.parametrize("bad,tokens", [
    # flap without its trigger: the required key is named
    ("flap node=0", ["'flap node=0'", "every=<secs>"]),
    ("flap node=0 count=2", ["every=<secs>"]),
    # non-numeric every/count: key and offending value are both named
    ("flap node=0 every=soon", ["'every'", "'soon'"]),
    ("flap node=0 every=1 count=lots", ["'count'", "'lots'"]),
    # zero/negative count
    ("flap node=0 every=1 count=0", ["count", ">= 1"]),
    # one-shot triggers on flap would silently drop every=/count=
    ("flap node=0 every=1 at_step=2", ["at_step=", "every="]),
    ("flap node=0 every=1 after_secs=3", ["after_secs=", "every="]),
    # flap-only keys leak onto other verbs
    ("kill node=0 at_step=3 every=1", ["flap-only"]),
    ("term node=0 at_step=3 count=2", ["flap-only"]),
])
def test_parse_plan_rejects_malformed_flap(bad, tokens):
    with pytest.raises(ChaosPlanError) as ei:
        parse_plan(bad)
    msg = str(ei.value)
    assert "\n" not in msg, f"multi-line chaos error: {msg!r}"
    for token in tokens:
        assert token in msg, f"error {msg!r} does not name {token!r}"


def test_flap_fires_once_per_incarnation_until_count_spent(tmp_path,
                                                          monkeypatch):
    """Each 'process incarnation' (a fresh ChaosAgent over the same
    sentinel dir, as a restarted attempt would build) delivers at most
    one flap kill after ``every`` seconds of uptime, and the ``.f<k>``
    sentinels bound the job-wide total at ``count``."""
    kills = []
    monkeypatch.setattr(chaos.ChaosAgent, "_fire_flap",
                        lambda self, a: kills.append(a.index))

    def incarnation(uptime):
        agent = chaos.ChaosAgent(parse_plan("flap node=0 every=5 count=2"),
                                 executor_id=0, state_dir=str(tmp_path))
        agent._armed_at -= uptime          # fast-forward this process
        return agent

    young = incarnation(uptime=1.0)
    young.on_tick()
    assert kills == []                     # not up for `every` yet

    a1 = incarnation(uptime=6.0)
    a1.on_tick()
    a1.on_tick()                           # same incarnation: no re-fire
    assert kills == [0]
    a2 = incarnation(uptime=6.0)           # the restarted replacement
    a2.on_tick()
    assert kills == [0, 0]
    a3 = incarnation(uptime=60.0)          # count=2 spent: disarmed
    a3.on_tick()
    assert kills == [0, 0]
    assert a3.flap_fired_count(a3.actions[0]) == 2


def test_from_env_filters_to_this_executor(monkeypatch, tmp_path):
    monkeypatch.setenv(chaos.PLAN_ENV, "kill node=1 at_step=3")
    assert chaos.from_env(0, state_dir=str(tmp_path)) is None  # not targeted
    agent = chaos.from_env(1, state_dir=str(tmp_path))
    assert agent is not None and agent.actions[0].verb == "kill"
    monkeypatch.delenv(chaos.PLAN_ENV)
    assert chaos.from_env(1, state_dir=str(tmp_path)) is None


def test_action_fires_once_per_job(tmp_path):
    """The sentinel file disarms an already-fired action across restarts —
    a static env plan must not re-kill every relaunched attempt."""
    calls = []
    agent = chaos.ChaosAgent(parse_plan("stall node=0 at_step=2"),
                             executor_id=0, state_dir=str(tmp_path))

    class Rep:
        def stall(self, secs=None):
            calls.append(secs)

    agent.attach(Rep())
    agent.on_step(1)
    assert calls == []
    agent.on_step(2)
    agent.on_step(3)
    assert calls == [None]  # fired exactly once
    assert chaos.fired_at(str(tmp_path), node=0) is not None

    # a relaunched attempt re-parses the same env: sentinel disarms it
    agent2 = chaos.ChaosAgent(parse_plan("stall node=0 at_step=2"),
                              executor_id=0, state_dir=str(tmp_path))
    agent2.attach(Rep())
    agent2.on_step(5)
    assert calls == [None]


# ------------------------------------------------------- driver scope

def test_parse_plan_driver_scope():
    (a,) = parse_plan("kill driver after_secs=0.5")
    assert a.verb == "kill" and a.node == chaos.DRIVER_NODE
    assert a.after_secs == 0.5
    assert a.describe() == "kill driver after_secs=0.5"
    # a mixed plan: worker agents filter the driver action out for free
    plan = parse_plan("kill node=1 at_step=3; kill driver after_secs=2")
    assert [a.node for a in plan] == [1, chaos.DRIVER_NODE]


@pytest.mark.parametrize("bad,tokens", [
    # only kill supports the driver scope
    ("stall driver secs=1 after_secs=1", ["'kill'", "driver"]),
    # the driver has no worker steps: at_step= is meaningless
    ("kill driver at_step=3", ["at_step", "after_secs="]),
    # driver actions need their wall-clock trigger
    ("kill driver", ["after_secs="]),
    # 'driver' and node= are mutually exclusive scopes
    ("kill node=2 driver after_secs=1", ["driver", "node="]),
])
def test_parse_plan_driver_rejections_are_single_line(bad, tokens):
    with pytest.raises(ChaosPlanError) as ei:
        parse_plan(bad)
    msg = str(ei.value)
    assert "\n" not in msg, f"multi-line chaos error: {msg!r}"
    for token in tokens:
        assert token in msg, f"error {msg!r} does not name {token!r}"


def test_driver_chaos_fires_once_with_sentinel(tmp_path):
    """DriverChaos fires its kill exactly once per job — the
    ``chaos.driver.<index>`` sentinel disarms a re-armed plan (a
    RESUMED driver re-runs the same env) and records the fired-at
    wall clock that failover-latency accounting reads back."""
    fired = []
    drv = chaos.DriverChaos(parse_plan("kill driver after_secs=0.05"),
                            on_fire=fired.append,
                            state_dir=str(tmp_path))
    drv.start()
    deadline = time.monotonic() + 5.0
    while not fired and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(fired) == 1 and fired[0].node == chaos.DRIVER_NODE
    t0 = chaos.fired_at(str(tmp_path), "driver")
    assert t0 is not None and abs(time.time() - t0) < 60
    # a resumed driver arms the SAME plan again: sentinel disarms it
    drv2 = chaos.DriverChaos(parse_plan("kill driver after_secs=0.01"),
                             on_fire=fired.append,
                             state_dir=str(tmp_path))
    drv2.start()
    time.sleep(0.3)
    drv2.stop()
    assert len(fired) == 1
    assert chaos.fired_at(str(tmp_path), "driver") == t0


def test_driver_from_env_filters_driver_actions(monkeypatch, tmp_path):
    monkeypatch.setenv(chaos.PLAN_ENV, "kill node=0 after_secs=9")
    assert chaos.driver_from_env(lambda a: None,
                                 state_dir=str(tmp_path)) is None
    monkeypatch.setenv(chaos.PLAN_ENV,
                       "kill node=0 after_secs=9; kill driver after_secs=5")
    drv = chaos.driver_from_env(lambda a: None, state_dir=str(tmp_path))
    assert drv is not None and len(drv.actions) == 1
    assert drv.actions[0].node == chaos.DRIVER_NODE
    drv.stop()
    monkeypatch.delenv(chaos.PLAN_ENV)
    assert chaos.driver_from_env(lambda a: None,
                                 state_dir=str(tmp_path)) is None


# ------------------------------------------------- kill/restore scenarios

pytestmark_integration = pytest.mark.integration


@pytest.mark.integration
def test_chaos_kill_detected_classified_fast(tmp_path):
    """SIGKILL one worker mid-training: the monitor must classify a crash
    in < 5 s from process observation alone — no feed socket exists to
    break (InputMode.TENSORFLOW), which was the only pre-existing
    steady-state signal."""
    cluster = TPUCluster.run(
        funcs.fn_report_steps, {"total_steps": 400, "step_secs": 0.05},
        num_workers=2, input_mode=InputMode.TENSORFLOW,
        reservation_timeout=60, working_dir=str(tmp_path),
        worker_env={"JAX_PLATFORMS": "cpu",
                    "TFOS_CHAOS": "kill node=1 at_step=3"},
        hang_timeout=60)
    failure = cluster.monitor.wait(timeout=30)
    assert failure is not None, "monitor never detected the SIGKILL"
    assert failure.kind == "crash"
    assert failure.failed_workers == (1,)

    fired = chaos.fired_at(str(tmp_path), node=1)
    assert fired is not None, "chaos sentinel missing"
    detection_secs = failure.detected_at - fired
    assert detection_secs < 5.0, f"detection took {detection_secs:.2f}s"

    with pytest.raises(ClusterFailure, match="crash"):
        cluster.shutdown(timeout=60)


@pytest.mark.integration
def test_chaos_stalled_heartbeat_aborted_as_hang(tmp_path):
    """A live process whose heartbeats stall (the wedged-collective shape)
    must be aborted within ~hang_timeout — not after shutdown's join
    timeout (the worker sleeps 120 s; the test must finish far sooner)."""
    t0 = time.monotonic()
    cluster = TPUCluster.run(
        funcs.fn_report_then_sleep, {"sleep_secs": 120},
        num_workers=1, input_mode=InputMode.TENSORFLOW,
        reservation_timeout=60, working_dir=str(tmp_path),
        worker_env={"JAX_PLATFORMS": "cpu",
                    "TFOS_CHAOS": "stall node=0 at_step=2"},
        hang_timeout=3.0, heartbeat_interval=0.25)
    failure = cluster.monitor.wait(timeout=30)
    assert failure is not None, "hang watchdog never fired"
    assert failure.kind == "hang"

    fired = chaos.fired_at(str(tmp_path), node=0)
    detection_secs = failure.detected_at - fired
    assert detection_secs < 10.0, f"hang detection took {detection_secs:.2f}s"

    with pytest.raises(ClusterFailure, match="hang"):
        cluster.shutdown(timeout=60)
    assert time.monotonic() - t0 < 60, "hang path waited on the join"


@pytest.mark.integration
def test_chaos_sigterm_classified_preemption(tmp_path):
    """An unguarded SIGTERM death is classified preemption (exit shape
    -SIGTERM), not crash — run_with_recovery treats both as retryable but
    operators alert on them differently."""
    cluster = TPUCluster.run(
        funcs.fn_report_steps, {"total_steps": 400, "step_secs": 0.05},
        num_workers=1, input_mode=InputMode.TENSORFLOW,
        reservation_timeout=60, working_dir=str(tmp_path),
        worker_env={"JAX_PLATFORMS": "cpu",
                    "TFOS_CHAOS": "term node=0 at_step=2"},
        hang_timeout=60)
    failure = cluster.monitor.wait(timeout=30)
    assert failure is not None and failure.kind == "preemption"
    with pytest.raises(ClusterFailure, match="preemption"):
        cluster.shutdown(timeout=60)


@pytest.mark.integration
def test_restart_budget_exhausted_emits_classified_event(tmp_path):
    """When run_with_recovery's sliding-window budget is exhausted, the
    give-up is OBSERVABLE before the re-raise: a classified
    ``budget_exhausted`` event in the job's health EventLog and a
    ``tfos_restarts_total{kind="budget_exhausted"}`` count — operators
    can tell "gave up" from "still retrying"."""
    import os

    from tensorflowonspark_tpu import metrics as tpu_metrics
    from tensorflowonspark_tpu.cluster import run_with_recovery
    from tensorflowonspark_tpu.observability import EventLog

    c = tpu_metrics.get_registry().counter("tfos_restarts_total",
                                           labelnames=("kind",))
    before = c.value(kind="budget_exhausted") or 0
    with pytest.raises(RuntimeError):
        run_with_recovery(
            funcs.fn_crash_infra, {}, num_workers=1,
            max_restarts=5, restart_budget=(0, 60.0), backoff_base=0.1,
            working_dir=str(tmp_path),
            worker_env={"JAX_PLATFORMS": "cpu"},
            reservation_timeout=60, shutdown_timeout=60)
    assert c.value(kind="budget_exhausted") == before + 1
    path = os.path.join(str(tmp_path), "health_events.jsonl")
    events = [e for e in EventLog.read(path)
              if e["kind"] == "budget_exhausted"]
    assert len(events) == 1, events
    assert events[0]["failure_kind"] == "infra"
    assert events[0]["max_restarts"] == 0
    assert events[0]["window_secs"] == 60.0


@pytest.mark.integration
@pytest.mark.slow
def test_flap_churn_exhausts_restart_budget(tmp_path):
    """Sustained churn end-to-end: a flapping worker (SIGKILL every
    incarnation after 1s, 3 kills total) burns run_with_recovery's
    restart budget — the driver retries the first kills, then gives up
    with the classified budget_exhausted signal."""
    import os

    from tensorflowonspark_tpu.cluster import run_with_recovery
    from tensorflowonspark_tpu.observability import EventLog

    restarts = []
    with pytest.raises(RuntimeError):
        run_with_recovery(
            funcs.fn_report_steps, {"total_steps": 400, "step_secs": 0.05},
            num_workers=1, max_restarts=5, restart_budget=(1, 300.0),
            backoff_base=0.1,
            on_restart=lambda attempt, exc, kind: restarts.append(kind),
            working_dir=str(tmp_path),
            worker_env={"JAX_PLATFORMS": "cpu",
                        "TFOS_CHAOS": "flap node=0 every=1 count=3"},
            reservation_timeout=60, shutdown_timeout=60, hang_timeout=60)
    assert restarts == ["crash"], restarts   # one retry, then budget gone
    flap_sentinels = [f for f in os.listdir(str(tmp_path))
                      if f.startswith("chaos.0.0.f")]
    assert len(flap_sentinels) >= 2, flap_sentinels
    events = [e["kind"] for e in EventLog.read(
        os.path.join(str(tmp_path), "health_events.jsonl"))]
    assert "budget_exhausted" in events


@pytest.mark.integration
@pytest.mark.slow
def test_chaos_kill_recovery_resumes_from_checkpoint(tmp_path):
    """End-to-end kill/restore: chaos SIGKILLs the chief at step 3,
    run_with_recovery relaunches with backoff, and the job completes with
    step numbers proving checkpoint resume (3 pre-kill + 3 resumed, not
    6 + 3) — the whole-job-restart recovery model, now under a real
    mid-training SIGKILL instead of an in-map_fun raise."""
    from tensorflowonspark_tpu.checkpoint import CheckpointManager
    from tensorflowonspark_tpu.cluster import run_with_recovery

    model_dir = str(tmp_path / "ckpt")
    restarts = []
    run_with_recovery(
        funcs.fn_train_ckpt_report,
        {"total_steps": 6, "model_dir": model_dir, "step_secs": 0.05},
        num_workers=2, max_restarts=2, backoff_base=0.2,
        on_restart=lambda attempt, exc, kind: restarts.append(kind),
        working_dir=str(tmp_path),
        worker_env={"JAX_PLATFORMS": "cpu",
                    "TFOS_CHAOS": "kill node=0 at_step=3"},
        reservation_timeout=60, shutdown_timeout=120, hang_timeout=60)

    assert restarts == ["crash"], restarts
    ckpt = CheckpointManager(model_dir)
    assert ckpt.latest_step() == 6
    assert float(ckpt.restore()["w"]) == 6.0  # 3 pre-kill + 3 resumed
    ckpt.close()

    with open(tmp_path / "resume.0") as f:
        starts = [line.split()[1] for line in f.read().splitlines()]
    assert starts[0] == "0", starts
    assert "3" in starts[1:], f"chief must resume from step 3, got {starts}"
