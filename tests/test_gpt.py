"""GPT decoder: causality, cached-decode equivalence, compiled generation.

Parametrized over ``scan_layers`` — the nn.scan(+remat) stacking and the
plain layer loop must be behaviorally identical (they differ only in the
parameter tree layout and compile/memory profile).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu.models.gpt import (GPT, GPTConfig, greedy_generate,
                                              init_cache, sample_generate)


def _cfg(scan_layers=False):
    return GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                     intermediate_size=64, max_position_embeddings=32,
                     dtype=jnp.float32, scan_layers=scan_layers,
                     remat=scan_layers)


CFG = _cfg()


def _params(cfg=CFG):
    model = GPT(cfg)
    ids = jnp.ones((2, 8), jnp.int32)
    return model.init(jax.random.key(0), ids)["params"]


@pytest.mark.parametrize("scan_layers", [False, True])
def test_forward_shape_and_causality(scan_layers):
    CFG = _cfg(scan_layers)
    params = _params(CFG)
    model = GPT(CFG)
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, CFG.vocab_size)
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 8, CFG.vocab_size)

    # changing a future token must not change past logits
    ids2 = ids.at[:, 5].set((ids[:, 5] + 1) % CFG.vocab_size)
    logits2 = model.apply({"params": params}, ids2)
    np.testing.assert_allclose(np.asarray(logits[:, :5]),
                               np.asarray(logits2[:, :5]), rtol=1e-5)
    assert not np.allclose(np.asarray(logits[:, 5:]),
                           np.asarray(logits2[:, 5:]))


def _assert_cached_decode_matches(cfg, params=None, seq_len=8, seed=2):
    """Shared oracle: token-by-token decode through the KV cache must
    reproduce the full-sequence logits for any config."""
    params = _params(cfg) if params is None else params
    ids = jax.random.randint(jax.random.key(seed), (2, seq_len), 0,
                             cfg.vocab_size)
    full = GPT(cfg).apply({"params": params}, ids)
    model = GPT(cfg, decode=True)
    cache = init_cache(cfg, params, batch=2)
    outs = []
    for t in range(seq_len):
        logits, vars_ = model.apply({"params": params, "cache": cache},
                                    ids[:, t:t + 1], mutable=["cache"])
        cache = vars_["cache"]
        outs.append(logits[:, 0])
    np.testing.assert_allclose(np.asarray(jnp.stack(outs, axis=1)),
                               np.asarray(full), rtol=2e-4, atol=2e-4)
    return params


@pytest.mark.parametrize("scan_layers", [False, True])
def test_cached_decode_matches_full_forward(scan_layers):
    cfg = _cfg(scan_layers)
    params = _assert_cached_decode_matches(cfg)
    if scan_layers:
        # params carry ONE stacked block, not per-layer copies
        assert "layers" in params and "layer_0" not in params
        assert jax.tree.leaves(params["layers"])[0].shape[0] == cfg.num_layers


@pytest.mark.parametrize("scan_layers", [False, True])
def test_train_gradients_flow(scan_layers):
    """value_and_grad through the (possibly remat'd scan) stack: finite
    loss, nonzero grads for every parameter."""
    cfg = _cfg(scan_layers)
    params = _params(cfg)
    ids = jax.random.randint(jax.random.key(5), (2, 8), 0, cfg.vocab_size)

    def loss_fn(p):
        import optax

        logits = GPT(cfg).apply({"params": p}, ids)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], ids[:, 1:]).mean()

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
    assert np.isfinite(float(loss))
    norms = [float(jnp.abs(g).sum()) for g in jax.tree.leaves(grads)]
    assert all(n > 0 for n in norms), "dead gradient leaf"


@pytest.mark.parametrize("scan_layers", [False, True])
@pytest.mark.parametrize("train", [False, True])
def test_remat_gradients_both_stackings(scan_layers, train):
    """Regression for the loop-branch remat bug (r5 sweep
    ``gpt_train_b32_remat``): ``nn.remat(DecoderBlock)`` without
    ``static_argnums`` traced the ``train`` kwarg, and the ``not train``
    dropout toggle raised ``TracerBoolConversionError`` under jit.
    ``remat=True`` must differentiate on BOTH stacking branches, with
    ``train`` taking both static values."""
    import dataclasses

    cfg = dataclasses.replace(_cfg(scan_layers), remat=True)
    params = _params(cfg)
    ids = jax.random.randint(jax.random.key(5), (2, 8), 0, cfg.vocab_size)

    def loss_fn(p):
        import optax

        logits = GPT(cfg).apply(
            {"params": p}, ids, train=train,
            rngs={"dropout": jax.random.key(7)} if train else None)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], ids[:, 1:]).mean()

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
    assert np.isfinite(float(loss))
    norms = [float(jnp.abs(g).sum()) for g in jax.tree.leaves(grads)]
    assert all(n > 0 for n in norms), "dead gradient leaf"


@pytest.mark.parametrize("scan_layers", [False, True])
def test_cached_prefill_matches_full_forward(scan_layers):
    """Prefill through the decode path (whole prompt at once) == full."""
    CFG = _cfg(scan_layers)
    params = _params(CFG)
    ids = jax.random.randint(jax.random.key(3), (2, 6), 0, CFG.vocab_size)
    full = GPT(CFG).apply({"params": params}, ids)
    model = GPT(CFG, decode=True)
    cache = init_cache(CFG, params, batch=2)
    logits, _ = model.apply({"params": params, "cache": cache}, ids,
                            mutable=["cache"])
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("scan_layers", [False, True])
def test_greedy_generate_matches_naive_rollout(scan_layers):
    cfg = _cfg(scan_layers)
    params = _params(cfg)
    prompt = jax.random.randint(jax.random.key(4), (2, 4), 0, cfg.vocab_size)
    out = jax.jit(greedy_generate, static_argnums=(0, 3))(
        cfg, params, prompt, 5)
    assert out.shape == (2, 9)
    np.testing.assert_array_equal(np.asarray(out[:, :4]), np.asarray(prompt))

    # naive rollout: recompute the whole sequence each step, take argmax
    model = GPT(cfg)
    ids = prompt
    for _ in range(5):
        logits = model.apply({"params": params}, ids)
        ids = jnp.concatenate(
            [ids, jnp.argmax(logits[:, -1:], axis=-1)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ids))


def test_sample_generate_limits_and_reproducibility():
    params = _params()
    prompt = jax.random.randint(jax.random.key(6), (2, 4), 0, CFG.vocab_size)
    key = jax.random.key(7)

    # temperature -> 0 is exactly greedy
    greedy = greedy_generate(CFG, params, prompt, 4)
    cold = sample_generate(CFG, params, prompt, 4, key, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(cold), np.asarray(greedy))

    # top_k=1 is greedy regardless of temperature
    k1 = sample_generate(CFG, params, prompt, 4, key, temperature=2.0, top_k=1)
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(greedy))

    # same key -> same rollout; different key -> (almost surely) different
    a = sample_generate(CFG, params, prompt, 8, key, temperature=5.0)
    b = sample_generate(CFG, params, prompt, 8, key, temperature=5.0)
    c = sample_generate(CFG, params, prompt, 8, jax.random.key(8),
                        temperature=5.0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))

    import pytest

    with pytest.raises(ValueError, match="temperature"):
        sample_generate(CFG, params, prompt, 4, key, temperature=-1.0)


def test_generate_bounds_and_zero_tokens():
    import pytest

    params = _params()
    prompt = jnp.ones((1, 4), jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(greedy_generate(CFG, params, prompt, 0)),
        np.asarray(prompt))
    with pytest.raises(ValueError, match="exceeds max_position_embeddings"):
        greedy_generate(CFG, params, prompt, CFG.max_position_embeddings)


def test_tp_partitioning_annotations_present():
    params = _params()
    q = params["layer_0"]["attn"]["query"]["kernel"]
    assert getattr(q, "names", None) == (None, "tp")


def test_generate_with_tp_sharded_params_matches_single_device():
    """Distributed inference: params placed on a tp=2 mesh (flax
    partitioning annotations -> GSPMD), generation must be identical to
    the unsharded run — 'same module, one chip or a mesh'."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorflowonspark_tpu.parallel import make_mesh
    from tensorflowonspark_tpu.parallel.mesh import MeshSpec
    from tensorflowonspark_tpu.parallel.sharding import flax_shardings

    params = _params()
    prompt = jax.random.randint(jax.random.key(9), (2, 4), 0, CFG.vocab_size)
    want = greedy_generate(CFG, params, prompt, 6)

    mesh = make_mesh(MeshSpec(tp=2, dp=1), devices=jax.devices()[:2])
    model = GPT(CFG)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.ones((2, 8), jnp.int32)))
    shardings = flax_shardings(mesh, abstract)["params"]
    placed = jax.device_put(params, shardings)
    # annotated kernels actually shard over tp (unwrap the flax box)
    q = placed["layer_0"]["attn"]["query"]["kernel"]
    q = getattr(q, "value", q)
    assert q.sharding.spec == P(None, "tp")
    assert q.addressable_shards[0].data.shape[1] == q.shape[1] // 2

    with mesh:
        got = greedy_generate(CFG, placed, prompt, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("scan_layers", [False, True])
def test_int8_kv_cache_decode_tracks_bf16_cache(scan_layers):
    """kv_cache_int8=True: same params, the quantized cache's greedy tokens
    must match the full-precision cache's (tiny model, wide margins)."""
    import dataclasses

    cfg = _cfg(scan_layers)
    params = _params(cfg)
    prompt = jax.random.randint(jax.random.key(5), (2, 6), 0, cfg.vocab_size)
    want = greedy_generate(cfg, params, prompt, 8)

    qcfg = dataclasses.replace(cfg, kv_cache_int8=True)
    got = greedy_generate(qcfg, params, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    # the cache collection really holds int8 values
    cache = init_cache(qcfg, params, batch=2)
    assert any(v.dtype == jnp.int8 for v in jax.tree.leaves(cache))


def test_int8_kv_cache_halves_cache_bytes():
    import dataclasses

    cfg = _cfg()
    params = _params(cfg)
    full = init_cache(cfg, params, batch=2)
    quant = init_cache(dataclasses.replace(cfg, kv_cache_int8=True),
                       params, batch=2)

    def nbytes(tree):
        return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))

    # fp32 test dtype -> int8 values are 4x smaller; the per-(pos, head)
    # fp32 scales cost 4/D extra bytes per value — large at this toy D=8,
    # ~6% at a real D=64 (where the ratio approaches 0.27)
    assert nbytes(quant) < 0.4 * nbytes(full), (nbytes(quant), nbytes(full))


class TestBeamSearch:
    @pytest.mark.parametrize("scan_layers", [False, True])
    def test_one_beam_equals_greedy(self, scan_layers):
        from tensorflowonspark_tpu.models.gpt import beam_generate

        CFG = _cfg(scan_layers)
        params = _params(CFG)
        prompt = jax.random.randint(jax.random.key(7), (2, 5), 0,
                                    CFG.vocab_size)
        want = greedy_generate(CFG, params, prompt, 7)
        got = beam_generate(CFG, params, prompt, 7, num_beams=1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("scan_layers", [False, True])
    def test_wider_beam_never_scores_below_greedy(self, scan_layers):
        from tensorflowonspark_tpu.models.gpt import beam_generate

        CFG = _cfg(scan_layers)
        params = _params(CFG)
        prompt = jax.random.randint(jax.random.key(8), (3, 4), 0,
                                    CFG.vocab_size)
        N = 6
        model = GPT(CFG)

        def seq_logprob(full):
            logits = model.apply({"params": params}, full)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            # score of the N generated tokens given their prefixes
            idx = jnp.arange(full.shape[1] - N - 1, full.shape[1] - 1)
            tgt = full[:, idx + 1]
            picked = jnp.take_along_axis(logp[:, idx], tgt[:, :, None],
                                         axis=-1)[..., 0]
            return picked.sum(-1)

        greedy = greedy_generate(CFG, params, prompt, N)
        beam, scores = beam_generate(CFG, params, prompt, N, num_beams=4,
                                     return_scores=True)
        sg = np.asarray(seq_logprob(greedy))
        sb = np.asarray(seq_logprob(beam))
        assert np.all(sb >= sg - 1e-4), (sb, sg)
        # reported scores agree with an independent full-forward rescoring
        np.testing.assert_allclose(np.asarray(scores), sb, rtol=1e-4,
                                   atol=1e-4)

    def test_eos_freezes_beam(self):
        from tensorflowonspark_tpu.models.gpt import beam_generate

        params = _params()
        prompt = jax.random.randint(jax.random.key(9), (2, 4), 0,
                                    CFG.vocab_size)
        out = beam_generate(CFG, params, prompt, 10, num_beams=3,
                            eos_id=0)
        gen = np.asarray(out)[:, 4:]
        for row in gen:
            hits = np.where(row == 0)[0]
            if len(hits):  # after the first EOS, only EOS (frozen beam)
                assert np.all(row[hits[0]:] == 0), row

    def test_out_of_range_eos_raises(self):
        from tensorflowonspark_tpu.models.gpt import beam_generate

        params = _params()
        prompt = jnp.zeros((1, 3), jnp.int32)
        with pytest.raises(ValueError, match="eos_id"):
            beam_generate(CFG, params, prompt, 4, eos_id=CFG.vocab_size)
        with pytest.raises(ValueError, match="eos_id"):
            beam_generate(CFG, params, prompt, 4, eos_id=-1)

    def test_length_penalty_selection(self):
        # length_penalty=1.0 selects by mean logprob; with no EOS all
        # lengths equal so selection must match the default raw-sum pick
        from tensorflowonspark_tpu.models.gpt import beam_generate

        params = _params()
        prompt = jax.random.randint(jax.random.key(11), (2, 4), 0,
                                    CFG.vocab_size)
        raw, s_raw = beam_generate(CFG, params, prompt, 6, num_beams=3,
                                   return_scores=True)
        lp, s_lp = beam_generate(CFG, params, prompt, 6, num_beams=3,
                                 length_penalty=1.0, return_scores=True)
        np.testing.assert_array_equal(np.asarray(raw), np.asarray(lp))
        np.testing.assert_allclose(np.asarray(s_raw), np.asarray(s_lp),
                                   rtol=1e-5)
        # and with an EOS the penalized run still returns a valid beam
        out = beam_generate(CFG, params, prompt, 8, num_beams=3, eos_id=0,
                            length_penalty=1.0)
        assert out.shape == (2, 4 + 8)

    def test_length_penalty_flips_selection(self):
        # deterministic case where per-length normalization reverses the
        # raw-sum pick: beam 0 has the better sum but a much longer
        # sequence's mean beats it after dividing by generated length
        from tensorflowonspark_tpu.models.gpt import _select_beam

        scores = jnp.array([[-4.0, -4.5]])
        lengths = jnp.array([[2, 8]])
        assert int(_select_beam(scores, lengths, 0.0)[0]) == 0
        # -4/2=-2.0 vs -4.5/8=-0.5625 -> penalized picks beam 1
        assert int(_select_beam(scores, lengths, 1.0)[0]) == 1
        # modern-HF generated-only normalization (prompt EXCLUDED): the
        # review's canonical example — old full-length (T0=10) HF picked
        # beam 0 (-5/15 vs -9/20); transformers >= 4.38 picks beam 1
        scores2 = jnp.array([[-5.0, -9.0]])
        lengths2 = jnp.array([[5, 10]])
        assert int(_select_beam(scores2, lengths2, 1.0)[0]) == 1


class TestGroupedQueryAttention:
    @pytest.mark.parametrize("kv_heads", [1, 2])
    def test_gqa_cached_decode_matches_full_forward(self, kv_heads):
        import dataclasses

        _assert_cached_decode_matches(
            dataclasses.replace(_cfg(), num_kv_heads=kv_heads), seq_len=10)

    def test_gqa_shrinks_cache_and_generates(self):
        import dataclasses

        base = _cfg()
        gqa = dataclasses.replace(base, num_kv_heads=1)  # MQA: 4x smaller
        p_gqa = GPT(gqa).init(jax.random.key(0),
                              jnp.ones((1, 8), jnp.int32))["params"]

        def nbytes(tree):
            return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))

        c_base = init_cache(base, _params(base), batch=2)
        c_gqa = init_cache(gqa, p_gqa, batch=2)
        assert nbytes(c_gqa) < 0.3 * nbytes(c_base)

        out = greedy_generate(gqa, p_gqa, jnp.ones((2, 4), jnp.int32), 6)
        assert out.shape == (2, 10)

    def test_gqa_with_int8_kv_and_beam(self):
        import dataclasses

        from tensorflowonspark_tpu.models.gpt import beam_generate

        cfg = dataclasses.replace(_cfg(), num_kv_heads=2, kv_cache_int8=True)
        params = GPT(cfg).init(jax.random.key(0),
                               jnp.ones((1, 8), jnp.int32))["params"]
        prompt = jax.random.randint(jax.random.key(2), (2, 4), 0,
                                    cfg.vocab_size)
        want = greedy_generate(cfg, params, prompt, 6)
        got = beam_generate(cfg, params, prompt, 6, num_beams=1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_bad_kv_heads_raises(self):
        import dataclasses

        cfg = dataclasses.replace(_cfg(), num_kv_heads=3)  # 4 % 3 != 0
        with pytest.raises(ValueError, match="divisible"):
            GPT(cfg).init(jax.random.key(0), jnp.ones((1, 4), jnp.int32))


    def test_gqa_dense_matches_custom_attention_fn(self):
        """The attention_fn broadcast path (jnp.repeat of K/V) must agree
        with the grouped-einsum dense path — head-order parity."""
        import dataclasses

        def dense_attn(q, k, v, mask=None, causal=False):
            s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                           k.astype(jnp.float32)) / (q.shape[-1] ** 0.5)
            if causal:
                pos = jnp.arange(q.shape[1])
                s = jnp.where((pos[:, None] >= pos[None, :])[None, None],
                              s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))

        base = dataclasses.replace(_cfg(), num_kv_heads=2)
        withfn = dataclasses.replace(base, attention_fn=dense_attn)
        ids = jax.random.randint(jax.random.key(0), (2, 8), 0,
                                 base.vocab_size)
        params = GPT(base).init(jax.random.key(1), ids)["params"]
        np.testing.assert_allclose(
            np.asarray(GPT(withfn).apply({"params": params}, ids)),
            np.asarray(GPT(base).apply({"params": params}, ids)),
            rtol=2e-4, atol=2e-4)


class TestRoPE:
    @pytest.mark.parametrize("scan_layers", [False, True])
    def test_rope_cached_decode_matches_full_forward(self, scan_layers):
        import dataclasses

        cfg = dataclasses.replace(_cfg(scan_layers), pos_encoding="rope")
        params = _assert_cached_decode_matches(cfg, seq_len=9)
        assert "pos_emb" not in params  # no position table under rope

    def test_rope_relative_shift_invariance(self):
        """RoPE scores depend on relative distance only: rotating q/k at
        positions p and p+s must give identical q·k for any shift s."""
        from tensorflowonspark_tpu.models.gpt import _rope

        q = jax.random.normal(jax.random.key(0), (1, 6, 2, 16))
        k = jax.random.normal(jax.random.key(1), (1, 6, 2, 16))

        def scores(shift):
            pos = jnp.arange(6) + shift
            qr = _rope(q, pos, 10000.0)
            kr = _rope(k, pos, 10000.0)
            return jnp.einsum("bqhd,bkhd->bhqk", qr, kr)

        np.testing.assert_allclose(np.asarray(scores(0)),
                                   np.asarray(scores(37)), rtol=1e-4,
                                   atol=1e-4)

    def test_rope_generation_and_beam(self):
        import dataclasses

        from tensorflowonspark_tpu.models.gpt import beam_generate

        cfg = dataclasses.replace(_cfg(), pos_encoding="rope",
                                  num_kv_heads=2, kv_cache_int8=True)
        params = GPT(cfg).init(jax.random.key(0),
                               jnp.ones((1, 8), jnp.int32))["params"]
        prompt = jax.random.randint(jax.random.key(2), (2, 4), 0,
                                    cfg.vocab_size)
        want = greedy_generate(cfg, params, prompt, 6)
        got = beam_generate(cfg, params, prompt, 6, num_beams=1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


    def test_bad_pos_encoding_and_odd_head_dim_raise(self):
        import dataclasses

        with pytest.raises(ValueError, match="pos_encoding"):
            dataclasses.replace(_cfg(), pos_encoding="rotary")
        with pytest.raises(ValueError, match="even head_dim"):
            GPTConfig(hidden_size=40, num_heads=8, pos_encoding="rope")


class TestLlamaStyleConfig:
    def _llama_cfg(self, scan_layers=False):
        return GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                         num_heads=4, num_kv_heads=2, intermediate_size=48,
                         max_position_embeddings=32, dtype=jnp.float32,
                         pos_encoding="rope", norm="rmsnorm", mlp="swiglu",
                         scan_layers=scan_layers, remat=scan_layers)

    @pytest.mark.parametrize("scan_layers", [False, True])
    def test_cached_decode_matches_full_forward(self, scan_layers):
        _assert_cached_decode_matches(self._llama_cfg(scan_layers))

    def test_param_structure_and_grads(self):
        import optax

        cfg = self._llama_cfg()
        model = GPT(cfg)
        ids = jax.random.randint(jax.random.key(0), (2, 8), 0,
                                 cfg.vocab_size)
        params = model.init(jax.random.key(1), ids)["params"]
        block = params["layer_0"]
        assert "mlp_gate" in block and "mlp_up" in block
        assert "scale" in block["ln1"] and "bias" not in block["ln1"]  # RMS

        def loss(p):
            logits = model.apply({"params": p}, ids)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], ids[:, 1:]).mean()

        g = jax.grad(loss)(params)
        flat = jax.tree.leaves(g)
        assert all(bool(jnp.all(jnp.isfinite(x))) for x in flat)
        assert any(float(jnp.abs(x).sum()) > 0 for x in flat)

    def test_bad_norm_or_mlp_raises(self):
        with pytest.raises(ValueError, match="norm"):
            GPTConfig(norm="batchnorm")
        with pytest.raises(ValueError, match="mlp"):
            GPTConfig(mlp="relu")


class TestSlidingWindow:
    def test_windowed_decode_matches_full_forward(self):
        import dataclasses

        cfg = dataclasses.replace(_cfg(), sliding_window=5,
                                  pos_encoding="rope", num_kv_heads=2)
        _assert_cached_decode_matches(cfg, seq_len=12)

    def test_window_changes_long_range_attention(self):
        import dataclasses

        cfg = _cfg()
        wcfg = dataclasses.replace(cfg, sliding_window=2)
        params = _params(cfg)
        ids = jax.random.randint(jax.random.key(0), (1, 12), 0,
                                 cfg.vocab_size)
        full = GPT(cfg).apply({"params": params}, ids)
        local = GPT(wcfg).apply({"params": params}, ids)
        # early positions (inside any window) agree; late ones differ
        np.testing.assert_allclose(np.asarray(full[:, :2]),
                                   np.asarray(local[:, :2]), rtol=1e-5)
        assert not np.allclose(np.asarray(full[:, 6:]),
                               np.asarray(local[:, 6:]))

    def test_windowed_dense_matches_flash_kernel(self):
        import dataclasses

        from tensorflowonspark_tpu.ops import flash_attention

        cfg = dataclasses.replace(_cfg(), sliding_window=6)
        withfn = dataclasses.replace(
            cfg, attention_fn=lambda q, k, v, mask=None, causal=False,
            window=None: flash_attention(q, k, v, causal=causal,
                                         window=window, block_q=16,
                                         block_k=16))
        ids = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                 cfg.vocab_size)
        params = _params(cfg)
        np.testing.assert_allclose(
            np.asarray(GPT(withfn).apply({"params": params}, ids)),
            np.asarray(GPT(cfg).apply({"params": params}, ids)),
            rtol=2e-4, atol=2e-4)

    def test_bad_window_raises(self):
        with pytest.raises(ValueError, match="sliding_window"):
            GPTConfig(sliding_window=0)


    def test_window_with_incompatible_attention_fn_raises(self):
        import dataclasses

        def no_window_attn(q, k, v, mask=None, causal=False):
            raise AssertionError("should not be called")

        cfg = dataclasses.replace(_cfg(), sliding_window=4,
                                  attention_fn=no_window_attn)
        with pytest.raises(ValueError, match="window= kwarg"):
            GPT(cfg).init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))


    @pytest.mark.parametrize("int8", [False, True])
    def test_rolling_cache_generation_matches_full_cache(self, int8):
        """Rolling buffer (cache size = window) must generate the exact
        same tokens as the full-length cache under the same window."""
        import dataclasses

        base = dataclasses.replace(_cfg(), sliding_window=5,
                                   pos_encoding="rope", kv_cache_int8=int8)
        rolled = dataclasses.replace(base, rolling_kv_cache=True)
        params = _params(base)
        prompt = jax.random.randint(jax.random.key(4), (2, 9), 0,
                                    base.vocab_size)
        want = greedy_generate(base, params, prompt, 10)
        got = greedy_generate(rolled, params, prompt, 10)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_rolling_cache_is_window_sized(self):
        import dataclasses

        cfg = dataclasses.replace(_cfg(), sliding_window=4,
                                  rolling_kv_cache=True)
        params = _params(cfg)
        cache = init_cache(cfg, params, batch=2)
        k = cache["layer_0"]["attn"]["k"]
        assert k.shape[1] == 4  # window slots, not max_position_embeddings

    def test_rolling_requires_window(self):
        with pytest.raises(ValueError, match="rolling_kv_cache"):
            GPTConfig(rolling_kv_cache=True)


class TestTopP:
    def test_top_p_one_equals_plain_sampling(self):
        params = _params()
        prompt = jnp.ones((2, 4), jnp.int32)
        a = sample_generate(CFG, params, prompt, 6, jax.random.key(0),
                            temperature=0.9)
        b = sample_generate(CFG, params, prompt, 6, jax.random.key(0),
                            temperature=0.9, top_p=1.0)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_tiny_top_p_is_greedy(self):
        # a nucleus so small only the argmax survives -> greedy rollout
        params = _params()
        prompt = jnp.ones((2, 4), jnp.int32)
        want = greedy_generate(CFG, params, prompt, 6)
        got = sample_generate(CFG, params, prompt, 6, jax.random.key(3),
                              temperature=1.0, top_p=1e-6)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_top_p_restricts_support(self):
        # sampled tokens must come from each row's exact nucleus (the
        # smallest sorted prefix whose mass reaches top_p)
        params = _params()
        prompt = jnp.ones((3, 4), jnp.int32)
        logits = np.asarray(GPT(CFG).apply({"params": params}, prompt)[:, -1])

        def nucleus(row, p):
            order = np.argsort(row)[::-1]
            probs = np.exp(row - row.max())
            probs /= probs.sum()
            cum = np.cumsum(probs[order])
            keep = (cum - probs[order]) < p  # mass before the token
            return set(order[keep].tolist())

        nuclei = [nucleus(row, 0.1) for row in logits]
        for seed in range(8):
            out = sample_generate(CFG, params, prompt, 1,
                                  jax.random.key(seed), top_p=0.1)
            first = np.asarray(out)[:, -1]
            for b, t in enumerate(first):
                assert int(t) in nuclei[b], (b, int(t), nuclei[b])

    def test_top_p_validation(self):
        with pytest.raises(ValueError, match="top_p"):
            sample_generate(CFG, _params(), jnp.ones((1, 2), jnp.int32), 2,
                            jax.random.key(0), top_p=0.0)


class TestLookupGenerate:
    """Prompt-lookup speculative decoding: greedy-exact, fewer forwards."""

    def _mk(self, **kw):
        import dataclasses

        cfg = dataclasses.replace(
            _cfg(), max_position_embeddings=128, **kw)
        params = GPT(cfg).init(jax.random.key(0),
                               jnp.ones((1, 4), jnp.int32))["params"]
        return cfg, params

    @pytest.mark.parametrize("pos_encoding", ["learned", "rope"])
    @pytest.mark.parametrize("batch", [1, 3])
    def test_matches_greedy_exactly(self, pos_encoding, batch):
        from tensorflowonspark_tpu.models import lookup_generate

        cfg, params = self._mk(
            pos_encoding=pos_encoding,
            norm="rmsnorm" if pos_encoding == "rope" else "layernorm")
        prompt = jax.random.randint(jax.random.key(7), (batch, 10), 0,
                                    cfg.vocab_size)
        want = greedy_generate(cfg, params, prompt, 24)
        got = lookup_generate(cfg, params, prompt, 24)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_matches_greedy_with_scan_layers(self):
        """Stacked [num_layers] cache index leaves must rewind too."""
        from tensorflowonspark_tpu.models import lookup_generate

        cfg, params = self._mk(scan_layers=True)
        prompt = jax.random.randint(jax.random.key(13), (2, 10), 0,
                                    cfg.vocab_size)
        want = greedy_generate(cfg, params, prompt, 20)
        got = lookup_generate(cfg, params, prompt, 20)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_fewer_forwards_on_repetitive_text(self):
        from tensorflowonspark_tpu.models import lookup_generate

        cfg, params = self._mk()
        rep = jnp.tile(jnp.arange(6), 5)[None, :]
        want = greedy_generate(cfg, params, rep, 30)
        got, stats = lookup_generate(cfg, params, rep, 30,
                                     return_stats=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # the point of speculation: well under one forward per token
        assert int(stats["forwards"]) <= 15

    def test_composes_with_gqa_and_int8(self):
        from tensorflowonspark_tpu.models import lookup_generate
        from tensorflowonspark_tpu.ops import quantize_params

        cfg, params = self._mk(num_kv_heads=2)
        qp = quantize_params(params)
        prompt = jax.random.randint(jax.random.key(11), (2, 8), 0,
                                    cfg.vocab_size)
        want = greedy_generate(cfg, qp, prompt, 16)
        got = lookup_generate(cfg, qp, prompt, 16)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_jits_as_one_program(self):
        import functools

        from tensorflowonspark_tpu.models import lookup_generate

        cfg, params = self._mk()
        gen = jax.jit(functools.partial(lookup_generate, ngram=2,
                                        draft_len=4),
                      static_argnums=(0, 3))
        prompt = jax.random.randint(jax.random.key(2), (1, 10), 0,
                                    cfg.vocab_size)
        want = greedy_generate(cfg, params, prompt, 12)
        np.testing.assert_array_equal(
            np.asarray(gen(cfg, params, prompt, 12)), np.asarray(want))

    def test_guards(self):
        import dataclasses

        from tensorflowonspark_tpu.models import lookup_generate

        cfg, params = self._mk()
        prompt = jnp.ones((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="draft_len"):
            lookup_generate(cfg, params, prompt, 8, draft_len=0)
        with pytest.raises(ValueError, match="max_position_embeddings"):
            lookup_generate(cfg, params, prompt, 124)
        rcfg = dataclasses.replace(cfg, sliding_window=16,
                                   rolling_kv_cache=True)
        with pytest.raises(ValueError, match="rolling_kv_cache"):
            lookup_generate(rcfg, params, prompt, 8)
