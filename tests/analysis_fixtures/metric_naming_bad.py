"""Positive fixture: metric registrations violating the catalog naming."""
from tensorflowonspark_tpu.metrics import Counter, Histogram, get_registry

reg = get_registry()

# missing tfos_ prefix
requests = reg.counter("serving_requests_total", "no prefix")

# counter without the _total suffix
steps = reg.counter("tfos_replica_steps", "no unit suffix")

# gauge without any unit suffix
depth = reg.gauge("tfos_queue_depth", "no unit suffix")

# not snake case (uppercase)
latency = reg.histogram("tfos_TTFT_seconds", "not lowercase")

# direct constructors imported from the metrics module are checked too
bad_direct = Counter("plainname_total")
bad_hist = Histogram("tfos_latency_millis")

# chained off the factory (no intermediate name) is still a registration
chained = get_registry().counter("tfos_chained_registrations")

# gauges must NOT borrow the counter suffix — *_total reads as monotonic
fake_counter = reg.gauge("tfos_live_conns_total", "not a counter")
