"""wire-protocol negative fixture: matched ends, wildcard honesty, and
the guard-tail + incremental-field idioms all stay clean."""

OP_STATS = "stats"


def send_generate(send, conn, prompt):
    msg = {"op": "generate", "prompt": prompt}
    msg["max_new_tokens"] = 64  # incremental field: counts as set
    send(conn, msg)


def send_stats(send, conn):
    send(conn, {"op": OP_STATS})


def send_gang(send, conn, event):
    # dynamic event: the producer is honest about not being indexable,
    # so event-refined handlers of "gang" are not findings
    send(conn, {"op": "gang", "event": event, "seq": 1})


def send_done(emit, rid):
    emit({"event": "done", "rid": rid})


def serve(recv, send, conn):
    while True:
        msg = recv(conn)
        op = msg.get("op") if isinstance(msg, dict) else None
        if op == "generate":
            send(conn, (msg["prompt"], msg.get("max_new_tokens")))
        elif op == "stats":
            send(conn, "ok")
        elif op == "gang":
            if msg.get("event") == "barrier":
                send(conn, "ack")


def wait_ack(recv, conn, want):
    while True:
        msg = recv(conn)
        # comparing against a non-literal consumes every event of "gang"
        if msg.get("op") == "gang" and msg.get("event") == want:
            return msg


def pump(q):
    while True:
        item = q.get(timeout=1)
        if item.get("op") != "generate":
            continue
        # guard-tail handler: these reads belong to op "generate"
        return item["prompt"]


def drain(events):
    for e in events:
        ev = e.get("event")
        if ev == "done":
            return e["rid"]
