"""Negative fixture: the lifecycles the rule wants to see."""
import socket
import threading
from multiprocessing.shared_memory import SharedMemory


def ctx_probe(host, port):
    with socket.socket() as s:
        s.connect((host, port))
        return s.recv(16)


def finally_segment(nbytes):
    seg = SharedMemory(create=True, size=nbytes)
    try:
        seg.buf[0] = 1
        return bytes(seg.buf[:4])
    finally:
        seg.close()
        seg.unlink()


def daemon_worker():
    t = threading.Thread(target=print, daemon=True)  # daemon: no join needed
    t.start()


def handed_off():
    sock = socket.socket()
    return sock          # ownership escapes to the caller


def ctx_read(path):
    with open(path) as f:
        return f.read()
