"""Negative fixture: broad handlers that actually handle."""
import logging

logger = logging.getLogger(__name__)


def logs(fn):
    try:
        return fn()
    except Exception:
        logger.warning("fn failed; using default")
        return None


def reraises(fn):
    try:
        return fn()
    except Exception:
        raise RuntimeError("fn failed")


def propagates(fn, errors):
    try:
        return fn()
    except Exception as e:
        errors.append(e)        # error kept, not swallowed


def narrow(path):
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return None


def suppressed_with_reason(fn):
    try:
        return fn()
    # tfos: ignore[broad-except] — fixture: documented deliberate swallow
    except Exception:
        pass
