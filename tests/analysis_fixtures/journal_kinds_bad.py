"""journal-kinds positive fixture: all four drift directions fire.

Content-anchored like the real control plane: a KNOWN_KINDS allowlist,
a replay ``_fold`` dispatch, recorder call sites, and a tracing
CONTEXT_KINDS tuple with its emitters.
"""

KNOWN_KINDS = frozenset({"admit", "finish", "ghost_kind"})

CONTEXT_KINDS = ("crash", "comet_strike")

CRASH = "crash"


class State:
    def _fold(self, rec):
        kind = rec.get("kind")
        if kind == "admit":
            self.inflight = rec["rid"]
        # "finish" is allowlisted but never folded: replayed state
        # silently loses completions


class Plane:
    def admit(self, rid):
        self.journal.record("admit", rid=rid)

    def finish(self, rid):
        self._jrecord("finish", rid=rid)

    def rogue(self, rid):
        # recorded but not in KNOWN_KINDS: replay drops it
        self.journal.record("not_allowlisted", rid=rid)


def report(log):
    log.emit("crash", node=0)
    # "comet_strike" is in CONTEXT_KINDS but nothing ever emits it
