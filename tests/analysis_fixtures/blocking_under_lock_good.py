"""blocking-under-lock negative fixture: the same calls outside any lock
region, timeout-bounded variants under the lock, the condition-wait idiom
(which releases the lock), non-queue ``.get()`` accessors, and a
reasoned suppression."""

import os
import threading
import time


class Plane:
    def __init__(self, sock, q, worker, reservations):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._sock = sock
        self._queue = q
        self._worker = worker
        self._reservations = reservations

    def pump(self):
        data = self._sock.recv(4096)
        with self._lock:
            item = self._queue.get(timeout=1.0)
        self._worker.join(timeout=5.0)
        time.sleep(0.1)
        return data, item

    def wait_ready(self):
        with self._cond:
            # Condition.wait releases the lock while blocked: the idiom
            # the dispatch loop is built on, never flagged
            self._cond.wait()

    def snapshot(self):
        with self._lock:
            # a snapshot accessor, not a dequeue: receiver is not
            # queue-shaped
            return self._reservations.get()

    def persist(self, f, line):
        with self._lock:
            f.write(line)
            # durability contract: record must be on disk before release
            os.fsync(f.fileno())  # tfos: ignore[blocking-under-lock]
