"""Positive fixture: nested map_fun captures a lock, a socket, a client."""
import socket
import threading

from tensorflowonspark_tpu import TPUCluster
from tensorflowonspark_tpu.queues import QueueClient


def driver(args):
    lock = threading.Lock()
    sock = socket.socket()
    client = QueueClient(("127.0.0.1", 0), b"k")

    def map_fun(a, ctx):
        with lock:
            sock.send(b"x")
            client.put("input", a)

    cluster = TPUCluster.run(map_fun, args, 2)
    return cluster
