"""doc-drift negative fixture root: code catalogs and the sibling docs/
agree exactly."""

from tensorflowonspark_tpu.metrics import get_registry

VERBS = ("kill", "term")

reg = get_registry()

documented = reg.counter("tfos_documented_total", "in the catalog")


def validate_name(name):
    return name.startswith("tfos_")
