"""Positive fixture: host effects and traced branching inside jit."""
import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def impure_step(params, batch):
    t0 = time.time()                # frozen at trace time
    noise = np.random.normal()      # drawn once at trace time
    print("step", t0)               # fires only while tracing
    loss = jnp.mean(batch) + noise
    if loss > 0:                    # Python branch on a traced value
        loss = loss * 2
    return float(loss)              # forced concretization


def host_loss(x):
    return x.item()                 # device sync per call


wrapped = jax.jit(host_loss)
