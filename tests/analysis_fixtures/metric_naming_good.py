"""Negative fixture: conformant names and lookalikes that must not flag."""
import collections

from tensorflowonspark_tpu.metrics import Counter, get_registry

reg = get_registry()

ok_counter = reg.counter("tfos_serving_requests_total", "by outcome",
                         labelnames=("outcome",))
ok_gauge = reg.gauge("tfos_serving_queue_depth_count", "queued requests")
ok_hist = reg.histogram("tfos_serving_ttft_seconds", "first-token latency")
ok_bytes = reg.counter("tfos_shm_payload_bytes_total", "payload bytes")
ok_direct = Counter("tfos_restarts_total", "recovery relaunches")

# collections.Counter is NOT a metric registration — no finding even
# though the name would violate every metric rule
word_counts = collections.Counter("abcabc")


# a third-party client's .gauge/.counter/.histogram is not ours to
# police — only registry receivers are checked
class _StatsdLike:
    def gauge(self, name, value):
        pass

    def counter(self, name):
        pass


statsd = _StatsdLike()
statsd.gauge("response_time_ms", 12)
statsd.counter("hits")

# dynamically built names are out of scope for the static rule (the
# runtime validate_name still rejects bad ones)
name = "tfos_" + "dynamic" + "_total"
dynamic = reg.counter(name, "built at runtime")
