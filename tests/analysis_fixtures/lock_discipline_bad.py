"""Positive fixture: unlocked cross-thread mutation + an AB-BA lock cycle."""
import threading


class UnlockedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._worker)
        self._thread.start()

    def _worker(self):
        while True:
            with self._lock:
                self.count += 1

    def reset(self):
        self.count = 0          # main thread, no lock: flagged


class OrderCycle:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()

    def forward(self):
        with self._alock:
            with self._block:
                pass

    def backward(self):
        with self._block:
            with self._alock:   # opposite order: deadlock potential
                pass
