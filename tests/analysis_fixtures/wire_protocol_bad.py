"""wire-protocol positive fixture: every cross-file direction fires.

Self-contained on purpose: the rule's finalize directions are gated on
having seen BOTH ends of the protocol in the analyzed set, so one file
holding a producer side and a consumer side exercises the cross-file
logic exactly as a repo-wide run does.
"""

OP_ORBIT = "orbit"


def send_launch(conn, send, payload):
    # consumed below, but the handler hard-reads a field nobody sets
    send(conn, {"op": "launch", "payload": payload})


def send_orbit(send, conn):
    # produced (via a module constant) but no handler dispatches on it
    send(conn, {"op": OP_ORBIT, "alt_km": 550})


def send_dock_with_wrong_event(send, conn):
    # handlers of "dock" only match event "hard"; "soft" falls through
    send(conn, {"op": "dock", "event": "soft", "port": 2})


def send_telemetry(emit):
    # bare-event namespace: produced, never matched by any consumer
    emit({"event": "telemetry", "rssi": -70})


def serve(recv, send, conn):
    while True:
        msg = recv(conn)
        op = msg.get("op")
        if op == "launch":
            # "payload" exists; "fuel_kg" is set by no producer of launch
            send(conn, (msg["payload"], msg["fuel_kg"]))
        elif op == "dock":
            if msg.get("event") == "hard":
                send(conn, "clamped")
        elif op == "land":
            # nothing ever sends "land": dead handler
            send(conn, "down")


def drain(events):
    for e in events:
        if e.get("event") == "splashdown":
            # nothing ever emits "splashdown"
            return e
