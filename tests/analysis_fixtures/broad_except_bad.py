"""Positive fixture: silent swallows of broad exceptions."""


def swallow(fn):
    try:
        return fn()
    except Exception:
        pass


def swallow_bare(fn):
    try:
        return fn()
    except:  # noqa: E722
        return None


def swallow_base(fn):
    try:
        return fn()
    except BaseException:
        return None
