"""Positive fixture: leak-prone creations with no finally / context mgr."""
import socket
import threading
from multiprocessing.shared_memory import SharedMemory


def leaky_probe(host, port):
    s = socket.socket()                 # flagged
    s.connect((host, port))
    data = s.recv(16)
    s.close()                           # happy-path only: an exception above leaks the fd
    return data


def leaky_segment(nbytes):
    seg = SharedMemory(create=True, size=nbytes)    # flagged
    seg.buf[0] = 1
    value = bytes(seg.buf[:4])
    seg.close()
    return value


def leaky_worker():
    t = threading.Thread(target=print)  # non-daemon, never joined: flagged
    t.start()


def leaky_read(path):
    f = open(path)                      # flagged
    return f.read()
