"""compat-discipline positive fixture: every raw-reference shape —
from-imports off jax roots, dotted module imports, and attribute chains
(including the nested experimental path, which must report once)."""

import jax
import jax.experimental.shard_map
from jax import typeof
from jax.experimental.shard_map import shard_map
from jax import lax


def spread(f, mesh, specs):
    return jax.shard_map(f, mesh=mesh, in_specs=specs)


def spread_old(f, mesh):
    return jax.experimental.shard_map.shard_map(f, mesh=mesh)


def group_size(axis):
    return lax.axis_size(axis)


def widen(x, axes):
    return jax.lax.pcast(x, axes)


def probe(x):
    return jax.typeof(x)
