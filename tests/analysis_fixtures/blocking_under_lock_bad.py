"""blocking-under-lock positive fixture: the full blocking catalog, each
inside a held-lock region (``with``, explicit acquire/release bracketing,
and the lock-held-by-caller docstring convention)."""

import os
import subprocess
import threading
import time


class Plane:
    def __init__(self, sock, q, worker):
        self._lock = threading.Lock()
        self._sock = sock
        self._queue = q
        self._worker = worker

    def pump(self):
        with self._lock:
            time.sleep(0.5)
            data = self._sock.recv(4096)
            item = self._queue.get()
            self._worker.join()
            return data, item

    def persist(self, f, line):
        with self._lock:
            f.write(line)
            os.fsync(f.fileno())

    def shell(self, cmd):
        self._lock.acquire()
        try:
            return subprocess.run(cmd, capture_output=True)
        finally:
            self._lock.release()

    def _drain(self):
        """Drain the queue (lock held by caller)."""
        return self._queue.get()
