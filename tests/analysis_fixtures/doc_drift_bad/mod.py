"""doc-drift positive fixture root: an undocumented registration, a stale
catalog row, and a chaos-verb grammar drift in both directions (see the
sibling docs/)."""

from tensorflowonspark_tpu.metrics import get_registry

VERBS = ("kill", "flap")

reg = get_registry()

documented = reg.counter("tfos_documented_total", "in the catalog")
undocumented = reg.counter("tfos_undocumented_total",
                           "missing from the catalog")


def validate_name(name):
    return name.startswith("tfos_")
