"""journal-kinds negative fixture: allowlist, fold, recorders, and the
tracing context kinds all agree — including the UPPERCASE-constant
emitter routing (health.py's idiom)."""

KNOWN_KINDS = frozenset({"admit", "finish"})

CONTEXT_KINDS = ("crash", "hang")

CRASH = "crash"


class State:
    def _fold(self, rec):
        kind = rec.get("kind")
        if kind == "admit":
            self.inflight = rec["rid"]
        elif kind == "finish":
            self.inflight = None


class Plane:
    def admit(self, rid):
        self.journal.record("admit", rid=rid)

    def finish(self, rid):
        self._jrecord("finish", rid=rid)

    def note(self, secs):
        # a goodput recorder is not the journal: never counted
        self.goodput.record("step", secs)


def report(log):
    log.emit(CRASH, node=0)
    log.emit("hang", node=1)
