"""Negative fixture: payloads that are fine to pickle into spawned workers.

Module-level functions pickle by reference; closures over plain data are
harmless; objects created INSIDE map_fun are per-process by construction.
"""
import threading

from tensorflowonspark_tpu import TPUCluster


def map_fun_module_level(args, ctx):
    lock = threading.Lock()  # created inside the worker: fine
    with lock:
        return args


def driver(args):
    scale = 2.0  # plain data in the closure: pickles fine

    def map_fun(a, ctx):
        return a.batch_size * scale

    TPUCluster.run(map_fun, args, 2)
    TPUCluster.run(map_fun_module_level, args, 2)
