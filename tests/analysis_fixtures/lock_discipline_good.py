"""Negative fixture: disciplined locking.

Every cross-thread mutation holds the lock; helpers called with the lock
held say so in their docstring (the project convention the rule honors);
nested acquisition follows one global order.
"""
import threading


class LockedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        while True:
            with self._lock:
                self._bump()

    def _bump(self):
        """Increment (lock held by caller)."""
        self.count += 1

    def reset(self):
        with self._lock:
            self.count = 0


class OrderedLocks:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()

    def forward(self):
        with self._alock:
            with self._block:
                pass

    def also_forward(self):
        with self._alock:
            with self._block:
                pass
