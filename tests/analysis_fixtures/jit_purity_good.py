"""Negative fixture: pure jit functions using the supported idioms."""
import jax
import jax.numpy as jnp


@jax.jit
def pure_step(params, batch, rng):
    noise = jax.random.normal(rng, batch.shape)   # keyed randomness: fine
    loss = jnp.mean(batch + noise)
    loss = jnp.where(loss > 0, loss * 2, loss)    # traced select: fine
    jax.debug.print("loss {l}", l=loss)           # runtime print: fine
    return jax.lax.cond(loss > 1, lambda l: l, lambda l: -l, loss)


@jax.jit
def static_branches(x, flag=None):
    if flag is None:          # `is None` is a static test: fine
        return x
    if x.ndim > 2:            # shape/ndim/dtype are static: fine
        return x.sum(axis=0)
    return x


def host_side(x):
    # not jit-compiled: host calls are legitimate here
    import time

    return time.time(), float(x)
