"""compat-discipline negative fixture: the blessed idiom — every shimmed
symbol reached through the compat seam; unshimmed jax usage stays raw."""

import jax
import jax.numpy as jnp
from tensorflowonspark_tpu.compat import (axis_size, has_vma, pcast,
                                          shard_map, vma_of)


def spread(f, mesh, specs):
    return shard_map(f, mesh=mesh, in_specs=specs)


def group_size(axis):
    return axis_size(axis)


def widen(x, axes):
    return pcast(x, axes)


def probe(x):
    # unshimmed jax API is fine raw — only the drift-prone symbols
    # route through compat
    if has_vma(x):
        return vma_of(x)
    return jax.device_count(), jnp.asarray(x)
