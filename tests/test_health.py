"""Health subsystem unit tests: heartbeat publishing, monitor
classification (crash/hang/preemption), watchdog arming, and the restart
policy helpers behind ``run_with_recovery``.

Process-level detection with real worker processes lives in
``tests/test_chaos.py``; here the monitor runs against in-process fakes so
each classification branch is exercised deterministically and fast.
"""

import signal
import threading
import time

import pytest

from tensorflowonspark_tpu import health
from tensorflowonspark_tpu.health import (ClusterFailure, ClusterMonitor,
                                          HeartbeatReporter, RestartBudget,
                                          backoff_delay, classify_failure,
                                          classify_restart)
from tensorflowonspark_tpu.queues import QueueServer


# --------------------------------------------------------------- reporter

@pytest.fixture()
def kv_server():
    srv = QueueServer(authkey=b"k" * 16, qnames=("input",), mode="local",
                      shm=False)
    srv.start()
    yield srv
    srv.stop()


def test_heartbeat_reporter_publishes_and_reports_steps(kv_server):
    rep = HeartbeatReporter(kv_server, interval=0.05)
    rep.start()
    try:
        time.sleep(0.2)
        hb = kv_server.kv_get(health.HEARTBEAT_KEY)
        assert hb["seq"] >= 1 and hb["step"] is None and hb["phase"] == "boot"

        rep.report_step(7, phase="step")  # publishes immediately, no beat wait
        hb2 = kv_server.kv_get(health.HEARTBEAT_KEY)
        assert hb2["step"] == 7 and hb2["seq"] > hb["seq"]

        rep.set_phase("preempted")
        assert kv_server.kv_get(health.HEARTBEAT_KEY)["phase"] == "preempted"
    finally:
        rep.stop()


def test_heartbeat_reporter_stall_freezes_payload(kv_server):
    rep = HeartbeatReporter(kv_server, interval=0.05)
    rep.start()
    try:
        rep.report_step(1)
        rep.stall()  # forever
        frozen = kv_server.kv_get(health.HEARTBEAT_KEY)
        time.sleep(0.25)
        rep.report_step(2)  # suppressed too: a wedge reports nothing
        assert kv_server.kv_get(health.HEARTBEAT_KEY)["seq"] == frozen["seq"]
    finally:
        rep.stop()


# ---------------------------------------------------------------- monitor

class FakeBackend:
    def __init__(self, n):
        self._alive = [True] * n
        self._codes: dict[int, int | None] = {i: None for i in range(n)}

    def die(self, i, code):
        self._alive[i] = False
        self._codes[i] = code

    def alive(self):
        return list(self._alive)

    def failed(self):
        return [i for i, a in enumerate(self._alive)
                if not a and self._codes[i] not in (0, None)]

    def exitcodes(self):
        return dict(self._codes)

    def terminate(self):
        self._alive = [False] * len(self._alive)


class FakeCluster:
    def __init__(self, n):
        self.backend = FakeBackend(n)
        self.cluster_info = [{"executor_id": i, "addr": ("127.0.0.1", 1),
                              "authkey": b"x"} for i in range(n)]
        self.working_dir = None  # no event log file in unit tests
        self.aborted = False

    def _abort(self):
        self.aborted = True
        self.backend.terminate()


class FakeKV:
    """Stands in for the monitor's per-node QueueClient."""

    def __init__(self, payloads):
        self.payloads = payloads  # executor_id -> mutable payload dict|None

    def client(self, info):
        eid = info["executor_id"]
        outer = self

        class _C:
            def kv_get(self, key):
                p = outer.payloads.get(eid)
                if isinstance(p, Exception):
                    raise p
                return p

            def close(self):
                pass

        return _C()


def _monitor(cluster, payloads, **kw):
    kw.setdefault("poll_interval", 0.02)
    return ClusterMonitor(cluster, client_factory=FakeKV(payloads).client, **kw)


def test_monitor_classifies_crash_and_aborts():
    cluster = FakeCluster(2)
    mon = _monitor(cluster, {}, hang_timeout=60)
    mon.start()
    try:
        cluster.backend.die(1, code=1)
        failure = mon.wait(timeout=5)
        assert failure is not None and failure.kind == health.CRASH
        assert failure.failed_workers == (1,)
        assert cluster.aborted
    finally:
        mon.stop()


def test_monitor_classifies_sigterm_exit_as_preemption():
    cluster = FakeCluster(1)
    mon = _monitor(cluster, {}, hang_timeout=60)
    mon.start()
    try:
        cluster.backend.die(0, code=-int(signal.SIGTERM))
        failure = mon.wait(timeout=5)
        assert failure is not None and failure.kind == health.PREEMPTION
    finally:
        mon.stop()


def test_monitor_hang_requires_arming():
    """A frozen payload with NO reported step (a long compile) must never
    trip the watchdog; the same staleness after step >= 1 must."""
    payloads = {0: {"seq": 1, "step": None, "phase": "init"}}
    cluster = FakeCluster(1)
    mon = _monitor(cluster, payloads, hang_timeout=0.2)
    mon.start()
    try:
        time.sleep(0.7)  # stale for > 3x hang_timeout, but unarmed
        assert mon.failure is None and not cluster.aborted

        payloads[0] = {"seq": 2, "step": 3, "phase": "step"}  # arm...
        time.sleep(0.1)          # ...let the monitor see the change
        # payload now frozen (seq never advances) -> hang
        failure = mon.wait(timeout=5)
        assert failure is not None and failure.kind == health.HANG
        assert "heartbeat stale" in str(failure)
        assert cluster.aborted
    finally:
        mon.stop()


def test_monitor_step_timeout_detects_stuck_step():
    """Heartbeats keep flowing (background thread alive) but the reported
    step stops advancing — the SPMD-collective wedge; only step_timeout
    catches this shape."""
    payloads = {0: {"seq": 1, "step": 2, "phase": "step"}}
    cluster = FakeCluster(1)
    mon = _monitor(cluster, payloads, hang_timeout=60, step_timeout=0.3)

    def beat():  # advance seq, never step
        while not mon._stop.is_set():
            payloads[0] = dict(payloads[0], seq=payloads[0]["seq"] + 1)
            time.sleep(0.02)

    t = threading.Thread(target=beat, daemon=True)
    mon.start()
    t.start()
    try:
        failure = mon.wait(timeout=5)
        assert failure is not None and failure.kind == health.HANG
        assert "stuck at step" in str(failure)
    finally:
        mon.stop()


def test_monitor_unreachable_kv_counts_as_stale():
    payloads = {0: {"seq": 1, "step": 1, "phase": "step"}}
    cluster = FakeCluster(1)
    mon = _monitor(cluster, payloads, hang_timeout=0.3)
    mon.start()
    try:
        time.sleep(0.1)
        payloads[0] = ConnectionError("kv down")  # node stops answering
        failure = mon.wait(timeout=5)
        assert failure is not None and failure.kind == health.HANG
    finally:
        mon.stop()


def test_monitor_ignores_clean_exit():
    cluster = FakeCluster(1)
    mon = _monitor(cluster, {}, hang_timeout=0.2)
    mon.start()
    try:
        cluster.backend.die(0, code=0)  # finished, not failed
        time.sleep(0.5)
        assert mon.failure is None and not cluster.aborted
    finally:
        mon.stop()


def test_monitor_keep_polling_reports_each_failure_once_and_survives():
    """Serving mode (abort_on_failure=False, keep_polling=True): each
    replica death is classified once, handed to on_failure, and the
    monitor keeps watching the survivors instead of stopping — a second
    death is detected too, and the cluster is never aborted."""
    cluster = FakeCluster(3)
    seen: list = []
    mon = _monitor(cluster, {}, hang_timeout=60, abort_on_failure=False,
                   keep_polling=True, on_failure=seen.append)
    mon.start()
    try:
        cluster.backend.die(1, code=1)
        failure = mon.wait(timeout=5)
        assert failure is not None and failure.failed_workers == (1,)
        assert not cluster.aborted

        cluster.backend.die(2, code=-int(signal.SIGTERM))
        deadline = time.time() + 5
        while len(seen) < 2 and time.time() < deadline:
            time.sleep(0.02)
        assert len(mon.failures) == 2, "second death not detected"
        assert mon.failures[0].kind == health.CRASH
        assert mon.failures[1].kind == health.PREEMPTION
        assert [f.failed_workers for f in seen] == [(1,), (2,)]
        # one report per death: give the poller time to re-trip, if buggy
        time.sleep(0.2)
        assert len(mon.failures) == 2
        assert not cluster.aborted
    finally:
        mon.stop()


def test_monitor_keep_polling_retires_hung_node_from_watch():
    """A hang-classified node must be reported once and then retired from
    the heartbeat check (its payload stays frozen forever)."""
    payloads = {0: {"seq": 2, "step": 3, "phase": "step"},
                1: {"seq": 1, "step": None, "phase": "init"}}
    cluster = FakeCluster(2)
    seen: list = []
    mon = _monitor(cluster, payloads, hang_timeout=0.2,
                   abort_on_failure=False, keep_polling=True,
                   on_failure=seen.append)
    mon.start()
    try:
        deadline = time.time() + 5
        while not mon.failures and time.time() < deadline:
            time.sleep(0.02)
        assert mon.failures and mon.failures[0].kind == health.HANG
        assert mon.failures[0].failed_workers == (0,)
        time.sleep(0.5)      # stale forever; must not re-report
        assert len(mon.failures) == 1
        assert not cluster.aborted
    finally:
        mon.stop()


def test_monitor_on_failure_subscriber_exception_is_contained():
    """A buggy on_failure subscriber must not kill detection (or the
    abort that follows it)."""
    cluster = FakeCluster(1)

    def boom(failure):
        raise RuntimeError("subscriber bug")

    mon = _monitor(cluster, {}, hang_timeout=60, on_failure=boom)
    mon.start()
    try:
        cluster.backend.die(0, code=1)
        failure = mon.wait(timeout=5)
        assert failure is not None and failure.kind == health.CRASH
        deadline = time.time() + 5  # abort runs just after the wait() event
        while not cluster.aborted and time.time() < deadline:
            time.sleep(0.02)
        assert cluster.aborted      # abort still ran after the bad callback
    finally:
        mon.stop()


# ------------------------------------------------------- restart policy

def test_classify_failure_user_vs_infra():
    user_tb = ("worker 0 failed:\nTraceback (most recent call last):\n"
               '  File "m.py", line 1, in fn\n'
               "ValueError: deliberate failure")
    infra_tb = ("worker 0 failed:\nTraceback (most recent call last):\n"
                "ConnectionError: injected infra failure")
    mixed_tb = ("2 workers failed (0, 1):\n--- worker 0 failed ---\n"
                "ValueError: bad\n--- worker 1 failed ---\n"
                "ConnectionResetError: peer gone")
    assert classify_failure(RuntimeError(user_tb)) == health.USER
    assert classify_failure(RuntimeError(infra_tb)) == health.INFRA
    # any infra participant makes the aggregate retryable
    assert classify_failure(RuntimeError(mixed_tb)) == health.INFRA
    assert classify_failure(TimeoutError("reservation timed out")) == health.INFRA
    assert classify_failure(ValueError("driver-side bad arg")) == health.USER
    for kind in (health.CRASH, health.HANG, health.PREEMPTION):
        assert classify_failure(ClusterFailure(kind, "x")) == kind


def test_classify_failure_preflight_rejection_is_no_retry():
    """A submit-time payload rejection is deterministic — retrying it with
    backoff just delays the user's error by the whole restart budget."""
    from tensorflowonspark_tpu.analysis.preflight import PreflightError

    exc = PreflightError("map_fun", [("map_fun closure 'lock'",
                                      "threading lock (unpicklable)")])
    assert classify_failure(exc) == health.USER
    assert not health.classify_restart(classify_failure(exc))


def test_classify_restart_policy():
    assert not classify_restart(health.USER)
    for kind in (health.CRASH, health.HANG, health.PREEMPTION, health.INFRA):
        assert classify_restart(kind)


def test_backoff_delay_exponential_with_jitter():
    for attempt, ceiling in [(1, 1.0), (2, 2.0), (3, 4.0), (10, 30.0)]:
        for _ in range(20):
            d = backoff_delay(attempt, base=1.0, cap=30.0)
            assert 0.5 * ceiling <= d <= ceiling


def test_restart_budget_sliding_window():
    b = RestartBudget(2, window_secs=10.0)
    assert b.allow(now=0.0)
    assert b.allow(now=1.0)
    assert not b.allow(now=2.0)      # 3 restarts inside 10s
    assert b.allow(now=20.0)         # old restarts aged out of the window
