"""Pallas flash-attention kernel vs the dense oracle (interpret mode on CPU).

Mirrors the reference's test posture of exercising real code paths without
special hardware (SURVEY.md §4: `local-cluster` on one machine); here the
kernels run under the Pallas interpreter so CI needs no TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu.ops import flash_attention
from tensorflowonspark_tpu.parallel.ring_attention import reference_attention


def _rand(key, *shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype=dtype)


def _qkv(seed, B, T, H, D, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    return (_rand(ks[0], B, T, H, D, dtype=dtype),
            _rand(ks[1], B, T, H, D, dtype=dtype),
            _rand(ks[2], B, T, H, D, dtype=dtype))


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = _qkv(0, 2, 64, 4, 16)
    got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_forward_key_padding_mask():
    q, k, v = _qkv(1, 2, 48, 2, 8)
    mask = jnp.arange(48)[None, :] < jnp.array([[30], [48]])
    got = flash_attention(q, k, v, mask=mask, block_q=16, block_k=16)
    want = reference_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_ragged_seq_len_padded_internally():
    # 50 is not a block multiple → exercises the padding path.
    q, k, v = _qkv(2, 1, 50, 2, 8)
    got = flash_attention(q, k, v, block_q=16, block_k=16)
    want = reference_attention(q, k, v)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_cross_attention_lengths():
    B, H, D = 2, 2, 8
    ks = jax.random.split(jax.random.key(3), 3)
    q = _rand(ks[0], B, 24, H, D)
    k = _rand(ks[1], B, 40, H, D)
    v = _rand(ks[2], B, 40, H, D)
    got = flash_attention(q, k, v, block_q=16, block_k=16)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    p = jax.nn.softmax(s, axis=-1)
    want = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_bf16_forward_close():
    q, k, v = _qkv(4, 1, 32, 2, 16, dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, block_q=16, block_k=16)
    assert got.dtype == jnp.bfloat16
    want = reference_attention(q, k, v)
    np.testing.assert_allclose(got.astype(np.float32),
                               want.astype(np.float32), atol=3e-2, rtol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    q, k, v = _qkv(5, 2, 32, 2, 8)
    mask = jnp.arange(32)[None, :] < jnp.array([[32], [20]])

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, mask=mask, causal=causal,
                            block_q=16, block_k=16)
        return jnp.sum(jnp.sin(o))  # non-trivial cotangent

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(reference_attention(q, k, v, mask=mask,
                                                   causal=causal)))

    g_got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_got, g_want, "qkv"):
        np.testing.assert_allclose(got, want, atol=5e-5, rtol=5e-4,
                                   err_msg=f"d{name}")


def test_gradients_ragged_padding():
    q, k, v = _qkv(6, 1, 20, 2, 8)  # padded to 24 internally

    f = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, block_q=8, block_k=8) ** 2))
    d = jax.grad(lambda q: jnp.sum(reference_attention(q, k, v) ** 2))
    np.testing.assert_allclose(f(q), d(q), atol=5e-5, rtol=5e-4)
    assert np.all(np.isfinite(f(q)))


def test_jit_and_vjp_compile_once():
    q, k, v = _qkv(7, 1, 32, 2, 8)
    step = jax.jit(jax.grad(lambda q: flash_attention(
        q, k, v, causal=True, block_q=16, block_k=16).sum()))
    assert np.all(np.isfinite(step(q)))


def test_as_bert_attention_fn():
    """flash_attention plugs into BertConfig.attention_fn unchanged."""
    import functools
    from tensorflowonspark_tpu.models import Bert, BertConfig

    cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=1,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=32, dropout_rate=0.0,
                     dtype=jnp.float32,
                     attention_fn=functools.partial(
                         flash_attention, block_q=16, block_k=16))
    ids = jnp.ones((2, 16), jnp.int32)
    mask = jnp.arange(16)[None, :] < jnp.array([[16], [9]])
    params = Bert(cfg).init(jax.random.key(0), ids, mask)
    out = Bert(cfg).apply(params, ids, mask)
    assert out.shape == (2, 16, 32)
    assert np.all(np.isfinite(out))

    dense = BertConfig(**{**cfg.__dict__, "attention_fn": None})
    want = Bert(dense).apply(params, ids, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_pick_block_bounds_padding_waste():
    from tensorflowonspark_tpu.ops.flash_attention import _pick_block

    # just past a 512 boundary: pad to one extra 128-tile, not a full 512
    block, padded = _pick_block(520, 512)
    assert padded == 640 and block == 128
    # exact multiples keep the big block
    assert _pick_block(4096, 512) == (512, 4096)
    assert _pick_block(2048, 512) == (512, 2048)
    # tiny sequences stay tiny
    assert _pick_block(48, 16) == (16, 48)
    b, p = _pick_block(20, 512)
    assert p >= 20 and p % b == 0 and p - 20 < 8


def test_flash_odd_length_past_block_boundary():
    """T just past the block size must stay correct through _pick_block."""
    q, k, v = _qkv(8, 1, 136, 2, 8)  # 136 = 128 + 8
    got = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_fully_masked_rows_yield_zeros():
    """A batch row whose key-padding mask is all-False must produce zeros
    (and zero gradients), not the mean of V (the online-softmax degenerate
    case ADVICE.md round 1 flagged)."""
    q, k, v = _qkv(9, 2, 16, 2, 8)
    mask = np.ones((2, 16), bool)
    mask[1, :] = False  # batch row 1: every key masked

    out = flash_attention(q, k, v, mask=jnp.asarray(mask),
                          block_q=16, block_k=16)
    out = np.asarray(out)
    assert np.all(out[1] == 0.0), "fully-masked row must be exactly zero"
    # row 0 unchanged vs dense
    want = reference_attention(q[:1], k[:1], v[:1])
    np.testing.assert_allclose(out[:1], np.asarray(want), atol=2e-5, rtol=2e-5)

    # gradients: masked row contributes exactly nothing
    def loss(q, k, v):
        return (flash_attention(q, k, v, mask=jnp.asarray(mask),
                                block_q=16, block_k=16) ** 2).sum()

    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (dq, dk, dv):
        g = np.asarray(g)
        assert np.all(np.isfinite(g))
        assert np.all(g[1] == 0.0), "masked batch row must get zero grads"


class TestSlidingWindow:
    def _dense_windowed(self, q, k, v, window):
        T = q.shape[1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (q.shape[-1] ** 0.5)
        pos = jnp.arange(T)
        keep = (pos[:, None] >= pos[None, :]) & \
               (pos[None, :] > pos[:, None] - window)
        s = jnp.where(keep[None, None], s.astype(jnp.float32), -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))

    @pytest.mark.parametrize("window", [1, 5, 48, 200])
    def test_matches_dense_band_oracle(self, window):
        from tensorflowonspark_tpu.ops import flash_attention

        B, T, H, D = 2, 128, 2, 16
        q = jax.random.normal(jax.random.key(0), (B, T, H, D))
        k = jax.random.normal(jax.random.key(1), (B, T, H, D))
        v = jax.random.normal(jax.random.key(2), (B, T, H, D))
        got = flash_attention(q, k, v, causal=True, window=window,
                              block_q=32, block_k=32)
        want = self._dense_windowed(q, k, v, window)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_match_band_oracle(self):
        from tensorflowonspark_tpu.ops import flash_attention

        B, T, H, D, W = 1, 64, 2, 8, 13
        q = jax.random.normal(jax.random.key(3), (B, T, H, D))
        k = jax.random.normal(jax.random.key(4), (B, T, H, D))
        v = jax.random.normal(jax.random.key(5), (B, T, H, D))

        def f_flash(q, k, v):
            return flash_attention(q, k, v, causal=True, window=W,
                                   block_q=16, block_k=16).sum()

        def f_dense(q, k, v):
            return self._dense_windowed(q, k, v, W).sum()

        gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_window_requires_causal_and_positive(self):
        from tensorflowonspark_tpu.ops import flash_attention

        x = jnp.zeros((1, 16, 1, 8))
        with pytest.raises(ValueError, match="causal"):
            flash_attention(x, x, x, window=4)
        with pytest.raises(ValueError, match=">= 1"):
            flash_attention(x, x, x, causal=True, window=0)


def test_flash_blocks_anchor_on_sweep_artifact(tmp_path, monkeypatch):
    """Default block sizes come from the committed on-chip block sweep
    when one exists, and fall back to 512x512 otherwise."""
    import importlib

    # ops/__init__ shadows the submodule name with the function, so a
    # plain `import ... as` would bind the function — load the module
    fa_mod = importlib.import_module(
        "tensorflowonspark_tpu.ops.flash_attention")

    art = tmp_path / "flash_sweep.json"
    monkeypatch.setattr(fa_mod, "_FLASH_SWEEP_PATH", str(art))

    fa_mod._tuned_blocks.cache_clear()
    assert fa_mod._tuned_blocks() == (512, 512)  # no artifact yet

    art.write_text('{"best_block": "1024x256"}')
    fa_mod._tuned_blocks.cache_clear()
    assert fa_mod._tuned_blocks() == (1024, 256)

    art.write_text('{"best_block": "garbage"}')
    fa_mod._tuned_blocks.cache_clear()
    assert fa_mod._tuned_blocks() == (512, 512)
    fa_mod._tuned_blocks.cache_clear()  # leave no tmp-path state behind
