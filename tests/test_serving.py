"""Continuous batching (``models/serving.py``): greedy-exact output per
request regardless of admission order, slot reuse, or batch company.

The oracle for every request is a SOLO ``greedy_generate`` run on its
prompt (the scalar-index decode path) — so these tests also lock the
per-row-position substrate (``GPTConfig.per_row_positions``) against the
reference implementation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu.models import (GPT, GPTConfig, ContinuousBatcher,
                                          greedy_generate)


def _make(pos_encoding="rope", **kw):
    cfg = GPTConfig(vocab_size=61, hidden_size=32, num_layers=2, num_heads=4,
                    intermediate_size=64, max_position_embeddings=48,
                    dtype=jnp.float32, pos_encoding=pos_encoding, **kw)
    params = GPT(cfg).init(jax.random.key(0),
                           jnp.ones((1, 4), jnp.int32))["params"]
    return cfg, params


def _oracle(cfg, params, prompt, n):
    out = greedy_generate(cfg, params, jnp.asarray(prompt)[None, :], n)
    return np.asarray(out)[0, len(prompt):]


@pytest.mark.parametrize("pos_encoding", ["rope", "learned"])
def test_staggered_requests_match_solo_greedy(pos_encoding):
    """More requests than slots, different prompt lengths and budgets:
    every request's tokens equal its solo greedy run."""
    cfg, params = _make(pos_encoding)
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, cfg.vocab_size, (t,)).astype(np.int32), n)
            for t, n in ((5, 7), (3, 12), (8, 4), (5, 9), (2, 6), (6, 1))]

    b = ContinuousBatcher(cfg, params, max_batch=2)
    rids = [b.submit(p, n) for p, n in reqs]
    results = b.run()

    assert sorted(results) == sorted(rids)
    for rid, (prompt, n) in zip(rids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(cfg, params, prompt, n))


def test_unload_load_params_keeps_compiled_exactness():
    """The warm-standby posture: unload drops the weights but keeps the
    compiled executables; a reloaded (host-numpy, peer-cloned-shaped)
    tree decodes token-identically with no live-state carryover.
    Guards: submit while weightless raises; unload with live work
    refuses."""
    cfg, params = _make()
    prompt = np.asarray([3, 1, 4, 1, 5], np.int32)
    b = ContinuousBatcher(cfg, params, max_batch=2)
    rid = b.submit(prompt, 6)
    with pytest.raises(RuntimeError, match="live requests"):
        b.unload_params()                 # in-flight work: refuse
    want = b.run()[rid]
    b.unload_params()
    assert b.params is None
    with pytest.raises(RuntimeError, match="no parameters"):
        b.submit(prompt, 2)
    with pytest.raises(ValueError):
        b.load_params(None)
    # reload a HOST tree (what a peer clone delivers) — same executables
    b.load_params(jax.tree.map(lambda x: np.asarray(x), params))
    rid2 = b.submit(prompt, 6)
    np.testing.assert_array_equal(b.run()[rid2], want)
    np.testing.assert_array_equal(want, _oracle(cfg, params, prompt, 6))


def test_load_params_drops_stale_prefix_cache():
    """Paged mode: a parameter swap must rebuild the prefix index empty —
    cached pages hold KV computed under the OLD weights, and a post-swap
    hit against them would decode wrong tokens when the trees differ."""
    cfg, params = _make()
    prompt = np.arange(1, 25, dtype=np.int32)      # spans whole pages
    b = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    rid = b.submit(prompt, 4)
    b.run()
    b.result(rid, pop=True)
    assert b.prefix_stats()["cached_pages"] > 0    # index is warm
    b.unload_params()
    # a DIFFERENT tree (fresh seed): the old pages are poison now
    params2 = GPT(cfg).init(jax.random.key(7),
                            jnp.ones((1, 4), jnp.int32))["params"]
    b.load_params(jax.device_put(params2))
    assert b.prefix_stats()["cached_pages"] == 0   # index flushed
    rid2 = b.submit(prompt, 4)
    out = b.run()[rid2]
    assert b.prefix_stats()["hit"] == 0, "stale prefix page was reused"
    np.testing.assert_array_equal(out, _oracle(cfg, params2, prompt, 4))


def test_mid_flight_admission_does_not_disturb_running_slots():
    """Submit while another request is mid-decode; both stay exact."""
    cfg, params = _make()
    rng = np.random.default_rng(1)
    p1 = rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32)
    p2 = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)

    b = ContinuousBatcher(cfg, params, max_batch=2)
    r1 = b.submit(p1, 10)
    for _ in range(4):           # r1 alone for a few steps
        b.step()
    r2 = b.submit(p2, 5)         # admitted mid-flight of r1
    results = b.run()

    np.testing.assert_array_equal(results[r1], _oracle(cfg, params, p1, 10))
    np.testing.assert_array_equal(results[r2], _oracle(cfg, params, p2, 5))


def test_eos_frees_slot_early_and_slot_reuse_is_clean():
    """A request stopping at eos releases its slot; the slot's next
    tenant is unaffected by the leftover cache rows."""
    cfg, params = _make()
    rng = np.random.default_rng(2)
    p1 = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
    # pick the eos id as the 3rd token the oracle would emit, so the
    # request genuinely stops early
    oracle1 = _oracle(cfg, params, p1, 10)
    eos = int(oracle1[2])
    p2 = rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32)

    b = ContinuousBatcher(cfg, params, max_batch=1, eos_id=eos)
    r1 = b.submit(p1, 10)
    r2 = b.submit(p2, 6)         # waits for the only slot
    results = b.run()

    # r1: truncated at (and including) the FIRST eos occurrence
    first = list(oracle1).index(eos)
    np.testing.assert_array_equal(results[r1], oracle1[:first + 1])
    assert len(results[r1]) < len(oracle1), "eos did not stop early"
    # r2 reused r1's slot; exactness = prefix-up-to-eos of its solo run
    want2 = _oracle(cfg, params, p2, 6)
    got2 = results[r2]
    if eos in want2:
        want2 = want2[:list(want2).index(eos) + 1]
    np.testing.assert_array_equal(got2, want2)


def test_single_step_budget_and_validation():
    cfg, params = _make()
    with pytest.raises(ValueError, match="max_batch"):
        ContinuousBatcher(cfg, params, max_batch=0)
    b = ContinuousBatcher(cfg, params, max_batch=2)
    with pytest.raises(ValueError, match="empty prompt"):
        b.submit(np.array([], np.int32), 4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        b.submit(np.array([1, 2], np.int32), 0)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        b.submit(np.arange(40, dtype=np.int32), 20)
    rid = b.submit(np.array([1, 2, 3], np.int32), 1)  # 1-token budget
    # finishing AT admission must still be reported by step()
    assert b.step() == [rid]
    results = b.run()
    np.testing.assert_array_equal(results[rid],
                                  _oracle(cfg, params, [1, 2, 3], 1))


def test_has_free_slot_counts_pending():
    """The documented drive loop 'submit while has_free_slot()' must
    terminate: queued requests count against free slots."""
    cfg, params = _make()
    b = ContinuousBatcher(cfg, params, max_batch=2)
    n = 0
    while b.has_free_slot():
        b.submit(np.array([1, 2], np.int32), 3)
        n += 1
        assert n <= 2, "has_free_slot ignored the pending queue"
    assert n == 2


def test_one_decode_executable_for_the_lifetime():
    """The decode step never recompiles across admissions/retirements."""
    cfg, params = _make()
    b = ContinuousBatcher(cfg, params, max_batch=2)
    b.submit(np.array([1, 2], np.int32), 3)
    b.submit(np.array([3, 4, 5], np.int32), 8)
    b.submit(np.array([6], np.int32), 4)
    b.run()
    assert b._step._cache_size() == 1, "decode step recompiled"


def test_rolling_cache_rejected():
    cfg, params = _make(sliding_window=8, rolling_kv_cache=True)
    with pytest.raises(ValueError, match="rolling_kv_cache"):
        ContinuousBatcher(cfg, params, max_batch=2)


def _variant_setup(variant):
    """(cfg, params) for one decode-feature variant — shared by the plain
    and speculative composition matrices so the two cannot drift."""
    kw = {}
    if variant == "gqa":
        kw["num_kv_heads"] = 2
    if variant == "window":
        kw["sliding_window"] = 8
    cfg, params = _make("rope", **kw)
    if variant in ("int8", "int4"):
        from tensorflowonspark_tpu.ops import quantize_params

        params = quantize_params(params,
                                 bits=4 if variant == "int4" else 8)
    return cfg, params


@pytest.mark.parametrize("variant", ["int8", "int4", "gqa", "window"])
def test_serving_composes_with_decode_features(variant):
    """Continuous batching must stay greedy-exact under the decode
    stack's other features: int8/int4 weight-only quantization,
    grouped-query attention, sliding-window attention (full cache)."""
    cfg, params = _variant_setup(variant)

    rng = np.random.default_rng(3)
    reqs = [(rng.integers(0, cfg.vocab_size, (t,)).astype(np.int32), n)
            for t, n in ((4, 6), (7, 9), (3, 5))]
    b = ContinuousBatcher(cfg, params, max_batch=2)
    rids = [b.submit(p, n) for p, n in reqs]
    results = b.run()
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(results[rid], _oracle(cfg, params, p, n))


def test_serving_with_tp_sharded_params_under_mesh():
    """Distributed inference: ContinuousBatcher over Megatron-tp-sharded
    parameters on a 2-device mesh — greedy-exact against a solo sharded
    greedy run (same reduction order), with params verified actually
    sharded over tp."""
    from tensorflowonspark_tpu.parallel import MeshSpec, make_mesh
    from tensorflowonspark_tpu.parallel.sharding import flax_shardings

    # vocab divisible by tp (tok_emb shards its rows over tp)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                    intermediate_size=64, max_position_embeddings=48,
                    dtype=jnp.float32, pos_encoding="rope")
    params = GPT(cfg).init(jax.random.key(0),
                           jnp.ones((1, 4), jnp.int32))["params"]
    mesh = make_mesh(MeshSpec(tp=2, dp=1), devices=jax.devices()[:2])

    model = GPT(cfg)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.ones((1, 4), jnp.int32)))
    shardings = flax_shardings(mesh, abstract)["params"]
    sharded = jax.device_put(params, shardings)
    n_tp = sum("tp" in str(s.spec) for s in jax.tree.leaves(shardings))
    assert n_tp > 0, "no parameter actually sharded over tp"

    rng = np.random.default_rng(4)
    reqs = [(rng.integers(0, cfg.vocab_size, (t,)).astype(np.int32), n)
            for t, n in ((5, 8), (3, 11), (7, 5))]
    with mesh:
        b = ContinuousBatcher(cfg, sharded, max_batch=2)
        rids = [b.submit(p, n) for p, n in reqs]
        results = b.run()
        for rid, (p, n) in zip(rids, reqs):
            want = np.asarray(greedy_generate(
                cfg, sharded, jnp.asarray(p)[None, :], n))[0, len(p):]
            np.testing.assert_array_equal(results[rid], want)


def test_sampling_deterministic_and_company_independent():
    """A sampled request's tokens are a pure function of (seed, temp,
    top_p) — identical alone, batched with greedy neighbors, or after
    slot churn; and greedy neighbors stay greedy-exact next to it."""
    cfg, params = _make()
    rng = np.random.default_rng(5)
    p = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
    pg = rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32)

    def sampled_run(extra_greedy):
        b = ContinuousBatcher(cfg, params, max_batch=2)
        rid = b.submit(p, 9, temperature=0.8, top_p=0.9, seed=123)
        gids = [b.submit(pg, n) for n in extra_greedy]
        res = b.run()
        return res[rid], [res[g] for g in gids]

    alone, _ = sampled_run([])
    with_company, greedy_outs = sampled_run([6, 3, 7])
    np.testing.assert_array_equal(alone, with_company)
    for g in greedy_outs:
        np.testing.assert_array_equal(
            g, _oracle(cfg, params, pg, len(g)))

    # a different seed must (overwhelmingly) change the trajectory
    b = ContinuousBatcher(cfg, params, max_batch=1)
    rid = b.submit(p, 9, temperature=0.8, top_p=0.9, seed=124)
    other = b.run()[rid]
    assert not np.array_equal(alone, other)


def test_tiny_top_p_equals_greedy():
    """top_p -> 0 keeps only the argmax token: sampling must reduce to
    the greedy trajectory at any temperature."""
    cfg, params = _make()
    rng = np.random.default_rng(6)
    p = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
    b = ContinuousBatcher(cfg, params, max_batch=1)
    rid = b.submit(p, 8, temperature=1.3, top_p=1e-6, seed=7)
    np.testing.assert_array_equal(b.run()[rid], _oracle(cfg, params, p, 8))


def test_sampling_validation():
    cfg, params = _make()
    b = ContinuousBatcher(cfg, params, max_batch=1)
    with pytest.raises(ValueError, match="temperature"):
        b.submit(np.array([1], np.int32), 2, temperature=-0.1)
    with pytest.raises(ValueError, match="top_p"):
        b.submit(np.array([1], np.int32), 2, top_p=0.0)
    with pytest.raises(ValueError, match="top_p"):
        b.submit(np.array([1], np.int32), 2, top_p=1.5)


def test_seed_must_fit_int32():
    cfg, params = _make()
    b = ContinuousBatcher(cfg, params, max_batch=1)
    with pytest.raises(ValueError, match="seed"):
        b.submit(np.array([1], np.int32), 2, temperature=0.5, seed=2**35)


def test_batcher_nucleus_matches_sample_generate_filter():
    """Serving and sample_generate share nucleus_filter — same kept set
    (ties included) on a crafted tied distribution."""
    from tensorflowonspark_tpu.models.gpt import nucleus_filter

    logits = jnp.asarray([3.0, 2.0, 2.0, 0.0, -1.0])
    out = nucleus_filter(logits, 0.75)
    # top token (p~0.58) kept; both TIED 2.0 tokens kept (threshold
    # semantics), tail masked
    assert np.isfinite(np.asarray(out[:3])).all()
    assert np.isneginf(np.asarray(out[3:])).all()


def test_prefill_bucketing_is_exact_and_bounds_compiles():
    """Right-padded power-of-two prefill buckets: every prompt length in
    3..9 stays greedy-exact, and the prefill compile count is the
    (bucket, group-size) count, not the length count.  Equal budgets make
    slots free in pairs, so same-bucket pairs share batched executables:
    (3,4)->bucket4 group2, (5,6) and (7,8)->bucket8 group2 (reused),
    9->bucket16 solo."""
    cfg, params = _make()
    rng = np.random.default_rng(8)
    b = ContinuousBatcher(cfg, params, max_batch=2)
    reqs = [(rng.integers(0, cfg.vocab_size, (t,)).astype(np.int32), 6)
            for t in range(3, 10)]
    rids = [b.submit(p, n) for p, n in reqs]
    results = b.run()
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(cfg, params, p, n))
    assert {k for k in b._prefill_jit if k[0] == "final"} \
        == {("final", 4, 2), ("final", 8, 2), ("final", 16, 1)}, \
        sorted(map(str, b._prefill_jit))


@pytest.mark.parametrize("pos_encoding", ["rope", "learned"])
def test_chunked_prefill_matches_whole(pos_encoding):
    """Long-context admission: prompts prefilled in fixed chunks through
    the cached decode path are greedy-exact vs the whole-prompt oracle,
    and the chunk loop adds only (chunk + final-bucket) executables."""
    cfg, params = _make(pos_encoding)
    rng = np.random.default_rng(9)
    b = ContinuousBatcher(cfg, params, max_batch=2, prefill_chunk=6)
    reqs = [(rng.integers(0, cfg.vocab_size, (t,)).astype(np.int32), 5)
            for t in (20, 23, 4)]   # 4 <= chunk -> whole-prompt path
    rids = [b.submit(p, n) for p, n in reqs]
    results = b.run()
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(cfg, params, p, n))
    keys = set(b._prefill_jit)
    assert ("chunk", 6) in keys
    # chunked finals run solo (rest 2, 5 -> buckets 2, 8 at group 1) +
    # the short whole prompt (bucket 4, admitted alone once slots free)
    assert {k for k in keys if k[0] == "final"} \
        == {("final", 2, 1), ("final", 8, 1), ("final", 4, 1)}


def test_failed_step_poisons_the_batcher():
    """A device failure mid-step leaves the donated cache unrecoverable:
    the batcher must refuse further use with an error naming the original
    failure, instead of silently decoding from a poisoned cache."""
    cfg, params = _make()
    b = ContinuousBatcher(cfg, params, max_batch=2)
    b.submit(np.asarray([1, 2, 3], np.int32), 5)
    b.step()
    boom = RuntimeError("RESOURCE_EXHAUSTED: synthetic device OOM")

    def raising_step(params, cache, tokens):
        raise boom
    b._step = raising_step
    with pytest.raises(RuntimeError, match="synthetic device OOM"):
        b.step()
    for call in (b.step, b.run, lambda: b.submit([1], 1)):
        with pytest.raises(RuntimeError, match="unusable(.|\n)*synthetic"):
            call()


def test_burst_admission_shares_one_prefill_dispatch():
    """A burst of same-bucket arrivals is admitted with ONE batched
    prefill call and one scatter — and every request stays greedy-exact
    vs its solo oracle (batching must not change numerics)."""
    cfg, params = _make()
    rng = np.random.default_rng(11)
    b = ContinuousBatcher(cfg, params, max_batch=8)
    calls = []
    orig = b._prefill_final
    b._prefill_final = lambda *a: calls.append(1) or orig(*a)
    reqs = [(rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32), n)
            for n in (4, 6, 3, 5, 7, 4, 6, 5)]
    rids = [b.submit(p, n) for p, n in reqs]
    results = b.run()
    assert len(calls) == 1, f"expected one batched prefill, got {len(calls)}"
    assert set(b._prefill_jit) >= {("final", 8, 8), ("scatter", 8)}
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(cfg, params, p, n))


def test_group_padding_rows_never_land():
    """A group of 3 pads to 4 prefill rows; the pad row's garbage cache
    is dropped at scatter (out-of-bounds slot) and running slots are
    untouched: all requests remain greedy-exact."""
    cfg, params = _make()
    rng = np.random.default_rng(12)
    b = ContinuousBatcher(cfg, params, max_batch=4)
    # occupy one slot first so the burst of 3 lands beside a live row
    early_p = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
    early = b.submit(early_p, 10)
    b.step()
    reqs = [(rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32), n)
            for n in (4, 5, 6)]
    rids = [b.submit(p, n) for p, n in reqs]
    results = b.run()
    assert ("final", 8, 4) in b._prefill_jit   # group of 3 padded to 4
    np.testing.assert_array_equal(results[early],
                                  _oracle(cfg, params, early_p, 10))
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(cfg, params, p, n))


def test_chunked_admission_is_time_sliced():
    """Admitting a long (chunked) prompt must NOT stall running slots:
    each step advances the in-flight prefill by one chunk while active
    requests keep decoding, the target slot stays reserved until the
    final chunk lands, and both outputs remain greedy-exact."""
    cfg, params = _make()
    rng = np.random.default_rng(13)
    b = ContinuousBatcher(cfg, params, max_batch=2, prefill_chunk=4)
    short = rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32)
    r1 = b.submit(short, 20)
    b.step()                                  # r1 active
    slot1 = next(i for i, s in enumerate(b.slots) if s is not None)

    long_p = rng.integers(0, cfg.vocab_size, (18,)).astype(np.int32)
    r2 = b.submit(long_p, 5)                  # 18 > 4: chunked, 4+final
    for _ in range(4):                        # chunk slices 1..4
        n_before = len(b.slots[slot1].tokens)
        b.step()
        assert b._inflight is not None, "inflight finished too early"
        assert b._reserved, "target slot not reserved during chunking"
        assert len(b.slots[slot1].tokens) == n_before + 1, \
            "running slot stalled during chunked admission"
    b.step()                                  # final chunk: scatter+admit
    assert b._inflight is None and not b._reserved
    results = b.run()
    np.testing.assert_array_equal(results[r1],
                                  _oracle(cfg, params, short, 20))
    np.testing.assert_array_equal(results[r2],
                                  _oracle(cfg, params, long_p, 5))


def test_short_requests_bypass_blocked_chunked_head():
    """A second long prompt queued behind an active chunked admission
    must not stall short requests: they admit into free slots while the
    first long prompt streams; all outputs stay greedy-exact."""
    cfg, params = _make()
    rng = np.random.default_rng(14)
    b = ContinuousBatcher(cfg, params, max_batch=3, prefill_chunk=4)
    longs = [rng.integers(0, cfg.vocab_size, (t,)).astype(np.int32)
             for t in (18, 14)]
    shorts = [rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32)
              for _ in range(2)]
    r_l1 = b.submit(longs[0], 5)
    r_l2 = b.submit(longs[1], 5)
    r_s = [b.submit(p, 8) for p in shorts]
    b.step()
    # long-1 is streaming; long-2 blocked; both shorts must be in slots
    assert b._inflight is not None
    active = {s.request_id for s in b.slots if s is not None}
    assert set(r_s) <= active, (active, r_s)
    results = b.run()
    for rid, (p, n) in zip([r_l1, r_l2] + r_s,
                           [(longs[0], 5), (longs[1], 5)]
                           + [(p, 8) for p in shorts]):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(cfg, params, p, n))


def test_speculative_batcher_greedy_exact_and_accepts():
    """Speculative continuous batching: repetitive prompts (lookup hits)
    and novel prompts stay greedy-exact vs solo oracles, per-row
    acceptance actually fires, and each slot commits its OWN accepted
    length (not the batch minimum)."""
    cfg, params = _make()
    rng = np.random.default_rng(15)
    # highly repetitive prompt -> the n-gram lookup drafts well
    rep = np.tile(np.asarray([7, 11, 23], np.int32), 5)
    novel = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
    b = ContinuousBatcher(cfg, params, max_batch=2, speculative_k=4)
    r1 = b.submit(rep, 12)
    r2 = b.submit(novel, 9)
    results = b.run()
    np.testing.assert_array_equal(results[r1],
                                  _oracle(cfg, params, rep, 12))
    np.testing.assert_array_equal(results[r2],
                                  _oracle(cfg, params, novel, 9))
    assert b.spec_proposed > 0
    # the repetitive prompt makes acceptance deterministic under a
    # correct verify: drafts MUST be accepted, and committed tokens must
    # then exceed what one-per-dispatch decoding could produce
    assert b.spec_accepted > 0
    assert b.decode_dispatches < 21


def test_speculative_matches_plain_batcher_and_solo():
    """Staggered mixed-length requests through a speculative batcher
    equal the plain batcher AND the solo oracle token-for-token."""
    cfg, params = _make()
    rng = np.random.default_rng(16)
    reqs = [(np.tile(rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
                     3), n) for n in (10, 7, 5, 8)]
    bs = ContinuousBatcher(cfg, params, max_batch=2, speculative_k=3)
    rids = [bs.submit(p, n) for p, n in reqs]
    res_s = bs.run()
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(res_s[rid], _oracle(cfg, params, p, n))


def test_speculative_eos_truncation():
    """An accepted draft containing eos must truncate exactly where solo
    greedy would stop."""
    cfg, params = _make()
    rng = np.random.default_rng(17)
    p = np.tile(rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32), 4)
    oracle = _oracle(cfg, params, p, 12)
    eos = int(oracle[4])
    b = ContinuousBatcher(cfg, params, max_batch=1, eos_id=eos,
                          speculative_k=4)
    rid = b.submit(p, 12)
    results = b.run()
    first = list(oracle).index(eos)
    np.testing.assert_array_equal(results[rid], oracle[:first + 1])


def test_speculative_composes_with_sampling():
    """Sampled slots inside a speculative batcher draft nothing and
    produce the exact tokens the plain sampling batcher produces (pure
    function of request parameters, regardless of speculation around
    them)."""
    cfg, params = _make()
    rng = np.random.default_rng(18)
    rep = np.tile(np.asarray([5, 9], np.int32), 6)
    nov = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)

    def run(spec):
        b = ContinuousBatcher(cfg, params, max_batch=2,
                              speculative_k=4 if spec else None)
        r_greedy = b.submit(rep, 10)
        r_samp = b.submit(nov, 8, temperature=0.9, top_p=0.8, seed=42)
        res = b.run()
        return res[r_greedy], res[r_samp]

    g_spec, s_spec = run(True)
    g_plain, s_plain = run(False)
    np.testing.assert_array_equal(g_spec, g_plain)
    np.testing.assert_array_equal(s_spec, s_plain)


def test_speculative_with_tp_sharded_params_under_mesh():
    """Speculation composes with distributed inference: the fused verify
    runs over Megatron-tp-sharded params on a 2-device mesh, per-row
    acceptance fires, and outputs equal the solo sharded greedy run."""
    from tensorflowonspark_tpu.parallel import MeshSpec, make_mesh
    from tensorflowonspark_tpu.parallel.sharding import flax_shardings

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                    intermediate_size=64, max_position_embeddings=64,
                    dtype=jnp.float32, pos_encoding="rope")
    params = GPT(cfg).init(jax.random.key(0),
                           jnp.ones((1, 4), jnp.int32))["params"]
    mesh = make_mesh(MeshSpec(tp=2, dp=1), devices=jax.devices()[:2])
    abstract = jax.eval_shape(
        lambda: GPT(cfg).init(jax.random.key(0),
                              jnp.ones((1, 4), jnp.int32)))
    sharded = jax.device_put(params, flax_shardings(mesh, abstract)["params"])

    rep = np.tile(np.asarray([3, 8, 13], np.int32), 4)
    with mesh:
        b = ContinuousBatcher(cfg, sharded, max_batch=2, speculative_k=4)
        rid = b.submit(rep, 12)
        results = b.run()
        want = np.asarray(greedy_generate(
            cfg, sharded, jnp.asarray(rep)[None, :], 12))[0, len(rep):]
    np.testing.assert_array_equal(results[rid], want)
    assert b.spec_accepted > 0


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_fuzz_random_schedules_stay_greedy_exact(seed):
    """Randomized drive: arbitrary submit/step interleavings, mixed
    prompt lengths (short, bucketed, chunked), mixed budgets, random
    slot counts, speculation on/off — every request must equal its solo
    greedy oracle regardless of schedule."""
    cfg, params = _make()
    rng = np.random.default_rng(seed)
    spec = int(rng.integers(0, 2))
    block = None if spec else [None, 4, 8][int(rng.integers(0, 3))]
    b = ContinuousBatcher(
        cfg, params, max_batch=int(rng.integers(1, 5)),
        prefill_chunk=int(rng.integers(4, 9)),
        speculative_k=(3 if spec else None),
        decode_block_steps=block)
    reqs, rids = [], []
    n_req = int(rng.integers(4, 9))
    submitted = 0
    while submitted < n_req:       # run() drains whatever remains after
        if rng.random() < 0.5:
            t = int(rng.integers(2, 20))
            if rng.random() < 0.4:      # repetitive: speculation bites
                p = np.tile(rng.integers(0, cfg.vocab_size,
                                         (2,)).astype(np.int32),
                            (t + 1) // 2)[:t]
            else:
                p = rng.integers(0, cfg.vocab_size, (t,)).astype(np.int32)
            n = int(rng.integers(1, 9))
            reqs.append((p, n))
            rids.append(b.submit(p, n))
            submitted += 1
        for _ in range(int(rng.integers(1, 4))):
            b.step()
    results = b.run()
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(
            results[rid], _oracle(cfg, params, p, n),
            err_msg=f"seed={seed} spec={spec} rid={rid}")


@pytest.mark.parametrize("variant", ["int8", "int4", "gqa", "window"])
def test_speculative_composes_with_decode_features(variant):
    """The fused verify path must stay greedy-exact under quantized
    weights, grouped-query attention, and sliding windows — same
    matrix the plain batcher is locked against."""
    cfg, params = _variant_setup(variant)
    rng = np.random.default_rng(24)
    reqs = [(np.tile(rng.integers(0, cfg.vocab_size,
                                  (3,)).astype(np.int32), 4), n)
            for n in (7, 9, 5)]
    b = ContinuousBatcher(cfg, params, max_batch=2, speculative_k=3)
    rids = [b.submit(p, n) for p, n in reqs]
    results = b.run()
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(cfg, params, p, n))
    assert b.spec_accepted > 0


# -- multi-step decode blocks ---------------------------------------------

def test_block_decode_matches_solo_greedy():
    """decode_block_steps: identical tokens to per-step decode (the scan
    body IS the plain step), across staggered budgets and eos-free
    traffic."""
    cfg, params = _make()
    rng = np.random.default_rng(7)
    reqs = [(rng.integers(0, cfg.vocab_size, (t,)).astype(np.int32), n)
            for t, n in ((5, 16), (3, 9), (8, 4), (2, 13))]
    b = ContinuousBatcher(cfg, params, max_batch=2, decode_block_steps=8)
    rids = [b.submit(p, n) for p, n in reqs]
    results = b.run()
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(cfg, params, p, n))


def test_block_decode_amortizes_dispatches():
    """One request, budget 32, block 8: the decode dispatch count must
    collapse well below the step count (pow2 blocks bounded by remaining
    budget), with decode_steps still counting every step."""
    cfg, params = _make()
    p = np.arange(4, dtype=np.int32) + 1
    b = ContinuousBatcher(cfg, params, max_batch=2, decode_block_steps=8)
    rid = b.submit(p, 33)        # 1 at prefill + 32 decode steps
    res = b.run()
    assert res[rid].size == 33
    assert b.decode_steps == 32
    # 32 steps in 8-blocks: 4 dispatches (+0..2 tail singles depending on
    # pow2 flooring) — far below 32
    assert b.decode_dispatches <= 6, b.decode_dispatches
    np.testing.assert_array_equal(res[rid], _oracle(cfg, params, p, 33))


def test_block_decode_sampled_rows_match_per_step():
    """Sampled requests under blocks: output is the same pure function
    of (seed, step) as the per-step batcher — the in-scan step counter
    must line up exactly."""
    cfg, params = _make()
    rng = np.random.default_rng(9)
    p1 = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
    p2 = rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32)

    def drive(block):
        b = ContinuousBatcher(cfg, params, max_batch=2,
                              decode_block_steps=block)
        r1 = b.submit(p1, 12, temperature=0.8, top_p=0.9, seed=11)
        r2 = b.submit(p2, 7)                      # greedy alongside
        out = b.run()
        return out[r1], out[r2]

    a1, a2 = drive(None)
    b1, b2 = drive(8)
    np.testing.assert_array_equal(a1, b1)
    np.testing.assert_array_equal(a2, b2)
    np.testing.assert_array_equal(b2, _oracle(cfg, params, p2, 7))


def test_block_decode_eos_truncates_and_slot_reuses():
    """A row hitting eos mid-block: later scanned tokens are discarded,
    the slot frees, and a follow-up request admitted into that slot
    stays exact."""
    cfg, params = _make()
    p = np.arange(5, dtype=np.int32) + 1
    ref = _oracle(cfg, params, p, 24)
    eos = int(ref[2])
    # the oracle-with-eos stops at the FIRST occurrence of that token
    cut = int(np.flatnonzero(ref == eos)[0])
    b = ContinuousBatcher(cfg, params, max_batch=1, eos_id=eos,
                          decode_block_steps=8)
    r1 = b.submit(p, 24)
    got = b.run()[r1]
    np.testing.assert_array_equal(got, ref[:cut + 1])
    p2 = np.arange(4, dtype=np.int32) + 2
    r2 = b.submit(p2, 6)
    out = b.run()
    ref2 = _oracle(cfg, params, p2, 6)
    cut2 = np.flatnonzero(ref2 == eos)
    if cut2.size:                 # same eos id applies to the follow-up
        ref2 = ref2[:int(cut2[0]) + 1]
    np.testing.assert_array_equal(out[r2], ref2)


def test_block_decode_admission_latency_policy():
    """Admission precedes the block decision inside one step(), so a
    queued request with a free slot admits immediately.  For a request
    that CANNOT admit yet (no free slot): with ``eos_id`` set, an eos
    could free a slot any step, so the batcher must single-step; without
    eos, no slot can free before the minimum remaining budget, so
    blocking up to that bound delays the queued request by zero steps
    and MUST be taken."""
    cfg, params = _make()
    rng = np.random.default_rng(3)
    p1 = rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32)
    p2 = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)

    # eos set -> conservative single steps while a request waits
    b = ContinuousBatcher(cfg, params, max_batch=1, decode_block_steps=8,
                          eos_id=cfg.vocab_size + 1)   # never fires
    r1 = b.submit(p1, 20)
    b.step()                     # admit r1; r1 owns the only slot
    steps_before = b.decode_steps
    r2 = b.submit(p2, 5)         # cannot admit: no free slot
    b.step()
    assert b.decode_steps - steps_before == 1  # single, not a block
    out = b.run()
    np.testing.assert_array_equal(out[r1], _oracle(cfg, params, p1, 20))
    np.testing.assert_array_equal(out[r2], _oracle(cfg, params, p2, 5))

    # no eos -> blocks keep running while the request waits (zero-delay
    # bound) and amortization survives a full backlog drain
    b2 = ContinuousBatcher(cfg, params, max_batch=1, decode_block_steps=8)
    q1 = b2.submit(p1, 20)
    b2.step()
    q2 = b2.submit(p2, 5)
    b2.step()
    assert b2.decode_steps > b2.decode_dispatches  # a block ran
    out2 = b2.run()
    np.testing.assert_array_equal(out2[q1], _oracle(cfg, params, p1, 20))
    np.testing.assert_array_equal(out2[q2], _oracle(cfg, params, p2, 5))
    # first tokens come from the prefills: 19 + 4 decode steps total
    assert b2.decode_steps == 23
    assert b2.decode_dispatches < 12           # ... in far fewer dispatches


# -- streaming callback + load snapshot -----------------------------------

@pytest.mark.parametrize("kw", [{}, {"decode_block_steps": 8},
                                {"speculative_k": 3},
                                {"prefill_chunk": 4}])
def test_on_token_streams_exactly_the_oracle(kw):
    """The ``submit(on_token=...)`` stream equals the solo greedy oracle
    token-for-token, in order, under every decode regime (per-step,
    scanned blocks, speculative verify, chunked prefill) — discarded
    block/draft tokens never surface."""
    cfg, params = _make()
    rng = np.random.default_rng(30)
    streamed: dict[int, list] = {}

    def on_token(rid, tok):
        streamed.setdefault(rid, []).append(tok)

    b = ContinuousBatcher(cfg, params, max_batch=2, **kw)
    # repetitive prompt so speculation drafts; a long one so chunking
    # chunks; mixed budgets so slots churn
    reqs = [(np.tile(np.asarray([7, 11, 23], np.int32), 5), 10),
            (rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32), 7),
            (rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32), 1)]
    rids = [b.submit(p, n, on_token=on_token) for p, n in reqs]
    results = b.run()
    for rid, (p, n) in zip(rids, reqs):
        oracle = _oracle(cfg, params, p, n).tolist()
        assert streamed[rid] == oracle, f"stream diverged ({kw})"
        assert results[rid].tolist() == oracle
    assert not b._on_token, "finished requests must drop their callbacks"


def test_on_token_fires_before_finish_and_with_eos():
    """Tokens stream as they commit (mid-flight, not at the end): after
    the first step the stream holds exactly the first oracle token while
    the request is still running; an eos stop truncates the stream
    exactly like the result."""
    cfg, params = _make()
    rng = np.random.default_rng(32)
    p = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
    oracle = _oracle(cfg, params, p, 6)
    streamed: list = []
    b = ContinuousBatcher(cfg, params, max_batch=1)
    rid = b.submit(p, 6, on_token=lambda r, t: streamed.append((r, t)))
    b.step()   # admits (prefill commits token 1) + one decode step
    early = [t for r, t in streamed if r == rid]
    assert early == oracle[: len(early)].tolist() and 0 < len(early) < 6
    assert b.result(rid) is None, "tokens must stream BEFORE finish"
    results = b.run()
    assert [t for _, t in streamed] == results[rid].tolist() \
        == oracle.tolist()

    # eos truncation: the stream ends where the result ends (first eos),
    # not at the budget
    eos = int(oracle[0])
    streamed2: list = []
    b2 = ContinuousBatcher(cfg, params, max_batch=1, eos_id=eos)
    rid2 = b2.submit(p, 10, on_token=lambda r, t: streamed2.append(t))
    res2 = b2.run()[rid2]
    first = list(_oracle(cfg, params, p, 10)).index(eos)
    assert streamed2 == res2.tolist() \
        == _oracle(cfg, params, p, 10)[: first + 1].tolist()


def test_load_counts_every_live_request_once():
    cfg, params = _make()
    b = ContinuousBatcher(cfg, params, max_batch=2, prefill_chunk=4)
    # dense mode: no page pool, so the memory-pressure gauges read 0
    assert b.load() == {"active": 0, "pending": 0, "reserved": 0,
                        "total": 0, "free_pages": 0, "total_pages": 0}
    rng = np.random.default_rng(31)
    b.submit(rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32), 6)
    b.submit(rng.integers(0, cfg.vocab_size, (18,)).astype(np.int32), 5)
    b.submit(rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32), 6)
    assert b.load() == {"active": 0, "pending": 3, "reserved": 0,
                        "total": 3, "free_pages": 0, "total_pages": 0}
    b.step()
    # short prompt active; the long one is the in-flight chunked
    # admission (pending, with its slot reserved); the third queued
    load = b.load()
    assert load["total"] == 3, load
    assert load["active"] >= 1 and load["reserved"] == 1, load
    b.run()
    assert b.load() == {"active": 0, "pending": 0, "reserved": 0,
                        "total": 0, "free_pages": 0, "total_pages": 0}


# -- paged KV + shared prefix cache (kv_page_tokens) ----------------------

@pytest.mark.parametrize("pos_encoding", ["rope", "learned"])
def test_paged_matches_solo_greedy(pos_encoding):
    """Paged-KV decode (block-table pool instead of the dense cache) is
    token-exact vs the solo greedy oracle across staggered mixed-length
    requests — the locked contract, paged edition."""
    cfg, params = _make(pos_encoding)
    rng = np.random.default_rng(40)
    reqs = [(rng.integers(0, cfg.vocab_size, (t,)).astype(np.int32), n)
            for t, n in ((5, 7), (3, 12), (8, 4), (9, 9), (2, 6), (6, 1))]
    b = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    rids = [b.submit(p, n) for p, n in reqs]
    results = b.run()
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(cfg, params, p, n))
    # pages all returned (free + still-cached prefix pages = the pool)
    st = b.prefix_stats()
    assert st["free_pages"] == st["total_pages"], st


def test_paged_prefix_hit_skips_reprefill_and_stays_exact():
    """Same-system-prompt requests: the first admission misses and
    indexes its full prompt pages; later ones match the chain, prefill
    only their tails, and stay greedy-exact.  A prompt diverging
    MID-page matches only up to the divergence page (copy-on-write: it
    prefills a private copy, the shared original is untouched — the
    original must still hit afterwards)."""
    cfg, params = _make()
    rng = np.random.default_rng(41)
    pre = rng.integers(0, cfg.vocab_size, (17,)).astype(np.int32)  # 2 pages
    A = np.concatenate([pre, rng.integers(0, cfg.vocab_size,
                                          (3,)).astype(np.int32)])
    B = A.copy()
    B[11] = (B[11] + 1) % cfg.vocab_size      # diverges inside page 2
    b = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    ra = b.submit(A, 5)
    b.run()
    assert b.prefix_stats()["miss"] == 1
    rb = b.submit(B, 5)
    res = b.run()
    np.testing.assert_array_equal(res[rb], _oracle(cfg, params, B, 5))
    assert b.prefix_stats()["partial"] == 1   # shared page 1, private 2
    ra2 = b.submit(A, 5)
    res = b.run()
    np.testing.assert_array_equal(res[ra2], _oracle(cfg, params, A, 5))
    np.testing.assert_array_equal(b.result(ra), res[ra2])
    assert b.prefix_stats()["hit"] == 1, b.prefix_stats()


def test_paged_exhaustion_backpressures_then_drains_exact():
    """A pool too small for the queue: admission blocks on free pages
    (not free slots), requests wait their turn, every one completes
    greedy-exact, and the pool leaks nothing."""
    cfg, params = _make()
    rng = np.random.default_rng(42)
    b = ContinuousBatcher(cfg, params, max_batch=4, kv_page_tokens=8,
                          kv_pool_pages=6)
    # 30 tokens -> 4 pages each: only one fits at a time despite 4 slots
    reqs = [(rng.integers(0, cfg.vocab_size, (10,)).astype(np.int32), 20)
            for _ in range(3)]
    rids = [b.submit(p, n) for p, n in reqs]
    b.step()
    assert sum(s is not None for s in b.slots) == 1, \
        "page exhaustion must hold admissions back"
    load = b.load()
    assert load["pending"] == 2 and load["total_pages"] == 6, load
    res = b.run()
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(res[rid], _oracle(cfg, params, p, n))
    st = b.prefix_stats()
    assert st["free_pages"] == st["total_pages"] == 6, st
    assert all(s is None for s in b.slots)


def test_paged_submit_rejects_requests_larger_than_the_pool():
    cfg, params = _make()
    b = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                          kv_pool_pages=3)   # 24 tokens max
    with pytest.raises(ValueError, match="KV pages"):
        b.submit(np.arange(20, dtype=np.int32) % cfg.vocab_size, 10)
    rid = b.submit(np.arange(10, dtype=np.int32) % cfg.vocab_size, 10)
    np.testing.assert_array_equal(
        b.run()[rid], _oracle(cfg, params,
                              np.arange(10, dtype=np.int32)
                              % cfg.vocab_size, 10))


def test_paged_eviction_under_pressure_then_reprefill_exact():
    """Cached prefix pages are evicted (LRU, refcount 0 only) when the
    pool runs dry; a later request for the evicted prefix re-prefills
    from scratch and is still exact."""
    cfg, params = _make()
    rng = np.random.default_rng(43)
    b = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                          kv_pool_pages=8)
    A = rng.integers(0, cfg.vocab_size, (17,)).astype(np.int32)
    b.submit(A, 4)
    b.run()
    for _ in range(3):          # churn: evicts A's cached pages
        b.submit(rng.integers(0, cfg.vocab_size, (20,)).astype(np.int32),
                 20)
        b.run()
    assert b.prefix_stats()["evictions"] > 0
    ra = b.submit(A, 4)
    np.testing.assert_array_equal(b.run()[ra], _oracle(cfg, params, A, 4))


def test_paged_mixed_greedy_sampled_hit_and_miss_paths():
    """Hit-vs-miss exactness under mixed traffic: greedy requests stay
    oracle-exact and a sampled request is the same pure function of
    (seed, temp, top_p) whether its prefix hits the cache, misses it,
    or the batcher is dense."""
    cfg, params = _make()
    rng = np.random.default_rng(44)
    pre = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
    samp_p = np.concatenate([pre, rng.integers(0, cfg.vocab_size,
                                               (4,)).astype(np.int32)])
    greedy_p = np.concatenate([pre, rng.integers(0, cfg.vocab_size,
                                                 (3,)).astype(np.int32)])

    def run(paged, warm):
        b = ContinuousBatcher(cfg, params, max_batch=2,
                              **({"kv_page_tokens": 8} if paged else {}))
        if warm:    # populate the prefix index so the next admits HIT
            b.submit(np.concatenate(
                [pre, np.asarray([1], np.int32)]), 2)
            b.run()
        rs = b.submit(samp_p, 8, temperature=0.8, top_p=0.9, seed=7)
        rg = b.submit(greedy_p, 8)
        res = b.run()
        if warm:
            st = b.prefix_stats()
            assert st["hit"] >= 2, st
        return res[rs], res[rg]

    s_hit, g_hit = run(True, True)
    s_miss, g_miss = run(True, False)
    s_dense, g_dense = run(False, False)
    np.testing.assert_array_equal(s_hit, s_dense)
    np.testing.assert_array_equal(s_miss, s_dense)
    np.testing.assert_array_equal(g_hit, g_dense)
    np.testing.assert_array_equal(g_miss, g_dense)
    np.testing.assert_array_equal(g_dense,
                                  _oracle(cfg, params, greedy_p, 8))


@pytest.mark.parametrize("kw", [{"prefill_chunk": 6},
                                {"decode_block_steps": 8},
                                {"speculative_k": 4}])
def test_paged_composes_with_decode_regimes(kw):
    """Paged KV under every decode regime (time-sliced chunked prefill,
    scanned blocks, speculative verify): greedy-exact, including a
    prefix-hit admission mid-composition."""
    cfg, params = _make()
    rng = np.random.default_rng(45)
    pre = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
    reqs = [(np.concatenate([pre, rng.integers(
        0, cfg.vocab_size, (k,)).astype(np.int32)]), n)
        for k, n in ((3, 8), (5, 6))]
    reqs.append((np.tile(np.asarray([7, 11, 23], np.int32), 5), 10))
    b = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                          **kw)
    rids = [b.submit(p, n) for p, n in reqs]
    results = b.run()
    for rid, (p, n) in zip(rids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(cfg, params, p, n))


def test_paged_with_tp_sharded_params_under_mesh():
    """Paged decode over Megatron-tp-sharded params on a 2-device mesh
    (the pool's head axis shards with tp): greedy-exact vs the solo
    sharded oracle, with a prefix hit in the mix."""
    from tensorflowonspark_tpu.parallel import MeshSpec, make_mesh
    from tensorflowonspark_tpu.parallel.sharding import flax_shardings

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                    intermediate_size=64, max_position_embeddings=48,
                    dtype=jnp.float32, pos_encoding="rope")
    params = GPT(cfg).init(jax.random.key(0),
                           jnp.ones((1, 4), jnp.int32))["params"]
    mesh = make_mesh(MeshSpec(tp=2, dp=1), devices=jax.devices()[:2])
    abstract = jax.eval_shape(
        lambda: GPT(cfg).init(jax.random.key(0), jnp.ones((1, 4), jnp.int32)))
    sharded = jax.device_put(params,
                             flax_shardings(mesh, abstract)["params"])

    rng = np.random.default_rng(46)
    pre = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
    reqs = [(np.concatenate([pre, rng.integers(
        0, cfg.vocab_size, (k,)).astype(np.int32)]), n)
        for k, n in ((3, 8), (4, 6))]
    with mesh:
        b = ContinuousBatcher(cfg, sharded, max_batch=2, kv_page_tokens=8)
        results = {}
        for p, n in reqs:    # serialized so the second admission HITS
            rid = b.submit(p, n)
            results[rid] = b.run()[rid]
        for rid, (p, n) in zip(sorted(results), reqs):
            want = np.asarray(greedy_generate(
                cfg, sharded, jnp.asarray(p)[None, :], n))[0, len(p):]
            np.testing.assert_array_equal(results[rid], want)
    assert b.prefix_stats()["hit"] >= 1


def test_paged_validation():
    cfg, params = _make()
    with pytest.raises(ValueError, match="power of two"):
        ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=6)
    with pytest.raises(ValueError, match="divide"):
        ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=32)
    with pytest.raises(ValueError, match="kv_page_tokens"):
        ContinuousBatcher(cfg, params, max_batch=2, kv_pool_pages=8)
    with pytest.raises(ValueError, match="kv_pool_pages"):
        ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                          kv_pool_pages=0)
    cfg8, params8 = _make(kv_cache_int8=True)
    with pytest.raises(ValueError, match="kv_cache_int8"):
        ContinuousBatcher(cfg8, params8, max_batch=2, kv_page_tokens=8)


def test_block_decode_validation():
    cfg, params = _make()
    with pytest.raises(ValueError, match="decode_block_steps"):
        ContinuousBatcher(cfg, params, max_batch=2, decode_block_steps=1)
    with pytest.raises(ValueError, match="mutually exclusive"):
        ContinuousBatcher(cfg, params, max_batch=2, decode_block_steps=4,
                          speculative_k=2)


# ---------------------------------------------------------------------------
# KV-page session handoff (disaggregated prefill/decode; docs/serving.md)

def _drive_handoff(pre, max_steps=30):
    """Step a prefill-only batcher until its pending work is exported;
    returns every (request_id, session) pair."""
    sessions = []
    for _ in range(max_steps):
        pre.step()
        sessions.extend(pre.take_sessions())
        if not pre.load()["total"]:
            break
    return sessions


def test_handoff_greedy_exact_on_miss_path():
    """Prefill-only export → decode adopt: the stitched stream (first
    token from the prefill side + the decode side's tokens) equals the
    solo greedy oracle, and the decode batcher never runs a prefill
    dispatch."""
    cfg, params = _make()
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, cfg.vocab_size, (t,)).astype(np.int32), n)
            for t, n in ((5, 7), (11, 5), (16, 6), (3, 9))]
    pre = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                            prefill_only=True)
    rids = [pre.submit(p, n) for p, n in reqs]
    sessions = dict(_drive_handoff(pre))
    assert sorted(sessions) == sorted(rids)
    assert pre.sessions_exported == len(reqs)
    assert pre.decode_dispatches == 0, "a prefill pool must never step"

    dec = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    dmap = {dec.adopt_session(sessions[rid]): rid for rid in rids}
    results = dec.run()
    assert dec.prefill_dispatches == 0, \
        "a decode gang must never re-prefill an adopted session"
    assert dec.sessions_adopted == len(reqs)
    for drid, prid in dmap.items():
        prompt, n = reqs[rids.index(prid)]
        np.testing.assert_array_equal(results[drid],
                                      _oracle(cfg, params, prompt, n))


def test_handoff_sampled_exact():
    """A sampled session hands off with its sampler state: the decode
    side's continuation is token-identical to an unsplit batcher run of
    the same (prompt, budget, temperature, top_p, seed)."""
    cfg, params = _make()
    prompt = np.asarray([7, 3, 9, 1, 4, 2, 8], np.int32)
    kw = dict(temperature=0.8, top_p=0.9, seed=123)
    pre = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                            prefill_only=True)
    rid = pre.submit(prompt, 9, **kw)
    [(_, sess)] = _drive_handoff(pre)
    dec = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    drid = dec.adopt_session(sess)
    got = dec.run()[drid]

    solo = ContinuousBatcher(cfg, params, max_batch=1, kv_page_tokens=8)
    srid = solo.submit(prompt, 9, **kw)
    np.testing.assert_array_equal(got, solo.run()[srid])


def test_handoff_prefix_hit_path_exact_and_imports_only_tail():
    """A decode pool already holding the session's system prefix adopts
    WITHOUT importing the matched pages (cross-request reuse composes
    with the handoff) and stays oracle-exact."""
    cfg, params = _make()
    rng = np.random.default_rng(1)
    sysp = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
    dec = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    seed_p = np.concatenate([sysp, rng.integers(0, cfg.vocab_size, (3,))
                             .astype(np.int32)])
    dec.submit(seed_p, 4)
    dec.run()                               # seeds sysp's 2 full pages
    h0 = dec.prefix_stats()

    prompt = np.concatenate([sysp, rng.integers(0, cfg.vocab_size, (5,))
                             .astype(np.int32)])
    pre = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                            prefill_only=True)
    pre.submit(prompt, 6)
    [(_, sess)] = _drive_handoff(pre)
    drid = dec.adopt_session(sess)
    got = dec.run()[drid]
    h1 = dec.prefix_stats()
    assert h1["hit"] == h0["hit"] + 1, "adopt missed the seeded prefix"
    np.testing.assert_array_equal(got, _oracle(cfg, params, prompt, 6))


def test_adopt_rejects_corrupt_and_mismatched_sessions_loudly():
    """A transfer whose per-page content hashes or layout signature
    don't verify raises a typed ``ValueError`` from ``adopt_session``
    itself — before any device write, without poisoning the batcher."""
    cfg, params = _make()
    prompt = np.asarray([5, 4, 3, 2, 1, 6, 7, 8, 9], np.int32)
    pre = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                            prefill_only=True)
    pre.submit(prompt, 5)
    [(_, sess)] = _drive_handoff(pre)

    dec = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    corrupt = dict(sess)
    corrupt["kv"] = [np.array(a, copy=True) for a in sess["kv"]]
    corrupt["kv"][0].flat[5] += 1
    with pytest.raises(ValueError, match="content hash mismatch"):
        dec.adopt_session(corrupt)
    mismatched = dict(sess, page_tokens=16)
    with pytest.raises(ValueError, match="page_tokens"):
        dec.adopt_session(mismatched)
    # a key-skewed descriptor is ValueError too — a KeyError would
    # escape the serve loop's typed-error bounce and crash the worker
    truncated = {k: v for k, v in sess.items() if k != "page_hashes"}
    with pytest.raises(ValueError, match="missing key"):
        dec.adopt_session(truncated)
    raced = dict(sess)
    raced["kv"] = [a[..., :-1] for a in sess["kv"]]
    with pytest.raises(ValueError, match="layout mismatch"):
        dec.adopt_session(raced)
    # the rejections never touched the engine: it still serves exactly
    drid = dec.adopt_session(sess)
    np.testing.assert_array_equal(dec.run()[drid],
                                  _oracle(cfg, params, prompt, 5))


def test_prefill_only_validation_and_direct_finish():
    cfg, params = _make()
    with pytest.raises(ValueError, match="kv_page_tokens"):
        ContinuousBatcher(cfg, params, max_batch=2, prefill_only=True)
    with pytest.raises(ValueError, match="decode-time"):
        ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                          prefill_only=True, speculative_k=2)
    pre = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                            prefill_only=True)
    with pytest.raises(ValueError, match="prefill-only"):
        pre.adopt_session({"v": 1})
    # a budget-1 request finishes AT the prefill (no session to hand
    # off): the prefill pool completes it directly
    prompt = np.asarray([1, 2, 3], np.int32)
    rid = pre.submit(prompt, 1)
    done = []
    for _ in range(5):
        done += pre.step()
        if done:
            break
    assert done == [rid] and not pre.take_sessions()
    np.testing.assert_array_equal(pre.result(rid),
                                  _oracle(cfg, params, prompt, 1))


def test_set_role_specializes_idle_engine_both_ways():
    """Promote-with-role (warm standby joining a disagg pool): a
    role-less engine flips to prefill posture and exports a session
    exactly as a constructor-built prefill pool would, then flips back
    to decode posture and adopts it — the two specializations one warm
    pool must be able to back."""
    cfg, params = _make()
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, cfg.vocab_size, (10,)).astype(np.int32)
    b = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    assert not b.prefill_only
    b.set_role("prefill")
    assert b.prefill_only
    b.submit(prompt, 5)
    sessions = _drive_handoff(b)
    assert len(sessions) == 1 and b.decode_dispatches == 0
    b.set_role("decode")
    assert not b.prefill_only
    drid = b.adopt_session(sessions[0][1])
    np.testing.assert_array_equal(b.run()[drid],
                                  _oracle(cfg, params, prompt, 5))
    assert b.prefill_dispatches == 1     # the pre-handoff prefill only


def test_set_role_validation():
    cfg, params = _make()
    b = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    with pytest.raises(ValueError, match="unknown role"):
        b.set_role("both")
    # prefill posture keeps the constructor's constraints
    unpaged = ContinuousBatcher(cfg, params, max_batch=2)
    with pytest.raises(ValueError, match="paged KV"):
        unpaged.set_role("prefill")
    spec = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                             speculative_k=2)
    with pytest.raises(ValueError, match="decode-time"):
        spec.set_role("prefill")
    # a live request pins the posture
    b.submit(np.asarray([1, 2, 3], np.int32), 3)
    with pytest.raises(RuntimeError, match="live requests"):
        b.set_role("prefill")
    b.run()
    b.set_role("prefill")                # drained: legal again
    assert b.prefill_only


def test_handoff_composes_with_chunked_prefill():
    """A long prompt streamed through the prefill pool's chunked
    admission exports the identical session a whole-prompt prefill
    would: the decode side stays oracle-exact."""
    cfg, params = _make()
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, cfg.vocab_size, (30,)).astype(np.int32)
    pre = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8,
                            prefill_chunk=8, prefill_only=True)
    pre.submit(prompt, 6)
    sessions = _drive_handoff(pre)
    assert len(sessions) == 1
    dec = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    drid = dec.adopt_session(sessions[0][1])
    np.testing.assert_array_equal(dec.run()[drid],
                                  _oracle(cfg, params, prompt, 6))


def test_export_import_prefix_cache_roundtrip_exact():
    """The standby promotion's page clone: a donor's prefix-cache
    export imports into a fresh batcher as matchable cached pages, and
    decoding against them is oracle-exact (hash-verified; corrupt
    imports rejected)."""
    cfg, params = _make()
    rng = np.random.default_rng(4)
    sysp = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
    donor = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    donor.submit(np.concatenate(
        [sysp, rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32)]), 4)
    donor.run()
    export = donor.export_prefix_cache()
    assert export is not None and export["pages"] >= 2

    imp = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    assert imp.import_prefix_cache(export) == export["pages"]
    probe = np.concatenate(
        [sysp, rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32)])
    rid = imp.submit(probe, 5)
    got = imp.run()[rid]
    assert imp.prefix_stats()["hit"] == 1, "imported pages never matched"
    np.testing.assert_array_equal(got, _oracle(cfg, params, probe, 5))

    bad = dict(export)
    bad["kv"] = [np.array(a, copy=True) for a in export["kv"]]
    bad["kv"][0].flat[0] += 1
    fresh = ContinuousBatcher(cfg, params, max_batch=2, kv_page_tokens=8)
    with pytest.raises(ValueError, match="content hash mismatch"):
        fresh.import_prefix_cache(bad)
    # dense batchers have nothing to export/import
    dense = ContinuousBatcher(cfg, params, max_batch=2)
    assert dense.export_prefix_cache() is None
    assert dense.import_prefix_cache(export) == 0
