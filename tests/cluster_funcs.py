"""Top-level map_fun fixtures for cluster integration tests.

Must live in an importable module so ``multiprocessing`` spawn can pickle
them — the same constraint Spark puts on closures shipped to executors.
Mirrors the reference's tiny inline map_funs (SURVEY.md §4: orchestration is
tested with trivial functions, real models live in examples/).
"""

import os


def fn_noop(args, ctx):
    """Registers, does nothing, exits cleanly."""


def fn_write_role(args, ctx):
    """Record each node's role assignment for template assertions."""
    path = os.path.join(ctx.working_dir, f"role.{ctx.executor_id}")
    with open(path, "w") as f:
        f.write(f"{ctx.job_name}:{ctx.task_index}:{int(ctx.is_chief)}:{ctx.num_workers}")


def fn_sum_feed(args, ctx):
    """Consume the feed, write the running sum (train-mode round trip)."""
    feed = ctx.get_data_feed(train_mode=True)
    total = 0
    count = 0
    while not feed.should_stop():
        batch = feed.next_batch(args["batch_size"], timeout=30)
        total += sum(batch)
        count += len(batch)
    with open(os.path.join(ctx.working_dir, f"sum.{ctx.executor_id}"), "w") as f:
        f.write(f"{total}:{count}")


def fn_square_inference(args, ctx):
    """Echo x**2 for every sample (inference round trip)."""
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(4, timeout=30)
        if batch:
            feed.batch_results([x * x for x in batch])


def fn_tiny_batch_inference(args, ctx):
    """Emit one result message per sample — maximal output-queue pressure
    (regression: inference must drain results while its puts are blocked)."""
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(1, timeout=30)
        if batch:
            feed.batch_results([x + 1000 for x in batch])


def fn_crash(args, ctx):
    raise ValueError("deliberate failure for error-propagation test")


def fn_crash_before_register(args, ctx):  # pragma: no cover - not called
    raise RuntimeError("unused")


def fn_train_linear_export(args, ctx):
    """Train y ≈ w·x + b from the feed; chief exports a serving signature.

    The pipeline-test workload (reference model: the small Keras model in
    ``tests/test_pipeline.py`` upstream): real SGD on the fed data followed
    by a chief-only export that TFModel.transform loads back.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    feed = ctx.get_data_feed(train_mode=True)
    w = jnp.zeros(())
    b = jnp.zeros(())
    lr = args.lr

    @jax.jit
    def step(w, b, x, y):
        def loss(w, b):
            return jnp.mean((w * x + b - y) ** 2)

        gw, gb = jax.grad(loss, argnums=(0, 1))(w, b)
        return w - lr * gw, b - lr * gb

    while not feed.should_stop():
        batch = feed.next_batch_arrays(args.batch_size, timeout=30)
        if batch is None:
            break
        x, y = batch
        w, b = step(w, b, jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32))

    if ctx.is_chief:
        from tensorflowonspark_tpu.checkpoint import export_model

        def serve(p, x):
            return p["w"] * x + p["b"]

        export_model(args.export_dir, serve, {"w": w, "b": b},
                     [np.zeros((2,), np.float32)],
                     input_names=["x"], output_names=["y"], is_chief=True)


def fn_terminating_consumer(args, ctx):
    """Read a few batches then terminate early (early-stop semantics)."""
    feed = ctx.get_data_feed()
    feed.next_batch(4, timeout=30)
    feed.terminate(drain_secs=1.0)
    with open(os.path.join(ctx.working_dir, f"term.{ctx.executor_id}"), "w") as f:
        f.write("terminated")


def fn_distributed_pjit_train(args, ctx):
    """Cross-process SPMD training: ``ctx.initialize_distributed()`` over
    loopback (CPU backend, gloo collectives) + one jitted train step whose
    mesh spans BOTH worker processes.

    Exercises the composed path SURVEY.md §4 calls the "local-cluster
    pattern": agents/local procs + coordination service + cross-process
    collectives (reference analogue: TF_CONFIG + MultiWorkerMirrored over
    two Spark executors).  Writes ``dist.<id>`` with the final loss/weights
    so the driver can compare against the single-process value.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    ctx.initialize_distributed()

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == ctx.num_workers, jax.process_count()
    devs = jax.devices()  # global device list, across processes
    mesh = Mesh(np.array(devs), ("dp",))
    rep = NamedSharding(mesh, P())

    rng = np.random.default_rng(0)
    X = rng.standard_normal((8, 4)).astype(np.float32)
    y = (X @ np.arange(1.0, 5.0, dtype=np.float32)).astype(np.float32)
    xsh = NamedSharding(mesh, P("dp"))
    Xg = jax.make_array_from_callback(X.shape, xsh, lambda i: X[i])
    yg = jax.make_array_from_callback(y.shape, xsh, lambda i: y[i])

    lr = 0.1

    @jax.jit
    def train_step(w, X, y):
        def loss_fn(w):
            return jnp.mean((X @ w - y) ** 2)  # mean over the GLOBAL batch

        loss, g = jax.value_and_grad(loss_fn)(w)
        return w - lr * g, loss

    w = jax.device_put(jnp.zeros((4,), jnp.float32), rep)
    for _ in range(int(args.get("steps", 3))):
        w, loss = train_step(w, Xg, yg)

    path = os.path.join(ctx.working_dir, f"dist.{ctx.executor_id}")
    w_host = np.asarray(jax.device_get(w))
    with open(path, "w") as f:
        f.write(f"{jax.process_count()}:{len(devs)}:{float(loss):.8f}:"
                + ",".join(f"{v:.8f}" for v in w_host))


def fn_train_checkpoint_crash_once(args, ctx):
    """Deterministic 'training' with orbax checkpoints; injects ONE chief
    crash mid-run on the first attempt (sentinel file) so
    ``run_with_recovery``'s relaunch-then-resume path is exercised.

    Appends each attempt's start step to ``resume.<id>`` — the test asserts
    the relaunch resumed from the checkpoint, not step 0.
    """
    import numpy as np

    from tensorflowonspark_tpu.checkpoint import CheckpointManager

    total, crash_at = args["total_steps"], args["crash_at"]
    ckpt = CheckpointManager(args["model_dir"])
    start, w = 0, np.zeros(())
    latest = ckpt.latest_step()
    if latest is not None:
        state = ckpt.restore()
        start, w = int(state["step"]), np.asarray(state["w"])
    with open(os.path.join(ctx.working_dir, f"resume.{ctx.executor_id}"), "a") as f:
        f.write(f"{start}\n")

    sentinel = os.path.join(ctx.working_dir, "crash-injected")
    for s in range(start, total):
        w = w + 1.0
        step = s + 1
        if ctx.is_chief and step == crash_at and not os.path.exists(sentinel):
            ckpt.save(step, {"step": np.asarray(step), "w": w}, force=True)
            ckpt.wait()
            with open(sentinel, "w"):
                pass
            raise RuntimeError("injected preemption")
    if ctx.is_chief:
        ckpt.save(total, {"step": np.asarray(total), "w": w}, force=True)
        ckpt.close()


def fn_distributed_pipeline_train(args, ctx):
    """Cross-process PIPELINE parallelism: a pp=2 mesh spanning two worker
    processes, so the GPipe schedule's stage-hop ``ppermute`` crosses a
    real process boundary (gloo) — the multihost path single-process tests
    can't reach.  Writes ``pipe.<id>`` with the loss trajectory."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    ctx.initialize_distributed()

    import numpy as np
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.parallel import make_mesh, pipeline_apply
    from tensorflowonspark_tpu.parallel.mesh import MeshSpec
    from jax.sharding import NamedSharding, PartitionSpec as P

    devs = jax.devices()
    assert len(devs) == 2 and jax.process_count() == 2
    mesh = make_mesh(MeshSpec(pp=2, dp=1), devices=devs)

    def stage_fn(p, x):
        return x + jnp.tanh(x @ p["w"])

    hid, num_mb, steps = 8, 2, int(args.get("steps", 2))
    rng = np.random.default_rng(0)
    w0 = (rng.standard_normal((2, hid, hid)) * 0.1).astype(np.float32)
    x_np = rng.standard_normal((4, hid)).astype(np.float32)
    tx = optax.sgd(0.1)

    stacked_sh = NamedSharding(mesh, P("pp", None, None))
    stacked = jax.make_array_from_callback(
        w0.shape, stacked_sh, lambda i: w0[i])
    params = {"w": stacked}
    opt_state = jax.jit(tx.init)(params)
    x = jax.device_put(jnp.asarray(x_np), NamedSharding(mesh, P()))

    @jax.jit
    def train_step(params, opt_state, x):
        def loss_fn(p):
            y = pipeline_apply(mesh, stage_fn, p, x, num_microbatches=num_mb)
            return jnp.mean(y ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, x)
        losses.append(float(loss))

    path = os.path.join(ctx.working_dir, f"pipe.{ctx.executor_id}")
    with open(path, "w") as f:
        f.write(":".join(f"{v:.8f}" for v in losses))
