"""Top-level map_fun fixtures for cluster integration tests.

Must live in an importable module so ``multiprocessing`` spawn can pickle
them — the same constraint Spark puts on closures shipped to executors.
Mirrors the reference's tiny inline map_funs (SURVEY.md §4: orchestration is
tested with trivial functions, real models live in examples/).
"""

import os


def fn_noop(args, ctx):
    """Registers, does nothing, exits cleanly."""


def fn_write_role(args, ctx):
    """Record each node's role assignment for template assertions."""
    path = os.path.join(ctx.working_dir, f"role.{ctx.executor_id}")
    with open(path, "w") as f:
        f.write(f"{ctx.job_name}:{ctx.task_index}:{int(ctx.is_chief)}:{ctx.num_workers}")


def fn_sum_feed(args, ctx):
    """Consume the feed, write the running sum (train-mode round trip)."""
    feed = ctx.get_data_feed(train_mode=True)
    total = 0
    count = 0
    while not feed.should_stop():
        batch = feed.next_batch(args["batch_size"], timeout=30)
        total += sum(batch)
        count += len(batch)
    with open(os.path.join(ctx.working_dir, f"sum.{ctx.executor_id}"), "w") as f:
        f.write(f"{total}:{count}")


def fn_square_inference(args, ctx):
    """Echo x**2 for every sample (inference round trip)."""
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(4, timeout=30)
        if batch:
            feed.batch_results([x * x for x in batch])


def fn_tiny_batch_inference(args, ctx):
    """Emit one result message per sample — maximal output-queue pressure
    (regression: inference must drain results while its puts are blocked)."""
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(1, timeout=30)
        if batch:
            feed.batch_results([x + 1000 for x in batch])


def fn_crash(args, ctx):
    raise ValueError("deliberate failure for error-propagation test")


def fn_crash_infra(args, ctx):
    """Crash with an infra-shaped error (retried by run_with_recovery's
    classifier, unlike fn_crash's deterministic ValueError)."""
    raise ConnectionError("injected infra failure")


def fn_report_steps(args, ctx):
    """Step loop that reports progress to the health monitor — the chaos
    tests' 'training': deterministic steps the TFOS_CHAOS plan can target."""
    import time

    total = int(args.get("total_steps", 100))
    for s in range(1, total + 1):
        ctx.report_step(s)
        time.sleep(float(args.get("step_secs", 0.1)))
    with open(os.path.join(ctx.working_dir, f"steps.{ctx.executor_id}"), "w") as f:
        f.write(str(total))


def fn_goodput_metrics_steps(args, ctx):
    """Telemetry-plane workload: a step loop recording goodput via
    ``ctx.goodput()`` and a registry counter — both must become visible
    from the DRIVER through the heartbeat-carried snapshots.  Loops until
    the driver sets kv ``stop_goodput`` (or ``max_secs`` elapses)."""
    import time

    from tensorflowonspark_tpu import metrics as tpu_metrics

    rec = ctx.goodput()
    demo = tpu_metrics.get_registry().counter(
        "tfos_test_worker_steps_total", "steps run by the test map_fun")
    deadline = time.monotonic() + float(args.get("max_secs", 30))
    step = 0
    while time.monotonic() < deadline:
        if ctx.mgr is not None and ctx.mgr.kv_get("stop_goodput"):
            break
        step += 1
        with rec.time("step"):
            time.sleep(0.02)
        demo.inc()
        ctx.report_step(step)
        time.sleep(0.02)


def fn_report_then_sleep(args, ctx):
    """Report a couple of steps (arming the hang watchdog / giving a
    chaos ``stall`` its trigger), then block — the wedged-worker shape."""
    import time

    ctx.report_step(1)
    ctx.report_step(2)
    time.sleep(float(args.get("sleep_secs", 120)))


def fn_train_ckpt_report(args, ctx):
    """Deterministic 'training' with per-step orbax checkpoints and
    ``ctx.report_step`` progress — the kill/restore chaos workload.  Unlike
    ``fn_train_checkpoint_crash_once`` it injects nothing itself: the
    TFOS_CHAOS plan supplies the fault.  Appends ``<wall_time> <start>``
    per attempt to ``resume.<id>`` so tests/bench assert resume points and
    restart-to-first-step latency."""
    import time

    import numpy as np

    from tensorflowonspark_tpu.checkpoint import CheckpointManager

    total = int(args["total_steps"])
    ckpt = CheckpointManager(args["model_dir"])
    start, w = 0, np.zeros(())
    if ckpt.latest_step() is not None:
        state = ckpt.restore()
        start, w = int(state["step"]), np.asarray(state["w"])
    with open(os.path.join(ctx.working_dir, f"resume.{ctx.executor_id}"), "a") as f:
        f.write(f"{time.time():.6f} {start}\n")

    for s in range(start, total):
        w = w + 1.0
        step = s + 1
        if ctx.is_chief:
            ckpt.save(step, {"step": np.asarray(step), "w": w}, force=True)
            ckpt.wait()  # durable BEFORE report_step can fire a chaos kill
        ctx.report_step(step)
        time.sleep(float(args.get("step_secs", 0.05)))
    if ctx.is_chief:
        ckpt.close()


def fn_crash_before_register(args, ctx):  # pragma: no cover - not called
    raise RuntimeError("unused")


def fn_train_linear_export(args, ctx):
    """Train y ≈ w·x + b from the feed; chief exports a serving signature.

    The pipeline-test workload (reference model: the small Keras model in
    ``tests/test_pipeline.py`` upstream): real SGD on the fed data followed
    by a chief-only export that TFModel.transform loads back.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    feed = ctx.get_data_feed(train_mode=True)
    w = jnp.zeros(())
    b = jnp.zeros(())
    lr = args.lr

    @jax.jit
    def step(w, b, x, y):
        def loss(w, b):
            return jnp.mean((w * x + b - y) ** 2)

        gw, gb = jax.grad(loss, argnums=(0, 1))(w, b)
        return w - lr * gw, b - lr * gb

    while not feed.should_stop():
        batch = feed.next_batch_arrays(args.batch_size, timeout=30)
        if batch is None:
            break
        x, y = batch
        w, b = step(w, b, jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32))

    if ctx.is_chief:
        from tensorflowonspark_tpu.checkpoint import export_model

        def serve(p, x):
            return p["w"] * x + p["b"]

        export_model(args.export_dir, serve, {"w": w, "b": b},
                     [np.zeros((2,), np.float32)],
                     input_names=["x"], output_names=["y"], is_chief=True)


def fn_terminating_consumer(args, ctx):
    """Read a few batches then terminate early (early-stop semantics)."""
    feed = ctx.get_data_feed()
    feed.next_batch(4, timeout=30)
    feed.terminate(drain_secs=1.0)
    with open(os.path.join(ctx.working_dir, f"term.{ctx.executor_id}"), "w") as f:
        f.write("terminated")


def fn_distributed_pjit_train(args, ctx):
    """Cross-process SPMD training: ``ctx.initialize_distributed()`` over
    loopback (CPU backend, gloo collectives) + one jitted train step whose
    mesh spans BOTH worker processes.

    Exercises the composed path SURVEY.md §4 calls the "local-cluster
    pattern": agents/local procs + coordination service + cross-process
    collectives (reference analogue: TF_CONFIG + MultiWorkerMirrored over
    two Spark executors).  Writes ``dist.<id>`` with the final loss/weights
    so the driver can compare against the single-process value.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    ctx.initialize_distributed()

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == ctx.num_workers, jax.process_count()
    devs = jax.devices()  # global device list, across processes
    mesh = Mesh(np.array(devs), ("dp",))
    rep = NamedSharding(mesh, P())

    rng = np.random.default_rng(0)
    X = rng.standard_normal((8, 4)).astype(np.float32)
    y = (X @ np.arange(1.0, 5.0, dtype=np.float32)).astype(np.float32)
    xsh = NamedSharding(mesh, P("dp"))
    Xg = jax.make_array_from_callback(X.shape, xsh, lambda i: X[i])
    yg = jax.make_array_from_callback(y.shape, xsh, lambda i: y[i])

    lr = 0.1

    @jax.jit
    def train_step(w, X, y):
        def loss_fn(w):
            return jnp.mean((X @ w - y) ** 2)  # mean over the GLOBAL batch

        loss, g = jax.value_and_grad(loss_fn)(w)
        return w - lr * g, loss

    w = jax.device_put(jnp.zeros((4,), jnp.float32), rep)
    for _ in range(int(args.get("steps", 3))):
        w, loss = train_step(w, Xg, yg)

    path = os.path.join(ctx.working_dir, f"dist.{ctx.executor_id}")
    w_host = np.asarray(jax.device_get(w))
    with open(path, "w") as f:
        f.write(f"{jax.process_count()}:{len(devs)}:{float(loss):.8f}:"
                + ",".join(f"{v:.8f}" for v in w_host))


def fn_distributed_multidev_train(args, ctx):
    """Multi-process × MULTI-DEVICE GSPMD: 2 processes × 4 CPU devices each
    → one 8-device global mesh — the actual TPU-pod regime (SURVEY.md §7
    hard part 1) that neither the 2×1-device tests nor the single-process
    8-device dryrun reach.

    Two mesh layouts, switched by ``args["span_process_boundary"]``:
      False — dp2 ACROSS the processes, fsdp2·tp2 INSIDE each (the layout
        a pod would use: high-traffic axes on-host);
      True — device order transposed so every tp PAIR spans the process
        boundary (tp collectives ride the inter-process link) — the
        composition no single-process test can exercise.

    Trains a tanh MLP and writes loss trajectory + a replicated parameter
    fingerprint; the driver compares both against a numpy oracle.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    ctx.initialize_distributed()

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorflowonspark_tpu.parallel import make_mesh
    from tensorflowonspark_tpu.parallel.mesh import MeshSpec

    assert jax.process_count() == 2, jax.process_count()
    devs = jax.devices()
    assert len(devs) == 8, f"need 2 procs x 4 devices, got {len(devs)}"
    span = bool(args.get("span_process_boundary"))
    if span:
        # transpose the device grid: tp pairs become (proc0_dev, proc1_dev)
        grid = np.array(devs).reshape(2, 4).T.reshape(-1)
        mesh = make_mesh(MeshSpec(dp=4, fsdp=1, tp=2), devices=grid)
        pairs = mesh.devices.reshape(4, 2)
        for pair in pairs:
            procs = {d.process_index for d in pair}
            assert procs == {0, 1}, f"tp pair does not span processes: {procs}"
        w1_spec, data_spec = P(None, "tp"), P("dp")
    else:
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2), devices=devs)
        outer = mesh.devices.reshape(2, -1)
        assert {d.process_index for d in outer[0]} == {0}
        assert {d.process_index for d in outer[1]} == {1}
        w1_spec, data_spec = P("fsdp", "tp"), P(("dp", "fsdp"))

    _mlp_train_and_write(args, ctx, mesh, w1_spec=w1_spec,
                         data_spec=data_spec, out_prefix="mdev")


def _mlp_train_and_write(args, ctx, mesh, *, w1_spec, data_spec,
                         out_prefix):
    """Shared tanh-MLP parity harness for the multi-process mesh workers:
    same seeds/lr/shapes as ``tests.test_distributed._mlp_oracle``, so
    every caller's output file compares against the one oracle.  Writes
    ``<out_prefix>.<executor_id>`` with the loss trajectory + a replicated
    parameter fingerprint (the sharded weights themselves are not
    addressable from any single process)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    devs = jax.devices()
    rng = np.random.default_rng(0)
    X_np = rng.standard_normal((8, 4)).astype(np.float32)
    y_np = rng.standard_normal((8,)).astype(np.float32)
    W1_np = (rng.standard_normal((4, 8)) * 0.5).astype(np.float32)
    W2_np = (rng.standard_normal((8,)) * 0.5).astype(np.float32)

    def put(a, spec):
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(a.shape, sh, lambda i: a[i])

    X = put(X_np, data_spec)
    y = put(y_np, P(data_spec[0]) if data_spec else P())
    W1 = put(W1_np, w1_spec)
    W2 = put(W2_np, P("tp"))

    lr = 0.1

    @jax.jit
    def train_step(W1, W2, X, y):
        def loss_fn(W1, W2):
            h = jnp.tanh(X @ W1)
            return jnp.mean((h @ W2 - y) ** 2)

        loss, (g1, g2) = jax.value_and_grad(loss_fn, argnums=(0, 1))(W1, W2)
        return W1 - lr * g1, W2 - lr * g2, loss

    losses = []
    for _ in range(int(args.get("steps", 3))):
        W1, W2, loss = train_step(W1, W2, X, y)
        losses.append(float(loss))
    fp = float(jax.jit(lambda a, b: jnp.sum(a ** 2) + jnp.sum(b ** 2))(W1, W2))

    path = os.path.join(ctx.working_dir, f"{out_prefix}.{ctx.executor_id}")
    with open(path, "w") as f:
        f.write(f"{jax.process_count()}:{len(devs)}:"
                + ",".join(f"{v:.8f}" for v in losses) + f":{fp:.8f}")


def fn_distributed_hybrid_mesh_train(args, ctx):
    """``make_hybrid_mesh`` with its ``process_index`` slice fallback, on a
    REAL process boundary: 2 processes × 4 CPU devices = 2 "slices", no
    ``slice_key`` override — dp lands across the processes (the DCN
    analogue), fsdp·tp inside each.  Same MLP math as
    ``fn_distributed_multidev_train`` so the driver compares against the
    same single-process oracle."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    ctx.initialize_distributed()

    from jax.sharding import PartitionSpec as P

    from tensorflowonspark_tpu.parallel import make_hybrid_mesh

    assert jax.process_count() == 2, jax.process_count()
    devs = jax.devices()
    assert len(devs) == 8, f"need 2 procs x 4 devices, got {len(devs)}"
    mesh = make_hybrid_mesh(ici=dict(fsdp=2, tp=2), dcn=dict(dp=2))
    assert dict(mesh.shape) == {"pp": 1, "dp": 2, "fsdp": 2, "ep": 1,
                                "sp": 1, "tp": 2}, dict(mesh.shape)
    # each dp block must be exactly one process's devices (slice = process)
    blocks = mesh.devices.reshape(2, -1)
    assert {d.process_index for d in blocks[0]} == {0}
    assert {d.process_index for d in blocks[1]} == {1}

    _mlp_train_and_write(args, ctx, mesh, w1_spec=P("fsdp", "tp"),
                         data_spec=P(("dp", "fsdp")), out_prefix="hybrid")


def fn_distributed_pipeline_multidev(args, ctx):
    """GPipe across processes WITH multi-device stages: mesh pp2·dp2·tp2
    over 2 processes × 4 devices — each pipeline stage lives on one
    process and is itself Megatron-tp·dp-sharded
    (``make_transformer_stage``), so the stage-hop ppermute crosses the
    process boundary while tp psums stay inside each stage."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    ctx.initialize_distributed()

    import numpy as np
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorflowonspark_tpu.parallel import (make_mesh, pipeline_apply,
                                                make_transformer_stage,
                                                stack_stage_params)
    from tensorflowonspark_tpu.parallel.mesh import MeshSpec

    devs = jax.devices()
    assert len(devs) == 8 and jax.process_count() == 2
    mesh = make_mesh(MeshSpec(pp=2, dp=2, tp=2), devices=devs)
    stages = mesh.devices.reshape(2, -1)  # pp outermost -> one per process
    assert {d.process_index for d in stages[0]} == {0}
    assert {d.process_index for d in stages[1]} == {1}

    hid, heads, ffn, seq, vocab = 32, 4, 64, 8, 64
    num_mb, steps = 2, int(args.get("steps", 2))
    stage_fn, init_fn, param_specs = make_transformer_stage(
        hid, heads, ffn, tp=2, causal=True)
    tx = optax.adamw(1e-3)
    batch = 2 * num_mb * 2  # 2 rows per microbatch per dp shard
    data_spec = P(("dp", "fsdp"), "sp", None)
    rng = np.random.default_rng(0)
    ids_np = rng.integers(0, vocab, (batch, seq)).astype(np.int32)

    def init_params():
        keys = jax.random.split(jax.random.key(0), 2)
        return {
            "emb": jax.random.normal(jax.random.key(1), (vocab, hid)) * 0.02,
            "stages": stack_stage_params([init_fn(k) for k in keys]),
        }

    p_sh = {
        "emb": NamedSharding(mesh, P()),
        "stages": jax.tree.map(
            lambda s: NamedSharding(mesh, P("pp", *s)), param_specs,
            is_leaf=lambda s: isinstance(s, P)),
    }

    with mesh:
        params = jax.jit(init_params, out_shardings=p_sh)()
        opt_state = jax.jit(tx.init)(params)
        ids = jax.make_array_from_callback(
            ids_np.shape, NamedSharding(mesh, P(("dp", "fsdp"), None)),
            lambda i: ids_np[i])

        def loss_fn(p):
            x = p["emb"][ids]
            y = pipeline_apply(mesh, stage_fn, p["stages"], x,
                               num_microbatches=num_mb,
                               param_specs=param_specs, data_spec=data_spec)
            logits = jnp.einsum("bsh,vh->bsv", y, p["emb"])
            labels = jnp.roll(ids, -1, axis=1)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()

        @jax.jit
        def train_step(p, o):
            loss, grads = jax.value_and_grad(loss_fn)(p)
            updates, o = tx.update(grads, o, p)
            return optax.apply_updates(p, updates), o, loss

        losses = []
        for _ in range(steps):
            params, opt_state, loss = train_step(params, opt_state)
            losses.append(float(loss))

    path = os.path.join(ctx.working_dir, f"mpipe.{ctx.executor_id}")
    with open(path, "w") as f:
        f.write(":".join(f"{v:.8f}" for v in losses))


def fn_train_checkpoint_crash_once(args, ctx):
    """Deterministic 'training' with orbax checkpoints; injects ONE chief
    crash mid-run on the first attempt (sentinel file) so
    ``run_with_recovery``'s relaunch-then-resume path is exercised.

    Appends each attempt's start step to ``resume.<id>`` — the test asserts
    the relaunch resumed from the checkpoint, not step 0.
    """
    import numpy as np

    from tensorflowonspark_tpu.checkpoint import CheckpointManager

    total, crash_at = args["total_steps"], args["crash_at"]
    ckpt = CheckpointManager(args["model_dir"])
    start, w = 0, np.zeros(())
    latest = ckpt.latest_step()
    if latest is not None:
        state = ckpt.restore()
        start, w = int(state["step"]), np.asarray(state["w"])
    with open(os.path.join(ctx.working_dir, f"resume.{ctx.executor_id}"), "a") as f:
        f.write(f"{start}\n")

    sentinel = os.path.join(ctx.working_dir, "crash-injected")
    for s in range(start, total):
        w = w + 1.0
        step = s + 1
        if ctx.is_chief and step == crash_at and not os.path.exists(sentinel):
            ckpt.save(step, {"step": np.asarray(step), "w": w}, force=True)
            ckpt.wait()
            with open(sentinel, "w"):
                pass
            raise RuntimeError("injected preemption")
    if ctx.is_chief:
        ckpt.save(total, {"step": np.asarray(total), "w": w}, force=True)
        ckpt.close()


def fn_distributed_pipeline_train(args, ctx):
    """Cross-process PIPELINE parallelism: a pp=2 mesh spanning two worker
    processes, so the GPipe schedule's stage-hop ``ppermute`` crosses a
    real process boundary (gloo) — the multihost path single-process tests
    can't reach.  Writes ``pipe.<id>`` with the loss trajectory."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    ctx.initialize_distributed()

    import numpy as np
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu.parallel import make_mesh, pipeline_apply
    from tensorflowonspark_tpu.parallel.mesh import MeshSpec
    from jax.sharding import NamedSharding, PartitionSpec as P

    devs = jax.devices()
    assert len(devs) == 2 and jax.process_count() == 2
    mesh = make_mesh(MeshSpec(pp=2, dp=1), devices=devs)

    def stage_fn(p, x):
        return x + jnp.tanh(x @ p["w"])

    hid, num_mb, steps = 8, 2, int(args.get("steps", 2))
    rng = np.random.default_rng(0)
    w0 = (rng.standard_normal((2, hid, hid)) * 0.1).astype(np.float32)
    x_np = rng.standard_normal((4, hid)).astype(np.float32)
    tx = optax.sgd(0.1)

    stacked_sh = NamedSharding(mesh, P("pp", None, None))
    stacked = jax.make_array_from_callback(
        w0.shape, stacked_sh, lambda i: w0[i])
    params = {"w": stacked}
    opt_state = jax.jit(tx.init)(params)
    x = jax.device_put(jnp.asarray(x_np), NamedSharding(mesh, P()))

    @jax.jit
    def train_step(params, opt_state, x):
        def loss_fn(p):
            y = pipeline_apply(mesh, stage_fn, p, x, num_microbatches=num_mb)
            return jnp.mean(y ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, x)
        losses.append(float(loss))

    path = os.path.join(ctx.working_dir, f"pipe.{ctx.executor_id}")
    with open(path, "w") as f:
        f.write(":".join(f"{v:.8f}" for v in losses))


def fn_write_cache_env(args, ctx):
    """Record the worker-side compile-cache env contract (node.run must
    export the JAX cache vars before the user fn, honoring the TFOS_*
    knobs)."""
    path = os.path.join(ctx.working_dir, f"cacheenv.{ctx.executor_id}")
    with open(path, "w") as f:
        f.write(os.environ.get("JAX_COMPILATION_CACHE_DIR", "MISSING") + ":"
                + os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                                 "MISSING"))


def fn_publish_crash_once(args, ctx):
    """Continual-loop crash-atomicity workload: the first attempt
    publishes a multi-MB candidate and SIGKILLs itself immediately —
    the driver's collector is racing that enqueue, so it either never
    sees the message or dies mid-``get`` on a torn stream; a partial
    payload must never surface.  The second attempt (sentinel present)
    publishes a small clean candidate and exits 0.  Payloads are
    deterministic ``np.full`` so the driver asserts whole-or-nothing."""
    import signal

    import numpy as np

    from tensorflowonspark_tpu.continual import CheckpointPublisher

    pub = CheckpointPublisher(ctx, args["model"], timeout=30.0)
    sentinel = os.path.join(ctx.working_dir, "publish-crash-injected")
    if not os.path.exists(sentinel):
        with open(sentinel, "w"):
            pass
        n = int(args.get("big_elems", 1 << 20))
        pub.publish(1, {"w": np.full((n,), 1.0, np.float64)})
        os.kill(os.getpid(), signal.SIGKILL)
    pub.publish(2, {"w": np.full((8,), 2.0, np.float64)})


def batch_predict_scale(model, records, trial_params):
    """Batch-plane scorer over array shards: one bytes record per row,
    scaled by the grid trial's ``scale`` (default 2.0) — deterministic, so
    restarted and uninterrupted runs are byte-identical."""
    import numpy as np

    scale = float((trial_params or {}).get("scale", 2.0))
    arr = np.asarray(records, dtype=np.float64)
    return [(row * scale).tobytes() for row in arr]


def batch_predict_scale_paced(model, records, trial_params):
    """``batch_predict_scale`` with a small per-shard delay: paces the
    queue so a mid-job chaos kill reliably lands while work is still
    outstanding (a free-running scorer lets one worker drain everything
    before the victim's trigger step).  Output is byte-identical to the
    unpaced scorer."""
    import time

    time.sleep(0.1)
    return batch_predict_scale(model, records, trial_params)


def batch_predict_len(model, records, trial_params):
    """Batch-plane scorer over tfrecord shards: echo each raw record's
    length (records arrive as bytes)."""
    return [len(r).to_bytes(4, "little") for r in records]


def batch_model_builder_offset(args):
    """Model builder fixture: built once per worker process; the returned
    'model' is an offset the predict fn applies."""
    return {"offset": float(args.get("offset", 100.0))}


def batch_predict_with_model(model, records, trial_params):
    """Proves the builder's model reaches every predict call."""
    import numpy as np

    arr = np.asarray(records, dtype=np.float64)
    return [(row + model["offset"]).tobytes() for row in arr]


def serving_tiny_gpt_builder(args):
    """Model builder for serving-tier tests (``serving.ServingCluster``):
    a deterministic seeded tiny GPT, rebuilt identically in every replica
    process AND by the driver-side oracle, so cluster outputs can be
    asserted greedy-exact against solo ``greedy_generate`` runs."""
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=83, hidden_size=32, num_layers=2, num_heads=4,
                    intermediate_size=64, max_position_embeddings=64,
                    dtype=jnp.float32, pos_encoding="rope")
    params = GPT(cfg).init(jax.random.key(int(args.get("seed", 0))),
                           jnp.ones((1, 4), jnp.int32))["params"]
    return cfg, params


def shm_crash_server(pipe):
    """test_shm consumer-crash fixture: serve a queue (shm negotiation on),
    acknowledge the feed, then die HARD — no finally blocks, no atexit —
    simulating a worker crash while it still holds zero-copy leases."""
    from tensorflowonspark_tpu.queues import QueueServer

    srv = QueueServer(authkey=b"k" * 16, qnames=("input",), mode="local")
    addr = srv.start()
    pipe.send(addr)
    # hold the fed item's views so the lease is live at crash time
    item = srv.queue_get("input", timeout=30)
    pipe.send(int(item[0, 0]))  # prove the shm payload arrived intact
    pipe.recv()              # wait for the driver's kill order
    os._exit(1)


def serving_sharded_gpt_builder(args):
    """Model builder for SHARDED serving-tier tests: like
    ``serving_tiny_gpt_builder`` but with every tp-sharded dimension
    (vocab, heads, intermediate) divisible by the test gangs' tp=2/4,
    so the Megatron layout actually shards."""
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                    intermediate_size=64, max_position_embeddings=64,
                    dtype=jnp.float32, pos_encoding="rope")
    params = GPT(cfg).init(jax.random.key(int(args.get("seed", 0))),
                           jnp.ones((1, 4), jnp.int32))["params"]
    return cfg, params


def rollout_parity_cfg():
    """The estimator→serve parity test's tiny GPT config — ONE
    definition shared by the trainer, the batch-eval workers, the
    serving replicas, and the driver-side oracle."""
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import GPTConfig

    return GPTConfig(vocab_size=31, hidden_size=16, num_layers=1,
                     num_heads=2, intermediate_size=32,
                     max_position_embeddings=32, dtype=jnp.float32,
                     pos_encoding="rope")


def rollout_parity_builder(args):
    """Model builder restoring the estimator-trained checkpoint from
    ``args["model_dir"]`` (top level so spawn pickles it by reference)
    — the registry entry behind the estimator → eval → promote → serve
    parity path.  A target-less orbax restore returns flax
    ``Partitioned`` kernels as ``{"value": array}`` boxes; serving
    applies raw arrays, so unbox them."""
    from tensorflowonspark_tpu.checkpoint import CheckpointManager

    def unbox(tree):
        if isinstance(tree, dict):
            if set(tree) == {"value"}:
                return unbox(tree["value"])
            return {k: unbox(v) for k, v in tree.items()}
        return tree

    with CheckpointManager(args["model_dir"]) as ckpt:
        state = ckpt.restore()
    params = state["params"] if isinstance(state, dict) else state.params
    return rollout_parity_cfg(), unbox(params)


def rollout_parity_predict(model, records, trial_params):
    """Batch-plane predict fn for the parity test's GridSearch eval:
    greedy-decode each prompt record under the restored params."""
    import jax.numpy as jnp
    import numpy as np

    from tensorflowonspark_tpu.models import greedy_generate

    cfg, params = model
    out = []
    for rec in records:
        p = np.asarray(rec, np.int32).reshape(-1)
        toks = np.asarray(greedy_generate(
            cfg, params, jnp.asarray(p)[None, :],
            int(trial_params.get("budget", 4))))[0, p.size:]
        out.append(toks.astype(np.int32).tobytes())
    return out
