"""Top-level map_fun fixtures for cluster integration tests.

Must live in an importable module so ``multiprocessing`` spawn can pickle
them — the same constraint Spark puts on closures shipped to executors.
Mirrors the reference's tiny inline map_funs (SURVEY.md §4: orchestration is
tested with trivial functions, real models live in examples/).
"""

import os


def fn_noop(args, ctx):
    """Registers, does nothing, exits cleanly."""


def fn_write_role(args, ctx):
    """Record each node's role assignment for template assertions."""
    path = os.path.join(ctx.working_dir, f"role.{ctx.executor_id}")
    with open(path, "w") as f:
        f.write(f"{ctx.job_name}:{ctx.task_index}:{int(ctx.is_chief)}:{ctx.num_workers}")


def fn_sum_feed(args, ctx):
    """Consume the feed, write the running sum (train-mode round trip)."""
    feed = ctx.get_data_feed(train_mode=True)
    total = 0
    count = 0
    while not feed.should_stop():
        batch = feed.next_batch(args["batch_size"], timeout=30)
        total += sum(batch)
        count += len(batch)
    with open(os.path.join(ctx.working_dir, f"sum.{ctx.executor_id}"), "w") as f:
        f.write(f"{total}:{count}")


def fn_square_inference(args, ctx):
    """Echo x**2 for every sample (inference round trip)."""
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(4, timeout=30)
        if batch:
            feed.batch_results([x * x for x in batch])


def fn_tiny_batch_inference(args, ctx):
    """Emit one result message per sample — maximal output-queue pressure
    (regression: inference must drain results while its puts are blocked)."""
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(1, timeout=30)
        if batch:
            feed.batch_results([x + 1000 for x in batch])


def fn_crash(args, ctx):
    raise ValueError("deliberate failure for error-propagation test")


def fn_crash_before_register(args, ctx):  # pragma: no cover - not called
    raise RuntimeError("unused")


def fn_train_linear_export(args, ctx):
    """Train y ≈ w·x + b from the feed; chief exports a serving signature.

    The pipeline-test workload (reference model: the small Keras model in
    ``tests/test_pipeline.py`` upstream): real SGD on the fed data followed
    by a chief-only export that TFModel.transform loads back.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    feed = ctx.get_data_feed(train_mode=True)
    w = jnp.zeros(())
    b = jnp.zeros(())
    lr = args.lr

    @jax.jit
    def step(w, b, x, y):
        def loss(w, b):
            return jnp.mean((w * x + b - y) ** 2)

        gw, gb = jax.grad(loss, argnums=(0, 1))(w, b)
        return w - lr * gw, b - lr * gb

    while not feed.should_stop():
        batch = feed.next_batch_arrays(args.batch_size, timeout=30)
        if batch is None:
            break
        x, y = batch
        w, b = step(w, b, jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32))

    if ctx.is_chief:
        from tensorflowonspark_tpu.checkpoint import export_model

        def serve(p, x):
            return p["w"] * x + p["b"]

        export_model(args.export_dir, serve, {"w": w, "b": b},
                     [np.zeros((2,), np.float32)],
                     input_names=["x"], output_names=["y"], is_chief=True)


def fn_terminating_consumer(args, ctx):
    """Read a few batches then terminate early (early-stop semantics)."""
    feed = ctx.get_data_feed()
    feed.next_batch(4, timeout=30)
    feed.terminate(drain_secs=1.0)
    with open(os.path.join(ctx.working_dir, f"term.{ctx.executor_id}"), "w") as f:
        f.write("terminated")
