"""Multi-host agent backend: real agent daemons on localhost.

The reference's multi-host path is exercised through Spark's
``local-cluster[N, ...]`` master — real separate executor processes on one
machine (SURVEY.md §4).  The analogue here: spawn real ``HostAgent`` daemons
as subprocesses, then run the full ``TPUCluster`` contract through
``AgentBackend`` against them.
"""

import os
import secrets
import subprocess
import sys

import pytest

pytestmark = pytest.mark.integration  # spawns real agent daemons

from tensorflowonspark_tpu.agent import AgentBackend, HostAgent, _AgentConn  # noqa: E402
from tests import cluster_funcs as funcs

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def agent_fleet():
    """Two real host-agent daemons on localhost with a shared authkey."""
    key = secrets.token_bytes(16)
    procs, addrs = [], []
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    try:
        for _ in range(2):
            p = subprocess.Popen(
                [sys.executable, "-m", "tensorflowonspark_tpu.agent",
                 "--port", "0", "--authkey-hex", key.hex()],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, cwd=ROOT, env=env)
            procs.append(p)
            line = p.stdout.readline().strip()  # "AGENT host:port"
            assert line.startswith("AGENT "), f"unexpected agent banner {line!r}"
            host, port = line.split(" ", 1)[1].rsplit(":", 1)
            addrs.append((host, int(port)))
        yield key, addrs
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_agent_train_roundtrip(agent_fleet, tmp_path):
    from tensorflowonspark_tpu import TPUCluster

    key, addrs = agent_fleet
    backend = AgentBackend(addrs, authkey=key,
                           worker_env={"JAX_PLATFORMS": "cpu"})
    cluster = TPUCluster.run(
        funcs.fn_sum_feed, {"batch_size": 8}, num_workers=2,
        backend=backend, reservation_timeout=60,
        working_dir=str(tmp_path))
    try:
        cluster.train(list(range(100)), num_epochs=1)
    finally:
        cluster.shutdown(timeout=120)
    sums = []
    for i in range(2):
        with open(tmp_path / f"sum.{i}") as f:
            total, count = map(int, f.read().split(":"))
        sums.append((total, count))
    assert sum(t for t, _ in sums) == sum(range(100))
    assert sum(c for _, c in sums) == 100
    # round-robin assignment: both agents hosted one worker each
    assert all(c > 0 for _, c in sums)


def test_agent_inference_roundtrip(agent_fleet, tmp_path):
    from tensorflowonspark_tpu import TPUCluster

    key, addrs = agent_fleet
    backend = AgentBackend(addrs, authkey=key,
                           worker_env={"JAX_PLATFORMS": "cpu"})
    cluster = TPUCluster.run(
        funcs.fn_square_inference, {}, num_workers=2, backend=backend,
        reservation_timeout=60, working_dir=str(tmp_path))
    try:
        preds = cluster.inference(list(range(24)))
        assert sorted(preds) == sorted(x * x for x in range(24))
    finally:
        cluster.shutdown(timeout=120)


def test_agent_error_propagation(agent_fleet, tmp_path):
    from tensorflowonspark_tpu import TPUCluster

    key, addrs = agent_fleet
    backend = AgentBackend(addrs[:1], authkey=key,
                           worker_env={"JAX_PLATFORMS": "cpu"})
    cluster = TPUCluster.run(
        funcs.fn_crash, {}, num_workers=1, backend=backend,
        reservation_timeout=60, working_dir=str(tmp_path))
    with pytest.raises(RuntimeError, match="deliberate failure"):
        cluster.shutdown(timeout=120)


def test_agent_rejects_bad_authkey(agent_fleet):
    _, addrs = agent_fleet
    with pytest.raises((PermissionError, EOFError, ConnectionError, OSError)):
        conn = _AgentConn(addrs[0], authkey=b"wrong-key-entirely", timeout=5)
        conn.request({"type": "PING"})


def test_agent_ping_and_status(agent_fleet):
    key, addrs = agent_fleet
    conn = _AgentConn(addrs[0], authkey=key)
    try:
        pong = conn.request({"type": "PING"})
        assert pong["ok"] and pong["workers"] == []
        assert conn.request({"type": "STATUS"}) == {}
    finally:
        conn.close()


def test_agent_oversubscription(agent_fleet, tmp_path):
    """4 workers over 2 agents — the multiple-executors-per-host shape."""
    from tensorflowonspark_tpu import TPUCluster

    key, addrs = agent_fleet
    backend = AgentBackend(addrs, authkey=key,
                           worker_env={"JAX_PLATFORMS": "cpu"})
    cluster = TPUCluster.run(
        funcs.fn_write_role, {}, num_workers=4, backend=backend,
        reservation_timeout=60, working_dir=str(tmp_path))
    cluster.shutdown(timeout=120)
    roles = []
    for i in range(4):
        with open(tmp_path / f"role.{i}") as f:
            roles.append(f.read())
    assert len(roles) == 4
    assert sum(1 for r in roles if r.split(":")[2] == "1") == 1  # one chief


def test_failed_worker_logs_reach_driver_via_agent(tmp_path):
    """A remote-path worker's stack trace must reach the driver THROUGH THE
    AGENT (LOGS protocol), not the shared filesystem: the crash files are
    deleted before shutdown to simulate a no-shared-FS pod (VERDICT r1
    missing #4 / SURVEY.md §7 hard part 3)."""
    import glob
    import os

    from tensorflowonspark_tpu.cluster import TPUCluster

    key = b"\x02" * 16
    agent = HostAgent(port=0, authkey=key, log_dir=str(tmp_path / "agentlogs"))
    addr = agent.start()
    try:
        backend = AgentBackend([addr], authkey=key,
                               worker_env={"JAX_PLATFORMS": "cpu"})
        cluster = TPUCluster.run(funcs.fn_crash, {}, num_workers=1,
                                 working_dir=str(tmp_path), backend=backend,
                                 reservation_timeout=60)
        backend.join(timeout=60)  # let the worker crash
        # simulate remote host: the driver cannot see the crash files
        for f in glob.glob(os.path.join(str(tmp_path), "error.*")):
            os.remove(f)

        with pytest.raises(RuntimeError) as ei:
            cluster.shutdown(timeout=60)
        msg = str(ei.value)
        assert "deliberate failure" in msg, msg  # the actual traceback text
        assert "executor 0 log tail" in msg

        # the LOGS call is also available directly
        logs = backend.fetch_logs([0])
        assert "deliberate failure" in logs[0]
        backend.close()
    finally:
        agent.stop()


def test_agent_conn_reconnects_after_transient_reset():
    """One transient socket failure must not poison the cached connection:
    request() reconnects + retries once before propagating (satellite:
    _AgentConn reconnect)."""
    key = b"\x03" * 16
    agent = HostAgent(port=0, authkey=key)
    addr = agent.start()
    try:
        conn = _AgentConn(addr, authkey=key, timeout=10)
        assert conn.request({"type": "PING"})["ok"]
        conn._sock.close()  # simulate a reset/timeout poisoning the socket
        pong = conn.request({"type": "PING"})  # must transparently reconnect
        assert pong["ok"] and pong["workers"] == []
        conn.close()
    finally:
        agent.stop()
