"""Observability layer: tensorboard spawn/URL, profiler trace, goodput.

Reference posture (SURVEY.md §5): tensorboard is the only facility —
spawned on worker:0/chief, (tb_pid, tb_port) registered, URL surfaced by
``TFCluster.tensorboard_url()``.  The spawn tests boot a *real* TensorBoard
(skipped when the package isn't installed) because the failure mode being
guarded — TB dying at import time — only reproduces with the real thing.
"""

import json
import os
import time

import pytest

from tensorflowonspark_tpu import observability
from tensorflowonspark_tpu.observability import GoodputRecorder


# -- goodput ---------------------------------------------------------------

def test_goodput_accounting():
    rec = GoodputRecorder()
    with rec.time("init"):
        time.sleep(0.05)
    for _ in range(3):
        with rec.time("step"):
            time.sleep(0.02)
    s = rec.summary()
    assert s["counts"] == {"init": 1, "step": 3}
    assert s["secs"]["step"] == pytest.approx(0.06, abs=0.04)
    assert 0.0 < s["goodput"] < 1.0
    assert s["secs"]["idle"] >= 0.0


def test_goodput_write(tmp_path):
    rec = GoodputRecorder()
    rec.record("step", 1.0)
    out = str(tmp_path / "goodput.json")
    s = rec.write(out)
    loaded = json.load(open(out))
    assert loaded["counts"] == s["counts"]
    assert loaded["secs"]["step"] == pytest.approx(1.0)
    assert loaded["goodput"] == pytest.approx(s["goodput"])


# -- profiler --------------------------------------------------------------

def test_profile_trace_writes_events(tmp_path):
    import jax
    import jax.numpy as jnp

    logdir = str(tmp_path / "prof")
    with observability.profile_trace(logdir):
        jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    # jax.profiler.trace writes plugins/profile/<run>/... under logdir
    found = [os.path.join(r, f) for r, _, fs in os.walk(logdir) for f in fs]
    assert found, "no profiler output written"


def test_annotate_smoke():
    with observability.annotate("mystep"):
        pass


# -- tensorboard spawn -----------------------------------------------------

def test_start_tensorboard_real_module(tmp_path):
    """Spawns the real tensorboard and requires it to actually serve HTTP
    (regression: setuptools>=81 removed pkg_resources → TB died instantly;
    the _shims/pkg_resources.py injection keeps it bootable)."""
    import urllib.request

    pytest.importorskip("tensorboard")
    res = observability.start_tensorboard(str(tmp_path / "tb"), wait_secs=1.0)
    assert res is not None
    proc, port = res
    assert port > 0
    try:
        status = None
        # 90s budget: TB's bootstrap on a saturated 1-core box can exceed
        # 30s (observed flake when the suite shares the core with other
        # jobs); serving normally starts within ~5s
        for _ in range(90):
            try:
                status = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}", timeout=3).status
                break
            except OSError:
                time.sleep(1)
        assert status == 200, "tensorboard never served HTTP"
    finally:
        observability.stop_tensorboard(proc)
    assert proc.poll() is not None


def test_cluster_tensorboard_url(tmp_path):
    """End to end: tensorboard=True → tb_port registered → URL surfaced."""
    from tensorflowonspark_tpu import TPUCluster
    from tests import cluster_funcs as funcs

    cluster = TPUCluster.run(
        funcs.fn_noop, {}, 2, tensorboard=True,
        tensorboard_logdir=str(tmp_path / "tblog"),
        worker_env={"JAX_PLATFORMS": "cpu"}, reservation_timeout=60,
        working_dir=str(tmp_path / "wd"))
    url = cluster.tensorboard_url()
    try:
        assert url is not None and url.startswith("http://")
        ports = [n.get("tb_port", 0) for n in cluster.cluster_info]
        assert sum(1 for p in ports if p) == 1  # exactly one chief spawn
    finally:
        cluster.shutdown(timeout=120)


@pytest.mark.integration
def test_goodput_and_worker_metrics_visible_from_driver(tmp_path):
    """The heartbeat-carried telemetry transport end to end: a map_fun
    using ``ctx.goodput()`` + a registry counter becomes visible in the
    driver's aggregated ``cluster.metrics()`` view (and the Prometheus
    page) while the job runs — not only as an end-of-job file."""
    from tensorflowonspark_tpu import TPUCluster
    from tests import cluster_funcs as funcs

    cluster = TPUCluster.run(
        funcs.fn_goodput_metrics_steps, {"max_secs": 60}, 1,
        worker_env={"JAX_PLATFORMS": "cpu"}, reservation_timeout=60,
        working_dir=str(tmp_path / "wd"))
    try:
        deadline = time.monotonic() + 30
        node0 = None
        while time.monotonic() < deadline:
            node0 = cluster.metrics()["nodes"].get(0)
            if node0 and node0.get("goodput") \
                    and node0["goodput"]["counts"].get("step", 0) > 0 \
                    and "tfos_test_worker_steps_total" in node0["metrics"]:
                break
            time.sleep(0.25)
        assert node0 is not None and node0.get("goodput"), \
            "goodput never arrived in the driver's aggregated view"
        assert node0["goodput"]["counts"]["step"] > 0
        assert 0.0 < node0["goodput"]["goodput"] <= 1.0
        samples = node0["metrics"]["tfos_test_worker_steps_total"]["samples"]
        assert samples and samples[0][1] > 0
        # the merged exposition page carries the worker series, labeled
        text = cluster.metrics_text()
        assert 'tfos_test_worker_steps_total{node="0"}' in text
        # standalone /metrics endpoint for training-only jobs
        import urllib.request

        host, port = cluster.serve_metrics()
        body = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=5).read().decode()
        assert "tfos_test_worker_steps_total" in body
    finally:
        import contextlib

        with contextlib.suppress(Exception):
            cluster._client_for(0).kv_set("stop_goodput", "1")
        cluster.shutdown(timeout=120)


def test_event_log_jsonl_roundtrip(tmp_path):
    """EventLog appends one timestamped JSON object per event (creating
    parent dirs) and reads them back — the health monitor's audit trail."""
    path = str(tmp_path / "events" / "health_events.jsonl")
    log = observability.EventLog(path)
    t0 = time.time()
    log.emit("monitor_started", workers=2)
    log.emit("crash", workers=[1], message="worker 1 exit=-9")
    log.close()

    log2 = observability.EventLog(path)  # append mode: reopen must not clobber
    log2.emit("abort", reason="crash")
    log2.close()

    recs = observability.EventLog.read(path)
    assert [r["kind"] for r in recs] == ["monitor_started", "crash", "abort"]
    assert recs[1]["workers"] == [1]
    assert all(r["t"] >= t0 - 1 for r in recs)


def test_event_log_read_skips_truncated_final_line(tmp_path, caplog):
    """A driver killed mid-emit leaves a partial JSON line; a post-mortem
    read must keep every good record and skip the fragment with a
    warning, not raise and lose the whole file."""
    path = str(tmp_path / "events.jsonl")
    log = observability.EventLog(path)
    log.emit("monitor_started", workers=2)
    log.emit("crash", workers=[0])
    log.close()
    with open(path, "a") as f:
        f.write('{"t": 123.4, "kind": "abo')   # killed mid-write

    import logging

    with caplog.at_level(logging.WARNING,
                         logger="tensorflowonspark_tpu.observability"):
        recs = observability.EventLog.read(path)
    assert [r["kind"] for r in recs] == ["monitor_started", "crash"]
    assert any("malformed" in r.message for r in caplog.records)

    # mid-file corruption (torn page) must not hide the records after it
    with open(path, "a") as f:
        f.write('\n{"t": 125.0, "kind": "late"}\n')
    recs = observability.EventLog.read(path)
    assert [r["kind"] for r in recs] == ["monitor_started", "crash", "late"]


def test_event_log_read_survives_line_cut_mid_utf8_sequence(tmp_path,
                                                            caplog):
    """The torn byte can fall INSIDE a multi-byte UTF-8 sequence — a
    text-mode read would raise ``UnicodeDecodeError`` before any line
    splitting happens and lose the whole file; the binary-read per-line
    decode skips exactly the cut line."""
    import json
    import logging

    path = str(tmp_path / "events.jsonl")
    log = observability.EventLog(path)
    log.emit("monitor_started", workers=2)
    log.close()
    whole = json.dumps({"t": 9.0, "kind": "crash", "detail": "nœud"},
                       ensure_ascii=False).encode("utf-8")
    cut = whole[:whole.index(b"\xc5") + 1]     # half of the œ
    with open(path, "ab") as f:
        f.write(cut)
    with caplog.at_level(logging.WARNING,
                         logger="tensorflowonspark_tpu.observability"):
        recs = observability.EventLog.read(path)
    assert [r["kind"] for r in recs] == ["monitor_started"]
    assert any("malformed" in r.message for r in caplog.records)


# -- latency histogram -----------------------------------------------------

def test_latency_histogram_percentiles():
    h = observability.LatencyHistogram()
    assert len(h) == 0 and h.percentile(99) is None
    assert h.summary()["count"] == 0 and h.summary()["p50_secs"] is None
    for ms in range(1, 101):           # 1..100 ms
        h.record(ms / 1000.0)
    s = h.summary()
    assert s["count"] == 100
    # nearest-rank: every reported value is an actual sample
    assert s["p50_secs"] == pytest.approx(0.050)
    assert s["p95_secs"] == pytest.approx(0.095)
    assert s["p99_secs"] == pytest.approx(0.099)
    assert s["max_secs"] == pytest.approx(0.100)
    assert s["mean_secs"] == pytest.approx(0.0505)
    assert h.percentile(100) == pytest.approx(0.100)


def test_latency_histogram_single_sample_and_concurrent_records():
    h = observability.LatencyHistogram()
    h.record(0.25)
    s = h.summary()
    assert s["p50_secs"] == s["p99_secs"] == s["max_secs"] == 0.25

    # hot-path contract: record from many threads without a lock
    import threading

    h2 = observability.LatencyHistogram()

    def worker():
        for _ in range(500):
            h2.record(0.001)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(h2) == 8 * 500          # list.append is GIL-atomic


def test_latency_histogram_reservoir_is_bounded():
    """A long-lived frontend must not grow the sample list forever: the
    reservoir keeps a ring of the most recent ``cap`` samples, percentile
    semantics stay nearest-rank on that window, and ``count`` reports the
    total ever recorded."""
    h = observability.LatencyHistogram(cap=100)
    for ms in range(1, 1001):          # 10x the cap
        h.record(ms / 1000.0)
    assert len(h._samples) == 100      # memory bounded at cap
    assert len(h) == 1000              # total recorded preserved
    s = h.summary()
    assert s["count"] == 1000
    # retained window is the most recent 100 samples: 0.901..1.000
    assert s["p50_secs"] == pytest.approx(0.950)
    assert s["p99_secs"] == pytest.approx(0.999)
    assert s["max_secs"] == pytest.approx(1.000)
    assert 0.901 <= s["mean_secs"] <= 1.0
    # every reported value is a sample that actually occurred
    assert s["p95_secs"] in h._samples

    # concurrent records against a small cap: bounded and crash-free
    import threading

    h2 = observability.LatencyHistogram(cap=64)

    def worker():
        for i in range(500):
            h2.record(i / 1000.0)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # bounded: cap + at most one fill-phase straggler append per thread
    assert len(h2._samples) <= 64 + 8
    assert h2.summary()["p99_secs"] is not None


def test_event_log_emit_after_close_degrades_to_warning(tmp_path, caplog):
    """A late monitor-thread emit into a closed log must warn, not raise
    ValueError out of the writer thread."""
    import logging

    path = str(tmp_path / "events.jsonl")
    log = observability.EventLog(path)
    log.emit("monitor_started", workers=1)
    log.close()
    with caplog.at_level(logging.WARNING,
                         logger="tensorflowonspark_tpu.observability"):
        rec = log.emit("late_event", detail="after close")   # must not raise
        log.emit("later_still")                              # warns only once
    assert rec["kind"] == "late_event"
    warnings = [r for r in caplog.records if "unwritable" in r.message]
    assert len(warnings) == 1
    # the file keeps only the pre-close events
    recs = observability.EventLog.read(path)
    assert [r["kind"] for r in recs] == ["monitor_started"]
