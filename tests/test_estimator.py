"""Estimator surface: train_and_evaluate, max_steps semantics, resume.

Every test in this module runs its body in a SUBPROCESS (one fresh
``pytest <this_file>::<test>`` child per test, see ``_isolated``): the
estimator suite carries a known pre-existing flake — a hard segfault
inside jax's CPU runtime (``_batched_device_put_impl`` /pjit lowering,
reproducible under CPU contention, predates the health/chaos PR) — and
a native crash in-process takes down the WHOLE pytest run, losing every
not-yet-run test with it.  Isolation fixes the blast radius, not the
symptom: a segfaulting child becomes one attributable test failure
(named signal in the assertion message) instead of an rc=139 session.
"""

import functools
import os
import subprocess
import sys

import numpy as np
import optax
import pytest

from tensorflowonspark_tpu.estimator import (Estimator, EvalSpec, TrainSpec,
                                             train_and_evaluate)

_CHILD_ENV = "TFOS_ESTIMATOR_ISOLATED"


def _isolated(fn):
    """Run the decorated test in a fresh pytest child process.

    Parent side: re-invoke ``pytest <file>::<name>`` with ``_CHILD_ENV``
    set and assert on the child's exit status, naming the signal when
    the child died natively.  Child side (env var present): run the test
    body normally.  Fixtures resolve in the child — the parent's are
    unused."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if os.environ.get(_CHILD_ENV) == "1":
            return fn(*args, **kwargs)
        cmd = [sys.executable, "-m", "pytest", "-q", "-x",
               "-p", "no:cacheprovider", "-p", "no:randomly",
               f"{os.path.abspath(__file__)}::{fn.__name__}"]
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=600,
            env={**os.environ, _CHILD_ENV: "1"})
        if proc.returncode != 0:
            died = (f"crashed natively with signal {-proc.returncode}"
                    if proc.returncode < 0
                    else f"failed (exit {proc.returncode})")
            raise AssertionError(
                f"isolated estimator test {fn.__name__} {died}\n"
                f"--- child stdout (tail) ---\n{proc.stdout[-4000:]}\n"
                f"--- child stderr (tail) ---\n{proc.stderr[-2000:]}")
    return wrapper


def _linreg_problem(seed=0, n=64, d=4):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(d, 1)).astype(np.float32)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = x @ w_true
    return x, y


def _make_estimator(model_dir, save_every=10, tx=None, **kwargs):
    import jax.numpy as jnp

    def init_fn():
        return {"w": jnp.zeros((4, 1))}

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    def metrics_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return {"mse": jnp.mean((pred - batch["y"]) ** 2),
                "mae": jnp.mean(jnp.abs(pred - batch["y"]))}

    return Estimator(init_fn, loss_fn, tx or optax.sgd(0.1), str(model_dir),
                     eval_metrics_fn=metrics_fn, save_every_steps=save_every,
                     **kwargs)


def _batches(x, y, bs=16):
    def input_fn():
        for i in range(0, len(x), bs):
            yield {"x": x[i:i + bs], "y": y[i:i + bs]}
    return input_fn


@_isolated
def test_train_and_evaluate_learns_and_reports(tmp_path):
    x, y = _linreg_problem()
    with _make_estimator(tmp_path / "m") as est:
        baseline = est.evaluate(_batches(x, y), steps=2)["mse"]
        final = train_and_evaluate(
            est,
            TrainSpec(input_fn=_batches(x, y), max_steps=40),
            EvalSpec(input_fn=_batches(x, y), steps=4, throttle_steps=15))
        assert final["global_step"] == 40
        assert final["mse"] < baseline * 0.1, (baseline, final)
        assert "mae" in final


@_isolated
def test_max_steps_is_total_budget_and_resume_works(tmp_path):
    x, y = _linreg_problem()
    with _make_estimator(tmp_path / "m") as est:
        est.train(_batches(x, y), max_steps=12)
        assert est.global_step == 12
        w_after = np.asarray(est.params["w"])

    # "restart": a fresh Estimator on the same model_dir resumes at step 12
    with _make_estimator(tmp_path / "m") as est2:
        assert est2.global_step == 12
        np.testing.assert_allclose(np.asarray(est2.params["w"]), w_after)
        est2.train(_batches(x, y), max_steps=20)  # only the remaining 8
        assert est2.global_step == 20


@_isolated
def test_resume_at_max_steps_still_runs_final_eval(tmp_path):
    x, y = _linreg_problem()
    with _make_estimator(tmp_path / "m") as est:
        est.train(_batches(x, y), max_steps=10)
    # relaunch with the SAME budget: no training remains, but
    # train_and_evaluate must still deliver the final eval metrics
    with _make_estimator(tmp_path / "m") as est2:
        final = train_and_evaluate(
            est2,
            TrainSpec(input_fn=_batches(x, y), max_steps=10),
            EvalSpec(input_fn=_batches(x, y), steps=2, throttle_steps=5))
        assert final["global_step"] == 10
        assert "mse" in final


@_isolated
def test_export_serves_trained_params(tmp_path):
    import jax.numpy as jnp

    from tensorflowonspark_tpu.checkpoint import ExportedModel

    x, y = _linreg_problem()
    with _make_estimator(tmp_path / "m") as est:
        est.train(_batches(x, y), max_steps=30)
        w = np.asarray(est.params["w"])
        out = est.export(str(tmp_path / "export"),
                         lambda p, x: x @ p["w"],
                         [jnp.zeros((4, 4))])
    assert out is not None
    served = ExportedModel.load(str(tmp_path / "export"))
    out_vals = served(x[:8])
    pred = np.asarray(next(iter(out_vals.values()))
                      if isinstance(out_vals, dict) else out_vals)
    np.testing.assert_allclose(pred, x[:8] @ w, rtol=1e-5)

    # non-chief writes nothing
    with _make_estimator(tmp_path / "m") as est2:
        assert est2.export(str(tmp_path / "e2"), lambda p, x: x @ p["w"],
                           [np.zeros((4, 4))], is_chief=False) is None


@_isolated
def test_goodput_accounting(tmp_path):
    x, y = _linreg_problem()
    with _make_estimator(tmp_path / "m") as est:
        est.train(_batches(x, y), max_steps=8)
        g = est.goodput()
    assert g["counts"]["step"] == 8
    assert 0.0 < g["goodput"] <= 1.0
    for cat in ("init", "data", "step", "checkpoint"):
        assert g["secs"].get(cat, 0) >= 0


@_isolated
def test_predict_streams_batches(tmp_path):
    x, y = _linreg_problem()
    with _make_estimator(tmp_path / "m") as est:
        est.train(_batches(x, y), max_steps=30)
        w = np.asarray(est.params["w"])
        preds = list(est.predict(_batches(x, y),
                                 lambda p, b: b["x"] @ p["w"]))
    assert len(preds) == 4  # 64 samples / bs 16
    np.testing.assert_allclose(np.concatenate(preds), x @ w, rtol=1e-5)

    with _make_estimator(tmp_path / "m") as est2:
        import pytest as _pytest

        with _pytest.raises(ValueError, match="predict_fn"):
            next(est2.predict(_batches(x, y)))


@_isolated
def test_predict_params_override_and_goodput(tmp_path):
    """Satellite: ``predict(params=...)`` scores a candidate tree (grid
    trial / EMA weights) without touching trained state, and predict's
    input waits land in goodput()'s ``data`` bucket like train's."""
    x, y = _linreg_problem()
    ones = {"w": np.ones((4, 1), np.float32)}
    with _make_estimator(tmp_path / "m") as est:
        est.train(_batches(x, y), max_steps=20)
        base = est.goodput()
        w_trained = np.asarray(est.params["w"])

        preds = list(est.predict(_batches(x, y),
                                 lambda p, b: b["x"] @ p["w"], params=ones))
        np.testing.assert_allclose(np.concatenate(preds), x @ ones["w"],
                                   rtol=1e-5)
        # the override was per-call: trained params still serve by default
        np.testing.assert_allclose(np.asarray(est.params["w"]), w_trained)
        preds2 = list(est.predict(_batches(x, y),
                                  lambda p, b: b["x"] @ p["w"]))
        np.testing.assert_allclose(np.concatenate(preds2), x @ w_trained,
                                   rtol=1e-5)

        g = est.goodput()
        assert g["counts"]["data"] > base["counts"]["data"]
        assert g["secs"]["data"] >= base["secs"]["data"]
        assert g["counts"]["step"] > base["counts"]["step"]


@_isolated
def test_profile_steps_writes_trace(tmp_path):
    import glob
    import os

    import jax.numpy as jnp
    import optax

    x, y = _linreg_problem()

    def init_fn():
        return {"w": jnp.zeros((4, 1))}

    def loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)

    model_dir = str(tmp_path / "m")
    with Estimator(init_fn, loss_fn, optax.sgd(0.1), model_dir,
                   profile_steps=(2, 4)) as est:
        est.train(_batches(x, y), max_steps=6)
        assert not est._profiling
    traces = glob.glob(os.path.join(model_dir, "tensorboard", "plugins",
                                    "profile", "*"))
    assert traces, "no xprof trace directory written"


@_isolated
def test_throttle_steps_must_be_positive():
    with pytest.raises(ValueError, match="throttle_steps"):
        EvalSpec(input_fn=lambda: iter(()), throttle_steps=0)


@_isolated
def test_empty_input_fn_raises(tmp_path):
    with _make_estimator(tmp_path / "m") as est:
        with pytest.raises(ValueError, match="no batches"):
            est.train(lambda: iter(()), max_steps=5)
        with pytest.raises(ValueError, match="no batches"):
            est.evaluate(lambda: iter(()), steps=2)


@_isolated
def test_enable_compilation_cache(tmp_path):
    import jax

    from tensorflowonspark_tpu.util import enable_compilation_cache

    old = jax.config.jax_compilation_cache_dir
    try:
        d = enable_compilation_cache(str(tmp_path / "cache"))
        assert os.path.isdir(d)
        assert jax.config.jax_compilation_cache_dir == d
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


@_isolated
def test_input_state_resumes_pipeline_after_restart(tmp_path):
    """A restarted estimator must continue the data stream where the saved
    checkpoint left it, not re-train the epoch's first batches (tf.data
    iterator-checkpointing parity)."""
    import jax.numpy as jnp

    seen_a, seen_b = [], []

    def make(record):
        def init_fn():
            return {"w": jnp.zeros(())}

        def loss_fn(params, batch):
            return params["w"] ** 2 + 0.0 * batch["i"].sum()

        def input_fn():
            for i in range(100):  # long epoch: never exhausted
                record.append(i)
                yield {"i": np.full((8,), i, np.float32)}

        return init_fn, loss_fn, input_fn

    init_fn, loss_fn, input_fn = make(seen_a)
    with Estimator(init_fn, loss_fn, optax.sgd(0.1), str(tmp_path / "m"),
                   save_every_steps=5, summary_dir="") as est:
        est.train(input_fn, max_steps=7)  # final save at step 7

    # "restart": a fresh estimator against the same model_dir
    init_fn, loss_fn, input_fn = make(seen_b)
    with Estimator(init_fn, loss_fn, optax.sgd(0.1), str(tmp_path / "m"),
                   save_every_steps=5, summary_dir="") as est:
        assert est.global_step == 7
        assert est._pending_input_resume == {"epoch": 0, "batches": 7}
        est.train(input_fn, max_steps=10)

    # the resumed run must TRAIN on batches 7, 8, 9 (the replayed prefix
    # 0..6 is only skipped through, never stepped on)
    trained_b = seen_b[7:10] if len(seen_b) >= 10 else None
    assert seen_b[:7] == list(range(7))  # deterministic replay of prefix
    assert trained_b == [7, 8, 9], (seen_b, trained_b)


@_isolated
def test_input_state_disabled_restarts_epoch(tmp_path):
    import jax.numpy as jnp

    def init_fn():
        return {"w": jnp.zeros(())}

    def loss_fn(params, batch):
        return params["w"] ** 2 + 0.0 * batch["i"].sum()

    def input_fn():
        for i in range(50):
            yield {"i": np.full((8,), i, np.float32)}

    kw = dict(save_every_steps=5, summary_dir="",
              checkpoint_input_state=False)
    with Estimator(init_fn, loss_fn, optax.sgd(0.1), str(tmp_path / "m"),
                   **kw) as est:
        est.train(input_fn, max_steps=6)
    with Estimator(init_fn, loss_fn, optax.sgd(0.1), str(tmp_path / "m"),
                   **kw) as est:
        assert est._pending_input_resume is None
        est.train(input_fn, max_steps=8)


@_isolated
def test_early_stopping_halts_on_plateau(tmp_path):
    import jax.numpy as jnp

    def init_fn():
        return {"w": jnp.zeros(())}

    def loss_fn(params, batch):
        # loss is constant in w: every eval round plateaus immediately
        return 1.0 + 0.0 * params["w"] + 0.0 * batch["i"].sum()

    def input_fn():
        for i in range(16):
            yield {"i": np.full((8,), i, np.float32)}

    with Estimator(init_fn, loss_fn, optax.sgd(0.1), str(tmp_path / "m"),
                   summary_dir="") as est:
        final = train_and_evaluate(
            est,
            TrainSpec(input_fn=input_fn, max_steps=1000),
            EvalSpec(input_fn=input_fn, steps=2, throttle_steps=4,
                     early_stopping_patience=2))
        # 1 improving round (first) + 2 stale rounds = stop at step 12
        assert est.global_step == 12, est.global_step
        assert final["loss"] == pytest.approx(1.0)


@_isolated
def test_early_stopping_patience_validation():
    with pytest.raises(ValueError, match="early_stopping_patience"):
        EvalSpec(input_fn=lambda: [], early_stopping_patience=0)


@_isolated
def test_early_stopping_state_survives_restart(tmp_path):
    import jax.numpy as jnp

    def make():
        def init_fn():
            return {"w": jnp.zeros(())}

        def loss_fn(params, batch):
            return 1.0 + 0.0 * params["w"] + 0.0 * batch["i"].sum()

        def input_fn():
            for i in range(16):
                yield {"i": np.full((8,), i, np.float32)}

        return init_fn, loss_fn, input_fn

    init_fn, loss_fn, input_fn = make()
    spec = dict(steps=2, throttle_steps=4, early_stopping_patience=3)
    with Estimator(init_fn, loss_fn, optax.sgd(0.1), str(tmp_path / "m"),
                   summary_dir="") as est:
        # run exactly 2 eval rounds (1 improving + 1 stale), then "crash"
        train_and_evaluate(est, TrainSpec(input_fn=input_fn, max_steps=8),
                           EvalSpec(input_fn=input_fn, **spec))
        assert est.global_step == 8

    init_fn, loss_fn, input_fn = make()
    with Estimator(init_fn, loss_fn, optax.sgd(0.1), str(tmp_path / "m"),
                   summary_dir="") as est:
        # resumed run: stale=1 carried over, so only 2 more stale rounds
        # (not 3) before the stop — step 16, not 20
        train_and_evaluate(est, TrainSpec(input_fn=input_fn, max_steps=1000),
                           EvalSpec(input_fn=input_fn, **spec))
        assert est.global_step == 16, est.global_step

    # a third launch of an already-stopped run must not train at all
    init_fn, loss_fn, input_fn = make()
    with Estimator(init_fn, loss_fn, optax.sgd(0.1), str(tmp_path / "m"),
                   summary_dir="") as est:
        train_and_evaluate(est, TrainSpec(input_fn=input_fn, max_steps=1000),
                           EvalSpec(input_fn=input_fn, **spec))
        assert est.global_step == 16, est.global_step


@_isolated
def test_early_stopping_unknown_metric_raises(tmp_path):
    import jax.numpy as jnp

    def init_fn():
        return {"w": jnp.zeros(())}

    def loss_fn(params, batch):
        return params["w"] ** 2 + 0.0 * batch["i"].sum()

    def input_fn():
        for i in range(8):
            yield {"i": np.full((8,), i, np.float32)}

    with Estimator(init_fn, loss_fn, optax.sgd(0.1), str(tmp_path / "m"),
                   summary_dir="") as est:
        with pytest.raises(ValueError, match="accuracy"):
            train_and_evaluate(
                est, TrainSpec(input_fn=input_fn, max_steps=8),
                EvalSpec(input_fn=input_fn, steps=2, throttle_steps=4,
                         early_stopping_patience=1, metric="accuracy"))


@_isolated
def test_negative_min_delta_rejected():
    with pytest.raises(ValueError, match="min_delta"):
        EvalSpec(input_fn=lambda: [], early_stopping_patience=1,
                 min_delta=-0.1)


@_isolated
def test_warm_start_loads_params_but_not_step(tmp_path):
    x, y = _linreg_problem()
    with _make_estimator(tmp_path / "donor") as est:
        est.train(_batches(x, y), max_steps=20)
        trained_w = np.asarray(est.params["w"])
    assert not np.allclose(trained_w, 0.0)

    with Estimator(*_triple(), str(tmp_path / "fresh"), summary_dir="",
                   warm_start_from=str(tmp_path / "donor")) as est:
        assert est.global_step == 0  # step starts fresh...
        np.testing.assert_allclose(np.asarray(est.params["w"]), trained_w)

    # a dir with a checkpoint ignores warm_start_from
    with Estimator(*_triple(), str(tmp_path / "donor"), summary_dir="",
                   warm_start_from=str(tmp_path / "fresh")) as est:
        assert est.global_step == 20

    with pytest.raises(ValueError, match="no\\s+checkpoint"):
        Estimator(*_triple(), str(tmp_path / "x"), summary_dir="",
                  warm_start_from=str(tmp_path / "empty"))


def _triple():
    import jax.numpy as jnp

    def init_fn():
        return {"w": jnp.zeros((4, 1))}

    def loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)

    return init_fn, loss_fn, optax.sgd(0.1)


@_isolated
def test_estimator_to_serve_parity(tmp_path):
    """Estimator → serve parity, end to end on one stack (ROADMAP item
    5's last pipeline gap): train a tiny GPT through ``Estimator``/
    ``train_and_evaluate`` (checkpoint under ``model_dir``), run the
    batch plane's ``GridSearch`` as the OFFLINE EVAL whose verdict gates
    promotion (``ModelRegistry.evaluate_grid``), then serve the
    promoted version on a real ``ServingCluster`` — with the served
    output greedy-exact vs a solo ``greedy_generate`` oracle over the
    SAME restored checkpoint."""
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.batch.gridsearch import GridSearch
    from tensorflowonspark_tpu.batch.manifest import ShardManifest
    from tensorflowonspark_tpu.models import GPT, greedy_generate
    from tensorflowonspark_tpu.serving import ModelRegistry, ServingCluster
    from tests.cluster_funcs import (rollout_parity_builder,
                                     rollout_parity_cfg,
                                     rollout_parity_predict)

    cfg = rollout_parity_cfg()
    model_dir = str(tmp_path / "ckpt")
    rng = np.random.default_rng(0)
    # batch rows divisible by any local device count (the default
    # DataParallelStrategy shards the batch over all devices)
    data = rng.integers(1, cfg.vocab_size, (16, 9)).astype(np.int32)

    def init_fn():
        return GPT(cfg).init(jax.random.key(0),
                             jnp.ones((1, 4), jnp.int32))["params"]

    def loss_fn(params, batch):
        x = batch["x"]
        logits = GPT(cfg).apply({"params": params}, x[:, :-1])
        logp = jax.nn.log_softmax(logits)
        picked = jnp.take_along_axis(logp, x[:, 1:, None], axis=-1)
        return -jnp.mean(picked)

    def input_fn():
        for i in range(0, len(data), 8):
            yield {"x": data[i:i + 8]}

    with Estimator(init_fn, loss_fn, optax.adam(1e-2), model_dir,
                   save_every_steps=2, handle_preemption=False,
                   summary_dir="") as est:
        final = train_and_evaluate(
            est, TrainSpec(input_fn=input_fn, max_steps=4),
            EvalSpec(input_fn=input_fn, steps=1, throttle_steps=4))
        assert final["global_step"] == 4

    # the driver-side oracle decodes under the SAME restored checkpoint
    _cfg, params = rollout_parity_builder({"model_dir": model_dir})
    prompts = [data[i, :5] for i in range(4)]
    budget = 4
    oracle = [np.asarray(greedy_generate(
        cfg, params, jnp.asarray(p)[None, :], budget))[0, p.size:].tolist()
        for p in prompts]

    # offline eval: the batch plane's GridSearch over the checkpoint
    reg = ModelRegistry()
    reg.register("parity", "v1", rollout_parity_builder)
    assert not reg.promotable("parity", "v1")
    gs = GridSearch(
        ShardManifest.from_arrays([np.stack(prompts[:2]),
                                   np.stack(prompts[2:])]),
        str(tmp_path / "eval"), rollout_parity_predict,
        param_grid=[{"budget": budget}],
        model_builder=rollout_parity_builder,
        predict_args={"model_dir": model_dir}, batch_size=2)
    gs.run(num_workers=1, max_restarts=0,
           worker_env={"JAX_PLATFORMS": "cpu"},
           working_dir=str(tmp_path / "wd"),
           reservation_timeout=120, shutdown_timeout=120)

    def scorer(results):
        got = [np.frombuffer(b, np.int32).tolist() for b in results]
        exact = sum(g == o for g, o in zip(got, oracle))
        return ({"exact": exact, "n": len(got)},
                len(got) == len(oracle) and exact == len(oracle))

    assert reg.evaluate_grid("parity", "v1", gs, "t0", scorer)
    assert reg.promotable("parity", "v1")
    assert reg.version("parity", "v1").eval_metrics == {"exact": 4, "n": 4}

    # serve the promoted version on one cluster; the registry entry's
    # builder restores the estimator checkpoint in the replica process
    serving = ServingCluster.run(
        None, 1, registry=reg, model=("parity", "v1"),
        replica_args={"model_dir": model_dir},
        worker_env={"JAX_PLATFORMS": "cpu"}, reservation_timeout=120)
    try:
        with serving.client() as c:
            got = c.generate(prompts[0], budget, model="parity")
        assert got.tolist() == oracle[0], \
            "served output diverged from the trained checkpoint's oracle"
        m = serving.metrics()
        assert m["registry"]["parity"]["v1"]["state"] == "serving"
        assert m["replicas"][0]["model"] == "parity"
    finally:
        serving.shutdown(timeout=300)
