"""Cluster orchestration integration tests.

Reference model: ``tests/test_TFCluster.py`` — run/train/inference/shutdown
round trips with trivial map_funs on a local multi-process cluster, both
input modes, error propagation (SURVEY.md §4).  Worker processes are real
OS processes via LocalProcessBackend, the rebuild's ``local-cluster`` analogue.
"""

import os

import pytest

from tensorflowonspark_tpu.cluster import (InputMode, Partitioned, TPUCluster,
                                           _build_cluster_template, _partition)
from tests import cluster_funcs as funcs

pytestmark = pytest.mark.integration


def _run(map_fun, num_workers=2, tmp=None, **kw):
    return TPUCluster.run(map_fun, kw.pop("tf_args", {}), num_workers,
                          reservation_timeout=60, working_dir=str(tmp), **kw)


def test_run_and_shutdown_noop(tmp_path):
    cluster = _run(funcs.fn_noop, 2, tmp_path)
    cluster.shutdown(timeout=60)


def test_role_assignment_template(tmp_path):
    cluster = _run(funcs.fn_write_role, 3, tmp_path, master_node="chief")
    cluster.shutdown(timeout=60)
    roles = {}
    for i in range(3):
        with open(os.path.join(str(tmp_path), f"role.{i}")) as f:
            roles[i] = f.read()
    assert roles[0].startswith("chief:0:1")     # chief is executor 0 and is_chief
    assert roles[1].startswith("worker:0:0")
    assert roles[2].startswith("worker:1:0")
    assert all(r.endswith(":3") for r in roles.values())


def test_train_feed_roundtrip(tmp_path):
    cluster = _run(funcs.fn_sum_feed, 2, tmp_path, tf_args={"batch_size": 8})
    cluster.train(list(range(100)), num_epochs=1)
    cluster.shutdown(timeout=60)
    total = count = 0
    for i in range(2):
        with open(os.path.join(str(tmp_path), f"sum.{i}")) as f:
            t, c = f.read().split(":")
            total += int(t)
            count += int(c)
    assert total == sum(range(100))
    assert count == 100


def test_train_multi_epoch(tmp_path):
    cluster = _run(funcs.fn_sum_feed, 2, tmp_path, tf_args={"batch_size": 16})
    cluster.train(list(range(10)), num_epochs=3)
    cluster.shutdown(timeout=60)
    total = count = 0
    for i in range(2):
        with open(os.path.join(str(tmp_path), f"sum.{i}")) as f:
            t, c = f.read().split(":")
            total += int(t)
            count += int(c)
    assert count == 30
    assert total == 3 * sum(range(10))


def test_inference_roundtrip(tmp_path):
    cluster = _run(funcs.fn_square_inference, 2, tmp_path)
    preds = cluster.inference(list(range(20)))
    cluster.shutdown(timeout=60)
    assert sorted(preds) == sorted(x * x for x in range(20))


def test_inference_more_partitions_than_nodes(tmp_path):
    # regression: multiple partitions routed to one node must be fed
    # sequentially, not interleaved by concurrent feeder threads
    cluster = _run(funcs.fn_square_inference, 2, tmp_path)
    preds = cluster.inference(Partitioned([[1, 2], [3, 4], [5, 6], [7]]))
    cluster.shutdown(timeout=60)
    assert sorted(preds) == sorted(x * x for x in range(1, 8))


def test_inference_backpressure_tiny_output_batches(tmp_path):
    # regression: worker emits 1 result message per sample; with queue_depth=4
    # the output queue fills while the driver is still feeding — the feeder
    # must drain results while its puts block instead of deadlocking
    cluster = _run(funcs.fn_tiny_batch_inference, 1, tmp_path, queue_depth=4)
    preds = cluster.inference(list(range(64)), chunk_size=8, feed_timeout=60)
    cluster.shutdown(timeout=60)
    assert sorted(preds) == [x + 1000 for x in range(64)]


def test_error_propagation_on_shutdown(tmp_path):
    cluster = _run(funcs.fn_crash, 2, tmp_path, input_mode=InputMode.TENSORFLOW)
    with pytest.raises(RuntimeError, match="deliberate failure"):
        cluster.shutdown(timeout=60)


def test_early_terminate_stops_feed(tmp_path):
    cluster = _run(funcs.fn_terminating_consumer, 1, tmp_path)
    # feed far more data than the consumer will read; must not hang
    cluster.train(list(range(10000)), num_epochs=0, feed_timeout=30)
    cluster.shutdown(timeout=60)
    assert os.path.exists(os.path.join(str(tmp_path), "term.0"))


# -- pure-function unit tests ----------------------------------------------

def test_build_cluster_template_roles():
    t = _build_cluster_template(5, num_ps=2, master_node="master", eval_node=True)
    assert t == {"ps": [0, 1], "evaluator": [4], "master": [2], "worker": [3]}


def test_build_cluster_template_workers_only():
    assert _build_cluster_template(3, 0, None, False) == {"worker": [0, 1, 2]}


def test_partition_even_split():
    parts = _partition(list(range(10)), 3)
    assert [len(p) for p in parts] == [4, 4, 2]
    assert sum(parts, []) == list(range(10))


def test_partition_explicit():
    parts = _partition(Partitioned([[1, 2], [3]]), 99)
    assert parts == [[1, 2], [3]]
