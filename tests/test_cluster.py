"""Cluster orchestration integration tests.

Reference model: ``tests/test_TFCluster.py`` — run/train/inference/shutdown
round trips with trivial map_funs on a local multi-process cluster, both
input modes, error propagation (SURVEY.md §4).  Worker processes are real
OS processes via LocalProcessBackend, the rebuild's ``local-cluster`` analogue.
"""

import os

import pytest

from tensorflowonspark_tpu.cluster import (InputMode, Partitioned, TPUCluster,
                                           _build_cluster_template, _partition)
from tests import cluster_funcs as funcs

pytestmark = pytest.mark.integration


def _run(map_fun, num_workers=2, tmp=None, **kw):
    return TPUCluster.run(map_fun, kw.pop("tf_args", {}), num_workers,
                          reservation_timeout=60, working_dir=str(tmp), **kw)


def test_run_and_shutdown_noop(tmp_path):
    cluster = _run(funcs.fn_noop, 2, tmp_path)
    cluster.shutdown(timeout=60)


def test_role_assignment_template(tmp_path):
    cluster = _run(funcs.fn_write_role, 3, tmp_path, master_node="chief")
    cluster.shutdown(timeout=60)
    roles = {}
    for i in range(3):
        with open(os.path.join(str(tmp_path), f"role.{i}")) as f:
            roles[i] = f.read()
    assert roles[0].startswith("chief:0:1")     # chief is executor 0 and is_chief
    assert roles[1].startswith("worker:0:0")
    assert roles[2].startswith("worker:1:0")
    assert all(r.endswith(":3") for r in roles.values())


def test_train_feed_roundtrip(tmp_path):
    cluster = _run(funcs.fn_sum_feed, 2, tmp_path, tf_args={"batch_size": 8})
    cluster.train(list(range(100)), num_epochs=1)
    cluster.shutdown(timeout=60)
    total = count = 0
    for i in range(2):
        with open(os.path.join(str(tmp_path), f"sum.{i}")) as f:
            t, c = f.read().split(":")
            total += int(t)
            count += int(c)
    assert total == sum(range(100))
    assert count == 100


def test_train_multi_epoch(tmp_path):
    cluster = _run(funcs.fn_sum_feed, 2, tmp_path, tf_args={"batch_size": 16})
    cluster.train(list(range(10)), num_epochs=3)
    cluster.shutdown(timeout=60)
    total = count = 0
    for i in range(2):
        with open(os.path.join(str(tmp_path), f"sum.{i}")) as f:
            t, c = f.read().split(":")
            total += int(t)
            count += int(c)
    assert count == 30
    assert total == 3 * sum(range(10))


def test_inference_roundtrip(tmp_path):
    cluster = _run(funcs.fn_square_inference, 2, tmp_path)
    preds = cluster.inference(list(range(20)))
    cluster.shutdown(timeout=60)
    assert sorted(preds) == sorted(x * x for x in range(20))


def test_inference_more_partitions_than_nodes(tmp_path):
    # regression: multiple partitions routed to one node must be fed
    # sequentially, not interleaved by concurrent feeder threads
    cluster = _run(funcs.fn_square_inference, 2, tmp_path)
    preds = cluster.inference(Partitioned([[1, 2], [3, 4], [5, 6], [7]]))
    cluster.shutdown(timeout=60)
    assert sorted(preds) == sorted(x * x for x in range(1, 8))


def test_inference_ordering_multi_node_uneven_partitions(tmp_path):
    """Satellite: result ordering across MULTIPLE feedable nodes follows
    partition index, with uneven partitions and more partitions than
    nodes — previously asserted only order-insensitively / single-node.
    Exact list equality: partition p goes to node p % N, results are
    re-merged by partition index regardless of node finish order."""
    cluster = _run(funcs.fn_square_inference, 3, tmp_path)
    parts = [[1, 2, 3], [4], [5, 6], [7, 8, 9, 10], [], [11]]
    preds = cluster.inference(Partitioned(parts))
    cluster.shutdown(timeout=60)
    assert preds == [x * x for x in range(1, 12)]  # exact order, not sorted


def test_inference_ordering_uneven_flat_split(tmp_path):
    """Same contract for a flat list: _partition's uneven split (larger
    partitions first) must re-merge into the input order."""
    cluster = _run(funcs.fn_square_inference, 2, tmp_path)
    data = list(range(23))
    preds = cluster.inference(data)
    cluster.shutdown(timeout=60)
    assert preds == [x * x for x in data]


def test_inference_backpressure_tiny_output_batches(tmp_path):
    # regression: worker emits 1 result message per sample; with queue_depth=4
    # the output queue fills while the driver is still feeding — the feeder
    # must drain results while its puts block instead of deadlocking
    cluster = _run(funcs.fn_tiny_batch_inference, 1, tmp_path, queue_depth=4)
    preds = cluster.inference(list(range(64)), chunk_size=8, feed_timeout=60)
    cluster.shutdown(timeout=60)
    assert sorted(preds) == [x + 1000 for x in range(64)]


def test_error_propagation_on_shutdown(tmp_path):
    cluster = _run(funcs.fn_crash, 2, tmp_path, input_mode=InputMode.TENSORFLOW)
    with pytest.raises(RuntimeError, match="deliberate failure"):
        cluster.shutdown(timeout=60)


def test_early_terminate_stops_feed(tmp_path):
    cluster = _run(funcs.fn_terminating_consumer, 1, tmp_path)
    # feed far more data than the consumer will read; must not hang
    cluster.train(list(range(10000)), num_epochs=0, feed_timeout=30)
    cluster.shutdown(timeout=60)
    assert os.path.exists(os.path.join(str(tmp_path), "term.0"))


# -- pure-function unit tests ----------------------------------------------

def test_build_cluster_template_roles():
    t = _build_cluster_template(5, num_ps=2, master_node="master", eval_node=True)
    assert t == {"ps": [0, 1], "evaluator": [4], "master": [2], "worker": [3]}


def test_build_cluster_template_workers_only():
    assert _build_cluster_template(3, 0, None, False) == {"worker": [0, 1, 2]}


def test_partition_even_split():
    parts = _partition(list(range(10)), 3)
    assert [len(p) for p in parts] == [4, 4, 2]
    assert sum(parts, []) == list(range(10))


def test_partition_explicit():
    parts = _partition(Partitioned([[1, 2], [3]]), 99)
    assert parts == [[1, 2], [3]]


def test_driver_side_streaming_stop(tmp_path):
    """An unbounded feed (num_epochs=0) must be stoppable from the DRIVER
    via stop_feed(), without worker-side DataFeed.terminate() (reference:
    TFCluster.py::shutdown's Spark-Streaming background path)."""
    import threading
    import time as _time

    cluster = _run(funcs.fn_sum_feed, 2, tmp_path, tf_args={"batch_size": 8})
    feeder = threading.Thread(
        target=cluster.train,
        args=(list(range(40)),), kwargs={"num_epochs": 0, "chunk_size": 8},
        daemon=True)
    feeder.start()
    _time.sleep(1.5)             # let several epochs stream
    assert feeder.is_alive(), "unbounded feed should still be streaming"

    cluster.stop_feed()
    feeder.join(timeout=30)
    assert not feeder.is_alive(), "stop_feed() must unblock the feeder thread"

    cluster.shutdown(timeout=60)  # delivers EndOfFeed; workers drain + exit
    consumed = 0
    for i in range(2):
        with open(os.path.join(str(tmp_path), f"sum.{i}")) as f:
            consumed += int(f.read().split(":")[1])
    assert consumed > 0, "workers should have consumed streamed data"


def test_run_with_recovery_resumes_from_checkpoint(tmp_path):
    """One injected chief crash mid-training: run_with_recovery must
    relaunch the cluster and the job must complete with the step count
    preserved (resume from orbax, not restart from 0) — SURVEY.md §5
    'recovery = whole-job restart + resume'."""
    from tensorflowonspark_tpu.checkpoint import CheckpointManager
    from tensorflowonspark_tpu.cluster import run_with_recovery

    model_dir = str(tmp_path / "ckpt")
    run_with_recovery(
        funcs.fn_train_checkpoint_crash_once,
        {"total_steps": 7, "crash_at": 3, "model_dir": model_dir},
        num_workers=2, max_restarts=2,
        working_dir=str(tmp_path), worker_env={"JAX_PLATFORMS": "cpu"},
        reservation_timeout=60, shutdown_timeout=120)

    ckpt = CheckpointManager(model_dir)
    assert ckpt.latest_step() == 7
    state = ckpt.restore()
    assert float(state["w"]) == 7.0  # 3 pre-crash steps + 4 resumed, not 7+3
    ckpt.close()

    with open(tmp_path / "resume.0") as f:
        starts = f.read().split()
    assert starts[0] == "0", starts
    assert "3" in starts[1:], f"chief must resume from step 3, got {starts}"


def test_run_with_recovery_gives_up_after_max_restarts(tmp_path):
    from tensorflowonspark_tpu.cluster import run_with_recovery

    with pytest.raises(RuntimeError, match="deliberate failure"):
        run_with_recovery(
            funcs.fn_crash, {}, num_workers=1, max_restarts=1,
            working_dir=str(tmp_path), worker_env={"JAX_PLATFORMS": "cpu"},
            reservation_timeout=60, shutdown_timeout=60)


def test_worker_compile_cache_env_contract(tmp_path, monkeypatch):
    """node.run exports the persistent-compile-cache env (honoring the
    TFOS_COMPILATION_CACHE / TFOS_CACHE_MIN_COMPILE_SECS knobs) before
    the user's map_fun — the relaunch-reuses-compiles contract."""
    # a pre-set JAX_* env would win (by design); test from a clean slate
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    monkeypatch.delenv("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                       raising=False)
    cluster = _run(funcs.fn_write_cache_env, 2, tmp_path,
                   worker_env={"TFOS_COMPILATION_CACHE": "/tmp/tfos_ct_cache",
                               "TFOS_CACHE_MIN_COMPILE_SECS": "0.7"})
    cluster.shutdown(timeout=60)
    for i in range(2):
        with open(os.path.join(str(tmp_path), f"cacheenv.{i}")) as f:
            assert f.read() == "/tmp/tfos_ct_cache:0.7"


def test_raise_worker_errors_aggregates_all_crashes(tmp_path):
    """A multi-worker failure must surface EVERY worker's traceback in one
    error, not one per restart (satellite: _raise_worker_errors)."""
    from tensorflowonspark_tpu.cluster import _raise_worker_errors

    (tmp_path / "error.0").write_text("Traceback...\nValueError: boom zero\n")
    (tmp_path / "error.2").write_text("Traceback...\nTypeError: boom two\n")
    with pytest.raises(RuntimeError) as ei:
        _raise_worker_errors(str(tmp_path), 3)
    msg = str(ei.value)
    assert "worker 0" in msg and "worker 2" in msg
    assert "boom zero" in msg and "boom two" in msg

    # single-crash format unchanged (the common case, matched by callers)
    (tmp_path / "error.2").unlink()
    with pytest.raises(RuntimeError, match="worker 0 failed"):
        _raise_worker_errors(str(tmp_path), 3)


class FlakyBackend:
    """LocalProcessBackend whose first start() raises — the relaunch-during-
    re-provisioning shape (an agent fleet not yet back after preemption)."""

    def __init__(self, fail_times=1, worker_env=None):
        from tensorflowonspark_tpu.cluster import LocalProcessBackend

        self._inner = LocalProcessBackend(worker_env=worker_env)
        self.fail_times = fail_times
        self.start_calls = 0

    def start(self, *a, **kw):
        self.start_calls += 1
        if self.start_calls <= self.fail_times:
            raise ConnectionError("agents still re-provisioning")
        self._inner.start(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_run_with_recovery_retries_bootstrap_failure(tmp_path):
    """When TPUCluster.run ITSELF raises (backend cannot launch), the
    recovery loop must classify it infra and relaunch — previously only
    in-training failures were exercised."""
    from tensorflowonspark_tpu.cluster import run_with_recovery

    backend = FlakyBackend(fail_times=1, worker_env={"JAX_PLATFORMS": "cpu"})
    run_with_recovery(
        funcs.fn_noop, {}, num_workers=1, max_restarts=2, backoff_base=0.1,
        backend=backend, working_dir=str(tmp_path),
        reservation_timeout=60, shutdown_timeout=60)
    assert backend.start_calls == 2  # failed once, relaunched, completed


def test_run_with_recovery_user_error_not_retried(tmp_path):
    """A deterministic map_fun ValueError classifies 'user': no relaunch,
    no burned restart budget — the error surfaces on the first attempt."""
    from tensorflowonspark_tpu.cluster import run_with_recovery

    restarts = []
    with pytest.raises(RuntimeError, match="deliberate failure"):
        run_with_recovery(
            funcs.fn_crash, {}, num_workers=1, max_restarts=3,
            on_restart=lambda *a: restarts.append(a),
            working_dir=str(tmp_path), worker_env={"JAX_PLATFORMS": "cpu"},
            reservation_timeout=60, shutdown_timeout=60)
    assert restarts == [], "user error must not be retried"


def test_run_with_recovery_restart_budget_window(tmp_path):
    """restart_budget=(R, T) bounds the restart RATE below max_restarts:
    an infra crash loop stops after R windowed restarts."""
    from tensorflowonspark_tpu.cluster import run_with_recovery

    kinds = []
    with pytest.raises(RuntimeError, match="injected infra failure"):
        run_with_recovery(
            funcs.fn_crash_infra, {}, num_workers=1, max_restarts=5,
            restart_budget=(1, 3600.0), backoff_base=0.1,
            on_restart=lambda attempt, exc, kind: kinds.append(kind),
            working_dir=str(tmp_path), worker_env={"JAX_PLATFORMS": "cpu"},
            reservation_timeout=60, shutdown_timeout=60)
    assert kinds == ["infra"], kinds  # one restart allowed, then budget cut


def test_shutdown_warns_on_stuck_feeder(tmp_path, caplog, monkeypatch):
    """A feeder thread that outlives the join window must be named in a
    warning before its QueueClient is closed out from under it."""
    import logging as _logging
    import threading

    class StubBackend:
        def join(self, timeout=None):
            return True

        def failed(self):
            return []

        def terminate(self):
            pass

    class StubServer:
        def stop(self):
            pass

    monkeypatch.setattr(TPUCluster, "FEEDER_JOIN_SECS", 0.2)
    cluster = TPUCluster(StubBackend(), StubServer(), [], {"num_workers": 0},
                         InputMode.TENSORFLOW, working_dir=str(tmp_path))
    release = threading.Event()
    t = threading.Thread(target=release.wait, name="stuck-feeder", daemon=True)
    t.start()
    cluster._active_feeders.add(t)
    try:
        with caplog.at_level(_logging.WARNING,
                             logger="tensorflowonspark_tpu.cluster"):
            cluster.shutdown(timeout=5)
        assert any("stuck-feeder" in r.getMessage() for r in caplog.records)
    finally:
        release.set()


def test_monitor_disabled_and_enabled(tmp_path):
    """monitor=False must actually disable the watchdog (regression: the
    run() parameter was once shadowed by a local), and the default must
    expose a running monitor on the handle."""
    cluster = _run(funcs.fn_noop, 1, tmp_path / "off", monitor=False)
    try:
        assert cluster.monitor is None
    finally:
        cluster.shutdown(timeout=60)
    (tmp_path / "on").mkdir()
    cluster = _run(funcs.fn_noop, 1, tmp_path / "on")
    try:
        assert cluster.monitor is not None
        assert cluster.monitor.failure is None
    finally:
        cluster.shutdown(timeout=60)
    assert (tmp_path / "on" / "health_events.jsonl").exists()
