"""Int8 weight-only quantization: numerics, pytree behavior, GPT decode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorflowonspark_tpu.models import GPT, GPTConfig, greedy_generate
from tensorflowonspark_tpu.ops import (Int8Array, quantize_int8,
                                       quantize_params, tree_nbytes)

TINY = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
                 intermediate_size=64, max_position_embeddings=64,
                 dtype=jnp.float32)


def test_quantize_int8_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.key(0), (64, 48), jnp.float32)
    qa = quantize_int8(w)
    assert qa.q.dtype == jnp.int8 and qa.shape == w.shape
    # symmetric per-channel: worst-case error is half a quantization step
    step = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 127.0
    assert float(jnp.max(jnp.abs(jnp.asarray(qa) - w) - step / 2)) <= 1e-6


def test_int8array_is_a_pytree_and_jits():
    w = jax.random.normal(jax.random.key(1), (16, 8))
    qa = quantize_int8(w)
    leaves = jax.tree.leaves(qa)
    assert len(leaves) == 2  # q + scale flow through jit/device_put

    @jax.jit
    def matmul(qa, x):
        return x @ jnp.asarray(qa)

    x = jnp.ones((4, 16))
    np.testing.assert_allclose(matmul(qa, x), x @ jnp.asarray(qa), rtol=1e-6)


def test_quantize_params_targets_kernels_only():
    params = GPT(TINY).init(jax.random.key(0),
                            jnp.ones((1, 8), jnp.int32))["params"]
    qparams = quantize_params(params)

    flat = jax.tree_util.tree_flatten_with_path(
        qparams, is_leaf=lambda x: isinstance(x, Int8Array))[0]
    kinds = {}
    for path, leaf in flat:
        name = str(getattr(path[-1], "key", path[-1]))
        kinds.setdefault(name, type(leaf).__name__)
    assert kinds["kernel"] == "Int8Array"
    for keep in ("embedding", "pos_emb", "bias", "scale"):
        assert kinds[keep] != "Int8Array", keep
    # tiny-model bound: embeddings/LN stay fp32, kernels drop ~4x
    assert tree_nbytes(qparams) < 0.5 * tree_nbytes(params)


@pytest.mark.parametrize("scan_layers", [False, True])
def test_gpt_decode_with_int8_params(scan_layers):
    import dataclasses

    cfg = dataclasses.replace(TINY, scan_layers=scan_layers)
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.ones((1, 8), jnp.int32))["params"]
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 0, cfg.vocab_size)

    qparams = quantize_params(params)
    # forward logits stay close to full precision...
    full = model.apply({"params": params}, prompt)
    quant = model.apply({"params": qparams}, prompt)
    assert float(jnp.max(jnp.abs(full - quant))) < 0.15 * float(
        jnp.max(jnp.abs(full)))
    # ...and the compiled KV-cache decode runs end to end on them
    out = jax.jit(greedy_generate, static_argnums=(0, 3))(
        cfg, qparams, prompt, 6)
    assert out.shape == (2, 11)
    assert bool(jnp.all(out[:, :5] == prompt))


def test_int8_decode_composes_with_tensor_parallelism(jax_cpu_mesh_devices):
    """Quantized params placed on a tp=2 mesh: generation must match the
    single-device quantized run, with q kernels actually sharded."""
    from jax.sharding import PartitionSpec as P

    from tensorflowonspark_tpu.ops import shard_quantized
    from tensorflowonspark_tpu.parallel import make_mesh
    from tensorflowonspark_tpu.parallel.mesh import MeshSpec
    from tensorflowonspark_tpu.parallel.sharding import flax_shardings

    import dataclasses

    cfg = dataclasses.replace(TINY, vocab_size=96)  # tp-divisible embedding
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.ones((1, 8), jnp.int32))["params"]
    prompt = jax.random.randint(jax.random.key(3), (2, 4), 0,
                                cfg.vocab_size)
    qparams = quantize_params(params)
    want = greedy_generate(cfg, qparams, prompt, 6)

    mesh = make_mesh(MeshSpec(tp=2, dp=1), devices=jax_cpu_mesh_devices[:2])
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32)))
    shardings = flax_shardings(mesh, abstract)["params"]
    placed = shard_quantized(qparams, shardings)

    qk = placed["layer_0"]["attn"]["query"]["kernel"]
    assert qk.q.sharding.spec == P(None, "tp")
    assert qk.q.addressable_shards[0].data.shape[1] == qk.shape[1] // 2
    # the out-projection kernel shards its INPUT dim; its scale (size-1
    # there) must stay unsharded on that axis
    ok = placed["layer_0"]["attn"]["out"]["kernel"]
    assert ok.q.sharding.spec == P("tp", None)
    assert ok.scale.sharding.spec in (P(None, None), P())

    with mesh:
        got = greedy_generate(cfg, placed, prompt, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_export_plainifies_quant_inside_flax_boxes(tmp_path):
    """A quantized leaf wrapped in a flax ``Partitioned`` box must still
    export as quantized: _plainify_int8 unboxes non-quant AxisMetadata
    inline (it runs before export_model's meta.unbox, which would
    DEQUANTIZE an Int4PackedArray — its unbox() is the flax param-read
    dequant)."""
    from flax.core import meta

    from tensorflowonspark_tpu.checkpoint import ExportedModel, export_model
    from tensorflowonspark_tpu.ops import quantize_int4, quantize_int8

    w = jax.random.normal(jax.random.key(0), (16, 8))
    params = {"a": meta.Partitioned(quantize_int8(w), names=(None, "tp")),
              "b": meta.Partitioned(quantize_int4(w), names=(None, "tp"))}
    x = np.ones((4, 16), np.float32)

    def fn(p, x):
        return x @ jnp.asarray(p["a"]) + x @ jnp.asarray(p["b"])

    want = fn({"a": quantize_int8(w), "b": quantize_int4(w)}, x)
    d = str(tmp_path / "e")
    export_model(d, fn, params, [x])
    loaded = ExportedModel.load(d)
    got = next(iter(loaded(x).values()))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    qdtypes = []

    def walk(n):
        if isinstance(n, dict):
            if "q" in n:
                qdtypes.append(str(n["q"].dtype))
            for v in n.values():
                walk(v)

    walk(loaded.params)
    assert sorted(qdtypes) == ["int8", "uint8"]  # both stayed quantized


def test_int4_packed_tp_indivisible_axis_replicates(jax_cpu_mesh_devices):
    """A spec valid for the LOGICAL kernel shape may not divide the packed
    buffer's halved last dim (logical out=4 over tp=4 -> packed dim 2, or
    odd packed dims).  shard_quantized must replicate that axis instead of
    raising, and the dequant must stay exact."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tensorflowonspark_tpu.ops import quantize_params, shard_quantized

    mesh = Mesh(np.array(jax_cpu_mesh_devices[:4]).reshape(4), ("tp",))
    params = {"d": {"kernel": jax.random.normal(jax.random.key(0), (16, 4))}}
    sh = {"d": {"kernel": NamedSharding(mesh, P(None, "tp"))}}
    q4 = quantize_params(params, bits=4)
    placed = shard_quantized(q4, sh)
    leaf = placed["d"]["kernel"]
    assert leaf.q.sharding.spec == P(None, None)  # replicated, not raised
    np.testing.assert_array_equal(
        np.asarray(jnp.asarray(leaf)),
        np.asarray(jnp.asarray(q4["d"]["kernel"])))


def test_int8_export_serves_without_model_code(tmp_path):
    """Quantize -> export_model -> ExportedModel: the serving artifact
    stores int8 weights and replies like the in-process quantized model."""
    import flax.linen as nn

    from tensorflowonspark_tpu.checkpoint import ExportedModel, export_model

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(32)(x)
            x = nn.relu(x)
            return nn.Dense(4)(x)

    net = Net()
    x = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
    params = net.init(jax.random.key(0), x)["params"]
    qparams = quantize_params(params)
    want = net.apply({"params": qparams}, x)

    def fwd(p, x):
        return net.apply({"params": p}, x)

    export_dir = str(tmp_path / "export")
    export_model(export_dir, fwd, qparams, [x])

    loaded = ExportedModel.load(export_dir)
    got = next(iter(loaded(x).values()))  # single output, default name
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # weights on disk / in memory stay int8 (check the restored tree)
    flat = jax.tree.leaves(loaded.params)
    assert any(getattr(l, "dtype", None) == jnp.int8 for l in flat)


@pytest.mark.parametrize("storage", ["packed", "native"])
def test_quantize_int4_roundtrip_error_bounded(storage):
    from tensorflowonspark_tpu.ops import (Int4Array, Int4PackedArray,
                                           quantize_int4)

    w = jax.random.normal(jax.random.key(2), (64, 48), jnp.float32)
    qa = quantize_int4(w, storage=storage)
    if storage == "native":
        assert isinstance(qa, Int4Array)
        assert qa.q.shape == w.shape and qa.q.dtype == jnp.int4
    else:
        assert isinstance(qa, Int4PackedArray)
        assert qa.q.shape == (64, 24) and qa.q.dtype == jnp.uint8
    assert qa.shape == w.shape and qa.ndim == 2
    # worst-case error: half a step of the 15-level grid
    step = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 7.0
    assert float(jnp.max(jnp.abs(jnp.asarray(qa) - w) - step / 2)) <= 1e-6
    # packed accounting: two weights per byte + fp32 scales
    assert qa.nbytes == w.size // 2 + 48 * 4


def test_int4_packed_lifted_axis_transform_fails_loudly():
    """add_axis/remove_axis (flax lifted-transform protocol) must raise,
    not return self: a transform that really changes a param axis would
    leave logical_shape stale and dequantize the wrong dim silently
    (ADVICE r5 item 1)."""
    from tensorflowonspark_tpu.ops import quantize_int4

    qa = quantize_int4(jax.random.normal(jax.random.key(3), (8, 6)),
                       storage="packed")
    with pytest.raises(NotImplementedError, match="lifted"):
        qa.add_axis(0, {})
    with pytest.raises(NotImplementedError, match="lifted"):
        qa.remove_axis(0, {})


def test_int4_packed_matches_native_dequant():
    """The uint8 nibble packing is a pure storage change: packed and
    native int4 dequantize to IDENTICAL arrays, including odd last dims
    (padding sliced back off) and negative values (nibble sign
    extension)."""
    from tensorflowonspark_tpu.ops import quantize_int4

    for shape in ((64, 48), (5, 7), (3, 4, 9)):
        w = jax.random.normal(jax.random.key(9), shape, jnp.float32)
        native = jnp.asarray(quantize_int4(w, storage="native"))
        packed_arr = quantize_int4(w, storage="packed")
        assert packed_arr.shape == shape
        np.testing.assert_array_equal(np.asarray(jnp.asarray(packed_arr)),
                                      np.asarray(native))


def test_int4_exact_for_representable_grid():
    """Values already on the int4 grid dequantize exactly (incl.
    negative values)."""
    from tensorflowonspark_tpu.ops import quantize_int4

    q = np.array([[-7, -3, 0, 1], [5, 7, -1, 2]], np.float32).T  # K=4, N=2
    w = jnp.asarray(q) * 0.25
    np.testing.assert_allclose(np.asarray(jnp.asarray(quantize_int4(w))),
                               np.asarray(w), rtol=0, atol=1e-7)


@pytest.mark.parametrize("storage", ["packed", "native"])
def test_int4array_jits_and_matmuls(storage):
    from tensorflowonspark_tpu.ops import quantize_int4

    w = jax.random.normal(jax.random.key(3), (32, 16))
    qa = quantize_int4(w, storage=storage)
    assert len(jax.tree.leaves(qa)) == 2

    @jax.jit
    def matmul(qa, x):
        return x @ jnp.asarray(qa)

    x = jax.random.normal(jax.random.key(4), (4, 32))
    got = matmul(qa, x)
    # against the dequantized reference (quantization error already
    # covered above); jit path must agree with eager dequant
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(x @ jnp.asarray(qa)),
                               rtol=1e-5, atol=1e-5)


def test_quantize_params_bits4_targets_kernels():
    from tensorflowonspark_tpu.ops import Int4PackedArray, quantize_params

    params = {"a": {"kernel": jnp.ones((8, 4))},
              "odd": {"kernel": jnp.ones((7, 5))},  # odd dims both axes
              "bias": jnp.ones((4,))}
    qp = quantize_params(params, bits=4)
    assert isinstance(qp["a"]["kernel"], Int4PackedArray)
    assert isinstance(qp["odd"]["kernel"], Int4PackedArray)
    assert qp["odd"]["kernel"].shape == (7, 5)
    assert not isinstance(qp["bias"], Int4PackedArray)


def test_gpt_decode_with_int4_params():
    """End-to-end: greedy decode runs on int4-packed weights and emits
    valid token ids; tree bytes ~half of int8."""
    import dataclasses

    from tensorflowonspark_tpu.models import GPT, GPTConfig, greedy_generate
    from tensorflowonspark_tpu.ops import quantize_params, tree_nbytes

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, intermediate_size=64,
                    max_position_embeddings=64, dtype=jnp.float32)
    params = GPT(cfg).init(jax.random.key(0),
                           jnp.ones((1, 4), jnp.int32))["params"]
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, 128)
    q8 = quantize_params(params)
    q4 = quantize_params(params, bits=4)
    # kernel payloads halve (embeddings/norms stay fp and dominate this
    # tiny model, so compare the quantized leaves, not the whole tree)
    from tensorflowonspark_tpu.ops import Int4PackedArray
    from tensorflowonspark_tpu.ops.quant import Int8Array

    def quantized_bytes(tree, cls):
        return sum(l.nbytes for l in jax.tree.leaves(
            tree, is_leaf=lambda x: isinstance(x, cls))
            if isinstance(l, cls))

    assert quantized_bytes(q4, Int4PackedArray) < \
        0.6 * quantized_bytes(q8, Int8Array)
    assert tree_nbytes(q4) < tree_nbytes(q8)
    out = greedy_generate(cfg, q4, prompt, 8)
    assert out.shape == (2, 16)
    assert bool(jnp.all((out >= 0) & (out < 128)))


def test_int4_export_serves_without_model_code(tmp_path):
    """bits=4 trees flow through export_model/ExportedModel like int8."""
    import flax.linen as nn

    from tensorflowonspark_tpu.checkpoint import ExportedModel, export_model

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(nn.relu(nn.Dense(32)(x)))

    net = Net()
    x = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
    params = net.init(jax.random.key(0), x)["params"]
    qparams = quantize_params(params, bits=4)
    want = net.apply({"params": qparams}, x)

    export_dir = str(tmp_path / "export")
    export_model(export_dir, lambda p, x: net.apply({"params": p}, x),
                 qparams, [x])
    loaded = ExportedModel.load(export_dir)
    got = next(iter(loaded(x).values()))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # the restored tree is the plain orbax form: packed uint8 nibble
    # buffers (halved last dim) + lshape records; the dequant lives in
    # the traced StableHLO, so the disk/HBM payload stays packed
    def find(node, key, acc):
        if isinstance(node, dict):
            if key in node:
                acc.append(node[key])
            for v in node.values():
                find(v, key, acc)
        return acc

    qs = [q for q in find(loaded.params, "q", [])
          if q.dtype == jnp.uint8]
    lshapes = [tuple(int(d) for d in ls)
               for ls in find(loaded.params, "lshape", [])]
    assert len(qs) == 2 and len(lshapes) == 2
    assert sorted(q.shape for q in qs) == [(16, 16), (32, 2)]
    assert sorted(lshapes) == [(16, 32), (32, 4)]
