"""Sanity locks for the analytic MXU-ceiling/roofline model
(``scripts/resnet_mxu_ceiling.py``) — the CPU-side half of the MFU-plateau
diagnosis (VERDICT r3 item 2)."""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

from resnet_mxu_ceiling import analyze, resnet50_convs  # noqa: E402


def test_conv_inventory_matches_resnet50():
    convs = resnet50_convs("conv7")
    # 1 stem + per-stage (3,4,6,3) bottlenecks x 3 convs + 4 projections
    assert len(convs) == 1 + 3 * (3 + 4 + 6 + 3) + 4
    names = [c[0] for c in convs]
    assert names[0] == "stem_conv7"
    assert "s2b1_proj" in names and "s1b2_proj" not in names
    # v1.5: the stride lives on the 3x3
    by_name = {c[0]: c for c in convs}
    assert by_name["s2b1_3x3"][5] == 2 and by_name["s2b1_1x1a"][5] == 1


def test_flops_match_known_resnet50_count():
    """Useful train FLOPs must land on the known ~24 GFLOP/img
    (8.02 fwd x ~3 for train, minus the stem's absent dgrad) — the same
    convention as the bench's MFU numerator."""
    out = analyze(256, "conv7")
    per_img = out["total_train_gflops_useful"] / 256
    assert 21 < per_img < 26, per_img


def test_bounds_are_bounds():
    out = analyze(256, "conv7")
    assert 0 < out["padding_ceiling_mfu"] <= 1
    assert 0 < out["roofline_mfu"] <= out["padding_ceiling_mfu"] + 1e-9
    # the measured plateau (0.232-0.246) must sit BELOW the optimistic
    # roofline — if a code change ever drops the roofline under the
    # measurement, the model's assumptions are broken
    assert out["roofline_mfu"] > 0.25
    # s2d and conv7 ceilings are near-equal once the stem has no dgrad —
    # the analytic echo of the measured +0.8% s2d non-gain
    s2d = analyze(256, "s2d")
    assert abs(s2d["padding_ceiling_mfu"]
               - out["padding_ceiling_mfu"]) < 0.05
