"""Steady-state failure detection: heartbeats, watchdog, classification.

The reference's recovery model is whole-job restart + checkpoint resume
(SURVEY.md §5), and the rebuild has the restart loop
(``cluster.run_with_recovery``) and preemption latching (``preemption.py``)
— but until this module, failure *detection* existed only at bootstrap:
``cluster._watch_for_crashes`` exits once reservations complete, so a
mid-training crash was noticed only when a feeder socket happened to break,
and a hung worker (the SPMD-collective wedge named in ``TPUCluster._abort``'s
docstring) was never detected before ``shutdown``'s multi-day join timeout.
Spark gave the reference this for free (executor heartbeats + task failure
propagation); this module is the from-scratch equivalent:

- :class:`HeartbeatReporter` — worker side.  A background thread in
  ``node.run``'s harness publishes ``{seq, time, step, phase}`` into the
  node's existing kv store every ``interval`` seconds; the user's
  ``map_fun`` advances the ``step`` field through ``ctx.report_step()``.
- :class:`ClusterMonitor` — driver side, running for the cluster's whole
  life.  Polls ``backend.alive()``/``failed()`` and per-node heartbeat age,
  classifies what it sees (:class:`ClusterFailure` kinds ``crash`` /
  ``hang`` / ``preemption``), emits health events through
  :class:`~tensorflowonspark_tpu.observability.EventLog`, and triggers
  fail-fast :meth:`TPUCluster._abort` so a half-dead SPMD job is torn down
  in seconds instead of wedging on collectives.

Staleness is measured on the *driver's* clock from when a heartbeat payload
last **changed** (the ``seq`` counter), so cross-host clock skew cannot
false-positive the watchdog.  The hang watchdog only arms once a node has
reported at least one step — a long XLA compile before step 1 must not be
mistaken for a wedge.

Restart policy helpers (:func:`classify_failure`, :func:`classify_restart`,
:func:`backoff_delay`, :class:`RestartBudget`) back the upgraded
``cluster.run_with_recovery`` loop: deterministic user errors (a
``ValueError`` out of the map_fun's first step) are not retried, infra
failures always are, with exponential backoff + jitter inside a
max-R-restarts-per-T-seconds budget window.
"""

from __future__ import annotations

import contextlib
import logging
import os
import random
import re
import signal
import threading
import time
from collections import deque

from tensorflowonspark_tpu import metrics as tpu_metrics
from tensorflowonspark_tpu import observability
from tensorflowonspark_tpu.queues import QueueClient

logger = logging.getLogger(__name__)

HEARTBEAT_KEY = "heartbeat"

# Failure kinds a ClusterFailure / classify_failure can carry.
CRASH = "crash"            # worker process exited nonzero / was killed
HANG = "hang"              # heartbeat stale or step progress stalled
PREEMPTION = "preemption"  # SIGTERM-shaped exit (spot/preemptible reclaim)
USER = "user"              # deterministic error raised by the map_fun
INFRA = "infra"            # everything environmental (sockets, timeouts...)
#: terminal outcome kind: ``run_with_recovery``'s sliding-window restart
#: budget overflowed — the driver GAVE UP (emitted to the health
#: EventLog and as ``tfos_restarts_total{kind="budget_exhausted"}``
#: before the final re-raise, so operators can tell "gave up" from
#: "still retrying")
BUDGET_EXHAUSTED = "budget_exhausted"

# Exception types that mean "the user's code is wrong and will be wrong
# again on the next attempt" — retrying burns the restart budget for
# nothing.  Matched by *name* against worker tracebacks, which arrive as
# text (cluster._raise_worker_errors re-raises crash files).
_NO_RETRY_ERRORS = frozenset({
    "ValueError", "TypeError", "KeyError", "IndexError", "AttributeError",
    "AssertionError", "ZeroDivisionError", "NotImplementedError",
    "ImportError", "ModuleNotFoundError", "NameError",
    # submit-time payload rejection (analysis.preflight): deterministic —
    # the same payload fails identically on every attempt
    "PreflightError",
})

_TB_ERROR_RE = re.compile(r"^([A-Za-z_][\w.]*(?:Error|Exception|Interrupt))\b",
                          re.MULTILINE)


class ClusterFailure(RuntimeError):
    """A classified steady-state failure detected by :class:`ClusterMonitor`.

    ``kind`` is one of ``crash`` / ``hang`` / ``preemption``;
    ``failed_workers`` names the executor ids the detection implicates;
    ``detected_at`` is the driver's ``time.time()`` at detection (used by
    ``scripts/bench_recovery.py`` for detection-latency accounting).
    """

    def __init__(self, kind: str, message: str, failed_workers=()):
        super().__init__(message)
        self.kind = kind
        self.failed_workers = tuple(failed_workers)
        self.detected_at = time.time()


# ------------------------------------------------------------- worker side

class HeartbeatReporter:
    """Background liveness publisher for one worker process.

    Publishes ``{seq, time, step, phase, pid}`` under kv key ``heartbeat``
    every ``interval`` seconds through ``mgr`` (the node's in-process
    :class:`~tensorflowonspark_tpu.queues.QueueServer` — the driver's
    monitor reads it over the same TCP kv the feed already uses, so no new
    port or protocol).  ``report_step`` publishes immediately, so the
    driver sees step progress with sub-interval latency.

    The reporter is also the mount point for chaos injection
    (:mod:`~tensorflowonspark_tpu.chaos`): step- and time-triggered faults
    piggyback on ``report_step`` / the beat thread, and the ``stall``
    fault suppresses publishing to simulate a wedged process whose OS
    process is still alive.
    """

    def __init__(self, mgr, interval: float = 1.0):
        self.mgr = mgr
        self.interval = float(interval)
        self._seq = 0
        self._step: int | None = None
        self._phase = "boot"
        self._goodput = None            # observability.GoodputRecorder
        self._metrics_extras: dict = {}  # last snapshot, reused per publish
        # RLock: set_phase("preempted") runs inside the SIGTERM handler,
        # which executes on the MAIN thread and may interrupt report_step
        # while it holds this lock — a plain Lock would self-deadlock
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._stall_until = 0.0          # monotonic deadline; inf = forever
        self._chaos = None
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "HeartbeatReporter":
        self._publish(include_metrics=True)
        self._thread = threading.Thread(target=self._run, name="heartbeat",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- producer API ----------------------------------------------------
    def report_step(self, step: int, phase: str = "step") -> None:
        """Record training progress (the ``ctx.report_step()`` hook).

        Arms the driver's hang watchdog from the first step ≥ 1 onward and
        gives chaos actions their deterministic ``at_step`` trigger.
        """
        with self._lock:
            self._step = int(step)
            self._phase = phase
        self._publish()
        if self._chaos is not None:
            self._chaos.on_step(int(step))

    def set_phase(self, phase: str) -> None:
        """Lifecycle phase (``boot``/``init``/``run``/``preempted``/...)
        surfaced to the driver's classifier."""
        with self._lock:
            self._phase = phase
        self._publish(include_metrics=True)

    def attach_goodput(self, recorder) -> None:
        """Carry ``recorder.summary()`` in the heartbeat payload so the
        driver's aggregated ``metrics()`` view shows per-node goodput
        live, not only as an end-of-job JSON file (``ctx.goodput()`` is
        the map_fun-side entry point)."""
        self._goodput = recorder
        self._publish(include_metrics=True)

    def note_preempted(self) -> None:
        """Signal-handler-safe phase flip to ``preempted``: one attribute
        store, NO locks and NO kv write — ``_publish`` goes through the
        queue server's non-reentrant kv lock, which the interrupted main
        thread may hold mid-``report_step``.  The beat thread publishes
        the new phase within one ``interval``; the driver reads it only
        after the exit, so the delay is immaterial."""
        self._phase = "preempted"

    def stall(self, secs: float | None = None) -> None:
        """Stop publishing for ``secs`` (``None`` = forever) — the chaos
        'wedged process' fault: the OS process stays alive, the heartbeat
        goes stale, and the driver's watchdog must notice."""
        self._stall_until = (float("inf") if secs is None
                             else time.monotonic() + float(secs))

    def attach_chaos(self, agent) -> None:
        self._chaos = agent
        agent.attach(self)

    # -- internals -------------------------------------------------------
    def _publish(self, include_metrics: bool = False) -> None:
        """Publish the heartbeat payload.  ``include_metrics`` refreshes
        this process's metrics-registry snapshot (and goodput summary) —
        the zero-new-sockets telemetry transport.  Only the periodic
        beat (and phase changes) pay the snapshot cost; ``report_step``'s
        per-step publishes reuse the cached extras so a fast decode/
        train loop never folds histograms on its hot path, yet EVERY
        payload the driver samples carries telemetry (at most one
        ``interval`` stale)."""
        if time.monotonic() < self._stall_until:
            return
        if include_metrics:
            # snapshot outside the reporter lock: it takes registry locks
            # of its own and runs collect hooks
            try:
                extras = {"metrics": tpu_metrics.get_registry().snapshot()}
                if self._goodput is not None:
                    extras["goodput"] = self._goodput.summary()
                self._metrics_extras = extras
            # tfos: ignore[broad-except] — telemetry enrichment must never
            # block liveness reporting; the bare heartbeat still goes out
            except Exception:
                logger.debug("heartbeat metrics snapshot failed",
                             exc_info=True)
        with self._lock:
            self._seq += 1
            payload = {"seq": self._seq, "time": time.time(),
                       "step": self._step, "phase": self._phase,
                       "pid": os.getpid(), **self._metrics_extras}
        try:
            self.mgr.kv_set(HEARTBEAT_KEY, payload)
        # tfos: ignore[broad-except] — liveness reporting must never kill
        # training; a dropped heartbeat IS the signal the driver detects
        except Exception:
            pass

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            if self._chaos is not None:
                self._chaos.on_tick()
            self._publish(include_metrics=True)


# ------------------------------------------------------------- driver side

class ClusterMonitor:
    """Steady-state watchdog over one running :class:`TPUCluster`.

    Runs a daemon thread from the end of ``TPUCluster.run`` until
    ``shutdown``/``_abort``, doing two checks per ``poll_interval``:

    1. **process check** — ``backend.failed()``; a nonzero exit is
       classified ``crash``, a uniformly SIGTERM-shaped exit (or one whose
       last reported phase was ``preempted``) is ``preemption``.
    2. **heartbeat check** — per-node kv read of the ``heartbeat`` payload
       via a dedicated short-timeout :class:`QueueClient` (``shm=False``:
       the monitor must not consume zero-copy ring slots).  A node whose
       payload has not *changed* for ``hang_timeout`` seconds — measured on
       the driver's clock — is classified ``hang``; likewise, with
       ``step_timeout`` set, a node whose *step* has not advanced.  Both
       checks arm only once that node has reported step ≥ 1, so long
       initial compiles cannot false-positive.

    On any failure the monitor records a :class:`ClusterFailure`, emits a
    health event, and (with ``abort_on_failure``, the default) triggers the
    cluster's fail-fast ``_abort()`` so surviving workers are torn down
    instead of wedging on collectives.  ``TPUCluster.shutdown`` re-raises
    the recorded failure; ``cluster.run_with_recovery`` classifies it for
    the restart decision.

    **Serving-tier mode** (``abort_on_failure=False, keep_polling=True,
    on_failure=...``): an online serving cluster must OUTLIVE a replica
    death — the right response is re-routing, not teardown.  With
    ``keep_polling`` the monitor does not stop at the first failure: each
    classified failure is appended to :attr:`failures`, handed to the
    ``on_failure(failure)`` callback (exceptions suppressed — detection
    must outlive a buggy subscriber), and the implicated workers are
    retired from both checks so one dead replica is reported exactly once
    while the survivors stay under watch.  Training clusters keep the
    default fail-fast single-shot behavior.
    """

    def __init__(self, cluster, hang_timeout: float = 120.0,
                 poll_interval: float = 0.5, step_timeout: float | None = None,
                 abort_on_failure: bool = True, event_log=None,
                 client_factory=None, on_failure=None,
                 keep_polling: bool = False, on_phase=None):
        self.cluster = cluster
        self.hang_timeout = float(hang_timeout)
        self.poll_interval = float(poll_interval)
        self.step_timeout = None if step_timeout is None else float(step_timeout)
        self.abort_on_failure = abort_on_failure
        self._own_events = event_log is None and bool(
            getattr(cluster, "working_dir", None))
        if self._own_events:
            event_log = observability.EventLog(
                os.path.join(cluster.working_dir, "health_events.jsonl"))
        self.events = event_log
        self._client_factory = client_factory or (
            lambda info: QueueClient(info["addr"], info["authkey"],
                                     timeout=2.0, shm=False))
        self.on_failure = on_failure
        #: ``on_phase(eid, phase)`` fires when a node's heartbeat-reported
        #: lifecycle phase CHANGES (exceptions suppressed, like
        #: ``on_failure``).  The serving tier subscribes to catch phase
        #: ``preempted`` while the process is still alive — its grace
        #: window — and turn it into drain-and-replace instead of waiting
        #: for the exit.
        self.on_phase = on_phase
        self.keep_polling = bool(keep_polling)
        #: every classified failure, in detection order (one entry per
        #: failure with ``keep_polling``; at most one without)
        self.failures: list[ClusterFailure] = []
        self._handled: set[int] = set()  # workers already reported
        self._clients: dict[int, QueueClient] = {}
        self._kv_retry_at: dict[int, float] = {}  # reconnect cooldowns
        self._hb: dict[int, dict] = {}
        self._failures_total = tpu_metrics.get_registry().counter(
            "tfos_health_failures_total",
            "Classified cluster failures detected by the monitor.",
            labelnames=("kind",))
        self._failure: ClusterFailure | None = None
        self._failure_evt = threading.Event()
        self._stop = threading.Event()
        self._poll_lock = threading.Lock()
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ClusterMonitor":
        self._emit("monitor_started",
                   workers=len(self.cluster.cluster_info),
                   hang_timeout=self.hang_timeout,
                   step_timeout=self.step_timeout)
        self._thread = threading.Thread(target=self._loop,
                                        name="cluster-monitor", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        # the monitor thread itself reaches stop() through cluster._abort()
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        for c in self._clients.values():
            with contextlib.suppress(Exception):
                c.close()
        # tfos: ignore[lock-discipline] — the monitor thread is joined above;
        # a >5s join straggler only swaps per-eid entries (GIL-atomic) and
        # its next _poll_kv sees _stop set
        self._clients.clear()
        if self._own_events and self.events is not None:
            self.events.close()
            self.events = None
            self._own_events = False

    @property
    def failure(self) -> ClusterFailure | None:
        return self._failure

    def wait(self, timeout: float | None = None) -> ClusterFailure | None:
        """Block until a failure is detected (or ``timeout``); returns it."""
        self._failure_evt.wait(timeout)
        return self._failure

    def node_metrics(self) -> dict[int, dict]:
        """Last heartbeat-carried telemetry per node: ``{eid: {"metrics":
        <registry snapshot>, "goodput": <summary|None>, "step", "phase",
        "age_secs"}}`` — the driver-side aggregation point behind
        ``TPUCluster.metrics()`` / ``ServingCluster.metrics()``.  Purely
        a read of what the monitor already polls; no extra kv round."""
        now = time.monotonic()
        out: dict[int, dict] = {}
        for eid, rec in list(self._hb.items()):
            if eid in self._handled:
                # dead/retired workers must drop off the merged page,
                # not freeze at their last-reported values
                continue
            out[eid] = {"metrics": rec.get("metrics") or {},
                        "goodput": rec.get("goodput"),
                        "step": rec.get("step"), "phase": rec.get("phase"),
                        "age_secs": now - rec.get("seen", now)}
        return out

    def live_unhandled(self) -> list[int]:
        """Executor ids still alive and not yet retired from watching —
        the scoring/serving capacity a ``keep_polling`` consumer (the
        serving tier, the batch dispatcher) can still route work to.
        One backend sweep; no kv round."""
        _codes, alive, _failed = self._backend_snapshot()
        out = []
        for node in list(self.cluster.cluster_info):
            eid = node["executor_id"]
            if eid in self._handled:
                continue
            if eid < len(alive) and not alive[eid]:
                continue
            out.append(eid)
        return out

    def ignore_worker(self, executor_id: int) -> None:
        """Retire ``executor_id`` from both checks: a deliberately
        drained-and-stopped member (elastic scale-down, preemption
        drain) must not be classified as a crash/hang when it exits —
        nor keep contributing a frozen row to ``node_metrics``.  Its
        kv client is dropped."""
        eid = int(executor_id)
        with self._poll_lock:  # serialize vs an in-flight poll's checks
            self._handled.add(eid)
            cli = self._clients.pop(eid, None)
        if cli is not None:
            with contextlib.suppress(Exception):
                cli.close()

    def ignore_workers(self, executor_ids) -> None:
        """Retire several workers at once — the serving tier's gang verb:
        a mesh-sharded replica drains/dies as one unit, so its whole
        executor-id block leaves the watch together."""
        for eid in executor_ids:
            self.ignore_worker(int(eid))

    def poll_now(self) -> ClusterFailure | None:
        """One synchronous check, returning any (new or prior) failure.

        ``TPUCluster.shutdown`` calls this right after ``backend.join``
        returns: a worker that died *during* the join unblocks it
        immediately — possibly inside the monitor thread's poll sleep — and
        must still leave with a classified failure, not fall through to the
        generic nonzero-exit error.
        """
        with self._poll_lock:
            if self._failure is None or self.keep_polling:
                self._poll_once()
        return self._failure

    # -- monitor loop ----------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                with self._poll_lock:
                    if self._failure is not None and not self.keep_polling:
                        return
                    if self._poll_once() and not self.keep_polling:
                        return
            except Exception:  # the watchdog must outlive its own bugs
                logger.exception("cluster monitor poll failed")
            self._stop.wait(self.poll_interval)

    def _poll_once(self) -> bool:
        codes, alive, failed = self._backend_snapshot()
        return (self._check_processes(codes, failed)
                or self._check_heartbeats(alive))

    def _backend_snapshot(self):
        """One backend sweep per poll: ``(exitcodes, alive, failed)``.

        Derived from a single ``exitcodes()`` call when the backend has one
        (exitcode None ⇔ alive, on both LocalProcessBackend and
        AgentBackend) — on AgentBackend each separate ``alive()``/
        ``failed()`` call would be a full STATUS round to every agent, and
        reading one snapshot also removes the window where the two calls
        could disagree within a poll.
        """
        backend = self.cluster.backend
        exitcodes = getattr(backend, "exitcodes", None)
        if exitcodes is not None:
            try:
                codes = dict(exitcodes())
                alive = [codes.get(i) is None
                         for i in range(len(self.cluster.cluster_info))]
                failed = [i for i, c in sorted(codes.items())
                          if c not in (0, None)]
                return codes, alive, failed
            except Exception:
                logger.debug("backend.exitcodes() failed; falling back to "
                             "alive()/failed()", exc_info=True)
        codes = {}
        try:
            alive = list(backend.alive())
        except Exception:
            logger.debug("backend.alive() failed mid-poll", exc_info=True)
            alive = []
        try:
            failed = list(backend.failed())
        except Exception:
            logger.debug("backend.failed() failed mid-poll", exc_info=True)
            failed = []
        return codes, alive, failed

    def _check_processes(self, codes: dict, failed: list) -> bool:
        failed = [i for i in failed if i not in self._handled]
        if not failed:
            return False
        sigterm = -int(signal.SIGTERM)
        preempted = (
            all(codes.get(i) == sigterm for i in failed)
            or any(self._hb.get(i, {}).get("phase") == "preempted"
                   for i in failed))
        kind = PREEMPTION if preempted else CRASH
        detail = ", ".join(f"worker {i} exit={codes.get(i)}" for i in failed)
        self._fail(ClusterFailure(
            kind, f"{kind} detected: {detail}", failed_workers=failed))
        return True

    def _check_heartbeats(self, alive: list) -> bool:
        now = time.monotonic()
        # copy: cluster_info grows in place when workers are added live
        for node in list(self.cluster.cluster_info):
            eid = node["executor_id"]
            if eid in self._handled:
                continue  # already reported; keep_polling watches the rest
            if eid < len(alive) and not alive[eid]:
                continue  # exited; crash/preemption handled by process check
            payload = self._poll_kv(node)
            rec = self._hb.setdefault(eid, {
                "seq": None, "seen": now, "step": None, "step_seen": now,
                "phase": None})
            if payload and payload.get("seq") != rec["seq"]:
                rec["seq"] = payload.get("seq")
                rec["seen"] = now
                new_phase = payload.get("phase")
                if new_phase != rec["phase"]:
                    rec["phase"] = new_phase
                    if self.on_phase is not None:
                        try:
                            self.on_phase(eid, new_phase)
                        except Exception:
                            logger.exception("on_phase subscriber raised")
                # heartbeat-carried telemetry (metrics.py): keep the last
                # snapshot/goodput per node for the aggregated cluster view
                if "metrics" in payload:
                    rec["metrics"] = payload.get("metrics")
                if "goodput" in payload:
                    rec["goodput"] = payload.get("goodput")
                if payload.get("step") != rec["step"]:
                    rec["step"] = payload.get("step")
                    rec["step_seen"] = now
            if rec["step"] is None or rec["step"] < 1:
                continue  # watchdog unarmed until the node reports a step
            hb_age = now - rec["seen"]
            if hb_age > self.hang_timeout:
                self._fail(ClusterFailure(
                    HANG,
                    f"hang detected: worker {eid} heartbeat stale for "
                    f"{hb_age:.1f}s (hang_timeout={self.hang_timeout}s, "
                    f"last step {rec['step']}, phase {rec['phase']})",
                    failed_workers=(eid,)))
                return True
            step_age = now - rec["step_seen"]
            if self.step_timeout is not None and step_age > self.step_timeout:
                self._fail(ClusterFailure(
                    HANG,
                    f"hang detected: worker {eid} stuck at step "
                    f"{rec['step']} for {step_age:.1f}s "
                    f"(step_timeout={self.step_timeout}s)",
                    failed_workers=(eid,)))
                return True
        return False

    def _poll_kv(self, node: dict):
        eid = node["executor_id"]
        now = time.monotonic()
        if now < self._kv_retry_at.get(eid, 0.0):
            return None  # recent connect failure: don't stall this poll
        cli = self._clients.get(eid)
        try:
            if cli is None:
                cli = self._clients[eid] = self._client_factory(node)
            payload = cli.kv_get(HEARTBEAT_KEY)
            self._kv_retry_at.pop(eid, None)
            return payload
        # tfos: ignore[broad-except] — deliberate: an unreachable kv is an
        # EXPECTED state the watchdog is built to absorb, and the handler
        # acts on it (drops the client, arms the reconnect backoff)
        except Exception:
            # unreachable kv: drop the client and back off reconnecting —
            # a netsplit node's connect can otherwise block a whole poll
            # (delaying detection for every OTHER node); driver-clock
            # staleness accrues regardless, so a wedged node still becomes
            # a hang once armed
            if cli is not None:
                with contextlib.suppress(Exception):
                    cli.close()
            self._clients.pop(eid, None)
            self._kv_retry_at[eid] = now + max(2.0, 4 * self.poll_interval)
            return None

    def _fail(self, failure: ClusterFailure) -> None:
        """Record + publish one classified failure (_poll_lock held by
        caller — every path here runs inside a _poll_once)."""
        self._failure = failure
        self.failures.append(failure)
        self._failures_total.inc(kind=failure.kind)
        logger.error("cluster monitor: %s", failure)
        self._emit(failure.kind, message=str(failure),
                   workers=list(failure.failed_workers))
        self._failure_evt.set()
        if self.keep_polling:
            self._handled.update(failure.failed_workers)
        if self.on_failure is not None:
            try:
                self.on_failure(failure)
            except Exception:
                logger.exception("on_failure subscriber raised")
        if self.abort_on_failure:
            self._emit("abort", reason=failure.kind)
            with contextlib.suppress(Exception):
                self.cluster._abort()

    def _emit(self, kind: str, **fields) -> None:
        if self.events is not None:
            with contextlib.suppress(Exception):
                self.events.emit(kind, **fields)


# ------------------------------------------------------ restart policy

def classify_failure(exc: BaseException) -> str:
    """Map a failed attempt's exception to a failure kind.

    ``ClusterFailure`` carries its own kind; worker tracebacks (the
    ``RuntimeError`` text re-raised from crash files) are scanned for the
    exception types they contain — deterministic user errors classify
    ``user``, anything environmental classifies ``infra``.
    """
    if isinstance(exc, ClusterFailure):
        return exc.kind
    if isinstance(exc, (ConnectionError, EOFError, TimeoutError)):
        return INFRA
    found = _TB_ERROR_RE.findall(str(exc))
    if found and all(name.rsplit(".", 1)[-1] in _NO_RETRY_ERRORS
                     for name in found):
        return USER
    if type(exc).__name__ in _NO_RETRY_ERRORS and not found:
        return USER
    return INFRA


def classify_restart(kind: str) -> bool:
    """Should ``run_with_recovery`` relaunch after a ``kind`` failure?
    Deterministic user errors fail the same way every attempt — everything
    else (crash/hang/preemption/infra) is worth a restart."""
    return kind != USER


def backoff_delay(attempt: int, base: float = 1.0, cap: float = 30.0) -> float:
    """Exponential backoff with jitter for restart ``attempt`` (1-based):
    ``min(cap, base * 2**(attempt-1))`` scaled by uniform(0.5, 1.0), so
    simultaneous restarting drivers don't stampede a recovering service."""
    d = min(float(cap), float(base) * (2.0 ** max(0, attempt - 1)))
    return d * random.uniform(0.5, 1.0)


class RestartBudget:
    """Sliding-window restart budget: at most ``max_restarts`` restarts in
    any ``window_secs`` span.  A crash loop that respects per-attempt
    limits can still burn quota forever; the window bounds the *rate*."""

    def __init__(self, max_restarts: int, window_secs: float):
        self.max_restarts = int(max_restarts)
        self.window_secs = float(window_secs)
        self._times: deque[float] = deque()

    def allow(self, now: float | None = None) -> bool:
        """Record a restart at ``now``; False once the window overflows."""
        now = time.monotonic() if now is None else now
        self._times.append(now)
        while self._times and now - self._times[0] > self.window_secs:
            self._times.popleft()
        return len(self._times) <= self.max_restarts
