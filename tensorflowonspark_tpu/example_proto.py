"""``tf.train.Example`` protobuf wire codec — dependency-free.

The reference converts DataFrame rows to/from serialized ``tf.train.Example``
protos (``dfutil.py::toTFExample`` / ``fromTFExample``) using TensorFlow's
generated proto classes.  TensorFlow isn't a dependency of this rebuild, so
the tiny stable schema is encoded/decoded directly at the protobuf wire
level.  The message graph (tensorflow/core/example/example.proto and
feature.proto):

    Example  { Features features = 1; }
    Features { map<string, Feature> feature = 1; }
    Feature  { oneof kind { BytesList bytes_list = 1;
                            FloatList float_list = 2;
                            Int64List int64_list = 3; } }
    BytesList { repeated bytes value = 1; }
    FloatList { repeated float value = 1 [packed]; }
    Int64List { repeated int64 value = 1 [packed]; }

Output is byte-compatible with TF: records written here parse with
``tf.train.Example.FromString`` and vice versa (packed and unpacked repeated
encodings are both accepted on decode).
"""

from __future__ import annotations

import struct
from typing import Any, Iterable

_WIRE_VARINT = 0
_WIRE_64BIT = 1
_WIRE_LEN = 2
_WIRE_32BIT = 5


# -- varint / wire primitives ----------------------------------------------

def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:
        value &= (1 << 64) - 1  # two's-complement 64-bit (proto int64)
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _signed64(value: int) -> int:
    return value - (1 << 64) if value >= (1 << 63) else value


def _tag(field: int, wire: int) -> int:
    return (field << 3) | wire


def _write_len_field(out: bytearray, field: int, payload: bytes) -> None:
    _write_varint(out, _tag(field, _WIRE_LEN))
    _write_varint(out, len(payload))
    out.extend(payload)


def _skip_field(buf: bytes, pos: int, wire: int) -> int:
    if wire == _WIRE_VARINT:
        _, pos = _read_varint(buf, pos)
        return pos
    if wire == _WIRE_64BIT:
        return pos + 8
    if wire == _WIRE_LEN:
        n, pos = _read_varint(buf, pos)
        return pos + n
    if wire == _WIRE_32BIT:
        return pos + 4
    raise ValueError(f"unsupported wire type {wire}")


# -- Feature encode ---------------------------------------------------------

def encode_bytes_list(values: Iterable[bytes]) -> bytes:
    inner = bytearray()
    for v in values:
        if isinstance(v, str):
            v = v.encode("utf-8")
        _write_len_field(inner, 1, bytes(v))
    out = bytearray()
    _write_len_field(out, 1, bytes(inner))  # Feature.bytes_list = 1
    return bytes(out)


def encode_float_list(values: Iterable[float]) -> bytes:
    values = list(values)
    packed = struct.pack(f"<{len(values)}f", *values)
    inner = bytearray()
    _write_len_field(inner, 1, packed)      # FloatList.value packed
    out = bytearray()
    _write_len_field(out, 2, bytes(inner))  # Feature.float_list = 2
    return bytes(out)


def encode_int64_list(values: Iterable[int]) -> bytes:
    packed = bytearray()
    for v in values:
        _write_varint(packed, int(v))
    inner = bytearray()
    _write_len_field(inner, 1, bytes(packed))  # Int64List.value packed
    out = bytearray()
    _write_len_field(out, 3, bytes(inner))     # Feature.int64_list = 3
    return bytes(out)


def encode_feature(values: Any) -> bytes:
    """Encode a python value/list into a Feature by type sniffing, the same
    dispatch ``dfutil.py::toTFExample`` does on DataFrame column types."""
    import numpy as np

    if isinstance(values, np.ndarray):
        values = values.tolist()
    if not isinstance(values, (list, tuple)):
        values = [values]
    if not values:
        return encode_bytes_list([])
    first = values[0]
    if isinstance(first, (bytes, bytearray, str)):
        return encode_bytes_list(values)
    if isinstance(first, (bool, int, np.integer)):
        return encode_int64_list(int(v) for v in values)
    if isinstance(first, (float, np.floating)):
        return encode_float_list(float(v) for v in values)
    raise TypeError(f"cannot encode feature from {type(first).__name__}")


def encode_example(features: dict[str, Any]) -> bytes:
    """dict of {name: value/list} → serialized tf.train.Example bytes."""
    feat_map = bytearray()
    for name in sorted(features):                 # deterministic output
        entry = bytearray()
        _write_len_field(entry, 1, name.encode("utf-8"))   # key
        _write_len_field(entry, 2, encode_feature(features[name]))  # value
        _write_len_field(feat_map, 1, bytes(entry))  # Features.feature entry
    out = bytearray()
    _write_len_field(out, 1, bytes(feat_map))        # Example.features = 1
    return bytes(out)


# -- Feature decode ---------------------------------------------------------

def _decode_bytes_list(buf: bytes) -> list[bytes]:
    values = []
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        if tag >> 3 == 1 and (tag & 7) == _WIRE_LEN:
            n, pos = _read_varint(buf, pos)
            values.append(buf[pos:pos + n])
            pos += n
        else:
            pos = _skip_field(buf, pos, tag & 7)
    return values


def _decode_float_list(buf: bytes) -> list[float]:
    values: list[float] = []
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == _WIRE_LEN:      # packed
            n, pos = _read_varint(buf, pos)
            values.extend(struct.unpack(f"<{n // 4}f", buf[pos:pos + n]))
            pos += n
        elif field == 1 and wire == _WIRE_32BIT:  # unpacked
            values.append(struct.unpack("<f", buf[pos:pos + 4])[0])
            pos += 4
        else:
            pos = _skip_field(buf, pos, wire)
    return values


def _decode_int64_list(buf: bytes) -> list[int]:
    values: list[int] = []
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == _WIRE_LEN:      # packed
            n, pos = _read_varint(buf, pos)
            end = pos + n
            while pos < end:
                v, pos = _read_varint(buf, pos)
                values.append(_signed64(v))
        elif field == 1 and wire == _WIRE_VARINT:  # unpacked
            v, pos = _read_varint(buf, pos)
            values.append(_signed64(v))
        else:
            pos = _skip_field(buf, pos, wire)
    return values


def decode_feature(buf: bytes) -> tuple[str, list]:
    """Feature bytes → (kind, values) where kind ∈ bytes/float/int64."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire != _WIRE_LEN:
            pos = _skip_field(buf, pos, wire)
            continue
        n, pos = _read_varint(buf, pos)
        payload = buf[pos:pos + n]
        pos += n
        if field == 1:
            return "bytes", _decode_bytes_list(payload)
        if field == 2:
            return "float", _decode_float_list(payload)
        if field == 3:
            return "int64", _decode_int64_list(payload)
    return "bytes", []   # empty Feature


_KINDS = ("bytes", "float", "int64")


def decode_example(buf: bytes) -> dict[str, tuple[str, list]]:
    """Serialized Example → {name: (kind, values)}.

    Uses the native parser (``native/tfrecord.cc::exp_scan``, measured
    ~6× the pure-Python loop on MNIST-shaped records) when the codec
    library is available; the Python path below is the behavioral oracle
    and the fallback.  Outputs are identical either way."""
    out = _decode_example_native(buf)
    if out is not None:
        return out
    return decode_example_py(buf)


def _decode_example_native(buf: bytes) -> dict[str, tuple[str, list]] | None:
    import ctypes

    import numpy as np

    from tensorflowonspark_tpu.tfrecord import _native

    lib = _native()
    if lib is None:
        return None
    buf = bytes(buf)  # ctypes c_char_p rejects bytearray/memoryview
    buflen = len(buf)
    max_feats = 64
    while True:
        meta = np.empty((max_feats, 6), np.int64)
        n = lib.exp_scan(buf, buflen,
                         meta.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                         max_feats)
        if n < 0:
            raise ValueError("malformed Example protobuf")
        if n <= max_feats:
            break
        max_feats = int(n)
    features: dict[str, tuple[str, list]] = {}
    for i in range(int(n)):
        name_off, name_len, kind, count, pay_off, pay_len = (
            int(v) for v in meta[i])
        name = buf[name_off:name_off + name_len].decode("utf-8")
        payload = buf[pay_off:pay_off + pay_len]
        if kind == 2:                                    # int64
            arr = np.empty(count, np.int64)
            got = lib.exp_read_int64(
                payload, pay_len,
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), count)
            if got != count:
                raise ValueError("malformed int64 list")
            values = arr.tolist()
        elif kind == 1:                                  # float
            arr = np.empty(count, np.float32)
            got = lib.exp_read_float(
                payload, pay_len,
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), count)
            if got != count:
                raise ValueError("malformed float list")
            values = arr.tolist()
        else:                                            # bytes
            offs = np.empty((max(count, 1), 2), np.int64)
            got = lib.exp_read_bytes(
                payload, pay_len,
                offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), count)
            if got != count:
                raise ValueError("malformed bytes list")
            values = [payload[int(o):int(o) + int(ln)]
                      for o, ln in offs[:count]]
        features[name] = (_KINDS[kind], values)
    return features


def decode_example_py(buf: bytes) -> dict[str, tuple[str, list]]:
    """Pure-Python Example decoder (oracle + no-compiler fallback)."""
    features: dict[str, tuple[str, list]] = {}
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        if tag >> 3 == 1 and (tag & 7) == _WIRE_LEN:   # Example.features
            n, pos = _read_varint(buf, pos)
            fbuf = buf[pos:pos + n]
            pos += n
            fpos = 0
            while fpos < len(fbuf):
                ftag, fpos = _read_varint(fbuf, fpos)
                if ftag >> 3 == 1 and (ftag & 7) == _WIRE_LEN:  # map entry
                    en, fpos = _read_varint(fbuf, fpos)
                    entry = fbuf[fpos:fpos + en]
                    fpos += en
                    key, value = None, ("bytes", [])
                    epos = 0
                    while epos < len(entry):
                        etag, epos = _read_varint(entry, epos)
                        efield, ewire = etag >> 3, etag & 7
                        if ewire != _WIRE_LEN:
                            epos = _skip_field(entry, epos, ewire)
                            continue
                        vn, epos = _read_varint(entry, epos)
                        payload = entry[epos:epos + vn]
                        epos += vn
                        if efield == 1:
                            key = payload.decode("utf-8")
                        elif efield == 2:
                            value = decode_feature(payload)
                    if key is not None:
                        features[key] = value
                else:
                    fpos = _skip_field(fbuf, fpos, ftag & 7)
        else:
            pos = _skip_field(buf, pos, tag & 7)
    return features
