"""Cross-host bulk transport: the zero-copy data plane beyond localhost.

The shm ring (``shm.py``) removed the same-host copies, but every
cross-host byte — training ``DataFeed`` chunks, serving intake, batch
``array`` shards, standby weight clones, disaggregated KV-page session
handoffs — still rode the per-message pickle socket
(``reservation.MessageSocket``).  That path is efficient for ONE large
contiguous buffer (out-of-band framing, ``recv_into`` straight into the
final backing store) but keeps two structural costs for realistic
payloads:

- **sub-64 KB buffers travel in-band** (``OOB_MIN_BYTES``): a chunk of
  sample-sized arrays pays a full pickle-stream build on the sender and
  a full copy out of the stream on the receiver — two extra passes over
  every byte.  The threshold exists because per-buffer ``sendall``/
  ``recv_into`` syscalls made small-buffer OOB 5x SLOWER; the fix is not
  a lower threshold but **scatter/gather frames**: many buffers per
  syscall (``sendmsg`` iovecs out, one contiguous slab region in).
- **a fresh receive allocation per message**: every OOB buffer lands in
  a brand-new ``bytearray`` whose pages fault in under ``recv_into``;
  a **pool of pre-registered reusable slabs** keeps the pages warm.

:class:`BulkChannel` is the third transport tier, negotiated during the
queue authkey hello (``queues.py``), preference order **shm > bulk >
per-message pickle**:

- the message is pickled ONCE (protocol 5) with a much lower out-of-band
  threshold (:data:`BULK_OOB_MIN`); the pickle stream travels in a small
  envelope frame, the buffers as a sequence of **chunk frames** — fixed
  20-byte header ``[magic][ver][flags][stream id][seq][length][crc]``
  followed by raw bytes gathered *directly from the source buffers*
  (``sendmsg`` scatter/gather — no intermediate copy of the payload,
  in-band or otherwise);
- buffers are packed into the receiver's slab at 64-byte-aligned offsets
  (:func:`~tensorflowonspark_tpu.shm.aligned_layout`, shared with the
  shm ring); the sender interleaves zero-padding iovecs so the wire
  stream IS the slab image and each chunk is ONE contiguous
  ``recv_into`` — no per-buffer syscalls on either side;
- the receiver hands ``pickle.loads(buffers=...)`` zero-copy
  ``memoryview`` s over the slab, GC-lease-tracked exactly like the shm
  ring's segment views: the slab returns to the pool when the LAST view
  of the message dies;
- **send-side pipelining**: with ``TFOS_BULK_PIPELINE=1`` (default: auto,
  on when the host has >1 CPU) a per-channel writer thread issues the
  ``sendmsg`` for chunk *i* while the caller assembles + checksums chunk
  *i+1* — measured a wash on a 1-core host (everything serializes on the
  GIL anyway), a real overlap on multi-core;
- **per-stream integrity**: every chunk header carries a CRC and the
  stream ends with a digest frame over all chunk CRCs + the total
  length, so a desynced or corrupted stream is rejected as a connection
  error (:class:`BulkIntegrityError`) before any frame of it reaches the
  consumer.  ``TFOS_BULK_CRC`` picks the coverage: ``fast`` (default)
  checksums the first :data:`CRC_SAMPLE_BYTES` of each chunk — catches
  desync, truncation, mis-offset scatter, and stale-slab reuse at ~zero
  cost; ``full`` checksums every byte (measured ~2.4x slower end-to-end
  on a 1-core host: zlib.crc32 runs at ~1.2 GB/s there, i.e. at wire
  speed); ``off`` disables payload CRCs (headers are still validated).
  End-to-end content guarantees stay where they belong: the KV-page
  handoff verifies per-page blake2b hashes in ``adopt_session``
  regardless of transport.

Fallback semantics mirror the shm tier, per message and per connection:

- ``TFOS_TPU_NO_BULK=1`` (or ``bulk=False`` on either endpoint) pins the
  per-message pickle protocol for the whole connection;
- a failed ``bulk_hello`` (old peer, refusing server) silently
  downgrades the connection;
- per message: payloads with no bulk-eligible buffers, below
  :data:`default_min_payload`, or larger than the peer's advertised slab
  (**oversized**) travel as an inline envelope — the same pickle-5
  out-of-band socket framing as the tier below, so backpressure and odd
  shapes degrade throughput, never correctness;
- slab-pool exhaustion (the consumer still holds views over every slab)
  allocates a one-shot slab instead (counted ``pool_miss``) — bulk
  framing is kept, only the page-warm reuse is lost.

Telemetry (docs/observability.md): ``tfos_transport_messages_total`` /
``tfos_transport_bytes_total`` labeled by tier (``bulk``/``inline``) and
direction, ``tfos_transport_chunk_seconds`` per received chunk, and
``tfos_transport_fallbacks_total`` by reason (``handshake`` /
``oversized`` / ``small`` / ``pool_miss``).
"""

from __future__ import annotations

import logging
import os
import pickle
import struct
import threading
import time
import weakref
import zlib

import numpy as np

from tensorflowonspark_tpu.shm import aligned_layout, aligned_layout_lens

logger = logging.getLogger(__name__)

__all__ = [
    "BulkChannel", "BulkIntegrityError", "SlabPool", "SlabLease",
    "aligned_layout_lens", "bulk_enabled", "bulk_resolve",
    "hello_payload", "accept_payload",
]

#: kill switch: set to "1" to keep every connection off the bulk tier
DISABLE_ENV = "TFOS_TPU_NO_BULK"
#: wire chunk size in KiB (client proposes, server may clamp down)
CHUNK_KB_ENV = "TFOS_BULK_CHUNK_KB"
#: receive-slab size in MiB — also the oversized-payload bound a peer
#: advertises in the hello
SLAB_MB_ENV = "TFOS_BULK_SLAB_MB"
#: number of reusable receive slabs per channel
SLABS_ENV = "TFOS_BULK_SLABS"
#: minimum total out-of-band bytes before a message takes the bulk path
MIN_KB_ENV = "TFOS_BULK_MIN_KB"
#: payload CRC coverage: "fast" (sampled, default) | "full" | "off"
CRC_ENV = "TFOS_BULK_CRC"
#: "1"/"0" forces the pipelined writer on/off (default: auto by CPU count)
PIPELINE_ENV = "TFOS_BULK_PIPELINE"

#: measured on the loopback-simulated cross-host A/B: 4 MB chunks beat
#: 1 MB by ~25% on a 1-core host (fewer header parses + recv wakeups);
#: the pipelined writer still overlaps at this granularity on multi-core
DEFAULT_CHUNK_BYTES = 4 << 20
DEFAULT_SLAB_BYTES = 32 << 20
DEFAULT_SLABS = 4
DEFAULT_MIN_PAYLOAD = 256 << 10

#: buffers at least this large leave the pickle stream on the bulk path
#: (the gather framing amortizes the old per-buffer syscall cost that
#: forced MessageSocket.OOB_MIN_BYTES up to 64 KB)
BULK_OOB_MIN = 4096
#: per-message buffer-count cap (envelope size + iovec bookkeeping bound)
BULK_MAX_BUFFERS = 4096

#: "fast" CRC mode samples this prefix of every chunk
CRC_SAMPLE_BYTES = 4096

#: hard per-stream byte bound: chunk/digest frame length fields are
#: 32-bit, so a receive capacity above this is clamped at negotiation —
#: payloads beyond it take the inline (pickle-5 socket) path, whose
#: per-buffer size table is 64-bit
MAX_STREAM_BYTES = (1 << 32) - 1

CRC_MODES = ("fast", "full", "off")

#: chunk frame header: magic, version, flags, stream id, seq, length, crc
_HDR = struct.Struct(">BBHIIII")
FRAME_MAGIC = 0xB7
FRAME_VERSION = 1
FLAG_END = 0x1      #: last payload chunk of the stream
FLAG_DIGEST = 0x2   #: stream-digest frame (crc = crc32 over chunk crcs)

#: stay clear of the kernel iovec limit (IOV_MAX, typically 1024) —
#: a chunk needing more segments is simply written in several sendmsg
#: calls, no extra framing required
_IOV_CAP = 512

_ZEROS = bytes(64)  # alignment padding source (gaps are < 64 bytes)


class BulkIntegrityError(EOFError):
    """A bulk stream failed verification (bad header, CRC or digest
    mismatch, sequence gap).  Subclasses ``EOFError`` so every receive
    loop treats the connection as dead — a desynced byte stream cannot
    be resynchronized — but callers log it explicitly first."""


def bulk_enabled() -> bool:
    """False when the operator disabled the bulk tier via env."""
    return os.environ.get(DISABLE_ENV, "").strip() not in ("1", "true", "yes")


def bulk_resolve(param: bool | None) -> bool:
    """Tri-state policy shared by QueueServer and QueueClient (mirrors
    ``shm.shm_resolve``): ``None`` = auto, ``False`` = refuse, ``True``
    = want bulk but the env kill switch still vetoes."""
    return bulk_enabled() if param is None else bool(param) and bulk_enabled()


def default_chunk_bytes() -> int:
    return int(float(os.environ.get(CHUNK_KB_ENV,
                                    DEFAULT_CHUNK_BYTES >> 10)) * 1024)


def default_slab_bytes() -> int:
    return int(float(os.environ.get(SLAB_MB_ENV,
                                    DEFAULT_SLAB_BYTES >> 20)) * (1 << 20))


def default_slabs() -> int:
    return int(os.environ.get(SLABS_ENV, DEFAULT_SLABS))


def default_min_payload() -> int:
    return int(float(os.environ.get(MIN_KB_ENV,
                                    DEFAULT_MIN_PAYLOAD >> 10)) * 1024)


def resolve_crc(proposed: str | None = None) -> str:
    """This endpoint's CRC mode: the env knob wins, else the peer's
    proposal, else ``fast``.  Unknown values fall back to ``fast`` (a
    typo'd knob must not silently disable verification)."""
    mode = os.environ.get(CRC_ENV, "").strip().lower() or proposed or "fast"
    return mode if mode in CRC_MODES else "fast"


def pipeline_resolve() -> bool:
    """Whether to run the pipelined writer thread: env override first,
    else on for multi-core hosts (measured a wash — slightly negative —
    when everything shares one core)."""
    v = os.environ.get(PIPELINE_ENV, "").strip()
    if v in ("1", "true", "yes"):
        return True
    if v in ("0", "false", "no"):
        return False
    return (os.cpu_count() or 1) > 1


# --------------------------------------------------------------------------
# receive side: the reusable slab pool + GC-tracked leases

class SlabPool:
    """Pre-registered reusable receive buffers (module docstring).

    ``acquire`` leases a slab for one incoming stream; the lease's views
    (handed to ``pickle.loads``) anchor it, and the slab returns to the
    free list when the last view dies — the same GC-lease design as the
    shm ring's receive side, applied to process-local memory.  An
    exhausted pool falls back to a one-shot slab (``pool_misses``): the
    bulk framing is unaffected, only page-warm reuse is lost.
    """

    #: floor for a demand-sized slab: small streams still get a
    #: reusable buffer without fragmenting the pool into tiny slabs
    MIN_SLAB = 1 << 20

    def __init__(self, slabs: int | None = None,
                 slab_bytes: int | None = None):
        self.slabs = slabs if slabs is not None else default_slabs()
        self.slab_bytes = (slab_bytes if slab_bytes is not None
                           else default_slab_bytes())
        self._free: list[bytearray] = []
        self._created = 0
        self._lock = threading.Lock()
        self._closed = False
        self.pool_misses = 0

    def _slab_size(self, nbytes: int) -> int:
        # demand-sized: the advertised ``slab_bytes`` is the peer's
        # oversized BOUND, not the allocation — a 32 MB bytearray costs
        # ~15 ms (memset + faults) where 2 MB costs ~0.07 ms, so a
        # stream of 2 MB messages must not pay max-size slabs up front.
        # Round up to the next power of two so the steady repeated-size
        # stream reuses instead of churning near-fit slabs.
        size = max(int(nbytes), self.MIN_SLAB)
        return min(1 << (size - 1).bit_length(), self.slab_bytes)

    def acquire(self, nbytes: int) -> "SlabLease":
        """A lease over a slab with room for ``nbytes`` (caller bounds
        ``nbytes`` by the advertised slab size before sending)."""
        slab = None
        if nbytes <= self.slab_bytes:
            with self._lock:
                # best-fit reuse: the smallest free slab that holds it
                fits = [s for s in self._free if len(s) >= nbytes]
                if fits:
                    slab = min(fits, key=len)
                    self._free.remove(slab)
                elif not self._closed:
                    if self._created >= self.slabs and self._free:
                        # full pool, nothing fits: the stream size grew
                        # past the demand-sized slabs — evict the
                        # smallest free one and allocate bigger in its
                        # place, else every future message would pay the
                        # one-shot path forever
                        self._free.remove(min(self._free, key=len))
                        self._created -= 1
                    if self._created < self.slabs:
                        # pre-fault the pages once: reused slabs then
                        # absorb recv_into without per-message fault
                        # storms
                        slab = bytearray(self._slab_size(nbytes))
                        np.frombuffer(slab, np.uint8)[::4096] = 0
                        self._created += 1
        if slab is None:
            with self._lock:
                self.pool_misses += 1
            return SlabLease(self, bytearray(nbytes), pooled=False)
        return SlabLease(self, slab, pooled=True)

    def _release(self, slab: bytearray) -> None:
        with self._lock:
            if not self._closed:
                self._free.append(slab)

    @property
    def free_slabs(self) -> int:
        with self._lock:
            return len(self._free) + (self.slabs - self._created)

    def close(self) -> None:
        """Drop the free list; leased slabs die with their last view."""
        with self._lock:
            self._closed = True
            self._free = []


class SlabLease:
    """One incoming stream's slab: scatter target, then view factory."""

    def __init__(self, pool: SlabPool, slab: bytearray, pooled: bool):
        self._pool = pool
        self._slab = slab
        self._pooled = pooled
        self.mv = memoryview(slab)

    def views(self, offs: list[int], lens: list[int]) -> list[memoryview]:
        """Zero-copy per-buffer ``memoryview`` s, lease-anchored.

        Identical mechanism to ``shm.SegmentMap.views``: each view wraps
        a per-message ndarray slice; numpy's base collapse lands every
        derived array on it, so the ``weakref.finalize`` fires — and the
        slab returns to the pool — only once NO view of this message's
        data is alive.
        """
        slab_arr = np.frombuffer(self.mv, np.uint8)
        pool, slab, pooled = self._pool, self._slab, self._pooled
        self.mv = None          # views own the buffer from here on
        if pooled:
            # ONE finalizer per message: every view below is a slice of
            # ``slab_arr``, numpy's base collapse makes every array the
            # consumer derives from them reference ``slab_arr`` too — so
            # it dies (and the slab recycles) exactly when the LAST view
            # of this message's data dies.
            weakref.finalize(slab_arr, pool._release, slab)
        return [memoryview(slab_arr[off:off + ln])
                for off, ln in zip(offs, lens)]

    def discard(self) -> None:
        """Abort before views were handed out (stream failed)."""
        self.mv = None
        if self._pooled:
            self._pool._release(self._slab)


# --------------------------------------------------------------------------
# send side: chunk assembly over the aligned layout

def _iter_chunks(bufs: list, offs: list[int], total: int,
                 chunk_bytes: int):
    """Yield ``(clen, iovecs)`` wire chunks covering the aligned layout
    ``[0, total)``: buffer bytes where a buffer is mapped, zero padding
    in the alignment gaps — so the byte stream IS the receiver's slab
    image and each chunk is one contiguous ``recv_into``."""
    spans = []  # (start, memoryview) in layout order, gaps implied
    for off, v in zip(offs, bufs):
        spans.append((off, v.cast("B") if v.format != "B" or v.ndim != 1
                      else v))
    pos = 0
    si = 0
    while pos < total:
        clen = min(chunk_bytes, total - pos)
        end = pos + clen
        iov: list = []
        cur = pos
        while cur < end:
            if si < len(spans):
                s_off, s_v = spans[si]
                if cur < s_off:                      # alignment gap
                    pad = min(s_off, end) - cur
                    while pad > 0:
                        take = min(pad, len(_ZEROS))
                        iov.append(_ZEROS[:take])
                        pad -= take
                        cur += take
                    continue
                s_end = s_off + s_v.nbytes
                take = min(s_end, end) - cur
                iov.append(s_v[cur - s_off:cur - s_off + take])
                cur += take
                if cur >= s_end:
                    si += 1
            else:                                    # trailing gap
                pad = end - cur
                while pad > 0:
                    take = min(pad, len(_ZEROS))
                    iov.append(_ZEROS[:take])
                    pad -= take
                    cur += take
        yield clen, iov
        pos = end


def _chunk_crc(iov: list, mode: str) -> int:
    """Sender-side chunk CRC per the negotiated mode (module docstring):
    chained ``zlib.crc32`` over every byte (``full``) or the first
    :data:`CRC_SAMPLE_BYTES` (``fast``); 0 for ``off``."""
    if mode == "off":
        return 0
    crc = 0
    budget = None if mode == "full" else CRC_SAMPLE_BYTES
    for piece in iov:
        if budget is not None:
            if budget <= 0:
                break
            piece = piece[:budget] if len(piece) > budget else piece
            budget -= len(piece)
        crc = zlib.crc32(piece, crc)
    return crc & 0xFFFFFFFF


def _recv_crc(view: memoryview, mode: str) -> int:
    if mode == "off":
        return 0
    if mode == "fast" and len(view) > CRC_SAMPLE_BYTES:
        view = view[:CRC_SAMPLE_BYTES]
    return zlib.crc32(view) & 0xFFFFFFFF


def _sendmsg_all(sock, iov: list) -> None:
    """``sendmsg`` the full iovec list, handling partial writes and the
    kernel's IOV_MAX by advancing through the list."""
    idx = 0
    skip = 0
    while idx < len(iov):
        batch: list = []
        first = True
        for v in iov[idx:idx + _IOV_CAP]:
            batch.append(v[skip:] if first and skip else v)
            first = False
        sent = sock.sendmsg(batch)
        while sent > 0 and idx < len(iov):
            remaining = len(iov[idx]) - skip
            if sent >= remaining:
                sent -= remaining
                idx += 1
                skip = 0
            else:
                skip += sent
                sent = 0


class _PipelinedWriter:
    """Per-channel writer thread: the caller enqueues fully assembled
    frame iovec lists and immediately assembles (and checksums) the next
    chunk while this thread's ``sendmsg`` blocks in the kernel.  FIFO, so
    frame order on the wire is exactly enqueue order; any socket error is
    latched and re-raised to the next ``write``/``join`` caller."""

    def __init__(self, sock):
        import queue as _q

        self._sock = sock
        self._q: "_q.Queue" = _q.Queue(maxsize=4)
        self._exc: BaseException | None = None
        self._thread = threading.Thread(target=self._run,
                                        name="bulk-writer", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                if self._exc is None:
                    _sendmsg_all(self._sock, item)
            except Exception as e:
                # ANY escape would kill this thread with frames queued
                # and leave the next flush() deadlocked in Queue.join();
                # latch it instead — write/flush re-raise it to the
                # caller, who treats the connection as dead
                self._exc = e
            finally:
                self._q.task_done()

    def write(self, iov: list) -> None:
        if self._exc is not None:
            raise self._exc
        self._q.put(iov)

    def flush(self) -> None:
        self._q.join()
        if self._exc is not None:
            raise self._exc

    def close(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=5)


# --------------------------------------------------------------------------
# the channel

class BulkChannel:
    """Bulk-aware framing for one authenticated queue connection side
    (module docstring).  Wire envelopes, mirroring ``shm.ShmChannel``:

        {"bulk": {"sid", "lens", "total", "crc", "p"}}   # stream head
        {"p": pickle5-stream, "b": [buf, ...]}           # inline

    A ``bulk`` envelope is followed on the socket by chunk frames
    covering ``total`` bytes (the last one flagged ``FLAG_END``) and one
    digest frame, which this side reads directly off the socket into a
    leased slab.
    """

    def __init__(self, ms, sock, chunk_bytes: int | None = None,
                 peer_max: int | None = None, crc_mode: str = "fast",
                 slabs: int | None = None, slab_bytes: int | None = None,
                 pipeline: bool | None = None):
        self._ms = ms
        self._sock = sock
        self.chunk_bytes = int(chunk_bytes or default_chunk_bytes())
        #: the PEER's receive-slab capacity — our oversized bound
        #: (clamped: the frame headers' length fields are 32-bit)
        self.peer_max = min(int(peer_max or default_slab_bytes()),
                            MAX_STREAM_BYTES)
        self.crc_mode = crc_mode if crc_mode in CRC_MODES else "fast"
        self.min_payload = default_min_payload()
        self._pool = SlabPool(slabs, slab_bytes)
        self._sid = 0
        self._writer: _PipelinedWriter | None = None
        self._pipeline = (pipeline_resolve() if pipeline is None
                          else bool(pipeline))
        # per-channel stats (tests/bench) + process-wide registry metrics
        self.bulk_msgs = 0
        self.inline_msgs = 0
        self.fallbacks = 0
        from tensorflowonspark_tpu import metrics as _metrics

        reg = _metrics.get_registry()
        self._m_msgs = reg.counter(
            "tfos_transport_messages_total",
            "Bulk-transport messages by tier and direction.",
            labelnames=("tier", "dir"))
        self._m_bytes = reg.counter(
            "tfos_transport_bytes_total",
            "Bulk-transport payload bytes by tier and direction.",
            labelnames=("tier", "dir"))
        self._m_chunk = reg.histogram(
            "tfos_transport_chunk_seconds",
            "Receive time per bulk chunk frame.")
        self._m_fall = reg.counter(
            "tfos_transport_fallbacks_total",
            "Messages that left the bulk path, by reason.",
            labelnames=("reason",))

    # -- send --------------------------------------------------------------
    def send(self, msg) -> None:
        data, bufs = self._ms.split_oob(msg, oob_min=BULK_OOB_MIN,
                                        max_buffers=BULK_MAX_BUFFERS)
        offs, total = aligned_layout(bufs) if bufs else ([], 0)
        raw = sum(v.nbytes for v in bufs)
        if not bufs or total < self.min_payload or total > self.peer_max:
            if bufs:
                self.fallbacks += 1
                self._m_fall.inc(reason="oversized" if total > self.peer_max
                                 else "small")
            self.inline_msgs += 1
            self._m_msgs.inc(tier="inline", dir="tx")
            self._m_bytes.inc(raw + len(data), tier="inline", dir="tx")
            # inline: the ALREADY-pickled stream + buffers re-wrapped as
            # uint8 arrays ride MessageSocket's own out-of-band framing
            # (no re-pickle, no extra copies) — the per-message tier
            p = np.frombuffer(data, np.uint8) \
                if len(data) >= self._ms.OOB_MIN_BYTES else data
            self._write_frames([self._ms.frame_bytes(
                {"p": p, "b": [np.frombuffer(v, np.uint8) for v in bufs]})])
            self._flush()
            return
        self._sid += 1
        sid = self._sid
        env = self._ms.frame_bytes(
            {"bulk": {"sid": sid, "lens": [v.nbytes for v in bufs],
                      "total": total, "crc": self.crc_mode, "p": data}})
        self._write_frames([env])
        seq = 0
        digest = 0
        pos = 0
        for clen, iov in _iter_chunks(bufs, offs, total, self.chunk_bytes):
            pos += clen
            crc = _chunk_crc(iov, self.crc_mode)
            digest = zlib.crc32(crc.to_bytes(4, "big"), digest)
            flags = FLAG_END if pos >= total else 0
            hdr = _HDR.pack(FRAME_MAGIC, FRAME_VERSION, flags, sid, seq,
                            clen, crc)
            self._write_frames([[hdr, *iov]])
            seq += 1
        hdr = _HDR.pack(FRAME_MAGIC, FRAME_VERSION, FLAG_DIGEST, sid, seq,
                        total, digest & 0xFFFFFFFF)
        self._write_frames([[hdr]])
        self._flush()
        self.bulk_msgs += 1
        self._m_msgs.inc(tier="bulk", dir="tx")
        self._m_bytes.inc(raw, tier="bulk", dir="tx")

    def _write_frames(self, frames: list) -> None:
        for iov in frames:
            if self._pipeline:
                if self._writer is None:
                    self._writer = _PipelinedWriter(self._sock)
                self._writer.write(iov)
            else:
                _sendmsg_all(self._sock, iov)

    def _flush(self) -> None:
        # the strict request-response protocol means the caller reads a
        # reply next; the writer must have drained first so a writer
        # error surfaces here, on the message that caused it
        if self._writer is not None:
            self._writer.flush()

    # -- receive -----------------------------------------------------------
    def receive(self):
        env = self._ms.receive(self._sock)
        if not isinstance(env, dict) or not ("bulk" in env or "p" in env):
            return env      # un-enveloped control frame: pass through
        bulk = env.get("bulk")
        if bulk is None:
            p = env["p"]
            if not isinstance(p, (bytes, bytearray)):   # uint8-wrapped
                p = memoryview(p)
            bufs = env["b"]
            self._m_msgs.inc(tier="inline", dir="rx")
            self._m_bytes.inc(sum(len(b) for b in bufs) + len(p),
                              tier="inline", dir="rx")
            return pickle.loads(p, buffers=bufs)
        return self._receive_stream(bulk)

    def _receive_stream(self, bulk: dict):
        lens = bulk["lens"]
        total = int(bulk["total"])
        sid = int(bulk["sid"])
        mode = bulk.get("crc", self.crc_mode)
        offs, expect_total = aligned_layout_lens(lens)
        if expect_total != total:
            raise BulkIntegrityError(
                f"bulk stream {sid}: advertised total {total} != layout "
                f"total {expect_total}")
        lease = self._pool.acquire(total)
        ok = False
        try:
            mv = lease.mv
            pos = 0
            seq = 0
            digest = 0
            while True:
                t0 = time.perf_counter()
                magic, ver, flags, h_sid, h_seq, clen, crc = _HDR.unpack(
                    self._ms._recv_exact(self._sock, _HDR.size))
                if magic != FRAME_MAGIC or ver != FRAME_VERSION:
                    raise BulkIntegrityError(
                        f"bulk chunk magic/version mismatch: "
                        f"(0x{magic:02x}, v{ver})")
                if h_sid != sid:
                    raise BulkIntegrityError(
                        f"bulk stream id mismatch: chunk {h_sid} inside "
                        f"stream {sid}")
                if flags & FLAG_DIGEST:
                    if pos != total or h_seq != seq:
                        raise BulkIntegrityError(
                            f"bulk stream {sid} truncated: digest after "
                            f"{pos}/{total} bytes, {seq} chunk(s)")
                    if mode != "off" and crc != (digest & 0xFFFFFFFF):
                        raise BulkIntegrityError(
                            f"bulk stream {sid} digest mismatch")
                    if clen != total:
                        raise BulkIntegrityError(
                            f"bulk stream {sid} digest length mismatch: "
                            f"{clen} != {total}")
                    break
                if h_seq != seq:
                    raise BulkIntegrityError(
                        f"bulk stream {sid} sequence gap: chunk {h_seq}, "
                        f"expected {seq}")
                if pos + clen > total:
                    raise BulkIntegrityError(
                        f"bulk stream {sid} overrun: {pos + clen} > {total}")
                self._ms._recv_exact_into(self._sock, mv[pos:pos + clen])
                if mode != "off":
                    got = _recv_crc(mv[pos:pos + clen], mode)
                    if got != crc:
                        raise BulkIntegrityError(
                            f"bulk stream {sid} chunk {seq} CRC mismatch "
                            f"({mode}): 0x{got:08x} != 0x{crc:08x}")
                digest = zlib.crc32(crc.to_bytes(4, "big"), digest)
                pos += clen
                seq += 1
                self._m_chunk.record(time.perf_counter() - t0)
            views = lease.views(offs, lens)
            ok = True
            self.bulk_msgs += 1
            self._m_msgs.inc(tier="bulk", dir="rx")
            self._m_bytes.inc(sum(lens), tier="bulk", dir="rx")
            return pickle.loads(bulk["p"], buffers=views)
        finally:
            if not ok:
                lease.discard()

    # -- stats / lifecycle -------------------------------------------------
    @property
    def stats(self) -> dict:
        return {"bulk_msgs": self.bulk_msgs,
                "inline_msgs": self.inline_msgs,
                "fallbacks": self.fallbacks,
                "pool_misses": self._pool.pool_misses,
                "free_slabs": self._pool.free_slabs}

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        self._pool.close()


# --------------------------------------------------------------------------
# negotiation payloads (the queue hello's third tier — queues.py drives)

def hello_payload() -> dict:
    """The client's ``bulk_hello`` body: proposed chunk size, this side's
    receive capacity (the server's oversized bound for responses), CRC
    proposal, and the frame version."""
    return {"op": "bulk_hello", "ver": FRAME_VERSION,
            "chunk": default_chunk_bytes(), "max": default_slab_bytes(),
            "crc": resolve_crc()}


def accept_payload(hello: dict) -> dict | None:
    """Server side: validate a ``bulk_hello`` and compute the negotiated
    parameters (None = refuse).  The chunk size is the smaller of the two
    proposals; each side keeps its own receive capacity and advertises it
    so the PEER can bound outgoing payloads; the server resolves the CRC
    mode (its env knob wins over the client proposal).  The returned
    ``peer_max`` (the client's validated capacity) is for the SERVER's
    own channel — callers pop it before relaying the rest to the
    client."""
    try:
        if int(hello.get("ver")) != FRAME_VERSION:
            return None
        chunk = min(int(hello["chunk"]), default_chunk_bytes())
        if chunk < 4096:
            return None
        # a malformed capacity refuses the hello rather than killing the
        # serve thread later; 0/absent falls back to this side's default
        peer_max = int(hello.get("max") or 0) or None
        return {"chunk": chunk, "max": default_slab_bytes(),
                "crc": resolve_crc(hello.get("crc")),
                "peer_max": peer_max}
    except (TypeError, ValueError, KeyError):
        return None
