// TFRecord framing codec: CRC32C + record frame/parse, exposed via a C ABI
// for the Python ctypes wrapper (tensorflowonspark_tpu/tfrecord.py).
//
// The reference gets TFRecord IO from the JVM tensorflow-hadoop JAR
// (dfutil.py -> saveAsNewAPIHadoopFile with TFRecordFileOutputFormat) and the
// TF C++ runtime; this is the rebuild's native equivalent (SURVEY.md §2b
// "TFRecord on HDFS from JVM"), JVM-free.  The hot loop — CRC32C over every
// record body — is the part worth doing natively; file IO stays in Python.
//
// Format (TFRecord on-disk framing):
//   uint64le length
//   uint32le masked_crc32c(length bytes)
//   byte     data[length]
//   uint32le masked_crc32c(data)
//
// CRC32C uses the Castagnoli polynomial (reversed 0x82F63B78), slice-by-8
// tables for ~1 byte/cycle without SSE4.2 intrinsics (portable across the
// build hosts).  mask(crc) = ((crc >> 15) | (crc << 17)) + 0xa282ead8.

#include <cstdint>
#include <cstddef>
#include <cstring>

namespace {

uint32_t kTable[8][256];
bool kInit = false;

void init_tables() {
  if (kInit) return;
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int j = 0; j < 8; ++j)
      crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    kTable[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i)
    for (int t = 1; t < 8; ++t)
      kTable[t][i] = (kTable[t - 1][i] >> 8) ^ kTable[0][kTable[t - 1][i] & 0xFF];
  kInit = true;
}

inline uint32_t crc32c_impl(uint32_t crc, const uint8_t* p, size_t n) {
  crc = ~crc;
  while (n >= 8) {
    uint64_t w;
    std::memcpy(&w, p, 8);
    w ^= crc;  // little-endian assumption (x86/arm64, matches the fleet)
    crc = kTable[7][w & 0xFF] ^ kTable[6][(w >> 8) & 0xFF] ^
          kTable[5][(w >> 16) & 0xFF] ^ kTable[4][(w >> 24) & 0xFF] ^
          kTable[3][(w >> 32) & 0xFF] ^ kTable[2][(w >> 40) & 0xFF] ^
          kTable[1][(w >> 48) & 0xFF] ^ kTable[0][(w >> 56) & 0xFF];
    p += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ kTable[0][(crc ^ *p++) & 0xFF];
  return ~crc;
}

inline uint32_t mask_crc(uint32_t crc) {
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

inline void put_u32(uint8_t* out, uint32_t v) {
  out[0] = v & 0xFF; out[1] = (v >> 8) & 0xFF;
  out[2] = (v >> 16) & 0xFF; out[3] = (v >> 24) & 0xFF;
}

inline uint32_t get_u32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
         ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}

inline uint64_t get_u64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 7; i >= 0; --i) v = (v << 8) | p[i];
  return v;
}

}  // namespace

extern "C" {

uint32_t tfr_crc32c(const uint8_t* data, size_t n) {
  init_tables();
  return crc32c_impl(0, data, n);
}

uint32_t tfr_masked_crc(const uint8_t* data, size_t n) {
  init_tables();
  return mask_crc(crc32c_impl(0, data, n));
}

// Frame one record: writes length+lencrc+data+datacrc into out (caller
// allocates n+16 bytes).  Returns bytes written.
size_t tfr_frame(const uint8_t* data, size_t n, uint8_t* out) {
  init_tables();
  uint8_t len_le[8];
  uint64_t len = n;
  for (int i = 0; i < 8; ++i) { len_le[i] = len & 0xFF; len >>= 8; }
  std::memcpy(out, len_le, 8);
  put_u32(out + 8, mask_crc(crc32c_impl(0, len_le, 8)));
  std::memcpy(out + 12, data, n);
  put_u32(out + 12 + n, mask_crc(crc32c_impl(0, data, n)));
  return n + 16;
}

// Parse the record starting at buf+off.  Sets *data_off/*data_len and
// returns the offset of the next record.  Returns -1 at clean EOF
// (off == buflen), -2 on truncation, -3 on length-crc mismatch, -4 on
// data-crc mismatch (crc checks only when verify != 0).
int64_t tfr_next(const uint8_t* buf, size_t buflen, size_t off,
                 size_t* data_off, size_t* data_len, int verify) {
  init_tables();
  if (off == buflen) return -1;
  if (off + 12 > buflen) return -2;
  uint64_t len = get_u64(buf + off);
  if (verify &&
      get_u32(buf + off + 8) != mask_crc(crc32c_impl(0, buf + off, 8)))
    return -3;
  // overflow-safe: a corrupt length near UINT64_MAX must not wrap past buflen
  if (off + 16 > buflen || len > buflen - (off + 16)) return -2;
  if (verify &&
      get_u32(buf + off + 12 + len) !=
          mask_crc(crc32c_impl(0, buf + off + 12, len)))
    return -4;
  *data_off = off + 12;
  *data_len = len;
  return (int64_t)(off + 16 + len);
}

}  // extern "C"
