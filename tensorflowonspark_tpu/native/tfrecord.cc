// TFRecord framing codec: CRC32C + record frame/parse, exposed via a C ABI
// for the Python ctypes wrapper (tensorflowonspark_tpu/tfrecord.py).
//
// The reference gets TFRecord IO from the JVM tensorflow-hadoop JAR
// (dfutil.py -> saveAsNewAPIHadoopFile with TFRecordFileOutputFormat) and the
// TF C++ runtime; this is the rebuild's native equivalent (SURVEY.md §2b
// "TFRecord on HDFS from JVM"), JVM-free.  The hot loop — CRC32C over every
// record body — is the part worth doing natively; file IO stays in Python.
//
// Format (TFRecord on-disk framing):
//   uint64le length
//   uint32le masked_crc32c(length bytes)
//   byte     data[length]
//   uint32le masked_crc32c(data)
//
// CRC32C uses the Castagnoli polynomial (reversed 0x82F63B78), slice-by-8
// tables for ~1 byte/cycle without SSE4.2 intrinsics (portable across the
// build hosts).  mask(crc) = ((crc >> 15) | (crc << 17)) + 0xa282ead8.

#include <cstdint>
#include <cstddef>
#include <cstring>

namespace {

uint32_t kTable[8][256];
bool kInit = false;

void init_tables() {
  if (kInit) return;
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int j = 0; j < 8; ++j)
      crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    kTable[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i)
    for (int t = 1; t < 8; ++t)
      kTable[t][i] = (kTable[t - 1][i] >> 8) ^ kTable[0][kTable[t - 1][i] & 0xFF];
  kInit = true;
}

inline uint32_t crc32c_impl(uint32_t crc, const uint8_t* p, size_t n) {
  crc = ~crc;
  while (n >= 8) {
    uint64_t w;
    std::memcpy(&w, p, 8);
    w ^= crc;  // little-endian assumption (x86/arm64, matches the fleet)
    crc = kTable[7][w & 0xFF] ^ kTable[6][(w >> 8) & 0xFF] ^
          kTable[5][(w >> 16) & 0xFF] ^ kTable[4][(w >> 24) & 0xFF] ^
          kTable[3][(w >> 32) & 0xFF] ^ kTable[2][(w >> 40) & 0xFF] ^
          kTable[1][(w >> 48) & 0xFF] ^ kTable[0][(w >> 56) & 0xFF];
    p += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ kTable[0][(crc ^ *p++) & 0xFF];
  return ~crc;
}

inline uint32_t mask_crc(uint32_t crc) {
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

inline void put_u32(uint8_t* out, uint32_t v) {
  out[0] = v & 0xFF; out[1] = (v >> 8) & 0xFF;
  out[2] = (v >> 16) & 0xFF; out[3] = (v >> 24) & 0xFF;
}

inline uint32_t get_u32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
         ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}

inline uint64_t get_u64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 7; i >= 0; --i) v = (v << 8) | p[i];
  return v;
}

}  // namespace

extern "C" {

uint32_t tfr_crc32c(const uint8_t* data, size_t n) {
  init_tables();
  return crc32c_impl(0, data, n);
}

uint32_t tfr_masked_crc(const uint8_t* data, size_t n) {
  init_tables();
  return mask_crc(crc32c_impl(0, data, n));
}

// Frame one record: writes length+lencrc+data+datacrc into out (caller
// allocates n+16 bytes).  Returns bytes written.
size_t tfr_frame(const uint8_t* data, size_t n, uint8_t* out) {
  init_tables();
  uint8_t len_le[8];
  uint64_t len = n;
  for (int i = 0; i < 8; ++i) { len_le[i] = len & 0xFF; len >>= 8; }
  std::memcpy(out, len_le, 8);
  put_u32(out + 8, mask_crc(crc32c_impl(0, len_le, 8)));
  std::memcpy(out + 12, data, n);
  put_u32(out + 12 + n, mask_crc(crc32c_impl(0, data, n)));
  return n + 16;
}

// Parse the record starting at buf+off.  Sets *data_off/*data_len and
// returns the offset of the next record.  Returns -1 at clean EOF
// (off == buflen), -2 on truncation, -3 on length-crc mismatch, -4 on
// data-crc mismatch (crc checks only when verify != 0).
int64_t tfr_next(const uint8_t* buf, size_t buflen, size_t off,
                 size_t* data_off, size_t* data_len, int verify) {
  init_tables();
  if (off == buflen) return -1;
  if (off + 12 > buflen) return -2;
  uint64_t len = get_u64(buf + off);
  if (verify &&
      get_u32(buf + off + 8) != mask_crc(crc32c_impl(0, buf + off, 8)))
    return -3;
  // overflow-safe: a corrupt length near UINT64_MAX must not wrap past buflen
  if (off + 16 > buflen || len > buflen - (off + 16)) return -2;
  if (verify &&
      get_u32(buf + off + 12 + len) !=
          mask_crc(crc32c_impl(0, buf + off + 12, len)))
    return -4;
  *data_off = off + 12;
  *data_len = len;
  return (int64_t)(off + 16 + len);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// tf.train.Example decoder (the loadTFRecords/fromTFExample hot path —
// reference: the tensorflow-hadoop JAR's JVM-side parsing, SURVEY.md §2b).
// Python's per-varint loop parses ~2.6k records/s; this parser is the
// native replacement behind example_proto.decode_example.
//
// Wire shapes handled (mirrors example_proto.py exactly):
//   Example{ features=1: Features{ feature=1(map entry){ key=1, value=2:
//     Feature{ bytes_list=1 | float_list=2 | int64_list=3 } } } }
//   *List.value = field 1, packed OR unpacked.
// ---------------------------------------------------------------------------

namespace {

// varint; returns new pos or -1 on truncation/overlong
inline int64_t read_varint(const uint8_t* b, int64_t pos, int64_t end,
                           uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (pos < end && shift <= 63) {
    uint8_t byte = b[pos++];
    v |= (uint64_t)(byte & 0x7F) << shift;
    if (!(byte & 0x80)) { *out = v; return pos; }
    shift += 7;
  }
  return -1;
}

inline int64_t skip_field(const uint8_t* b, int64_t pos, int64_t end,
                          uint32_t wire) {
  uint64_t tmp;
  switch (wire) {
    case 0: return read_varint(b, pos, end, &tmp);
    case 1: return pos + 8 <= end ? pos + 8 : -1;
    case 2: {
      int64_t p = read_varint(b, pos, end, &tmp);
      if (p < 0 || tmp > (uint64_t)(end - p)) return -1;
      return p + (int64_t)tmp;
    }
    case 5: return pos + 4 <= end ? pos + 4 : -1;
    default: return -1;
  }
}

// count elements in a *List message body [pos, end): field 1 packed/unpacked
inline int64_t count_list(const uint8_t* b, int64_t pos, int64_t end,
                          int kind /*0 bytes,1 float,2 int64*/) {
  int64_t count = 0;
  uint64_t tmp;
  while (pos < end) {
    uint64_t tag;
    pos = read_varint(b, pos, end, &tag);
    if (pos < 0) return -1;
    uint32_t field = tag >> 3, wire = tag & 7;
    if (field == 1 && wire == 2) {           // length-delimited
      uint64_t n;
      pos = read_varint(b, pos, end, &n);
      if (pos < 0 || n > (uint64_t)(end - pos)) return -1;
      if (kind == 0) {
        count += 1;                          // one bytes value
      } else if (kind == 1) {
        count += (int64_t)(n / 4);           // packed floats
      } else {                               // packed varints
        int64_t p = pos, pend = pos + (int64_t)n;
        while (p < pend) {
          p = read_varint(b, p, pend, &tmp);
          if (p < 0) return -1;
          ++count;
        }
      }
      pos += (int64_t)n;
    } else if (field == 1 && wire == 5 && kind == 1) {
      count += 1; pos += 4;                  // unpacked float
      if (pos > end) return -1;
    } else if (field == 1 && wire == 0 && kind == 2) {
      pos = read_varint(b, pos, end, &tmp);  // unpacked int64
      if (pos < 0) return -1;
      ++count;
    } else {
      pos = skip_field(b, pos, end, wire);
      if (pos < 0) return -1;
    }
  }
  return count;
}

}  // namespace

extern "C" {

// Scan an Example. meta rows of 6 int64s per feature:
//   {name_off, name_len, kind(0/1/2), count, payload_off, payload_len}
// offsets into buf; payload is the *List message body.  Returns the number
// of features (even if > max_feats — caller re-calls with a bigger meta),
// or -1 on malformed input.
int64_t exp_scan(const uint8_t* buf, size_t buflen, int64_t* meta,
                 int64_t max_feats) {
  int64_t n_feats = 0;
  int64_t pos = 0, end = (int64_t)buflen;
  while (pos < end) {
    uint64_t tag;
    pos = read_varint(buf, pos, end, &tag);
    if (pos < 0) return -1;
    if ((tag >> 3) == 1 && (tag & 7) == 2) {          // Example.features
      uint64_t flen;
      pos = read_varint(buf, pos, end, &flen);
      if (pos < 0 || flen > (uint64_t)(end - pos)) return -1;
      int64_t fpos = pos, fend = pos + (int64_t)flen;
      pos = fend;
      while (fpos < fend) {
        uint64_t ftag;
        fpos = read_varint(buf, fpos, fend, &ftag);
        if (fpos < 0) return -1;
        if ((ftag >> 3) != 1 || (ftag & 7) != 2) {
          fpos = skip_field(buf, fpos, fend, ftag & 7);
          if (fpos < 0) return -1;
          continue;
        }
        uint64_t elen;                                 // map entry
        fpos = read_varint(buf, fpos, fend, &elen);
        if (fpos < 0 || elen > (uint64_t)(fend - fpos)) return -1;
        int64_t epos = fpos, eend = fpos + (int64_t)elen;
        fpos = eend;
        int64_t name_off = -1, name_len = 0;
        int64_t kind = 0, count = 0, pay_off = 0, pay_len = 0;
        while (epos < eend) {
          uint64_t etag;
          epos = read_varint(buf, epos, eend, &etag);
          if (epos < 0) return -1;
          uint32_t efield = etag >> 3, ewire = etag & 7;
          if (ewire != 2) {
            epos = skip_field(buf, epos, eend, ewire);
            if (epos < 0) return -1;
            continue;
          }
          uint64_t vlen;
          epos = read_varint(buf, epos, eend, &vlen);
          if (epos < 0 || vlen > (uint64_t)(eend - epos)) return -1;
          if (efield == 1) {                           // key
            name_off = epos; name_len = (int64_t)vlen;
          } else if (efield == 2) {    // Feature (proto: LAST value wins,
                                       // matching the Python oracle)
            int64_t vpos = epos, vend = epos + (int64_t)vlen;
            while (vpos < vend) {
              uint64_t vtag;
              vpos = read_varint(buf, vpos, vend, &vtag);
              if (vpos < 0) return -1;
              uint32_t vfield = vtag >> 3, vwire = vtag & 7;
              if (vwire != 2 || vfield < 1 || vfield > 3) {
                vpos = skip_field(buf, vpos, vend, vwire);
                if (vpos < 0) return -1;
                continue;
              }
              uint64_t llen;                           // the *List message
              vpos = read_varint(buf, vpos, vend, &llen);
              if (vpos < 0 || llen > (uint64_t)(vend - vpos)) return -1;
              kind = (int64_t)vfield - 1;              // 0/1/2
              pay_off = vpos; pay_len = (int64_t)llen;
              count = count_list(buf, vpos, vpos + (int64_t)llen, (int)kind);
              if (count < 0) return -1;
              break;               // first list within THIS Feature wins
            }
          }
          epos += (int64_t)vlen;
        }
        if (name_off >= 0) {
          if (n_feats < max_feats) {
            int64_t* row = meta + n_feats * 6;
            row[0] = name_off; row[1] = name_len; row[2] = kind;
            row[3] = count; row[4] = pay_off; row[5] = pay_len;
          }
          ++n_feats;
        }
      }
    } else {
      pos = skip_field(buf, pos, end, tag & 7);
      if (pos < 0) return -1;
    }
  }
  return n_feats;
}

// Decode an int64 *List body into out[count].  Returns elements written.
int64_t exp_read_int64(const uint8_t* b, size_t len, int64_t* out,
                       int64_t count) {
  int64_t pos = 0, end = (int64_t)len, w = 0;
  uint64_t tmp;
  while (pos < end && w < count) {
    uint64_t tag;
    pos = read_varint(b, pos, end, &tag);
    if (pos < 0) return -1;
    uint32_t field = tag >> 3, wire = tag & 7;
    if (field == 1 && wire == 2) {
      uint64_t n;
      pos = read_varint(b, pos, end, &n);
      if (pos < 0 || n > (uint64_t)(end - pos)) return -1;
      int64_t p = pos, pend = pos + (int64_t)n;
      while (p < pend && w < count) {
        p = read_varint(b, p, pend, &tmp);
        if (p < 0) return -1;
        out[w++] = (int64_t)tmp;
      }
      pos += (int64_t)n;
    } else if (field == 1 && wire == 0) {
      pos = read_varint(b, pos, end, &tmp);
      if (pos < 0) return -1;
      out[w++] = (int64_t)tmp;
    } else {
      pos = skip_field(b, pos, end, wire);
      if (pos < 0) return -1;
    }
  }
  return w;
}

// Decode a float *List body into out[count].
int64_t exp_read_float(const uint8_t* b, size_t len, float* out,
                       int64_t count) {
  int64_t pos = 0, end = (int64_t)len, w = 0;
  while (pos < end && w < count) {
    uint64_t tag;
    pos = read_varint(b, pos, end, &tag);
    if (pos < 0) return -1;
    uint32_t field = tag >> 3, wire = tag & 7;
    if (field == 1 && wire == 2) {
      uint64_t n;
      pos = read_varint(b, pos, end, &n);
      if (pos < 0 || n > (uint64_t)(end - pos)) return -1;
      int64_t m = (int64_t)(n / 4);
      if (m > count - w) m = count - w;
      std::memcpy(out + w, b + pos, (size_t)m * 4);
      w += m;
      pos += (int64_t)n;
    } else if (field == 1 && wire == 5) {
      if (pos + 4 > end) return -1;
      std::memcpy(out + w, b + pos, 4);
      ++w; pos += 4;
    } else {
      pos = skip_field(b, pos, end, wire);
      if (pos < 0) return -1;
    }
  }
  return w;
}

// Offsets of bytes values within a bytes *List body: offs[i*2]={off,len}
// relative to the payload pointer.  Returns values written.
int64_t exp_read_bytes(const uint8_t* b, size_t len, int64_t* offs,
                       int64_t count) {
  int64_t pos = 0, end = (int64_t)len, w = 0;
  while (pos < end && w < count) {
    uint64_t tag;
    pos = read_varint(b, pos, end, &tag);
    if (pos < 0) return -1;
    uint32_t field = tag >> 3, wire = tag & 7;
    if (field == 1 && wire == 2) {
      uint64_t n;
      pos = read_varint(b, pos, end, &n);
      if (pos < 0 || n > (uint64_t)(end - pos)) return -1;
      offs[w * 2] = pos; offs[w * 2 + 1] = (int64_t)n;
      ++w;
      pos += (int64_t)n;
    } else {
      pos = skip_field(b, pos, end, wire);
      if (pos < 0) return -1;
    }
  }
  return w;
}

}  // extern "C"
