"""Reference-named façade: ``tensorflowonspark.gpu_info`` → this module.

``gpu_info.py::get_gpus`` picked free GPUs via ``nvidia-smi``; on TPU the
host's chips belong to one process and JAX enumerates them, so the shim in
:mod:`~tensorflowonspark_tpu.device_info` returns local device ids instead.
"""

from tensorflowonspark_tpu.device_info import (MAX_RETRIES, get_gpus,  # noqa: F401
                                               num_local_devices)
