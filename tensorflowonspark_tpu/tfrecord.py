"""TFRecord file IO: native C++ codec with a pure-Python fallback.

The reference reads/writes TFRecords through the JVM ``tensorflow-hadoop``
JAR (``dfutil.py::saveAsTFRecords`` → ``saveAsNewAPIHadoopFile`` with
``TFRecordFileOutputFormat``) and TF's C++ readers; this module is the
JVM-free native equivalent (SURVEY.md §2b).  Framing + CRC32C run in
``native/tfrecord.cc`` (compiled on demand with ``g++``); Python keeps only
file handling, so the per-record hot path never computes checksums in the
interpreter.  When no compiler is available the pure-Python CRC32C fallback
keeps everything working (slower, same format).

The format is byte-identical to TensorFlow's, so files written here load in
``tf.data.TFRecordDataset`` and vice versa.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import tempfile
from typing import Iterable, Iterator

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_SOURCE = os.path.join(_NATIVE_DIR, "tfrecord.cc")

_lib = None          # ctypes CDLL once loaded
_lib_failed = False  # don't retry a failed build every call


def _trusted_so(so_path: str) -> bool:
    """Only dlopen a cached .so owned by us (or root) and not writable by
    anyone else — the cache dir lives under a world-writable tmpdir, so an
    unchecked path would let another local user plant a library."""
    try:
        st = os.lstat(so_path)
    except OSError:
        return False
    import stat as _stat

    return (_stat.S_ISREG(st.st_mode)
            and st.st_uid in (os.getuid(), 0)
            and not (st.st_mode & 0o022))


def _trusted_dir(target_dir: str, private: bool) -> bool:
    """The directory itself must be ours too: an attacker-owned pre-existing
    cache dir could swap the .so between build and dlopen regardless of the
    file check.  ``private`` additionally demands mode 0700 (tmpdir caches);
    the in-package dir may be root-owned/world-readable like the package."""
    import stat as _stat

    try:
        st = os.lstat(target_dir)
    except OSError:
        return False
    if not _stat.S_ISDIR(st.st_mode):
        return False
    if private:
        return st.st_uid == os.getuid() and not (st.st_mode & 0o077)
    return st.st_uid in (os.getuid(), 0) and not (st.st_mode & 0o022)


def _build_library() -> str | None:
    """Compile native/tfrecord.cc → libtfrecord.so (cached beside the source,
    falling back to a per-user cache dir when the package is read-only)."""
    try:
        source_mtime = os.path.getmtime(_SOURCE)
    except OSError:
        source_mtime = None  # source not shipped: accept any valid prebuilt
    user_cache = os.path.join(tempfile.gettempdir(),
                              f"tfos_tpu_native_{os.getuid()}")
    for target_dir in (_NATIVE_DIR, user_cache):
        private = target_dir == user_cache
        so_path = os.path.join(target_dir, "libtfrecord.so")
        try:
            os.makedirs(target_dir, mode=0o700, exist_ok=True)
        except OSError:
            continue
        if not _trusted_dir(target_dir, private):
            logger.debug("cache dir %s not trusted; skipping", target_dir)
            continue
        if (os.path.exists(so_path) and _trusted_so(so_path)
                and (source_mtime is None
                     or os.path.getmtime(so_path) >= source_mtime)):
            return so_path
        if source_mtime is None:
            continue  # nothing to build from
        tmp = None
        try:
            # unpredictable temp name (mkstemp) → no symlink-clobber window
            fd, tmp = tempfile.mkstemp(prefix=".libtfrecord.", suffix=".so",
                                       dir=target_dir)
            os.close(fd)
            subprocess.run(
                ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", _SOURCE, "-o", tmp],
                check=True, capture_output=True, timeout=120)
            os.chmod(tmp, 0o755 if not private else 0o700)
            os.replace(tmp, so_path)  # atomic: concurrent builders both succeed
            tmp = None
            logger.info("built native TFRecord codec: %s", so_path)
            return so_path
        except (OSError, subprocess.SubprocessError) as e:
            logger.debug("native build in %s failed: %s", target_dir, e)
        finally:
            if tmp is not None:  # failed build: don't litter the cache dir
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
    return None


def _native():
    """Load (building if needed) the native codec; None → use Python fallback."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    so_path = _build_library()
    if so_path is None:
        logger.warning("no native TFRecord codec (g++ unavailable?); "
                       "using pure-Python CRC32C")
        _lib_failed = True
        return None
    try:
        lib = ctypes.CDLL(so_path)
        lib.tfr_masked_crc.restype = ctypes.c_uint32
        lib.tfr_masked_crc.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.tfr_crc32c.restype = ctypes.c_uint32
        lib.tfr_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.tfr_frame.restype = ctypes.c_size_t
        lib.tfr_frame.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p]
        lib.tfr_next.restype = ctypes.c_int64
        lib.tfr_next.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
                                 ctypes.POINTER(ctypes.c_size_t),
                                 ctypes.POINTER(ctypes.c_size_t), ctypes.c_int]
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.exp_scan.restype = ctypes.c_int64
        lib.exp_scan.argtypes = [ctypes.c_char_p, ctypes.c_size_t, i64p,
                                 ctypes.c_int64]
        lib.exp_read_int64.restype = ctypes.c_int64
        lib.exp_read_int64.argtypes = [ctypes.c_char_p, ctypes.c_size_t, i64p,
                                       ctypes.c_int64]
        lib.exp_read_float.restype = ctypes.c_int64
        lib.exp_read_float.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                       ctypes.POINTER(ctypes.c_float),
                                       ctypes.c_int64]
        lib.exp_read_bytes.restype = ctypes.c_int64
        lib.exp_read_bytes.argtypes = [ctypes.c_char_p, ctypes.c_size_t, i64p,
                                       ctypes.c_int64]
    except (OSError, AttributeError) as e:  # stale/corrupt/wrong-arch cache
        logger.warning("native TFRecord codec failed to load (%s); "
                       "using pure-Python CRC32C", e)
        _lib_failed = True
        return None
    _lib = lib
    return _lib


# -- pure-Python CRC32C fallback (same Castagnoli polynomial) ---------------

_PY_TABLE: list[int] | None = None


def _py_table() -> list[int]:
    global _PY_TABLE
    if _PY_TABLE is None:
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ 0x82F63B78 if crc & 1 else crc >> 1
            table.append(crc)
        _PY_TABLE = table
    return _PY_TABLE


def crc32c(data: bytes) -> int:
    data = bytes(data)  # ctypes c_char_p rejects bytearray/memoryview
    lib = _native()
    if lib is not None:
        return lib.tfr_crc32c(data, len(data))
    table = _py_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ table[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def masked_crc(data: bytes) -> int:
    data = bytes(data)
    lib = _native()
    if lib is not None:
        return lib.tfr_masked_crc(data, len(data))
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- framing ----------------------------------------------------------------

def frame_record(data: bytes) -> bytes:
    """One framed TFRecord: len + crc(len) + data + crc(data)."""
    data = bytes(data)
    lib = _native()
    if lib is not None:
        out = ctypes.create_string_buffer(len(data) + 16)
        n = lib.tfr_frame(data, len(data), out)
        return out.raw[:n]
    header = struct.pack("<Q", len(data))
    return (header + struct.pack("<I", masked_crc(header)) + data
            + struct.pack("<I", masked_crc(data)))


class TFRecordCorruptError(ValueError):
    """A framing/CRC failure in a TFRecord stream, carrying *where*: the
    source ``path`` (None for in-memory buffers) and the byte ``offset``
    of the record whose frame failed — enough to seek straight to the
    damage with ``dd``/``xxd`` instead of re-deriving it from a bare
    ``struct.error``."""

    def __init__(self, reason: str, *, path: str | None = None,
                 offset: int | None = None):
        where = f" at offset {offset}" if offset is not None else ""
        src = f" in {path!r}" if path else ""
        super().__init__(f"{reason}{where}{src}")
        self.path = path
        self.offset = offset


def iter_records(buf: bytes, verify: bool = True,
                 path: str | None = None) -> Iterator[bytes]:
    """Yield record payloads from an in-memory TFRecord file image.
    ``path`` only labels corruption errors with the buffer's origin."""
    buf = bytes(buf)
    lib = _native()
    off = 0
    if lib is not None:
        d_off = ctypes.c_size_t()
        d_len = ctypes.c_size_t()
        while True:
            nxt = lib.tfr_next(buf, len(buf), off, ctypes.byref(d_off),
                               ctypes.byref(d_len), int(verify))
            if nxt == -1:
                return
            if nxt == -2:
                raise TFRecordCorruptError("truncated record",
                                           path=path, offset=off)
            if nxt in (-3, -4):
                raise TFRecordCorruptError(
                    f"crc mismatch ({'length' if nxt == -3 else 'data'})",
                    path=path, offset=off)
            yield buf[d_off.value:d_off.value + d_len.value]
            off = nxt
        return
    # Python fallback
    n = len(buf)
    while off < n:
        if off + 12 > n:
            raise TFRecordCorruptError("truncated record",
                                       path=path, offset=off)
        header = buf[off:off + 8]
        (length,) = struct.unpack("<Q", header)
        (len_crc,) = struct.unpack("<I", buf[off + 8:off + 12])
        if verify and len_crc != masked_crc(header):
            raise TFRecordCorruptError("crc mismatch (length)",
                                       path=path, offset=off)
        if off + 16 + length > n:
            raise TFRecordCorruptError("truncated record",
                                       path=path, offset=off)
        data = buf[off + 12:off + 12 + length]
        (data_crc,) = struct.unpack("<I", buf[off + 12 + length:off + 16 + length])
        if verify and data_crc != masked_crc(data):
            raise TFRecordCorruptError("crc mismatch (data)",
                                       path=path, offset=off)
        yield data
        off += 16 + length


# -- file API ---------------------------------------------------------------

class TFRecordWriter:
    """Write framed records to a file (tf.io.TFRecordWriter analogue).

    ``path`` may be local or any fsspec scheme (``gs://``, ``memory://``,
    ...) — the HDFS-write capability the reference gets from the
    tensorflow-hadoop JAR (``dfutil.py::saveAsTFRecords``).
    """

    def __init__(self, path: str):
        from tensorflowonspark_tpu import filesystem as fsutil

        self.path = path
        self._f = fsutil.open_output(path, "wb")

    def write(self, record: bytes) -> None:
        self._f.write(frame_record(record))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_records(path: str, verify: bool = True) -> Iterator[bytes]:
    """Stream record payloads from a TFRecord file.

    True streaming (header, then exact-size payload read) — multi-GB part
    files are never slurped whole, matching ``tf.data.TFRecordDataset``'s
    memory profile.  CRCs still run natively via :func:`masked_crc`.
    ``path`` may be local or any fsspec scheme (``gs://`` on TPU pods).
    """
    from tensorflowonspark_tpu import filesystem as fsutil

    with fsutil.open_file(path, "rb") as f:
        off = 0
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise TFRecordCorruptError("truncated record (tail shorter "
                                           "than the 12-byte frame header)",
                                           path=path, offset=off)
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:])
            if verify and len_crc != masked_crc(header[:8]):
                raise TFRecordCorruptError("crc mismatch (length)",
                                           path=path, offset=off)
            body = f.read(length + 4)
            if len(body) < length + 4:
                raise TFRecordCorruptError(
                    f"truncated record (payload ends {length + 4 - len(body)}"
                    " byte(s) early)", path=path, offset=off)
            data = body[:length]
            if verify and struct.unpack("<I", body[length:])[0] != masked_crc(data):
                raise TFRecordCorruptError("crc mismatch (data)",
                                           path=path, offset=off)
            yield data
            off += 16 + length


def write_records(path: str, records: Iterable[bytes]) -> int:
    """Write all ``records`` to ``path``; returns the record count."""
    count = 0
    with TFRecordWriter(path) as w:
        for r in records:
            w.write(r)
            count += 1
    return count
