"""Reference-named façade: ``tensorflowonspark.TFCluster`` → this module.

A reference user's driver script does::

    from tensorflowonspark import TFCluster
    cluster = TFCluster.run(sc, map_fun, args, num_executors, num_ps,
                            tensorboard, TFCluster.InputMode.SPARK)
    cluster.train(dataRDD, num_epochs)
    cluster.shutdown()

This module keeps that exact call shape (``TFCluster.py::run``): ``sc`` is
accepted and ignored (there is no Spark; pass ``None``), everything else
maps onto :class:`tensorflowonspark_tpu.cluster.TPUCluster`.
"""

from __future__ import annotations

import logging

from tensorflowonspark_tpu.cluster import (InputMode, Partitioned,  # noqa: F401
                                           TPUCluster)

logger = logging.getLogger(__name__)

# the reference exposes the class as TFCluster.TFCluster
TFCluster = TPUCluster


def run(sc, map_fun, tf_args, num_executors: int, num_ps: int = 0,
        tensorboard: bool = False, input_mode: int = InputMode.TENSORFLOW,
        log_dir: str | None = None, driver_ps_nodes: bool = False,
        master_node: str | None = None, reservation_timeout: float = 600.0,
        queues=("input", "output", "error"), eval_node: bool = False,
        release_port: bool = True, **kwargs) -> TPUCluster:
    """Reference: ``TFCluster.py::run`` — same positional signature.

    ``sc`` (the SparkContext) is unused: the cluster backend replaces Spark
    (SURVEY.md §2b).  ``release_port`` is advisory (ports are bound by the
    node runtime).  Extra ``kwargs`` pass through to ``TPUCluster.run``.
    """
    if callable(sc):
        # a map_fun in the sc slot means the caller used TPUCluster.run's
        # signature (no sc); fail loudly instead of shifting every arg by one
        raise TypeError(
            "TFCluster.run's first argument is the (ignored) SparkContext — "
            "pass None, or call TPUCluster.run(map_fun, ...) for the "
            "sc-less signature")
    if sc is not None:
        logger.info("TFCluster.run: SparkContext argument ignored "
                    "(no Spark in the TPU runtime)")
    return TPUCluster.run(
        map_fun, tf_args, num_executors, num_ps=num_ps,
        tensorboard=tensorboard, input_mode=input_mode,
        master_node=master_node, eval_node=eval_node,
        driver_ps_nodes=driver_ps_nodes,
        reservation_timeout=reservation_timeout,
        queues=list(queues), tensorboard_logdir=log_dir, **kwargs)
