"""Feed-queue sentinel markers.

Equivalent of the reference's ``tensorflowonspark/marker.py`` (``Marker``,
``EndPartition`` and the terminal end-of-feed sentinel).  Instances of these
classes are pushed onto the data-plane queues between ordinary data chunks:

- ``EndPartition`` marks a partition boundary so ``DataFeed.next_batch`` can
  return partial batches aligned to partition edges (reference:
  ``TFNode.py::DataFeed.next_batch``).
- ``EndOfFeed`` is the terminal sentinel pushed by ``TPUCluster.shutdown`` /
  the feeder when no more data will ever arrive (reference:
  ``TFSparkNode.py::shutdown``).
"""


class Marker:
    """Base class for queue sentinels."""

    __slots__ = ()

    def __repr__(self):  # pragma: no cover - cosmetic
        return f"<{type(self).__name__}>"

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self))


class EndPartition(Marker):
    """Marks the end of one data partition within the feed queue."""

    __slots__ = ()


class EndOfFeed(Marker):
    """Terminal sentinel: no more data will arrive on this queue."""

    __slots__ = ()
