"""Filesystem indirection: local paths plus any fsspec-backed URI scheme.

The reference reads/writes TFRecords on HDFS through the Hadoop FileSystem
API (``dfutil.py::saveAsTFRecords``/``loadTFRecords`` via the
tensorflow-hadoop JAR) and resolves user paths against ``defaultFS``
(``TFNode.py::hdfs_path``) — so a path like ``hdfs://...`` or a relative
path on a cluster "just works".  Round 1's rebuild resolved such paths but
then opened them with plain ``open()``, so a TPU-VM pod reading training
data from ``gs://`` — the normal production case — could not work
(VERDICT r1, missing #2).

This module is the one open/glob/exists surface the data layer
(``tfrecord``, ``dfutil``, ``data.Dataset.from_tfrecords``) goes through:

- plain local paths use the stdlib directly (no fsspec import cost);
- ``scheme://`` URIs (``gs://``, ``s3://``, ``hdfs://``, ``memory://``,
  ``file://`` ...) go through fsspec when it is importable, with a clear
  error naming the missing dependency otherwise.

Checkpoints never come through here — orbax handles ``gs://`` itself.
"""

from __future__ import annotations

import glob as globlib
import os
import re
from typing import IO

__all__ = ["has_scheme", "open_file", "open_output", "expand_glob",
           "exists", "isfile", "listdir", "makedirs", "remove", "join"]

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.\-]*://")


def has_scheme(path: str) -> bool:
    """True for ``scheme://...`` URIs (``C:\\`` never matches: no ``//``)."""
    return bool(_SCHEME_RE.match(path))


def _fs(path: str):
    """``(fsspec_filesystem, stripped_path)`` for a URI."""
    try:
        from fsspec.core import url_to_fs
    except ImportError as e:  # pragma: no cover - fsspec is in the image
        raise ImportError(
            f"reading {path!r} requires fsspec (pip install fsspec, plus the "
            "scheme's backend, e.g. gcsfs for gs://)") from e
    return url_to_fs(path)


def open_file(path: str, mode: str = "rb") -> IO:
    """Open for reading (or any mode, without parent-dir creation)."""
    if not has_scheme(path):
        return open(path, mode)
    fs, p = _fs(path)
    return fs.open(p, mode)


def open_output(path: str, mode: str = "wb") -> IO:
    """Open for writing, creating parent directories where the backend has
    them (local dirs, memory://; object stores need no mkdir)."""
    if not has_scheme(path):
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        return open(path, mode)
    fs, p = _fs(path)
    parent = p.rsplit("/", 1)[0] if "/" in p else ""
    if parent:
        try:
            fs.makedirs(parent, exist_ok=True)
        except (NotImplementedError, OSError, ValueError):
            pass  # object stores have no directories
    return fs.open(p, mode)


def expand_glob(pattern: str) -> list[str]:
    """Sorted matches for a glob pattern, scheme preserved in the results."""
    if not has_scheme(pattern):
        return sorted(globlib.glob(pattern))
    fs, p = _fs(pattern)
    return sorted(fs.unstrip_protocol(m) for m in fs.glob(p))


def exists(path: str) -> bool:
    if not has_scheme(path):
        return os.path.exists(path)
    fs, p = _fs(path)
    return fs.exists(p)


def isfile(path: str) -> bool:
    if not has_scheme(path):
        return os.path.isfile(path)
    fs, p = _fs(path)
    return fs.isfile(p)


def listdir(path: str) -> list[str]:
    """Basenames of a directory's entries (``os.listdir`` semantics)."""
    if not has_scheme(path):
        return os.listdir(path)
    fs, p = _fs(path)
    return [entry.rstrip("/").rsplit("/", 1)[-1]
            for entry in fs.ls(p, detail=False)]


def remove(path: str) -> None:
    """Delete one file/object (missing paths raise ``OSError`` like
    ``os.remove``)."""
    if not has_scheme(path):
        os.remove(path)
        return
    fs, p = _fs(path)
    try:
        fs.rm_file(p)
    except FileNotFoundError:
        raise
    except Exception as e:  # fsspec backends vary in error types
        raise OSError(f"remove({path}) failed: {e}") from e


def makedirs(path: str) -> None:
    if not has_scheme(path):
        os.makedirs(path, exist_ok=True)
        return
    fs, p = _fs(path)
    try:
        fs.makedirs(p, exist_ok=True)
    except (NotImplementedError, OSError, ValueError):
        pass  # object stores have no directories


def join(base: str, *parts: str) -> str:
    """Path join that keeps URI schemes intact (``/`` separator)."""
    if not has_scheme(base):
        return os.path.join(base, *parts)
    out = base.rstrip("/")
    for part in parts:
        out += "/" + part.strip("/")
    return out
