"""Observability: TensorBoard, the JAX profiler, and goodput accounting.

The reference's entire observability story (SURVEY.md §5) is: spawn a
``tensorboard`` subprocess on one node when ``tensorboard=True``
(``TFSparkNode.py::run``), register ``(tb_pid, tb_port)`` in the
reservation, surface it via ``TFCluster.tensorboard_url()``, and leave
profiling to whatever the user's TF callbacks emit.  This module keeps that
surface and adds the TPU-era equivalents:

- :func:`start_tensorboard` — the subprocess spawn (module-invoked, so no
  PATH dependency), returning ``(proc, port)``; the reservation carries
  ``(tb_pid, tb_port)``;
- :func:`start_profiler_server` / :func:`profile_trace` — ``jax.profiler``
  wiring (xprof traces viewable in TensorBoard's profile plugin, the
  TPU-native replacement for tf.profiler callbacks);
- :class:`GoodputRecorder` — badput accounting in the spirit of
  ``ml-goodput-measurement``: wall time split into productive step time vs
  init/compile/checkpoint/idle, because on large TPU fleets *goodput* (not
  step speed) is the capacity metric.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import os
import subprocess
import sys
import threading
import time
from collections import defaultdict

from tensorflowonspark_tpu import util

logger = logging.getLogger(__name__)


# ------------------------------------------------------------- tensorboard

def start_tensorboard(logdir: str, port: int | None = None,
                      wait_secs: float = 0.0):
    """Spawn TensorBoard on ``logdir``; returns ``(proc, port)`` or ``None``.

    Reference: the ``tensorboard`` subprocess spawned for worker:0/chief in
    ``TFSparkNode.py::run``.  Spawned as ``python -m tensorboard.main`` so it
    works without a console-script on PATH; returns None (never raises) when
    tensorboard isn't importable — observability must not kill training.
    """
    try:
        import tensorboard  # noqa: F401 — availability probe
    except ImportError:
        logger.warning("tensorboard=True but tensorboard is not installed")
        return None
    port = port or util.get_free_port()
    os.makedirs(logdir, exist_ok=True)
    env = os.environ.copy()
    try:
        import pkg_resources  # noqa: F401 — removed in setuptools>=81
    except ImportError:
        shim = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_shims")
        env["PYTHONPATH"] = shim + os.pathsep + env.get("PYTHONPATH", "")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "tensorboard.main", "--logdir", logdir,
             "--port", str(port), "--host", "0.0.0.0", "--load_fast", "false"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env=env, start_new_session=True)
    except OSError as e:
        logger.warning("could not spawn tensorboard: %s", e)
        return None
    if wait_secs:
        time.sleep(wait_secs)
        if proc.poll() is not None:
            logger.warning("tensorboard exited immediately (code %s)",
                           proc.returncode)
            return None
    logger.info("tensorboard pid %d serving %s on port %d",
                proc.pid, logdir, port)
    return proc, port


def stop_tensorboard(proc) -> None:
    if proc is None:
        return
    with contextlib.suppress(OSError):
        proc.terminate()
        try:
            proc.wait(5)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(5)  # reap — a kill without wait leaves a zombie


def tensorboard_url(cluster_info) -> str | None:
    """URL of the cluster's TensorBoard from the reservation records
    (``tb_port`` registered by the chief-designate node)."""
    for n in cluster_info:
        if n.get("tb_port"):
            return f"http://{n['host']}:{n['tb_port']}"
    return None


# ---------------------------------------------------------------- profiler

def start_profiler_server(port: int | None = None) -> int:
    """Start the in-process profiler RPC server (``jax.profiler``); a
    TensorBoard profile plugin (or ``xprof``) can then capture live traces
    from ``host:port``."""
    import jax

    port = port or util.get_free_port()
    jax.profiler.start_server(port)
    logger.info("jax profiler server on port %d", port)
    return port


@contextlib.contextmanager
def profile_trace(logdir: str):
    """Trace the enclosed block into ``logdir`` (viewable in TensorBoard →
    Profile).  The reference had no in-framework tracer; this is the
    one-liner the TPU stack makes possible."""
    import jax

    os.makedirs(logdir, exist_ok=True)
    with jax.profiler.trace(logdir):
        yield


def annotate(name: str):
    """Named sub-trace for the profiler timeline (``TraceAnnotation``)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


# ------------------------------------------------------------ health events

class EventLog:
    """Append-only JSONL stream of cluster lifecycle/health events.

    The reference surfaced executor failures through the Spark UI/event
    log; this is the rebuild's equivalent record.  One JSON object per
    line, each stamped with the writer's ``time.time()`` — the
    :class:`~tensorflowonspark_tpu.health.ClusterMonitor` writes
    ``monitor_started`` / ``crash`` / ``hang`` / ``preemption`` / ``abort``
    events here (default path: ``<working_dir>/health_events.jsonl``), and
    ``scripts/bench_recovery.py`` reads the timestamps back for
    detection-latency accounting.  Line-buffered append, so a post-mortem
    sees every event the driver managed to classify before dying.
    """

    def __init__(self, path: str, echo: bool = True):
        """``echo=False`` silences the per-event INFO log line — required
        for per-request/per-span streams (serving audit, tracing) whose
        emit rate would flood the process log."""
        self.path = path
        self._echo = echo
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a", buffering=1)
        self._lock = threading.Lock()
        self._write_failed = False

    def emit(self, kind: str, **fields) -> dict:
        """Append one event.  Safe after :meth:`close` (and after the fd
        is otherwise gone): a late monitor-thread emit into a closed
        line-buffered file degrades to a one-time logged warning instead
        of a ``ValueError`` out of the writer thread.  Later emits still
        attempt the write (a transient failure — brief ENOSPC — may
        clear), but only the first failure warns."""
        rec = {"t": time.time(), "kind": kind, **fields}
        line = json.dumps(rec) + "\n"
        with self._lock:
            try:
                self._f.write(line)
            except (ValueError, OSError, AttributeError) as e:
                # ValueError: write-after-close; OSError: fd gone
                if not self._write_failed:
                    self._write_failed = True
                    logger.warning(
                        "event log %s is unwritable (%s); dropped %r — "
                        "later writes are retried silently", self.path, e,
                        kind)
                return rec
        if self._echo:
            logger.info("health event: %s %s", kind, fields or "")
        return rec

    def close(self) -> None:
        with self._lock, contextlib.suppress(OSError, ValueError):
            self._f.close()

    @staticmethod
    def read(path: str) -> list[dict]:
        """Parse an event file back into records (bench/test helper).

        Tolerates malformed lines: a driver killed mid-``emit`` leaves a
        truncated final line — cut mid-payload, mid-UTF-8 sequence, or
        before its newline — and a post-mortem read that raised on it
        would lose every GOOD record in the file.  The file is read as
        bytes and decoded per line (a text-mode iterator raises
        ``UnicodeDecodeError`` on a torn multibyte tail and drops every
        line after it); bad lines are skipped with a warning, intact
        lines before AND after still come back."""
        out: list[dict] = []
        with open(path, "rb") as f:
            data = f.read()
        for lineno, raw in enumerate(data.split(b"\n"), 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                out.append(json.loads(raw.decode("utf-8")))
            except (UnicodeDecodeError, json.JSONDecodeError):
                logger.warning(
                    "skipping malformed event at %s:%d (truncated by a "
                    "mid-write death?): %.80r", path, lineno, raw)
        return out


# ------------------------------------------------------ latency histogram

class LatencyHistogram:
    """Latency percentile accumulator (p50/p95/p99) with a lock-free
    hot path and a **bounded** sample reservoir.

    ``record`` costs one ``itertools.count`` tick plus one list
    append/assign — all GIL-atomic, no lock — so request threads never
    contend to record a sample (the serving frontend records TTFT/e2e
    from many connection threads at once).  The reservoir is a ring of
    the most recent ``cap`` samples (default 4096): a long-lived serving
    frontend at millions-of-users scale must not grow a sample list
    forever, and recency is the window an operator actually wants
    percentiles over.  Readers take a snapshot copy (GIL-atomic slice)
    and sort it; percentile reads are O(cap log cap) off the hot path.
    Percentiles use the nearest-rank method on the retained window, so
    every reported value is a latency that actually occurred;
    ``summary()['count']`` stays the TOTAL recorded count.
    """

    DEFAULT_CAP = 4096

    def __init__(self, cap: int = DEFAULT_CAP):
        self._cap = max(1, int(cap))
        self._samples: list[float] = []
        self._ids = itertools.count()   # thread-safe total-count source
        self._count = 0

    def record(self, secs: float) -> None:
        i = next(self._ids)
        if i >= self._count:            # monotonic, benign-race update
            self._count = i + 1
        v = float(secs)
        s = self._samples
        n = len(s)
        if n >= self._cap:
            # the list never shrinks, so i % n is always in range even
            # if a fill-phase straggler appends concurrently; indexing
            # by the ACTUAL length keeps every slot reachable
            s[i % n] = v
        else:
            # fill phase: racing threads may overshoot cap by at most
            # one slot each (bounded, and still part of the ring above)
            s.append(v)

    def __len__(self) -> int:
        """Total samples recorded (retained window is ``min(len, cap)``)."""
        return max(len(self._samples), self._count)

    @staticmethod
    def _rank(snap: list, q: float):
        """Nearest-rank pick from a sorted snapshot (``ceil(q/100*n)``-th
        sample, 1-based, clamped)."""
        n = len(snap)
        return snap[min(n, int(max(1, -(-n * q // 100)))) - 1]

    def percentile(self, q: float) -> float | None:
        """Nearest-rank percentile ``q`` in [0, 100]; None when empty."""
        snap = sorted(self._samples)
        return self._rank(snap, q) if snap else None

    def window_summary(self, since_count: int) -> dict:
        """:meth:`summary` restricted to the samples recorded AFTER the
        first ``since_count`` — the canary-gate window: a baseline
        snapshot's total ``count`` feeds back in, so the gate compares
        bake-window latencies and is never biased by history the other
        side doesn't share (warm-up compiles in the incumbent's
        cumulative percentiles were exactly that bias).  Exact while
        the reservoir has not wrapped (total <= cap — the gate-scale
        case); after a wrap the retained recent ring is the best
        available approximation of the window."""
        total = len(self)
        n = max(0, total - max(0, int(since_count)))
        snap = list(self._samples)
        if n and total <= len(snap):
            # no wrap yet: the list is still in append order
            snap = snap[total - n:]
        if n == 0 or not snap:
            return {"count": 0, "mean_secs": None, "p50_secs": None,
                    "p95_secs": None, "p99_secs": None, "max_secs": None}
        snap.sort()
        return {"count": n,
                "mean_secs": sum(snap) / len(snap),
                "p50_secs": self._rank(snap, 50),
                "p95_secs": self._rank(snap, 95),
                "p99_secs": self._rank(snap, 99),
                "max_secs": snap[-1]}

    def summary(self) -> dict:
        """``{count, mean_secs, p50_secs, p95_secs, p99_secs, max_secs}``
        (None-valued stats when no sample was recorded).  ``count`` is
        the total ever recorded; the other stats cover the retained
        window (the most recent ``cap`` samples)."""
        snap = sorted(self._samples)
        n = len(snap)
        if not n:
            return {"count": 0, "mean_secs": None, "p50_secs": None,
                    "p95_secs": None, "p99_secs": None, "max_secs": None}
        return {"count": len(self), "mean_secs": sum(snap) / n,
                "p50_secs": self._rank(snap, 50),
                "p95_secs": self._rank(snap, 95),
                "p99_secs": self._rank(snap, 99), "max_secs": snap[-1]}


# ----------------------------------------------------------------- goodput

# ------------------------------------------------------- summary writing

class SummaryWriter:
    """TensorBoard scalar writer with zero TF dependency.

    TensorBoard event files are TFRecord streams of ``Event`` protos; this
    writer hand-encodes the ``Event``/``Summary`` wire format (the same
    approach as :mod:`.example_proto`) and frames records with the
    package's own :class:`~.tfrecord.TFRecordWriter` (CRC32C via the C++
    codec).  Byte-compatibility with TensorBoard's reader is pinned by
    test against the TF event parser.

    The reference delegated training curves to Keras/TF summary callbacks
    (SURVEY.md §5); here the estimator writes them natively::

        with SummaryWriter(logdir) as w:
            w.scalar("loss", 0.5, step=10)
            w.scalars({"loss": 0.4, "acc": 0.9}, step=20)
    """

    _FILE_VERSION = "brain.Event:2"

    def __init__(self, logdir: str, filename_suffix: str = ""):
        import socket

        from tensorflowonspark_tpu import filesystem as fsutil
        from tensorflowonspark_tpu.tfrecord import TFRecordWriter

        # scheme-aware: logdir may be gs:// etc., like the checkpoint dir
        fsutil.makedirs(logdir)
        name = (f"events.out.tfevents.{time.time():.6f}."
                f"{socket.gethostname()}{filename_suffix}")
        self.path = fsutil.join(logdir, name)
        self._w = TFRecordWriter(self.path)
        self._w.write(self._encode_event(file_version=self._FILE_VERSION))

    @staticmethod
    def _encode_event(step: int | None = None, summary: bytes | None = None,
                      file_version: str | None = None) -> bytes:
        import struct

        from tensorflowonspark_tpu.example_proto import (_tag, _write_len_field,
                                                         _write_varint)

        out = bytearray()
        _write_varint(out, _tag(1, 1))                 # wall_time: double
        out.extend(struct.pack("<d", time.time()))
        if step is not None:
            _write_varint(out, _tag(2, 0))             # step: int64
            _write_varint(out, int(step))
        if file_version is not None:
            _write_len_field(out, 3, file_version.encode())
        if summary is not None:
            _write_len_field(out, 5, summary)
        return bytes(out)

    @staticmethod
    def _encode_summary(metrics: dict) -> bytes:
        import struct

        from tensorflowonspark_tpu.example_proto import (_tag, _write_len_field,
                                                         _write_varint)

        out = bytearray()
        for tag_name, value in metrics.items():
            val = bytearray()
            _write_len_field(val, 1, str(tag_name).encode())  # Value.tag
            _write_varint(val, _tag(2, 5))                    # simple_value
            val.extend(struct.pack("<f", float(value)))
            _write_len_field(out, 1, bytes(val))              # Summary.value
        return bytes(out)

    def scalar(self, tag: str, value: float, step: int) -> None:
        self.scalars({tag: value}, step)

    def scalars(self, metrics: dict, step: int) -> None:
        """Write a dict of scalars as one event at ``step`` and flush —
        a live TensorBoard should see the point now, and a preempted
        process must not lose its buffered curves."""
        self._w.write(self._encode_event(
            step=step, summary=self._encode_summary(metrics)))
        self._w.flush()

    def flush(self) -> None:
        self._w.flush()

    def close(self) -> None:
        self._w.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class GoodputRecorder:
    """Wall-clock accounting: productive step time vs everything else.

    Categories follow the badput taxonomy: ``init`` (bootstrap + compile),
    ``checkpoint`` (save/restore stalls), ``data`` (feed waits), ``step``
    (productive compute).  Unattributed wall time counts as ``idle``.

        rec = GoodputRecorder()
        with rec.time("init"): state = make_state()
        while ...:
            with rec.time("data"): batch = feed.next_batch(...)
            with rec.time("step"): state, _ = train_step(state, batch)
        rec.summary()  # {'goodput': 0.87, 'wall_secs': ..., 'secs': {...}}
    """

    PRODUCTIVE = ("step",)

    def __init__(self):
        self._t0 = time.monotonic()
        self._secs: dict[str, float] = defaultdict(float)
        self._counts: dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def time(self, category: str):
        start = time.monotonic()
        try:
            yield
        finally:
            self._secs[category] += time.monotonic() - start
            self._counts[category] += 1

    def record(self, category: str, secs: float, count: bool = True) -> None:
        self._secs[category] += secs
        if count:
            self._counts[category] += 1

    def summary(self) -> dict:
        wall = time.monotonic() - self._t0
        attributed = sum(self._secs.values())
        secs = dict(self._secs)
        secs["idle"] = max(0.0, wall - attributed)
        productive = sum(self._secs[c] for c in self.PRODUCTIVE)
        return {
            "wall_secs": wall,
            "goodput": productive / wall if wall > 0 else 0.0,
            "secs": secs,
            "counts": dict(self._counts),
        }

    def write(self, path: str) -> dict:
        """Write the summary as one JSON file (per-host goodput roll-up)."""
        s = self.summary()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(s, f, indent=2)
        return s
